"""AOT-batched inference engine: bucketed shapes, fused whole-request dispatch.

XLA programs are shape-static, so a serving path that jits on the request's
natural batch size recompiles on every new size — a latency cliff exactly
when traffic shifts. The engine instead fixes a small ladder of batch
**buckets** (e.g. 1/8/32) and an **image-size ladder** (e.g. 192/224/256),
AOT-compiles one executable per ``(bucket, image_size)`` pair at warmup
(``jit(...).lower(...).compile()`` — no first-request compile stall), and
dispatches every batch to the smallest bucket that fits, zero-padding the
tail rows and slicing them back off the logits. Padding is sound because the
folded forward is row-independent (no BN batch statistics — the export fold
removed BN entirely), so the real rows' logits are BITWISE identical to an
unpadded run of the same bucket (pinned by tests/test_serve.py).

**Fused multi-chunk dispatch** kills the per-chunk dispatch boundary for
oversized requests (PAPERS.md "Kernel Looping", arXiv 2410.23668:
inter-call synchronization, not compute, caps inference throughput). A
request larger than the biggest bucket used to be N per-chunk dispatches
with host pad/stage/enqueue between every pair; now the chunk loop rolls
INTO the compiled program: all K chunks stage into one ``(K, bucket, S, S,
3)`` host buffer, transfer once, and a ``lax.scan`` over the leading chunk
axis runs the folded forward K times device-side — ONE dispatch, one
transfer, one ``device_get`` for the whole request. Fused executables are
keyed ``(bucket, image_size, K)`` on a small chunk-count ladder
(``fuse_ladder``, default 2/4, AOT-warmed like everything else); an
off-ladder chunk count decomposes greedily into ladder pieces (7 chunks =
4+2+1 → 3 dispatches, not 7), and the worst case degrades to the per-chunk
path. The scan body is the same forward the per-chunk executables compile
at the same ``(bucket, size)``, so fused logits are **bitwise identical**
to the chunked path (pinned by tests across K, tails, and bf16). The
per-chunk path (K=1) is unchanged and remains the mesh / fallback route.

**Async dispatch** is the pipelining primitive: :meth:`predict_async` stages
and dispatches every piece of a request and returns a
:class:`PendingPrediction` WITHOUT syncing — JAX's async dispatch keeps the
device computing while the host pads/stages the next piece (or the next
request entirely; serve/pipeline.py builds continuous batching on top).
The only host<->device sync is :meth:`PendingPrediction.result`, which is
safe under concurrent callers (a once-latch: one thread syncs, the rest get
the cached array). ``predict`` is literally ``predict_async(...).result()``,
so the two paths share one executable cache and are bitwise-identical by
construction.

Tail padding writes into a **reused per-(bucket, size, K) staging slot**
instead of ``np.concatenate([chunk, pad])``: no allocation per dispatch, and
only the pad rows are re-zeroed. With ``overlap_staging=False`` (the legacy
sync path) there is one slot per key and reuse right after dispatch is safe
because ``jnp.asarray`` copies the host buffer synchronously (the device
array never aliases the staging memory); the bitwise-parity tests would
catch any backend that broke that assumption.

**Overlapped staging** (``overlap_staging=True``, serve.overlap config)
removes that synchronous copy from the dispatch path: each key gets a small
round-robin pool of ``staging_slots`` host buffers, the transfer goes
through ``jax.device_put`` — which may return BEFORE the device has read the
host memory — and the resulting device array is donated to the executable
exactly as before. The invariant that used to rest on the synchronous copy
("the staging buffer is reusable the moment dispatch returns") becomes an
explicit slot lifecycle: a slot's buffer may be rewritten only after its
last transfer is KNOWN complete. The completion proof is the slot's
**fence** — the device-side logits of the dispatch that consumed the slot
(the donated input array itself is deleted by donation and cannot be
waited on): outputs existing implies the compute ran, which implies the
input transfer finished with the host memory. ``_SlotPool.acquire`` blocks
on the fence before handing a slot out (``serve.slot_wait_seconds`` — with
``staging_slots`` ≥ the pipeline's in-flight window this wait is normally
zero), so the H2D copy of batch N+1 overlaps compute of batch N while the
host buffers stay torn-write-free (yamt-lint YAMT014 pins the
mutation-after-async-device_put discipline this code is the sanctioned
idiom for). A dispatch that FAILS between the device_put and fence arming
(device OOM, a trace callback raising) orphans the slot's buffer — fresh
storage replaces it and the in-flight transfer keeps the old memory — so
the pipeline's keep-serving-after-engine-errors policy can never recycle a
possibly-in-transfer buffer. Overlapped and sync staging move the same wire bytes, so
logits are **bitwise identical** across the two modes (pinned by
tests/test_overlap.py across buckets, sizes, fused K, and bf16).

**Quantized wire** (``wire="uint8"``, serve.quant config, serve/quant.py):
clients submit RAW pixels, every staging slot / ``ShapeDtypeStruct`` /
transfer is ``uint8`` — exactly 1/4 of the f32 wire's bytes per image,
counted precisely by ``serve.h2d_bytes`` — and the compiled program
denormalizes on device with the pipeline's mean/std before the folded
forward (a fused prelude: one dispatch, no host normalize pass; a single
per-channel multiply when the mean is zero, which is the bitwise-parity
regime). Every other structure composes unchanged: fused K scans u8 chunk
buffers, overlap fences u8 slots, the sharded path snapshots u8. Int8-weight
bundles (``serve.quant.weights``, serve/export.py) need no engine plumbing
at all — ``apply_folded`` dequantizes ``w_q * w_scale`` in-program, so HBM
holds int8 while compute stays f32/bf16. There is ONE wire dtype per
engine, resolved from config at construction (never a per-call fork):
flipping ``serve.quant.wire`` is a config change, not a code path change.

**Device-resident request ring** (``ring_slots`` > 0, serve.ring config,
serve/ring.py): the steady-state generalization of the fused scan. R
pre-staged batch slots per (model, max-bucket, size) key are consumed by
ONE ``lax.scan`` dispatch carrying an active-slot mask — host threads only
feed slots (:meth:`InferenceEngine.ring_stage`: async ``device_put``
through a ring-private fence-tracked slot pool) and drain per-slot logits
(:meth:`InferenceEngine.ring_dispatch` returns a standard
:class:`PendingPrediction`); a partially-filled window runs the same
executable with padded slots entering as device-side zeros and their
outputs masked away, so ring logits are bitwise-identical to the per-batch
path by construction. Ring executables are keyed ``(model, bucket, size,
R)`` in their own cache alongside the fused ``(model, bucket, size, K)``
ladder; staging stays geometry-shared across zoo tenants. The ring
requires ``mesh=None`` (like fusion, device_put sharding semantics
differ) and each ring dispatch observes ``serve.dispatch_seconds`` exactly
once — a whole window is one engine piece, which is the point.

**Compilation never blocks warm traffic**: a cold (off-ladder) key compiles
under a dedicated compile lock with a double-checked insert, OUTSIDE the
dispatch lock — while one thread pays a cold compile, concurrent warm-size
dispatches proceed (a regression test pins it; the old behavior stalled ALL
traffic for the full compile). Off-ladder executables and staging buffers
live in a bounded LRU (``offladder_cache`` entries; on-ladder keys are
never evicted) so a size-scanning client cannot grow the caches without
bound — evictions count ``serve.evicted_executables``.

Input buffers are donated to the executable (``donate_argnums``): the padded
batch is engine-private and dead after the call, so XLA may overwrite it
in-place instead of allocating — on TPU that removes one HBM buffer per
in-flight request batch. The donated device array must never be read after
dispatch (yamt-lint YAMT008 exists to catch exactly that class of bug;
tests/fixtures/lint/yamt008/clean/async_engine_ok.py and
fused_scan_ok.py pin this engine's dispatch shapes as clean).

Optional data parallelism: pass a ``parallel/mesh`` mesh and every bucket is
sharded over its 'data' axis (params replicated) — the eval forward has no
collectives, so partitioning is pure SPMD batch splitting. The fused path
is bypassed under a mesh (device_put sharding semantics differ; the
per-chunk path serves every chunk exactly as before). The sharded path's
staging-copy semantics are PINNED, not defensive: ``shard_batch``'s
device_put reads the host buffer on a backend-defined schedule, so a
pool-owned staging buffer is snapshotted with a synchronous ``np.array``
copy before sharding and its slot is released immediately — the sharded
path never waits on (or arms) a fence, and overlap cannot corrupt sharded
inputs (regression-tested in tests/test_overlap.py).

Instrumentation (obs/): ``serve.dispatch_seconds`` (host stage+dispatch per
piece), ``serve.dispatch_to_complete_seconds`` (first dispatch -> logits on
host), ``serve.run_seconds`` (predict start -> result done),
``serve.h2d_seconds`` (host wall of the staging transfer call) /
``serve.slot_wait_seconds`` (fence waits on slot acquire),
``serve.fused_dispatches`` / ``serve.fused_chunks`` (fused pieces and the
chunks they covered), ``serve.evicted_executables``,
``serve.infer_images`` / ``serve.padded_rows`` / per-bucket hit counters;
``serve/stage`` + ``serve/h2d`` + ``serve/dispatch`` +
``serve/dispatch_fused`` + ``serve/complete`` spans. Device telemetry
(obs/device.py): every compile goes through ``timed_compile``
(``obs.compile_seconds``/``obs.compiles`` + per-executable
``obs.cost_flops.*``/``obs.cost_bytes.*`` cost_analysis gauges), every
dispatch feeds ``serve.dispatched_flops`` AND ``serve.dispatched_bytes``
(the transfer-side twin — cost_analysis bytes joined to dispatches), and
the derived ``serve.achieved_flops_per_s`` gauge is cost FLOPs ÷ measured
``serve.run_seconds`` — dispatch efficiency.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.specs import Network
from ..obs import device as obs_device
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..parallel import mesh as mesh_lib
from . import quant
from .admission import UnknownModel
from .export import InferenceBundle, apply_folded
from .ring import RingEntry

# the implicit model name of a single-bundle engine: its cost keys carry no
# model suffix, so every pre-zoo dashboard/bench key (serve_b8_s224_k1)
# stays valid — only explicitly-named zoo tenants get the _m<name> suffix
DEFAULT_MODEL = "default"

# bf16 serving parity bar vs the fp32 forward on the same folded weights:
# bf16 has an 8-bit mantissa (~0.4% relative), accumulated through a deep
# stack; measured max |logit delta| on the test nets is ~1e-2..1e-1, so the
# pinned tolerance carries ~3x headroom (tests/test_serve.py pins it, the
# serve_bench fp32-vs-bf16 A/B records the measured delta per artifact).
BF16_PARITY_ATOL = 0.35


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


def _cost_key(bucket: int, size: int, k: int, tag: str = "") -> str:
    """Registry-safe executable key for the per-key cost gauges
    (``obs.cost_flops.serve_b8_s224_k1``) and the hang report's table.
    ``tag`` distinguishes quantized variants (``_u8`` wire, ``_w8``
    weights) so an A/B running several engines in one process never
    cross-writes another mode's cost gauges."""
    return f"serve_b{bucket}_s{size}_k{k}{tag}"


def _ring_cost_key(bucket: int, size: int, r: int, tag: str = "") -> str:
    """Cost-gauge key of a ring executable — ``ring{R}`` instead of
    ``k{K}`` so a ring of depth 4 never collides with the fused K=4 scan
    of the same geometry (they are different programs: the ring carries
    the mask and R donated slot arguments)."""
    return f"serve_b{bucket}_s{size}_ring{r}{tag}"


class _StagingSlot:
    """One host staging buffer + the fence guarding its reuse.

    ``fence`` is the device-side logits of the dispatch that consumed this
    slot's transfer (armed right after dispatch, overlap mode only). The
    buffer may be rewritten only once the fence is ready: the executable's
    outputs existing proves the compute ran, which proves the async H2D
    transfer finished reading the host memory. The donated INPUT array
    cannot serve as the fence — donation deletes it the moment the dispatch
    returns."""

    __slots__ = ("buf", "fence")

    def __init__(self, buf: np.ndarray):
        self.buf = buf
        self.fence = None


class _SlotPool:
    """Round-robin pool of staging slots for one (bucket, size, K) key.

    Dispatches are serialized by the engine's dispatch lock, so the pool
    needs no lock of its own. With N slots, acquire() only blocks when the
    slot's consumer is still among the last N dispatches in flight — sized
    at (pipeline max_inflight), the fence wait is normally a no-op and
    ``serve.slot_wait_seconds`` stays ~0."""

    __slots__ = ("slots", "_next")

    def __init__(self, shape: tuple[int, ...], n: int, dtype=np.float32):
        # the buffer dtype IS the wire dtype (serve.quant.wire): uint8 slots
        # hold, and transfer, exactly 1/4 of the f32 bytes
        self.slots = [_StagingSlot(np.zeros(shape, dtype)) for _ in range(n)]
        self._next = 0

    def acquire(self, reg) -> _StagingSlot:
        """Next slot, its buffer safe to rewrite: waits for the slot's last
        armed fence (usually already ready) before handing it out."""
        slot = self.slots[self._next]
        self._next = (self._next + 1) % len(self.slots)
        if slot.fence is not None:
            t0 = time.perf_counter()
            jax.block_until_ready(slot.fence)
            reg.histogram("serve.slot_wait_seconds").observe(time.perf_counter() - t0)
            slot.fence = None
        return slot


class _ModelState:
    """Per-tenant state of one loaded bundle inside a multi-model engine
    (serve/zoo.py): its network, device-resident params, weight mode, cost
    tag, and image-size ladder. Executables are keyed ``(model, bucket,
    image_size, K)``; staging slot pools stay keyed ``(bucket, image_size,
    K)`` and are SHARED across tenants — a host staging buffer's shape and
    dtype depend only on the geometry and the wire, never on whose weights
    consume it (the fence lifecycle already guarantees the previous
    consumer, whichever model it was, finished reading before reuse)."""

    __slots__ = ("name", "net", "params", "weights", "cost_tag", "image_size", "image_sizes")

    def __init__(self, name: str, net: Network, params, weights: str, cost_tag: str,
                 image_size: int, image_sizes: tuple[int, ...]):
        self.name = name
        self.net = net
        self.params = params
        self.weights = weights
        self.cost_tag = cost_tag
        self.image_size = image_size
        self.image_sizes = image_sizes


class PendingPrediction:
    """Device-side handle returned by :meth:`InferenceEngine.predict_async`.

    Holds the dispatched-but-unsynced logits of every piece; ``result()`` is
    the ONE host<->device sync (device_get, slice off pad rows, concat) and
    caches its value, so calling it twice is free. It is thread-safe: a
    once-latch serializes concurrent callers, exactly one performs the sync
    and everyone gets the same cached array. Until the sync the device is
    free to still be computing — that's the point.

    ``dispatches`` is the number of engine dispatch pieces behind this
    handle (1 for an on-bucket or fully-fused batch, more when an oversized
    request decomposed) — it survives ``result()`` clearing ``_parts``, so
    the pipeline's ``serve.dispatches_per_wakeup`` can count real dispatches
    rather than handles.
    """

    __slots__ = ("_engine", "_parts", "_t_start", "_t_dispatched", "_out", "_lock", "_ctxs",
                 "dispatches")

    def __init__(self, engine: "InferenceEngine", parts, t_start: float, t_dispatched: float,
                 ctxs=()):
        self._engine = engine
        self._parts = parts  # [(device_logits, real_rows), ...]
        self.dispatches = len(parts)
        self._t_start = t_start
        self._t_dispatched = t_dispatched
        self._out: np.ndarray | None = None
        self._ctxs = tuple(ctxs)  # RequestContexts riding this handle (may be empty)
        # once-latch: two threads racing result() must not double-sync the
        # histograms or read _parts after the winner cleared it
        self._lock = threading.Lock()

    def result(self) -> np.ndarray:
        """Block until every piece's logits are on host; (N, num_classes)."""
        with self._lock:
            if self._out is None:
                reg = self._engine._reg
                with obs_trace.get_tracer().span("serve/complete", "serve", pieces=len(self._parts)):
                    outs = []
                    for dev, rows in self._parts:
                        arr = np.asarray(jax.device_get(dev))
                        # fused pieces come back (K, bucket, classes); flatten
                        # the chunk axis before slicing off the pad rows
                        outs.append(arr.reshape(-1, arr.shape[-1])[:rows])
                    # completed edge emitted INSIDE the complete span so the
                    # flow arrow binds to this slice on the sync thread
                    for c in self._ctxs:
                        c.advance("completed")
                now = time.perf_counter()
                reg.histogram("serve.dispatch_to_complete_seconds").observe(now - self._t_dispatched)
                reg.histogram("serve.run_seconds").observe(now - self._t_start)
                self._out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
                self._parts = ()  # drop the device references as soon as synced
            return self._out


class InferenceEngine:
    """Compiled serving wrapper around a loaded :class:`InferenceBundle`.

    ``predict(images)`` accepts any batch size: requests larger than the
    biggest bucket are served by the fused multi-chunk executables (one
    dispatch per ladder piece; per-chunk fallback), everything else is
    padded up to the smallest fitting bucket. ``predict_async`` is the
    no-sync variant feeding the pipelined batcher. Mixed image sizes hit the
    ``image_sizes`` ladder's warm executables; a size off the ladder
    compiles lazily (once, without blocking warm traffic) instead of
    failing, and ``serve.compile_seconds.count`` exposes the cliff.
    """

    def __init__(
        self,
        bundle: InferenceBundle | None = None,
        *,
        models: dict[str, InferenceBundle] | None = None,
        default_model: str | None = None,
        model_image_sizes: dict[str, Sequence[int]] | None = None,
        buckets: Sequence[int] = (1, 8, 32),
        compute_dtype: str = "float32",
        mesh=None,
        donate_input: bool = True,
        image_size: int | None = None,
        image_sizes: Sequence[int] | None = None,
        fuse_ladder: Sequence[int] = (2, 4),
        offladder_cache: int = 8,
        overlap_staging: bool = False,
        staging_slots: int = 2,
        wire: str = "float32",
        wire_mean: Sequence[float] | None = None,
        wire_std: Sequence[float] | None = None,
        ring_slots: int = 0,
    ):
        if not buckets:
            raise ValueError("engine needs at least one batch bucket")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {self.buckets}")
        # tenant resolution (serve/zoo.py): the legacy single-bundle form is
        # a one-model zoo under the reserved DEFAULT_MODEL name, whose cost
        # keys carry no model suffix — pre-zoo callers see zero change
        if models:
            if bundle is not None:
                raise ValueError("pass either bundle= or models=, not both")
            bundles = dict(models)
        else:
            if bundle is None:
                raise ValueError("engine needs a bundle or a models= dict")
            bundles = {DEFAULT_MODEL: bundle}
        for name in bundles:
            if not name or not name.replace("-", "").replace("_", "").isalnum():
                raise ValueError(
                    f"model name {name!r} must be non-empty [A-Za-z0-9_-] "
                    "(it becomes a metric-family and cost-key component)")
        self._default = default_model or next(iter(bundles))
        if self._default not in bundles:
            raise ValueError(
                f"default_model {self._default!r} not among loaded models {tuple(bundles)}")
        # chunk-count ladder for fused dispatch; K=1 (the per-chunk path) is
        # implicit, so only K >= 2 entries are meaningful. () disables fusion.
        self.fuse_ladder = tuple(sorted(set(int(k) for k in (fuse_ladder or ()) if int(k) >= 2)))
        if offladder_cache < 1:
            raise ValueError(f"offladder_cache must be >= 1, got {offladder_cache}")
        self._offladder_cap = int(offladder_cache)
        if staging_slots < 1:
            raise ValueError(f"staging_slots must be >= 1, got {staging_slots}")
        # overlapped staging: async jax.device_put through a fence-tracked
        # slot pool instead of the synchronous jnp.asarray copy (see module
        # docstring). Off = the legacy single-slot sync path, bit-identical.
        self._overlap = bool(overlap_staging)
        self._staging_slots = int(staging_slots) if self._overlap else 1
        self._compute_dtype = _dtype(compute_dtype)
        # the WIRE dtype (serve.quant.wire): what clients submit, what the
        # staging slots hold, and what crosses H2D. "uint8" ships RAW pixels
        # at 1/4 the bytes; the compiled program denormalizes on device with
        # the pipeline's mean/std (serve/quant.py — a single per-channel
        # multiply when the mean is zero, which is the bitwise-parity case).
        # There is ONE wire per engine — it is a transport property, so every
        # zoo tenant shares it (and the shared staging slot pools).
        self._wire = wire
        self._wire_np = quant.wire_np_dtype(wire)  # validates the name too
        self._wire_jnp = jnp.uint8 if wire == "uint8" else jnp.float32
        self._denorm_scale, self._denorm_shift = quant.denorm_constants(wire_mean, wire_std)
        # device-resident request ring (serve.ring config, serve/ring.py):
        # 0 = off. The ring is a mesh-less structure for the same reason
        # fusion is (device_put sharding semantics differ under a mesh),
        # and a depth-1 "ring" is just the per-batch path with extra steps.
        if ring_slots and ring_slots < 2:
            raise ValueError(f"ring_slots must be 0 (off) or >= 2, got {ring_slots}")
        if ring_slots and mesh is not None:
            raise ValueError("the request ring requires mesh=None "
                             "(data-parallel serving rides the per-chunk path)")
        self._ring_slots = int(ring_slots)
        self._mesh = mesh
        self._donate = donate_input
        if mesh is not None:
            bad = [b for b in self.buckets if b % mesh.size]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by the {mesh.size}-device mesh; "
                    "data-parallel serving pads to whole per-device shards"
                )
        # per-tenant state: net, device params, weight mode (int8-weight
        # bundles need no engine plumbing — apply_folded dequantizes
        # in-program — but cost-gauge keys must not collide across modes OR
        # models in one process), cost tag, and image-size ladder. The
        # legacy image_size/image_sizes kwargs apply to the default model.
        sizes_by_model = dict(model_image_sizes or {})
        self._model_states: dict[str, _ModelState] = {}
        for name, b in bundles.items():
            m_size = int(image_size) if (image_size and name == self._default) \
                else int(b.net.image_size)
            extra = sizes_by_model.get(name)
            if extra is None and name == self._default:
                extra = image_sizes
            m_sizes = tuple(sorted(set(int(s) for s in (extra or ())) | {m_size}))
            if m_sizes[0] < 1:
                raise ValueError(f"image sizes must be >= 1, got {m_sizes} for {name!r}")
            m_weights = "int8" if any(
                "w_q" in leaf for leaf in jax.tree.leaves(
                    b.params, is_leaf=lambda x: isinstance(x, dict) and "w_q" in x)
                if isinstance(leaf, dict)
            ) else "float32"
            cost_tag = ("_u8" if wire == "uint8" else "") + (
                "_w8" if m_weights == "int8" else "") + (
                f"_m{name}" if name != DEFAULT_MODEL else "")
            params = (mesh_lib.replicate(b.params, mesh) if mesh is not None
                      else jax.tree.map(jnp.asarray, b.params))
            self._model_states[name] = _ModelState(
                name, b.net, params, m_weights, cost_tag, m_size, m_sizes)
        # single-model compatibility surface: the default tenant's identity
        # IS the engine's (tests, healthz, and the sync batcher read these)
        _st = self._model_states[self._default]
        self.net: Network = _st.net
        self.image_size = _st.image_size
        self.image_sizes = _st.image_sizes
        self._params = _st.params
        self._weights = _st.weights
        self._cost_tag = _st.cost_tag
        # executables are keyed (model, bucket, image_size, K); K == 1 is the
        # plain per-chunk executable, K >= 2 the fused scan. Staging slot
        # pools stay keyed (bucket, image_size, K) — geometry + wire fully
        # determine a host buffer, so tenants SHARE the pools (fences make
        # cross-model reuse safe exactly like same-model reuse).
        self._compiled: dict[tuple[str, int, int, int], jax.stages.Compiled] = {}
        self._staging: dict[tuple[int, int, int], _SlotPool] = {}
        # ring executables keyed (model, bucket, image_size, R) in their own
        # cache alongside the fused ladder (a ring program has a different
        # signature: mask + R donated slots). Ring staging pools are keyed
        # (bucket, image_size) and — like the per-piece pools — SHARED
        # across zoo tenants: a slot's host buffer depends only on geometry
        # and wire. The pipeline only engages the ring on ladder sizes
        # (ring_ready), so these caches are bounded by the warmed ladder.
        self._ring_compiled: dict[tuple[str, int, int, int], jax.stages.Compiled] = {}
        self._ring_staging: dict[tuple[int, int], _SlotPool] = {}
        # off-ladder keys live in a bounded PER-MODEL LRU (on-ladder keys are
        # pinned): a size-scanning client must not grow the caches without
        # bound, and a churn burst on one tenant must never evict another
        # tenant's warm executables (each model gets its own offladder_cache
        # budget — the no-cross-eviction contract tests/test_zoo.py pins)
        self._offladder: dict[str, OrderedDict[tuple[int, int, int], None]] = {
            name: OrderedDict() for name in self._model_states}
        # one dispatcher at a time: staging buffers are reused across calls
        self._dispatch_lock = threading.Lock()
        # compiles serialize with each other but NOT with dispatch: a cold
        # key must never stall concurrent warm traffic (double-checked
        # insert in _ensure_compiled)
        self._compile_lock = threading.Lock()
        # guards _compiled/_staging/_offladder mutation + LRU bookkeeping
        self._cache_lock = threading.Lock()
        self._reg = get_registry()
        # device telemetry (obs/device.py, both idempotent): memory pull
        # gauges + the achieved-FLOPS dispatch-efficiency gauge
        obs_device.install_memory_gauges(self._reg)
        obs_device.install_dispatch_efficiency_gauge(self._reg)

    # -- zoo surface --------------------------------------------------------

    @property
    def models(self) -> tuple[str, ...]:
        """Names of the loaded tenants (a single-bundle engine reports the
        reserved ``("default",)``) — the set the lease advertises and the
        admission edge validates X-Model against."""
        return tuple(self._model_states)

    @property
    def default_model(self) -> str:
        """The tenant unqualified requests (no X-Model) resolve to."""
        return self._default

    def model_weights(self, model: str) -> str:
        """Weight storage of one tenant's bundle ("float32" | "int8")."""
        return self._model_state(model).weights

    def model_image_ladder(self, model: str) -> tuple[int, ...]:
        """One tenant's warmed image-size ladder."""
        return self._model_state(model).image_sizes

    def _model_state(self, model: str | None) -> _ModelState:
        st = self._model_states.get(model or self._default)
        if st is None:
            raise UnknownModel(model, self._model_states)
        return st

    # -- quantization surface ----------------------------------------------

    @property
    def wire(self) -> str:
        """The wire mode name ("float32" | "uint8")."""
        return self._wire

    @property
    def wire_np_dtype(self):
        """numpy dtype clients' batches are coerced to (the batchers read
        this so submit-side coercion matches the staging buffers)."""
        return self._wire_np

    @property
    def weights(self) -> str:
        """Weight storage of the loaded bundle ("float32" | "int8")."""
        return self._weights

    @property
    def quant_mode(self) -> str:
        """One label summarizing both quantization rungs — the
        ``serve.quant_mode`` build-info value (docs/OBSERVABILITY.md)."""
        return f"wire={self._wire},weights={self._weights}"

    @property
    def wire_parity_exact(self) -> bool:
        """True when the u8 wire's device denorm is a single per-channel
        multiply (zero mean): logits are BITWISE identical to the f32 wire
        fed :func:`serve.quant.normalize_reference` pixels. With a nonzero
        mean the backend may fuse the multiply+add into an FMA, so parity is
        the measured-delta gate instead (serve/quant.py)."""
        return quant.shift_free(self._denorm_shift)

    # -- compilation --------------------------------------------------------

    def _on_ladder(self, model: str, key: tuple[int, int, int]) -> bool:
        bucket, size, k = key
        return (
            bucket in self.buckets
            and size in self._model_states[model].image_sizes
            and (k == 1 or k in self.fuse_ladder)
        )

    def _build(self, model: str, bucket: int, size: int, k: int):
        st = self._model_states[model]

        def run_one(params, x):
            if self._wire == "uint8":
                # the uint8 wire's in-program denorm prelude: raw pixels ->
                # the f32 values the f32 wire would have carried (a single
                # per-channel multiply when the mean is zero — the bitwise
                # case; serve/quant.py). Fused into the same dispatch.
                x = quant.denormalize_device(x, self._denorm_scale, self._denorm_shift)
            return apply_folded(st.net, params, x, compute_dtype=self._compute_dtype)

        if k == 1:
            run = run_one
            x_shape = jax.ShapeDtypeStruct((bucket, size, size, 3), self._wire_jnp)
        else:
            # the chunk loop, in-program: scan the SAME per-chunk forward
            # over the leading chunk axis — one dispatch for K chunks
            def run(params, xs):
                def body(carry, x):
                    return carry, run_one(params, x)

                _, ys = jax.lax.scan(body, None, xs)
                return ys

            x_shape = jax.ShapeDtypeStruct((k, bucket, size, size, 3), self._wire_jnp)
        kwargs = {}
        if self._mesh is not None:
            kwargs["in_shardings"] = (
                mesh_lib.replicated_sharding(self._mesh),
                mesh_lib.batch_sharding(self._mesh),
            )
        fn = jax.jit(run, donate_argnums=(1,) if self._donate else (), **kwargs)
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("serve/compile", "serve", bucket=bucket, image_size=size,
                                         k=k, model=model):
            # obs/device.py: compile time -> obs.compile_seconds/obs.compiles,
            # cost_analysis flops/bytes -> per-executable obs.cost_* gauges —
            # every warmed executable is cost-accounted in the obs snapshot
            compiled = obs_device.timed_compile(
                fn.lower(st.params, x_shape), _cost_key(bucket, size, k, st.cost_tag),
                registry=self._reg,
            )
        self._reg.histogram("serve.compile_seconds").observe(time.perf_counter() - t0)
        return compiled

    def _build_ring(self, model: str, bucket: int, size: int, r: int):
        """Compile the ring executable for ``(model, bucket, size, R)``: a
        ``lax.scan`` over R stacked slot arrays plus an active-slot mask.
        The scan body is the SAME per-chunk forward the (bucket, size, 1)
        executable compiles — denorm prelude included on the u8 wire — so
        an active slot's logits are bitwise-identical to the per-batch
        path; a masked (padded) slot's output is selected to zeros by a
        scalar-bool ``where``, which cannot perturb the active slots. All
        R slot arguments are donated (each is engine-staged and dead after
        the call); the mask and params are not."""
        st = self._model_states[model]

        def run_one(params, x):
            if self._wire == "uint8":
                # same in-program denorm prelude as _build's K executables
                # (serve/quant.py): the ring scans RAW u8 slots and
                # denormalizes inside the scan body
                x = quant.denormalize_device(x, self._denorm_scale, self._denorm_shift)
            return apply_folded(st.net, params, x, compute_dtype=self._compute_dtype)

        def run(params, mask, *slots):
            xs = jnp.stack(slots)

            def body(carry, xm):
                x, m = xm
                y = run_one(params, x)
                # scalar-bool select: active slots pass through bit-exact,
                # padded slots' outputs are discarded by the drain anyway
                return carry, jnp.where(m, y, jnp.zeros_like(y))

            _, ys = jax.lax.scan(body, None, (xs, mask))
            return ys

        slot_shape = jax.ShapeDtypeStruct((bucket, size, size, 3), self._wire_jnp)
        mask_shape = jax.ShapeDtypeStruct((r,), jnp.bool_)
        donate = tuple(range(2, 2 + r)) if self._donate else ()
        fn = jax.jit(run, donate_argnums=donate)
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("serve/compile", "serve", bucket=bucket,
                                         image_size=size, ring=r, model=model):
            compiled = obs_device.timed_compile(
                fn.lower(st.params, mask_shape, *([slot_shape] * r)),
                _ring_cost_key(bucket, size, r, st.cost_tag),
                registry=self._reg,
            )
        self._reg.histogram("serve.compile_seconds").observe(time.perf_counter() - t0)
        return compiled

    def _ensure_ring_compiled(self, model: str, key: tuple[int, int, int]):
        """Ring executable for ``(model, bucket, size, R)``, compiling on
        miss with the same never-block-warm-traffic discipline as
        :meth:`_ensure_compiled`. No LRU: ring keys are bounded by the
        warmed ladder (the pipeline refuses off-ladder ring engagement)."""
        full = (model,) + key
        with self._cache_lock:
            exe = self._ring_compiled.get(full)
        if exe is not None:
            return exe
        with self._compile_lock:
            with self._cache_lock:
                exe = self._ring_compiled.get(full)
            if exe is not None:
                return exe
            exe = self._build_ring(model, *key)
            with self._cache_lock:
                self._ring_compiled[full] = exe
            return exe

    def _ensure_compiled(self, model: str, key: tuple[int, int, int]):
        """Executable for ``(model, *key)``, compiling on miss WITHOUT
        holding the dispatch lock (double-checked insert): warm traffic
        keeps flowing while a cold size pays its compile. Off-ladder
        eviction is scoped to ``model``'s own LRU slice; the shared staging
        pool for the evicted geometry is dropped only when NO tenant still
        holds an executable of that geometry."""
        full = (model,) + key
        with self._cache_lock:
            exe = self._compiled.get(full)
            if exe is not None:
                lru = self._offladder[model]
                if key in lru:
                    lru.move_to_end(key)
                return exe
        with self._compile_lock:
            with self._cache_lock:
                exe = self._compiled.get(full)
            if exe is not None:
                return exe
            exe = self._build(model, *key)
            with self._cache_lock:
                self._compiled[full] = exe
                if not self._on_ladder(model, key):
                    lru = self._offladder[model]
                    lru[key] = None
                    lru.move_to_end(key)
                    while len(lru) > self._offladder_cap:
                        old, _ = lru.popitem(last=False)
                        self._compiled.pop((model,) + old, None)
                        if not any((m,) + old in self._compiled for m in self._model_states):
                            self._staging.pop(old, None)
                        self._reg.counter("serve.evicted_executables").inc()
            return exe

    def warmup(self) -> None:
        """AOT-compile every ladder executable up front so the first request
        of any size never hits a compile stall: for EVERY tenant, each
        (bucket, image_size) pair of its own ladder, plus — when fusion is
        on — the fused (max-bucket, size, K) scan for every K on the fuse
        ladder."""
        cap = self.buckets[-1]
        for model, st in self._model_states.items():
            for s in st.image_sizes:
                for b in self.buckets:
                    self._ensure_compiled(model, (b, s, 1))
                if self._mesh is None:
                    for k in self.fuse_ladder:
                        self._ensure_compiled(model, (cap, s, k))
                    if self._ring_slots:
                        self._ensure_ring_compiled(model, (cap, s, self._ring_slots))

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # -- dispatch -----------------------------------------------------------

    def _plan(self, n: int, size: int) -> list[tuple[int, int, int, int]]:
        """Split an N-row request into dispatch pieces ``(start, rows,
        bucket, k)``, in row order. Full max-bucket chunks fuse greedily
        into the largest ladder K first (7 chunks with ladder {2, 4} ->
        4+2+1 -> 3 dispatches); the tail chunk joins a fused piece only when
        it would pad up to the max bucket anyway (same bucket => same
        executable compute => parity with the per-chunk path is preserved);
        otherwise it dispatches per-chunk into its own smaller bucket,
        exactly as before. K=1 pieces are the unchanged per-chunk path."""
        cap = self.buckets[-1]
        m = -(-n // cap)  # chunk count, ceil
        tail = n - (m - 1) * cap
        fusable = 0
        if self.fuse_ladder and self._mesh is None and m >= 2:
            fusable = m if self._bucket_for(tail) == cap else m - 1
        pieces: list[tuple[int, int, int, int]] = []
        chunk = 0
        rem = fusable
        for k in sorted(self.fuse_ladder, reverse=True):
            while rem >= k:
                start = chunk * cap
                rows = min(n, (chunk + k) * cap) - start
                pieces.append((start, rows, cap, k))
                chunk += k
                rem -= k
        while chunk < m:
            start = chunk * cap
            rows = min(n, start + cap) - start
            pieces.append((start, rows, self._bucket_for(rows), 1))
            chunk += 1
        return pieces

    def _stage(self, rows_arr: np.ndarray, key: tuple[int, int, int]):
        """Executable-shaped host array for a piece's rows, as ``(array,
        slot)``: the rows themselves (reshaped, zero-copy — ``slot`` None;
        the caller's batch is never rewritten by the engine, so it needs no
        fence) when they fill the piece exactly, else a slot from the
        per-(bucket, size, K) pool with the tail rows zeroed. Acquire waits
        on the slot's fence, so an overlapped in-flight transfer is never
        torn by the rewrite; only the pad rows are re-zeroed — no
        per-dispatch allocation, no full-buffer copy."""
        bucket, size, k = key
        total = k * bucket
        n = rows_arr.shape[0]
        shape = (bucket, size, size, 3) if k == 1 else (k, bucket, size, size, 3)
        if n == total:
            return np.ascontiguousarray(rows_arr).reshape(shape), None
        with self._cache_lock:
            pool = self._staging.get(key)
            if pool is None:
                pool = self._staging[key] = _SlotPool(shape, self._staging_slots, self._wire_np)
        slot = pool.acquire(self._reg)
        flat = slot.buf.reshape(total, size, size, 3)
        flat[:n] = rows_arr
        flat[n:] = 0
        self._reg.counter("serve.padded_rows").inc(total - n)
        return slot.buf, slot

    def _dispatch_piece(self, images: np.ndarray, piece: tuple[int, int, int, int], size: int,
                        ctxs=(), model: str | None = None):
        """Stage + dispatch ONE piece (a chunk, or K fused chunks) against
        ``model``'s executable; returns (device_logits, real_rows) without
        syncing. The device array handed to the executable is donated; it is
        never read afterwards (YAMT008 discipline). ``ctxs`` are the piece's
        request contexts: their ids land on the dispatch span and their flow
        steps bind inside it."""
        start, rows, bucket, k = piece
        st = self._model_state(model)
        key = (bucket, size, k)
        exe = self._ensure_compiled(st.name, key)  # pre-warmed by predict_async; a hit
        tracer = obs_trace.get_tracer()
        t0 = time.perf_counter()
        slot = None
        wire_nbytes = 0
        try:
            with tracer.span("serve/stage", "serve", bucket=bucket, rows=rows, k=k):
                staged, slot = self._stage(images[start : start + rows], key)
                wire_nbytes = staged.nbytes
                if self._mesh is not None:
                    # pinned copy semantics: shard_batch's device_put reads the
                    # host buffer on a backend-defined schedule, so a pool-owned
                    # buffer is snapshotted synchronously and its slot freed NOW
                    # — the sharded path never arms a fence, and overlapped
                    # staging cannot tear sharded inputs (tests/test_overlap.py)
                    if slot is not None:
                        staged = np.array(staged)
                        slot = None
                    x = mesh_lib.shard_batch({"image": staged}, self._mesh)["image"]
                else:
                    t_h2d = time.perf_counter()
                    with tracer.span("serve/h2d", "serve", bucket=bucket, k=k,
                                     overlap=self._overlap):
                        if self._overlap:
                            # async H2D: device_put may return BEFORE the device
                            # has read the host memory — the slot fence armed
                            # after dispatch is what makes the buffer's next
                            # rewrite safe (YAMT014 discipline)
                            x = jax.device_put(staged)
                        else:
                            # jnp.asarray copies synchronously: the staging
                            # buffer is reusable the moment dispatch returns
                            # (parity tests pin it)
                            x = jnp.asarray(staged)
                    self._reg.histogram("serve.h2d_seconds").observe(time.perf_counter() - t_h2d)
            span = "serve/dispatch" if k == 1 else "serve/dispatch_fused"
            span_args = dict(bucket=bucket, image_size=size, rows=rows, k=k, model=st.name)
            if ctxs:
                span_args["rids"] = [c.rid for c in ctxs[:16]]  # keep args tiny
            with tracer.span(span, "serve", **span_args):
                logits = exe(st.params, x)
                for c in ctxs:  # in-span: the flow arrow binds to this slice
                    c.advance("dispatched")
                    tracer.flow_step("serve/req", c.rid)
            if slot is not None and self._overlap:
                # the executable's outputs existing proves its input transfer is
                # done with the host memory: the logits are the reuse fence
                slot.fence = logits
        except BaseException:
            if slot is not None and self._overlap:
                # A failure between the async device_put and fence arming
                # (device OOM in the executable, a trace callback raising)
                # would return the slot to rotation with NO fence while the
                # H2D transfer may still be reading its buffer — the next
                # acquire would rewrite it unguarded and hand the device torn
                # input. Orphan the buffer instead: the in-flight transfer
                # keeps the old memory alive, the slot gets fresh storage,
                # and the engine keeps serving (the pipeline deliberately
                # survives engine exceptions).
                slot.buf = np.zeros_like(slot.buf)
                slot.fence = None
            raise
        self._reg.histogram("serve.dispatch_seconds").observe(time.perf_counter() - t0)
        if k > 1:
            self._reg.counter("serve.fused_dispatches").inc()
            self._reg.counter("serve.fused_chunks").inc(k)
        self._reg.counter(f"serve.bucket_hits.{bucket}").inc(k)
        # the EXACT bytes this dispatch put on the H2D wire (the staged host
        # array's nbytes — wire-dtype-sized, so the uint8 wire shows the 4x
        # drop precisely): the instrument the quant A/B reads, next to the
        # cost-analysis whole-program serve.dispatched_bytes below
        if wire_nbytes:
            self._reg.counter("serve.h2d_bytes").inc(wire_nbytes)
        # cost-analysis FLOPs + bytes this dispatch put on the device: the
        # numerator of serve.achieved_flops_per_s (dispatch efficiency) and
        # its transfer-side twin serve.dispatched_bytes (obs/device.py).
        # XLA costs a lax.scan body ONCE, but the fused program runs the same
        # per-chunk forward k times — account k x the per-chunk cost.
        for counter, lookup in (
            ("serve.dispatched_flops", obs_device.flops_for),
            ("serve.dispatched_bytes", obs_device.bytes_for),
        ):
            cost = lookup(_cost_key(bucket, size, k, st.cost_tag))
            if k > 1:
                per_chunk = lookup(_cost_key(bucket, size, 1, st.cost_tag))
                if per_chunk:
                    cost = per_chunk * k
            if cost:
                self._reg.counter(counter).inc(cost)
        return logits, rows

    # -- device-resident request ring (serve/ring.py) -----------------------

    @property
    def ring_slots(self) -> int:
        """Ring depth R (0 = ring mode off) — the pipeline's engagement
        signal and the window's slot budget."""
        return self._ring_slots

    def ring_ready(self, model: str | None, size: int) -> bool:
        """Whether a ring window may form for ``(model, size)`` traffic:
        ring mode on, and ``size`` on the tenant's warmed ladder (an
        off-ladder size rides the per-batch path — it keeps the ring
        executable cache bounded by the ladder, and a size cold enough to
        be off-ladder is not the saturated steady state anyway)."""
        if not self._ring_slots:
            return False
        st = self._model_states.get(model or self._default)
        return st is not None and int(size) in st.image_sizes

    def ring_stage(self, images: np.ndarray) -> RingEntry:
        """Feed ONE ring slot: stage up to max-bucket rows into a host slot
        buffer and start its H2D transfer, WITHOUT dispatching — the host
        side of the window keeps feeding (and the device keeps computing
        the previous window) while this transfer is in flight. Returns the
        :class:`~.ring.RingEntry` that :meth:`ring_dispatch` consumes.

        Single-feeder contract: the ring staging pools are (deliberately)
        as lock-free as the dispatch-path pools, so slots are fed from ONE
        thread — the pipeline's collect thread. An exact-bucket feed
        transfers the caller's array zero-copy (freshly-stacked, per the
        predict_async contract); a partial feed copies into a pool slot
        whose fence (the consuming ring dispatch's logits) guards reuse."""
        if not self._ring_slots:
            raise RuntimeError("ring mode is off (ring_slots=0)")
        images = quant.coerce_wire(images, self._wire_np)
        if images.ndim != 4 or images.shape[1] != images.shape[2]:
            raise ValueError(f"ring_stage expects (N, S, S, 3), got shape {images.shape}")
        bucket = self.buckets[-1]
        n = images.shape[0]
        if not 0 < n <= bucket:
            raise ValueError(f"a ring slot holds 1..{bucket} rows, got {n}")
        size = int(images.shape[1])
        tracer = obs_trace.get_tracer()
        with tracer.span("serve/stage", "serve", bucket=bucket, rows=n, ring=True):
            if n == bucket:
                staged, slot = np.ascontiguousarray(images), None
            else:
                key = (bucket, size)
                with self._cache_lock:
                    pool = self._ring_staging.get(key)
                    if pool is None:
                        # 2R host buffers: R possibly consumed by the
                        # in-flight window + R being fed for the next one —
                        # the fence wait stays ~0 at steady state
                        pool = self._ring_staging[key] = _SlotPool(
                            (bucket, size, size, 3), 2 * self._ring_slots, self._wire_np)
                slot = pool.acquire(self._reg)
                slot.buf[:n] = images
                slot.buf[n:] = 0
                self._reg.counter("serve.padded_rows").inc(bucket - n)
                staged = slot.buf
            t_h2d = time.perf_counter()
            with tracer.span("serve/h2d", "serve", bucket=bucket, ring=True,
                             overlap=self._overlap):
                if self._overlap:
                    # async H2D: the slot's buffer is rewritable only after
                    # the consuming ring dispatch's fence (YAMT014)
                    x = jax.device_put(staged)
                else:
                    x = jnp.asarray(staged)
            self._reg.histogram("serve.h2d_seconds").observe(time.perf_counter() - t_h2d)
        self._reg.counter("serve.h2d_bytes").inc(staged.nbytes)
        return RingEntry(x, n, slot)

    def ring_dispatch(self, entries: Sequence[RingEntry], ctxs=(),
                      model: str | None = None) -> PendingPrediction:
        """Consume a window of staged slots in ONE dispatch: the masked
        ring scan runs every staged slot (and R - staged device-side zero
        pads) through ``model``'s forward, and the returned handle drains
        all per-slot logits with a single device_get. Every slot but the
        last must be FULL — the drain flattens ``(R, bucket, classes)``
        and slices the first ``rows``, which is only the staged rows when
        they are contiguous. Observes ``serve.dispatch_seconds`` exactly
        once: a whole window is one engine piece (``handle.dispatches`` ==
        1), which is what ``serve.dispatches_per_wakeup`` counts."""
        st = self._model_state(model)
        r = self._ring_slots
        if not r:
            raise RuntimeError("ring mode is off (ring_slots=0)")
        entries = list(entries)
        if not 0 < len(entries) <= r:
            raise ValueError(f"a ring window holds 1..{r} slots, got {len(entries)}")
        bucket = self.buckets[-1]
        if any(e.rows != bucket for e in entries[:-1]):
            raise ValueError("only the LAST ring slot may be partial "
                             "(the drain relies on contiguous valid rows)")
        size = int(entries[0].x.shape[1])
        rows = (len(entries) - 1) * bucket + entries[-1].rows
        ctxs = tuple(ctxs)
        exe = self._ensure_ring_compiled(st.name, (bucket, size, r))  # warmup hit
        self._reg.counter("serve.infer_images").inc(rows)
        if st.name != DEFAULT_MODEL:
            self._reg.counter(f"serve.infer_images.{st.name}").inc(rows)
        t_start = time.perf_counter()
        tracer = obs_trace.get_tracer()
        with self._dispatch_lock:
            t0 = time.perf_counter()
            try:
                span_args = dict(bucket=bucket, image_size=size, rows=rows,
                                 slots=len(entries), r=r, model=st.name)
                if ctxs:
                    span_args["rids"] = [c.rid for c in ctxs[:16]]
                with tracer.span("serve/ring", "serve", **span_args):
                    mask = np.zeros((r,), np.bool_)
                    mask[: len(entries)] = True
                    xs = [e.x for e in entries] + [
                        # device-side zero fill for the masked slots: no H2D,
                        # and each is a DISTINCT buffer (they are all donated)
                        jnp.zeros((bucket, size, size, 3), self._wire_jnp)
                        for _ in range(r - len(entries))
                    ]
                    ys = exe(st.params, jnp.asarray(mask), *xs)
                    for c in ctxs:
                        c.advance("dispatched")
                        tracer.flow_step("serve/req", c.rid)
                if self._overlap:
                    for e in entries:
                        if e.slot is not None:
                            # the window's outputs existing proves every
                            # slot's transfer finished: one fence for all
                            e.slot.fence = ys
            except BaseException:
                if self._overlap:
                    # same orphan discipline as _dispatch_piece: a failure
                    # before fence arming must not recycle buffers whose
                    # transfers may still be in flight
                    for e in entries:
                        if e.slot is not None:
                            e.slot.buf = np.zeros_like(e.slot.buf)
                            e.slot.fence = None
                raise
        self._reg.histogram("serve.dispatch_seconds").observe(time.perf_counter() - t0)
        self._reg.counter("serve.ring_dispatches").inc()
        if st.name != DEFAULT_MODEL:
            self._reg.counter(f"serve.ring_dispatches.{st.name}").inc()
        self._reg.histogram("serve.ring_slots_per_dispatch").observe(len(entries))
        self._reg.gauge("serve.ring_fill").set(len(entries) / r)
        self._reg.counter(f"serve.bucket_hits.{bucket}").inc(len(entries))
        # the device really computes ALL R scan iterations (the mask selects
        # outputs, it does not skip compute), so account R x the per-chunk
        # cost — the fill waste is visible as serve.ring_fill < 1, not
        # hidden in the FLOPs
        for counter, lookup in (
            ("serve.dispatched_flops", obs_device.flops_for),
            ("serve.dispatched_bytes", obs_device.bytes_for),
        ):
            per_chunk = lookup(_cost_key(bucket, size, 1, st.cost_tag))
            cost = per_chunk * r if per_chunk else lookup(
                _ring_cost_key(bucket, size, r, st.cost_tag))
            if cost:
                self._reg.counter(counter).inc(cost)
        return PendingPrediction(self, [(ys, rows)], t_start, time.perf_counter(), ctxs=ctxs)

    def predict_async(self, images: np.ndarray, ctxs=None,
                      model: str | None = None) -> PendingPrediction:
        """Dispatch without syncing: (N, S, S, 3) in the WIRE dtype -> handle
        whose ``result()`` yields (N, num_classes) float32 logits. On the
        float32 wire inputs are already-normalized pixels (pipeline
        semantics, the historical contract); on the uint8 wire they are RAW
        pixels 0..255 (integer arrays pass through; float arrays are
        rounded-and-clipped, serve/quant.py) and the compiled program
        denormalizes on device. An oversized
        request becomes ONE fused dispatch per ladder piece (a whole
        on-ladder request is a single dispatch + single transfer); every
        piece is dispatched before the caller can sync, so the device
        pipeline never drains between pieces.

        ``ctxs`` (optional) are the batch rows' RequestContexts
        (serve/context.py): their ids ride the dispatch spans and their
        phase/flow trace edges fire inside the engine's spans, so one
        request correlates from HTTP handler to completion thread.

        ``model`` (optional) names the zoo tenant to serve this batch
        (serve/zoo.py); None resolves to the default tenant, and an unserved
        name raises the typed :class:`~.admission.UnknownModel` — never a
        KeyError. One batch targets exactly one model (the batchers group by
        (model, shape) upstream).

        Caller contract under overlapped staging: an exact-bucket batch is
        transferred zero-copy via async ``device_put``, so ``images`` must
        not be mutated until ``result()`` returns (the batchers always pass
        freshly-stacked arrays; with ``overlap_staging=False`` the transfer
        copies synchronously and no such constraint exists)."""
        st = self._model_state(model)  # typed UnknownModel before any work
        images = quant.coerce_wire(images, self._wire_np)
        if images.ndim != 4 or images.shape[1] != images.shape[2]:
            raise ValueError(f"predict expects (N, S, S, 3), got shape {images.shape}")
        n = images.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        ctxs = tuple(ctxs or ())
        size = int(images.shape[1])
        self._reg.counter("serve.infer_images").inc(n)
        if st.name != DEFAULT_MODEL:
            self._reg.counter(f"serve.infer_images.{st.name}").inc(n)
        t_start = time.perf_counter()
        pieces = self._plan(n, size)
        # compile anything cold BEFORE taking the dispatch lock: a cold size
        # must not stall concurrent warm-size dispatches
        for key in {(bucket, size, k) for _, _, bucket, k in pieces}:
            self._ensure_compiled(st.name, key)
        # row i <-> ctxs[i] only when the caller submitted one ctx per row
        # (the batcher's coalesced single-image requests); otherwise the
        # whole batch belongs to every ctx (a multi-row client request)
        per_row = len(ctxs) == n
        with self._dispatch_lock:
            parts = [
                self._dispatch_piece(
                    images, piece, size,
                    ctxs=ctxs[piece[0] : piece[0] + piece[1]] if per_row else ctxs,
                    model=st.name,
                )
                for piece in pieces
            ]
        return PendingPrediction(self, parts, t_start, time.perf_counter(), ctxs=ctxs)

    def predict(self, images: np.ndarray, ctxs=None, model: str | None = None) -> np.ndarray:
        """(N, S, S, 3) in the wire dtype (float32 wire: already-normalized
        pipeline pixels; uint8 wire: raw pixels, denormalized on device) ->
        (N, num_classes) float32 logits. N is unconstrained: > max bucket is
        served fused (one dispatch per ladder piece), all dispatched before
        the single sync. ``model`` selects the zoo tenant (None = default)."""
        return self.predict_async(images, ctxs=ctxs, model=model).result()
