"""AOT-batched inference engine: bucketed shapes, pad-and-slice dispatch.

XLA programs are shape-static, so a serving path that jits on the request's
natural batch size recompiles on every new size — a latency cliff exactly
when traffic shifts. The engine instead fixes a small ladder of batch
**buckets** (e.g. 1/8/32), AOT-compiles one executable per bucket at warmup
(``jit(...).lower(...).compile()`` — no first-request compile stall), and
dispatches every batch to the smallest bucket that fits, zero-padding the
tail rows and slicing them back off the logits. Padding is sound because the
folded forward is row-independent (no BN batch statistics — the export fold
removed BN entirely), so the real rows' logits are BITWISE identical to an
unpadded run of the same bucket (pinned by tests/test_serve.py).

Input buffers are donated to the executable (``donate_argnums``): the padded
batch is engine-private and dead after the call, so XLA may overwrite it
in-place instead of allocating — on TPU that removes one HBM buffer per
in-flight request batch. The padded array must never be read after dispatch
(yamt-lint YAMT008 exists to catch exactly that class of bug).

Optional data parallelism: pass a ``parallel/mesh`` mesh and every bucket is
sharded over its 'data' axis (params replicated) — the eval forward has no
collectives, so partitioning is pure SPMD batch splitting.

Instrumentation (obs/): ``serve.run_seconds`` / ``serve.infer_images`` /
``serve.padded_rows`` / per-bucket hit counters in the registry; a
``serve/run`` span per dispatch.
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.specs import Network
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..parallel import mesh as mesh_lib
from .export import InferenceBundle, apply_folded


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


class InferenceEngine:
    """Compiled serving wrapper around a loaded :class:`InferenceBundle`.

    ``predict(images)`` accepts any batch size: requests larger than the
    biggest bucket are chunked, everything else is padded up to the smallest
    fitting bucket. One host sync per chunk (the device_get of the logits).
    """

    def __init__(
        self,
        bundle: InferenceBundle,
        *,
        buckets: Sequence[int] = (1, 8, 32),
        compute_dtype: str = "float32",
        mesh=None,
        donate_input: bool = True,
        image_size: int | None = None,
    ):
        if not buckets:
            raise ValueError("engine needs at least one batch bucket")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {self.buckets}")
        self.net: Network = bundle.net
        self.image_size = int(image_size or bundle.net.image_size)
        self._compute_dtype = _dtype(compute_dtype)
        self._mesh = mesh
        self._donate = donate_input
        if mesh is not None:
            bad = [b for b in self.buckets if b % mesh.size]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by the {mesh.size}-device mesh; "
                    "data-parallel serving pads to whole per-device shards"
                )
            self._params = mesh_lib.replicate(bundle.params, mesh)
        else:
            self._params = jax.tree.map(jnp.asarray, bundle.params)
        self._compiled: dict[int, jax.stages.Compiled] = {}
        self._reg = get_registry()

    # -- compilation --------------------------------------------------------

    def _build(self, bucket: int):
        def run(params, x):
            return apply_folded(self.net, params, x, compute_dtype=self._compute_dtype)

        kwargs = {}
        if self._mesh is not None:
            kwargs["in_shardings"] = (
                mesh_lib.replicated_sharding(self._mesh),
                mesh_lib.batch_sharding(self._mesh),
            )
        fn = jax.jit(run, donate_argnums=(1,) if self._donate else (), **kwargs)
        x_shape = jax.ShapeDtypeStruct((bucket, self.image_size, self.image_size, 3), jnp.float32)
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("serve/compile", "serve", bucket=bucket):
            compiled = fn.lower(self._params, x_shape).compile()
        self._reg.histogram("serve.compile_seconds").observe(time.perf_counter() - t0)
        return compiled

    def warmup(self) -> None:
        """AOT-compile every bucket up front so the first request of any size
        hits a ready executable, never a compile stall."""
        for b in self.buckets:
            if b not in self._compiled:
                self._compiled[b] = self._build(b)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # -- dispatch -----------------------------------------------------------

    def _run_chunk(self, chunk: np.ndarray) -> np.ndarray:
        n = chunk.shape[0]
        bucket = self._bucket_for(n)
        if bucket not in self._compiled:
            self._compiled[bucket] = self._build(bucket)
        if n < bucket:
            pad = np.zeros((bucket - n,) + chunk.shape[1:], chunk.dtype)
            chunk = np.concatenate([chunk, pad], axis=0)
            self._reg.counter("serve.padded_rows").inc(bucket - n)
        if self._mesh is not None:
            x = mesh_lib.shard_batch({"image": chunk}, self._mesh)["image"]
        else:
            x = jnp.asarray(chunk)
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("serve/run", "serve", bucket=bucket, rows=n):
            logits = self._compiled[bucket](self._params, x)
            out = np.asarray(jax.device_get(logits))[:n]
        self._reg.histogram("serve.run_seconds").observe(time.perf_counter() - t0)
        self._reg.counter(f"serve.bucket_hits.{bucket}").inc()
        return out

    def predict(self, images: np.ndarray) -> np.ndarray:
        """(N, H, W, 3) float32 (already normalized, pipeline semantics) ->
        (N, num_classes) float32 logits. N is unconstrained: > max bucket is
        served in max-bucket chunks."""
        images = np.asarray(images, np.float32)
        if images.ndim != 4:
            raise ValueError(f"predict expects (N, H, W, 3), got shape {images.shape}")
        n = images.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        self._reg.counter("serve.infer_images").inc(n)
        cap = self.buckets[-1]
        if n <= cap:
            return self._run_chunk(images)
        outs = [self._run_chunk(images[i : i + cap]) for i in range(0, n, cap)]
        return np.concatenate(outs, axis=0)
