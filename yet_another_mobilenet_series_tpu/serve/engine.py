"""AOT-batched inference engine: bucketed shapes, pipelined async dispatch.

XLA programs are shape-static, so a serving path that jits on the request's
natural batch size recompiles on every new size — a latency cliff exactly
when traffic shifts. The engine instead fixes a small ladder of batch
**buckets** (e.g. 1/8/32) and an **image-size ladder** (e.g. 192/224/256),
AOT-compiles one executable per ``(bucket, image_size)`` pair at warmup
(``jit(...).lower(...).compile()`` — no first-request compile stall), and
dispatches every batch to the smallest bucket that fits, zero-padding the
tail rows and slicing them back off the logits. Padding is sound because the
folded forward is row-independent (no BN batch statistics — the export fold
removed BN entirely), so the real rows' logits are BITWISE identical to an
unpadded run of the same bucket (pinned by tests/test_serve.py).

**Async dispatch** is the pipelining primitive: :meth:`predict_async` stages
and dispatches every chunk of a request and returns a
:class:`PendingPrediction` WITHOUT syncing — JAX's async dispatch keeps the
device computing while the host pads/stages the next chunk (or the next
request entirely; serve/pipeline.py builds continuous batching on top).
Large requests dispatch ALL chunks before the first ``device_get``; the only
host<->device sync is :meth:`PendingPrediction.result`. ``predict`` is
literally ``predict_async(...).result()``, so the two paths share one
executable and are bitwise-identical by construction.

Tail padding writes into a **reused per-(bucket, size) staging buffer**
instead of ``np.concatenate([chunk, pad])``: no allocation per dispatch, and
only the pad rows are re-zeroed. Reuse right after dispatch is safe because
``jnp.asarray`` copies the host buffer synchronously (the device array never
aliases the staging memory); the multi-chunk bitwise-parity tests would
catch any backend that broke that assumption.

Input buffers are donated to the executable (``donate_argnums``): the padded
batch is engine-private and dead after the call, so XLA may overwrite it
in-place instead of allocating — on TPU that removes one HBM buffer per
in-flight request batch. The donated device array must never be read after
dispatch (yamt-lint YAMT008 exists to catch exactly that class of bug;
tests/fixtures/lint/yamt008/clean/async_engine_ok.py pins this engine's
dispatch shape as clean).

Optional data parallelism: pass a ``parallel/mesh`` mesh and every bucket is
sharded over its 'data' axis (params replicated) — the eval forward has no
collectives, so partitioning is pure SPMD batch splitting.

Instrumentation (obs/): ``serve.dispatch_seconds`` (host stage+dispatch per
chunk), ``serve.dispatch_to_complete_seconds`` (first dispatch -> logits on
host), ``serve.run_seconds`` (predict start -> result done),
``serve.infer_images`` / ``serve.padded_rows`` / per-bucket hit counters;
``serve/stage`` + ``serve/dispatch`` + ``serve/complete`` spans.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.specs import Network
from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..parallel import mesh as mesh_lib
from .export import InferenceBundle, apply_folded

# bf16 serving parity bar vs the fp32 forward on the same folded weights:
# bf16 has an 8-bit mantissa (~0.4% relative), accumulated through a deep
# stack; measured max |logit delta| on the test nets is ~1e-2..1e-1, so the
# pinned tolerance carries ~3x headroom (tests/test_serve.py pins it, the
# serve_bench fp32-vs-bf16 A/B records the measured delta per artifact).
BF16_PARITY_ATOL = 0.35


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


class PendingPrediction:
    """Device-side handle returned by :meth:`InferenceEngine.predict_async`.

    Holds the dispatched-but-unsynced logits of every chunk; ``result()`` is
    the ONE host<->device sync (device_get, slice off pad rows, concat) and
    caches its value, so calling it twice is free. Until then the device is
    free to still be computing — that's the point.
    """

    __slots__ = ("_engine", "_parts", "_t_start", "_t_dispatched", "_out")

    def __init__(self, engine: "InferenceEngine", parts, t_start: float, t_dispatched: float):
        self._engine = engine
        self._parts = parts  # [(device_logits, real_rows), ...]
        self._t_start = t_start
        self._t_dispatched = t_dispatched
        self._out: np.ndarray | None = None

    def result(self) -> np.ndarray:
        """Block until every chunk's logits are on host; (N, num_classes)."""
        if self._out is None:
            reg = self._engine._reg
            with obs_trace.get_tracer().span("serve/complete", "serve", chunks=len(self._parts)):
                outs = [np.asarray(jax.device_get(dev))[:rows] for dev, rows in self._parts]
            now = time.perf_counter()
            reg.histogram("serve.dispatch_to_complete_seconds").observe(now - self._t_dispatched)
            reg.histogram("serve.run_seconds").observe(now - self._t_start)
            self._out = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
            self._parts = ()  # drop the device references as soon as synced
        return self._out


class InferenceEngine:
    """Compiled serving wrapper around a loaded :class:`InferenceBundle`.

    ``predict(images)`` accepts any batch size: requests larger than the
    biggest bucket are chunked, everything else is padded up to the smallest
    fitting bucket. ``predict_async`` is the no-sync variant feeding the
    pipelined batcher. Mixed image sizes hit the ``image_sizes`` ladder's
    warm executables; a size off the ladder compiles lazily (once) instead
    of failing, and ``serve.compile_seconds.count`` exposes the cliff.
    """

    def __init__(
        self,
        bundle: InferenceBundle,
        *,
        buckets: Sequence[int] = (1, 8, 32),
        compute_dtype: str = "float32",
        mesh=None,
        donate_input: bool = True,
        image_size: int | None = None,
        image_sizes: Sequence[int] | None = None,
    ):
        if not buckets:
            raise ValueError("engine needs at least one batch bucket")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"batch buckets must be >= 1, got {self.buckets}")
        self.net: Network = bundle.net
        self.image_size = int(image_size or bundle.net.image_size)
        self.image_sizes = tuple(sorted(set(int(s) for s in (image_sizes or ())) | {self.image_size}))
        if self.image_sizes[0] < 1:
            raise ValueError(f"image sizes must be >= 1, got {self.image_sizes}")
        self._compute_dtype = _dtype(compute_dtype)
        self._mesh = mesh
        self._donate = donate_input
        if mesh is not None:
            bad = [b for b in self.buckets if b % mesh.size]
            if bad:
                raise ValueError(
                    f"buckets {bad} not divisible by the {mesh.size}-device mesh; "
                    "data-parallel serving pads to whole per-device shards"
                )
            self._params = mesh_lib.replicate(bundle.params, mesh)
        else:
            self._params = jax.tree.map(jnp.asarray, bundle.params)
        # executables and staging buffers are keyed (bucket, image_size)
        self._compiled: dict[tuple[int, int], jax.stages.Compiled] = {}
        self._staging: dict[tuple[int, int], np.ndarray] = {}
        # one dispatcher at a time: staging buffers are reused across calls
        self._dispatch_lock = threading.Lock()
        self._reg = get_registry()

    # -- compilation --------------------------------------------------------

    def _build(self, bucket: int, size: int):
        def run(params, x):
            return apply_folded(self.net, params, x, compute_dtype=self._compute_dtype)

        kwargs = {}
        if self._mesh is not None:
            kwargs["in_shardings"] = (
                mesh_lib.replicated_sharding(self._mesh),
                mesh_lib.batch_sharding(self._mesh),
            )
        fn = jax.jit(run, donate_argnums=(1,) if self._donate else (), **kwargs)
        x_shape = jax.ShapeDtypeStruct((bucket, size, size, 3), jnp.float32)
        t0 = time.perf_counter()
        with obs_trace.get_tracer().span("serve/compile", "serve", bucket=bucket, image_size=size):
            compiled = fn.lower(self._params, x_shape).compile()
        self._reg.histogram("serve.compile_seconds").observe(time.perf_counter() - t0)
        return compiled

    def warmup(self) -> None:
        """AOT-compile every (bucket, image_size) pair up front so the first
        request of any size on the ladder hits a ready executable, never a
        compile stall."""
        for s in self.image_sizes:
            for b in self.buckets:
                if (b, s) not in self._compiled:
                    self._compiled[(b, s)] = self._build(b, s)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # -- dispatch -----------------------------------------------------------

    def _stage(self, chunk: np.ndarray, bucket: int, size: int) -> np.ndarray:
        """Bucket-shaped host array for ``chunk``: the chunk itself when it
        fills the bucket exactly, else the reused per-(bucket, size) staging
        buffer with the tail rows zeroed. Only the pad rows are re-zeroed —
        no per-dispatch allocation, no full-buffer copy."""
        n = chunk.shape[0]
        if n == bucket:
            return np.ascontiguousarray(chunk)
        key = (bucket, size)
        buf = self._staging.get(key)
        if buf is None:
            buf = self._staging[key] = np.zeros((bucket, size, size, 3), np.float32)
        buf[:n] = chunk
        buf[n:] = 0.0
        self._reg.counter("serve.padded_rows").inc(bucket - n)
        return buf

    def _dispatch_chunk(self, chunk: np.ndarray, size: int):
        """Stage + dispatch ONE chunk; returns (device_logits, real_rows)
        without syncing. The device array handed to the executable is
        donated; it is never read afterwards (YAMT008 discipline)."""
        n = chunk.shape[0]
        bucket = self._bucket_for(n)
        key = (bucket, size)
        if key not in self._compiled:
            self._compiled[key] = self._build(bucket, size)
        tracer = obs_trace.get_tracer()
        t0 = time.perf_counter()
        with tracer.span("serve/stage", "serve", bucket=bucket, rows=n):
            staged = self._stage(chunk, bucket, size)
            if self._mesh is not None:
                # defensive: device_put's host-read timing is backend-defined,
                # so never hand the reused staging buffer to the sharded path
                if staged is self._staging.get(key):
                    staged = np.array(staged)
                x = mesh_lib.shard_batch({"image": staged}, self._mesh)["image"]
            else:
                # jnp.asarray copies synchronously: the staging buffer is
                # reusable the moment dispatch returns (parity tests pin it)
                x = jnp.asarray(staged)
        with tracer.span("serve/dispatch", "serve", bucket=bucket, image_size=size, rows=n):
            logits = self._compiled[key](self._params, x)
        self._reg.histogram("serve.dispatch_seconds").observe(time.perf_counter() - t0)
        self._reg.counter(f"serve.bucket_hits.{bucket}").inc()
        return logits, n

    def predict_async(self, images: np.ndarray) -> PendingPrediction:
        """Dispatch without syncing: (N, S, S, 3) float32 -> handle whose
        ``result()`` yields (N, num_classes) float32 logits. Every chunk of
        an oversized request is dispatched before the caller can sync, so
        the device pipeline never drains between chunks."""
        images = np.asarray(images, np.float32)
        if images.ndim != 4 or images.shape[1] != images.shape[2]:
            raise ValueError(f"predict expects (N, S, S, 3), got shape {images.shape}")
        n = images.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        size = int(images.shape[1])
        self._reg.counter("serve.infer_images").inc(n)
        t_start = time.perf_counter()
        cap = self.buckets[-1]
        with self._dispatch_lock:
            parts = [self._dispatch_chunk(images[i : i + cap], size) for i in range(0, n, cap)]
        return PendingPrediction(self, parts, t_start, time.perf_counter())

    def predict(self, images: np.ndarray) -> np.ndarray:
        """(N, S, S, 3) float32 (already normalized, pipeline semantics) ->
        (N, num_classes) float32 logits. N is unconstrained: > max bucket is
        served in max-bucket chunks, all dispatched before the single sync."""
        return self.predict_async(images).result()
