"""Brownout: a graceful-degradation ladder under sustained overload.

Every resilience layer so far answers *failure* (retry, breaker, hedging,
replica restart) or answers overload by **rejecting harder** (quotas,
deadline shedding, queue bounds). Nothing trades *quality* for *goodput*:
under a sustained storm the hedger keeps duplicating work, the batchers
keep lingering for fill, and best_effort traffic keeps competing with
interactive at the quota boundary. The brownout controller closes that gap
with the overload half of the tail-at-scale playbook: a deterministic,
ORDERED ladder of degradations, stepped by the same measured signals the
autoscaler consumes (serve/signals.py — windowed per-class p99 off registry
bucket-count deltas, queue depth, breaker state), cheapest degradation
first:

======  ==================================================================
level   what degrades (cumulative — each level keeps everything below it)
======  ==================================================================
L0      healthy: nothing degraded
L1      hedging disabled — stop DUPLICATING work before shedding any
L2      batchers fill-or-flush — no coalescing linger; full batches only
        come from the backlog a storm supplies anyway
L3      best_effort rejected at the door (503 + ``Retry-After``)
L4      deadline-admission margin tightened (predicted wait inflated by
        ``margin``) + the batch class shed too
L5      interactive-only survival mode: every non-interactive class shed,
        transient-failure retries disabled, margin tightened further
======  ==================================================================

Stepping is **asymmetric with hysteresis and cooldown**: the ladder steps
UP one level per ``hold_up_s`` while overloaded (react in seconds — an
overload compounds), and steps DOWN one level per ``cooldown_s`` only
while every signal sits below the *down* thresholds (recover slowly — the
dead band between up/down thresholds plus the one-level-per-cooldown rule
makes the ladder monotone through a storm instead of flapping, the same
discipline as the autoscaler's scale actions). An open breaker counts as
overload evidence on its own: rejected requests never reach the latency
histogram, so the window can look idle exactly when the engine is sickest.

The controller owns no serving state — it PUSHES an immutable
:class:`BrownoutPolicy` into whichever actuation targets it was built with
(each implementing ``apply_brownout(policy)``): the batcher
(fill-or-flush), the admission controller (class shed / margin / retries),
and the router (hedging, class shed at the fleet tier). Observability:
``serve.brownout_level`` gauge (rides /metrics, /varz, and /healthz),
``serve.brownout_transitions`` counter with ``.up``/``.down`` direction
splits, a ``serve/brownout`` span per transition, and an autoscaler-style
:attr:`trace` of per-tick rows the serve_bench ``--overload`` artifact
records. docs/SERVING.md "Overload & brownout" is the operator's guide.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit
from .signals import SignalReader, Signals

# ladder depth: levels are 0..MAX_LEVEL inclusive
MAX_LEVEL = 5


@dataclasses.dataclass(frozen=True)
class BrownoutPolicy:
    """One ladder level's complete degradation set, pushed whole into every
    actuation target so a level change is atomic per target."""

    level: int
    hedging: bool  # may the router arm hedge timers?
    fill_or_flush: bool  # batchers skip the coalescing linger?
    shed_classes: frozenset[str]  # rejected at the door with Retry-After
    deadline_margin: float  # multiplier on the admission wait predictor
    retries: bool  # transient-failure retries still run?
    retry_after_s: float = 1.0  # the Retry-After hint on brownout sheds


def build_ladder(retry_after_s: float = 1.0) -> tuple[BrownoutPolicy, ...]:
    """The ordered L0..L5 policy ladder (module docstring table)."""
    none: frozenset[str] = frozenset()
    return (
        BrownoutPolicy(0, True, False, none, 1.0, True, retry_after_s),
        BrownoutPolicy(1, False, False, none, 1.0, True, retry_after_s),
        BrownoutPolicy(2, False, True, none, 1.0, True, retry_after_s),
        BrownoutPolicy(3, False, True, frozenset({"best_effort"}), 1.0, True, retry_after_s),
        # margins stay moderate (1.5x / 2.5x): the margin guts admission of
        # deadline-carrying traffic multiplicatively on top of the backlog
        # factor, and an over-tight L5 empties the queue so hard the ladder
        # oscillates at the top instead of holding
        BrownoutPolicy(4, False, True, frozenset({"best_effort", "batch"}), 1.5, True,
                       retry_after_s),
        BrownoutPolicy(5, False, True, frozenset({"best_effort", "batch"}), 2.5, False,
                       retry_after_s),
    )


class BrownoutController:
    """Steps the degradation ladder off one :class:`~.signals.SignalReader`.

    ``targets`` is any iterable of objects implementing
    ``apply_brownout(policy)`` (MicroBatcher / AdmissionController / Router
    — each consumes its own slice and ignores the rest). The decision logic
    is a plain :meth:`step` so tests drive it from scripted signal traces
    with injected clocks; :meth:`start` wraps it in the usual guarded
    control thread.
    """

    def __init__(
        self,
        signals: SignalReader,
        targets=(),
        *,
        interval_s: float = 0.5,
        up_p99_ms: float = 400.0,
        down_p99_ms: float = 100.0,
        up_queue_depth: float = 16.0,
        down_queue_depth: float = 2.0,
        hold_up_s: float = 1.0,
        cooldown_s: float = 5.0,
        max_level: int = MAX_LEVEL,
        retry_after_s: float = 1.0,
        log_fn=None,
    ):
        if down_p99_ms >= up_p99_ms or down_queue_depth >= up_queue_depth:
            raise ValueError("brownout down thresholds must sit strictly below up "
                             "thresholds (the dead band is the hysteresis)")
        if not 0 <= max_level <= MAX_LEVEL:
            raise ValueError(f"brownout max_level must be in [0, {MAX_LEVEL}], got {max_level}")
        if hold_up_s <= 0 or cooldown_s <= 0:
            raise ValueError("brownout hold_up_s and cooldown_s must be > 0")
        self._signals = signals
        self._targets = list(targets)
        self._interval_s = interval_s
        self._up_p99_s = up_p99_ms / 1e3
        self._down_p99_s = down_p99_ms / 1e3
        self._up_queue = up_queue_depth
        self._down_queue = down_queue_depth
        self._hold_up_s = hold_up_s
        self._cooldown_s = cooldown_s
        self._max_level = max_level
        # transition announcements; benches whose stdout IS the artifact
        # inject a stderr printer (the bench-contract one-JSON-line rule)
        self._log = log_fn or emit
        self._ladder = build_ladder(retry_after_s)
        self.level = 0
        self._last_up_t: float | None = None
        self._last_change_t: float | None = None
        self._t0 = time.perf_counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._reg = get_registry()
        self._reg.gauge("serve.brownout_level").set(0)
        # per-tick rows (t/level/p99_ms/queue_depth/breaker/action) — the
        # ladder-over-time trajectory the --overload bench artifact records
        self.trace: list[dict] = []
        self._apply(self._ladder[0])

    @property
    def policy(self) -> BrownoutPolicy:
        return self._ladder[self.level]

    # -- actuation -----------------------------------------------------------

    def _apply(self, policy: BrownoutPolicy) -> None:
        for target in self._targets:
            target.apply_brownout(policy)

    def _transition(self, new_level: int, now: float) -> None:
        direction = "up" if new_level > self.level else "down"
        with obs_trace.get_tracer().span("serve/brownout", "serve",
                                         frm=self.level, to=new_level):
            self.level = new_level  # yamt-lint: disable=YAMT019 — single-writer int publish from the controller loop; readers tolerate one stale tick
            self._apply(self._ladder[new_level])
        self._reg.gauge("serve.brownout_level").set(new_level)
        self._reg.counter("serve.brownout_transitions").inc()
        self._reg.counter(f"serve.brownout_transitions.{direction}").inc()
        self._last_change_t = now
        if direction == "up":
            self._last_up_t = now
        self._log(f"[serve] brownout {direction}: L{new_level} "
                  f"({'degrading' if direction == 'up' else 'recovering'})")

    # -- the control step ----------------------------------------------------

    def step(self, now: float | None = None, signals: Signals | None = None) -> dict:
        """One ladder decision; ``now``/``signals`` injectable for scripted
        tests. Returns the appended trace row."""
        now = time.perf_counter() if now is None else now
        sig = self._signals.read() if signals is None else signals
        overloaded = (
            (sig.p99_s is not None and sig.p99_s > self._up_p99_s)
            or sig.queue_depth > self._up_queue
            or sig.breaker_open
        )
        relaxed = (
            (sig.p99_s is None or sig.p99_s < self._down_p99_s)
            and sig.queue_depth < self._down_queue
            and not sig.breaker_open
        )
        action = "hold"
        if overloaded and self.level < self._max_level:
            # step UP at most once per hold_up_s: reacting fast matters, but
            # one window of bad luck must not jump straight to survival mode
            if self._last_up_t is None or now - self._last_up_t >= self._hold_up_s:
                self._transition(self.level + 1, now)
                action = "up"
        elif relaxed and self.level > 0:
            # step DOWN one level per cooldown: each restored degradation
            # adds load back, and the window must prove it holds before the
            # next restoration — the ladder cannot flap
            if self._last_change_t is None or now - self._last_change_t >= self._cooldown_s:
                self._transition(self.level - 1, now)
                action = "down"
        row = {
            "t": round(now - self._t0, 3),
            "level": self.level,
            "p99_ms": round(sig.p99_s * 1e3, 3) if sig.p99_s is not None else None,
            "queue_depth": round(sig.queue_depth, 3),
            "breaker_open": sig.breaker_open,
            "action": action,
        }
        self.trace.append(row)
        return row

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        pol = self.policy
        return {
            "level": self.level,
            "max_level": self._max_level,
            "hedging": pol.hedging,
            "fill_or_flush": pol.fill_or_flush,
            "shed_classes": sorted(pol.shed_classes),
            "deadline_margin": pol.deadline_margin,
            "retries": pol.retries,
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "BrownoutController":
        if self._thread is not None:
            raise RuntimeError("brownout controller already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="serve-brownout", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:  # YAMT011: a dead controller must be loud, not a frozen ladder
            while not self._stop.wait(self._interval_s):
                self.step()
        except Exception as e:  # noqa: BLE001 — contain, count, report
            get_registry().counter("serve.thread_crashes").inc()
            emit(f"[serve] brownout thread crashed: {type(e).__name__}: {e}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    @classmethod
    def from_config(cls, bc, signals: SignalReader, targets=()) -> "BrownoutController":
        """Build from a config.BrownoutConfig block (both CLIs)."""
        return cls(
            signals, targets,
            interval_s=bc.interval_s,
            up_p99_ms=bc.up_p99_ms, down_p99_ms=bc.down_p99_ms,
            up_queue_depth=bc.up_queue_depth, down_queue_depth=bc.down_queue_depth,
            hold_up_s=bc.hold_up_s, cooldown_s=bc.cooldown_s,
            max_level=bc.max_level, retry_after_s=bc.retry_after_s,
        )
