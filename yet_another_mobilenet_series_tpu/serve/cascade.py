"""Confidence cascade: cheap model answers, uncertain answers escalate.

FLASH/LANA-style cascading is the zoo's dominant cost lever: most requests
are EASY — a small int8 model's top-1 is already the big model's top-1 —
and the expensive tier should only burn FLOPs on the requests the small
tier is unsure about. :class:`CascadeTier` implements that policy at the
ROUTER level: it speaks the same submit protocol the frontend consumes
(``submit(image, priority, deadline_ms, ctx) -> Future`` + ``state()``),
wraps a :class:`~.router.Router`, and for each request

1. routes it to the **small** tier (a normal router submit — weighted
   pick over the replicas advertising the small model, retries, hedging);
2. scores the answer's confidence as the **top-1 softmax margin**
   (``p1 - p2`` — how far the winner is ahead of the runner-up);
3. **answers from the small tier** when the margin clears
   ``cascade.threshold`` (``serve.cascade.answered_small``), or
   **re-submits to the big tier** when it does not
   (``serve.cascade.escalations``), riding the SAME leg machinery
   (placement-aware pick, transport retries, hedging) with the escalation's
   legs stamped at ``TRACE_SEQ_CASCADE_BASE`` (serve/context.py) — a merged
   fleet trace shows small-leg -> escalation-leg as distinct rows of one
   request, never confused with a retry or a hedge.

Deadline preservation: the escalation inherits the request's REMAINING
deadline budget (elapsed small-tier time subtracted). A request whose
budget is already burned when the low-confidence answer lands returns the
small answer instead of escalating into a certain 504 — a degraded answer
beats a typed failure at the same cost (``serve.cascade.deadline_skips``).
An escalation that FAILS (no big-tier replica, transport exhaustion) also
falls back to the small answer (``serve.cascade.escalation_failures``) —
the cascade may never make a request fail that the small tier answered.

Explicit model pins: a request naming a model via ``X-Model`` bypasses the
cascade (``respect_explicit_model=True``, the default) — the cascade is a
policy for clients that did NOT choose; a client that chose gets exactly
what it asked for.

Instrumentation: ``serve.cascade.escalations`` /
``serve.cascade.answered_small`` counters, the ``serve.cascade.
escalation_rate`` gauge (escalations / decided), per-tier
``serve.cascade.latency_seconds.{small,big}`` histograms, and a
``serve.cascade.margin`` histogram of observed confidence margins (the
threshold-tuning instrument: its quantiles say what any given threshold
would have escalated).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from ..obs.registry import get_registry
from .context import TRACE_SEQ_CASCADE_BASE, RequestContext


def softmax_margin(logits) -> float:
    """Top-1 softmax margin of one logits row: ``p_top1 - p_top2`` in
    [0, 1]. Shift-invariant and monotone in the top-two logit gap; a
    single-class row is maximally confident by definition."""
    row = np.asarray(logits, np.float64).reshape(-1)
    if row.size < 2:
        return 1.0
    z = row - row.max()
    p = np.exp(z)
    p /= p.sum()
    top2 = np.partition(p, -2)[-2:]
    return float(top2[1] - top2[0])


class CascadeTier:
    """Router-level confidence cascade over a small and a big zoo tenant.

    Drop-in for the router in the frontend's admission slot: everything
    but ``submit``/``state`` delegates to the wrapped router (membership
    registration, backends, brownout — the cascade is routing POLICY, not
    membership)."""

    def __init__(self, router, *, small: str, big: str, threshold: float = 0.15,
                 respect_explicit_model: bool = True):
        if not 0.0 <= float(threshold) <= 1.0:
            raise ValueError(f"cascade threshold must be in [0, 1], got {threshold}")
        if small == big:
            raise ValueError(f"cascade small and big tiers are both {small!r}")
        self._router = router
        self.small = small
        self.big = big
        self.threshold = float(threshold)
        self._respect_explicit = bool(respect_explicit_model)
        self._reg = get_registry()
        self._lock = threading.Lock()
        self._escalations = 0
        self._answered_small = 0

    # -- the serving protocol (what Frontend consumes) -----------------------

    def submit(self, image, *, priority: str | None = None,
               deadline_ms: float | None = None, ctx=None,
               model: str | None = None) -> Future:
        model = model or (ctx.model if ctx is not None else None)
        if model is not None and self._respect_explicit:
            # the client PINNED a tenant: policy defers to choice
            self._reg.counter("serve.cascade.bypassed_explicit").inc()
            return self._router.submit(image, priority=priority,
                                       deadline_ms=deadline_ms, ctx=ctx, model=model)
        outer: Future = Future()
        t0 = time.perf_counter()
        inner = self._router.submit(image, priority=priority,
                                    deadline_ms=deadline_ms, ctx=ctx, model=self.small)
        inner.add_done_callback(
            lambda f: self._on_small(f, outer, image, priority, deadline_ms, ctx, t0)
        )
        return outer

    def _on_small(self, inner: Future, outer: Future, image, priority,
                  deadline_ms, ctx, t0: float) -> None:
        try:  # a crashed policy callback must not hang the outer future
            exc = inner.exception()
            if exc is not None:
                # the small tier FAILED (typed shed, no replica, ...): the
                # verdict passes through — cascading is for answers, not
                # for masking the fleet's admission decisions
                outer.set_exception(exc)
                return
            logits = inner.result()
            elapsed_s = time.perf_counter() - t0
            self._reg.histogram("serve.cascade.latency_seconds.small").observe(elapsed_s)
            margin = softmax_margin(logits)
            self._reg.histogram("serve.cascade.margin").observe(margin)
            if margin >= self.threshold:
                self._decided(escalated=False)
                self._reg.counter("serve.cascade.answered_small").inc()
                outer.set_result(logits)
                return
            remaining_ms = None
            if deadline_ms is not None:
                remaining_ms = deadline_ms - elapsed_s * 1e3
                if remaining_ms <= 0.0:
                    # the budget is gone: a degraded answer now beats a
                    # guaranteed 504 after another round trip
                    self._decided(escalated=False)
                    self._reg.counter("serve.cascade.deadline_skips").inc()
                    self._reg.counter("serve.cascade.answered_small").inc()
                    outer.set_result(logits)
                    return
            self._decided(escalated=True)
            self._reg.counter("serve.cascade.escalations").inc()
            # the escalation is its own routed request: a fresh context
            # (new trace id) pinned to the big tier, its legs stamped in
            # the cascade band (TRACE_SEQ_CASCADE_BASE) so the merged
            # trace tells an escalation from a retry or a hedge
            esc_ctx = RequestContext.mint(
                ctx.cls if ctx is not None else (priority or "interactive"),
                remaining_ms,
                client_tag=f"{ctx.wire_id}-cascade" if ctx is not None else None,
                model=self.big,
            )
            t_big = time.perf_counter()
            big_fut = self._router.submit(
                image, priority=priority, deadline_ms=remaining_ms, ctx=esc_ctx,
                model=self.big, seq_base=TRACE_SEQ_CASCADE_BASE,
            )
            big_fut.add_done_callback(
                lambda f: self._on_big(f, outer, logits, t_big)
            )
        except Exception as e:  # noqa: BLE001 — resolve, never hang
            if not outer.done():
                outer.set_exception(e)

    def _on_big(self, big_fut: Future, outer: Future, small_logits, t_big: float) -> None:
        try:
            exc = big_fut.exception()
            if exc is None:
                self._reg.histogram("serve.cascade.latency_seconds.big").observe(
                    time.perf_counter() - t_big)
                outer.set_result(big_fut.result())
                return
            # escalation failed: the small answer stands — the cascade may
            # never turn an answered request into a failure
            self._reg.counter("serve.cascade.escalation_failures").inc()
            outer.set_result(small_logits)
        except Exception as e:  # noqa: BLE001 — resolve, never hang
            if not outer.done():
                outer.set_exception(e)

    def _decided(self, *, escalated: bool) -> None:
        with self._lock:
            if escalated:
                self._escalations += 1
            else:
                self._answered_small += 1
            decided = self._escalations + self._answered_small
            rate = self._escalations / decided if decided else 0.0
        self._reg.gauge("serve.cascade.escalation_rate").set(rate)

    # -- introspection -------------------------------------------------------

    def state(self) -> dict:
        doc = self._router.state()
        with self._lock:
            decided = self._escalations + self._answered_small
            doc["cascade"] = {
                "small": self.small,
                "big": self.big,
                "threshold": self.threshold,
                "escalations": self._escalations,
                "answered_small": self._answered_small,
                "escalation_rate": (self._escalations / decided) if decided else 0.0,
            }
        return doc

    def __getattr__(self, name: str):
        # routing policy wraps membership/observability verbatim: /register,
        # backends(), apply_brownout, start/stop, ... all reach the router
        return getattr(self._router, name)
