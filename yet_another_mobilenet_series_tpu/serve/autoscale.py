"""Autoscaler: a control thread scaling the replica count off measured load.

The fleet's two live load signals are exactly the families /metrics already
exposes: tail latency (the router's per-class
``serve.router.latency_seconds`` histogram) and backlog (the per-replica
``queued_total`` the router polls from every ``/healthz``). The autoscaler
reads both at ``interval_s`` cadence and nudges the supervisor's target
replica count N inside ``[min_replicas, max_replicas]``:

- **scale up** when the WINDOW p99 (bucket-count deltas since the last
  tick, through the registry's own quantile math — not the whole-run
  quantile, which old traffic would anchor; the shared
  :class:`~.signals.SignalReader` implementation, consumed by the brownout
  ladder too) exceeds ``up_p99_ms`` OR the mean routable queue depth
  exceeds ``up_queue_depth``;
- **scale down** when the window p99 is below ``down_p99_ms`` (or the
  window is empty — an idle fleet drains to ``min_replicas``) AND the mean
  queue depth is below ``down_queue_depth``;
- **cooldown hysteresis**: after ANY scaling action, no further action for
  ``cooldown_s`` — a spawn takes seconds to absorb load, and flapping
  (up, down, up) costs a compile each flap. The up/down thresholds must
  not overlap (enforced at construction) so the steady state is a dead
  band, not an oscillator.

Every tick appends a row to :attr:`trace` (``t``/``n``/``p99_ms``/
``queue_depth``/``action``) — the N-over-time trajectory the serve_bench
``--fleet`` artifact records — and scaling actions count
``fleet.scale_ups`` / ``fleet.scale_downs`` with the ``fleet.replicas``
gauge tracking N.

The supervisor dependency is one method: ``fleet.scale_to(n) -> int``
(blocking; returns the achieved N), plus ``fleet.n_replicas``. The router
dependency is ``router.mean_queue_depth()``. Both are injectable, so the
decision logic unit-tests with fakes and no subprocesses.
"""

from __future__ import annotations

import threading
import time

from ..obs.registry import get_registry
from ..utils.logging import emit
from .hedge import ROUTER_LATENCY
from .signals import SignalReader


class Autoscaler:
    """Cooldown-hysteresis scaling controller between min and max replicas."""

    def __init__(
        self,
        fleet,
        router,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 1.0,
        cooldown_s: float = 5.0,
        up_p99_ms: float = 250.0,
        down_p99_ms: float = 50.0,
        up_queue_depth: float = 8.0,
        down_queue_depth: float = 1.0,
        signal_class: str = "interactive",
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(f"need 1 <= min_replicas <= max_replicas, got "
                             f"{min_replicas}..{max_replicas}")
        if down_p99_ms >= up_p99_ms or down_queue_depth >= up_queue_depth:
            raise ValueError("scale-down thresholds must sit strictly below scale-up "
                             "thresholds (the dead band is the hysteresis)")
        self._fleet = fleet
        self._router = router
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self._interval_s = interval_s
        self._cooldown_s = cooldown_s
        self._up_p99_s = up_p99_ms / 1e3
        self._down_p99_s = down_p99_ms / 1e3
        self._up_queue = up_queue_depth
        self._down_queue = down_queue_depth
        self._cls = signal_class
        self._reg = get_registry()
        # the shared windowed-signal reader (serve/signals.py): window p99
        # off bucket-count deltas + the router's polled backlog — one
        # implementation with the brownout ladder, pinned unchanged here
        self._signals = SignalReader(
            latency_family=ROUTER_LATENCY, signal_class=signal_class,
            quantile=0.99, queue_depth_fn=router.mean_queue_depth,
        )
        self._last_action_t: float | None = None
        self._t0 = time.perf_counter()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # the N-over-time trajectory: one row per tick, bench-artifact-ready
        self.trace: list[dict] = []

    # -- the control step ----------------------------------------------------

    def step(self, now: float | None = None) -> dict:
        """One control decision. Separated from the thread so tests drive
        the logic deterministically. Returns the appended trace row."""
        now = time.perf_counter() if now is None else now
        p99_s = self._signals.window_p99_s()
        queue_depth = self._signals.queue_depth()
        n = self._fleet.n_replicas
        in_cooldown = (
            self._last_action_t is not None and now - self._last_action_t < self._cooldown_s
        )
        action = "hold"
        if not in_cooldown:
            overloaded = (p99_s is not None and p99_s > self._up_p99_s) or queue_depth > self._up_queue
            relaxed = (p99_s is None or p99_s < self._down_p99_s) and queue_depth < self._down_queue
            if overloaded and n < self.max_replicas:
                n = self._fleet.scale_to(n + 1)
                self._reg.counter("fleet.scale_ups").inc()
                self._last_action_t = now
                action = "up"
            elif relaxed and n > self.min_replicas:
                n = self._fleet.scale_to(n - 1)
                self._reg.counter("fleet.scale_downs").inc()
                self._last_action_t = now
                action = "down"
        self._reg.gauge("fleet.replicas").set(n)
        row = {
            "t": round(now - self._t0, 3),
            "n": n,
            "p99_ms": round(p99_s * 1e3, 3) if p99_s is not None else None,
            "queue_depth": round(queue_depth, 3),
            "action": action,
            "in_cooldown": in_cooldown,
        }
        self.trace.append(row)
        return row

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="fleet-autoscale", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:  # YAMT011: a dead control thread must be loud, not a frozen N
            while not self._stop.wait(self._interval_s):
                self.step()
        except Exception as e:  # noqa: BLE001 — contain, count, report
            get_registry().counter("serve.thread_crashes").inc()
            emit(f"[fleet] autoscaler thread crashed: {type(e).__name__}: {e}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
