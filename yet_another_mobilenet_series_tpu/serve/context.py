"""Per-request identity threaded through the serving stack.

A request crosses four threads on its way to logits — the HTTP handler
(serve/frontend.py), the admission edge (serve/admission.py), the batcher's
collect/dispatch thread (serve/batcher.py, serve/pipeline.py), and the
completion thread that syncs the engine's handle (serve/engine.py
``PendingPrediction.result``). Before this module those hops were
anonymous: spans were flat per-thread events and a hang report could say
"the window is occupied" but not WHOSE request occupied it.

:class:`RequestContext` is the identity that survives the hops: a
process-monotonic request id (echoed to HTTP clients as ``X-Request-Id``),
the QoS class, the deadline, the arrival time, and the current ``phase``.
:meth:`advance` is the ONE place phase transitions emit trace events, so
the producers just call ``ctx.advance("dispatched")`` at the right moment
and the Chrome-trace async waterfall (``serve/request`` envelope with
``serve/queued`` and ``serve/inflight`` sub-phases, ``ph: b``/``e``) plus
the cross-thread flow arrows (``serve/req``, ``ph: s``/``t``/``f``) stay
consistent by construction — all keyed ``id = rid``, so Perfetto renders
one correlated row per request across every thread.

Phases (terminal states never regress — a late duplicate ``advance`` is a
no-op, so the idempotent future-resolution paths in the batcher stay safe):

``arrived`` -> ``queued`` -> ``dispatched`` -> ``completed`` | ``shed`` |
``failed``, then ``resolved`` once the admission future is delivered.

The context is cheap enough to mint per request unconditionally (a counter
increment and a clock read); the trace emission inside ``advance`` is
no-op'd by a disabled tracer exactly like spans.
"""

from __future__ import annotations

import itertools
import time

from ..obs import trace as obs_trace

_IDS = itertools.count(1)

TERMINAL_PHASES = ("completed", "shed", "failed", "resolved")

# hedge legs occupy seq 8+: route_attempts is small (<= ~3 retries per
# leg), so seq = attempt + LEG_SEQ_HEDGE * 8 is unique per (request, leg,
# attempt) and the Perfetto flow id (trace_id * 16 + seq) never collides
TRACE_SEQ_HEDGE_BASE = 8

# cascade escalation legs occupy seq 4..7 (between the primary's 0..3 and
# the hedge's 8+): the big-tier re-submit of a low-confidence small-tier
# answer is its own leg in the fleet trace, never confused with a retry or
# a hedge of the small-tier dispatch (serve/cascade.py)
TRACE_SEQ_CASCADE_BASE = 4


def parse_trace_parent(header: str | None) -> tuple[int, int, str] | None:
    """Parse an ``X-Trace-Parent: <trace_id>-<seq>-<leg>`` header (the
    router stamps one per leg — serve/router.py) into ``(trace_id, seq,
    leg)``. Malformed or absent headers return None: trace propagation is
    best-effort and must never fail a request."""
    if not header:
        return None
    parts = header.strip().split("-", 2)
    if len(parts) != 3:
        return None
    try:
        trace_id, seq = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if trace_id < 0 or not 0 <= seq < 16 or not parts[2]:
        return None
    return trace_id, seq, parts[2]


def trace_flow_id(trace_id: int, seq: int) -> int:
    """The Perfetto flow-event id shared by the router's ``flow_start`` and
    the replica's ``flow_end`` for one leg: 16 seq slots per trace id."""
    return trace_id * 16 + seq


class RequestContext:
    """Identity + QoS + phase for one in-system serving request."""

    __slots__ = ("rid", "cls", "deadline_ms", "client_tag", "t_arrival", "phase",
                 "trace_id", "trace_seq", "trace_leg", "model")

    def __init__(self, rid: int, cls: str, deadline_ms: float | None, client_tag: str | None = None,
                 trace_parent: str | None = None, model: str | None = None):
        self.rid = rid
        self.cls = cls
        self.deadline_ms = deadline_ms
        # zoo model identity (X-Model header, serve/zoo.py): which named
        # bundle serves this request; None = the replica's default model
        self.model = model
        # a client-supplied X-Request-Id is echoed back verbatim; the
        # internal rid stays monotonic (trace ids must be process-unique)
        self.client_tag = client_tag
        self.t_arrival = time.perf_counter()
        self.phase = "arrived"
        # fleet-level trace identity (X-Trace-Parent, stamped by the router
        # on every leg): the ROUTER's request id + this leg's seq/name, so
        # replica-side trace events carry the fleet-wide correlation key
        parsed = parse_trace_parent(trace_parent)
        self.trace_id = parsed[0] if parsed else None
        self.trace_seq = parsed[1] if parsed else 0
        self.trace_leg = parsed[2] if parsed else None

    @classmethod
    def mint(cls, qos_class: str, deadline_ms: float | None = None,
             client_tag: str | None = None,
             trace_parent: str | None = None,
             model: str | None = None) -> "RequestContext":
        return cls(next(_IDS), qos_class, deadline_ms, client_tag, trace_parent, model)

    @property
    def wire_id(self) -> str:
        """The value echoed as ``X-Request-Id``."""
        return self.client_tag or str(self.rid)

    def age_s(self) -> float:
        return time.perf_counter() - self.t_arrival

    def as_dict(self) -> dict:
        """JSON-safe view for hang reports / varz (watchdog serving info)."""
        return {
            "id": self.rid,
            "class": self.cls,
            "model": self.model,
            "deadline_ms": self.deadline_ms,
            "age_s": self.age_s(),
            "phase": self.phase,
            "trace": self.trace_id,
        }

    def _targs(self) -> dict:
        """Fleet-trace args attached to every emitted event when a trace
        parent rode in: the ROUTER-issued request id (and which leg this
        replica served), so a merged cross-process trace correlates replica
        events to the fleet request without string joins."""
        if self.trace_id is None:
            return {}
        return {"trace": self.trace_id, "leg": self.trace_leg}

    # -- the one trace-emission point ---------------------------------------

    def link_parent(self) -> None:
        """Emit the ``fleet/leg`` flow ARRIVAL (``ph: f``) binding the
        router's leg arrow to this replica's enclosing slice — called inside
        the frontend's ``serve/submit`` span, so Perfetto draws
        router -> leg -> replica as one connected arrow per leg. No-op
        without a trace parent (a direct client, no router above us)."""
        if self.trace_id is None:
            return
        obs_trace.get_tracer().flow_end(
            "fleet/leg", trace_flow_id(self.trace_id, self.trace_seq),
            trace=self.trace_id, leg=self.trace_leg, rid=self.rid,
        )

    def advance(self, phase: str) -> None:
        """Move to ``phase``, emitting the async/flow trace edges for the
        transition. Duplicate and post-terminal advances are no-ops, so
        every resolution path may call this defensively."""
        prev = self.phase
        if phase == prev or prev in TERMINAL_PHASES:
            return
        self.phase = phase
        tr = obs_trace.get_tracer()
        if not tr.enabled:
            return
        if phase == "queued":
            tr.async_begin("serve/queued", self.rid, **self._targs())
            tr.flow_start("serve/req", self.rid, cls=self.cls, **self._targs())
        elif phase == "dispatched":
            tr.async_end("serve/queued", self.rid)
            tr.async_begin("serve/inflight", self.rid, **self._targs())
            tr.flow_step("serve/req", self.rid)
        elif phase in ("completed", "shed", "failed"):
            # close whichever sub-phase the request died in (a reject can
            # fail straight out of "queued"; a shed can happen either side)
            tr.async_end("serve/inflight" if prev == "dispatched" else "serve/queued", self.rid)
            tr.flow_end("serve/req", self.rid, outcome=phase)

    def open_envelope(self) -> None:
        """Async envelope begin (admission, once per admitted request)."""
        obs_trace.get_tracer().async_begin(
            "serve/request", self.rid, cls=self.cls,
            deadline_ms=self.deadline_ms if self.deadline_ms is not None else 0.0,
            **self._targs(),
        )

    def close_envelope(self) -> None:
        """Async envelope end (admission, at final future resolution)."""
        obs_trace.get_tracer().async_end("serve/request", self.rid, outcome=self.phase,
                                         **self._targs())
        self.phase = "resolved"
