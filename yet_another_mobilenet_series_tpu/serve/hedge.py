"""Request hedging: duplicate the straggler, first answer wins.

Tail latency in a replica fleet is dominated by the occasional slow server
— a GC pause, a queue spike, a noisy neighbor — not by the median path. The
classic fix (Dean & Barroso, "The Tail at Scale") is to send a DUPLICATE of
a request that has outrun the fleet's typical latency to a second replica
and take whichever answer lands first. Inference is pure, so a duplicate
can never double-apply anything; the only costs are the extra load (bounded
by firing at the tail quantile — only ~1% of requests ever hedge) and the
discipline that the loser's late answer must be discarded without
double-resolving the caller's future.

Two pieces:

:class:`Hedger` — policy. The hedge timer is **derived from measured
latency**, not configured: the p-``quantile`` (default p99) of the router's
own per-class ``serve.router.latency_seconds.<class>`` histogram
(obs/registry.py bucketed quantiles — the same math /metrics exposes),
clamped to ``[min_timer_ms, max_timer_ms]``. Until a class has
``min_samples`` observations the timer is None and nothing hedges — a cold
fleet must not hedge on garbage estimates.

:class:`HedgedCall` — mechanism. One request's idempotent first-wins
resolution across its legs (``primary`` + at most one ``hedge``):

- the first successful leg resolves the future; a hedge-leg win counts
  ``serve.hedge_wins``;
- the LOSER's late answer is dropped and counted
  (``serve.hedge_wasted``) — never a double resolution, never an
  InvalidStateError escaping a worker thread;
- a leg failure only resolves the future once NO other launched leg can
  still answer, and when both legs failed the PRIMARY's error surfaces
  (the hedge was an optimization; its failure mode must not replace the
  primary verdict);
- ``serve.hedges`` counts fired duplicates (armed timers that actually
  launched a second leg, not armings).

Under brownout (serve/brownout.py) hedging is the FIRST thing to go — L1
stops duplicating work before anything is shed — and every timer that
would have armed while disabled counts ``serve.hedges_suppressed``
(:meth:`Hedger.suppressed`): the duplicate load the ladder declined to add.

The router (serve/router.py) owns the threading: it arms a
``threading.Timer`` per eligible request and cancels it when the primary
resolves first. Because the timer is armed at LEG start and fires on its
own thread, it also covers the partition case: a primary leg wedged on a
blackholed or half-open socket (serve/netchaos.py) cannot delay the hedge
— the duplicate goes out at the measured quantile while the stuck leg
waits out its read timeout, so a partitioned replica costs the fleet a
timer tick, not a client-visible stall.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, InvalidStateError

from ..obs.registry import get_registry

# the per-class latency family the router observes and the hedger reads
ROUTER_LATENCY = "serve.router.latency_seconds"


class Hedger:
    """Hedge-timer policy over the router's observed latency histograms."""

    def __init__(self, *, quantile: float = 0.99, min_samples: int = 20,
                 min_timer_ms: float = 10.0, max_timer_ms: float = 2000.0):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {quantile}")
        self.quantile = quantile
        self.min_samples = max(1, int(min_samples))
        self.min_timer_s = min_timer_ms / 1e3
        self.max_timer_s = max_timer_ms / 1e3
        self._reg = get_registry()

    def observe(self, cls: str, latency_s: float) -> None:
        """Feed one completed request's router-side latency (any leg)."""
        self._reg.histogram(f"{ROUTER_LATENCY}.{cls}").observe(latency_s)

    def timer_s(self, cls: str) -> float | None:
        """Seconds to wait before duplicating a request of ``cls``; None
        while the class histogram is too thin to trust (no hedging)."""
        hist = self._reg.histogram(f"{ROUTER_LATENCY}.{cls}")
        if hist.count < self.min_samples:
            return None
        return min(max(hist.quantile(self.quantile), self.min_timer_s), self.max_timer_s)

    def suppressed(self) -> None:
        """Record one hedge the brownout ladder declined to arm
        (``serve.hedges_suppressed``) — the router calls this when a timer
        WOULD have fired but hedging is disabled at L1+."""
        self._reg.counter("serve.hedges_suppressed").inc()


class HedgedCall:
    """First-wins resolution of one request across its launched legs."""

    PRIMARY = "primary"
    HEDGE = "hedge"

    def __init__(self, future: Future):
        self.future = future
        self._lock = threading.Lock()
        self._resolved = False
        self._launched = {self.PRIMARY}
        self._failed: dict[str, Exception] = {}
        self._reg = get_registry()

    def launch_hedge(self) -> bool:
        """Record the duplicate leg going out (counts ``serve.hedges``).
        False when the call already resolved — the caller must not send."""
        with self._lock:
            if self._resolved:
                return False
            self._launched.add(self.HEDGE)
        self._reg.counter("serve.hedges").inc()
        return True

    @property
    def resolved(self) -> bool:
        with self._lock:
            return self._resolved

    @property
    def hedged(self) -> bool:
        """True once a duplicate leg actually launched — the flight
        recorder's discriminator for "this request's outcome was a hedge
        race", not just an armed timer (obs/fleet.py hedge-outcome events)."""
        with self._lock:
            return self.HEDGE in self._launched

    def ok(self, leg: str, value) -> bool:
        """Leg ``leg`` answered. True if it won (resolved the future); a
        loser's late answer is dropped and counted, never double-delivered."""
        with self._lock:
            if self._resolved:
                won = False
            else:
                self._resolved = True
                won = True
        if not won:
            self._reg.counter("serve.hedge_wasted").inc()
            return False
        if leg == self.HEDGE:
            self._reg.counter("serve.hedge_wins").inc()
        try:
            self.future.set_result(value)
        except InvalidStateError:
            pass  # client cancelled; nothing left to deliver
        return True

    def err(self, leg: str, exc: Exception) -> bool:
        """Leg ``leg`` failed. Resolves the future (with the PRIMARY's error
        when both legs failed) only once no launched leg is still pending;
        True if this call delivered the final verdict."""
        with self._lock:
            if self._resolved:
                return False
            self._failed[leg] = exc
            if set(self._failed) != self._launched:
                return False  # another leg may still answer
            self._resolved = True
            final = self._failed.get(self.PRIMARY, exc)
        try:
            self.future.set_exception(final)
        except InvalidStateError:
            pass
        return True
