"""Thread-based micro-batching request queue in front of the engine.

Single-image requests are latency-cheap but throughput-poisonous: the chip
is happiest at the biggest bucket. The batcher coalesces concurrent
requests into engine batches — up to ``max_batch`` images or ``max_wait_ms``
of linger, whichever first — on a dedicated dispatch thread, so clients see
a Future and the engine sees full buckets.

Overload behavior is explicit, not emergent:

- **backpressure**: the queue is bounded (``queue_depth``); a full queue
  rejects ``submit`` with :class:`QueueFull` immediately instead of growing
  an unbounded latency tail.
- **timeout shedding**: a request carrying a deadline that expires while
  still queued is dropped with :class:`DeadlineExceeded` set on its Future —
  the engine never burns a bucket slot on an answer nobody is waiting for.

Instrumentation (obs/): ``serve.queue_wait_seconds`` (enqueue -> dispatch),
``serve.batch_size`` histograms, ``serve.requests`` / ``serve.completed`` /
``serve.shed_deadline`` / ``serve.rejected_full`` counters — all in the same
registry every scalars row and obs_registry.json snapshot carries.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np

from ..obs.registry import get_registry


class QueueFull(RuntimeError):
    """submit() rejected: the bounded request queue is at queue_depth."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it was still queued."""


class _Request:
    __slots__ = ("image", "future", "t_enqueue", "t_deadline")

    def __init__(self, image: np.ndarray, deadline_s: float | None):
        self.image = image
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.t_deadline = None if deadline_s is None else self.t_enqueue + deadline_s


class MicroBatcher:
    """Coalesces submit()ted images into predict_fn batches on a worker
    thread. ``predict_fn(images) -> logits`` is typically
    :meth:`serve.engine.InferenceEngine.predict`."""

    def __init__(
        self,
        predict_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        default_deadline_ms: float = 0.0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._predict = predict_fn
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1e3
        self._default_deadline_s = default_deadline_ms / 1e3 if default_deadline_ms > 0 else None
        self._q: queue.Queue[_Request] = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._reg = get_registry()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="serve-batcher", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the dispatch thread. ``drain=True`` serves what is already
        queued first; False fails pending requests immediately."""
        if self._thread is None:
            return
        if not drain:
            self._fail_queued(RuntimeError("batcher stopped"))
        self._stop.set()
        self._thread.join()
        self._thread = None
        self._fail_queued(RuntimeError("batcher stopped"))

    def _fail_queued(self, exc: Exception) -> None:
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            req.future.set_exception(exc)

    # -- client side --------------------------------------------------------

    def submit(self, image: np.ndarray, *, deadline_ms: float | None = None) -> Future:
        """Enqueue one (H, W, 3) image; returns a Future resolving to its
        logits row. Raises :class:`QueueFull` when the bounded queue is at
        capacity (the caller's backpressure signal)."""
        if self._thread is None:
            raise RuntimeError("batcher not started")
        deadline_s = deadline_ms / 1e3 if deadline_ms is not None else self._default_deadline_s
        req = _Request(np.asarray(image, np.float32), deadline_s)
        self._reg.counter("serve.requests").inc()
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._reg.counter("serve.rejected_full").inc()
            raise QueueFull(f"request queue at capacity ({self._q.maxsize})") from None
        return req.future

    # -- dispatch thread ----------------------------------------------------

    def _collect(self) -> list[_Request]:
        """Block for the first request, then linger up to max_wait_s (or
        until max_batch) for companions."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        t_close = time.perf_counter() + self._max_wait_s
        while len(batch) < self._max_batch:
            remaining = t_close - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _loop(self) -> None:
        while not (self._stop.is_set() and self._q.empty()):
            batch = self._collect()
            if not batch:
                continue
            now = time.perf_counter()
            live: list[_Request] = []
            for req in batch:
                if req.t_deadline is not None and now > req.t_deadline:
                    self._reg.counter("serve.shed_deadline").inc()
                    req.future.set_exception(
                        DeadlineExceeded(f"queued {now - req.t_enqueue:.3f}s past deadline")
                    )
                else:
                    self._reg.histogram("serve.queue_wait_seconds").observe(now - req.t_enqueue)
                    live.append(req)
            if not live:
                continue
            self._reg.histogram("serve.batch_size").observe(len(live))
            try:
                logits = self._predict(np.stack([r.image for r in live]))
            except Exception as e:  # noqa: BLE001 — a dying engine must not hang clients
                for req in live:
                    req.future.set_exception(e)
                continue
            for req, row in zip(live, logits):
                req.future.set_result(row)
            self._reg.counter("serve.completed").inc(len(live))
