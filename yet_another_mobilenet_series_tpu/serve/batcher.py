"""Thread-based micro-batching request queue in front of the engine.

Single-image requests are latency-cheap but throughput-poisonous: the chip
is happiest at the biggest bucket. The batcher coalesces concurrent
requests into engine batches — up to ``max_batch`` images or ``max_wait_ms``
of linger, whichever first — on a dedicated dispatch thread, so clients see
a Future and the engine sees full buckets. A coalesced batch of MIXED image
sizes partitions by shape and dispatches one engine batch per size, each
hitting its own (bucket, image_size) executable (serve/engine.py ladder).
A size group is handed to the engine WHOLE, never split here: one larger
than the biggest bucket rides the engine's fused multi-chunk path (one
``lax.scan`` dispatch per ladder piece, ``serve.fuse_chunks``), so
``max_batch`` above the largest bucket turns coalesced overflow into fused
whole-batch dispatches instead of a host-side chunk loop.

The collect wait is event-driven, not polled: an idle batcher blocks on the
queue (zero wakeups/s) and the first request of a burst is picked up the
moment it lands — ``stop()`` wakes the thread with a queue sentinel instead
of a poll-interval check. FIFO makes the sentinel double as the drain
barrier: everything enqueued before ``stop()`` is served first.

Overload behavior is explicit, not emergent:

- **backpressure**: the queue is bounded (``queue_depth``); a full queue
  rejects ``submit`` with :class:`QueueFull` immediately instead of growing
  an unbounded latency tail.
- **timeout shedding**: a request carrying a deadline that expires while
  still queued is dropped with :class:`DeadlineExceeded` set on its Future —
  the engine never burns a bucket slot on an answer nobody is waiting for.
  (The pipelined batcher additionally re-checks deadlines at completion —
  serve/pipeline.py.)

Failure containment (the robustness contract every layer above builds on):

- every request is tracked in a live set from submit to resolution, and all
  future resolution goes through :meth:`_finish_ok` / :meth:`_finish_err` —
  idempotent, so a late engine answer for a request that shutdown already
  failed is dropped instead of crashing a worker thread;
- ``stop(drain=True)`` is BOUNDED: if the engine wedges mid-batch,
  ``drain_timeout_s`` fails every still-unresolved request with
  :class:`DrainTimeout` instead of hanging shutdown forever (the worker
  threads are daemons and are abandoned to the hung call);
- the worker loop carries a top-level exception guard (yamt-lint YAMT011):
  an unexpected crash fails every live future and counts
  ``serve.thread_crashes`` instead of dying silently and hanging clients.

Requests carry an optional **priority class** (serve/admission.py taxonomy);
the batcher itself stays FIFO — class policy lives at admission time, where
rejecting is still cheap — but sheds are attributed per class
(``serve.shed_deadline.<class>``) so overload is diagnosable by QoS tier.

Instrumentation (obs/): ``serve.queue_wait_seconds`` (enqueue -> dispatch),
``serve.batch_size`` histograms, ``serve.requests`` (counted only on a
SUCCESSFUL enqueue — a rejected submit increments ``serve.rejected_full``
alone, so requests - completed - shed always balances) / ``serve.completed``
/ ``serve.shed_deadline`` / ``serve.rejected_full`` / ``serve.drain_timeouts``
/ ``serve.thread_crashes`` counters — all in the same registry every scalars
row and obs_registry.json snapshot carries.
"""

from __future__ import annotations

import inspect
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Callable

import numpy as np

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from ..utils.logging import emit
from .quant import coerce_wire

# queue sentinel: wakes the (blocking) collect thread for shutdown. FIFO
# ordering makes everything enqueued before stop() drain ahead of it.
_STOP = object()


class QueueFull(RuntimeError):
    """submit() rejected: the bounded request queue is at queue_depth."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it was still queued (or, on the
    pipelined path, before its completed batch was synced)."""


class DrainTimeout(RuntimeError):
    """stop(drain=True) gave up waiting for a wedged engine: the request was
    failed at shutdown instead of hanging it (serve.drain_timeout_s)."""


class _Request:
    __slots__ = ("image", "future", "t_enqueue", "t_deadline", "priority", "ctx", "model")

    def __init__(self, image: np.ndarray, deadline_s: float | None, priority: str | None = None,
                 ctx=None, model: str | None = None):
        self.image = image
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.t_deadline = None if deadline_s is None else self.t_enqueue + deadline_s
        self.priority = priority
        # RequestContext (serve/context.py) when the caller threads identity
        # through; phase advances ride the request across the thread hops
        self.ctx = ctx
        # zoo model identity (serve/zoo.py): batches never mix models — the
        # grouping key below includes it, so each engine batch targets one
        # model's (model, bucket, image_size, K) executable
        self.model = model

    def _advance(self, phase: str) -> None:
        if self.ctx is not None:
            self.ctx.advance(phase)


def _group_by_shape(reqs: list["_Request"]) -> list[list["_Request"]]:
    """Partition a coalesced batch by (model, image shape), insertion-ordered:
    mixed image-size traffic dispatches one engine batch per size, each
    hitting its own (bucket, image_size) executable — never a stack error —
    and mixed-MODEL traffic (serve/zoo.py) never shares a batch, so every
    dispatch targets exactly one model's ladder."""
    groups: dict[tuple, list[_Request]] = {}
    for r in reqs:
        groups.setdefault((r.model, r.image.shape), []).append(r)
    return list(groups.values())


class MicroBatcher:
    """Coalesces submit()ted images into predict_fn batches on a worker
    thread. ``predict_fn(images) -> logits`` is typically
    :meth:`serve.engine.InferenceEngine.predict`."""

    def __init__(
        self,
        predict_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        queue_depth: int = 256,
        default_deadline_ms: float = 0.0,
        drain_timeout_s: float = 0.0,
        wire_dtype=np.float32,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._predict = predict_fn
        # zoo-aware predict fns (serve/engine.py multi-model) take a model=
        # kwarg; plain fns (tests, lambdas) don't — detect once, like the
        # pipelined batcher's ctxs detection, so both keep working unchanged
        try:
            self._predict_takes_model = "model" in inspect.signature(predict_fn).parameters
        except (TypeError, ValueError):
            self._predict_takes_model = False
        # the serving WIRE dtype (serve.quant.wire via the engine): submit
        # coerces every image to it ONCE, so stacked batches reach the
        # engine already wire-typed — never a hardcoded np.float32 (the
        # pre-quantization literal YAMT016 now lints against)
        self._wire_dtype = np.dtype(wire_dtype)
        self._max_batch = max_batch
        self._max_wait_s = max_wait_ms / 1e3
        self._default_deadline_s = default_deadline_ms / 1e3 if default_deadline_ms > 0 else None
        self._drain_timeout_s = drain_timeout_s
        self._q: queue.Queue = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._reg = get_registry()
        # submit -> resolution tracking: the drain-timeout sweep and the
        # thread-crash guard fail exactly the requests still in flight
        self._live: set[_Request] = set()
        self._live_lock = threading.Lock()
        # empty-handed collect returns; stays 0 with the event-driven wait
        # (pinned by tests) — the old 50 ms poll produced ~20/s while idle
        self._idle_wakeups = 0
        # set when the stop sentinel is drawn mid-linger: serve the batch in
        # hand, then exit (never re-enqueue the sentinel — a full queue would
        # deadlock the put)
        self._exit_after_batch = False
        # brownout fill-or-flush (serve/brownout.py L2+): when True the
        # coalescing linger is skipped — top up from whatever is ALREADY
        # queued (under saturation that is a full batch) and dispatch
        # immediately; an idle lull must not add max_wait_ms of latency to
        # work the storm already queued
        self._fill_or_flush = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        self._stop.clear()
        self._start_threads()
        return self

    def _start_threads(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="serve-batcher", daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True) -> None:
        """Stop the worker thread(s). ``drain=True`` serves what is already
        queued first (FIFO: the wake sentinel lands behind every pending
        request); False fails pending requests immediately. The drain is
        bounded by ``drain_timeout_s`` (0 = wait forever): on timeout every
        still-unresolved request fails with :class:`DrainTimeout` and the
        wedged worker threads are abandoned (they are daemons)."""
        if self._thread is None:
            return
        if not drain:
            self._fail_queued(RuntimeError("batcher stopped"))
        self._stop.set()
        self._q.put(_STOP)  # wakes the blocking collect; drains ahead of it
        drained = self._join_threads(self._drain_timeout_s if self._drain_timeout_s > 0 else None)
        self._thread = None
        self._fail_queued(RuntimeError("batcher stopped"))
        if not drained:
            self._reg.counter("serve.drain_timeouts").inc()
            emit(f"[serve] drain timed out after {self._drain_timeout_s:.1f}s; "
                 "failing in-flight requests and abandoning the wedged worker")
            self._fail_live(DrainTimeout(
                f"batcher shutdown drain exceeded {self._drain_timeout_s:.1f}s "
                "(engine wedged mid-batch?)"
            ))

    def _join_threads(self, timeout_s: float | None = None) -> bool:
        """Join the worker(s); False when the drain budget ran out first."""
        self._thread.join(timeout_s)
        return not self._thread.is_alive()

    def _fail_queued(self, exc: Exception) -> None:
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req is _STOP:
                continue
            self._finish_err(req, exc)

    def _fail_live(self, exc: Exception) -> None:
        """Fail every request still unresolved anywhere in the batcher —
        queued, in a worker's hands, or dispatched-but-unsynced."""
        with self._live_lock:
            live = list(self._live)
        for req in live:  # _finish_err re-takes the lock per request
            self._finish_err(req, exc)

    # -- future resolution (idempotent, the only two mutation paths) --------

    def _finish_ok(self, req: _Request, row) -> bool:
        with self._live_lock:
            self._live.discard(req)
        req._advance("completed")  # no-op when the engine already marked it
        try:
            req.future.set_result(row)
            return True
        except InvalidStateError:
            return False  # already failed (drain timeout / crash sweep)

    def _finish_err(self, req: _Request, exc: Exception) -> bool:
        with self._live_lock:
            self._live.discard(req)
        req._advance("failed")  # no-op when already shed/completed
        try:
            req.future.set_exception(exc)
            return True
        except InvalidStateError:
            return False

    # -- client side --------------------------------------------------------

    def submit(
        self,
        image: np.ndarray,
        *,
        deadline_ms: float | None = None,
        priority: str | None = None,
        ctx=None,
        model: str | None = None,
    ) -> Future:
        """Enqueue one (H, W, 3) image; returns a Future resolving to its
        logits row. Raises :class:`QueueFull` when the bounded queue is at
        capacity (the caller's backpressure signal). ``priority`` tags the
        request with its QoS class (serve/admission.py) for per-class shed
        attribution; the batcher itself stays FIFO. ``ctx`` is the optional
        :class:`~.context.RequestContext` correlating this request's trace
        events across the thread hops. ``model`` names the zoo tenant
        (serve/zoo.py); requests for different models never share a batch."""
        if self._thread is None:
            raise RuntimeError("batcher not started")
        deadline_s = deadline_ms / 1e3 if deadline_ms is not None else self._default_deadline_s
        req = _Request(coerce_wire(image, self._wire_dtype), deadline_s, priority, ctx, model)
        with self._live_lock:
            self._live.add(req)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            with self._live_lock:
                self._live.discard(req)
            self._reg.counter("serve.rejected_full").inc()
            raise QueueFull(f"request queue at capacity ({self._q.maxsize})") from None
        self._reg.counter("serve.requests").inc()  # accepted only, after the enqueue
        req._advance("queued")  # flow start + queued async edge, submit thread
        return req.future

    # -- dispatch thread ----------------------------------------------------

    def _collect(self) -> list[_Request] | None:
        """Block (no polling) for the first request, then linger up to
        max_wait_s (or until max_batch) for companions. Returns None when
        the stop sentinel is drawn first — the thread's exit signal."""
        first = self._q.get()
        if first is _STOP:
            return None
        batch = [first]
        self._linger_fill(batch)
        return batch

    def set_fill_or_flush(self, enabled: bool) -> None:
        """Brownout actuator (L2+): disable the coalescing linger — batches
        fill only from what is already queued, then dispatch. Idempotent and
        safe to flip live from the controller thread."""
        self._fill_or_flush = bool(enabled)  # yamt-lint: disable=YAMT019 — single-writer bool flip from the brownout controller; the worker reads a stale value for at most one linger tick

    def apply_brownout(self, policy) -> None:
        """The batcher's slice of a :class:`~.brownout.BrownoutPolicy`."""
        self.set_fill_or_flush(policy.fill_or_flush)

    def _linger_fill(self, batch: list[_Request]) -> None:
        """Top ``batch`` up from the queue until max_batch or max_wait_s of
        linger, whichever first — the shared coalescing policy (also used by
        the pipelined back-to-back path when a drain comes up short). Under
        brownout fill-or-flush the linger window collapses to zero: only
        already-queued requests join, then the batch dispatches."""
        t_close = time.perf_counter() + self._max_wait_s
        while len(batch) < self._max_batch:
            if self._fill_or_flush:
                remaining = 0.0  # no waiting: drain what's there, then go
            else:
                remaining = t_close - time.perf_counter()
                if remaining <= 0:
                    break
            try:
                nxt = self._q.get(timeout=remaining) if remaining > 0 else self._q.get_nowait()
            except queue.Empty:
                break
            if nxt is _STOP:
                # serve this batch, then exit: anything enqueued after the
                # sentinel is failed by stop()'s final _fail_queued sweep
                self._exit_after_batch = True
                break
            batch.append(nxt)

    def _shed_expired(self, batch: list[_Request]) -> list[_Request]:
        """Dispatch-time deadline check: fail expired requests, record queue
        wait for the survivors."""
        now = time.perf_counter()
        live: list[_Request] = []
        for req in batch:
            if req.t_deadline is not None and now > req.t_deadline:
                self._shed(req, DeadlineExceeded(f"queued {now - req.t_enqueue:.3f}s past deadline"))
            else:
                self._reg.histogram("serve.queue_wait_seconds").observe(now - req.t_enqueue)
                live.append(req)
        return live

    def _shed(self, req: _Request, exc: DeadlineExceeded) -> None:
        self._reg.counter("serve.shed_deadline").inc()
        if req.priority:
            self._reg.counter(f"serve.shed_deadline.{req.priority}").inc()
        req._advance("shed")
        self._finish_err(req, exc)

    def _thread_crash(self, exc: Exception) -> None:
        """Terminal handler behind every worker's top-level guard (YAMT011):
        a crashing worker fails every live request instead of dying silently
        — a silently-dead collect thread would hang every future forever."""
        self._reg.counter("serve.thread_crashes").inc()
        emit(f"[serve] worker thread crashed: {type(exc).__name__}: {exc}")
        self._fail_live(exc)

    def _loop(self) -> None:
        try:
            obs_trace.get_tracer().register_thread()  # "serve-batcher" Perfetto row
            self._loop_inner()
        except Exception as e:  # noqa: BLE001 — terminal: contain, don't hang clients
            self._thread_crash(e)

    def _loop_inner(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            if not batch:
                self._idle_wakeups += 1
                continue
            self._serve_batch(batch)
            if self._exit_after_batch:
                return

    def _serve_batch(self, batch: list[_Request]) -> None:
        live = self._shed_expired(batch)
        for group in _group_by_shape(live):
            self._reg.histogram("serve.batch_size").observe(len(group))
            for req in group:  # queued -> in-flight edge, dispatch thread
                req._advance("dispatched")
            try:
                stacked = np.stack([r.image for r in group])
                if self._predict_takes_model and group[0].model is not None:
                    logits = self._predict(stacked, model=group[0].model)
                else:
                    logits = self._predict(stacked)
            except Exception as e:  # noqa: BLE001 — a dying engine must not hang clients
                for req in group:
                    self._finish_err(req, e)
                continue
            done = 0
            for req, row in zip(group, logits):
                done += self._finish_ok(req, row)
            if done:
                self._reg.counter("serve.completed").inc(done)
