"""Device-side telemetry: compile/cost accounting, memory gauges, profiler
capture — the device twin of the host-side obs/ stack (docs/OBSERVABILITY.md
"Device telemetry").

Everything below the dispatch boundary used to be a black box: the serve
engine and the train step compile XLA executables whose FLOPs/bytes the
compiler KNOWS (``cost_analysis()``) but nothing recorded, device memory was
invisible until an OOM, and the only profiler window was the train-only
step-indexed one. Three surfaces, all wired through the existing registry so
they ride every snapshot, ``/metrics``, ``/varz``, ``obs_report`` and the
watchdog hang report for free:

- **compile telemetry** — :func:`timed_compile` wraps every
  ``lower().compile()`` (serve/engine.py ``_build``; cli/train.py records the
  train step via :func:`record_cost` on the already-traced ``Lowered``):
  per-key compile seconds land in the ``obs.compile_seconds`` histogram +
  ``obs.compiles`` counter, and the executable's ``cost_analysis()``
  flops/bytes land in per-key ``obs.cost_flops.<key>`` /
  ``obs.cost_bytes.<key>`` gauges plus the :func:`compile_report` table the
  hang report embeds. The engine feeds dispatched-executable flops into
  ``serve.dispatched_flops`` and the matching cost bytes into
  ``serve.dispatched_bytes`` (the transfer-side twin, via :func:`bytes_for`),
  and :func:`install_dispatch_efficiency_gauge`
  derives ``serve.achieved_flops_per_s`` = dispatched cost FLOPs ÷ measured
  ``serve.run_seconds`` — the "how much of the paper FLOPs did the wall
  clock actually deliver" number ROADMAP item 3's latency work keys on.
- **memory telemetry** — :func:`install_memory_gauges` registers PULL gauges
  (read only at snapshot time — the existing log cadence — zero extra device
  syncs): per-device ``device.bytes_in_use.d<i>`` / peak / limit from
  ``device.memory_stats()`` (absent on backends that don't report, e.g. CPU),
  ``device.live_buffer_bytes`` from ``jax.live_arrays()``, and
  ``host.rss_bytes`` from ``/proc/self/statm``. Because they are registry
  gauges they are automatically dumped into ``hang_report.json`` and
  ``train_health.json`` (both embed full snapshots).
- **profiler capture** — :class:`ProfilerCapture` is the start/stop pair
  behind the serving frontend's ``POST /profile/start|stop`` endpoints
  (docs/SERVING.md): a lock-guarded ``jax.profiler`` window whose owner
  (cli/serve.py) guarantees ``stop_if_active()`` on every drain path, so an
  operator who never sends the stop request cannot leak a capture past
  shutdown. The train-loop window stays step-indexed in cli/train.py; lint
  rule YAMT013 pins the try/finally discipline for both.

Cost analysis is best-effort by design: backends disagree on the
``cost_analysis()`` return shape (dict vs list-of-dicts) and some refuse it
entirely — a telemetry miss must never take a compile down, so every reader
is wrapped and a miss records nothing.
"""

from __future__ import annotations

import os
import threading
import time

from .registry import MetricsRegistry, get_registry

# per-key cost table: key -> {"flops", "bytes", "compile_seconds"} — the
# compile_report() section of hang reports and the engine's dispatched-flops
# lookup. Process-lifetime like the registry itself.
_COSTS: dict[str, dict] = {}
_COSTS_LOCK = threading.Lock()


def _extract_cost(raw) -> dict:
    """Normalize a ``cost_analysis()`` result (dict, or list of per-module
    dicts on some backends) to {"flops": float, "bytes": float}; {} when the
    backend reported nothing usable."""
    if raw is None:
        return {}
    if isinstance(raw, (list, tuple)):
        merged: dict[str, float] = {}
        for d in raw:
            if isinstance(d, dict):
                for k, v in d.items():
                    merged[k] = merged.get(k, 0.0) + float(v)
        raw = merged
    if not isinstance(raw, dict):
        return {}
    out = {}
    if "flops" in raw:
        out["flops"] = float(raw["flops"])
    if "bytes accessed" in raw:
        out["bytes"] = float(raw["bytes accessed"])
    return out


def record_cost(key: str, stage, *, compile_seconds: float | None = None,
                registry: MetricsRegistry | None = None) -> dict:
    """Record ``stage.cost_analysis()`` (a ``jax.stages.Lowered`` or
    ``Compiled``) for executable ``key``: per-key ``obs.cost_flops.<key>`` /
    ``obs.cost_bytes.<key>`` gauges + the :func:`compile_report` entry.
    Returns the extracted cost dict ({} when the backend reported nothing) —
    never raises on a cost-analysis miss."""
    reg = registry or get_registry()
    try:
        cost = _extract_cost(stage.cost_analysis())
    except Exception:  # noqa: BLE001 — telemetry must never fail a compile
        cost = {}
    entry = dict(cost)
    if compile_seconds is not None:
        entry["compile_seconds"] = round(float(compile_seconds), 6)
    with _COSTS_LOCK:
        _COSTS[key] = entry
    if "flops" in cost:
        reg.gauge(f"obs.cost_flops.{key}").set(cost["flops"])
    if "bytes" in cost:
        reg.gauge(f"obs.cost_bytes.{key}").set(cost["bytes"])
    return cost


def timed_compile(lowered, key: str, *, registry: MetricsRegistry | None = None):
    """``lowered.compile()`` with the device-compile telemetry attached:
    compile wall time into ``obs.compile_seconds`` (histogram) +
    ``obs.compiles`` (counter), and the executable's cost_analysis
    flops/bytes into the per-key gauges (:func:`record_cost`). This is THE
    wrapper every explicit AOT compile goes through (serve/engine.py)."""
    reg = registry or get_registry()
    t0 = time.perf_counter()
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    reg.histogram("obs.compile_seconds").observe(dt)
    reg.counter("obs.compiles").inc()
    record_cost(key, compiled, compile_seconds=dt, registry=reg)
    return compiled


def flops_for(key: str) -> float:
    """Recorded cost-analysis FLOPs of executable ``key`` (0.0 when the
    backend reported none) — the engine's per-dispatch accounting lookup."""
    with _COSTS_LOCK:
        return float(_COSTS.get(key, {}).get("flops", 0.0))


def bytes_for(key: str) -> float:
    """Recorded cost-analysis bytes-accessed of executable ``key`` (0.0 when
    the backend reported none) — the transfer-side twin of :func:`flops_for`:
    the engine joins it to every dispatch as ``serve.dispatched_bytes``, the
    number the staging-overlap win is read against (docs/SERVING.md)."""
    with _COSTS_LOCK:
        return float(_COSTS.get(key, {}).get("bytes", 0.0))


def compile_report() -> dict:
    """{key: {flops, bytes, compile_seconds}} for every recorded executable —
    embedded in the watchdog hang report and printable from obs_report."""
    with _COSTS_LOCK:
        return {k: dict(v) for k, v in sorted(_COSTS.items())}


# ---------------------------------------------------------------------------
# memory gauges (pull-based: zero cost until a snapshot reads them)
# ---------------------------------------------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> float:
    with open("/proc/self/statm") as f:
        return float(int(f.read().split()[1]) * _PAGE_SIZE)


def _live_buffer_bytes() -> float:
    import jax

    return float(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))


_MEM_INSTALLED = False
_MEM_LOCK = threading.Lock()


def install_memory_gauges(registry: MetricsRegistry | None = None) -> None:
    """Register the device/host memory PULL gauges (idempotent; both CLIs
    call this at startup). Each gauge's callback runs only when a snapshot is
    taken — the existing log cadence — and ``memory_stats()`` / ``statm``
    reads are host-side, so telemetry adds no device syncs. Backends without
    ``memory_stats()`` support (CPU) simply skip the per-device HBM gauges;
    RSS and live-buffer accounting still land."""
    global _MEM_INSTALLED
    with _MEM_LOCK:
        if _MEM_INSTALLED:
            return
        _MEM_INSTALLED = True
    import jax

    reg = registry or get_registry()
    reg.gauge("host.rss_bytes").set_fn(_rss_bytes)
    reg.gauge("device.live_buffer_bytes").set_fn(_live_buffer_bytes)
    for i, dev in enumerate(jax.devices()):
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — a backend without stats is not an error
            stats = None
        if not stats:
            continue

        def make_reader(d, field):
            return lambda: float((d.memory_stats() or {}).get(field, 0))

        for field, name in (
            ("bytes_in_use", "bytes_in_use"),
            ("peak_bytes_in_use", "peak_bytes_in_use"),
            ("bytes_limit", "bytes_limit"),
        ):
            if field in stats:
                reg.gauge(f"device.{name}.d{i}").set_fn(make_reader(dev, field))


def install_dispatch_efficiency_gauge(registry: MetricsRegistry | None = None) -> None:
    """``serve.achieved_flops_per_s`` pull gauge: cumulative cost-analysis
    FLOPs the engine dispatched (``serve.dispatched_flops``) divided by the
    cumulative measured wall time those requests took
    (``serve.run_seconds.sum``). Idempotent — the engine installs it once."""
    reg = registry or get_registry()
    flops = reg.counter("serve.dispatched_flops")
    run = reg.histogram("serve.run_seconds")

    def achieved() -> float:
        return flops.value / run.total if run.total > 0 else 0.0

    reg.gauge("serve.achieved_flops_per_s").set_fn(achieved)


# ---------------------------------------------------------------------------
# build info (the /metrics build_info family)
# ---------------------------------------------------------------------------


def _git_sha(repo_dir: str | None = None) -> str:
    """HEAD sha read straight from .git (no subprocess: serving startup must
    not fork a shell); "" when not a checkout."""
    d = repo_dir or os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        git = os.path.join(d, ".git")
        with open(os.path.join(git, "HEAD")) as f:
            head = f.read().strip()
        if not head.startswith("ref:"):
            return head[:40]
        ref = head.split(None, 1)[1]
        ref_path = os.path.join(git, *ref.split("/"))
        if os.path.exists(ref_path):
            with open(ref_path) as f:
                return f.read().strip()[:40]
        with open(os.path.join(git, "packed-refs")) as f:
            for line in f:
                parts = line.strip().split()
                if len(parts) == 2 and parts[1] == ref:
                    return parts[0][:40]
    except OSError:
        pass
    return ""


def build_info() -> dict:
    """Version-attribution labels for the ``build_info`` metric family: git
    sha, jax/jaxlib versions, backend platform. A scraped fleet can group
    replicas by exactly what they run."""
    import jax
    import jaxlib

    return {
        "git_sha": _git_sha() or "unknown",
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "platform": jax.default_backend(),
    }


# ---------------------------------------------------------------------------
# profiler capture (the serving frontend's /profile endpoints)
# ---------------------------------------------------------------------------


class ProfilerCapture:
    """Config/HTTP-triggered ``jax.profiler`` window for the SERVING path —
    the train-only step-indexed window generalized (docs/SERVING.md
    "Profiler capture"). ``start``/``stop`` arrive as separate requests, so a
    function-local try/finally cannot guard the pair; instead the capture is
    lock-guarded single-flight and its OWNER (cli/serve.py's drain path)
    calls :meth:`stop_if_active` on every shutdown, bounding a leaked window
    at process drain. The xplane dump lands under ``dir`` for
    scripts/trace_ops.py aggregation."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self._lock = threading.Lock()
        self._active_since: float | None = None

    @property
    def active(self) -> bool:
        return self._active_since is not None

    def start(self) -> dict:
        """Begin a capture; raises RuntimeError when one is already open."""
        import jax

        with self._lock:
            if self._active_since is not None:
                raise RuntimeError(
                    f"profiler capture already active for "
                    f"{time.perf_counter() - self._active_since:.1f}s"
                )
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)  # yamt-lint: disable=YAMT013 — stop arrives via /profile/stop; stop_if_active() guards every drain path
            self._active_since = time.perf_counter()
            get_registry().counter("obs.profiler_captures").inc()
        return {"trace_dir": self.trace_dir}

    def stop(self) -> dict:
        """End the capture; raises RuntimeError when none is open."""
        import jax

        with self._lock:
            if self._active_since is None:
                raise RuntimeError("no profiler capture active")
            t0 = self._active_since
            self._active_since = None
            jax.profiler.stop_trace()
        return {"trace_dir": self.trace_dir,
                "captured_s": round(time.perf_counter() - t0, 3)}

    def stop_if_active(self) -> None:
        """Drain-path guard: close a still-open window without raising —
        the shutdown equivalent of the train loop's finally."""
        try:
            self.stop()
        except RuntimeError:
            pass
        except Exception:  # noqa: BLE001 — a torn capture must not block drain
            get_registry().counter("obs.profiler_stop_errors").inc()
