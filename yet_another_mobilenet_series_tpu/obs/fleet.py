"""Fleet-wide observability: metrics federation + the incident flight
recorder.

One replica's /metrics answers "how is THIS process doing"; a fleet
operator's questions — what is the fleet p99, which replica is burning the
error budget, what happened in the 30 s before that ejection — need signals
JOINED across processes. Two pieces live here, both jax-free (they run in
the router supervisor, which must stay importable without an accelerator):

:class:`FleetFederation` — a pull-based scrape loop over every live
backend's ``/varz`` (driven from the supervisor's main loop on the existing
poll cadence, cli/fleet.py). Replicas ship RAW histogram bucket counts
(``Histogram.state()``): every process bins into the same fixed log-spaced
ladder (obs/registry.py ``DEFAULT_BUCKET_BOUNDS``), so the cross-replica
merge is an exact count sum — the federated fleet quantile is IDENTICAL to
the quantile of the pooled per-replica observations, not an average of
averages. Each scrape:

- sums per-replica bucket-count DELTAS into fleet-windowed per-class p99
  gauges (``fleet.window_p99_seconds.<class>``, through the registry's own
  ``quantiles_from_counts`` — the same interpolation every other consumer
  uses);
- accumulates merged CUMULATIVE counts (``merged`` in :meth:`snapshot`,
  the bench's federation-correctness oracle);
- feeds the SLO tracker (serve/signals.py :class:`~..serve.signals.SLOTracker`)
  with summed completed/bad deltas and exports its burn rates
  (``fleet.slo_burn_rate.{short,long}``);
- refreshes the replica-labeled Prometheus families
  (:meth:`render_prometheus`, appended to the router frontend's /metrics):
  ``fleet_<family>_bucket{replica="...",...,le="..."}`` per histogram plus
  every replica's ``fleet_build_info{replica="..."} 1`` under one family.

:class:`FlightRecorder` — a bounded ring of significant fleet events
(ejections/readmissions, lease expirations, breaker flips, brownout
transitions, hedge outcomes, terminal records for failed/shed requests),
fed by the router's event sink (``Router.set_event_sink``) and the brownout
controller (the recorder is an ``apply_brownout`` target). On a trigger —
brownout reaching ``incident_level``, any ejection, or SLO fast-burn — the
NEXT :meth:`maybe_dump` writes ``incident_<reason>.json``: the ring, the
federated snapshot, and the last per-replica /varz — the "what was the
fleet doing when it went wrong" artifact, rate-limited so a flapping
trigger cannot spam the log dir.

Threading: ``record`` is called from routing/poll threads, sometimes UNDER
the router lock, so it is a bare ``deque.append`` + attribute store (both
GIL-atomic) — no lock, no I/O. All file I/O happens in ``maybe_dump`` on
the supervisor's main loop. ``scrape_once`` is single-owner (the main
loop); the handler-facing readers (``render_prometheus`` / ``snapshot``)
take the federation lock only to copy out the last scrape's state.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from .registry import (
    PROM_LABEL_FAMILIES,
    _fmt,
    _prom_name,
    get_registry,
    quantiles_from_counts,
)

# the per-replica counter families summed into the SLO tracker's feed:
# total = completed + bad; bad = everything that burned budget (typed
# rejections, deadline sheds, engine failures)
_SLO_TOTAL_PREFIXES = ("serve.completed.",)
_SLO_BAD_PREFIXES = ("serve.rejected.", "serve.shed_deadline.", "serve.failed")

# event kinds that arm an incident dump on their own (a fast-burn trigger
# arrives via trigger(); brownout transitions via apply_brownout)
_TRIGGER_KINDS = frozenset({"ejection", "lease_expired"})


def _prom_family_labeled(name: str) -> tuple[str, str]:
    """(family, label-clause) for one federated metric name, reusing the
    registry's fold rules (serve.latency_seconds.interactive ->
    class="interactive") under the ``fleet_`` namespace — federated
    families must not collide with the router's OWN local families on the
    same /metrics page."""
    if "." in name:
        fam, suffix = name.rsplit(".", 1)
        label = PROM_LABEL_FAMILIES.get(fam)
        if label is not None:
            return "fleet_" + _prom_name(fam), f'{label}="{suffix}"'
    return "fleet_" + _prom_name(name), ""


class FleetFederation:
    """Scrape-merge loop over every backend's /varz (see module docstring)."""

    def __init__(
        self,
        backends_fn,
        *,
        slo=None,
        recorder=None,
        signal_classes=("interactive",),
        latency_family: str = "serve.latency_seconds",
        scrape_timeout_s: float = 2.0,
    ):
        # () -> [(key, client)]: the router's own keep-alive clients
        # (Router.backends) — ReplicaClient connections are per-thread, so
        # the scrape never contends with route workers for a socket
        self._backends_fn = backends_fn
        self._slo = slo
        self._recorder = recorder
        self._signal_classes = tuple(signal_classes)
        self._latency_family = latency_family
        self._scrape_timeout_s = float(scrape_timeout_s)
        self._reg = get_registry()
        self._lock = threading.Lock()
        # per-(replica, histogram) previous counts for windowed deltas, and
        # per-(replica, counter) previous values for the SLO feed
        self._prev_counts: dict[tuple[str, str], list[int]] = {}
        self._prev_flat: dict[tuple[str, str], float] = {}
        # merged cumulative bucket counts per histogram name (exact sum of
        # every delta ever scraped — survives replica restarts, which a
        # naive "sum the cumulative counts" would double-count or lose)
        self._merged: dict[str, dict] = {}
        self._last_varz: dict[str, dict] = {}
        self._last_p99: dict[str, float | None] = {}
        self._scrapes = 0
        self._errors = 0

    # -- the scrape (single-owner: the supervisor main loop) -----------------

    def scrape_once(self) -> dict:
        """Pull every backend's /varz once; merge. Returns a summary dict
        (scraped/error counts) for the caller's log line. A replica that
        fails to answer is skipped this tick — federation is best-effort
        and must never take the router down."""
        t0 = time.perf_counter()
        docs: dict[str, dict] = {}
        errors = 0
        for key, client in self._backends_fn():
            try:
                status, doc = client.varz(timeout_s=self._scrape_timeout_s)
            except Exception:  # noqa: BLE001 — a dead replica is a skipped scrape
                errors += 1
                continue
            if status != 200 or not isinstance(doc, dict):
                errors += 1
                continue
            docs[key] = doc
        window_deltas: dict[str, list[int]] = {}
        slo_total = 0.0
        slo_bad = 0.0
        with self._lock:
            for key, doc in docs.items():
                for name, st in (doc.get("histograms") or {}).items():
                    counts = [int(c) for c in st.get("counts") or []]
                    prev = self._prev_counts.get((key, name))
                    delta = self._delta(counts, prev)
                    self._prev_counts[(key, name)] = counts
                    self._merge_cumulative(name, st, delta)
                    if name.startswith(self._latency_family + "."):
                        cls = name[len(self._latency_family) + 1:]
                        acc = window_deltas.setdefault(cls, [0] * len(delta))
                        if len(acc) == len(delta):
                            for i, d in enumerate(delta):
                                acc[i] += d
                flat = doc.get("metrics") or {}
                slo_total += self._flat_delta(key, flat, _SLO_TOTAL_PREFIXES)
                slo_bad += self._flat_delta(key, flat, _SLO_BAD_PREFIXES)
            self._last_varz = docs
            self._scrapes += 1
            self._errors += errors
            # fleet-windowed per-class p99 off the summed deltas: the exact
            # quantile of every completion the fleet saw since last tick
            for cls in self._signal_classes:
                delta = window_deltas.get(cls)
                bounds = (self._merged.get(f"{self._latency_family}.{cls}") or {}).get("bounds")
                if delta and bounds and sum(delta):
                    (p99,) = quantiles_from_counts(bounds, delta, (0.99,))
                else:
                    p99 = None
                self._last_p99[cls] = p99
                self._reg.gauge(f"fleet.window_p99_seconds.{cls}").set(p99 or 0.0)
        self._reg.gauge("fleet.federated_replicas").set(len(docs))
        primary = self._signal_classes[0] if self._signal_classes else None
        if self._slo is not None:
            total = slo_total + slo_bad
            self._slo.observe(int(total), int(slo_bad),
                              p99_s=self._last_p99.get(primary))
            self._reg.gauge("fleet.slo_burn_rate.short").set(
                self._slo.burn_rate(self._slo.short_window_s))
            self._reg.gauge("fleet.slo_burn_rate.long").set(
                self._slo.burn_rate(self._slo.long_window_s))
            if self._slo.fast_burn and self._recorder is not None:
                self._recorder.trigger("slo_fast_burn")
        self._reg.histogram("fleet.scrape_seconds").observe(time.perf_counter() - t0)
        return {"scraped": len(docs), "errors": errors}

    @staticmethod
    def _delta(counts: list[int], prev: list[int] | None) -> list[int]:
        """Per-bucket delta with counter-reset handling: a replica restart
        zeroes its histograms, so any negative component means the current
        counts ARE the delta (the fresh process's whole history)."""
        if prev is None or len(prev) != len(counts):
            return list(counts)
        delta = [c - p for c, p in zip(counts, prev)]
        if any(d < 0 for d in delta):
            return list(counts)
        return delta

    def _merge_cumulative(self, name: str, st: dict, delta: list[int]) -> None:
        bounds = list(st.get("bounds") or [])
        m = self._merged.get(name)
        if m is None or m["bounds"] != bounds or len(m["counts"]) != len(delta):
            self._merged[name] = {"bounds": bounds, "counts": list(delta)}
            return
        for i, d in enumerate(delta):
            m["counts"][i] += d

    def _flat_delta(self, key: str, flat: dict, prefixes) -> float:
        """Sum of deltas of every flat metric matching ``prefixes`` for one
        replica (reset-aware, like :meth:`_delta`)."""
        out = 0.0
        for name, value in flat.items():
            if not any(name == p or name.startswith(p) for p in prefixes):
                continue
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            prev = self._prev_flat.get((key, name), 0.0)
            d = v - prev
            self._prev_flat[(key, name)] = v
            out += v if d < 0 else d  # reset: the fresh count is the delta
        return out

    # -- handler-facing readers ----------------------------------------------

    def last_varz(self) -> dict:
        """The most recent per-replica /varz documents (incident dumps)."""
        with self._lock:
            return dict(self._last_varz)

    def merged_counts(self) -> dict:
        """{histogram name: {"bounds", "counts"}} — the fleet's cumulative
        merged bucket counts (the bench's federation oracle)."""
        with self._lock:
            return {k: {"bounds": list(v["bounds"]), "counts": list(v["counts"])}
                    for k, v in self._merged.items()}

    def snapshot(self) -> dict:
        """JSON view for the router's /varz ``fleet`` section and incident
        dumps: who was scraped, the fleet-windowed tails, SLO state."""
        with self._lock:
            replicas = {
                key: {
                    "identity": (doc.get("replica") or {}),
                    "draining": bool(doc.get("draining")),
                    "queued_total": (doc.get("admission") or {}).get("queued_total"),
                }
                for key, doc in self._last_varz.items()
            }
            out = {
                "replicas": replicas,
                "window_p99_s": dict(self._last_p99),
                "scrapes": self._scrapes,
                "scrape_errors": self._errors,
            }
        if self._slo is not None:
            out["slo"] = self._slo.state()
        return out

    def render_prometheus(self) -> str:
        """Replica-labeled exposition of the last scrape: every replica's
        histogram families under the ``fleet_`` namespace with a
        ``replica="<id>"`` label, plus one ``fleet_build_info`` family
        carrying every replica's identity labels. Deterministic ordering
        (sorted replicas x sorted families) so the output golden-tests."""
        with self._lock:
            docs = dict(self._last_varz)
        lines: list[str] = []
        typed: set[str] = set()
        binfo_lines: list[str] = []
        for key in sorted(docs):
            doc = docs[key]
            rid = str((doc.get("replica") or {}).get("replica_id") or key)
            binfo = doc.get("build_info") or {}
            labels = ",".join([f'replica="{rid}"'] + [
                f'{_prom_name(k)}="{v}"' for k, v in sorted(binfo.items())
            ])
            binfo_lines.append(f"fleet_build_info{{{labels}}} 1")
            for name in sorted(doc.get("histograms") or {}):
                st = doc["histograms"][name]
                fam, label = _prom_family_labeled(name)
                if fam not in typed:
                    typed.add(fam)
                    lines.append(f"# TYPE {fam} histogram")
                base = f'replica="{rid}"' + (f",{label}" if label else "")
                cum = 0
                for bound, c in zip(st.get("bounds") or [], st.get("counts") or []):
                    cum += int(c)
                    lines.append(f'{fam}_bucket{{{base},le="{_fmt(bound)}"}} {cum}')
                total = int(st.get("count") or 0)
                lines.append(f'{fam}_bucket{{{base},le="+Inf"}} {total}')
                lines.append(f"{fam}_sum{{{base}}} {_fmt(st.get('sum') or 0.0)}")
                lines.append(f"{fam}_count{{{base}}} {total}")
        out = []
        if binfo_lines:
            out.append("# TYPE fleet_build_info gauge")
            out.extend(binfo_lines)
        out.extend(lines)
        return "\n".join(out) + "\n" if out else ""


class FlightRecorder:
    """Bounded ring of significant fleet events + triggered incident dumps
    (see module docstring). ``record`` is the router's event sink; the
    brownout controller drives :meth:`apply_brownout`; the supervisor main
    loop drives :meth:`maybe_dump`."""

    def __init__(self, log_dir: str, *, ring: int = 256,
                 min_interval_s: float = 30.0, incident_level: int = 3):
        self.log_dir = log_dir
        self.incident_level = int(incident_level)
        self.min_interval_s = float(min_interval_s)
        self._ring: collections.deque = collections.deque(maxlen=max(int(ring), 8))
        # the armed trigger reason (None = nothing pending): a plain
        # attribute store — record() runs under the router lock and must
        # not block, and a GIL-atomic store is all arming needs
        self._pending: str | None = None
        self._last_dump_t = float("-inf")  # monotonic
        self._brownout_level = 0
        self._dumps = 0
        self._reg = get_registry()

    # -- producers (non-blocking; may run under the router lock) -------------

    def record(self, kind: str, **fields) -> None:
        """Append one event. Wall-clock timestamp BY DESIGN: incident
        events are read next to per-replica logs from other hosts, so the
        timeline must be in shared wall time, never differenced into a
        duration (the YAMT017 hazard is subtraction, not the reading)."""
        evt = {"t_unix": time.time(), "kind": str(kind)}
        evt.update(fields)
        self._ring.append(evt)  # deque.append is GIL-atomic; no lock, no I/O
        if kind in _TRIGGER_KINDS:
            self._pending = kind  # GIL-atomic arm; maybe_dump (single consumer) clears it

    def apply_brownout(self, policy) -> None:
        """Brownout-target protocol (serve/brownout.py): record level
        transitions; a climb to ``incident_level`` or beyond arms a dump."""
        level = int(policy.level)
        prev = self._brownout_level
        if level == prev:
            return
        self._brownout_level = level
        self.record("brownout_transition", level=level, prev=prev,
                    shed_classes=sorted(policy.shed_classes),
                    hedging=bool(policy.hedging))
        if level >= self.incident_level and level > prev:
            self._pending = f"brownout_l{level}"  # GIL-atomic arm, single consumer

    def trigger(self, reason: str) -> None:
        """Arm an incident dump explicitly (the federation's SLO fast-burn
        path)."""
        self.record("trigger", reason=str(reason))
        self._pending = str(reason)  # GIL-atomic arm, single consumer

    # -- the consumer (supervisor main loop) ---------------------------------

    def events(self) -> list[dict]:
        return list(self._ring)

    def maybe_dump(self, federation=None) -> str | None:
        """Write ``incident_<reason>.json`` if a trigger is armed and the
        rate limit allows; returns the path (None = nothing written). The
        artifact is self-contained: the event ring, the federated fleet
        snapshot, the last per-replica /varz, and the local registry — what
        a responder needs WITHOUT the processes that produced it."""
        reason = self._pending
        if reason is None:
            return None
        now = time.monotonic()
        if now - self._last_dump_t < self.min_interval_s:
            return None  # stay armed; dump when the limiter reopens
        self._pending = None  # single consumer by contract (supervisor main loop)
        self._last_dump_t = now
        doc = {
            "reason": reason,
            # wall timestamp for cross-host correlation (never differenced)
            "t_unix": time.time(),
            "brownout_level": self._brownout_level,
            "events": self.events(),
            "registry": self._reg.snapshot(),
        }
        if federation is not None:
            doc["fleet"] = federation.snapshot()
            doc["replica_varz"] = federation.last_varz()
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
        path = os.path.join(self.log_dir, f"incident_{safe}.json")
        os.makedirs(self.log_dir, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)  # atomic: a reader sees whole JSON or nothing
        self._dumps += 1
        self._reg.counter("fleet.incidents").inc()
        return path
