"""Stall watchdog: a heartbeat thread that turns a silent hang into a
post-mortem file.

PROFILE.md's dead-tunnel rounds are the motivating failure: the train loop
blocks forever inside a dispatch (or ``next(train_iter)``), nothing is
logged, and the job dies only when the scheduler reaps it. The watchdog is
armed by the train loop at every completed step (and at eval/checkpoint/
rematerialize progress events, whose host time legitimately dwarfs a step);
when no heartbeat lands within the configured deadline it writes
``hang_report.json`` to the log dir — open spans from the tracer, the last
completed step and phase, a full registry snapshot, and every thread's stack
— then keeps the process untouched (the job still dies; now it dies loud).

The report is written at most once per process: a hang is a terminal state,
and re-dumping every poll interval would only shred the first, most accurate
stack capture.
"""

# yamt-lint: disable-file=YAMT019 — lock-free by design: arm() publishes the
# heartbeat fields (_beat_ns/_step/_phase) as single GIL-atomic stores from
# one writer (the train loop), and the poll thread tolerates a torn trio or a
# stale read for exactly one poll interval; _fired/_info follow the same
# single-writer publish discipline (docs/LINT.md "Concurrency rules").

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from .registry import MetricsRegistry
from .trace import SpanTracer

REPORT_NAME = "hang_report.json"


class StallWatchdog:
    def __init__(
        self,
        log_dir: str,
        deadline_s: float,
        *,
        tracer: SpanTracer | None = None,
        registry: MetricsRegistry | None = None,
        poll_s: float = 0.0,
        logger=None,
        info_providers: dict | None = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"watchdog deadline must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s) if poll_s > 0 else max(min(deadline_s / 4.0, 1.0), 0.05)
        self.report_path = os.path.join(log_dir, REPORT_NAME)
        self._tracer = tracer
        self._registry = registry
        self._logger = logger
        self._beat_ns: int | None = None
        self._step: int | None = None
        self._phase = "startup"
        self._fired = False
        # name -> zero-arg callable whose return value lands in the report's
        # "info" section (e.g. serving: batcher threads, window occupancy,
        # breaker state); a provider that raises contributes its error string
        # instead of taking the report down
        self._info: dict = dict(info_providers or {})
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="yamt-obs-watchdog", daemon=True)

    # -- train-loop surface --------------------------------------------------

    def start(self) -> None:
        # arm immediately: a tunnel that wedges before step 1 completes is
        # exactly the hang this exists for (deadline must therefore exceed
        # the first step's compile time — docs/OBSERVABILITY.md tuning)
        self.arm(step=None, phase="startup")
        self._thread.start()

    def register_info(self, name: str, fn) -> None:
        """Attach a named state provider to future hang reports (the serving
        stack registers breaker/queue/window state here — docs/SERVING.md)."""
        self._info[name] = fn

    def arm(self, step: int | None = None, phase: str = "step") -> None:
        """Heartbeat: "the loop made progress". Called per completed train
        step and at eval/checkpoint/rematerialize boundaries."""
        if step is not None:
            self._step = step
        self._phase = phase
        self._beat_ns = time.monotonic_ns()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=max(self.poll_s * 4, 1.0))

    @property
    def fired(self) -> bool:
        return self._fired

    # -- watchdog thread -----------------------------------------------------

    def _run(self) -> None:
        # top-level guard (yamt-lint YAMT011): a crashed watchdog thread is a
        # silently-disarmed alarm — at least say so on the way down
        try:
            self._run_inner()
        except Exception:  # noqa: BLE001 — terminal for the thread; be loud
            sys.stderr.write("WATCHDOG: thread crashed:\n" + traceback.format_exc())

    def _run_inner(self) -> None:
        while not self._stop.wait(self.poll_s):
            beat = self._beat_ns
            if beat is None or self._fired:
                continue
            elapsed = (time.monotonic_ns() - beat) / 1e9
            if elapsed <= self.deadline_s:
                continue
            self._fired = True
            try:
                self._dump(elapsed)
                msg = (
                    f"WATCHDOG: no progress for {elapsed:.1f}s "
                    f"(deadline {self.deadline_s:.1f}s, last phase "
                    f"'{self._phase}', last step {self._step}); wrote {self.report_path}"
                )
                if self._logger is not None:
                    self._logger.error(msg)
                else:
                    sys.stderr.write(msg + "\n")
            except Exception:
                sys.stderr.write("WATCHDOG: failed to write hang report:\n" + traceback.format_exc())

    def _dump(self, elapsed_s: float) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        threads = {
            f"{names.get(tid, 'thread')}-{tid}": traceback.format_stack(frame)
            for tid, frame in sys._current_frames().items()
        }
        info = {}
        for name, fn in self._info.items():
            try:
                info[name] = fn()
            except Exception as e:  # noqa: BLE001 — a dying provider must not kill the report
                info[name] = f"provider failed: {type(e).__name__}: {e}"
        # the device-side compile/cost table (obs/device.py): a hang during
        # or right after a compile names WHICH executable was last built and
        # what the compiler said it costs — memory gauges ride in the
        # registry snapshot below
        from .device import compile_report

        report = {
            "seconds_since_last_beat": elapsed_s,
            "deadline_s": self.deadline_s,
            "last_step": self._step,
            "last_phase": self._phase,
            "open_spans": self._tracer.open_spans() if self._tracer is not None else [],
            "registry": self._registry.snapshot() if self._registry is not None else {},
            "executables": compile_report(),
            "threads": threads,
            "info": info,
        }
        tmp = f"{self.report_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, self.report_path)
