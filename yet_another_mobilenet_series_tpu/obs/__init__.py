"""Runtime telemetry: a process-wide metrics registry, a coordinator-only
span tracer, and a stall watchdog (docs/OBSERVABILITY.md).

Three independent layers, composable and individually cheap enough to leave
on in production:

- ``registry``: typed counters/gauges/histograms unifying every ad-hoc
  runtime signal (decode failures, checkpoint barrier waits, rebuilds after
  rematerialization, forced host syncs); snapshots ride into every
  ``Logger.scalars`` row under an ``obs/`` prefix.
- ``trace``: a ring-buffered span tracer (context-manager API, monotonic
  clocks, no host<->device syncs on the hot path) emitting
  Chrome-trace/Perfetto JSON. Unlike the ``jax.profiler`` window it composes
  with ``train.steps_per_dispatch > 1``: spans measure HOST time around
  dispatches, so grouping stays on.
- ``watchdog``: a heartbeat thread armed per train step; if no step (or
  eval/checkpoint progress event) lands within a configurable deadline it
  dumps ``hang_report.json`` — open spans, last completed step, registry
  snapshot, all thread stacks — before the job dies silently (PROFILE.md's
  dead-tunnel rounds are the motivating failure mode).
- ``device``: the layer BELOW the dispatch boundary — compile-time +
  cost_analysis accounting for every AOT executable, pull-based HBM/RSS
  memory gauges, the dispatch-efficiency (achieved FLOPS) gauge, and the
  serving profiler capture (docs/OBSERVABILITY.md "Device telemetry").
"""

from . import device
from .registry import Counter, Gauge, Histogram, MetricsRegistry, get_registry
from .trace import SpanTracer, configure, get_tracer
from .watchdog import StallWatchdog

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SpanTracer",
    "StallWatchdog",
    "configure",
    "device",
    "get_registry",
    "get_tracer",
]
