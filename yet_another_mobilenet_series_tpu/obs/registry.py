"""Process-wide typed metrics registry (counters, gauges, histograms).

Unifies the runtime signals that previously lived as ad-hoc module state
(native-loader decode failures reached into from the train loop, bare
``print`` warnings in the data pipeline, checkpoint barrier waits and
post-rematerialize rebuilds that were invisible outside one-off benches).
Producers anywhere in the process register/update metrics by name;
``Logger.scalars`` snapshots the whole registry into every metrics row, so
one ``metrics.jsonl`` stream carries every signal.

Thread-safety: metric updates are single bytecode-level mutations guarded by
a lock only where a read-modify-write races (counter inc, histogram
observe); ``snapshot()`` may be called from the watchdog thread at any time.
Gauges may be backed by a pull callback (``set_fn``) so sources that already
keep their own total (the native loader's C-side failure count) are read
lazily at snapshot time instead of being pushed per batch.
"""

from __future__ import annotations

import threading
from typing import Callable


class Counter:
    """Monotonic count. ``inc`` is the only mutator."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-written value, or a pull callback (``set_fn``) read at snapshot
    time. A callback that raises falls back to the last good reading — a
    dying producer (e.g. a closed ctypes loader) must not take the metrics
    stream down with it."""

    __slots__ = ("name", "_value", "_fn")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                self._value = float(self._fn())
            except Exception:  # yamt-lint: disable=YAMT012 — documented: a dying pull producer keeps the last good reading
                pass
        return self._value


class Histogram:
    """Streaming summary stats (count/sum/min/max) — enough to read "how
    many, how long, worst case" for durations like checkpoint barrier waits
    without keeping samples."""

    __slots__ = ("name", "count", "total", "vmin", "vmax", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)

    def summary(self) -> dict[str, float]:
        if not self.count:
            return {"count": 0.0, "sum": 0.0, "mean": 0.0, "max": 0.0}
        return {
            "count": float(self.count),
            "sum": self.total,
            "mean": self.total / self.count,
            "max": self.vmax,
        }


class MetricsRegistry:
    """Name -> typed metric, get-or-create semantics. Re-requesting a name
    with a different type is a programming error and fails loudly."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {type(m).__name__}, "
                    f"requested as {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict[str, float]:
        """Flat {name: float} view of every metric; histograms expand to
        ``name.count/.sum/.mean/.max``. Safe to call from any thread."""
        with self._lock:
            metrics = dict(self._metrics)
        out: dict[str, float] = {}
        for name in sorted(metrics):
            m = metrics[name]
            if isinstance(m, Histogram):
                for k, v in m.summary().items():
                    out[f"{name}.{k}"] = v
            else:
                out[name] = float(m.value)
        return out

    def reset(self) -> None:
        """Drop every metric (tests; never called by production code — the
        registry is process-lifetime by design)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every producer and consumer shares."""
    return _REGISTRY
