"""Process-wide typed metrics registry (counters, gauges, histograms).

Unifies the runtime signals that previously lived as ad-hoc module state
(native-loader decode failures reached into from the train loop, bare
``print`` warnings in the data pipeline, checkpoint barrier waits and
post-rematerialize rebuilds that were invisible outside one-off benches).
Producers anywhere in the process register/update metrics by name;
``Logger.scalars`` snapshots the whole registry into every metrics row, so
one ``metrics.jsonl`` stream carries every signal.

Histograms are BUCKETED: every observation lands in a fixed log-spaced
bucket ladder (``DEFAULT_BUCKET_BOUNDS``, overridable per registry via
``set_default_buckets`` — the ``obs.histogram_buckets`` config knob — or per
histogram at creation), so online p50/p95/p99 estimates come out of
``snapshot()`` without keeping samples: the quantile is linearly
interpolated inside the bucket that crosses the target rank, clamped to the
tracked min/max. Error is bounded by one bucket width (~1.78x per rung on
the default quarter-decade ladder) — tests/test_obs.py pins the estimate
against a sorted-sample reference. ``render_prometheus()`` emits the same
state as Prometheus text exposition (``GET /metrics`` on the serving
frontend): histogram families get cumulative ``_bucket{le=...}`` lines plus
``quantile=`` samples, and dotted per-class/per-bucket metric names
(``serve.latency_seconds.interactive``) fold into one labeled family
(``serve_latency_seconds{class="interactive"}``) via ``PROM_LABEL_FAMILIES``.

Thread-safety: metric updates are single bytecode-level mutations guarded by
a lock only where a read-modify-write races (counter inc, histogram
observe); ``snapshot()`` may be called from the watchdog thread at any time.
Gauges may be backed by a pull callback (``set_fn``) so sources that already
keep their own total (the native loader's C-side failure count) are read
lazily at snapshot time instead of being pushed per batch.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Sequence

# Quarter-decade log ladder from 100 µs to ~56 s (24 bounds + overflow):
# wide enough for queue waits and whole-request latencies, fine enough that
# a one-bucket quantile error is ~1.78x — the SLO question is "is p99 5 ms
# or 50 ms", not "5.0 or 5.2". Durations in seconds by convention.
DEFAULT_BUCKET_BOUNDS: tuple[float, ...] = tuple(
    round(1e-4 * (10.0 ** 0.25) ** i, 10) for i in range(24)
)

# Rendered quantiles: snapshot()/render_prometheus() columns and the serving
# frontend's /varz payload all agree on this set.
QUANTILES: tuple[float, ...] = (0.5, 0.95, 0.99)

# Dotted families whose last segment is a label value, not part of the
# metric name: "serve.latency_seconds.interactive" is one sample of the
# serve_latency_seconds family at class="interactive" in the exposition.
PROM_LABEL_FAMILIES: dict[str, str] = {
    "serve.latency_seconds": "class",
    "serve.requests": "class",
    "serve.completed": "class",
    "serve.rejected": "class",
    "serve.retries": "class",
    "serve.shed_deadline": "class",
    "serve.bucket_hits": "bucket",
    # the fleet router's per-class latency (the hedge timer's input)
    "serve.router.latency_seconds": "class",
    # brownout ladder transitions split by direction (up = degrading)
    "serve.brownout_transitions": "direction",
    # fleet-federated derived gauges (obs/fleet.py): windowed fleet-wide
    # p99 per class from exactly-merged replica bucket counts, and the SLO
    # tracker's burn rate per window (short/long — serve/signals.py)
    "fleet.window_p99_seconds": "class",
    "fleet.slo_burn_rate": "window",
    # per-tenant accounting on a zoo-serving replica (serve/admission.py)
    "serve.model_requests": "model",
    "serve.model_completed": "model",
    "serve.model_latency_seconds": "model",
    # per-model image throughput split (serve/engine.py; DEFAULT_MODEL
    # rides the unlabeled total only)
    "serve.infer_images": "model",
    # per-model ring-window split (serve/engine.py ring_dispatch; same
    # DEFAULT_MODEL-rides-the-total convention as infer_images)
    "serve.ring_dispatches": "model",
    # XLA cost_analysis gauges keyed by executable (obs/device.py)
    "obs.cost_flops": "key",
    "obs.cost_bytes": "key",
}


class Counter:
    """Monotonic count. ``inc`` is the only mutator."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-written value, or a pull callback (``set_fn``) read at snapshot
    time. A callback that raises falls back to the last good reading — a
    dying producer (e.g. a closed ctypes loader) must not take the metrics
    stream down with it."""

    __slots__ = ("name", "_value", "_fn")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                self._value = float(self._fn())
            except Exception:  # yamt-lint: disable=YAMT012 — documented: a dying pull producer keeps the last good reading
                pass
        return self._value


class Histogram:
    """Streaming summary stats (count/sum/min/max) plus fixed log-spaced
    bucket counts, so online quantile estimates (p50/p95/p99) come out of a
    snapshot without keeping samples — "how many, how long, worst case, AND
    where the tail sits" for durations like request latencies."""

    __slots__ = ("name", "count", "total", "vmin", "vmax", "bounds", "_bucket_counts", "_lock")

    def __init__(self, name: str, bounds: Sequence[float] = DEFAULT_BUCKET_BOUNDS):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.bounds = tuple(sorted(float(b) for b in bounds))
        if not self.bounds:
            raise ValueError(f"histogram {name!r} needs at least one bucket bound")
        # bucket i counts values <= bounds[i] (and > bounds[i-1]); the last
        # slot is the +Inf overflow bucket
        self._bucket_counts = [0] * (len(self.bounds) + 1)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.vmin = min(self.vmin, v)
            self.vmax = max(self.vmax, v)
            self._bucket_counts[bisect.bisect_left(self.bounds, v)] += 1

    def bucket_counts(self) -> tuple[int, ...]:
        """Per-bucket counts (NOT cumulative), one per bound + the overflow
        slot. Consistent snapshot: taken under the observe lock."""
        with self._lock:
            return tuple(self._bucket_counts)

    def state(self) -> dict:
        """The RAW mergeable state — bounds, non-cumulative counts, running
        count/sum/min/max — as one consistent JSON-safe snapshot. This is
        what /varz ships for metrics federation (obs/fleet.py): identical
        fixed bucket ladders make the cross-replica merge an exact count
        sum, so fleet quantiles lose nothing the per-replica ones had."""
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._bucket_counts),
                "count": self.count,
                "sum": self.total,
                "min": self.vmin if self.count else None,
                "max": self.vmax if self.count else None,
            }

    def _quantiles_locked(self, qs: Sequence[float]) -> list[float]:
        return quantiles_from_counts(
            self.bounds, self._bucket_counts, qs, vmin=self.vmin, vmax=self.vmax
        )

    def quantile(self, q: float) -> float:
        """Bucketed estimate of the q-quantile (0 when empty). Error is
        bounded by the width of the bucket the true quantile lands in."""
        with self._lock:
            return self._quantiles_locked((q,))[0]

    def summary(self) -> dict[str, float]:
        with self._lock:
            if not self.count:
                return {"count": 0.0, "sum": 0.0, "mean": 0.0, "min": 0.0, "max": 0.0,
                        **{_q_key(q): 0.0 for q in QUANTILES}}
            est = self._quantiles_locked(QUANTILES)
            return {
                "count": float(self.count),
                "sum": self.total,
                "mean": self.total / self.count,
                "min": self.vmin,
                "max": self.vmax,
                **{_q_key(q): v for q, v in zip(QUANTILES, est)},
            }


def _q_key(q: float) -> str:
    return "p" + format(q * 100, "g").replace(".", "_")  # 0.5 -> p50, 0.99 -> p99


def quantiles_from_counts(
    bounds: Sequence[float],
    counts: Sequence[int],
    qs: Sequence[float],
    *,
    vmin: float | None = None,
    vmax: float | None = None,
) -> list[float]:
    """Quantile estimates from per-bucket counts (len(bounds) + 1 slots, the
    last being overflow): walk the cumulative counts to the bucket that
    crosses each target rank and interpolate linearly inside it, clamped to
    the observed [vmin, vmax]. Shared by :class:`Histogram` and any consumer
    working from bucket-count DELTAS (scripts/serve_bench.py measures one
    round's quantiles as counts_after - counts_before through this exact
    function, so bench math and registry math cannot drift apart)."""
    total = sum(counts)
    if not total:
        return [0.0 for _ in qs]
    lo_clamp = 0.0 if vmin is None or vmin == float("inf") else vmin
    hi_clamp = bounds[-1] if vmax is None or vmax == float("-inf") else vmax
    out = []
    for q in qs:
        target = q * total
        cum = 0.0
        est = hi_clamp
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= target:
                lo = bounds[i - 1] if i > 0 else lo_clamp
                hi = bounds[i] if i < len(bounds) else hi_clamp
                lo = max(lo, lo_clamp)
                hi = min(max(hi, lo), hi_clamp)
                est = lo + (hi - lo) * (target - cum) / c
                break
            cum += c
        out.append(min(max(est, lo_clamp), hi_clamp))
    return out


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _prom_family(name: str) -> tuple[str, str]:
    """(family, label-clause) for one registry name: a known labeled family
    folds its last segment into a label, everything else is label-less."""
    if "." in name:
        fam, suffix = name.rsplit(".", 1)
        label = PROM_LABEL_FAMILIES.get(fam)
        if label is not None:
            return _prom_name(fam), f'{label}="{suffix}"'
    return _prom_name(name), ""


def _fmt(v: float) -> str:
    return format(float(v), ".10g")


class MetricsRegistry:
    """Name -> typed metric, get-or-create semantics. Re-requesting a name
    with a different type is a programming error and fails loudly."""

    def __init__(self, default_buckets: Sequence[float] = DEFAULT_BUCKET_BOUNDS):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._default_buckets = tuple(default_buckets)
        self._build_info: dict[str, str] = {}
        self._lock = threading.Lock()

    def set_build_info(self, labels: dict) -> None:
        """Install the ``build_info`` exposition family (git sha, jax
        version, platform — obs/device.py ``build_info()``): a constant-1
        gauge whose LABELS carry the identity, the standard Prometheus
        version-attribution idiom, so a scraped fleet can group replicas by
        exactly what they run. Also served verbatim in ``/varz``."""
        with self._lock:
            self._build_info = {str(k): str(v) for k, v in labels.items()}

    @property
    def build_info(self) -> dict:
        return dict(self._build_info)

    def _get(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, *args)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {type(m).__name__}, "
                    f"requested as {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, bounds: Sequence[float] | None = None) -> Histogram:
        """Get-or-create; ``bounds`` applies only at creation (an existing
        histogram keeps its ladder — bucket counts are not re-binnable)."""
        return self._get(name, Histogram, tuple(bounds) if bounds else self._default_buckets)

    def set_default_buckets(self, bounds: Sequence[float]) -> None:
        """Bucket ladder for histograms created AFTER this call (the
        ``obs.histogram_buckets`` config knob, applied at CLI startup before
        any serving histogram exists)."""
        if not bounds:
            return
        self._default_buckets = tuple(sorted(float(b) for b in bounds))  # yamt-lint: disable=YAMT019 — startup-ordered: applied at CLI boot before any serving histogram (or thread) exists

    def snapshot(self) -> dict[str, float]:
        """Flat {name: float} view of every metric; histograms expand to
        ``name.count/.sum/.mean/.min/.max/.p50/.p95/.p99``. Safe to call
        from any thread."""
        with self._lock:
            metrics = dict(self._metrics)
        out: dict[str, float] = {}
        for name in sorted(metrics):
            m = metrics[name]
            if isinstance(m, Histogram):
                for k, v in m.summary().items():
                    out[f"{name}.{k}"] = v
            else:
                out[name] = float(m.value)
        return out

    def histograms_state(self) -> dict[str, dict]:
        """``{name: Histogram.state()}`` for every histogram — the /varz
        federation section a fleet scraper merges exactly (bucket ladders
        are fixed, so summing counts across replicas is lossless)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.state() for name, m in sorted(metrics.items())
                if isinstance(m, Histogram)}

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the whole registry
        — the body behind ``GET /metrics`` (serve/frontend.py). Histograms
        emit cumulative ``_bucket{le=...}``/``_sum``/``_count`` plus
        ``quantile=`` estimate samples; counters/gauges one sample each.
        Stdlib-only, no client library."""
        with self._lock:
            metrics = dict(self._metrics)
            binfo = dict(self._build_info)
        lines: list[str] = []
        typed: set[str] = set()
        if binfo:
            labels = ",".join(
                f'{_prom_name(k)}="{v}"' for k, v in sorted(binfo.items())
            )
            lines.append("# TYPE build_info gauge")
            lines.append(f"build_info{{{labels}}} 1")

        def _type_line(fam: str, kind: str) -> None:
            if fam not in typed:
                typed.add(fam)
                lines.append(f"# TYPE {fam} {kind}")

        for name in sorted(metrics):
            m = metrics[name]
            fam, label = _prom_family(name)
            if isinstance(m, Histogram):
                _type_line(fam, "histogram")
                s = m.summary()
                cum = 0
                for bound, c in zip(m.bounds, m.bucket_counts()):
                    cum += c
                    sep = "," if label else ""
                    lines.append(f'{fam}_bucket{{{label}{sep}le="{_fmt(bound)}"}} {cum}')
                sep = "," if label else ""
                lines.append(f'{fam}_bucket{{{label}{sep}le="+Inf"}} {int(s["count"])}')
                lines.append(f"{fam}_sum{{{label}}} {_fmt(s['sum'])}" if label
                             else f"{fam}_sum {_fmt(s['sum'])}")
                lines.append(f"{fam}_count{{{label}}} {int(s['count'])}" if label
                             else f"{fam}_count {int(s['count'])}")
                for q in QUANTILES:
                    lines.append(
                        f'{fam}{{{label}{sep}quantile="{format(q, "g")}"}} {_fmt(s[_q_key(q)])}'
                    )
            else:
                _type_line(fam, "counter" if isinstance(m, Counter) else "gauge")
                lines.append(f"{fam}{{{label}}} {_fmt(m.value)}" if label
                             else f"{fam} {_fmt(m.value)}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric (tests; never called by production code — the
        registry is process-lifetime by design)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every producer and consumer shares."""
    return _REGISTRY
