"""Ring-buffered span tracer emitting Chrome-trace/Perfetto JSON.

Coordinator-only, host-side, and deliberately dumber than ``jax.profiler``:
spans measure HOST wall time (monotonic ``perf_counter_ns``) around the
things the profiler window cannot see without forcing
``steps_per_dispatch=1`` — data fetch, step dispatch, the log-boundary
``float()`` sync, prune events, eval, checkpoint saves, Trainer rebuilds.
Because a dispatch span closes when the host call RETURNS (async dispatch,
no device sync), tracing adds no host<->device round trips: an input-bound
step shows a fat ``data/next`` span, a dispatch-bound one a fat
``dispatch/*`` span, and a wedged tunnel an open span in the hang report.

The buffer is a fixed-size ring (``collections.deque(maxlen=...)``): a
multi-day run keeps the last N spans, never unbounded memory. Completed
spans are plain tuples; JSON rendering happens only at ``write()``.

Categories are load-bearing (docs/OBSERVABILITY.md span taxonomy): ``data``,
``dispatch``, ``sync``, ``prune``, ``eval``, ``ckpt``, ``rebuild``,
``serve`` (docs/SERVING.md).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time


class _NullSpan:
    """Shared do-nothing context manager returned by a disabled tracer —
    the hot path pays one method call and an attribute test, nothing else."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "t0_ns")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0_ns = time.perf_counter_ns()
        self._tracer._push(self)
        return self

    def __exit__(self, *exc):
        self._tracer._pop(self, time.perf_counter_ns())
        return False


class SpanTracer:
    def __init__(self, ring_size: int = 4096, enabled: bool = True):
        self.enabled = enabled
        self.ring_size = ring_size
        # completed spans: (name, cat, t0_ns, dur_ns, tid, args)
        self._events: collections.deque = collections.deque(maxlen=max(ring_size, 1))
        # open-span stacks keyed by thread id; each thread pushes/pops only
        # its own stack (GIL-atomic list ops), the watchdog reads copies
        self._open: dict[int, list[_Span]] = {}
        self._origin_ns = time.perf_counter_ns()
        self._pid = os.getpid()

    # -- hot path -----------------------------------------------------------

    def span(self, name: str, cat: str = "misc", **args):
        """Context manager timing one host-side region. ``args`` land in the
        Chrome-trace event's ``args`` block (keep them tiny and constant —
        NEVER pass a device array: stringifying it would force the very sync
        this tracer exists to avoid)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def _push(self, span: _Span) -> None:
        tid = threading.get_ident()
        stack = self._open.get(tid)
        if stack is None:
            stack = self._open[tid] = []
        stack.append(span)

    def _pop(self, span: _Span, t1_ns: int) -> None:
        stack = self._open.get(threading.get_ident())
        if stack and stack[-1] is span:
            stack.pop()
        self._events.append(
            (span.name, span.cat, span.t0_ns, t1_ns - span.t0_ns, threading.get_ident(), span.args)
        )

    # -- readout ------------------------------------------------------------

    def open_spans(self) -> list[dict]:
        """Currently-open spans across all threads (outermost first) — the
        "where was it stuck" section of the watchdog's hang report."""
        now = time.perf_counter_ns()
        out = []
        for tid, stack in list(self._open.items()):
            for span in list(stack):
                out.append(
                    {
                        "name": span.name,
                        "cat": span.cat,
                        "tid": tid,
                        "open_for_s": (now - span.t0_ns) / 1e9,
                        "args": span.args,
                    }
                )
        return out

    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (load via chrome://tracing or
        https://ui.perfetto.dev). Complete ("X") events, ts/dur in µs."""
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": "yamt coordinator"},
            }
        ]
        for name, cat, t0_ns, dur_ns, tid, args in list(self._events):
            ev = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": (t0_ns - self._origin_ns) / 1e3,
                "dur": dur_ns / 1e3,
                "pid": self._pid,
                "tid": tid,
            }
            if args:
                ev["args"] = args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        """Atomically write the Chrome-trace JSON next to the run's logs."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


# Module singleton: producers deep in the stack (prefetch_to_mesh, the
# checkpoint manager) fetch the tracer by call, so cli/train.py can configure
# it once without threading a tracer handle through every signature.
_TRACER = SpanTracer(ring_size=1, enabled=False)


def get_tracer() -> SpanTracer:
    return _TRACER


def configure(enabled: bool, ring_size: int = 4096) -> SpanTracer:
    """Install the process tracer (cli/train.py, coordinator only)."""
    global _TRACER
    _TRACER = SpanTracer(ring_size=ring_size, enabled=enabled)
    return _TRACER
