"""Ring-buffered span tracer emitting Chrome-trace/Perfetto JSON.

Coordinator-only, host-side, and deliberately dumber than ``jax.profiler``:
spans measure HOST wall time (monotonic ``perf_counter_ns``) around the
things the profiler window cannot see without forcing
``steps_per_dispatch=1`` — data fetch, step dispatch, the log-boundary
``float()`` sync, prune events, eval, checkpoint saves, Trainer rebuilds.
Because a dispatch span closes when the host call RETURNS (async dispatch,
no device sync), tracing adds no host<->device round trips: an input-bound
step shows a fat ``data/next`` span, a dispatch-bound one a fat
``dispatch/*`` span, and a wedged tunnel an open span in the hang report.

Beyond duration ("X") spans the tracer emits the Chrome-trace event kinds
that correlate ONE request across threads (serve/context.py threads them
through the serving stack):

- **async events** (``ph: b``/``e``, keyed by ``id``): a request's
  admit -> queue -> in-flight -> complete phases render as one nested
  waterfall row per request id in Perfetto, regardless of which thread
  emitted each edge;
- **flow events** (``ph: s``/``t``/``f``, same ``id``): arrows stitching
  the handler thread's submit to the collect thread's dispatch to the
  completion thread's sync;
- **metadata** (``ph: M``): ``thread_name`` rows for registered worker
  threads (``register_thread``), so Perfetto shows ``serve-collect`` /
  ``serve-complete``, not raw thread ids.

The buffer is a fixed-size ring (``collections.deque(maxlen=...)``): a
multi-day run keeps the last N events, never unbounded memory. Completed
events are plain tuples; JSON rendering happens only at ``write()``.

A span exited OUT OF ORDER (an exception path closing a parent before a
child, a handle resolved on a different thread) is removed from its stack
by identity wherever it sits and counted in ``obs.misnested_spans`` —
before this, the stale entry sat in ``_open`` forever and every later hang
report carried phantom "open" spans.

Categories are load-bearing (docs/OBSERVABILITY.md span taxonomy): ``data``,
``dispatch``, ``sync``, ``prune``, ``eval``, ``ckpt``, ``rebuild``,
``serve`` (docs/SERVING.md).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from .registry import get_registry


class _NullSpan:
    """Shared do-nothing context manager returned by a disabled tracer —
    the hot path pays one method call and an attribute test, nothing else."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "t0_ns")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0_ns = time.perf_counter_ns()
        self._tracer._push(self)
        return self

    def __exit__(self, *exc):
        self._tracer._pop(self, time.perf_counter_ns())
        return False


class SpanTracer:
    def __init__(self, ring_size: int = 4096, enabled: bool = True,
                 process_name: str = "yamt coordinator"):
        self.enabled = enabled
        self.ring_size = ring_size
        # the Perfetto process-row label: "router" for the fleet supervisor,
        # the replica_id for serving replicas — a merged cross-process trace
        # (scripts/trace_merge.py) needs each process to say who it is
        self.process_name = process_name
        # completed events: (ph, name, cat, t0_ns, dur_ns, tid, args, ev_id)
        # — ph "X" for duration spans (dur_ns set), "b"/"e" async and
        # "s"/"t"/"f" flow events (ev_id set, dur 0)
        self._events: collections.deque = collections.deque(maxlen=max(ring_size, 1))
        # open-span stacks keyed by thread id; each thread pushes/pops only
        # its own stack (GIL-atomic list ops), the watchdog reads copies
        self._open: dict[int, list[_Span]] = {}
        # tid -> human name for Perfetto thread_name metadata rows
        self._thread_names: dict[int, str] = {}
        self._origin_ns = time.perf_counter_ns()
        # wall-clock anchor sampled ADJACENT to the monotonic origin: every
        # event ts is relative to _origin_ns, so origin_unix is the one wall
        # timestamp that places this process's whole trace on a shared
        # timeline. trace_merge.py aligns N processes by differencing their
        # origins — error is bounded by inter-host wall skew plus the
        # sub-microsecond gap between these two adjacent clock reads.
        # Identity/alignment use only, never differenced into a duration
        # within one process (the YAMT017 hazard is same-process intervals).
        self.origin_unix = time.time()
        self._pid = os.getpid()

    # -- hot path -----------------------------------------------------------

    def span(self, name: str, cat: str = "misc", **args):
        """Context manager timing one host-side region. ``args`` land in the
        Chrome-trace event's ``args`` block (keep them tiny and constant —
        NEVER pass a device array: stringifying it would force the very sync
        this tracer exists to avoid)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def _push(self, span: _Span) -> None:
        tid = threading.get_ident()
        stack = self._open.get(tid)
        if stack is None:
            stack = self._open[tid] = []
        stack.append(span)

    def _pop(self, span: _Span, t1_ns: int) -> None:
        stack = self._open.get(threading.get_ident())
        if stack and stack[-1] is span:
            stack.pop()
        else:
            # out-of-order exit: remove by identity wherever it sits (its
            # own stack first, any other thread's second) so the entry can
            # never pollute later hang reports as a phantom open span.
            # list() snapshots _open: another thread registering its first
            # span mid-scan must not blow up this thread's span exit
            found = False
            for st in ([stack] if stack else []) + [
                s for s in list(self._open.values()) if s is not stack
            ]:
                for i in range(len(st) - 1, -1, -1):
                    if st[i] is span:
                        del st[i]
                        found = True
                        break
                if found:
                    break
            if found:
                get_registry().counter("obs.misnested_spans").inc()
        self._events.append(
            ("X", span.name, span.cat, span.t0_ns, t1_ns - span.t0_ns,
             threading.get_ident(), span.args, None)
        )

    def _mark(self, ph: str, name: str, cat: str, ev_id: int, args: dict | None) -> None:
        if not self.enabled:
            return
        self._events.append(
            (ph, name, cat, time.perf_counter_ns(), 0, threading.get_ident(), args, ev_id)
        )

    # async (nestable, per-id waterfall rows) -------------------------------

    def async_begin(self, name: str, ev_id: int, cat: str = "serve", **args) -> None:
        self._mark("b", name, cat, ev_id, args or None)

    def async_end(self, name: str, ev_id: int, cat: str = "serve", **args) -> None:
        self._mark("e", name, cat, ev_id, args or None)

    # flow (cross-thread arrows) --------------------------------------------

    def flow_start(self, name: str, ev_id: int, cat: str = "serve", **args) -> None:
        self._mark("s", name, cat, ev_id, args or None)

    def flow_step(self, name: str, ev_id: int, cat: str = "serve", **args) -> None:
        self._mark("t", name, cat, ev_id, args or None)

    def flow_end(self, name: str, ev_id: int, cat: str = "serve", **args) -> None:
        self._mark("f", name, cat, ev_id, args or None)

    def register_thread(self, name: str | None = None) -> None:
        """Name the CALLING thread's Perfetto row (``thread_name`` metadata
        event at ``to_chrome_trace``). Worker loops call this once at entry;
        default is the Python thread's own name (``serve-collect``, ...)."""
        if not self.enabled:
            return
        self._thread_names[threading.get_ident()] = (  # yamt-lint: disable=YAMT019 — per-thread dict: every thread writes only its OWN ident key

            name or threading.current_thread().name
        )

    # -- readout ------------------------------------------------------------

    def open_spans(self) -> list[dict]:
        """Currently-open spans across all threads (outermost first) — the
        "where was it stuck" section of the watchdog's hang report."""
        now = time.perf_counter_ns()
        out = []
        for tid, stack in list(self._open.items()):
            for span in list(stack):
                out.append(
                    {
                        "name": span.name,
                        "cat": span.cat,
                        "tid": tid,
                        "open_for_s": (now - span.t0_ns) / 1e9,
                        "args": span.args,
                    }
                )
        return out

    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (load via chrome://tracing or
        https://ui.perfetto.dev). Complete ("X"), async ("b"/"e"), flow
        ("s"/"t"/"f"), and metadata ("M") events, ts/dur in µs."""
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": self.process_name},
            }
        ]
        for tid, name in sorted(self._thread_names.items()):
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": self._pid,
                    "tid": tid,
                    "ts": 0,
                    "args": {"name": name},
                }
            )
        for ph, name, cat, t0_ns, dur_ns, tid, args, ev_id in list(self._events):
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": (t0_ns - self._origin_ns) / 1e3,
                "pid": self._pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = dur_ns / 1e3
            else:
                ev["id"] = ev_id
                if ph == "f":
                    ev["bp"] = "e"  # bind the arrow head to the enclosing slice
            if args:
                ev["args"] = args
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            # cross-process alignment block (scripts/trace_merge.py): which
            # process wrote this file and where its ts=0 sits on the wall
            "pid": self._pid,
            "process_name": self.process_name,
            "origin_unix": self.origin_unix,
        }

    def write(self, path: str) -> str:
        """Atomically write the Chrome-trace JSON next to the run's logs."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


# Module singleton: producers deep in the stack (prefetch_to_mesh, the
# checkpoint manager) fetch the tracer by call, so cli/train.py can configure
# it once without threading a tracer handle through every signature.
_TRACER = SpanTracer(ring_size=1, enabled=False)


def get_tracer() -> SpanTracer:
    return _TRACER


def configure(enabled: bool, ring_size: int = 4096,
              process_name: str = "yamt coordinator") -> SpanTracer:
    """Install the process tracer (cli/train.py, coordinator only).
    ``process_name`` labels this process's Perfetto row — serving processes
    pass their role ("router") or replica_id so a merged fleet trace reads
    as named process lanes, not anonymous pids."""
    global _TRACER
    _TRACER = SpanTracer(ring_size=ring_size, enabled=enabled, process_name=process_name)
    return _TRACER
