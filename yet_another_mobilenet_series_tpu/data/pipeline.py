"""ImageNet input pipeline (reference: DALI GPU pipes + LMDB + torchvision
fallback, SURVEY.md §2 #6).

TPU hosts have no GPU decoder, so the DALI role moves to the host CPU:
tf.data reading TFRecord shards with parallel JPEG decode, Inception-style
random-resized-crop + flip (+ optional color jitter) for train, and the
resize-shorter-side/center-crop eval transform — the exact augmentation
surface of the reference (SURVEY.md §7 hard part 2 lists these as top-1
parity hazards; every knob is in DataConfig). A native C++ decode pipeline
(native/) can replace the tf.data decode stage; a synthetic dataset serves
integration tests and throughput benches.

Per-host sharding: each process reads a disjoint shard slice
(jax.process_index), yielding its local_batch rows; parallel/mesh.shard_batch
assembles the global array (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from ..config import DataConfig
from ..obs.registry import get_registry
from ..utils.logging import emit

# tf is imported lazily: the heavy import (and its thread pools) should only
# exist in processes that actually build an input pipeline.
_tf = None


def _tf_mod():
    global _tf
    if _tf is None:
        import tensorflow as tf

        tf.config.set_visible_devices([], "GPU")
        tf.config.set_visible_devices([], "TPU")
        _tf = tf
    return _tf


# ---------------------------------------------------------------------------
# Decode + augment (tf graph functions)
# ---------------------------------------------------------------------------


def _decode_and_random_crop(tf, image_bytes, cfg: DataConfig, seed2):
    """Inception-style random-resized-crop, the reference's train transform.

    STATELESS randomness keyed by seed2 = [seed, stream position] (like the
    native C++ loader's (seed, global_batch, i) keying): augmentations are a
    pure function of the record's position, so a deterministic_input stream
    is bitwise-reproducible end-to-end and a resumed stream reproduces the
    uninterrupted run's pixels, not just its records."""
    shape = tf.io.extract_jpeg_shape(image_bytes)
    bbox = tf.constant([0.0, 0.0, 1.0, 1.0], dtype=tf.float32, shape=[1, 1, 4])
    begin, size, _ = tf.image.stateless_sample_distorted_bounding_box(
        shape,
        bounding_boxes=bbox,
        seed=seed2,
        min_object_covered=0.1,
        aspect_ratio_range=(cfg.rrc_ratio_min, cfg.rrc_ratio_max),
        area_range=(cfg.rrc_area_min, cfg.rrc_area_max),
        max_attempts=10,
        use_image_if_no_bounding_boxes=True,
    )
    offset_y, offset_x, _ = tf.unstack(begin)
    target_h, target_w, _ = tf.unstack(size)
    crop_window = tf.stack([offset_y, offset_x, target_h, target_w])
    image = tf.image.decode_and_crop_jpeg(image_bytes, crop_window, channels=3)
    image = tf.image.resize(image, [cfg.image_size, cfg.image_size], method="bilinear")
    return image


def _decode_center_crop(tf, image_bytes, cfg: DataConfig):
    """Eval: resize shorter side to eval_resize, center-crop image_size
    (reference: Resize(256)/CenterCrop(224), SURVEY.md §3.3)."""
    shape = tf.io.extract_jpeg_shape(image_bytes)
    h, w = shape[0], shape[1]
    ratio = tf.cast(cfg.eval_resize, tf.float32) / tf.cast(tf.minimum(h, w), tf.float32)
    rh = tf.cast(tf.round(tf.cast(h, tf.float32) * ratio), tf.int32)
    rw = tf.cast(tf.round(tf.cast(w, tf.float32) * ratio), tf.int32)
    image = tf.image.decode_jpeg(image_bytes, channels=3)
    image = tf.image.resize(image, [rh, rw], method="bilinear")
    top = (rh - cfg.image_size) // 2
    left = (rw - cfg.image_size) // 2
    return tf.image.crop_to_bounding_box(image, top, left, cfg.image_size, cfg.image_size)


def _color_jitter(tf, image, strength: float, seed2):
    """torchvision-ColorJitter semantics on a [0,255] float image, fixed
    order brightness→contrast→saturation: brightness multiplies (additive
    tf.image.random_brightness would be a no-op at this scale), contrast
    blends with the mean of the grayscale image, saturation blends with the
    per-pixel grayscale; each op clamps. The native C++ loader implements
    the identical definition (native/yamt_loader.cc color_jitter) so the two
    loaders' augmentations agree. Stateless draws keyed by seed2 + a
    per-factor offset (same distributions as the stateful originals)."""
    lo, hi = 1.0 - strength, 1.0 + strength

    def draw(offset):
        return tf.random.stateless_uniform([], seed=seed2 + tf.constant([offset, 0], tf.int64),
                                           minval=lo, maxval=hi)

    image = tf.clip_by_value(image * draw(1), 0.0, 255.0)
    gray = tf.image.rgb_to_grayscale(image)  # luminance weights .2989/.587/.114
    gm = tf.reduce_mean(gray)
    image = tf.clip_by_value(gm + (image - gm) * draw(2), 0.0, 255.0)
    # saturation blends with the grayscale of the POST-contrast image
    # (recomputed, as the C++ loader does) — not the pre-contrast gray
    gray = tf.image.rgb_to_grayscale(image)
    image = tf.clip_by_value(gray + (image - gray) * draw(3), 0.0, 255.0)
    return image


def _normalize(tf, image, cfg: DataConfig):
    image = tf.cast(image, tf.float32) / 255.0
    mean = tf.constant(cfg.mean, dtype=tf.float32)
    std = tf.constant(cfg.std, dtype=tf.float32)
    return (image - mean) / std


def _finalize(tf, image, cfg: DataConfig):
    """Last pixel op before batching: either host-normalized f32 (default)
    or uint8 for 4x lighter host->device transfer, normalized in-step on
    device (cfg.transfer_uint8; train/steps.py _input_normalizer applies
    the IDENTICAL f32 expression, so the only delta vs the default path is
    the <=0.5/255 rounding of post-augment float pixels — RRC/center-crop
    resize is bilinear (convex) and the jitter clamps, so values are
    already in [0,255])."""
    if cfg.transfer_uint8:
        return tf.cast(tf.clip_by_value(tf.round(image), 0.0, 255.0), tf.uint8)
    return _normalize(tf, image, cfg)


def _parse_example(tf, serialized):
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    }
    parsed = tf.io.parse_single_example(serialized, features)
    # TFRecord ImageNet convention stores labels 1..1000; 0 is background
    label = tf.cast(parsed["image/class/label"], tf.int32) - 1
    return parsed["image/encoded"], label


# ---------------------------------------------------------------------------
# Datasets
# ---------------------------------------------------------------------------


def _tfrecord_files(cfg: DataConfig, split: str) -> list[str]:
    # shard names are {split}-00000-of-00128; the -of- keeps sidecars like
    # {split}-classes.txt out of the match
    pattern = os.path.join(cfg.data_dir, f"{split}-*-of-*")
    import glob

    files = sorted(glob.glob(pattern))
    if not files:
        raise FileNotFoundError(f"no TFRecord shards matching {pattern}")
    return files


# (path, size, mtime_ns) -> record count; survives repeated resumes within a
# process. A JSON sidecar next to the shards persists counts across processes
# (best-effort: data_dir may be read-only).
_RECORD_COUNT_CACHE: dict = {}


def _count_tfrecord_records(path: str) -> int:
    """Exact record count by walking the TFRecord wire framing — per record:
    u64 length, u32 masked-crc(length), data[length], u32 masked-crc(data).
    Reads 8 bytes + one seek per record (no decode, no crc check), so a
    1.28M-record ImageNet epoch counts in seconds, once, cached."""
    import struct

    n = 0
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        pos = 0
        while pos < size:
            header = f.read(8)
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord framing in {path} at byte {pos}")
            (length,) = struct.unpack("<Q", header)
            pos += 8 + 4 + length + 4
            if pos > size:
                raise ValueError(f"TFRecord length field overruns {path} at byte {pos}")
            f.seek(pos)
            n += 1
    return n


def _host_records_per_epoch(cfg: DataConfig, host_files: list[str], files: list[str]) -> int:
    """THIS host's exact records-per-epoch, from actual per-shard counts.

    The estimate ceil(num_train_examples * host_share) is exact only when
    every shard holds the same record count AND num_train_examples matches
    the real total (ADVICE r4 #1); with uneven shards the resume position
    would drift by the per-epoch error times epochs crossed — silently
    breaking the record/pixel-exact guarantee deterministic_input claims.
    Counting is cheap (framing walk, cached in-process and in a sidecar), so
    exactness is unconditional rather than assumption-gated. Falls back to
    the estimate, loudly, only if a shard can't be walked (e.g. compressed
    records, which TFRecordDataset is not configured for here anyway)."""
    import json

    sidecar = os.path.join(cfg.data_dir, ".record_counts.json")
    disk: dict = {}
    try:
        with open(sidecar) as f:
            disk = json.load(f)
    except (OSError, ValueError):
        pass
    dirty = False
    total = 0
    try:
        for path in host_files:
            st = os.stat(path)
            key = (path, st.st_size, st.st_mtime_ns)
            skey = f"{os.path.basename(path)}:{st.st_size}:{st.st_mtime_ns}"
            if key in _RECORD_COUNT_CACHE:
                n = _RECORD_COUNT_CACHE[key]
            elif skey in disk:
                n = int(disk[skey])
                _RECORD_COUNT_CACHE[key] = n
            else:
                n = _count_tfrecord_records(path)
                _RECORD_COUNT_CACHE[key] = n
                disk[skey] = n
                dirty = True
            total += n
    except (OSError, ValueError) as e:
        est = max(-(-cfg.num_train_examples * len(host_files) // len(files)), 1)
        # counted, not just printed: a fallback here silently weakens the
        # exact-resume guarantee, so it must survive into metrics.jsonl
        get_registry().counter("data.record_count_fallbacks").inc()
        emit(f"[data] WARNING: could not count TFRecord shards ({e}); resume "
             f"arithmetic falls back to the equal-shards estimate "
             f"({est} records/epoch) — exact resume is NOT guaranteed if "
             f"shards are uneven")
        return est
    if dirty:
        tmp = sidecar + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(disk, f)
            os.replace(tmp, sidecar)
        except OSError:
            # read-only data_dir: in-process cache still holds
            try:
                os.unlink(tmp)
            except OSError:
                pass
    est = -(-cfg.num_train_examples * len(host_files) // len(files))
    get_registry().gauge("data.host_records_per_epoch").set(max(total, 1))
    if total != est:
        emit(f"[data] host shard records/epoch = {total} (counted; equal-shards "
             f"estimate was {est}) — using the exact count")
    return max(total, 1)


def make_train_dataset(cfg: DataConfig, local_batch: int, seed: int, process_index: int = 0,
                       process_count: int = 1, start_step: int = 0):
    """start_step: local batches this host has already consumed (the resume
    position; VERDICT r3 #2 / SURVEY §5 checkpoint bullet).

    - fake: EXACT continuation — rows are skipped on the tiny pre-decode
      (idx, label) stream, and every downstream op (stateless noise, batch)
      is a pure function of the row sequence, so the resumed stream equals
      the uninterrupted run's batches start_step, start_step+1, ...
      bit-for-bit (pinned by tests/test_resume_data.py).
    - imagenet/TFRecord: epoch-faithful continuation — the per-epoch file
      order is keyed statelessly by (seed, epoch) and the stream starts at
      start_step's epoch with the intra-epoch remainder of records skipped
      pre-decode. Record-level EXACTNESS requires
      cfg.deterministic_input — single-stream deterministic interleave with
      the (seed, epoch) file permutation as the only shuffle — or,
      equivalently, decode_threads=1 + shuffle_buffer=1 (the resume tests
      pin both forms). Measured price (BASELINE.md round 5): within ~7% of
      the default path on a 1-core host, where decode is serial either way;
      on a many-core production host the single interleave stream bounds
      record delivery, so re-measure there before enabling it for a full
      350-epoch run. Under default production settings the parallel
      interleave
      (deterministic=False, kept for throughput) reorders records, and the
      resume point restarts the shuffle buffer — up to shuffle_buffer
      records that sat unemitted in the interrupted run's buffer are
      skipped, and the same count near the skip point can repeat. Bounded
      by ONE buffer (16k records ~ 1% of an ImageNet epoch) per resume,
      not compounding; the guarantee that matters — the SAME epoch's file
      set from the same position, never an epoch-0 replay — holds
      regardless."""
    tf = _tf_mod()
    if cfg.dataset == "fake":
        return _fake_dataset(cfg, local_batch, seed, train=True,
                             process_index=process_index, process_count=process_count,
                             start_step=start_step)
    files = _tfrecord_files(cfg, cfg.train_split)
    host_files = files[process_index::process_count]
    if not host_files:
        raise ValueError(
            f"host {process_index}/{process_count} got zero TFRecord shards "
            f"({len(files)} total); fewer shards than hosts cannot feed training"
        )
    # THIS host's records-per-epoch drives the resume arithmetic. Files are
    # sharded by slicing, so a host's share is its file fraction — not the
    # uniform 1/process_count (with 16 shards on 3 hosts one host reads 6/16
    # of the records; the uniform estimate would drift ~12% per epoch and a
    # deep resume would land whole epochs away from the uninterrupted run).
    # Counts are EXACT per-shard walks (cached), not the equal-shards
    # estimate — uneven shards would otherwise drift by the per-epoch error
    # times epochs crossed (ADVICE r4 #1). Arithmetic is in RECORDS, not
    # batches: batching runs over the continuous record stream (no per-epoch
    # remainder drop), so after k steps exactly k*local_batch records are
    # consumed — a batches-per-epoch floor would drift by
    # (records_per_epoch % local_batch) every epoch.
    start_records = start_step * local_batch
    if start_records:
        records_per_epoch = _host_records_per_epoch(cfg, host_files, files)
        start_epoch = start_records // records_per_epoch
        skip_records = start_records % records_per_epoch
    else:
        start_epoch, skip_records = 0, 0  # fresh run: nothing to count or skip

    def epoch_files(e):
        # stateless per-epoch file permutation: epoch e's order is identical
        # whether reached by streaming or by resuming directly into it
        return tf.data.Dataset.from_tensor_slices(
            tf.random.experimental.stateless_shuffle(
                tf.constant(host_files), seed=tf.stack([tf.cast(seed, tf.int64), e])
            )
        )

    ds = tf.data.Dataset.range(start_epoch, tf.int64.max).flat_map(epoch_files)
    ds = ds.interleave(
        lambda f: tf.data.TFRecordDataset(f, buffer_size=16 * 1024 * 1024),
        # deterministic_input buys record-exact resume (and run-to-run
        # reproducible record order) at interleave-parallelism cost; the
        # default keeps throughput and accepts the one-buffer resume
        # approximation documented above
        cycle_length=1 if cfg.deterministic_input else cfg.decode_threads,
        num_parallel_calls=1 if cfg.deterministic_input else tf.data.AUTOTUNE,
        deterministic=bool(cfg.deterministic_input),
    )
    ds = ds.skip(skip_records)  # serialized records: skipped without decoding
    if not cfg.deterministic_input:
        # under deterministic_input the (seed, epoch) file permutation IS the
        # shuffle; a stateful record buffer would reintroduce resume drift
        ds = ds.shuffle(cfg.shuffle_buffer, seed=seed + 1)
    # stream position (= records consumed, matching the uninterrupted run's
    # numbering) keys the per-record stateless augmentation RNG: the same
    # position draws the same crop/flip/jitter whether reached by streaming
    # or by resume
    ds = ds.enumerate(start=start_records)

    # per-host seed offset (the native loader's convention,
    # native_loader.make_native_train_iter): without it every host would
    # draw the SAME crop/flip/jitter parameters at the same stream
    # position, correlating augmentations across the global batch
    aug_seed = seed + process_index

    def map_fn(pos, serialized):
        seed2 = tf.stack([tf.constant(aug_seed, tf.int64), pos])
        image_bytes, label = _parse_example(tf, serialized)
        image = _decode_and_random_crop(tf, image_bytes, cfg, seed2)
        image = tf.image.stateless_random_flip_left_right(
            image, seed2 + tf.constant([4, 0], tf.int64))
        if cfg.color_jitter > 0:
            image = _color_jitter(tf, image, cfg.color_jitter, seed2)
        if cfg.randaugment_layers > 0:
            from .randaugment import rand_augment

            # offsets >= 16 are reserved for RandAugment's per-layer draws
            # (randaugment._BASE_OFFSET); this map_fn owns offsets 0..4
            image = rand_augment(
                tf, image, cfg.randaugment_layers, cfg.randaugment_magnitude, seed2)
        image = _finalize(tf, image, cfg)
        image.set_shape([cfg.image_size, cfg.image_size, 3])
        return {"image": image, "label": label}

    ds = ds.map(map_fn, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(local_batch, drop_remainder=True)
    ds = ds.prefetch(cfg.prefetch)
    return ds


def eval_batches_per_host(cfg: DataConfig, local_batch: int, process_count: int = 1) -> int:
    """Fixed number of eval batches EVERY host must run. The eval step is a
    collective program: if hosts ran different batch counts the stragglers
    would deadlock in the all-reduce, so each host pads its finite stream up
    to this count (derived from the declared eval set size, the only number
    all hosts agree on without communicating)."""
    n = cfg.fake_eval_size if cfg.dataset == "fake" else cfg.num_eval_examples
    per_host = -(-n // process_count)  # ceil
    return max(-(-per_host // local_batch), 1)


def make_eval_dataset(cfg: DataConfig, local_batch: int, process_index: int = 0, process_count: int = 1):
    """Finite, exactly eval_batches_per_host batches on every host; the tail
    (and any all-dummy equalization batches) is padded with label=-1, which
    the eval step masks out so each example counts exactly once."""
    tf = _tf_mod()
    target = eval_batches_per_host(cfg, local_batch, process_count)
    if cfg.dataset == "fake":
        ds = _fake_dataset(cfg, local_batch, seed=0, train=False,
                           process_index=process_index, process_count=process_count)
    else:
        files = _tfrecord_files(cfg, cfg.val_split)
        ds = tf.data.Dataset.from_tensor_slices(files)
        ds = ds.interleave(tf.data.TFRecordDataset, cycle_length=4, num_parallel_calls=tf.data.AUTOTUNE)
        # record-level sharding: per-host example counts differ by at most 1
        # (file-level sharding can differ by whole shards — or leave a host
        # with zero files when process_count > len(files))
        ds = ds.shard(process_count, process_index)

        def map_fn(serialized):
            image_bytes, label = _parse_example(tf, serialized)
            image = _decode_center_crop(tf, image_bytes, cfg)
            image = _finalize(tf, image, cfg)
            image.set_shape([cfg.image_size, cfg.image_size, 3])
            return {"image": image, "label": label}

        ds = ds.map(map_fn, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(local_batch, drop_remainder=False)
        ds = ds.map(lambda b: _pad_batch(tf, b, local_batch))
    # equalize: append all-dummy batches, then cut to the agreed count
    dummy = tf.data.Dataset.from_tensors({
        "image": tf.zeros([local_batch, cfg.image_size, cfg.image_size, 3],
                          tf.uint8 if cfg.transfer_uint8 else tf.float32),
        "label": -tf.ones([local_batch], tf.int32),
    }).repeat(target)
    ds = ds.concatenate(dummy).take(target)
    return ds.prefetch(cfg.prefetch)


def _pad_batch(tf, batch, local_batch):
    n = tf.shape(batch["label"])[0]
    pad = local_batch - n

    def pad_t(t):
        padding = [[0, pad]] + [[0, 0]] * (len(t.shape) - 1)
        return tf.pad(t, padding)

    return {
        "image": pad_t(batch["image"]),
        "label": tf.concat([batch["label"], -tf.ones([pad], tf.int32)], 0),
    }


# ---------------------------------------------------------------------------
# Fake data (integration tests / benches without ImageNet)
# ---------------------------------------------------------------------------


def _fake_dataset(cfg: DataConfig, local_batch: int, seed: int, train: bool,
                  process_index: int = 0, process_count: int = 1, start_step: int = 0):
    """Learnable synthetic classification: each class has a fixed random
    template; samples are noisy copies. A real model reaches high accuracy in
    a few epochs — which is what the loss-decreases integration tests need
    (SURVEY.md §4.3). Sharded per host like the TFRecord path — without it
    every host would serve the identical stream (duplicate rows in the global
    train batch; double-counted-then-truncated eval)."""
    tf = _tf_mod()
    n_classes = cfg.fake_num_classes or 1000
    n = cfg.fake_train_size if train else cfg.fake_eval_size
    # Class templates are SHARED between train and eval (fixed seed) — only
    # the per-sample noise differs — otherwise eval measures an unlearnable
    # disjoint task and stays at chance forever.
    rng = np.random.RandomState(777)
    templates = tf.constant(
        rng.normal(0, 1, (n_classes, cfg.image_size, cfg.image_size, 3)).astype(np.float32)
    )
    # Only (index, label) rows are materialized; the image is template +
    # stateless per-index noise synthesized in the map. The previous version
    # pre-built all n full-size images in host RAM (e.g. 7.7 GB for 12800
    # samples at 224x224) and fed a TPU chip at ~60 img/s through the
    # resulting shuffle buffer.
    idx = np.arange(n, dtype=np.int64)
    labels = (idx % n_classes).astype(np.int32)
    idx, labels = idx[process_index::process_count], labels[process_index::process_count]
    noise_salt = seed + 1 if train else 987654

    def synth(rec):
        noise = tf.random.stateless_normal(
            (cfg.image_size, cfg.image_size, 3),
            seed=tf.stack([tf.constant(noise_salt, tf.int64), rec["idx"]]),
        )
        return {"image": tf.gather(templates, rec["label"]) + 0.3 * noise, "label": rec["label"]}

    ds = tf.data.Dataset.from_tensor_slices({"idx": idx, "label": labels})
    if train:
        # resume: skip start_step batches' worth of (idx,label) ROWS (cheap,
        # pre-synthesis). The seeded reshuffle sequence and the stateless
        # per-idx noise are pure functions of the stream position, so the
        # continuation is bit-identical to the uninterrupted run's.
        ds = ds.shuffle(len(idx), seed=seed).repeat().skip(start_step * local_batch)
        ds = ds.map(synth, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(local_batch, drop_remainder=True)
    else:
        ds = ds.map(synth, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(local_batch, drop_remainder=False)
        ds = ds.map(lambda b: _pad_batch(tf, b, local_batch))
    return ds.prefetch(tf.data.AUTOTUNE)


# ---------------------------------------------------------------------------
# numpy iterators + fault tolerance
# ---------------------------------------------------------------------------


def as_numpy(ds) -> Iterator[dict]:
    for batch in ds.as_numpy_iterator():
        yield batch


class CorruptRecordError(RuntimeError):
    """A record (or the batch it landed in) could not be decoded. Raised by
    the train/faults.py injector and recognized by resilient_batches; the
    real tf.data equivalents (InvalidArgumentError from a rotten JPEG,
    DataLossError from torn TFRecord framing) are classified alongside it."""


class DataPipelineError(RuntimeError):
    """Too many CONSECUTIVE corrupt batches: the stream is systematically
    broken (rotten shard, wrong directory), not transiently unlucky."""


def _is_corrupt_record_error(e: BaseException) -> bool:
    if isinstance(e, CorruptRecordError):
        return True
    # classify tf errors without importing tensorflow for non-tf pipelines
    if (type(e).__module__ or "").startswith("tensorflow"):
        tf = _tf_mod()
        return isinstance(e, (tf.errors.InvalidArgumentError, tf.errors.DataLossError))
    return False


def resilient_batches(it: Iterator[dict], max_consecutive: int = 16) -> Iterator[dict]:
    """Wraps a batch iterator so a corrupt/undecodable record costs one
    skipped batch (counted in ``data.corrupt_records``) instead of the run.

    tf.data surfaces a decode failure as an error on the batch the record
    landed in and KEEPS SERVING subsequent batches (verified against a
    corrupt-JPEG TFRecord; the iterator is not dead) — so skip-and-retry at
    the batch level is sound. ``max_consecutive`` consecutive failures abort
    with :class:`DataPipelineError`: a fully rotten shard must fail loudly,
    not spin forever. Any error that is NOT a record-decode failure
    propagates untouched — resilience here is for bad DATA, not bad code.
    """
    reg = get_registry()
    consecutive = 0
    while True:
        try:
            batch = next(it)
        except StopIteration:
            return
        except Exception as e:  # noqa: BLE001 — classified, then re-raised or counted
            if not _is_corrupt_record_error(e):
                raise
            consecutive += 1
            reg.counter("data.corrupt_records").inc()
            if consecutive >= max_consecutive:
                raise DataPipelineError(
                    f"{consecutive} consecutive corrupt/undecodable batches "
                    f"(data.max_consecutive_failures={max_consecutive}); the "
                    "stream is systematically broken"
                ) from e
            continue
        consecutive = 0
        yield batch


class PrefetchWorker:
    """Host-side background prefetch: a bounded queue fed by a worker thread,
    so batch production (tf.data next / native decode / augment) overlaps the
    train loop's dispatch work instead of serializing with it.

    Fault story (the point of this class living in the robustness PR): the
    worker carries a YAMT011 top-level crash guard — an unhandled exception
    in batch production is counted (``data.worker_crashes``), the loop is
    restarted in place up to ``max_restarts`` times
    (``data.worker_restarts``; the underlying iterator object survives its
    own exceptions, per resilient_batches), and when the budget is exhausted
    the error is handed to the CONSUMER through the queue — the train loop
    dies with the real cause, never by waiting forever on a silently dead
    thread."""

    _END = ("end", None)

    def __init__(self, it: Iterator[dict], depth: int = 4, max_restarts: int = 3):
        import queue
        import threading

        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = it
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._max_restarts = max_restarts
        self._thread = threading.Thread(target=self._run, name="yamt-data-prefetch", daemon=True)
        self._thread.start()

    # -- worker thread -------------------------------------------------------

    def _run(self):
        try:
            reg = get_registry()
            restarts = 0
            while not self._stop.is_set():
                try:
                    self._pump()
                    return  # stream exhausted (or stop requested) cleanly
                except Exception as e:  # noqa: BLE001 — bounded restart, then surface
                    reg.counter("data.worker_crashes").inc()
                    if restarts >= self._max_restarts:
                        self._put(("error", e))
                        return
                    restarts += 1
                    reg.counter("data.worker_restarts").inc()
                    emit(f"[data] prefetch worker crashed ({type(e).__name__}: {e}); "
                         f"restart {restarts}/{self._max_restarts}")
        except Exception as e:  # noqa: BLE001 — terminal guard (YAMT011): die loud
            self._put(("error", e))

    def _pump(self):
        while not self._stop.is_set():
            try:
                item = ("item", next(self._it))
            except StopIteration:
                self._put(self._END)
                return
            self._put(item)

    def _put(self, item):
        import queue

        # stop-aware put: a consumer that walked away must not wedge the
        # worker (and therefore interpreter shutdown) on a full queue
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer surface ----------------------------------------------------

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        kind, payload = self._q.get()
        if kind == "item":
            return payload
        if kind == "error":
            self.close()
            raise payload
        raise StopIteration

    def close(self):
        self._stop.set()
        # drain so a blocked _put observes the stop promptly
        import queue

        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)


def synthetic_device_batches(cfg: DataConfig, local_batch: int, num_classes: int) -> Iterator[dict]:
    """Pure on-device batches (no host pipeline at all) — isolates model
    throughput from input throughput in benches."""
    rng = np.random.RandomState(0)
    batch = {
        "image": rng.normal(0, 1, (local_batch, cfg.image_size, cfg.image_size, 3)).astype(np.float32),
        "label": (np.arange(local_batch) % num_classes).astype(np.int32),
    }
    while True:
        yield batch
