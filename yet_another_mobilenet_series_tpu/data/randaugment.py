"""Stateless RandAugment (arXiv:1909.13719) for the tf.data train pipeline.

Beyond reference parity: the reference's augmentation surface is
RRC/flip/color-jitter (SURVEY.md §2 #6); RandAugment completes the
EfficientNet-family training recipe (the official EfficientNet repo trains
with it in place of AutoAugment). Op set, magnitude mappings (``_MAX_LEVEL``
10), enhance-factor formulas, gray-fill 128, ``translate_const`` 100 and
``cutout_const`` 40 follow the public TF implementation so magnitudes mean
the same thing here as in published recipes; per-layer op selection draws an
apply-probability ~U(0.2, 0.8) like the official version.

Every draw is STATELESS, keyed by ``seed2 = [aug_seed, stream position]``
plus a per-draw offset (the pipeline contract, data/pipeline.py map_fn):
the same record position produces the same ops/magnitudes whether reached
by streaming or by resume, so ``deterministic_input`` streams stay
bitwise-reproducible. Ops run in uint8 (the official numerics — posterize
is bitwise, equalize histogram-based); input/output is the pipeline's
[0, 255] float32 HWC image.

NOT implemented by the native C++ loader — data/__init__ rejects
``loader=native`` + RandAugment rather than silently diverging.
"""

from __future__ import annotations

_MAX_LEVEL = 10.0
_FILL = 128
_TRANSLATE_CONST = 100.0
_CUTOUT_CONST = 40

# Per-layer stateless draw offsets: pipeline map_fn owns offsets < 16.
_LAYER_STRIDE = 8
_BASE_OFFSET = 16


def _u(tf, seed2, offset, lo=0.0, hi=1.0):
    return tf.random.stateless_uniform(
        [], seed=seed2 + tf.constant([offset, 0], tf.int64), minval=lo, maxval=hi
    )


def _blend(tf, image_a, image_b, factor):
    """PIL.Image.blend: a + factor * (b - a), clipped to uint8 range.
    factor 0 -> a (degenerate), 1 -> b (original), >1 extrapolates."""
    a = tf.cast(image_a, tf.float32)
    b = tf.cast(image_b, tf.float32)
    return tf.cast(tf.clip_by_value(a + factor * (b - a), 0.0, 255.0), tf.uint8)


def _autocontrast(tf, image):
    def scale_channel(ch):
        lo = tf.cast(tf.reduce_min(ch), tf.float32)
        hi = tf.cast(tf.reduce_max(ch), tf.float32)

        def scaled():
            scale = 255.0 / (hi - lo)
            return tf.cast(
                tf.clip_by_value((tf.cast(ch, tf.float32) - lo) * scale, 0.0, 255.0), tf.uint8
            )

        return tf.cond(hi > lo, scaled, lambda: ch)

    return tf.stack([scale_channel(image[..., c]) for c in range(3)], axis=-1)


def _equalize(tf, image):
    def scale_channel(ch):
        histo = tf.histogram_fixed_width(tf.cast(ch, tf.int32), [0, 255], nbins=256)
        nonzero = tf.reshape(tf.gather(histo, tf.where(histo != 0)), [-1])
        step = (tf.reduce_sum(nonzero) - nonzero[-1]) // 255

        def build_lut():
            lut = (tf.cumsum(histo) + (step // 2)) // step
            lut = tf.concat([[0], lut[:-1]], 0)
            return tf.cast(tf.clip_by_value(lut, 0, 255), tf.uint8)

        return tf.cond(step == 0, lambda: ch, lambda: tf.gather(build_lut(), tf.cast(ch, tf.int32)))

    return tf.stack([scale_channel(image[..., c]) for c in range(3)], axis=-1)


def _invert(tf, image):
    return 255 - image


def _posterize(tf, image, bits):
    # official semantics: keep `bits` high bits. The official formula yields
    # bits=0 below magnitude 2.5, where uint8 >> 8 is UNDEFINED (hardware
    # shift-mod); clamp to 1 kept bit instead of inheriting that UB.
    shift = 8 - max(1, bits)
    return tf.bitwise.left_shift(tf.bitwise.right_shift(image, shift), shift)


def _solarize(tf, image, threshold):
    # compare in int32: the official threshold reaches 256 at magnitude 10
    # (PIL solarize(256) == identity), which no uint8 constant can hold
    return tf.where(tf.cast(image, tf.int32) < threshold, image, 255 - image)


def _solarize_add(tf, image, addition, threshold=128):
    added = tf.cast(
        tf.clip_by_value(tf.cast(image, tf.int32) + addition, 0, 255), tf.uint8
    )
    return tf.where(tf.cast(image, tf.int32) < threshold, added, image)


def _gray3(tf, image):
    g = tf.image.rgb_to_grayscale(image)  # uint8 in, uint8 out
    return tf.tile(g, [1, 1, 3])


def _color(tf, image, factor):
    return _blend(tf, _gray3(tf, image), image, factor)


def _contrast(tf, image, factor):
    mean = tf.reduce_mean(tf.cast(_gray3(tf, image), tf.float32))
    degenerate = tf.cast(tf.fill(tf.shape(image), tf.cast(tf.round(mean), tf.uint8)), tf.uint8)
    return _blend(tf, degenerate, image, factor)


def _brightness(tf, image, factor):
    return _blend(tf, tf.zeros_like(image), image, factor)


def _sharpness(tf, image, factor):
    # degenerate = 3x3 smoothing ([[1,1,1],[1,5,1],[1,1,1]]/13) applied to
    # the interior (borders keep the original), the PIL SMOOTH kernel
    img = tf.cast(image, tf.float32)[None]
    kernel = tf.constant([[1, 1, 1], [1, 5, 1], [1, 1, 1]], tf.float32) / 13.0
    kernel = tf.tile(kernel[:, :, None, None], [1, 1, 3, 1])
    smoothed = tf.nn.depthwise_conv2d(img, kernel, [1, 1, 1, 1], padding="VALID")
    smoothed = tf.cast(tf.clip_by_value(smoothed, 0.0, 255.0), tf.uint8)[0]
    pad = [[1, 1], [1, 1], [0, 0]]
    interior = tf.pad(tf.ones_like(smoothed, tf.bool), pad)
    degenerate = tf.where(interior, tf.pad(smoothed, pad), image)
    return _blend(tf, degenerate, image, factor)


def _transform(tf, image, flat):
    """8-parameter projective transform, NEAREST + gray fill (official)."""
    out = tf.raw_ops.ImageProjectiveTransformV3(
        images=tf.cast(image, tf.float32)[None],
        transforms=tf.reshape(tf.stack(flat), [1, 8]),
        output_shape=tf.shape(image)[:2],
        fill_value=tf.constant(float(_FILL)),
        interpolation="NEAREST",
        fill_mode="CONSTANT",
    )
    return tf.cast(out[0], tf.uint8)


def _rotate(tf, image, degrees):
    radians = degrees * 3.141592653589793 / 180.0
    c, s = tf.cos(radians), tf.sin(radians)
    h = tf.cast(tf.shape(image)[0], tf.float32)
    w = tf.cast(tf.shape(image)[1], tf.float32)
    cx, cy = (w - 1.0) / 2.0, (h - 1.0) / 2.0
    # rotate about the center: translate(c) . rot . translate(-c)
    return _transform(
        tf, image,
        [c, -s, cx - c * cx + s * cy, s, c, cy - s * cx - c * cy, 0.0, 0.0],
    )


def _shear_x(tf, image, level):
    return _transform(tf, image, [1.0, level, 0.0, 0.0, 1.0, 0.0, 0.0, 0.0])


def _shear_y(tf, image, level):
    return _transform(tf, image, [1.0, 0.0, 0.0, level, 1.0, 0.0, 0.0, 0.0])


def _translate_x(tf, image, pixels):
    return _transform(tf, image, [1.0, 0.0, -pixels, 0.0, 1.0, 0.0, 0.0, 0.0])


def _translate_y(tf, image, pixels):
    return _transform(tf, image, [1.0, 0.0, 0.0, 0.0, 1.0, -pixels, 0.0, 0.0])


def _cutout(tf, image, pad_size, seed2, offset):
    h, w = tf.shape(image)[0], tf.shape(image)[1]
    cy = tf.random.stateless_uniform(
        [], seed=seed2 + tf.constant([offset, 0], tf.int64), minval=0, maxval=h, dtype=tf.int32
    )
    cx = tf.random.stateless_uniform(
        [], seed=seed2 + tf.constant([offset + 1, 0], tf.int64), minval=0, maxval=w, dtype=tf.int32
    )
    lower, upper = tf.maximum(0, cy - pad_size), tf.minimum(h, cy + pad_size)
    left, right = tf.maximum(0, cx - pad_size), tf.minimum(w, cx + pad_size)
    mask = tf.pad(
        tf.zeros([upper - lower, right - left], tf.uint8),
        [[lower, h - upper], [left, w - right]],
        constant_values=1,
    )[:, :, None]
    return image * mask + tf.cast(_FILL, tf.uint8) * (1 - mask)


def _enhance_factor(magnitude):
    return (magnitude / _MAX_LEVEL) * 1.8 + 0.1


def rand_augment(tf, image, num_layers: int, magnitude: int, seed2):
    """Apply `num_layers` randomly-selected ops at `magnitude` (0..10).

    `image`: [0,255] float32 HWC (the pipeline's post-crop representation).
    """
    m = float(magnitude)
    img = tf.cast(tf.clip_by_value(tf.round(image), 0.0, 255.0), tf.uint8)

    for layer in range(num_layers):
        base = _BASE_OFFSET + _LAYER_STRIDE * layer
        # random sign for the signed (geometric/solarize-add) ops
        sign = tf.where(_u(tf, seed2, base + 1) < 0.5, -1.0, 1.0)
        rot = sign * (m / _MAX_LEVEL) * 30.0
        shear = sign * (m / _MAX_LEVEL) * 0.3
        trans = sign * (m / _MAX_LEVEL) * _TRANSLATE_CONST
        enh = _enhance_factor(m)

        def branches(img, base=base, rot=rot, shear=shear, trans=trans, enh=enh):
            return [
                lambda: _autocontrast(tf, img),
                lambda: _equalize(tf, img),
                lambda: _invert(tf, img),
                lambda: _rotate(tf, img, rot),
                lambda: _posterize(tf, img, int((m / _MAX_LEVEL) * 4)),
                lambda: _solarize(tf, img, int((m / _MAX_LEVEL) * 256)),
                lambda: _color(tf, img, enh),
                lambda: _contrast(tf, img, enh),
                lambda: _brightness(tf, img, enh),
                lambda: _sharpness(tf, img, enh),
                lambda: _shear_x(tf, img, shear),
                lambda: _shear_y(tf, img, shear),
                lambda: _translate_x(tf, img, trans),
                lambda: _translate_y(tf, img, trans),
                lambda: _cutout(tf, img, _CUTOUT_CONST, seed2, base + 4),
                lambda: _solarize_add(tf, img, int((m / _MAX_LEVEL) * 110)),
            ]

        op_idx = tf.random.stateless_uniform(
            [], seed=seed2 + tf.constant([base, 0], tf.int64), minval=0, maxval=16, dtype=tf.int32
        )
        augmented = tf.switch_case(op_idx, branches(img))
        # official behavior: the selected op fires with p ~ U(0.2, 0.8)
        prob = _u(tf, seed2, base + 2, 0.2, 0.8)
        img = tf.where(_u(tf, seed2, base + 3) < prob, augmented, img)

    return tf.cast(img, tf.float32)
