"""ctypes binding for the native C++ input pipeline (native/yamt_loader.cc)
— the DALI-replacement decode+augment path (SURVEY.md §2 #6 native table).

Covers ImageFolder-style directory trees (the reference's torchvision
fallback): ``root/<class_name>/<image>.jpg``, classes sorted
lexicographically to indices — plus explicit (path, label) lists. Yields the
same {'image','label'} numpy batches as the tf.data pipeline, so the trainer
is agnostic to which pipeline feeds it (cfg.data.loader == 'native').
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Sequence

import numpy as np

from ..config import DataConfig
from ..obs.registry import get_registry

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native", "libyamt_loader.so")
_lib = None

# live loaders, so the train loop can log aggregate decode failures without
# holding a reference to the loader behind its iterator wrappers
import weakref

_live_loaders: "weakref.WeakSet[NativeLoader]" = weakref.WeakSet()


def total_decode_failures() -> int:
    """Sum of decode failures across live loaders (0 when none exist)."""
    return sum(l.decode_failures for l in list(_live_loaders) if l._handle is not None)


def build_library(force: bool = False) -> str:
    """Compiles native/libyamt_loader.so (g++ + libjpeg). Always runs make —
    a no-op when up to date — so a stale prebuilt library can never be used
    against newer ctypes signatures (the C ABI has grown arguments before;
    extra args are silently dropped by the calling convention)."""
    # timeout per YAMT015: a wedged compiler must fail the load loudly, not
    # hang the training process before its watchdog even exists
    if force:
        subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH), "-B"],
                       check=True, capture_output=True, timeout=600)
    else:
        subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH)],
                       check=True, capture_output=True, timeout=600)
    return _LIB_PATH


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(build_library())
    lib.loader_create.restype = ctypes.c_void_p
    lib.loader_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
    ]
    lib.loader_add_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.loader_start.argtypes = [ctypes.c_void_p]
    lib.loader_start.restype = ctypes.c_int
    lib.loader_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32)]
    lib.loader_next.restype = ctypes.c_int
    lib.loader_next_u8.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)]
    lib.loader_next_u8.restype = ctypes.c_int
    lib.loader_num_samples.argtypes = [ctypes.c_void_p]
    lib.loader_num_samples.restype = ctypes.c_int64
    lib.loader_decode_failures.argtypes = [ctypes.c_void_p]
    lib.loader_decode_failures.restype = ctypes.c_int64
    lib.loader_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def list_image_folder(root: str) -> tuple[list[str], list[int], list[str]]:
    """(paths, labels, class_names) for a root/<class>/<img>.jpg tree."""
    classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class directories under {root}")
    paths: list[str] = []
    labels: list[int] = []
    for idx, c in enumerate(classes):
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith((".jpg", ".jpeg")):
                paths.append(os.path.join(cdir, f))
                labels.append(idx)
    return paths, labels, classes


class LoaderExhausted(Exception):
    """The native stream ended (loader stopped/destroyed). A dedicated type —
    NOT StopIteration, which PEP 479 turns into RuntimeError when raised
    through a generator (data/__init__.py wraps next_batch in generators)."""


class NativeLoader:
    """Iterator over decoded/augmented batches from the C++ pipeline.

    Streams epochs continuously (train semantics; eval order is file order
    with a fresh pass every num_samples//batch batches, remainder dropped).
    The ring prefetches ahead, so the first batches of the next epoch may
    already be decoding while the current one is consumed."""

    def __init__(
        self,
        paths: Sequence[str],
        labels: Sequence[int],
        cfg: DataConfig,
        batch: int,
        *,
        train: bool,
        seed: int = 0,
        num_threads: int | None = None,
        pad_batches: int = 0,
        start_batch: int = 0,
    ):
        """pad_batches > 0: every pass serves exactly that many batches,
        padding past the sample list with label=-1 (exact eval counting).
        start_batch: resume position — the stream begins at this global
        batch index, bit-identical to an uninterrupted run's (every batch
        is a pure function of (seed, global_batch) in the C++ pipeline)."""
        lib = _load()
        mean = (ctypes.c_float * 3)(*cfg.mean)
        std = (ctypes.c_float * 3)(*cfg.std)
        self._lib = lib
        self._batch = batch
        self._size = cfg.image_size
        self._uint8 = bool(cfg.transfer_uint8)
        self._handle = lib.loader_create(
            cfg.image_size, cfg.eval_resize, batch,
            num_threads or cfg.decode_threads, int(train), seed, mean, std,
            cfg.rrc_area_min, cfg.rrc_area_max, cfg.rrc_ratio_min, cfg.rrc_ratio_max,
            cfg.color_jitter if train else 0.0, pad_batches, start_batch,
            int(cfg.transfer_uint8),
        )
        for p, l in zip(paths, labels):
            lib.loader_add_file(self._handle, os.fsencode(p), int(l))
        if lib.loader_start(self._handle) != 0:
            lib.loader_destroy(self._handle)
            self._handle = None
            if pad_batches:
                raise ValueError("padded eval pass needs at least one sample")
            raise ValueError(f"need at least one full batch of samples ({batch}); got {len(paths)}")
        _live_loaders.add(self)
        # pull-gauge: the train loop no longer reaches into this module at
        # log boundaries — the registry snapshot reads the live total
        # (corrupt inputs stay visible through the one metrics path)
        get_registry().gauge("data.decode_failures").set_fn(total_decode_failures)

    @property
    def num_samples(self) -> int:
        return int(self._lib.loader_num_samples(self._handle))

    @property
    def decode_failures(self) -> int:
        return int(self._lib.loader_decode_failures(self._handle))

    def __iter__(self) -> Iterator[dict]:
        while True:
            try:
                yield self.next_batch()
            except LoaderExhausted:
                return

    def next_batch(self) -> dict:
        labels = np.empty((self._batch,), np.int32)
        if self._uint8:
            # raw pixels, 4x smaller on the wire; the train/eval step
            # normalizes on device (train/steps.py _input_normalizer)
            images = np.empty((self._batch, self._size, self._size, 3), np.uint8)
            rc = self._lib.loader_next_u8(
                self._handle,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        else:
            images = np.empty((self._batch, self._size, self._size, 3), np.float32)
            rc = self._lib.loader_next(
                self._handle,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        if rc != 0:
            raise LoaderExhausted
        return {"image": images, "label": labels}

    def close(self):
        if self._handle is not None:
            self._lib.loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _host_shard(paths, labels, process_index: int, process_count: int):
    """Disjoint per-host slice (the tf.data path's ds.shard equivalent —
    without it every host would decode the identical stream and global
    batches would hold process_count duplicates of each sample)."""
    return paths[process_index::process_count], labels[process_index::process_count]


def make_native_train_iter(
    cfg: DataConfig, local_batch: int, seed: int, process_index: int = 0, process_count: int = 1,
    start_step: int = 0,
) -> NativeLoader:
    """start_step: local batches this host already consumed (== the global
    train step on every host) — the resumed stream continues from there."""
    paths, labels, _ = list_image_folder(os.path.join(cfg.data_dir, cfg.train_split))
    paths, labels = _host_shard(paths, labels, process_index, process_count)
    # per-host seed offset decorrelates shuffle order across hosts
    return NativeLoader(paths, labels, cfg, local_batch, train=True, seed=seed + process_index,
                        start_batch=start_step)


def make_native_eval_loader(
    cfg: DataConfig, local_batch: int, process_index: int = 0, process_count: int = 1
) -> tuple[NativeLoader, int]:
    """Returns (loader, num_batches) for one EXACT eval pass over this host's
    shard: every example counts once. num_batches derives from the LARGEST
    host shard (a number all hosts agree on without communicating), so every
    host runs the same count of collective eval steps; shards smaller than
    num_batches*batch pad the tail with label=-1 rows, which the eval step
    masks out of every metric."""
    paths, labels, _ = list_image_folder(os.path.join(cfg.data_dir, cfg.val_split))
    total = len(paths)
    paths, labels = _host_shard(paths, labels, process_index, process_count)
    max_shard = -(-total // process_count)  # largest host shard size (ceil)
    n_batches = max(-(-max_shard // local_batch), 1)
    loader = NativeLoader(paths, labels, cfg, local_batch, train=False, pad_batches=n_batches)
    return loader, n_batches


if __name__ == "__main__":
    import sys

    if "--build" in sys.argv:
        print(build_library(force=True))
