"""Input pipelines: tf.data (TFRecord/fake) and the native C++ loader.

make_train_source / make_eval_source are the single dispatch point for which
pipeline feeds the trainer — keyed on (dataset, loader) with invalid
combinations rejected up front, so the train and eval halves of a run can
never pick incompatible pipelines.

Valid combinations:
  dataset=imagenet + loader=tfdata   -> TFRecord shards via tf.data
  dataset=fake     + loader=tfdata   -> synthetic learnable data
  dataset=folder   + loader=native   -> ImageFolder tree via native/ C++
"""

from __future__ import annotations

from typing import Iterator

from ..config import DataConfig
from . import pipeline as _pipeline


def _check(cfg: DataConfig) -> None:
    ok = {("imagenet", "tfdata"), ("fake", "tfdata"), ("folder", "native"), ("fake", "synthetic")}
    if (cfg.dataset, cfg.loader) not in ok:
        raise ValueError(
            f"unsupported data config: dataset={cfg.dataset!r} loader={cfg.loader!r}; valid: {sorted(ok)}"
        )
    if cfg.transfer_uint8 and (cfg.dataset, cfg.loader) not in (
            ("imagenet", "tfdata"), ("folder", "native")):
        # fake templates live in normalized space — there are no [0,255]
        # pixels to quantize; the uint8 transfer path exists for the
        # real-JPEG pipelines (tf.data TFRecords and the native C++ loader)
        raise ValueError(
            "data.transfer_uint8 requires a real-JPEG pipeline "
            "(imagenet/tfdata or folder/native); "
            f"got dataset={cfg.dataset!r} loader={cfg.loader!r}"
        )
    if cfg.randaugment_layers < 0 or not 0 <= cfg.randaugment_magnitude <= 10:
        raise ValueError(
            f"randaugment_layers must be >= 0 and randaugment_magnitude in [0, 10]; "
            f"got {cfg.randaugment_layers}/{cfg.randaugment_magnitude}"
        )
    if cfg.randaugment_layers > 0 and (cfg.dataset, cfg.loader) != ("imagenet", "tfdata"):
        # implemented once, in the real-JPEG tf.data pipeline
        # (data/randaugment.py); fake templates live in normalized space and
        # the native loader has no implementation — rejecting beats silently
        # training without it (same policy as transfer_uint8 above)
        raise ValueError(
            "RandAugment requires the imagenet/tfdata pipeline "
            f"(data/randaugment.py); got dataset={cfg.dataset!r} loader={cfg.loader!r} "
            "(for fake-data smoke runs set data.randaugment_layers=0)"
        )


def make_train_source(cfg: DataConfig, local_batch: int, seed: int, process_index: int = 0,
                      process_count: int = 1, start_step: int = 0, inject=None) -> Iterator[dict]:
    """Infinite iterator of {'image','label'} numpy batches (this host's shard).

    start_step: local batches this host already consumed (== the global train
    step; identical on every host). A resumed run CONTINUES the data order
    from there instead of replaying the epoch-0 shuffle — bit-exact for the
    fake/tfdata and folder/native paths, epoch-faithful for TFRecords
    (pipeline.make_train_dataset docstring; tests/test_resume_data.py).

    inject: optional wrapper applied to the RAW stream before the resilience
    layers — the train-side chaos hook (train/faults.py FaultyTrainSource),
    placed there so injected corrupt records exercise the same skip/count/
    abort path real ones take. The resilience stack around it:
    corrupt-record skip with bounded consecutive-failure abort
    (cfg.skip_corrupt_records; pipeline.resilient_batches) and an optional
    guarded background prefetch thread (cfg.prefetch_thread;
    pipeline.PrefetchWorker)."""
    _check(cfg)
    if cfg.loader == "native":
        from . import native_loader

        src = iter(native_loader.make_native_train_iter(
            cfg, local_batch, seed, process_index, process_count, start_step=start_step))
    elif cfg.loader == "synthetic":
        # position-independent by construction (the same device-resident
        # batch forever) — nothing to skip
        src = _pipeline.synthetic_device_batches(cfg, local_batch, cfg.fake_num_classes or 1000)
    else:
        ds = _pipeline.make_train_dataset(cfg, local_batch, seed, process_index, process_count,
                                          start_step=start_step)
        # the RAW tf iterator object, not the as_numpy generator: a decode
        # error raised through a generator kills the generator (subsequent
        # next() is StopIteration), while tf's own iterator keeps serving
        # past the bad batch — which is what resilient_batches relies on
        src = iter(ds.as_numpy_iterator())
    if inject is not None:
        src = inject(src)
    if cfg.skip_corrupt_records:
        src = _pipeline.resilient_batches(src, max_consecutive=cfg.max_consecutive_failures)
    if cfg.prefetch_thread:
        src = _pipeline.PrefetchWorker(src, depth=cfg.prefetch)
    return src


def make_eval_source(cfg: DataConfig, local_batch: int, process_index: int = 0, process_count: int = 1) -> Iterator[dict]:
    """Finite iterator for one eval pass; identical batch count on every host."""
    _check(cfg)
    if cfg.loader == "native":
        from . import native_loader

        loader, n_batches = native_loader.make_native_eval_loader(cfg, local_batch, process_index, process_count)

        def gen():
            for served in range(n_batches):
                try:
                    yield loader.next_batch()
                except native_loader.LoaderExhausted:
                    # a padded eval pass has a KNOWN length; ending early means
                    # the loader died (stale .so, concurrent close) — and on a
                    # pod this host would run fewer collective steps than its
                    # peers, deadlocking them. Fail loudly with context.
                    raise RuntimeError(
                        f"native eval stream ended after {served}/{n_batches} batches"
                    ) from None

        return gen()
    ds = _pipeline.make_eval_dataset(cfg, local_batch, process_index, process_count)
    return _pipeline.as_numpy(ds)
