"""Measured-latency lookup table for latency-aware NAS (ROADMAP item 3).

The AtomNAS penalty weights each expanded channel ("atom") by its FLOPs
cost — but FLOPs is a poor proxy for measured latency (PAPERS.md: FLASH
arXiv 2108.00568, LANA arXiv 2107.10624): a 7x7 depthwise group and a 1x1
matmul column with equal MACs cost very different wall time on real
hardware. This module is the CONSUMER side of the measured alternative:
``scripts/latency_table.py`` benches every distinct block configuration of a
network at several expanded-channel widths through the serving AOT path and
writes a ``LATENCY_TABLE_*.json`` artifact (bench-contract shape,
provenance-stamped); :class:`LatencyTable` loads it and turns the
measurements into per-atom cost vectors via the FLASH/LANA recipe — fit
latency as a linear function of alive expanded channels and take the SLOPE
(seconds per atom) as each atom's marginal cost.

Keying: a block's measurement is looked up by its structural signature —
(in_channels, out_channels, expanded_channels, kernel_sizes, stride,
se_channels, input image size) via :func:`block_key`. The table is built FOR
a network (or a superset of its blocks), so a missing key is a hard error:
silently falling back to FLOPs would quietly un-measure the search
objective. ``nas/penalty.py`` selects this path with
``prune.cost="latency_table"`` + ``prune.latency_table=<path>`` (flag-gated;
the FLOPs default is untouched).

The per-atom slope is uniform across a block's atoms: the measurement prunes
whole width fractions, which removes channels from every kernel branch
proportionally, so the slope is the blended marginal channel cost. A
per-BRANCH slope (prune one kernel group at a time) is the natural
refinement once real-hardware tables exist — the artifact schema already
carries the kernel layout for it.
"""

from __future__ import annotations

import json

import numpy as np

from ..models.specs import Network
from ..ops.blocks import InvertedResidual


def block_key(spec: InvertedResidual, image_size: int, expanded: int | None = None) -> str:
    """Canonical signature of one measurable block configuration. ``expanded``
    overrides the spec's expanded width (the bench measures several widths of
    the SAME block family under one family key, so the family key uses the
    full width while each measurement row records its own alive channels)."""
    e = spec.expanded_channels if expanded is None else expanded
    k = ".".join(str(int(x)) for x in spec.kernel_sizes)
    return (
        f"in{spec.in_channels}_out{spec.out_channels}_e{e}_k{k}"
        f"_s{spec.stride}_se{spec.se_channels}_hw{image_size}"
    )


def block_input_sizes(net: Network, image_size: int | None = None) -> list[int]:
    """Input spatial resolution of every block — the ``hw`` half of each
    block's table key (same stride arithmetic as utils/profiling.py)."""
    hw = image_size or net.image_size
    hw = (hw - 1) // net.stem.stride + 1
    sizes = []
    for blk in net.blocks:
        sizes.append(hw)
        hw = (hw - 1) // blk.stride + 1
    return sizes


class LatencyTable:
    """Loaded ``LATENCY_TABLE_*.json``: family key -> (alive channel ladder,
    measured latency ladder), plus the artifact's provenance block."""

    def __init__(self, entries: dict[str, dict], provenance: dict | None = None):
        if not entries:
            raise ValueError("latency table has no entries")
        self.entries = entries
        self.provenance = dict(provenance or {})
        for key, e in entries.items():
            ch, lat = np.asarray(e["alive_channels"], np.float64), np.asarray(e["latency_s"], np.float64)
            if ch.shape != lat.shape or ch.size < 2:
                raise ValueError(f"table entry {key!r} needs >=2 (channels, latency) pairs")
            if np.any(lat <= 0):
                raise ValueError(f"table entry {key!r} has non-positive latency")

    @classmethod
    def load(cls, path: str) -> "LatencyTable":
        with open(path) as f:
            doc = json.load(f)
        entries = {e["key"]: e for e in doc.get("entries", [])}
        return cls(entries, provenance=doc.get("provenance"))

    def _entry(self, spec: InvertedResidual, image_size: int) -> dict:
        key = block_key(spec, image_size)
        e = self.entries.get(key)
        if e is None:
            raise KeyError(
                f"no latency measurement for block {key!r}; regenerate the table "
                f"with scripts/latency_table.py for this network/image size "
                f"(table has {len(self.entries)} entries)"
            )
        return e

    def block_latency(self, spec: InvertedResidual, image_size: int) -> float:
        """Measured per-image latency (seconds) at full width, interpolated
        on the alive-channel ladder."""
        e = self._entry(spec, image_size)
        ch = np.asarray(e["alive_channels"], np.float64)
        lat = np.asarray(e["latency_s"], np.float64)
        order = np.argsort(ch)
        return float(np.interp(spec.expanded_channels, ch[order], lat[order]))

    def atom_cost(self, spec: InvertedResidual, image_size: int) -> np.ndarray:
        """Per-atom marginal latency (seconds per expanded channel): the
        least-squares slope of measured latency vs alive channels, floored at
        a tiny positive fraction of the mean per-channel latency so a noisy
        flat measurement cannot zero (or invert) the penalty pressure."""
        e = self._entry(spec, image_size)
        ch = np.asarray(e["alive_channels"], np.float64)
        lat = np.asarray(e["latency_s"], np.float64)
        slope = float(np.polyfit(ch, lat, 1)[0])
        floor = 1e-3 * float(np.mean(lat / ch))
        return np.full(spec.expanded_channels, max(slope, floor), np.float64)

    def atom_cost_table(self, net: Network, blocks: set[int] | None = None,
                        image_size: int | None = None) -> tuple[dict[int, np.ndarray], float]:
        """({block index: per-atom seconds vector}, total measured block
        latency at full width) for ``net`` — the measured twin of
        utils/profiling.py's MACs table; the total is the normalizer
        ``prune.normalize_cost`` divides by (resolution-independent rho)."""
        sizes = block_input_sizes(net, image_size)
        costs: dict[int, np.ndarray] = {}
        total = 0.0
        for i, blk in enumerate(net.blocks):
            total += self.block_latency(blk, sizes[i])
            if blocks is None or i in blocks:
                costs[i] = self.atom_cost(blk, sizes[i])
        return costs, total
