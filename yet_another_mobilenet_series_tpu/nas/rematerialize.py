"""Physical shape rematerialization: turn masks into a smaller Network.

The reference rebuilds conv/BN tensors with fewer channels mid-training
(SURVEY.md §3.2 "this CHANGES PARAMETER SHAPES mid-training"); here the same
surgery happens at a coarse cadence (cfg.prune.remat_epochs), paying one
re-jit to convert masked (effective) FLOPs into real FLOPs and step time.
The serving export (serve/export.py) reuses the same surgery to hard-apply a
checkpoint's live masks before folding BN — a deployed bundle never pays
masked-supernet FLOPs.

Surgery per block, given its keep-set of expanded channels:
- expand conv columns, expand/dw BN rows, per-branch depthwise kernels,
  SE reduce rows + SE expand cols/bias, project conv rows are sliced;
- a kernel branch whose atoms all died is dropped entirely;
- a block whose atoms ALL died is dropped when it has a residual (the block
  degenerates to identity); without a residual its strongest atom is kept
  (the chain cannot be cut).
- optimizer/EMA accumulators are sliced identically (params-shaped subtrees
  inside the optax state are located by tree-structure match), so RMSProp/
  momentum history survives the rebuild.

NOTE on BN-stat recalibration: the reference recalibrates BatchNorm running
stats after each shrink (SURVEY.md §2 #11) because its gamma~=0 pruning only
*approximately* removes a channel (the BN beta still leaks through), so the
shrunk network computes a slightly different function whose downstream
statistics drifted. Here pruning is a hard mask applied after BN+act and the
rebuild is proven bit-exact against the masked forward (tests/test_nas.py),
so every surviving BN's statistics are unchanged by construction and no
recalibration pass is needed.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import numpy as np

from ..models.specs import Network
from ..ops.blocks import InvertedResidual


@dataclass
class RematReport:
    dropped_blocks: list[int]
    dropped_branches: dict[int, list[int]]  # old block idx -> dropped kernel sizes
    atoms_before: int
    atoms_after: int
    index_map: dict[int, int]  # old block idx -> new block idx


def _identity(x):
    return x


def _make_block_slicers(block: InvertedResidual, params_b: dict, keep: np.ndarray, branch_keeps: list[np.ndarray]):
    """Returns (new_block_params_slicer_tree) matching params_b structure."""
    sl: dict[str, Any] = {}
    if "expand" in params_b:
        sl["expand"] = {"w": lambda w: w[..., keep]}
        sl["expand_bn"] = {k: (lambda v: v[keep]) for k in params_b["expand_bn"]}
    for i, (k, g) in enumerate(zip(block.kernel_sizes, block.group_channels)):
        bk = branch_keeps[i]
        # dead branches get an identity placeholder (the slicer tree must
        # mirror the params tree); _renumber_dw_keys deletes them after.
        sl[f"dw{i}_k{k}"] = {"w": (lambda w, bk=bk: w[..., bk]) if bk.size else _identity}
    sl["dw_bn"] = {k: (lambda v: v[keep]) for k in params_b["dw_bn"]}
    if "se" in params_b:
        sl["se"] = {
            "reduce": {"w": lambda w: w[keep, :], "b": _identity},
            "expand": {"w": lambda w: w[:, keep], "b": lambda b: b[keep]},
        }
    sl["project"] = {"w": lambda w: w[..., keep, :]}
    sl["project_bn"] = {k: _identity for k in params_b["project_bn"]}
    return sl


def _renumber_dw_keys(block: InvertedResidual, branch_keeps: list[np.ndarray], tree: dict) -> dict:
    """Drop dead branches and renumber dw{i}_k{k} keys to be contiguous."""
    out = {}
    new_i = 0
    for i, k in enumerate(block.kernel_sizes):
        key = f"dw{i}_k{k}"
        if key not in tree:
            continue
        if branch_keeps[i].size == 0:
            continue
        out[f"dw{new_i}_k{k}"] = tree[key]
        new_i += 1
    for key, v in tree.items():
        if not key.startswith("dw") or key.endswith("_bn"):
            out.setdefault(key, v)
    return out


def _apply_slicers(slicer_tree, tree):
    return jax.tree.map(lambda fn, leaf: fn(leaf), slicer_tree, tree)


from ..utils.treeutil import map_params_shaped as _map_params_shaped


def rematerialize(
    net: Network,
    params: dict,
    state: dict,
    masks: dict[str, jax.Array],
    *,
    opt_state=None,
    ema_params=None,
    ema_state=None,
):
    """Returns (new_net, new_params, new_state, new_masks, extras, report)
    where extras = {'opt_state':..., 'ema_params':..., 'ema_state':...} holds
    whichever optional trees were passed, sliced to the new shapes."""
    np_masks = {k: np.asarray(v) for k, v in masks.items()}

    new_blocks: list[InvertedResidual] = []
    param_slicers: dict[str, Any] = {}
    state_slicers: dict[str, Any] = {}
    key_renumber: dict[str, Any] = {}
    dropped_blocks: list[int] = []
    dropped_branches: dict[int, list[int]] = {}
    index_map: dict[int, int] = {}
    atoms_before = atoms_after = 0

    for i, block in enumerate(net.blocks):
        key = str(i)
        m = np_masks.get(key)
        if m is None:  # non-prunable block: pass through
            index_map[i] = len(new_blocks)
            new_blocks.append(block)
            param_slicers[key] = jax.tree.map(lambda _: _identity, params["blocks"][key])
            state_slicers[key] = jax.tree.map(lambda _: _identity, state["blocks"][key])
            continue
        atoms_before += m.size
        keep = np.flatnonzero(m > 0)
        if keep.size == 0:
            if block.has_residual:
                dropped_blocks.append(i)
                continue
            # masking.make_mask_update never lets a non-residual block die
            # completely (it revives the strongest alive atom), and there is
            # NO shrunk network equivalent to an all-dead non-residual block
            # (its masked forward is a constant map). Refuse rather than
            # silently diverge from the masked supernet.
            raise ValueError(
                f"block {i} (no residual) has an all-dead mask; no equivalent "
                "rematerialization exists — masks must keep >=1 atom alive here"
            )
        atoms_after += keep.size

        offsets = np.cumsum([0] + list(block.group_channels))
        branch_keeps = []
        kept_kernels = []
        kept_groups = []
        dropped_k = []
        for j, (k, g) in enumerate(zip(block.kernel_sizes, block.group_channels)):
            bk = keep[(keep >= offsets[j]) & (keep < offsets[j + 1])] - offsets[j]
            branch_keeps.append(bk)
            if bk.size:
                kept_kernels.append(k)
                kept_groups.append(int(bk.size))
            else:
                dropped_k.append(k)
        if dropped_k:
            dropped_branches[i] = dropped_k

        new_block = replace(
            block,
            expanded_channels=int(keep.size),
            kernel_sizes=tuple(kept_kernels),
            group_channels=tuple(kept_groups),
            # the expand conv exists and must survive even if keep.size
            # happens to equal in_channels
            force_expand=block.has_expand,
        )
        index_map[i] = len(new_blocks)
        new_blocks.append(new_block)

        psl = _make_block_slicers(block, params["blocks"][key], keep, branch_keeps)
        # state trees hold mean/var per BN; expand/dw BNs are row-sliced,
        # project BN is untouched
        row = lambda v, keep=keep: v[keep]
        ssl = {
            bn: {leaf: (row if bn != "project_bn" else _identity) for leaf in state["blocks"][key][bn]}
            for bn in state["blocks"][key]
        }
        param_slicers[key] = psl
        state_slicers[key] = ssl
        key_renumber[key] = branch_keeps

    new_net = replace(net, blocks=tuple(new_blocks))

    def slice_params(p):
        out = dict(p)
        nb = {}
        for old_i, new_i in index_map.items():
            old_key, new_key = str(old_i), str(new_i)
            sub = _apply_slicers(param_slicers[old_key], p["blocks"][old_key])
            if old_key in key_renumber:
                sub = _renumber_dw_keys(net.blocks[old_i], key_renumber[old_key], sub)
            nb[new_key] = sub
        out["blocks"] = nb
        return out

    def slice_state(s):
        out = dict(s)
        nb = {}
        for old_i, new_i in index_map.items():
            old_key, new_key = str(old_i), str(new_i)
            nb[new_key] = _apply_slicers(state_slicers[old_key], s["blocks"][old_key])
        out["blocks"] = nb
        return out

    new_params = slice_params(params)
    new_state = slice_state(state)

    extras: dict[str, Any] = {}
    if opt_state is not None:
        pstruct = jax.tree.structure(params)
        extras["opt_state"] = _map_params_shaped(opt_state, pstruct, slice_params)
    if ema_params is not None:
        extras["ema_params"] = slice_params(ema_params)
    if ema_state is not None:
        extras["ema_state"] = slice_state(ema_state)

    from .masking import init_masks

    new_masks = init_masks(new_net)
    report = RematReport(dropped_blocks, dropped_branches, atoms_before, atoms_after, index_map)
    return new_net, new_params, new_state, new_masks, extras, report
