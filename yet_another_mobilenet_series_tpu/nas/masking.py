"""In-jit dynamic shrinkage via channel masks (SURVEY.md §3.2 TPU translation).

The reference physically rebuilds the network with fewer channels every K
steps — hostile to XLA's static shapes. Here shrinkage is a monotonic 0/1
mask over each block's expanded channels, updated *inside* jit at a fixed
cadence; masked forward == physically shrunk forward exactly (proven in
tests/test_ops.py and test_nas.py). Physical rematerialization happens at a
much coarser cadence (nas/rematerialize.py) to reclaim real FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import PruneConfig
from ..models.specs import Network
from ..utils.profiling import masked_macs


def prunable_blocks(net: Network) -> list[int]:
    """Blocks whose expanded channels are atoms. Blocks WITHOUT an expand conv
    (t=1 / depthwise-separable) are excluded: their depthwise channels are the
    block's input itself, so removing one cannot be rematerialized into a
    smaller dense block (the kept channels would be a non-contiguous gather of
    the input)."""
    return [i for i, b in enumerate(net.blocks) if b.has_expand]


def init_masks(net: Network) -> dict[str, jax.Array]:
    """All-alive masks for every prunable block (string block-index keys,
    matching the params tree convention)."""
    return {str(i): jnp.ones((net.blocks[i].expanded_channels,), jnp.float32) for i in prunable_blocks(net)}


def make_mask_update(net: Network, cfg: PruneConfig):
    """Returns update(params, masks) -> new_masks, jit-compatible.

    An atom dies when |gamma| < threshold; death is irreversible (mask is
    multiplied in), matching the reference's one-way shrinkage.
    """
    threshold = float(cfg.gamma_threshold)
    residual = {str(i): b.has_residual for i, b in enumerate(net.blocks)}

    def update(params, masks):
        new = {}
        for k, m in masks.items():
            gamma = params["blocks"][k]["dw_bn"]["gamma"]
            alive = m * (jnp.abs(gamma) >= threshold).astype(jnp.float32)
            if not residual[k]:
                # a non-residual block is the only path through the chain:
                # if everything fell below threshold, revive the strongest
                # previously-alive atom (rematerialize.py does the same).
                best = jnp.argmax(jnp.abs(gamma) * m)
                revive = (jnp.arange(m.shape[0]) == best).astype(jnp.float32) * m
                alive = jnp.where(jnp.sum(alive) == 0, revive, alive)
            new[k] = alive
        return new

    return update


def mask_summary(net: Network, masks) -> dict:
    """Host-side logging payload: alive atom counts + effective MACs — the
    'remaining FLOPs' line the reference logs during shrinkage."""
    np_masks = {int(k): np.asarray(v) for k, v in masks.items()}
    alive = int(sum(m.sum() for m in np_masks.values()))
    total = int(sum(m.size for m in np_masks.values()))
    return {
        "alive_atoms": alive,
        "total_atoms": total,
        "effective_macs": masked_macs(net, np_masks),
    }
