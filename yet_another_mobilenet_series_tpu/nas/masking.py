"""In-jit dynamic shrinkage via channel masks (SURVEY.md §3.2 TPU translation).

The reference physically rebuilds the network with fewer channels every K
steps — hostile to XLA's static shapes. Here shrinkage is a monotonic 0/1
mask over each block's expanded channels, updated *inside* jit at a fixed
cadence; masked forward == physically shrunk forward exactly (proven in
tests/test_ops.py and test_nas.py). Physical rematerialization happens at a
much coarser cadence (nas/rematerialize.py) to reclaim real FLOPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import PruneConfig
from ..models.specs import Network
from ..utils.profiling import masked_macs


def prunable_blocks(net: Network) -> list[int]:
    """Blocks whose expanded channels are atoms. Blocks WITHOUT an expand conv
    (t=1 / depthwise-separable) are excluded: their depthwise channels are the
    block's input itself, so removing one cannot be rematerialized into a
    smaller dense block (the kept channels would be a non-contiguous gather of
    the input)."""
    return [i for i, b in enumerate(net.blocks) if b.has_expand]


def init_masks(net: Network) -> dict[str, jax.Array]:
    """All-alive masks for every prunable block (string block-index keys,
    matching the params tree convention)."""
    return {str(i): jnp.ones((net.blocks[i].expanded_channels,), jnp.float32) for i in prunable_blocks(net)}


def make_mask_update(net: Network, cfg: PruneConfig):
    """Returns update(params, masks) -> new_masks, jit-compatible.

    An atom dies when |gamma| < threshold; death is irreversible (mask is
    multiplied in), matching the reference's one-way shrinkage.
    """
    threshold = float(cfg.gamma_threshold)
    residual = {str(i): b.has_residual for i, b in enumerate(net.blocks)}

    def update(params, masks):
        new = {}
        for k, m in masks.items():
            gamma = params["blocks"][k]["dw_bn"]["gamma"]
            alive = m * (jnp.abs(gamma) >= threshold).astype(jnp.float32)
            if not residual[k]:
                # a non-residual block is the only path through the chain:
                # if everything fell below threshold, revive the strongest
                # previously-alive atom (rematerialize.py does the same).
                best = jnp.argmax(jnp.abs(gamma) * m)
                revive = (jnp.arange(m.shape[0]) == best).astype(jnp.float32) * m
                alive = jnp.where(jnp.sum(alive) == 0, revive, alive)
            new[k] = alive
        return new

    return update


def make_prune_event(net: Network, cfg: PruneConfig, stop_step: int):
    """The COMPLETE per-cadence prune event as one jit-compatible function —
    reached-target check, adaptive-rho feedback, and the conditional mask
    update — of (params, masks, rho_mult, step) -> (masks, rho_mult).

    Until round 5 the reached/rho half lived host-side in cli/train.py,
    which forced steps_per_dispatch=1 under pruning (VERDICT r4 weak #3 /
    next #4): the longest runs — AtomNAS search — could not amortize a
    measured dispatch tax. Moving the event in-device makes the single-step
    and grouped paths share the identical program: the CLI dispatches it at
    the mask cadence, and dp.make_grouped_train_step inlines it after every
    unrolled sub-step, where the same (step % interval == 0) & (step <=
    stop) gate it carries makes off-cadence sub-steps a no-op.

    The reached check uses the in-jit linear form of
    utils/profiling.masked_macs (exact: every atom's expand/dw/SE/project
    MACs scale per-channel): effective = total - sum_b cost_b . (1 - m_b).

    `step` is the index of the JUST-COMPLETED step (ts.step after the
    sub-step), matching the host loop's step_i numbering."""
    from ..utils.profiling import profile_network

    update = make_mask_update(net, cfg)
    prof = profile_network(net)
    total = float(prof.total_macs)
    costs = {str(i): jnp.asarray(c, jnp.float32) for i, c in prof.atom_costs.items()}
    interval = int(cfg.mask_interval)
    target = float(cfg.target_flops)
    adaptive = cfg.rho_schedule == "adaptive" and target > 0

    def event(params, masks, rho_mult, step):
        do = (step % interval == 0) & (step <= stop_step)
        if target > 0:
            eff = jnp.asarray(total, jnp.float32)
            for k, m in masks.items():
                eff = eff - jnp.sum(costs[k] * (1.0 - m))
            reached = eff <= target
        else:
            reached = jnp.asarray(False)
        if adaptive and rho_mult is not None:
            new_rho = jnp.clip(
                rho_mult * jnp.where(reached, 1.0 - cfg.rho_adapt_rate, 1.0 + cfg.rho_adapt_rate),
                cfg.rho_adapt_min, cfg.rho_adapt_max)
            rho_mult = jnp.where(do, new_rho, rho_mult)
        new_masks = update(params, masks)
        apply_update = do & ~reached
        masks = {k: jnp.where(apply_update, new_masks[k], m) for k, m in masks.items()}
        return masks, rho_mult

    return event


def mask_summary(net: Network, masks) -> dict:
    """Host-side logging payload: alive atom counts + effective MACs — the
    'remaining FLOPs' line the reference logs during shrinkage."""
    np_masks = {int(k): np.asarray(v) for k, v in masks.items()}
    alive = int(sum(m.sum() for m in np_masks.values()))
    total = int(sum(m.size for m in np_masks.values()))
    return {
        "alive_atoms": alive,
        "total_atoms": total,
        "effective_macs": masked_macs(net, np_masks),
    }
