"""FLOPs-weighted BN-gamma L1 penalty — the AtomNAS search objective
(reference: utils/prune.py + the loss hook in train.py, SURVEY.md §3.2):

    loss = CE + rho * sum_atoms( flops_cost[atom] * |gamma[atom]| )

Each atom is one expanded channel of an InvertedResidual block; its gamma is
the corresponding entry of the block's post-depthwise BN scale (ops/blocks.py
keeps one concatenated BN across kernel branches precisely so this is a
single vector per block). Dead atoms (mask==0) are excluded so the penalty
pressure concentrates on the living network.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config import PruneConfig
from ..models.specs import Network
from ..utils.profiling import profile_network


def atom_cost_table(net: Network, cfg: PruneConfig) -> dict[str, np.ndarray]:
    """Per-block float32 cost vectors, keyed by block index as str (matching
    the params/masks key convention). Normalized by total network MACs when
    cfg.normalize_cost so rho is resolution/width independent."""
    from .masking import prunable_blocks

    prof = profile_network(net)
    scale = 1.0 / float(prof.total_macs) if cfg.normalize_cost else 1.0
    keep = set(prunable_blocks(net))
    return {str(i): (c * scale).astype(np.float32) for i, c in prof.atom_costs.items() if i in keep}


def make_penalty_fn(net: Network, cfg: PruneConfig):
    """Returns penalty_fn(params, masks) -> float32 scalar for the train step."""
    costs = {k: jnp.asarray(v) for k, v in atom_cost_table(net, cfg).items()}
    rho = float(cfg.rho)

    def penalty_fn(params, masks):
        total = jnp.zeros((), jnp.float32)
        for k, cost in costs.items():
            gamma = params["blocks"][k]["dw_bn"]["gamma"].astype(jnp.float32)
            term = cost * jnp.abs(gamma)
            if masks and k in masks:
                term = term * masks[k].astype(jnp.float32)
            total = total + jnp.sum(term)
        return rho * total

    return penalty_fn
