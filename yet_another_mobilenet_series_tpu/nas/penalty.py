"""Cost-weighted BN-gamma L1 penalty — the AtomNAS search objective
(reference: utils/prune.py + the loss hook in train.py, SURVEY.md §3.2):

    loss = CE + rho * sum_atoms( cost[atom] * |gamma[atom]| )

Each atom is one expanded channel of an InvertedResidual block; its gamma is
the corresponding entry of the block's post-depthwise BN scale (ops/blocks.py
keeps one concatenated BN across kernel branches precisely so this is a
single vector per block). Dead atoms (mask==0) are excluded so the penalty
pressure concentrates on the living network.

The cost source is ``prune.cost`` (ROADMAP item 3): ``"flops"`` (default —
the analytic per-atom MACs of utils/profiling.py, the AtomNAS objective) or
``"latency_table"`` (per-atom MEASURED-latency slopes from a
scripts/latency_table.py artifact via nas/latency.py — searching for the
serving-optimal network, not the FLOPs-optimal one; PAPERS.md FLASH/LANA).
Either way the penalty_fn shape is identical: only the cost constants
baked at build time differ, so switching objectives is a config flip.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config import PruneConfig
from ..models.specs import Network
from ..utils.profiling import profile_network


def atom_cost_table(net: Network, cfg: PruneConfig) -> dict[str, np.ndarray]:
    """Per-block float32 cost vectors, keyed by block index as str (matching
    the params/masks key convention). Normalized by the total network cost
    (MACs, or measured latency in table mode) when cfg.normalize_cost so rho
    is resolution/width independent — and comparable ACROSS cost modes."""
    from .masking import prunable_blocks

    keep = set(prunable_blocks(net))
    if cfg.cost == "latency_table":
        from .latency import LatencyTable

        if not cfg.latency_table:
            raise ValueError(
                "prune.cost='latency_table' needs prune.latency_table "
                "(a scripts/latency_table.py LATENCY_TABLE_*.json artifact)"
            )
        table = LatencyTable.load(cfg.latency_table)
        costs, total = table.atom_cost_table(net, keep)
        scale = 1.0 / total if cfg.normalize_cost else 1.0
        return {str(i): (c * scale).astype(np.float32) for i, c in costs.items()}
    if cfg.cost != "flops":
        raise ValueError(f"unknown prune.cost {cfg.cost!r} (expected 'flops' or 'latency_table')")
    prof = profile_network(net)
    scale = 1.0 / float(prof.total_macs) if cfg.normalize_cost else 1.0
    return {str(i): (c * scale).astype(np.float32) for i, c in prof.atom_costs.items() if i in keep}


def make_penalty_fn(net: Network, cfg: PruneConfig, steps_per_epoch: int | None = None):
    """Returns penalty_fn(params, masks, rho_mult=None, step=None) -> float32
    scalar for the train step.

    The effective penalty weight is ``rho * ramp(step) * rho_mult``
    (SURVEY.md §2 #11 "penalty weight (rho) schedule"): ``ramp`` is the in-jit
    linear warmup over cfg.rho_ramp_epochs (identity for the constant
    schedule), and ``rho_mult`` is the adaptive FLOPs-gap multiplier the train
    loop maintains in TrainState — a traced scalar, so adaptation never
    recompiles the step."""
    if cfg.rho_schedule not in ("constant", "ramp", "adaptive"):
        raise ValueError(f"unknown rho_schedule {cfg.rho_schedule!r}")
    if cfg.rho_schedule == "adaptive" and not cfg.target_flops:
        raise ValueError("rho_schedule='adaptive' needs prune.target_flops (the controller feeds on the FLOPs gap)")
    costs = {k: jnp.asarray(v) for k, v in atom_cost_table(net, cfg).items()}
    rho = float(cfg.rho)
    ramp_steps = 0
    if cfg.rho_schedule in ("ramp", "adaptive") and cfg.rho_ramp_epochs > 0:
        if steps_per_epoch is None:
            raise ValueError("rho_ramp_epochs needs steps_per_epoch")
        ramp_steps = max(int(cfg.rho_ramp_epochs * steps_per_epoch), 1)

    def penalty_fn(params, masks, rho_mult=None, step=None):
        total = jnp.zeros((), jnp.float32)
        for k, cost in costs.items():
            gamma = params["blocks"][k]["dw_bn"]["gamma"].astype(jnp.float32)
            term = cost * jnp.abs(gamma)
            if masks and k in masks:
                term = term * masks[k].astype(jnp.float32)
            total = total + jnp.sum(term)
        r = jnp.asarray(rho, jnp.float32)
        if ramp_steps and step is not None:
            r = r * jnp.clip(step.astype(jnp.float32) / ramp_steps, 0.0, 1.0)
        if rho_mult is not None:
            r = r * rho_mult.astype(jnp.float32)
        return r * total

    return penalty_fn
