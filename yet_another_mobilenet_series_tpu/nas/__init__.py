"""AtomNAS search machinery: penalty, masking, rematerialization, and the
measured-latency cost table (nas/latency.py, ROADMAP item 3)."""

from . import rematerialize  # submodule (rematerialize.rematerialize is the entry point)
from .latency import LatencyTable, block_input_sizes, block_key
from .masking import init_masks, make_mask_update, mask_summary, prunable_blocks
from .penalty import atom_cost_table, make_penalty_fn
from .rematerialize import RematReport

__all__ = [
    "init_masks", "make_mask_update", "mask_summary", "prunable_blocks",
    "atom_cost_table", "make_penalty_fn", "RematReport", "rematerialize",
    "LatencyTable", "block_input_sizes", "block_key",
]
