"""Headline benchmark: MobileNetV3-Large ImageNet training throughput,
images/sec/chip (the tracked metric, BASELINE.json:2), plus MFU.

Measures the full fused training step — forward, backward, RMSProp+WD update,
EMA, label-smoothed CE — in bfloat16 at 224x224 on device-resident data, so
the number is the model/step ceiling of SURVEY.md §3.1's hot loop (host input
throughput is benchmarked separately by the data pipeline).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "platform": ..., "mfu": ..., ...}
and exits 0 even on failure — a failed run emits value=null with an "error"
field instead of a stack trace (the round-1 bench died with rc=1 inside
backend init and produced no artifact at all; never again).

Structure: a supervisor (this process, no JAX import) launches the actual
measurement as a --worker subprocess, retrying with backoff on backend-init
failure and finally falling back to CPU so *some* structured number always
exists. The TPU backend here lives behind a fragile single-chip tunnel:
workers get a generous timeout and are never run concurrently.

vs_baseline: BASELINE.json ships "published": {} (no reference numbers were
recoverable — see SURVEY.md provenance warning), so the divisor is an explicit
assumption recorded here: ~1000 images/sec/chip for the reference's apex+DALI
MobileNet training on its contemporary GPU (V100 class). Replace when a real
reference measurement exists.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ASSUMED_BASELINE_IMG_S_PER_CHIP = 1000.0

# Dense peak bf16 FLOPs/s per chip, by device_kind substring (public specs).
PEAK_FLOPS_BY_KIND = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

WORKER_TIMEOUT_S = 1800  # generous: killing a mid-compile TPU job can wedge the tunnel
RETRIES = 3
BACKOFF_S = (5, 20)  # sleeps between the RETRIES attempts (len == RETRIES - 1)
# stop launching TPU attempts past this point so the CPU fallback always gets
# to run (observed: a dead tunnel burns ~25 min per failed backend init)
TPU_DEADLINE_S = 2400


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def peak_flops_for(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, flops in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return flops
    return None


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess)
# --------------------------------------------------------------------------


RETRYABLE_MARKERS = ("UNAVAILABLE", "Unable to initialize backend", "DEADLINE_EXCEEDED")


def worker(force_cpu: bool):
    """Runs the measurement; on failure prints an error JSON marked retryable
    (transient backend trouble) or not (deterministic, e.g. OOM fallbacks
    exhausted) so the supervisor doesn't repeat guaranteed-to-fail compiles."""
    try:
        _worker_body(force_cpu)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"
        print(json.dumps({
            "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
            "value": None,
            "error": msg[:2000],
            "retryable": any(m in msg for m in RETRYABLE_MARKERS),
        }))


def _worker_body(force_cpu: bool):
    import jax

    if force_cpu:
        # the sandbox's sitecustomize force-selects the axon TPU platform
        # regardless of JAX_PLATFORMS, so override the live config (same
        # trick as tests/conftest.py) before any backend is touched.
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from yet_another_mobilenet_series_tpu.config import ModelConfig, config_from_dict
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib
    from yet_another_mobilenet_series_tpu.train import optim, schedules, steps
    from yet_another_mobilenet_series_tpu.utils.profiling import profile_network

    platform = jax.default_backend()
    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    # batch sized for one v5e-class chip; scale with the mesh. The CPU path
    # exists only as a smoke/fallback mode (this sandbox has few cores) — the
    # recorded number comes from the real-TPU run. On HBM pressure the
    # fallback loop halves the batch (and finally enables activation remat).
    per_chip_batch = 256 if platform == "tpu" else 8
    image_size = 224 if platform == "tpu" else 64
    batch = per_chip_batch * n_chips
    log(f"bench: {platform} ({device_kind}) x{n_chips}, global batch {batch}, image {image_size}")

    mesh = mesh_lib.make_mesh(n_chips)
    net = get_model(ModelConfig(arch="mobilenet_v3_large", dropout=0.2), image_size)
    total_macs = profile_network(net, image_size).total_macs

    def build(batch, remat):
        cfg = config_from_dict({
            "model": {"arch": "mobilenet_v3_large", "dropout": 0.2},
            "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
            "schedule": {"schedule": "exp_decay", "base_lr": 0.064, "warmup_epochs": 5.0},
            "ema": {"enable": True},
            "train": {"batch_size": batch, "compute_dtype": "bfloat16", "remat": remat},
        })
        steps_per_epoch = 1281167 // batch
        lr_fn = schedules.make_lr_schedule(cfg.schedule, batch, steps_per_epoch, 350)
        params, _ = net.init(jax.random.PRNGKey(0))
        optimizer = optim.make_optimizer(cfg.optim, lr_fn, params)
        ts = steps.init_train_state(net, cfg, optimizer, jax.random.PRNGKey(0))
        ts = mesh_lib.replicate(ts, mesh)
        step_fn = dp.make_dp_train_step(net, cfg, optimizer, lr_fn, mesh)
        rng = np.random.RandomState(0)
        host_batch = {
            "image": rng.normal(0, 1, (batch, image_size, image_size, 3)).astype(np.float32),
            "label": (np.arange(batch) % 1000).astype(np.int32),
        }
        b = mesh_lib.shard_batch(host_batch, mesh)
        return step_fn, ts, b

    def sync(arr):
        """Hard sync: device_get of a dependent scalar. block_until_ready is
        NOT a reliable barrier through the axon tunnel — it often returns at
        dispatch-acknowledge time, which made round-2's first 'measurement'
        report a physically impossible 3.6x inflated rate (and >100% 'MFU'
        on eval microbenches). Only an actual device->host transfer of a
        value that depends on the work is trustworthy here."""
        return float(np.asarray(jax.device_get(arr)).ravel()[0])

    key = jax.random.PRNGKey(0)
    attempts = [(batch, False), (batch // 2, False), (batch // 2, True), (batch // 4, True)]
    step_fn = ts = b = None
    for try_batch, remat in attempts:
        try:
            step_fn, ts, b = build(try_batch, remat)
            t0 = time.perf_counter()
            ts, metrics = step_fn(ts, b, key)
            sync(metrics["loss"])
            batch = try_batch
            log(f"batch {batch} remat={remat}: compile+first step {time.perf_counter()-t0:.1f}s")
            break
        except Exception as e:  # XlaRuntimeError RESOURCE_EXHAUSTED etc.
            if "RESOURCE_EXHAUSTED" not in str(e) and "Out of memory" not in str(e):
                raise
            log(f"batch {try_batch} remat={remat} OOM; falling back")
            # drop the failed attempt's device buffers BEFORE rebuilding, or
            # they stay pinned in HBM and the smaller attempt OOMs too
            step_fn = ts = b = None
    if step_fn is None:
        raise RuntimeError("all batch-size fallbacks exhausted")

    # warmup
    for _ in range(3):
        ts, metrics = step_fn(ts, b, key)
    sync(metrics["loss"])

    iters = 20 if platform == "tpu" else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, metrics = step_fn(ts, b, key)
    sync(metrics["loss"])
    dt = time.perf_counter() - t0
    img_s = batch * iters / dt
    img_s_chip = img_s / n_chips
    log(f"steady: {dt/iters*1000:.1f} ms/step, {img_s:.0f} img/s total")

    # MFU, both conventions so consumers can't misread which one this is:
    # mfu counts the train step's actual FLOPs (fwd + ~2x for bwd, 2 FLOPs/MAC
    # = 6*MACs); mfu_fwd_only is the 2*MACs variant some checkers use.
    peak = peak_flops_for(device_kind) if platform == "tpu" else None
    mfu = round(6 * total_macs * img_s_chip / peak, 4) if peak else None
    mfu_fwd = round(2 * total_macs * img_s_chip / peak, 4) if peak else None

    # vs_baseline compares against the assumed 224px reference rate; a CPU
    # fallback measurement at 64px is not comparable — null it there.
    headline_config = platform == "tpu" and image_size == 224
    print(json.dumps({
        "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / ASSUMED_BASELINE_IMG_S_PER_CHIP, 3) if headline_config else None,
        "platform": platform,
        "device_kind": device_kind,
        "n_chips": n_chips,
        "batch_per_chip": batch // n_chips,
        "image_size": image_size,
        "ms_per_step": round(dt / iters * 1000, 2),
        "model_fwd_macs": total_macs,
        "mfu": mfu,
        "mfu_formula": "6*fwd_macs*img_s_chip/peak_bf16_flops (train fwd+bwd)",
        "mfu_fwd_only": mfu_fwd,
    }))


# --------------------------------------------------------------------------
# supervisor: retry + CPU fallback + always-structured output
# --------------------------------------------------------------------------


class WorkerTimeout(Exception):
    pass


def run_worker(force_cpu: bool) -> dict | None:
    """Returns the worker's JSON dict (success or structured error), None if it
    produced no JSON at all, or raises WorkerTimeout if it had to be killed."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
    if force_cpu:
        cmd.append("--cpu")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=WORKER_TIMEOUT_S,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        log(f"worker timed out after {WORKER_TIMEOUT_S}s")
        for stream in (e.stderr, e.stdout):
            if stream:
                text = stream.decode() if isinstance(stream, bytes) else stream
                log(f"partial output: {text[-1000:]}")
        raise WorkerTimeout from e
    sys.stderr.write(proc.stderr)
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and "metric" in out:
                return out
        except json.JSONDecodeError:
            continue
    log(f"worker rc={proc.returncode}, no JSON result; stdout tail: {proc.stdout[-500:]}")
    return None


def main():
    if "--worker" in sys.argv:
        worker(force_cpu="--cpu" in sys.argv)
        return
    if "--cpu" in sys.argv:  # direct CPU smoke mode, no supervisor
        worker(force_cpu=True)
        return

    last_err = "unknown"
    t_start = time.monotonic()
    for attempt in range(RETRIES):
        if attempt > 0 and time.monotonic() - t_start > TPU_DEADLINE_S:
            last_err += f"; TPU deadline {TPU_DEADLINE_S}s exceeded, skipping remaining retries"
            break
        try:
            result = run_worker(force_cpu=False)
        except WorkerTimeout:
            # a killed mid-compile TPU job can wedge the single-chip tunnel;
            # retrying against a possibly-wedged claim only burns timeouts —
            # go straight to the CPU fallback.
            last_err = f"tpu worker timed out after {WORKER_TIMEOUT_S}s (attempt {attempt + 1})"
            break
        if result is not None and result.get("value") is not None:
            print(json.dumps(result))
            return
        if result is not None:
            last_err = f"tpu worker error: {result.get('error', 'unknown')}"
            if not result.get("retryable", True):
                log(f"{last_err} (deterministic); skipping retries")
                break
        else:
            last_err = f"tpu worker produced no result (attempt {attempt + 1}/{RETRIES})"
        if attempt < RETRIES - 1:
            delay = BACKOFF_S[min(attempt, len(BACKOFF_S) - 1)]
            log(f"{last_err}; retrying in {delay}s")
            time.sleep(delay)

    log(f"TPU measurement failed ({last_err}); falling back to CPU smoke measurement")
    try:
        result = run_worker(force_cpu=True)
    except WorkerTimeout:
        result = None
    if result is not None and result.get("value") is not None:
        result["fallback_from"] = "tpu"
        result["tpu_error"] = last_err[:500]
        print(json.dumps(result))
        return

    print(json.dumps({
        "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "platform": None,
        "error": f"{last_err}; cpu fallback also failed",
    }))


if __name__ == "__main__":
    main()
