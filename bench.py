"""Headline benchmark: MobileNetV3-Large ImageNet training throughput,
images/sec/chip (the tracked metric, BASELINE.json:2), plus MFU.

Measures the full fused training step — forward, backward, RMSProp+WD update,
EMA, label-smoothed CE — in bfloat16 at 224x224 on device-resident data, so
the number is the model/step ceiling of SURVEY.md §3.1's hot loop (host input
throughput is benchmarked separately by the data pipeline).

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "platform": ..., "mfu": ..., ...}
and exits 0 even on failure — a failed run emits value=null with an "error"
field instead of a stack trace (the round-1 bench died with rc=1 inside
backend init and produced no artifact at all; never again).

Structure: a supervisor (this process, no JAX import) launches the actual
measurement as a --worker subprocess, retrying with backoff on backend-init
failure and finally falling back to CPU so *some* structured number always
exists. The TPU backend here lives behind a fragile single-chip tunnel:
workers get a generous timeout and are never run concurrently.

vs_baseline: BASELINE.json ships "published": {} (no reference numbers were
recoverable — see SURVEY.md provenance warning), so vs_baseline is null until
a real reference measurement exists; the earlier ~1000 img/s/chip V100-class
guess was noise in the headline artifact and now lives only in
"vs_baseline_note".

Liveness probe: the axon tunnel initializes in ~34 s when alive but takes
~25 min to FAIL when dead (observed both rounds; PROFILE.md). Rounds 1-2 the
driver's capture timed out (rc=1 / rc=124) while the bench was still inside
its retry ladder against a dead tunnel. So the supervisor now first runs a
--probe subprocess (import jax + list devices), hard-killed at PROBE_TIMEOUT_S.
Dead tunnel -> no TPU attempt at all -> CPU fallback; worst-case total
wall-clock ~(150 + 600) s, inside any sane driver window. Killing the probe
is safe where killing a *running job* is not (the round-2 wedge): against a
dead tunnel there is nothing to wedge, and an alive tunnel finishes init
well inside the kill window.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

VS_BASELINE_NOTE = (
    "null: BASELINE.json publishes no reference throughput and the reference "
    "mount is empty; no real divisor exists (an assumed ~1000 img/s/chip "
    "V100-class figure was dropped as noise)"
)

# Dense peak bf16 FLOPs/s per chip, by device_kind substring (public specs).
PEAK_FLOPS_BY_KIND = [
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),
    ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# TPU worker stays generous: killing a mid-compile TPU job can wedge the
# tunnel, and the probe has already established the tunnel is alive.
WORKER_TIMEOUT_S = int(os.environ.get("BENCH_WORKER_TIMEOUT_S", 1800))
CPU_WORKER_TIMEOUT_S = int(os.environ.get("BENCH_CPU_WORKER_TIMEOUT_S", 600))
# Liveness probe: alive tunnel initializes in ~34 s; dead takes ~25 min to
# fail. 150 s separates the two with ~4x margin on the alive side.
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", 150))
RETRIES = 3
BACKOFF_S = (5, 20)  # sleeps between the RETRIES attempts (len == RETRIES - 1)
# stop launching TPU attempts past this point so the CPU fallback always gets
# to run (only reachable when the probe said alive but workers still fail)
TPU_DEADLINE_S = 2400

REPO_DIR = os.path.dirname(os.path.abspath(__file__))
# Measured-winner config (written by scripts/tpu_watch.py's decision step
# after a BENCH_BN A/B applies PROFILE.md's >3% rule). `python bench.py`
# must pick the tuned variant up with no extra flags so the driver's
# end-of-round artifact reflects the repo's best-known configuration.
# BENCH_TUNING_PATH env override exists for the watcher's CPU rehearsal
# (tpu_watch --cpu-rehearsal): the rehearsal's decision steps must exercise
# the real adoption plumbing without touching the production tuning file.
TUNING_PATH = os.environ.get("BENCH_TUNING_PATH") or os.path.join(REPO_DIR, "BENCH_TUNING.json")


def provenance(cpu_rehearsal: bool | None = None) -> dict:
    """Shared bench-artifact provenance stamp: jax/jaxlib versions, python,
    platform/device kind, and the cpu-rehearsal flag — every bench/table
    artifact (serve_bench, train_chaos, latency_table, the headline worker)
    carries this block so a number can always be attributed to the software
    and hardware that produced it.

    Version lookup goes through importlib.metadata, NOT ``import jax`` — the
    bench supervisors (and train_chaos's parent) must never touch a backend.
    Platform/device fields are filled only when the calling process already
    imported jax; ``cpu_rehearsal`` defaults to "the backend is cpu" and can
    be forced by callers that know (train_chaos pins True)."""
    from importlib import metadata

    info: dict = {"python": ".".join(str(v) for v in sys.version_info[:3])}
    for pkg in ("jax", "jaxlib"):
        try:
            info[f"{pkg}_version"] = metadata.version(pkg)
        except metadata.PackageNotFoundError:
            info[f"{pkg}_version"] = None
    j = sys.modules.get("jax")
    if j is not None:
        try:
            devs = j.devices()
            info["platform"] = j.default_backend()
            info["device_kind"] = devs[0].device_kind
            info["n_devices"] = len(devs)
        except Exception as e:  # noqa: BLE001 — a dead backend must not kill the stamp
            info["platform_error"] = f"{type(e).__name__}: {e}"
    if cpu_rehearsal is None:
        cpu_rehearsal = info.get("platform") == "cpu"
    info["cpu_rehearsal"] = bool(cpu_rehearsal)
    return info


def stamp_provenance(artifact: dict, cpu_rehearsal: bool | None = None) -> dict:
    """Attach the provenance block in place (and return the artifact)."""
    artifact["provenance"] = provenance(cpu_rehearsal)
    return artifact


def partition_flags(flags_str: str) -> tuple[str, str]:
    """Split a flag string into (XLA_FLAGS part, LIBTPU_INIT_ARGS part).

    In this sandbox the host XLA build does not know the `--xla_tpu_*`
    options (fatal 'Unknown flag in XLA_FLAGS' at first backend touch,
    verified 2026-07-30); on PJRT-plugin TPUs those flags are consumed by
    libtpu via LIBTPU_INIT_ARGS instead. Every token must start with
    '--xla_' — the underscore matters (ADVICE r4 #2): a near-miss like
    '--xlatpu_...' would pass a bare '--xla' prefix check, land in host
    XLA_FLAGS, and hit the exact fatal 'Unknown flag' abort this guard
    exists to catch at validation time."""
    xla, libtpu = [], []
    for tok in flags_str.split():
        if not tok.startswith("--xla_"):
            raise ValueError(f"flag token {tok!r} does not start with --xla_")
        (libtpu if tok.startswith("--xla_tpu_") else xla).append(tok)
    return " ".join(xla), " ".join(libtpu)


def apply_flags_env(env: dict, flags_str: str) -> dict:
    """Merge a validated flag string into env (XLA_FLAGS / LIBTPU_INIT_ARGS,
    appended — never overwritten). One implementation for both the headline
    supervisor and the sweep, so the merge semantics cannot drift."""
    xla, libtpu = partition_flags(flags_str)
    if xla:
        env["XLA_FLAGS"] = f"{env.get('XLA_FLAGS', '')} {xla}".strip()
    if libtpu:
        env["LIBTPU_INIT_ARGS"] = f"{env.get('LIBTPU_INIT_ARGS', '')} {libtpu}".strip()
    return env


def read_tuning_flags() -> str:
    """Measured-winner XLA flags from the tuning file, supervisor-side (raw
    JSON only — the supervisor must never import jax). Returns "" unless a
    valid non-empty 'flags' string is present."""
    try:
        with open(TUNING_PATH) as f:
            raw = json.load(f)
        flags = raw.get("flags", "")
        if not isinstance(flags, str):
            raise ValueError("flags must be a string")
        partition_flags(flags)  # validates token shape
        return flags
    except FileNotFoundError:
        return ""
    except (OSError, ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
        log(f"tuning: ignoring flags from malformed {TUNING_PATH}: {e}")
        return ""


def load_tuning() -> dict:
    """Best-measured step config, or {} (the exact/no-remat parity baseline).
    A malformed tuning file must never take the headline bench down — it is
    an aux artifact; fall back to the baseline and say so on stderr. Every
    value is validated here (not just parsed): an invalid bn_mode would
    otherwise raise in EVERY ladder rung of both the TPU worker and the CPU
    fallback, shipping a value=null headline artifact. Worker-side only
    (imports the package, hence jax); validation is single-sourced in
    train/tuning.py so bench and the production CLI (train.tuning_file)
    can never disagree about well-formedness."""
    from yet_another_mobilenet_series_tpu.train.tuning import validate_tuning

    try:
        with open(TUNING_PATH) as f:
            raw = json.load(f)
        tuning = validate_tuning(raw)
        if not tuning:
            # a file with no tuning keys is the baseline, not a winner —
            # returning a truthy dict here would stamp a bogus tuning_source
            return {}
        tuning["source"] = raw.get("source")
        return tuning
    except FileNotFoundError:
        return {}
    except (OSError, ValueError, KeyError, TypeError) as e:
        log(f"tuning: ignoring malformed {TUNING_PATH}: {e}")
        return {}


def latest_tpu_artifact() -> dict | None:
    """Newest BENCH_TPU_r*.json (highest round number) as a provenance block,
    so a dead-tunnel fallback artifact still carries the repo's best-known
    real-hardware measurement (VERDICT r3 #3)."""
    best = None
    for path in glob.glob(os.path.join(REPO_DIR, "BENCH_TPU_r*.json")):
        m = re.search(r"BENCH_TPU_r(\d+)\.json$", path)
        if not m:
            continue
        rnd = int(m.group(1))
        if best is not None and rnd <= best[0]:
            continue
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(d, dict) and d.get("value") and d.get("platform") == "tpu":
            best = (rnd, path, d)
    if best is None:
        return None
    _, path, d = best
    # measured_utc is stamped into the artifact at write time (see
    # _worker_body); file mtime is only a last resort — for a git-tracked
    # artifact it is checkout time, not measurement time, so label it.
    if d.get("measured_utc"):
        date, date_source = d["measured_utc"][:10], "artifact"
    else:
        date = time.strftime("%Y-%m-%d", time.gmtime(os.path.getmtime(path)))
        date_source = "file_mtime (checkout-time lower bound, not measurement time)"
    return {
        "value": d["value"],
        "unit": d.get("unit"),
        "ms_per_step": d.get("ms_per_step"),
        "mfu": d.get("mfu"),
        "device_kind": d.get("device_kind"),
        "source": os.path.basename(path),
        "measured_date": date,
        "date_source": date_source,
    }


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def peak_flops_for(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, flops in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return flops
    return None


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess)
# --------------------------------------------------------------------------


RETRYABLE_MARKERS = ("UNAVAILABLE", "Unable to initialize backend", "DEADLINE_EXCEEDED")


def probe():
    """Liveness probe body (runs as a --probe subprocess): touch the backend
    and report. Prints one JSON line on success; a dead tunnel simply hangs
    inside backend init until the supervisor kills us."""
    t0 = time.perf_counter()
    import jax

    devs = jax.devices()
    print(json.dumps({
        "alive": True,
        "platform": jax.default_backend(),
        "n_devices": len(devs),
        "device_kind": devs[0].device_kind,
        "init_s": round(time.perf_counter() - t0, 1),
    }))


def run_probe() -> tuple[str, dict | None]:
    """Returns (status, info): ("alive", probe_json) when the backend came up
    inside PROBE_TIMEOUT_S; ("timeout", None) when it hung that long — the
    dead-tunnel signature (~25 min to fail vs ~34 s to init); ("failed",
    None) when the probe exited quickly without a backend — a FAST init
    failure, which the round-2 tunnel produced transiently and which the
    worker retry ladder can recover, so it must NOT be treated as dead."""
    cmd = [sys.executable, os.path.abspath(__file__), "--probe"]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        log(f"probe: no backend after {PROBE_TIMEOUT_S}s -> tunnel dead")
        return "timeout", None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and out.get("alive"):
                log(f"probe: {out.get('platform')} x{out.get('n_devices')} "
                    f"({out.get('device_kind')}) in {out.get('init_s')}s")
                return "alive", out
        except json.JSONDecodeError:
            continue
    log(f"probe: rc={proc.returncode} in {time.perf_counter()-t0:.0f}s, "
        f"no alive JSON; stderr tail: {proc.stderr[-300:]}")
    return "failed", None


def worker(force_cpu: bool):
    """Runs the measurement; on failure prints an error JSON marked retryable
    (transient backend trouble) or not (deterministic, e.g. OOM fallbacks
    exhausted) so the supervisor doesn't repeat guaranteed-to-fail compiles."""
    try:
        _worker_body(force_cpu)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"
        print(json.dumps({
            "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
            "value": None,
            "error": msg[:2000],
            "retryable": any(m in msg for m in RETRYABLE_MARKERS),
        }))


def _worker_body(force_cpu: bool):
    import jax

    if force_cpu:
        # the sandbox's sitecustomize force-selects the axon TPU platform
        # regardless of JAX_PLATFORMS, so override the live config (same
        # trick as tests/conftest.py) before any backend is touched.
        jax.config.update("jax_platforms", "cpu")
    from yet_another_mobilenet_series_tpu.utils.benchkit import build_train_fixture, sync
    from yet_another_mobilenet_series_tpu.utils.profiling import profile_network

    platform = jax.default_backend()
    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    # batch sized for one v5e-class chip; scale with the mesh. The CPU path
    # exists only as a smoke/fallback mode (this sandbox has few cores) — the
    # recorded number comes from the real-TPU run. On HBM pressure the
    # fallback loop halves the batch (and finally enables activation remat).
    per_chip_batch = 256 if platform == "tpu" else 8
    image_size = 224 if platform == "tpu" else 64
    batch = per_chip_batch * n_chips
    log(f"bench: {platform} ({device_kind}) x{n_chips}, global batch {batch}, image {image_size}")

    tuning = load_tuning()
    if tuning:
        log(f"bench: measured-winner tuning from {TUNING_PATH}: {tuning}")
    bn_mode = tuning.get("bn_mode", "exact")
    conv1x1_dot = bool(tuning.get("conv1x1_dot", False))
    remat_policy = tuning.get("remat_policy", "full")
    base_remat = bool(tuning.get("remat", False))

    key = jax.random.PRNGKey(0)
    # OOM ladder: first shrink batch under the tuned config, then fall back
    # to full remat (the most memory-conservative policy — a tuned
    # save_conv keeps activations the last-resort rung must not), deduped
    # so a tuned remat=True doesn't recompile an identical rung.
    attempts = []
    for cand in [(batch, base_remat, remat_policy), (batch // 2, base_remat, remat_policy),
                 (batch // 2, True, "full"), (batch // 4, True, "full")]:
        if cand not in attempts:
            attempts.append(cand)
    step_fn = ts = b = net = None
    used_remat, used_policy = base_remat, remat_policy
    for try_batch, remat, policy in attempts:
        try:
            step_fn, ts, b, net = build_train_fixture(
                try_batch, image_size, remat=remat, remat_policy=policy,
                bn_mode=bn_mode, conv1x1_dot=conv1x1_dot)
            t0 = time.perf_counter()
            ts, metrics = step_fn(ts, b, key)
            sync(metrics["loss"])
            batch = try_batch
            used_remat, used_policy = remat, policy
            log(f"batch {batch} remat={remat}/{policy}: compile+first step {time.perf_counter()-t0:.1f}s")
            break
        except Exception as e:  # XlaRuntimeError RESOURCE_EXHAUSTED etc.
            if "RESOURCE_EXHAUSTED" not in str(e) and "Out of memory" not in str(e):
                raise
            log(f"batch {try_batch} remat={remat} OOM; falling back")
            # drop the failed attempt's device buffers BEFORE rebuilding, or
            # they stay pinned in HBM and the smaller attempt OOMs too
            step_fn = ts = b = None
    if step_fn is None:
        raise RuntimeError("all batch-size fallbacks exhausted")
    # profile the SAME spec the fixture built (single source for the arch)
    total_macs = profile_network(net, image_size).total_macs

    # warmup
    for _ in range(3):
        ts, metrics = step_fn(ts, b, key)
    sync(metrics["loss"])

    iters = 20 if platform == "tpu" else 5
    k_dispatch = tuning.get("steps_per_dispatch", 1)  # validated int (load_tuning)
    if k_dispatch > 1:
        # measure the ADOPTED production dispatch mode: k steps per jit call
        # (cli/train.py steps_per_dispatch) — same step math, amortized
        # host-dispatch tax (the delta bench_bn's --dispatch-probe measured)
        from yet_another_mobilenet_series_tpu.parallel.dp import make_grouped_train_step

        gstep = make_grouped_train_step(step_fn, k_dispatch)
        batches = (b,) * k_dispatch
        groups = max(iters // k_dispatch, 1)
        iters = groups * k_dispatch
        ts, mets = gstep(ts, batches, key)  # compile + warm the grouped program
        sync(mets[-1]["loss"])
        t0 = time.perf_counter()
        for _ in range(groups):
            ts, mets = gstep(ts, batches, key)
        sync(mets[-1]["loss"])
    else:
        t0 = time.perf_counter()
        for _ in range(iters):
            ts, metrics = step_fn(ts, b, key)
        sync(metrics["loss"])
    dt = time.perf_counter() - t0
    img_s = batch * iters / dt
    img_s_chip = img_s / n_chips
    log(f"steady: {dt/iters*1000:.1f} ms/step, {img_s:.0f} img/s total")

    # MFU, both conventions so consumers can't misread which one this is:
    # mfu counts the train step's actual FLOPs (fwd + ~2x for bwd, 2 FLOPs/MAC
    # = 6*MACs); mfu_fwd_only is the 2*MACs variant some checkers use.
    peak = peak_flops_for(device_kind) if platform == "tpu" else None
    mfu = round(6 * total_macs * img_s_chip / peak, 4) if peak else None
    mfu_fwd = round(2 * total_macs * img_s_chip / peak, 4) if peak else None

    print(json.dumps({
        "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "vs_baseline_note": VS_BASELINE_NOTE,
        "platform": platform,
        "device_kind": device_kind,
        "n_chips": n_chips,
        "batch_per_chip": batch // n_chips,
        "image_size": image_size,
        "ms_per_step": round(dt / iters * 1000, 2),
        "model_fwd_macs": total_macs,
        "mfu": mfu,
        "mfu_formula": "6*fwd_macs*img_s_chip/peak_bf16_flops (train fwd+bwd)",
        "mfu_fwd_only": mfu_fwd,
        "step_config": {
            # used_*, not the tuned request: the OOM ladder may have turned
            # remat on / forced policy to full, and the artifact must
            # describe what actually ran
            "bn_mode": bn_mode, "remat": used_remat, "remat_policy": used_policy,
            "conv1x1_dot": conv1x1_dot, "steps_per_dispatch": k_dispatch,
            "tuning_source": tuning.get("source"),
            # what the process actually ran under (tuned flags arrive via env)
            "xla_flags_env": os.environ.get("XLA_FLAGS", ""),
            "libtpu_init_args_env": os.environ.get("LIBTPU_INIT_ARGS", ""),
        },
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "provenance": provenance(),
    }))


# --------------------------------------------------------------------------
# supervisor: retry + CPU fallback + always-structured output
# --------------------------------------------------------------------------


class WorkerTimeout(Exception):
    pass


def run_worker(force_cpu: bool, flags: str = "") -> dict | None:
    """Returns the worker's JSON dict (success or structured error), None if it
    produced no JSON at all, or raises WorkerTimeout if it had to be killed.
    `flags` (tuned XLA/libtpu flags) only ever applies to TPU workers — the
    CPU fallback must stay flag-free (host XLA aborts on unknown flags)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
    if force_cpu:
        cmd.append("--cpu")
    env = None
    if flags and not force_cpu:
        env = apply_flags_env(os.environ.copy(), flags)
        log(f"worker env: tuned flags {flags!r}")
    timeout_s = CPU_WORKER_TIMEOUT_S if force_cpu else WORKER_TIMEOUT_S
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
    except subprocess.TimeoutExpired as e:
        log(f"worker timed out after {timeout_s}s")
        for stream in (e.stderr, e.stdout):
            if stream:
                text = stream.decode() if isinstance(stream, bytes) else stream
                log(f"partial output: {text[-1000:]}")
        raise WorkerTimeout from e
    sys.stderr.write(proc.stderr)
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and "metric" in out:
                return out
        except json.JSONDecodeError:
            continue
    log(f"worker rc={proc.returncode}, no JSON result; stdout tail: {proc.stdout[-500:]}")
    return None


def main():
    if "--worker" in sys.argv:
        worker(force_cpu="--cpu" in sys.argv)
        return
    if "--probe" in sys.argv:
        probe()
        return
    if "--cpu" in sys.argv:  # direct CPU smoke mode, no supervisor
        worker(force_cpu=True)
        return

    last_err = "unknown"
    t_start = time.monotonic()
    probe_status, probe_result = run_probe()
    if probe_status == "timeout":
        # the dead-tunnel hang: skip every TPU attempt (each would burn
        # ~25 min) and record the binding metric via the CPU fallback
        emit_cpu_fallback(f"liveness probe found no TPU inside {PROBE_TIMEOUT_S}s")
        return
    if probe_status == "alive" and probe_result.get("platform") != "tpu":
        emit_cpu_fallback(
            f"liveness probe found platform={probe_result.get('platform')!r}, not tpu"
        )
        return
    # "alive" on TPU, or a FAST probe failure (transient init error): the
    # worker retry ladder below handles both — fast failures were retryable
    # in round 2 and WORKER_TIMEOUT_S still bounds a mid-ladder hang.
    if probe_status == "failed":
        log("probe failed fast (not the dead-tunnel hang); trying the worker ladder")
    tuned_flags = read_tuning_flags()
    for attempt in range(RETRIES):
        if attempt > 0 and time.monotonic() - t_start > TPU_DEADLINE_S:
            last_err += f"; TPU deadline {TPU_DEADLINE_S}s exceeded, skipping remaining retries"
            break
        try:
            result = run_worker(force_cpu=False, flags=tuned_flags)
        except WorkerTimeout:
            # a killed mid-compile TPU job can wedge the single-chip tunnel;
            # retrying against a possibly-wedged claim only burns timeouts —
            # go straight to the CPU fallback.
            last_err = f"tpu worker timed out after {WORKER_TIMEOUT_S}s (attempt {attempt + 1})"
            break
        if result is not None and result.get("value") is not None:
            print(json.dumps(result))
            return
        if result is not None:
            last_err = f"tpu worker error: {result.get('error', 'unknown')}"
            if not result.get("retryable", True):
                log(f"{last_err} (deterministic); skipping retries")
                break
        else:
            last_err = f"tpu worker produced no result (attempt {attempt + 1}/{RETRIES})"
        if attempt < RETRIES - 1:
            delay = BACKOFF_S[min(attempt, len(BACKOFF_S) - 1)]
            log(f"{last_err}; retrying in {delay}s")
            time.sleep(delay)

    emit_cpu_fallback(last_err)


def emit_cpu_fallback(tpu_err: str):
    log(f"TPU measurement unavailable ({tpu_err}); falling back to CPU smoke measurement")
    # the fallback artifact must never under-report what the repo knows
    # (VERDICT r3 #3): carry the newest real-TPU measurement with provenance
    last_tpu = latest_tpu_artifact()
    try:
        result = run_worker(force_cpu=True)
    except WorkerTimeout:
        result = None
    if result is not None and result.get("value") is not None:
        result["fallback_from"] = "tpu"
        result["tpu_error"] = tpu_err[:500]
        result["last_tpu"] = last_tpu
        print(json.dumps(result))
        return

    print(json.dumps({
        "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "vs_baseline_note": VS_BASELINE_NOTE,
        "platform": None,
        "error": f"{tpu_err}; cpu fallback also failed",
        "last_tpu": last_tpu,
    }))


if __name__ == "__main__":
    main()
