"""Headline benchmark: MobileNetV3-Large ImageNet training throughput,
images/sec/chip (the tracked metric, BASELINE.json:2).

Measures the full fused training step — forward, backward, RMSProp+WD update,
EMA, label-smoothed CE — in bfloat16 at 224x224 on device-resident data, so
the number is the model/step ceiling of SURVEY.md §3.1's hot loop (host input
throughput is benchmarked separately by the data pipeline).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

vs_baseline: BASELINE.json ships "published": {} (no reference numbers were
recoverable this round — see SURVEY.md provenance warning), so the divisor is
an explicit assumption recorded here: ~1000 images/sec/chip for the
reference's apex+DALI MobileNet training on its contemporary GPU (V100
class). Replace when a real reference measurement exists.
"""

from __future__ import annotations

import json
import sys
import time

ASSUMED_BASELINE_IMG_S_PER_CHIP = 1000.0


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax

    if "--cpu" in sys.argv:
        # local smoke mode: the sandbox's sitecustomize force-selects the axon
        # TPU platform regardless of JAX_PLATFORMS, so override the live config
        # (same trick as tests/conftest.py) before any backend is touched.
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib
    from yet_another_mobilenet_series_tpu.train import optim, schedules, steps

    platform = jax.default_backend()
    n_chips = len(jax.devices())
    # batch sized for one v5e-class chip; scale with the mesh. The CPU path
    # exists only as a smoke test (this sandbox has 1 core) — the recorded
    # number comes from the driver's real-TPU run. On HBM pressure the
    # fallback loop halves the batch (and finally enables activation remat).
    per_chip_batch = 256 if platform == "tpu" else 8
    image_size = 224 if platform == "tpu" else 64
    batch = per_chip_batch * n_chips
    log(f"bench: {platform} x{n_chips}, global batch {batch}, image {image_size}")

    from yet_another_mobilenet_series_tpu.config import ModelConfig

    mesh = mesh_lib.make_mesh(n_chips)
    net = get_model(ModelConfig(arch="mobilenet_v3_large", dropout=0.2), image_size)

    def build(batch, remat):
        cfg = config_from_dict({
            "model": {"arch": "mobilenet_v3_large", "dropout": 0.2},
            "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
            "schedule": {"schedule": "exp_decay", "base_lr": 0.064, "warmup_epochs": 5.0},
            "ema": {"enable": True},
            "train": {"batch_size": batch, "compute_dtype": "bfloat16", "remat": remat},
        })
        steps_per_epoch = 1281167 // batch
        lr_fn = schedules.make_lr_schedule(cfg.schedule, batch, steps_per_epoch, 350)
        params, _ = net.init(jax.random.PRNGKey(0))
        optimizer = optim.make_optimizer(cfg.optim, lr_fn, params)
        ts = steps.init_train_state(net, cfg, optimizer, jax.random.PRNGKey(0))
        ts = mesh_lib.replicate(ts, mesh)
        step_fn = dp.make_dp_train_step(net, cfg, optimizer, lr_fn, mesh)
        rng = np.random.RandomState(0)
        host_batch = {
            "image": rng.normal(0, 1, (batch, image_size, image_size, 3)).astype(np.float32),
            "label": (np.arange(batch) % 1000).astype(np.int32),
        }
        b = mesh_lib.shard_batch(host_batch, mesh)
        return step_fn, ts, b

    key = jax.random.PRNGKey(0)
    attempts = [(batch, False), (batch // 2, False), (batch // 2, True), (batch // 4, True)]
    step_fn = ts = b = None
    for try_batch, remat in attempts:
        try:
            step_fn, ts, b = build(try_batch, remat)
            t0 = time.perf_counter()
            ts, metrics = step_fn(ts, b, key)
            jax.block_until_ready(metrics["loss"])
            batch = try_batch
            log(f"batch {batch} remat={remat}: compile+first step {time.perf_counter()-t0:.1f}s")
            break
        except Exception as e:  # XlaRuntimeError RESOURCE_EXHAUSTED etc.
            if "RESOURCE_EXHAUSTED" not in str(e) and "Out of memory" not in str(e):
                raise
            log(f"batch {try_batch} remat={remat} OOM; falling back")
            # drop the failed attempt's device buffers BEFORE rebuilding, or
            # they stay pinned in HBM and the smaller attempt OOMs too
            step_fn = ts = b = None
    if step_fn is None:
        raise RuntimeError("all batch-size fallbacks exhausted")

    # warmup
    for _ in range(3):
        ts, metrics = step_fn(ts, b, key)
    jax.block_until_ready(metrics["loss"])

    iters = 20 if platform == "tpu" else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, metrics = step_fn(ts, b, key)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    img_s = batch * iters / dt
    img_s_chip = img_s / n_chips
    log(f"steady: {dt/iters*1000:.1f} ms/step, {img_s:.0f} img/s total")

    print(json.dumps({
        "metric": "mobilenet_v3_large_train_images_per_sec_per_chip",
        "value": round(img_s_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s_chip / ASSUMED_BASELINE_IMG_S_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
