// Native input pipeline: multithreaded JPEG decode + augment.
//
// This is the framework's DALI replacement (SURVEY.md §2 #6 and the native
// dependency table): the reference fed GPUs with NVIDIA DALI's C++/CUDA
// decode+augment pipeline; TPU hosts decode on CPU, so the same role is a
// C++ thread pool that JPEG-decodes (libjpeg, with fractional DCT scaling
// for cheap downscale), applies Inception-style random-resized-crop or the
// resize-shorter/center-crop eval transform, bilinear-resizes, flips, and
// normalizes straight into pinned float32 NHWC batch buffers handed to
// Python over a zero-copy ctypes API (data/native_loader.py).
//
// Threading model: workers claim individual (batch, sample) tasks from the
// oldest open batch first (work stealing WITHIN a batch — so time-to-first-
// batch scales with cores, not with batch size), decoding into per-sample
// slots of a ring of batch buffers; a batch becomes ready when all its
// samples are done. The consumer (Python) blocks in loader_next() on the
// ready queue. Deterministic per-epoch shuffling derives from (seed, epoch);
// per-sample augment RNG from (seed, batch, index) so results are
// reproducible regardless of thread interleaving or thread count.
//
// Eval exactness: with epoch_batches > 0 each pass is padded up to that many
// batches and positions past the sample list carry label -1 (masked by the
// eval step) — every example counts exactly once. Train decode failures are
// retried on deterministically-resampled indices; eval failures yield
// label -1 so a corrupt file can never count as a confident black image.

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>
#include <setjmp.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Config {
  int image_size;
  int eval_resize;
  int batch;
  int num_threads;
  int train;  // 1 = random-resized-crop + flip; 0 = resize + center crop
  uint64_t seed;
  float mean[3];
  float std[3];
  float rrc_area_min, rrc_area_max, rrc_ratio_min, rrc_ratio_max;
  // torchvision-ColorJitter-style strength (brightness/contrast/saturation
  // factors ~ U[1-s, 1+s]); 0 = off. Train only.
  float color_jitter;
  // >0: every pass serves exactly this many batches, padding positions past
  // the sample list with label -1 (exact eval counting). 0: train semantics
  // (drop remainder).
  int64_t epoch_batches;
  // Resume position: the stream starts at this GLOBAL batch index instead
  // of 0. Every batch is a pure function of its global index (epoch order
  // from (seed, epoch); per-sample augment RNG from (seed, global_batch,
  // i)), so starting the producer/consumer cursors here reproduces batch
  // start_batch, start_batch+1, ... of an uninterrupted run bit-for-bit —
  // a resumed training run continues the data order rather than replaying
  // the epoch-0 shuffle (SURVEY.md §5 checkpoint bullet; VERDICT r3 #2).
  int64_t start_batch;
  // 1: emit raw uint8 pixels (normalize moves in-step on device —
  // data.transfer_uint8, 4x less host->device volume; the float augment
  // pipeline is unchanged, workers quantize round+clip into the u8 ring).
  int transfer_uint8;
};

struct Sample {
  std::string path;
  int32_t label;
};

// --- decode ----------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decodes a JPEG file into an RGB u8 buffer. target_min > 0 picks the
// largest DCT scale_denom in {1,2,4,8} that keeps min(w,h) >= target_min —
// libjpeg then decodes at reduced resolution nearly for free (the eval
// fast path; train decodes full-res because RRC crops arbitrary regions).
bool decode_jpeg(const std::string& path, std::vector<uint8_t>* out, int* w, int* h,
                 int target_min) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  JpegErr err;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpeg_err_exit;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  int denom = 1;
  if (target_min > 0) {
    const int src_min = std::min<int>(cinfo.image_width, cinfo.image_height);
    while (denom < 8 && src_min / (denom * 2) >= target_min) denom *= 2;
  }
  cinfo.scale_num = 1;
  cinfo.scale_denom = denom;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  out->resize(size_t(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + size_t(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fclose(f);
  return true;
}

// --- resize / crop ---------------------------------------------------------

// Bilinear crop-and-resize from src (sw x sh RGB u8, crop rect) to a
// dst_size x dst_size float32 HWC tile in [0, 255], optionally mirrored.
// Jitter and normalization run as separate passes over the tile.
void crop_resize(const uint8_t* src, int sw, int sh, int cx, int cy, int cw, int ch,
                 float* dst, int dst_size, bool flip) {
  const float sx = float(cw) / dst_size;
  const float sy = float(ch) / dst_size;
  for (int y = 0; y < dst_size; ++y) {
    const float fy = cy + (y + 0.5f) * sy - 0.5f;
    const int y0 = std::clamp(int(std::floor(fy)), 0, sh - 1);
    const int y1 = std::min(y0 + 1, sh - 1);
    const float wy = fy - std::floor(fy);
    for (int x = 0; x < dst_size; ++x) {
      const float fx = cx + (x + 0.5f) * sx - 0.5f;
      const int x0 = std::clamp(int(std::floor(fx)), 0, sw - 1);
      const int x1 = std::min(x0 + 1, sw - 1);
      const float wx = fx - std::floor(fx);
      const int ox = flip ? (dst_size - 1 - x) : x;
      float* d = dst + (size_t(y) * dst_size + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        const float v00 = src[(size_t(y0) * sw + x0) * 3 + c];
        const float v01 = src[(size_t(y0) * sw + x1) * 3 + c];
        const float v10 = src[(size_t(y1) * sw + x0) * 3 + c];
        const float v11 = src[(size_t(y1) * sw + x1) * 3 + c];
        d[c] = (1 - wy) * ((1 - wx) * v00 + wx * v01) +
               wy * ((1 - wx) * v10 + wx * v11);
      }
    }
  }
}

inline float luminance(const float* px) {
  return 0.2989f * px[0] + 0.587f * px[1] + 0.114f * px[2];
}

// torchvision-ColorJitter semantics on a [0,255] tile, fixed order b->c->s:
// brightness multiplies, contrast blends with the mean of the grayscale
// image, saturation blends with the per-pixel grayscale; each op clamps to
// the valid range (matching torchvision's saturating arithmetic). The
// tf.data path implements the identical definition (data/pipeline.py
// _color_jitter) so the two loaders' augmentations agree.
void color_jitter(float* dst, int dst_size, float fb, float fc, float fs) {
  const int n = dst_size * dst_size;
  auto clamp255 = [](float v) { return std::clamp(v, 0.0f, 255.0f); };
  for (int i = 0; i < n * 3; ++i) dst[i] = clamp255(dst[i] * fb);
  double gsum = 0.0;
  for (int i = 0; i < n; ++i) gsum += luminance(dst + size_t(i) * 3);
  const float gm = float(gsum / n);
  for (int i = 0; i < n * 3; ++i) dst[i] = clamp255(gm + (dst[i] - gm) * fc);
  for (int i = 0; i < n; ++i) {
    float* px = dst + size_t(i) * 3;
    const float g = luminance(px);
    for (int c = 0; c < 3; ++c) px[c] = clamp255(g + (px[c] - g) * fs);
  }
}

void normalize(float* dst, int dst_size, const Config& cfg) {
  const int n = dst_size * dst_size;
  for (int i = 0; i < n; ++i) {
    float* px = dst + size_t(i) * 3;
    for (int c = 0; c < 3; ++c) px[c] = (px[c] / 255.0f - cfg.mean[c]) / cfg.std[c];
  }
}

// Inception-style random-resized-crop parameters (the reference's train
// augmentation; parameters surfaced in DataConfig).
void sample_rrc(std::mt19937_64& rng, int w, int h, const Config& cfg, int* cx, int* cy,
                int* cw, int* ch) {
  std::uniform_real_distribution<float> u01(0.0f, 1.0f);
  const float area = float(w) * h;
  for (int attempt = 0; attempt < 10; ++attempt) {
    const float target_area =
        area * (cfg.rrc_area_min + u01(rng) * (cfg.rrc_area_max - cfg.rrc_area_min));
    const float log_min = std::log(cfg.rrc_ratio_min);
    const float log_max = std::log(cfg.rrc_ratio_max);
    const float ratio = std::exp(log_min + u01(rng) * (log_max - log_min));
    const int tw = int(std::lround(std::sqrt(target_area * ratio)));
    const int th = int(std::lround(std::sqrt(target_area / ratio)));
    if (tw > 0 && th > 0 && tw <= w && th <= h) {
      *cx = int(u01(rng) * (w - tw + 1));
      *cy = int(u01(rng) * (h - th + 1));
      *cw = tw;
      *ch = th;
      return;
    }
  }
  // fallback: center crop of the largest valid square
  const int s = std::min(w, h);
  *cx = (w - s) / 2;
  *cy = (h - s) / 2;
  *cw = s;
  *ch = s;
}

// --- loader ----------------------------------------------------------------

struct BatchBuf {
  std::vector<float> images;    // f32 mode (host-normalized)
  std::vector<uint8_t> images8; // transfer_uint8 mode (raw pixels)
  std::vector<int32_t> labels;
  int64_t batch_index = -1;  // global batch id this buffer holds
};

// A batch whose samples are still being claimed/decoded. Workers claim the
// oldest open batch's next sample first, so all cores converge on the batch
// the consumer needs next.
struct OpenBatch {
  int slot;
  int64_t gb;
  int next_i;  // claim cursor
  int done;    // completed samples
};

struct Loader {
  Config cfg;
  std::vector<Sample> samples;
  // Immutable per-epoch shuffles, built on demand under mu and then shared
  // read-only. Workers prefetching across an epoch boundary hold different
  // epochs' orders concurrently — a single mutable vector would be a data
  // race. Old epochs are evicted once no new batch can reference them.
  std::map<int64_t, std::shared_ptr<const std::vector<uint32_t>>> orders;

  std::vector<BatchBuf> ring;
  std::map<int64_t, int> ready;     // batch index -> ring slot, consumer side
  std::queue<int> free_slots;       // ring slots available to fill
  std::vector<OpenBatch> open;      // batches mid-decode (oldest first)
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<int64_t> next_batch{0};   // producer cursor (global batch id)
  int64_t consumed = 0;                 // consumer cursor
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> decode_failures{0};

  int64_t batches_per_epoch() const {
    if (cfg.epoch_batches > 0) return cfg.epoch_batches;  // padded pass (eval)
    return int64_t(samples.size()) / cfg.batch;  // drop_remainder, like train
  }

  std::shared_ptr<const std::vector<uint32_t>> epoch_order(int64_t e) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = orders.find(e);
    if (it != orders.end()) return it->second;
    auto ord = std::make_shared<std::vector<uint32_t>>(samples.size());
    for (uint32_t i = 0; i < ord->size(); ++i) (*ord)[i] = i;
    if (cfg.train) {
      std::mt19937_64 rng(cfg.seed * 0x9E3779B97F4A7C15ULL + e);
      std::shuffle(ord->begin(), ord->end(), rng);
    }
    orders.emplace(e, ord);
    // Bound the cache. NOTE: return the local shared_ptr, NOT orders[e] —
    // when a straggler inserts an epoch older than everything cached, the
    // eviction below removes exactly that entry, and orders[e] would then
    // materialize a null pointer. An evicted epoch is simply recomputed on
    // next request (the permutation is a pure function of seed+epoch).
    while (orders.size() > 3) orders.erase(orders.begin());
    return ord;
  }

  void zero_sample(BatchBuf& buf, int i, int32_t label) {
    const size_t n = size_t(cfg.image_size) * cfg.image_size * 3;
    if (cfg.transfer_uint8) {
      // f32 mode emits NORMALIZED zeros (the mean pixel); the u8
      // equivalent is mean*255 per channel — raw zeros would device-
      // normalize to -mean/std (a black image), diverging the two modes
      // far beyond the quantization bound on decode-failed samples
      uint8_t fill[3];
      for (int c = 0; c < 3; ++c)
        fill[c] = uint8_t(std::clamp(std::lround(cfg.mean[c] * 255.0f), 0L, 255L));
      uint8_t* dst = buf.images8.data() + size_t(i) * n;
      for (size_t p = 0; p < n; ++p) dst[p] = fill[p % 3];
    } else {
      std::memset(buf.images.data() + size_t(i) * n, 0, sizeof(float) * n);
    }
    buf.labels[i] = label;
  }

  static constexpr int kDecodeAttempts = 8;

  void fill_sample(BatchBuf& buf, int64_t global_batch, int i) {
    const int64_t bpe = batches_per_epoch();
    const int64_t e = global_batch / bpe;
    const auto order_ptr = epoch_order(e);
    const std::vector<uint32_t>& order = *order_ptr;
    const int64_t pos = (global_batch % bpe) * cfg.batch + i;
    if (pos >= int64_t(order.size())) {
      // padded tail of an exact eval pass: label -1 is masked by the eval step
      zero_sample(buf, i, -1);
      return;
    }
    std::mt19937_64 rng(cfg.seed ^ (uint64_t(global_batch) << 20) ^ uint64_t(i) * 0x2545F4914F6CDD1DULL);

    // Train: a corrupt file retries on deterministically-resampled indices
    // (still reproducible across thread counts); eval keeps the file slot but
    // yields label -1 so it can never count as a confidently-labeled black
    // image. If every attempt fails the dataset is broken wholesale — emit
    // zeros with the last label and let the decode_failures counter (logged
    // by the train loop) surface it.
    const int attempts = cfg.train ? kDecodeAttempts : 1;
    std::vector<uint8_t> rgb;
    int w = 0, h = 0;
    const Sample* s = nullptr;
    bool ok = false;
    for (int a = 0; a < attempts && !ok; ++a) {
      s = &samples[order[(pos + int64_t(a) * 9973) % order.size()]];
      ok = decode_jpeg(s->path, &rgb, &w, &h, cfg.train ? 0 : cfg.eval_resize);
      if (!ok) decode_failures.fetch_add(1);
    }
    if (!ok || w <= 0 || h <= 0) {
      zero_sample(buf, i, cfg.train ? s->label : -1);
      return;
    }
    const size_t tile = size_t(cfg.image_size) * cfg.image_size * 3;
    // transfer_uint8: augment into a thread-local float tile, quantize into
    // the u8 ring at the end — the float pipeline (and its exact jitter
    // semantics) is shared verbatim between the two output modes
    thread_local std::vector<float> staging;
    float* dst;
    if (cfg.transfer_uint8) {
      staging.resize(tile);
      dst = staging.data();
    } else {
      dst = buf.images.data() + size_t(i) * tile;
    }
    if (cfg.train) {
      int cx, cy, cw, ch;
      sample_rrc(rng, w, h, cfg, &cx, &cy, &cw, &ch);
      const bool flip = std::uniform_int_distribution<int>(0, 1)(rng) == 1;
      crop_resize(rgb.data(), w, h, cx, cy, cw, ch, dst, cfg.image_size, flip);
      if (cfg.color_jitter > 0.0f) {
        std::uniform_real_distribution<float> uj(1.0f - cfg.color_jitter, 1.0f + cfg.color_jitter);
        const float fb = uj(rng), fc = uj(rng), fs = uj(rng);
        color_jitter(dst, cfg.image_size, fb, fc, fs);
      }
    } else {
      // resize shorter side to eval_resize, center-crop image_size — done in
      // one bilinear pass by cropping the source rect that maps onto the
      // final tile
      const float scale = float(cfg.eval_resize) / std::min(w, h);
      const float crop_src = cfg.image_size / scale;
      const float cx = (w - crop_src) / 2.0f;
      const float cy = (h - crop_src) / 2.0f;
      crop_resize(rgb.data(), w, h, int(std::lround(cx)), int(std::lround(cy)),
                  int(std::lround(crop_src)), int(std::lround(crop_src)), dst,
                  cfg.image_size, false);
    }
    if (cfg.transfer_uint8) {
      uint8_t* out = buf.images8.data() + size_t(i) * tile;
      for (size_t p = 0; p < tile; ++p)
        out[p] = uint8_t(std::clamp(std::lround(dst[p]), 0L, 255L));
    } else {
      normalize(dst, cfg.image_size, cfg);
    }
    buf.labels[i] = s->label;
  }

  // True when a worker has something to do: an unclaimed sample in an open
  // batch, or a free slot to open a new batch into. Call with mu held.
  bool has_task_locked() const {
    for (const auto& o : open)
      if (o.next_i < cfg.batch) return true;
    return !free_slots.empty();
  }

  void worker() {
    while (!stop.load()) {
      int slot;
      int64_t gb;
      int i;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] { return stop.load() || has_task_locked(); });
        if (stop.load()) return;
        OpenBatch* ob = nullptr;
        for (auto& o : open)
          if (o.next_i < cfg.batch) { ob = &o; break; }  // oldest first
        if (ob == nullptr) {
          const int s = free_slots.front();
          free_slots.pop();
          const int64_t g = next_batch.fetch_add(1);
          ring[s].batch_index = g;
          open.push_back(OpenBatch{s, g, 0, 0});
          ob = &open.back();
          if (cfg.batch > 1) cv_free.notify_all();  // more samples up for grabs
        }
        slot = ob->slot;
        gb = ob->gb;
        i = ob->next_i++;
      }
      fill_sample(ring[slot], gb, i);
      {
        std::lock_guard<std::mutex> lk(mu);
        for (auto it = open.begin(); it != open.end(); ++it) {
          if (it->gb == gb) {
            if (++(it->done) == cfg.batch) {
              ready.emplace(gb, slot);
              open.erase(it);
              cv_ready.notify_all();
            }
            break;
          }
        }
      }
    }
  }

  // consumer: blocks until the ring holds batch `consumed`, returns its slot
  int wait_batch() {
    std::unique_lock<std::mutex> lk(mu);
    cv_ready.wait(lk, [&] { return stop.load() || ready.count(consumed) > 0; });
    if (stop.load()) return -1;
    const int slot = ready[consumed];
    ready.erase(consumed);
    consumed++;
    return slot;
  }
};

}  // namespace

extern "C" {

void* loader_create(int image_size, int eval_resize, int batch, int num_threads,
                    int train, uint64_t seed, const float* mean, const float* std_,
                    float area_min, float area_max, float ratio_min, float ratio_max,
                    float color_jitter, int64_t epoch_batches, int64_t start_batch,
                    int transfer_uint8) {
  auto* L = new Loader();
  L->cfg = Config{image_size, eval_resize, batch, num_threads, train, seed,
                  {mean[0], mean[1], mean[2]}, {std_[0], std_[1], std_[2]},
                  area_min, area_max, ratio_min, ratio_max,
                  color_jitter, epoch_batches, start_batch, transfer_uint8};
  return L;
}

void loader_add_file(void* handle, const char* path, int32_t label) {
  auto* L = static_cast<Loader*>(handle);
  L->samples.push_back({path, label});
}

int loader_start(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  // padded (exact-eval) passes may hold ANY sample count — including zero
  // (a host whose shard is empty serves all-dummy label=-1 batches so the
  // collective eval step count still matches its peers). Streaming
  // drop-remainder passes need at least one full batch.
  if (L->cfg.epoch_batches <= 0 && int(L->samples.size()) < L->cfg.batch) return -1;
  // resume: both cursors begin at the requested global batch — workers
  // produce batches start_batch, start_batch+1, ... and the consumer waits
  // for exactly those indices
  L->next_batch.store(L->cfg.start_batch);
  L->consumed = L->cfg.start_batch;
  const int depth = std::max(2 * L->cfg.num_threads, 4);
  L->ring.resize(depth);
  for (int i = 0; i < depth; ++i) {
    const size_t n = size_t(L->cfg.batch) * L->cfg.image_size * L->cfg.image_size * 3;
    if (L->cfg.transfer_uint8) L->ring[i].images8.resize(n);
    else L->ring[i].images.resize(n);
    L->ring[i].labels.resize(L->cfg.batch);
    L->free_slots.push(i);
  }
  for (int t = 0; t < L->cfg.num_threads; ++t) {
    L->workers.emplace_back([L] { L->worker(); });
  }
  return 0;
}

// Blocks until the next in-order batch is decoded, then copies it out.
// Returns 0 on success.
int loader_next(void* handle, float* images_out, int32_t* labels_out) {
  auto* L = static_cast<Loader*>(handle);
  if (L->cfg.transfer_uint8) return -2;  // wrong mode: u8 loader, f32 copy-out
  const int slot = L->wait_batch();
  if (slot < 0) return -1;
  BatchBuf& buf = L->ring[slot];
  std::memcpy(images_out, buf.images.data(), buf.images.size() * sizeof(float));
  std::memcpy(labels_out, buf.labels.data(), buf.labels.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_slots.push(slot);
  }
  L->cv_free.notify_all();
  return 0;
}

// transfer_uint8 copy-out: raw pixels, 4x smaller than the f32 batch.
int loader_next_u8(void* handle, uint8_t* images_out, int32_t* labels_out) {
  auto* L = static_cast<Loader*>(handle);
  if (!L->cfg.transfer_uint8) return -2;  // wrong mode: f32 loader, u8 copy-out
  const int slot = L->wait_batch();
  if (slot < 0) return -1;
  BatchBuf& buf = L->ring[slot];
  std::memcpy(images_out, buf.images8.data(), buf.images8.size());
  std::memcpy(labels_out, buf.labels.data(), buf.labels.size() * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_slots.push(slot);
  }
  L->cv_free.notify_all();
  return 0;
}

int64_t loader_decode_failures(void* handle) {
  return static_cast<Loader*>(handle)->decode_failures.load();
}

int64_t loader_num_samples(void* handle) {
  return int64_t(static_cast<Loader*>(handle)->samples.size());
}

void loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"
