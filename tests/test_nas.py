"""AtomNAS machinery tests (SURVEY.md §4.1: penalty value on a toy net,
mask-prune -> rematerialize equivalence; §3.2 shrink semantics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import ModelConfig, PruneConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.models.serialize import network_from_dict, network_to_dict
from yet_another_mobilenet_series_tpu.nas import masking, penalty, rematerialize
from yet_another_mobilenet_series_tpu.utils.profiling import masked_macs, profile_network


def _supernet(num_classes=4, image_size=32):
    cfg = ModelConfig(
        arch="atomnas_supernet",
        num_classes=num_classes,
        dropout=0.0,
        block_specs=(
            {"t": 1, "c": 16, "n": 1, "s": 1, "k": [3, 5, 7]},   # non-prunable (t=1)
            {"t": 6, "c": 16, "n": 2, "s": 2, "k": [3, 5, 7]},   # residual on 2nd
            {"t": 6, "c": 24, "n": 1, "s": 2, "k": [3, 5, 7], "se": 0.25},
        ),
    )
    return get_model(cfg, image_size=image_size)


def test_prunable_blocks_excludes_t1():
    net = _supernet()
    assert masking.prunable_blocks(net) == [1, 2, 3]
    masks = masking.init_masks(net)
    assert set(masks) == {"1", "2", "3"}
    assert masks["1"].shape == (net.blocks[1].expanded_channels,)


def test_penalty_value_hand_computed():
    net = _supernet()
    pcfg = PruneConfig(enable=True, rho=2.0, normalize_cost=False)
    params, _ = net.init(jax.random.PRNGKey(0))
    costs = penalty.atom_cost_table(net, pcfg)
    pen_fn = penalty.make_penalty_fn(net, pcfg)
    masks = masking.init_masks(net)
    # kill half of block 1's atoms: they must leave the penalty
    m1 = np.asarray(masks["1"]).copy()
    m1[::2] = 0.0
    masks["1"] = jnp.asarray(m1)
    expected = 0.0
    for k, cost in costs.items():
        gamma = np.abs(np.asarray(params["blocks"][k]["dw_bn"]["gamma"]))
        m = np.asarray(masks[k])
        expected += float(np.sum(cost * gamma * m))
    got = float(pen_fn(params, masks))
    np.testing.assert_allclose(got, 2.0 * expected, rtol=1e-5)


def test_rho_ramp_and_mult_scale_penalty():
    """ramp schedule: penalty scales linearly with step over rho_ramp_epochs;
    rho_mult multiplies on top (the adaptive controller's handle)."""
    net = _supernet()
    pcfg = PruneConfig(enable=True, rho=2.0, normalize_cost=False, rho_schedule="ramp", rho_ramp_epochs=1.0)
    params, _ = net.init(jax.random.PRNGKey(0))
    masks = masking.init_masks(net)
    pen_fn = penalty.make_penalty_fn(net, pcfg, steps_per_epoch=10)
    base_fn = penalty.make_penalty_fn(net, PruneConfig(enable=True, rho=2.0, normalize_cost=False))
    full = float(base_fn(params, masks))
    assert float(pen_fn(params, masks, step=jnp.asarray(0))) == 0.0
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(5))), 0.5 * full, rtol=1e-5)
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(10))), full, rtol=1e-5)
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(999))), full, rtol=1e-5)
    got = float(pen_fn(params, masks, rho_mult=jnp.asarray(3.0), step=jnp.asarray(10)))
    np.testing.assert_allclose(got, 3.0 * full, rtol=1e-5)
    # without a step the ramp is skipped, mult still applies
    np.testing.assert_allclose(float(pen_fn(params, masks, rho_mult=jnp.asarray(0.5))), 0.5 * full, rtol=1e-5)


def test_rho_schedule_validation():
    net = _supernet()
    with pytest.raises(ValueError, match="rho_schedule"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="bogus"))
    with pytest.raises(ValueError, match="steps_per_epoch"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="ramp", rho_ramp_epochs=1.0))
    # adaptive without a target would silently never engage — reject up front
    with pytest.raises(ValueError, match="target_flops"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="adaptive"), steps_per_epoch=10)


def test_mask_update_thresholds_and_is_monotonic():
    net = _supernet()
    pcfg = PruneConfig(enable=True, gamma_threshold=0.5)
    params, _ = net.init(jax.random.PRNGKey(0))
    e1 = net.blocks[1].expanded_channels
    gamma = np.linspace(0, 1.2, e1).astype(np.float32)
    params["blocks"]["1"]["dw_bn"]["gamma"] = jnp.asarray(gamma)
    masks = masking.init_masks(net)
    update = jax.jit(masking.make_mask_update(net, pcfg))
    new = update(params, masks)
    np.testing.assert_array_equal(np.asarray(new["1"]), (np.abs(gamma) >= 0.5).astype(np.float32))
    # monotonic: resurrecting gamma doesn't resurrect the atom
    params["blocks"]["1"]["dw_bn"]["gamma"] = jnp.ones(e1)
    new2 = update(params, new)
    np.testing.assert_array_equal(np.asarray(new2["1"]), np.asarray(new["1"]))


def _random_masks(net, rng, kill_frac=0.5, kill_all_block=None, kill_branch=None):
    masks = {}
    for i in masking.prunable_blocks(net):
        b = net.blocks[i]
        m = (rng.uniform(size=b.expanded_channels) > kill_frac).astype(np.float32)
        if m.sum() == 0:
            m[0] = 1.0
        if kill_all_block == i:
            m[:] = 0.0
        if kill_branch is not None and kill_branch[0] == i:
            off = int(np.cumsum([0] + list(b.group_channels))[kill_branch[1]])
            m[off : off + b.group_channels[kill_branch[1]]] = 0.0
            if m.sum() == 0:
                m[-1] = 1.0  # keep the block itself alive via the last branch
        masks[str(i)] = jnp.asarray(m)
    return masks


def test_remat_exact_equivalence_with_branch_and_block_drop():
    """Masked supernet forward == rematerialized net forward, including a
    fully-dead residual block (dropped) and a fully-dead kernel branch."""
    net = _supernet()
    params, state = net.init(jax.random.PRNGKey(0))
    # make BN state non-trivial: one train pass
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    _, state = net.apply(params, state, x, train=True)

    rng = np.random.RandomState(0)
    masks = _random_masks(net, rng, kill_all_block=2, kill_branch=(3, 1))

    imasks = {int(k): v for k, v in masks.items()}
    y_masked, _ = net.apply(params, state, x, train=False, masks=imasks)

    new_net, new_params, new_state, new_masks, extras, report = rematerialize.rematerialize(
        net, params, state, masks
    )
    y_remat, _ = new_net.apply(new_params, new_state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_remat), rtol=1e-4, atol=1e-5)

    assert report.dropped_blocks == [2]  # residual block fully dead -> gone
    assert len(new_net.blocks) == len(net.blocks) - 1
    assert 5 in report.dropped_branches.get(3, [])  # k=5 branch killed
    # masks reset to all-ones on the new net
    assert all(float(m.min()) == 1.0 for m in new_masks.values())
    # effective macs(masked) == real macs(remat)
    np_masks = {int(k): np.asarray(v) for k, v in masks.items()}
    np.testing.assert_allclose(
        masked_macs(net, np_masks), profile_network(new_net).total_macs, rtol=1e-6
    )


@pytest.mark.slow
def test_remat_slices_optimizer_and_ema_state():
    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.train import optim, schedules, steps

    net = _supernet()
    cfg = config_from_dict({
        "model": {"num_classes": 4},
        "optim": {"optimizer": "rmsprop"},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {"compute_dtype": "float32"},
        "prune": {"enable": True},
    })
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 10)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    ts = ts.replace(masks=masking.init_masks(net))
    step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn, penalty_fn=penalty.make_penalty_fn(net, cfg.prune)))
    batch = {"image": jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3)), "label": jnp.arange(4) % 4}
    ts, _ = step_fn(ts, batch, jax.random.PRNGKey(2))

    masks = _random_masks(net, np.random.RandomState(1))
    new_net, new_params, new_state, new_masks, extras, _ = rematerialize.rematerialize(
        net, ts.params, ts.state, masks,
        opt_state=ts.opt_state, ema_params=ts.ema_params, ema_state=ts.ema_state,
    )
    # sliced optimizer state must initialize a further step without error
    new_opt = optim.make_optimizer(cfg.optim, lr_fn, new_params)
    ts2 = steps.TrainState(
        step=ts.step, params=new_params, state=new_state,
        opt_state=extras["opt_state"], ema_params=extras["ema_params"],
        ema_state=extras["ema_state"], masks=new_masks,
    )
    step2 = jax.jit(steps.make_train_step(new_net, cfg, new_opt, lr_fn, penalty_fn=penalty.make_penalty_fn(new_net, cfg.prune)))
    ts3, metrics = step2(ts2, batch, jax.random.PRNGKey(3))
    assert float(metrics["finite"]) == 1.0
    assert int(ts3.step) == 2
    # shapes really shrank
    assert profile_network(new_net).total_params < profile_network(net).total_params


def test_serialize_roundtrip_exact():
    net = _supernet()
    params, state = net.init(jax.random.PRNGKey(0))
    masks = _random_masks(net, np.random.RandomState(2))
    new_net, new_params, new_state, *_ = rematerialize.rematerialize(net, params, state, masks)
    d = network_to_dict(new_net)
    import json

    net2 = network_from_dict(json.loads(json.dumps(d)))
    assert net2 == new_net
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    y1, _ = new_net.apply(new_params, new_state, x, train=False)
    y2, _ = net2.apply(new_params, new_state, x, train=False)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_mask_summary_reports_effective_macs():
    net = _supernet()
    masks = masking.init_masks(net)
    s = masking.mask_summary(net, masks)
    assert s["alive_atoms"] == s["total_atoms"]
    np.testing.assert_allclose(s["effective_macs"], profile_network(net).total_macs)
    dead = {k: jnp.zeros_like(v) for k, v in masks.items()}
    s2 = masking.mask_summary(net, dead)
    assert s2["alive_atoms"] == 0
    assert s2["effective_macs"] < s["effective_macs"]


def test_prune_event_matches_legacy_host_semantics():
    """make_prune_event == the round-4 host-side block: reached-target gate,
    adaptive-rho feedback direction/clamp, conditional monotone mask update,
    and the (step % interval)&(step <= stop) cadence — including the no-op
    at off-cadence steps."""
    net = _supernet()
    pcfg = PruneConfig(enable=True, rho=0.1, mask_interval=2, gamma_threshold=0.1,
                       target_flops=1.0, rho_schedule="adaptive", rho_adapt_rate=0.05)
    params, _ = net.init(jax.random.PRNGKey(0))
    masks = masking.init_masks(net)
    # push two of block 1's gammas below threshold so the event has deaths
    g = np.asarray(params["blocks"]["1"]["dw_bn"]["gamma"]).copy()
    g[:2] = 0.01
    params["blocks"]["1"]["dw_bn"]["gamma"] = jnp.asarray(g)
    event = jax.jit(masking.make_prune_event(net, pcfg, stop_step=100))
    rho = jnp.ones((), jnp.float32)

    # off-cadence step: everything unchanged
    m1, r1 = event(params, masks, rho, jnp.asarray(1))
    np.testing.assert_array_equal(np.asarray(m1["1"]), np.asarray(masks["1"]))
    assert float(r1) == 1.0
    # on-cadence: deaths applied, rho pushed up (target unreachable)
    m2, r2 = event(params, masks, rho, jnp.asarray(2))
    assert float(jnp.sum(m2["1"])) == float(jnp.sum(masks["1"])) - 2
    np.testing.assert_allclose(float(r2), 1.05, rtol=1e-6)
    # past stop_step: frozen
    m3, r3 = event(params, masks, rho, jnp.asarray(102))
    np.testing.assert_array_equal(np.asarray(m3["1"]), np.asarray(masks["1"]))
    assert float(r3) == 1.0
    # reached target (huge target_flops): rho anneals, masks frozen
    pcfg_hit = PruneConfig(enable=True, rho=0.1, mask_interval=2, gamma_threshold=0.1,
                           target_flops=1e18, rho_schedule="adaptive", rho_adapt_rate=0.05)
    event_hit = jax.jit(masking.make_prune_event(net, pcfg_hit, stop_step=100))
    m4, r4 = event_hit(params, masks, rho, jnp.asarray(2))
    np.testing.assert_array_equal(np.asarray(m4["1"]), np.asarray(masks["1"]))
    np.testing.assert_allclose(float(r4), 0.95, rtol=1e-6)


def test_grouped_search_step_equals_singles():
    """VERDICT r4 next #4: k-step grouped dispatch WITH pruning active equals
    k single dispatches — masks bit-identical (threshold decisions), rho_mult
    identical, params within the grouped path's cross-step-fusion tolerance.
    The event runs host-gated after each single dispatch and in-device after
    each grouped sub-step; both share one jitted make_prune_event program."""
    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib
    from yet_another_mobilenet_series_tpu.train import optim, schedules, steps

    cfg = config_from_dict({
        "model": {"arch": "atomnas_supernet", "num_classes": 4, "dropout": 0.0,
                  "block_specs": [
                      {"t": 6, "c": 8, "n": 2, "s": 2, "k": [3, 5]},
                      {"t": 6, "c": 12, "n": 1, "s": 2, "k": [3, 5], "se": 0.25},
                  ]},
        "optim": {"optimizer": "sgd", "weight_decay": 0.0},
        "schedule": {"schedule": "constant", "base_lr": 0.05,
                     "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": False},
        "train": {"compute_dtype": "float32"},
        # normalize_cost (default) keeps the per-atom L1 gradient small —
        # with raw-MACs costs one SGD step blasts the seeded gammas far past
        # the threshold magnitude and no atom ever dies
        "prune": {"enable": True, "rho": 1e-4, "mask_interval": 2, "gamma_threshold": 0.12,
                  "target_flops": 1.0, "rho_schedule": "adaptive", "rho_adapt_rate": 0.05},
        "dist": {"sync_bn": True},
    })
    net = get_model(cfg.model, image_size=16)
    m = mesh_lib.make_mesh(8)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 16, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    pen = penalty.make_penalty_fn(net, cfg.prune)
    step = dp.make_dp_train_step(net, cfg, opt, lr_fn, m, penalty_fn=pen)
    event = jax.jit(masking.make_prune_event(net, cfg.prune, stop_step=100))

    def fresh_ts():
        ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
        # seed some gammas below threshold: deaths at events (steps 2 and 4)
        p = jax.tree.map(jnp.copy, ts.params)
        g = np.asarray(p["blocks"]["0"]["dw_bn"]["gamma"]).copy()
        g[1:4] = 0.01
        p["blocks"]["0"]["dw_bn"]["gamma"] = jnp.asarray(g)
        return mesh_lib.replicate(
            ts.replace(params=p, masks=masking.init_masks(net)), m)

    rng = jax.random.PRNGKey(9)
    batches = [
        mesh_lib.shard_batch({
            "image": np.asarray(jax.random.normal(jax.random.PRNGKey(20 + i), (16, 16, 16, 3))),
            "label": np.asarray((jnp.arange(16) + i) % 4),
        }, m)
        for i in range(4)
    ]

    ts_single = fresh_ts()
    init_alive = float(sum(np.asarray(v).sum() for v in jax.device_get(ts_single.masks).values()))
    for i, b in enumerate(batches):
        ts_single, _ = step(ts_single, b, rng)
        if (i + 1) % cfg.prune.mask_interval == 0:  # host gate, like the CLI
            masks, rho = event(ts_single.params, ts_single.masks,
                               ts_single.rho_mult, ts_single.step)
            ts_single = ts_single.replace(masks=masks, rho_mult=rho)

    grouped = dp.make_grouped_train_step(step, 2, event_fn=event)
    ts_grp = fresh_ts()
    ts_grp, _ = grouped(ts_grp, tuple(batches[:2]), rng)
    ts_grp, _ = grouped(ts_grp, tuple(batches[2:]), rng)

    ms, mg = jax.device_get(ts_single.masks), jax.device_get(ts_grp.masks)
    for k in ms:
        np.testing.assert_array_equal(np.asarray(ms[k]), np.asarray(mg[k]), err_msg=f"masks[{k}]")
    # the search actually pruned (the equality is not vacuous)
    final_alive = float(sum(np.asarray(v).sum() for v in ms.values()))
    assert final_alive < init_alive
    # adaptive rho advanced identically (2 events, never reached): 1.05^2
    np.testing.assert_allclose(float(ts_single.rho_mult), 1.05 ** 2, rtol=1e-6)
    np.testing.assert_allclose(float(ts_grp.rho_mult), float(ts_single.rho_mult), rtol=1e-7)
    for a, b in zip(jax.tree.leaves(jax.device_get(ts_single.params)),
                    jax.tree.leaves(jax.device_get(ts_grp.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)

    # epoch-TAIL composition: with 5 steps and k=2 the CLI dispatches
    # [grouped, grouped, single]; a cadence step landing on the single tail
    # (interval=5 -> event only at step 5) must still fire the event via the
    # host path (cli/train.py gates it on len(metric_list)==1, not on
    # grouping being off — the round-5 review caught the tail being dropped)
    import dataclasses as dc_

    cfg_t = dc_.replace(cfg, prune=dc_.replace(cfg.prune, mask_interval=5))
    event_t = jax.jit(masking.make_prune_event(net, cfg_t.prune, stop_step=100))
    b5 = batches + [mesh_lib.shard_batch({
        "image": np.asarray(jax.random.normal(jax.random.PRNGKey(30), (16, 16, 16, 3))),
        "label": np.asarray(jnp.arange(16) % 4)}, m)]

    ts_s = fresh_ts()
    for i, b in enumerate(b5):
        ts_s, _ = step(ts_s, b, rng)
        if (i + 1) % 5 == 0:
            masks, rho = event_t(ts_s.params, ts_s.masks, ts_s.rho_mult, ts_s.step)
            ts_s = ts_s.replace(masks=masks, rho_mult=rho)

    grouped_t = dp.make_grouped_train_step(step, 2, event_fn=event_t)
    ts_g = fresh_ts()
    ts_g, _ = grouped_t(ts_g, tuple(b5[:2]), rng)
    ts_g, _ = grouped_t(ts_g, tuple(b5[2:4]), rng)
    ts_g, _ = step(ts_g, b5[4], rng)  # the tail single dispatch...
    masks, rho = event_t(ts_g.params, ts_g.masks, ts_g.rho_mult, ts_g.step)
    ts_g = ts_g.replace(masks=masks, rho_mult=rho)  # ...takes the host path

    ms, mg = jax.device_get(ts_s.masks), jax.device_get(ts_g.masks)
    for k in ms:
        np.testing.assert_array_equal(np.asarray(ms[k]), np.asarray(mg[k]),
                                      err_msg=f"tail masks[{k}]")
    assert float(sum(np.asarray(v).sum() for v in ms.values())) < init_alive  # event fired
    np.testing.assert_allclose(float(ts_g.rho_mult), float(ts_s.rho_mult), rtol=1e-7)
