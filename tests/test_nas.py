"""AtomNAS machinery tests (SURVEY.md §4.1: penalty value on a toy net,
mask-prune -> rematerialize equivalence; §3.2 shrink semantics)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import ModelConfig, PruneConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.models.serialize import network_from_dict, network_to_dict
from yet_another_mobilenet_series_tpu.nas import masking, penalty, rematerialize
from yet_another_mobilenet_series_tpu.utils.profiling import masked_macs, profile_network


def _supernet(num_classes=4, image_size=32):
    cfg = ModelConfig(
        arch="atomnas_supernet",
        num_classes=num_classes,
        dropout=0.0,
        block_specs=(
            {"t": 1, "c": 16, "n": 1, "s": 1, "k": [3, 5, 7]},   # non-prunable (t=1)
            {"t": 6, "c": 16, "n": 2, "s": 2, "k": [3, 5, 7]},   # residual on 2nd
            {"t": 6, "c": 24, "n": 1, "s": 2, "k": [3, 5, 7], "se": 0.25},
        ),
    )
    return get_model(cfg, image_size=image_size)


def test_prunable_blocks_excludes_t1():
    net = _supernet()
    assert masking.prunable_blocks(net) == [1, 2, 3]
    masks = masking.init_masks(net)
    assert set(masks) == {"1", "2", "3"}
    assert masks["1"].shape == (net.blocks[1].expanded_channels,)


def test_penalty_value_hand_computed():
    net = _supernet()
    pcfg = PruneConfig(enable=True, rho=2.0, normalize_cost=False)
    params, _ = net.init(jax.random.PRNGKey(0))
    costs = penalty.atom_cost_table(net, pcfg)
    pen_fn = penalty.make_penalty_fn(net, pcfg)
    masks = masking.init_masks(net)
    # kill half of block 1's atoms: they must leave the penalty
    m1 = np.asarray(masks["1"]).copy()
    m1[::2] = 0.0
    masks["1"] = jnp.asarray(m1)
    expected = 0.0
    for k, cost in costs.items():
        gamma = np.abs(np.asarray(params["blocks"][k]["dw_bn"]["gamma"]))
        m = np.asarray(masks[k])
        expected += float(np.sum(cost * gamma * m))
    got = float(pen_fn(params, masks))
    np.testing.assert_allclose(got, 2.0 * expected, rtol=1e-5)


def test_rho_ramp_and_mult_scale_penalty():
    """ramp schedule: penalty scales linearly with step over rho_ramp_epochs;
    rho_mult multiplies on top (the adaptive controller's handle)."""
    net = _supernet()
    pcfg = PruneConfig(enable=True, rho=2.0, normalize_cost=False, rho_schedule="ramp", rho_ramp_epochs=1.0)
    params, _ = net.init(jax.random.PRNGKey(0))
    masks = masking.init_masks(net)
    pen_fn = penalty.make_penalty_fn(net, pcfg, steps_per_epoch=10)
    base_fn = penalty.make_penalty_fn(net, PruneConfig(enable=True, rho=2.0, normalize_cost=False))
    full = float(base_fn(params, masks))
    assert float(pen_fn(params, masks, step=jnp.asarray(0))) == 0.0
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(5))), 0.5 * full, rtol=1e-5)
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(10))), full, rtol=1e-5)
    np.testing.assert_allclose(float(pen_fn(params, masks, step=jnp.asarray(999))), full, rtol=1e-5)
    got = float(pen_fn(params, masks, rho_mult=jnp.asarray(3.0), step=jnp.asarray(10)))
    np.testing.assert_allclose(got, 3.0 * full, rtol=1e-5)
    # without a step the ramp is skipped, mult still applies
    np.testing.assert_allclose(float(pen_fn(params, masks, rho_mult=jnp.asarray(0.5))), 0.5 * full, rtol=1e-5)


def test_rho_schedule_validation():
    net = _supernet()
    with pytest.raises(ValueError, match="rho_schedule"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="bogus"))
    with pytest.raises(ValueError, match="steps_per_epoch"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="ramp", rho_ramp_epochs=1.0))
    # adaptive without a target would silently never engage — reject up front
    with pytest.raises(ValueError, match="target_flops"):
        penalty.make_penalty_fn(net, PruneConfig(enable=True, rho_schedule="adaptive"), steps_per_epoch=10)


def test_mask_update_thresholds_and_is_monotonic():
    net = _supernet()
    pcfg = PruneConfig(enable=True, gamma_threshold=0.5)
    params, _ = net.init(jax.random.PRNGKey(0))
    e1 = net.blocks[1].expanded_channels
    gamma = np.linspace(0, 1.2, e1).astype(np.float32)
    params["blocks"]["1"]["dw_bn"]["gamma"] = jnp.asarray(gamma)
    masks = masking.init_masks(net)
    update = jax.jit(masking.make_mask_update(net, pcfg))
    new = update(params, masks)
    np.testing.assert_array_equal(np.asarray(new["1"]), (np.abs(gamma) >= 0.5).astype(np.float32))
    # monotonic: resurrecting gamma doesn't resurrect the atom
    params["blocks"]["1"]["dw_bn"]["gamma"] = jnp.ones(e1)
    new2 = update(params, new)
    np.testing.assert_array_equal(np.asarray(new2["1"]), np.asarray(new["1"]))


def _random_masks(net, rng, kill_frac=0.5, kill_all_block=None, kill_branch=None):
    masks = {}
    for i in masking.prunable_blocks(net):
        b = net.blocks[i]
        m = (rng.uniform(size=b.expanded_channels) > kill_frac).astype(np.float32)
        if m.sum() == 0:
            m[0] = 1.0
        if kill_all_block == i:
            m[:] = 0.0
        if kill_branch is not None and kill_branch[0] == i:
            off = int(np.cumsum([0] + list(b.group_channels))[kill_branch[1]])
            m[off : off + b.group_channels[kill_branch[1]]] = 0.0
            if m.sum() == 0:
                m[-1] = 1.0  # keep the block itself alive via the last branch
        masks[str(i)] = jnp.asarray(m)
    return masks


def test_remat_exact_equivalence_with_branch_and_block_drop():
    """Masked supernet forward == rematerialized net forward, including a
    fully-dead residual block (dropped) and a fully-dead kernel branch."""
    net = _supernet()
    params, state = net.init(jax.random.PRNGKey(0))
    # make BN state non-trivial: one train pass
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    _, state = net.apply(params, state, x, train=True)

    rng = np.random.RandomState(0)
    masks = _random_masks(net, rng, kill_all_block=2, kill_branch=(3, 1))

    imasks = {int(k): v for k, v in masks.items()}
    y_masked, _ = net.apply(params, state, x, train=False, masks=imasks)

    new_net, new_params, new_state, new_masks, extras, report = rematerialize.rematerialize(
        net, params, state, masks
    )
    y_remat, _ = new_net.apply(new_params, new_state, x, train=False)
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_remat), rtol=1e-4, atol=1e-5)

    assert report.dropped_blocks == [2]  # residual block fully dead -> gone
    assert len(new_net.blocks) == len(net.blocks) - 1
    assert 5 in report.dropped_branches.get(3, [])  # k=5 branch killed
    # masks reset to all-ones on the new net
    assert all(float(m.min()) == 1.0 for m in new_masks.values())
    # effective macs(masked) == real macs(remat)
    np_masks = {int(k): np.asarray(v) for k, v in masks.items()}
    np.testing.assert_allclose(
        masked_macs(net, np_masks), profile_network(new_net).total_macs, rtol=1e-6
    )


@pytest.mark.slow
def test_remat_slices_optimizer_and_ema_state():
    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.train import optim, schedules, steps

    net = _supernet()
    cfg = config_from_dict({
        "model": {"num_classes": 4},
        "optim": {"optimizer": "rmsprop"},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {"compute_dtype": "float32"},
        "prune": {"enable": True},
    })
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 10)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    ts = ts.replace(masks=masking.init_masks(net))
    step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn, penalty_fn=penalty.make_penalty_fn(net, cfg.prune)))
    batch = {"image": jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3)), "label": jnp.arange(4) % 4}
    ts, _ = step_fn(ts, batch, jax.random.PRNGKey(2))

    masks = _random_masks(net, np.random.RandomState(1))
    new_net, new_params, new_state, new_masks, extras, _ = rematerialize.rematerialize(
        net, ts.params, ts.state, masks,
        opt_state=ts.opt_state, ema_params=ts.ema_params, ema_state=ts.ema_state,
    )
    # sliced optimizer state must initialize a further step without error
    new_opt = optim.make_optimizer(cfg.optim, lr_fn, new_params)
    ts2 = steps.TrainState(
        step=ts.step, params=new_params, state=new_state,
        opt_state=extras["opt_state"], ema_params=extras["ema_params"],
        ema_state=extras["ema_state"], masks=new_masks,
    )
    step2 = jax.jit(steps.make_train_step(new_net, cfg, new_opt, lr_fn, penalty_fn=penalty.make_penalty_fn(new_net, cfg.prune)))
    ts3, metrics = step2(ts2, batch, jax.random.PRNGKey(3))
    assert float(metrics["finite"]) == 1.0
    assert int(ts3.step) == 2
    # shapes really shrank
    assert profile_network(new_net).total_params < profile_network(net).total_params


def test_serialize_roundtrip_exact():
    net = _supernet()
    params, state = net.init(jax.random.PRNGKey(0))
    masks = _random_masks(net, np.random.RandomState(2))
    new_net, new_params, new_state, *_ = rematerialize.rematerialize(net, params, state, masks)
    d = network_to_dict(new_net)
    import json

    net2 = network_from_dict(json.loads(json.dumps(d)))
    assert net2 == new_net
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
    y1, _ = new_net.apply(new_params, new_state, x, train=False)
    y2, _ = net2.apply(new_params, new_state, x, train=False)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_mask_summary_reports_effective_macs():
    net = _supernet()
    masks = masking.init_masks(net)
    s = masking.mask_summary(net, masks)
    assert s["alive_atoms"] == s["total_atoms"]
    np.testing.assert_allclose(s["effective_macs"], profile_network(net).total_macs)
    dead = {k: jnp.zeros_like(v) for k, v in masks.items()}
    s2 = masking.mask_summary(net, dead)
    assert s2["alive_atoms"] == 0
    assert s2["effective_macs"] < s["effective_macs"]
