"""Measured-latency NAS cost table (nas/latency.py + scripts/latency_table.py
+ the prune.cost="latency_table" penalty mode — ROADMAP item 3) and the
checked-in LATENCY_TABLE_r01_cpu_rehearsal.json artifact contract."""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax

from yet_another_mobilenet_series_tpu.config import ModelConfig, PruneConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.nas import latency, masking, penalty

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "LATENCY_TABLE_r01_cpu_rehearsal.json")


def _latency_table_mod():
    spec = importlib.util.spec_from_file_location(
        "latency_table", os.path.join(REPO, "scripts", "latency_table.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _supernet(image_size=24):
    mc = ModelConfig(
        arch="atomnas_supernet", num_classes=4, dropout=0.0,
        block_specs=(
            {"t": 1, "c": 8, "n": 1, "s": 1, "k": [3]},        # non-prunable (t=1)
            {"t": 4, "c": 8, "n": 1, "s": 2, "k": [3, 5]},
            {"t": 4, "c": 16, "n": 1, "s": 2, "k": [3, 5]},
        ),
    )
    return get_model(mc, image_size=image_size)


@pytest.fixture(scope="module")
def tiny_table(tmp_path_factory):
    """A real measured table for the tiny supernet, built through the actual
    bench path (2 widths, 2 iters — seconds on CPU), written as an artifact
    and loaded back: the end-to-end path the pinned penalty A/B rides."""
    net = _supernet()
    mod = _latency_table_mod()
    entries = mod.build_table(net, [24], (0.5, 1.0), batch=2, iters=2)
    path = tmp_path_factory.mktemp("latbl") / "LATENCY_TABLE_test.json"
    path.write_text(json.dumps({"entries": entries}))
    return net, str(path), entries


def test_block_key_and_input_sizes():
    net = _supernet()
    sizes = latency.block_input_sizes(net, 24)
    assert len(sizes) == len(net.blocks)
    assert sizes[0] == 12  # stem stride 2 on 24
    assert sizes[2] == 6   # block 1 stride 2
    key = latency.block_key(net.blocks[1], sizes[1])
    assert key.startswith("in8_out8_e32_k3.5_s2_se0_hw12")
    # width override changes the e field only
    assert latency.block_key(net.blocks[1], sizes[1], expanded=16).split("_")[2] == "e16"


def test_table_build_load_and_atom_costs(tiny_table):
    net, path, entries = tiny_table
    # one entry per DISTINCT block signature, each with the width ladder
    assert len(entries) == len({e["key"] for e in entries}) == 3
    for e in entries:
        assert len(e["alive_channels"]) == len(e["latency_s"]) == 2
        assert all(v > 0 for v in e["latency_s"])
        assert all(f > 0 for f in e["cost_flops"])
    table = latency.LatencyTable.load(path)
    costs, total = table.atom_cost_table(net, set(masking.prunable_blocks(net)))
    assert set(costs) == set(masking.prunable_blocks(net))
    assert total > 0
    for i, c in costs.items():
        assert c.shape == (net.blocks[i].expanded_channels,)
        assert np.all(c > 0)  # the slope floor keeps every atom's cost positive
    # block_latency interpolates at full width == the measured full point
    e = entries[1]
    blk = next(b for i, b in enumerate(net.blocks)
               if latency.block_key(b, latency.block_input_sizes(net, 24)[i]) == e["key"])
    hw = int(e["key"].rsplit("hw", 1)[1])
    assert table.block_latency(blk, hw) == pytest.approx(max(
        lat for ch, lat in zip(e["alive_channels"], e["latency_s"])
        if ch == max(e["alive_channels"])))


def test_missing_block_is_a_hard_error(tiny_table):
    """A net the table was not built for must fail loudly — silently falling
    back to FLOPs would un-measure the search objective."""
    _, path, _ = tiny_table
    table = latency.LatencyTable.load(path)
    other = _supernet(image_size=32)  # different input resolutions -> new keys
    with pytest.raises(KeyError, match="no latency measurement"):
        table.atom_cost_table(other)


def test_table_validation_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"entries": []}))
    with pytest.raises(ValueError, match="no entries"):
        latency.LatencyTable.load(str(bad))
    bad.write_text(json.dumps({"entries": [
        {"key": "k", "alive_channels": [4], "latency_s": [1e-3]}]}))
    with pytest.raises(ValueError, match=">=2"):
        latency.LatencyTable.load(str(bad))
    bad.write_text(json.dumps({"entries": [
        {"key": "k", "alive_channels": [4, 8], "latency_s": [1e-3, 0.0]}]}))
    with pytest.raises(ValueError, match="non-positive"):
        latency.LatencyTable.load(str(bad))


def test_penalty_latency_mode_differs_from_flops_pinned(tiny_table):
    """THE pinned acceptance: prune.cost='latency_table' produces a
    different (measured-cost) penalty vector than FLOPs mode — and a working
    penalty_fn — while the flag-gated default stays bit-identical to the
    FLOPs path."""
    net, path, _ = tiny_table
    flops_cfg = PruneConfig(enable=True, rho=1.0)
    lat_cfg = PruneConfig(enable=True, rho=1.0, cost="latency_table", latency_table=path)
    flops_costs = penalty.atom_cost_table(net, flops_cfg)
    lat_costs = penalty.atom_cost_table(net, lat_cfg)
    assert set(flops_costs) == set(lat_costs)
    # both normalized (resolution-independent rho), so the vectors are
    # comparable — and MEASURABLY different: measured latency is not a
    # rescaled copy of analytic MACs (the whole point, PAPERS.md FLASH/LANA)
    diffs = [
        np.max(np.abs(lat_costs[k] - flops_costs[k])) / np.max(flops_costs[k])
        for k in flops_costs
    ]
    assert max(diffs) > 0.01, f"latency costs indistinguishable from FLOPs: {diffs}"
    # the penalty fn builds and evaluates finite in table mode
    params, _ = net.init(jax.random.PRNGKey(0))
    masks = masking.init_masks(net)
    pen = penalty.make_penalty_fn(net, lat_cfg)(params, masks)
    assert np.isfinite(float(pen)) and float(pen) > 0
    # default config never touches the table path
    assert PruneConfig().cost == "flops"


def test_penalty_cost_mode_validation():
    net = _supernet()
    with pytest.raises(ValueError, match="prune.latency_table"):
        penalty.atom_cost_table(net, PruneConfig(enable=True, cost="latency_table"))
    with pytest.raises(ValueError, match="unknown prune.cost"):
        penalty.atom_cost_table(net, PruneConfig(enable=True, cost="bogus"))


def test_checked_in_rehearsal_artifact_contract():
    """LATENCY_TABLE_r01_cpu_rehearsal.json: bench-contract shape, stamped
    provenance, a full mobilenet_v3_large block set with positive measured
    ladders, and loadable by the consumer API."""
    with open(ARTIFACT) as f:
        doc = json.load(f)
    assert doc["metric"] == "mobilenet_v3_large_block_latency_table"
    assert "error" not in doc
    assert doc["value"] == len(doc["entries"]) >= 10
    prov = doc["provenance"]
    assert prov["jax_version"] and prov["jaxlib_version"] and prov["python"]
    assert prov["platform"] == "cpu" and prov["cpu_rehearsal"] is True
    assert len(doc["widths"]) >= 2
    for e in doc["entries"]:
        assert len(e["alive_channels"]) == len(e["latency_s"]) == len(doc["widths"])
        assert all(v > 0 for v in e["latency_s"])
        assert e["alive_channels"] == sorted(e["alive_channels"])
    table = latency.LatencyTable.load(ARTIFACT)
    net = get_model(ModelConfig(arch="mobilenet_v3_large"), 224)
    costs, total = table.atom_cost_table(net, set(masking.prunable_blocks(net)))
    assert total > 0 and all(np.all(c > 0) for c in costs.values())
    # the searched objective is buildable straight off the checked-in table
    cfg = PruneConfig(enable=True, cost="latency_table", latency_table=ARTIFACT)
    assert set(penalty.atom_cost_table(net, cfg)) == set(
        str(i) for i in masking.prunable_blocks(net))
