"""ZeRO sharded-weight-update tests (PAPERS.md:5): equivalence with the
replicated update, true sharding of accumulators, ragged leaf handling."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib, zero
from yet_another_mobilenet_series_tpu.train import optim, schedules, steps


def _cfg(shard_opt: bool):
    return config_from_dict({
        "model": {
            "arch": "mobilenet_v2",
            "num_classes": 5,  # odd sizes: exercises ragged chunk padding
            "dropout": 0.0,
            "block_specs": [
                {"t": 3, "c": 12, "n": 1, "s": 2, "k": 3},
                {"t": 3, "c": 20, "n": 1, "s": 2, "k": [3, 5], "se": 0.25},
            ],
        },
        "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.02, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.99, "warmup": False},
        "train": {"compute_dtype": "float32"},
        "dist": {"shard_optimizer": shard_opt},
    })


@pytest.fixture()
def setup():
    cfg_rep = _cfg(False)
    net = get_model(cfg_rep.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg_rep.schedule, 16, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg_rep.optim, lr_fn, params)
    mesh = mesh_lib.make_mesh(8)
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (16, 16, 16, 3)),
        "label": jnp.arange(16) % 5,
    }
    return net, lr_fn, opt, mesh, batch


def _zero_state(net, cfg, opt, mesh):
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0), with_opt=False)
    ts = mesh_lib.replicate(ts, mesh)
    return ts.replace(opt_state=zero.init_opt_state(opt, ts.params, mesh))


@pytest.mark.slow
@pytest.mark.parametrize("bn_mode", ["exact", "fused_vjp"])
def test_zero_step_matches_replicated_update(setup, bn_mode):
    """ZeRO sharded update == replicated exact-mode step. The fused_vjp arm
    is the acceptance-#5 composition and pins that the custom backward's
    LOCAL dgamma/dbeta partials feed the psum_scatter correctly (a psum'd
    custom backward would double-count by the mesh size); its tolerances
    are looser since it also crosses BN formulations."""
    import dataclasses as dc

    net, lr_fn, opt, mesh, batch = setup
    b = mesh_lib.shard_batch(batch, mesh)

    ts_rep = mesh_lib.replicate(steps.init_train_state(net, _cfg(False), opt, jax.random.PRNGKey(0)), mesh)
    rep_step = dp.make_dp_train_step(net, _cfg(False), opt, lr_fn, mesh)
    ts_rep, met_rep = rep_step(ts_rep, b, jax.random.PRNGKey(7))

    cfg_z = _cfg(True)
    cfg_z = dc.replace(cfg_z, train=dc.replace(cfg_z.train, bn_mode=bn_mode))
    ts_z = _zero_state(net, cfg_z, opt, mesh)
    z_step = dp.make_dp_train_step(net, cfg_z, opt, lr_fn, mesh)
    ts_z, met_z = z_step(ts_z, b, jax.random.PRNGKey(7))

    same_bn = bn_mode == "exact"
    np.testing.assert_allclose(float(met_rep["loss"]), float(met_z["loss"]), rtol=1e-6 if same_bn else 1e-5)
    np.testing.assert_allclose(float(met_rep["grad_norm"]), float(met_z["grad_norm"]), rtol=1e-4)
    p_rtol, p_atol = (1e-4, 1e-6) if same_bn else (1e-3, 1e-5)
    for a, c in zip(jax.tree.leaves(ts_rep.params), jax.tree.leaves(ts_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=p_rtol, atol=p_atol)


def test_zero_opt_state_is_sharded(setup):
    net, lr_fn, opt, mesh, batch = setup
    ts_z = _zero_state(net, _cfg(True), opt, mesh)
    leaves = [l for l in jax.tree.leaves(ts_z.opt_state) if hasattr(l, "sharding") and l.ndim >= 1]
    assert leaves
    for l in leaves:
        assert l.sharding.spec == P("data"), (l.shape, l.sharding)
        assert l.shape[0] % 8 == 0  # n * chunk flat layout
    # accumulator memory per device is ~1/8 of the replicated layout
    per_dev = leaves[0].shape[0] // 8
    assert leaves[0].addressable_shards[0].data.shape == (per_dev,)


@pytest.mark.slow
def test_zero_multi_step_stays_in_sync_and_finite(setup):
    net, lr_fn, opt, mesh, batch = setup
    cfg = _cfg(True)
    b = mesh_lib.shard_batch(batch, mesh)
    ts = _zero_state(net, cfg, opt, mesh)
    z_step = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh)
    check = dp.make_replica_sync_check(mesh)
    for _ in range(4):
        ts, met = z_step(ts, b, jax.random.PRNGKey(3))
    assert float(met["finite"]) == 1.0
    assert float(check(ts.params)) == 0.0
    assert int(ts.step) == 4


@pytest.mark.slow
def test_zero_gather_scatter_roundtrip_and_portability(setup):
    """gather -> scatter is lossless, and the gathered (checkpoint) form can
    be scattered onto a DIFFERENT chip count (8-chip save -> 4-chip resume)."""
    net, lr_fn, opt, mesh, batch = setup
    cfg = _cfg(True)
    b = mesh_lib.shard_batch(batch, mesh)
    ts = _zero_state(net, cfg, opt, mesh)
    z_step = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh)
    ts, _ = z_step(ts, b, jax.random.PRNGKey(1))  # non-trivial accumulators

    gathered = jax.jit(zero.gather_opt_state)(ts.opt_state, ts.params)
    # gathered form is params-shaped: structures match leaf-for-leaf
    rms_like = [l for l in jax.tree.leaves(gathered)]
    assert any(l.ndim == 4 for l in rms_like)  # conv-kernel-shaped accumulators

    # roundtrip is lossless on the REAL entries (padding lanes restart at 0,
    # which is unobservable: pad grads are always 0 and pad params stay 0)
    back = zero.scatter_opt_state(jax.device_get(gathered), ts.params, mesh)
    gathered2 = jax.jit(zero.gather_opt_state)(back, ts.params)
    for a, c in zip(jax.tree.leaves(gathered), jax.tree.leaves(gathered2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))

    # different mesh size: 4 chips
    mesh4 = mesh_lib.make_mesh(4)
    opt4 = zero.scatter_opt_state(jax.device_get(gathered), mesh_lib.replicate(jax.device_get(ts.params), mesh4), mesh4)
    ts4 = steps.TrainState(
        step=mesh_lib.replicate(jax.device_get(ts.step), mesh4),
        params=mesh_lib.replicate(jax.device_get(ts.params), mesh4),
        state=mesh_lib.replicate(jax.device_get(ts.state), mesh4),
        opt_state=opt4,
        ema_params=mesh_lib.replicate(jax.device_get(ts.ema_params), mesh4),
        ema_state=mesh_lib.replicate(jax.device_get(ts.ema_state), mesh4),
        masks={},
    )
    z_step4 = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh4)
    b4 = mesh_lib.shard_batch(batch, mesh4)
    ts4, met4 = z_step4(ts4, b4, jax.random.PRNGKey(2))
    assert float(met4["finite"]) == 1.0


@pytest.mark.slow
@pytest.mark.parametrize("n", [2, 3, 6])
def test_zero_matches_replicated_at_awkward_mesh(setup, n):
    """VERDICT r2 weak #6: the (n*chunk,) flat layout's ragged padding paths
    at non-power-of-two mesh sizes — step-vs-replicated equivalence and the
    gather/scatter round-trip at mesh sizes where many leaves have
    total % n != 0."""
    net, lr_fn, opt, _, _ = setup
    mesh = mesh_lib.make_mesh(n)
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (4 * n, 16, 16, 3)),
        "label": jnp.arange(4 * n) % 5,
    }
    b = mesh_lib.shard_batch(batch, mesh)

    ts_rep = mesh_lib.replicate(steps.init_train_state(net, _cfg(False), opt, jax.random.PRNGKey(0)), mesh)
    ts_rep, met_rep = dp.make_dp_train_step(net, _cfg(False), opt, lr_fn, mesh)(ts_rep, b, jax.random.PRNGKey(7))
    ts_z = _zero_state(net, _cfg(True), opt, mesh)
    ts_z, met_z = dp.make_dp_train_step(net, _cfg(True), opt, lr_fn, mesh)(ts_z, b, jax.random.PRNGKey(7))

    # ragged chunks genuinely occur at these sizes (else the test is vacuous)
    assert any(l.size % n for l in jax.tree.leaves(ts_z.params))
    np.testing.assert_allclose(float(met_rep["loss"]), float(met_z["loss"]), rtol=1e-6)
    np.testing.assert_allclose(float(met_rep["grad_norm"]), float(met_z["grad_norm"]), rtol=1e-4)
    for a, c in zip(jax.tree.leaves(ts_rep.params), jax.tree.leaves(ts_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6)

    gathered = jax.jit(zero.gather_opt_state)(ts_z.opt_state, ts_z.params)
    back = zero.scatter_opt_state(jax.device_get(gathered), ts_z.params, mesh)
    gathered2 = jax.jit(zero.gather_opt_state)(back, ts_z.params)
    for a, c in zip(jax.tree.leaves(gathered), jax.tree.leaves(gathered2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


@pytest.mark.slow
def test_zero_resume_chain_8_4_8_matches_constant_mesh(setup):
    """A ZeRO run that checkpoints on 8 chips, resumes on 4, then returns to
    8 must track a run that never left the 8-chip mesh (the chip-count
    portability contract of the gathered checkpoint form, zero.py)."""
    net, lr_fn, opt, mesh8, batch = setup
    cfg = _cfg(True)
    b8 = mesh_lib.shard_batch(batch, mesh8)
    step8 = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh8)

    ts_ref = _zero_state(net, cfg, opt, mesh8)
    for _ in range(3):
        ts_ref, _ = step8(ts_ref, b8, jax.random.PRNGKey(9))

    def move(ts, mesh_to):
        # the checkpoint path in miniature: gather to the params-shaped host
        # form, then scatter onto the destination mesh. Field set comes from
        # TRAIN_STATE_FIELDS (via train_state_to_dict) so a future TrainState
        # field rides the chain instead of being silently reset.
        gathered = jax.device_get(jax.jit(zero.gather_opt_state)(ts.opt_state, ts.params))
        host = jax.device_get(steps.train_state_to_dict(ts))
        kwargs = {k: mesh_lib.replicate(v, mesh_to) for k, v in host.items() if k != "opt_state"}
        kwargs["opt_state"] = zero.scatter_opt_state(gathered, kwargs["params"], mesh_to)
        return steps.TrainState(**kwargs)

    mesh4 = mesh_lib.make_mesh(4)
    b4 = mesh_lib.shard_batch(batch, mesh4)
    ts = _zero_state(net, cfg, opt, mesh8)
    ts, _ = step8(ts, b8, jax.random.PRNGKey(9))
    ts = move(ts, mesh4)
    ts, _ = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh4)(ts, b4, jax.random.PRNGKey(9))
    ts = move(ts, mesh8)
    ts, met = step8(ts, b8, jax.random.PRNGKey(9))

    assert float(met["finite"]) == 1.0
    assert int(ts.step) == 3
    for a, c in zip(jax.tree.leaves(ts_ref.params), jax.tree.leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zero_grad_clip_matches_replicated(setup):
    """Grad clipping under the sharded update: the psum-aware clip stage
    (optim.clip_by_global_norm(psum_axis=...)) must reproduce the replicated
    path's clipped update exactly, with a clip small enough to engage."""
    import dataclasses as dc

    net, lr_fn, _, mesh, batch = setup
    params, _ = net.init(jax.random.PRNGKey(0))
    cfg_rep, cfg_z = _cfg(False), _cfg(True)
    ocfg = dc.replace(cfg_rep.optim, grad_clip_norm=0.05)
    cfg_rep = dc.replace(cfg_rep, optim=ocfg)
    cfg_z = dc.replace(cfg_z, optim=ocfg)
    opt_rep = optim.make_optimizer(ocfg, lr_fn, params)
    opt_z = optim.make_optimizer(ocfg, lr_fn, params, shard_axis=mesh_lib.DATA_AXIS)
    b = mesh_lib.shard_batch(batch, mesh)

    ts_rep = mesh_lib.replicate(steps.init_train_state(net, cfg_rep, opt_rep, jax.random.PRNGKey(0)), mesh)
    ts_rep, met_rep = dp.make_dp_train_step(net, cfg_rep, opt_rep, lr_fn, mesh)(ts_rep, b, jax.random.PRNGKey(7))
    ts_z = _zero_state(net, cfg_z, opt_z, mesh)
    ts_z, met_z = dp.make_dp_train_step(net, cfg_z, opt_z, lr_fn, mesh, clip_shard_aware=True)(
        ts_z, b, jax.random.PRNGKey(7)
    )

    # an optimizer NOT attested as shard-aware must be rejected loudly — a
    # plain clip would silently clip each shard by its local norm
    with pytest.raises(ValueError, match="shard_axis"):
        dp.make_dp_train_step(net, cfg_z, opt_rep, lr_fn, mesh)

    # the clip must have engaged (reported grad_norm is pre-clip)
    assert float(met_rep["grad_norm"]) > 0.05
    np.testing.assert_allclose(float(met_rep["grad_norm"]), float(met_z["grad_norm"]), rtol=1e-4)
    for a, c in zip(jax.tree.leaves(ts_rep.params), jax.tree.leaves(ts_z.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_zero_grouped_dispatch_matches_single_steps(setup):
    """steps_per_dispatch composes with ZeRO: k steps in one jit dispatch
    over the sharded-optimizer step equal k single dispatches (same data,
    same per-step rng fold) within cross-step-fusion rounding — the grouped
    program (k UNROLLED step graphs, dp.make_grouped_train_step) must
    thread the flat-sharded opt_state through consecutive psum_scatter
    updates AND leave it sharded on output, not just the replicated path
    test_parallel pins."""
    net, lr_fn, opt, mesh, batch = setup
    cfg = _cfg(True)
    rng = jax.random.PRNGKey(9)
    step = dp.make_dp_train_step(net, cfg, opt, lr_fn, mesh)
    batches = [
        mesh_lib.shard_batch({
            "image": np.asarray(jax.random.normal(jax.random.PRNGKey(20 + i), (16, 16, 16, 3))),
            "label": np.asarray((jnp.arange(16) + i) % 5),
        }, mesh)
        for i in range(4)
    ]

    ts_single = _zero_state(net, cfg, opt, mesh)
    for b in batches:
        ts_single, met_s = step(ts_single, b, rng)

    grouped = dp.make_grouped_train_step(step, 2)
    ts_grp = _zero_state(net, cfg, opt, mesh)
    ts_grp, mets = grouped(ts_grp, tuple(batches[:2]), rng)
    ts_grp, mets = grouped(ts_grp, tuple(batches[2:]), rng)

    assert int(ts_grp.step) == 4
    # the grouped jit must not silently gather/replicate the ZeRO shards on
    # output — that would keep numerics while defeating the memory saving
    opt_leaves = [l for l in jax.tree.leaves(ts_grp.opt_state)
                  if hasattr(l, "sharding") and l.ndim >= 1]
    assert opt_leaves
    for l in opt_leaves:
        assert l.sharding.spec == P("data"), (l.shape, l.sharding)
    for a, b2 in zip(jax.tree.leaves(jax.device_get(ts_single.params)),
                     jax.tree.leaves(jax.device_get(ts_grp.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2), rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(float(met_s["loss"]), float(mets[-1]["loss"]), rtol=1e-5)
