"""Data-pipeline fault tolerance (data/pipeline.py resilient_batches +
PrefetchWorker, train/faults.py injector): corrupt records cost one skipped
batch each — counted, bounded — and a crashed prefetch worker restarts a
bounded number of times, then surfaces the real error to the consumer.
"""

import itertools
import os
import time

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import DataConfig, TrainFaultsConfig
from yet_another_mobilenet_series_tpu.data import make_train_source
from yet_another_mobilenet_series_tpu.data.pipeline import (
    CorruptRecordError,
    DataPipelineError,
    PrefetchWorker,
    resilient_batches,
)
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.train.faults import FaultyTrainSource


def _corrupt_counter():
    return get_registry().snapshot().get("data.corrupt_records", 0.0)


# ---------------------------------------------------------------------------
# resilient_batches
# ---------------------------------------------------------------------------


def _gen_with_recovery(plan):
    """A generator dies permanently on raise (PEP 479 semantics would end the
    stream), so model the tf.data behavior — error on one next(), subsequent
    next() keeps serving — with an explicit iterator."""

    class It:
        def __init__(self):
            self._items = list(plan)

        def __iter__(self):
            return self

        def __next__(self):
            if not self._items:
                raise StopIteration
            item = self._items.pop(0)
            if item == "X":
                raise CorruptRecordError("synthetic corrupt record")
            if isinstance(item, Exception):
                raise item
            return {"label": item}

    return It()


def test_resilient_batches_skips_and_counts():
    before = _corrupt_counter()
    it = resilient_batches(_gen_with_recovery([1, "X", 2, "X", "X", 3]), max_consecutive=4)
    assert [b["label"] for b in it] == [1, 2, 3]
    assert _corrupt_counter() == before + 3


def test_resilient_batches_bounded_consecutive_abort():
    it = resilient_batches(_gen_with_recovery([1] + ["X"] * 5 + [2]), max_consecutive=3)
    assert next(it)["label"] == 1
    with pytest.raises(DataPipelineError, match="3 consecutive"):
        next(it)


def test_resilient_batches_propagates_non_record_errors():
    boom = RuntimeError("not a data problem")
    it = resilient_batches(_gen_with_recovery([1, boom]), max_consecutive=3)
    assert next(it)["label"] == 1
    with pytest.raises(RuntimeError, match="not a data problem"):
        next(it)


# ---------------------------------------------------------------------------
# PrefetchWorker
# ---------------------------------------------------------------------------


def test_prefetch_worker_preserves_order_and_drains():
    w = PrefetchWorker(iter({"label": i} for i in range(7)), depth=3)
    assert [b["label"] for b in w] == list(range(7))
    w.close()


def test_prefetch_worker_restarts_crashed_worker_bounded():
    """Two transient crashes inside the restart budget: the stream continues
    (counted); a third surfaces the error to the consumer."""

    class Flaky:
        def __init__(self, crash_times):
            self._n = 0
            self._crashes = crash_times

        def __iter__(self):
            return self

        def __next__(self):
            self._n += 1
            if self._n in self._crashes:
                raise RuntimeError(f"transient crash #{self._n}")
            if self._n > 8:
                raise StopIteration
            return {"label": self._n}

    snap = get_registry().snapshot()
    crashes0 = snap.get("data.worker_crashes", 0.0)
    restarts0 = snap.get("data.worker_restarts", 0.0)
    w = PrefetchWorker(Flaky({3, 5}), depth=2, max_restarts=3)
    assert [b["label"] for b in w] == [1, 2, 4, 6, 7, 8]
    snap = get_registry().snapshot()
    assert snap["data.worker_crashes"] == crashes0 + 2
    assert snap["data.worker_restarts"] == restarts0 + 2
    w.close()

    # budget exhausted: the real error reaches the consumer, not a hang
    w2 = PrefetchWorker(Flaky({1, 2, 3, 4, 5, 6, 7, 8, 9, 10}), depth=2, max_restarts=2)
    with pytest.raises(RuntimeError, match="transient crash"):
        list(w2)
    w2.close()


# ---------------------------------------------------------------------------
# train/faults.py injector
# ---------------------------------------------------------------------------


def _batches():
    i = 0
    while True:
        yield {"image": np.zeros((2, 4, 4, 3), np.float32), "label": np.full((2,), i, np.int32)}
        i += 1


def test_faulty_source_corrupt_schedule_is_seeded():
    def draws(seed):
        src = FaultyTrainSource(_batches(), seed=seed, corrupt_record_rate=0.5)
        out = []
        for _ in range(30):
            try:
                next(src)
                out.append(0)
            except CorruptRecordError:
                out.append(1)
        return out

    a, b = draws(3), draws(3)
    assert a == b and sum(a) > 0  # deterministic, and the rate actually fires
    assert draws(4) != a  # a different seed is a different schedule


def test_faulty_source_nan_and_stall_at_step():
    t0 = time.perf_counter()
    src = FaultyTrainSource(_batches(), nan_at_steps=(2,), stall_at_step=1, stall_ms=80.0)
    got = list(itertools.islice(src, 4))
    assert time.perf_counter() - t0 >= 0.08  # the stall really slept
    assert not np.isnan(got[0]["image"]).any() and not np.isnan(got[1]["image"]).any()
    assert np.isnan(got[2]["image"][0]).all() and not np.isnan(got[2]["image"][1:]).any()
    assert not np.isnan(got[3]["image"]).any()
    snap = get_registry().snapshot()
    assert snap["train.faults.nan_steps"] >= 1 and snap["train.faults.stalls"] >= 1


def test_faulty_source_start_step_offsets_schedule():
    src = FaultyTrainSource(_batches(), nan_at_steps=(12,), start_step=10)
    got = list(itertools.islice(src, 4))  # serves steps 10..13
    assert np.isnan(got[2]["image"][0]).all()  # step 12
    assert not any(np.isnan(g["image"]).any() for g in (got[0], got[1], got[3]))


def test_from_config_identity_when_disabled():
    it = _batches()
    assert FaultyTrainSource.from_config(it, TrainFaultsConfig()) is it


# ---------------------------------------------------------------------------
# end-to-end through make_train_source: injected corruption under the real
# resilience stack (+ the fake/tfdata pipeline), prefetch thread on
# ---------------------------------------------------------------------------


def test_make_train_source_survives_injected_corruption():
    cfg = DataConfig(dataset="fake", loader="tfdata", image_size=8,
                     fake_train_size=32, fake_num_classes=4, prefetch_thread=True)
    before = _corrupt_counter()
    src = make_train_source(
        cfg, local_batch=4, seed=7,
        inject=lambda it: FaultyTrainSource(it, seed=11, corrupt_record_rate=0.3),
    )
    got = list(itertools.islice(src, 10))
    assert len(got) == 10 and all(b["label"].shape == (4,) for b in got)
    assert _corrupt_counter() > before  # corrupt pulls were skipped AND counted
    # the surviving stream is the clean stream with corrupt pulls elided:
    # same batches, same order (injection raises BEFORE consuming a batch)
    clean = list(itertools.islice(make_train_source(cfg, local_batch=4, seed=7), 10))
    for a, b in zip(got, clean):
        np.testing.assert_array_equal(a["label"], b["label"])


def test_tfdata_corrupt_jpeg_is_skipped_and_counted(tmp_path):
    """A genuinely rotten JPEG inside a TFRecord: the tf.data iterator errors
    on the batch the record lands in and keeps serving; the resilience
    wrapper skips + counts. (The native C++ loader skips corrupt records
    internally and counts data.decode_failures — tests/test_native_loader.)"""
    tf = pytest.importorskip("tensorflow")
    PIL = pytest.importorskip("PIL")  # noqa: F841 — fixture JPEGs
    import io

    from PIL import Image

    os.makedirs(tmp_path / "rec")
    rs = np.random.RandomState(0)
    path = str(tmp_path / "rec" / "train-00000-of-00001")
    with tf.io.TFRecordWriter(path) as w:
        for i in range(8):
            if i == 3:
                payload = b"definitely not a jpeg"
            else:
                buf = io.BytesIO()
                Image.fromarray(rs.randint(0, 255, (16, 16, 3), np.uint8)).save(
                    buf, format="JPEG", quality=95)
                payload = buf.getvalue()
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[payload])),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[i + 1])),
            }))
            w.write(ex.SerializeToString())

    cfg = DataConfig(dataset="imagenet", loader="tfdata", data_dir=str(tmp_path / "rec"),
                     image_size=8, num_train_examples=8,
                     decode_threads=1, shuffle_buffer=1)
    before = _corrupt_counter()
    src = make_train_source(cfg, local_batch=2, seed=1)
    got = list(itertools.islice(src, 6))
    # the stream SURVIVED the rotten record (6 batches over an 8-record
    # epoch crosses it at least once) and the loss was counted
    assert len(got) == 6 and all(b["image"].shape == (2, 8, 8, 3) for b in got)
    assert _corrupt_counter() > before
