"""End-to-end CPU rehearsal of the watcher's unattended session (VERDICT r4
next #1): run_session had only ever been exercised piecewise — its first real
execution must not double as its integration test. This drives the REAL
chain (bench_bn A/B → decision → headline bench.py → trace capture+decode)
through `tpu_watch.py --cpu-rehearsal` as actual subprocesses against the
CPU backend, scoped to one A/B variant to fit the slow suite. The sweep
stage is exercised by the committed full-size rehearsal artifacts and the
decide_sweep unit tests (test_tpu_watch.py).
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cpu_rehearsal_session_chain(tmp_path):
    tuning = os.path.join(REPO, "BENCH_TUNING.json")
    tuning_before = open(tuning).read() if os.path.exists(tuning) else None

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # the rehearsal forces CPU itself (bench children via --cpu, the trace
    # child via env); the watcher process makes no backend touch. Drop the
    # pytest conftest's 8-fake-device XLA_FLAGS so children run the bench's
    # own single-device CPU config.
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["TPU_WATCH_ARTIFACT_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tpu_watch.py"),
         "--round", "99", "--cpu-rehearsal", "--variants", "exact:0,folded:0"],
        capture_output=True, text=True, timeout=1500, cwd=REPO, env=env)
    assert r.returncode == 0, f"rehearsal failed:\n{r.stderr[-4000:]}"

    ab = json.load(open(tmp_path / "BENCH_BN_r99_cpu_rehearsal.json"))
    assert ab["platform"] == "cpu" and ab["partial"] is False
    modes = {row["bn_mode"] for row in ab["rows"] if "bn_mode" in row}
    assert {"exact", "folded"} <= modes
    # the dispatch probe ran inside the A/B (chained vs lax.scan timing)
    assert any("dispatch_tax_ms" in row for row in ab["rows"])

    dec = json.load(open(tmp_path / "BENCH_DECISION_r99_cpu_rehearsal.json"))
    assert dec["baseline"] is not None  # rule anchored on the exact row

    head = json.load(open(tmp_path / "BENCH_TPU_r99_cpu_rehearsal.json"))
    assert head["platform"] == "cpu" and head["value"] > 0
    assert head["metric"] == "mobilenet_v3_large_train_images_per_sec_per_chip"

    # trace stage: captured through the REAL cli.train profiler window and
    # decoded by trace_ops.py. A CPU trace has no /device:TPU plane, so the
    # decoder's explicit no-TPU-plane diagnostic is the CORRECT output here —
    # the stage proves capture + decode + artifact plumbing, not TPU op math
    trace_txt = tmp_path / "TRACE_OPS_r99_cpu_rehearsal.txt"
    assert trace_txt.exists(), f"trace stage produced no artifact:\n{r.stderr[-4000:]}"
    body = trace_txt.read_text()
    assert "no /device:TPU plane" in body or "-- by op kind" in body

    # the production tuning file was never touched
    tuning_after = open(tuning).read() if os.path.exists(tuning) else None
    assert tuning_after == tuning_before
