"""Tier-1 gate: the package must lint clean under its own analyzer.

This is the enforcement half of the yamt-lint tentpole: every invariant the
rules encode (no host effects under trace — now followed through resolved
calls, PRNG discipline including cross-call key flow, real mesh axes,
TRAIN_STATE_FIELDS/TrainState agreement, apps/*.yml vs config.py schema,
version-resilient jax imports, donation discipline through attribute calls,
recompilation hazards at static positions — docs/LINT.md) is checked on
every PR by this pure-AST test. A finding here is a real hazard or an
undocumented suppression — fix the code, don't widen the gate.

The perf guard pins the gate's reason to exist: with the full
interprocedural layer (symbol table + call graph + summary fixpoint) a
whole-package run must stay effectively free, or people stop running it.
"""

import pathlib
import subprocess
import sys

from yet_another_mobilenet_series_tpu.analysis import check_suppressions, load_rules, run_lint

PACKAGE = pathlib.Path(__file__).resolve().parent.parent / "yet_another_mobilenet_series_tpu"
SCRIPTS = pathlib.Path(__file__).resolve().parent.parent / "scripts"

# the curated scripts/ subset: PRNG discipline and version-fragile imports
# apply to standalone benches/watchers exactly as to package code; the
# package-convention rules (logging sinks, config drift, donation idioms)
# deliberately do not
SCRIPT_RULES = {"YAMT002", "YAMT006"}


def test_package_lints_clean():
    findings = run_lint([PACKAGE])
    assert findings == [], (
        "the package must lint clean (see docs/LINT.md):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_new_interprocedural_rules_are_registered():
    ids = {r.id for r in load_rules()}
    assert {"YAMT009", "YAMT010", "YAMT019", "YAMT020", "YAMT021",
            "YAMT022", "YAMT023", "YAMT024", "YAMT025"} <= ids


def test_no_stale_suppressions():
    # every suppression in the package must still be earning its keep: the
    # audit re-runs the rules raw and flags comments whose rule no longer
    # fires at their site (scripts/lint.sh --check-suppressions in CI)
    findings = check_suppressions([PACKAGE])
    assert findings == [], (
        "stale suppression comments (delete them):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_scripts_lint_clean_under_curated_subset():
    findings = run_lint([SCRIPTS], select=SCRIPT_RULES)
    assert findings == [], (
        "scripts/ must lint clean under the curated subset (see docs/LINT.md):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_whole_package_lint_stays_fast():
    # un-cached end-to-end runs, interprocedural layer included (measured
    # ~3.3-4.5s on the 1-core box with the full 25-rule set, so the 5s bar
    # trips on a complexity regression, not machine noise). Timed in a
    # FRESH subprocess: 500-odd tests into a tier-1 session, pytest's
    # warning capture and stray daemon threads were measured inflating the
    # same run past 6s — that noise belongs to the suite, not the linter,
    # and it's the linter this bar gates. The child times only run_lint
    # (imports excluded; analysis/ is pure-stdlib, ~0.3s to load) and
    # reports the MIN of three runs: this box's scheduler was measured
    # stretching identical runs ±40%, and the minimum estimates the true
    # compute cost — a complexity regression raises every sample, noise
    # only some (each run rebuilds its Project, so nothing is amortized).
    code = (
        "import pathlib, time\n"
        "from yet_another_mobilenet_series_tpu.analysis import run_lint\n"
        f"pkg = pathlib.Path({str(PACKAGE)!r})\n"
        "best = min(\n"
        "    (lambda t0: (run_lint([pkg]), time.perf_counter() - t0)[1])(time.perf_counter())\n"
        "    for _ in range(3)\n"
        ")\n"
        "print(best)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    elapsed = float(out.stdout.strip().splitlines()[-1])
    assert elapsed < 5.0, f"run_lint over the package took {elapsed:.2f}s best-of-3 (bar: 5s)"


def test_apps_ymls_are_covered():
    # guard against the gate silently losing its yml coverage: the collector
    # must actually pick up the experiment files next to the code
    from yet_another_mobilenet_series_tpu.analysis.core import collect_paths

    py, yml = collect_paths([PACKAGE])
    assert any(p.endswith("config.py") for p in py)
    assert sum(p.endswith((".yml", ".yaml")) for p in yml) >= 10
