"""Tier-1 gate: the package must lint clean under its own analyzer.

This is the enforcement half of the yamt-lint tentpole: every invariant the
rules encode (no host effects under trace, PRNG discipline, real mesh axes,
TRAIN_STATE_FIELDS/TrainState agreement, apps/*.yml vs config.py schema,
version-resilient jax imports — docs/LINT.md) is checked on every PR by this
sub-second, pure-AST test. A finding here is a real hazard or an undocumented
suppression — fix the code, don't widen the gate.
"""

import pathlib

from yet_another_mobilenet_series_tpu.analysis import run_lint

PACKAGE = pathlib.Path(__file__).resolve().parent.parent / "yet_another_mobilenet_series_tpu"


def test_package_lints_clean():
    findings = run_lint([PACKAGE])
    assert findings == [], (
        "the package must lint clean (see docs/LINT.md):\n"
        + "\n".join(f.format() for f in findings)
    )


def test_apps_ymls_are_covered():
    # guard against the gate silently losing its yml coverage: the collector
    # must actually pick up the experiment files next to the code
    from yet_another_mobilenet_series_tpu.analysis.core import collect_paths

    py, yml = collect_paths([PACKAGE])
    assert any(p.endswith("config.py") for p in py)
    assert sum(p.endswith((".yml", ".yaml")) for p in yml) >= 10
