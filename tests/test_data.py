"""Input pipeline tests: TFRecord round-trip through the prep script, train/
eval transforms, padding/equalization semantics (SURVEY.md §4.3)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import DataConfig
from yet_another_mobilenet_series_tpu.data import pipeline as data_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tfrecord_dir(tmp_path_factory):
    from PIL import Image

    src = tmp_path_factory.mktemp("imgfolder")
    rng = np.random.RandomState(0)
    for c, color in enumerate([(220, 30, 30), (30, 220, 30), (30, 30, 220)]):
        d = src / f"class_{c}"
        d.mkdir()
        for i in range(8):
            arr = np.clip(np.asarray(color)[None, None, :] + rng.normal(0, 20, (70, 90, 3)), 0, 255)
            Image.fromarray(arr.astype(np.uint8)).save(d / f"im{i}.jpg", quality=92)
    dst = tmp_path_factory.mktemp("tfr")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "imagefolder_to_tfrecords.py"),
         "--src", str(src), "--dst", str(dst), "--split", "validation", "--shards", "2"],
        check=True, capture_output=True,
    )
    subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "imagefolder_to_tfrecords.py"),
         "--src", str(src), "--dst", str(dst), "--split", "train", "--shards", "2"],
        check=True, capture_output=True,
    )
    return str(dst)


def _cfg(tfrecord_dir, **over):
    kw = dict(
        dataset="imagenet", data_dir=tfrecord_dir, image_size=32, eval_resize=36,
        num_eval_examples=24, shuffle_buffer=64,
    )
    kw.update(over)
    return DataConfig(**kw)


@pytest.mark.slow
def test_eval_tfrecords_every_example_once(tfrecord_dir):
    cfg = _cfg(tfrecord_dir)
    ds = data_lib.make_eval_dataset(cfg, local_batch=10)
    batches = list(data_lib.as_numpy(ds))
    assert len(batches) == data_lib.eval_batches_per_host(cfg, 10)  # 24 -> 3 batches
    labels = np.concatenate([b["label"] for b in batches])
    valid = labels[labels >= 0]
    assert len(valid) == 24
    assert sorted(np.bincount(valid).tolist()) == [8, 8, 8]
    imgs = np.concatenate([b["image"] for b in batches])
    assert imgs.shape == (30, 32, 32, 3)
    # normalized: solid-ish colors -> bounded values, non-constant
    assert np.isfinite(imgs).all() and imgs.std() > 0.1


def test_eval_equalization_pads_all_dummy_batches(tfrecord_dir):
    cfg = _cfg(tfrecord_dir, num_eval_examples=50)  # declared > actual
    ds = data_lib.make_eval_dataset(cfg, local_batch=10)
    batches = list(data_lib.as_numpy(ds))
    assert len(batches) == 5  # fixed count from the declared size
    labels = np.concatenate([b["label"] for b in batches])
    assert (labels >= 0).sum() == 24  # real examples still counted once


def test_train_tfrecords_stream_and_augment(tfrecord_dir):
    cfg = _cfg(tfrecord_dir)
    ds = data_lib.make_train_dataset(cfg, local_batch=6, seed=0)
    it = data_lib.as_numpy(ds)
    b1 = next(it)
    b2 = next(it)
    assert b1["image"].shape == (6, 32, 32, 3)
    assert set(np.concatenate([b1["label"], b2["label"]]).tolist()) <= {0, 1, 2}
    # infinite stream: can pull more batches than the dataset holds
    for _ in range(8):
        next(it)


def test_fake_dataset_train_eval_share_templates():
    cfg = DataConfig(dataset="fake", image_size=16, fake_num_classes=4, fake_train_size=32, fake_eval_size=16)
    tr = next(data_lib.as_numpy(data_lib.make_train_dataset(cfg, 8, seed=0)))
    ev = next(data_lib.as_numpy(data_lib.make_eval_dataset(cfg, 8)))
    assert tr["image"].shape == (8, 16, 16, 3) and ev["image"].shape == (8, 16, 16, 3)
    # same class template underneath (noise differs): same-class means correlate
    t0 = tr["image"][tr["label"] == 0].mean(axis=0).ravel()
    e0 = ev["image"][ev["label"] == 0].mean(axis=0).ravel()
    assert np.corrcoef(t0, e0)[0, 1] > 0.7


def test_missing_tfrecords_clear_error(tmp_path):
    cfg = DataConfig(dataset="imagenet", data_dir=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        data_lib.make_train_dataset(cfg, 8, seed=0)


def test_tf_color_jitter_matches_native_semantics():
    """Same invariant as test_native_loader's jitter test: a uniform gray
    image stays uniform (blend-with-gray contrast/saturation) and scales
    multiplicatively within [1-s, 1+s] across samples."""
    tf = data_lib._tf_mod()
    s = 0.4
    img = tf.fill([32, 32, 3], 128.0)
    ratios = []
    for i in range(32):
        seed2 = tf.constant([7, i], tf.int64)  # stateless: keyed per sample
        out = data_lib._color_jitter(tf, img, s, seed2).numpy()
        assert float(out.std()) < 1e-3  # uniform in, uniform out
        ratios.append(float(out.mean()) / 128.0)
    ratios = np.asarray(ratios)
    assert np.all(ratios >= 1 - s - 1e-5) and np.all(ratios <= 1 + s + 1e-5)
    # multiplicative brightness: the factor spreads across the range
    assert ratios.max() - ratios.min() > 0.2, ratios


def test_tf_color_jitter_exact_semantics():
    """Pin the exact op definition (matching native/yamt_loader.cc): mult
    brightness -> blend with mean POST-brightness gray -> blend with
    PER-PIXEL POST-CONTRAST gray, clamping each step. Factors are recovered
    by replaying the seeded uniform sequence."""
    tf = data_lib._tf_mod()
    s = 0.4
    rng = np.random.RandomState(3)
    img_np = rng.uniform(0, 255, (6, 6, 3)).astype(np.float32)
    seed2 = tf.constant([123, 5], tf.int64)
    # stateless draws: replay the exact per-factor keys _color_jitter uses
    fb, fc, fs = (
        float(tf.random.stateless_uniform([], seed=seed2 + tf.constant([o, 0], tf.int64),
                                          minval=1 - s, maxval=1 + s))
        for o in (1, 2, 3)
    )
    out = data_lib._color_jitter(tf, tf.constant(img_np), s, seed2).numpy()

    lum = np.array([0.2989, 0.587, 0.114], np.float32)
    x = np.clip(img_np * fb, 0, 255)
    gray = (x @ lum)[..., None]
    x = np.clip(gray.mean() + (x - gray.mean()) * fc, 0, 255)
    gray2 = (x @ lum)[..., None]  # recomputed AFTER contrast
    x = np.clip(gray2 + (x - gray2) * fs, 0, 255)
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-2)


@pytest.mark.slow
def test_transfer_uint8_matches_f32_path_within_quantization(tfrecord_dir):
    """data.transfer_uint8 ships raw u8 pixels and normalizes in-step: for
    the SAME records/augmentations (deterministic_input), device-side
    normalize(u8 batch) must equal the host-normalized f32 batch within the
    u8 quantization bound (0.5/255/std per channel) — train AND eval paths.
    Also pins dtypes: u8 on the wire, f32 after the step-side normalizer."""
    import itertools

    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.train.steps import _input_normalizer

    def take(cfg_d, n=2):
        ds = data_lib.make_train_dataset(cfg_d, local_batch=6, seed=3)
        return list(itertools.islice(data_lib.as_numpy(ds), n))

    cfg_f32 = _cfg(tfrecord_dir, deterministic_input=True)
    cfg_u8 = _cfg(tfrecord_dir, deterministic_input=True, transfer_uint8=True)

    def full(u8):
        # ONE base literal, toggled only on the knob under test — the two
        # eval steps below must differ in nothing but the transfer encoding
        return config_from_dict({
            "model": {"arch": "mobilenet_v2", "num_classes": 3,
                      "block_specs": [{"t": 1, "c": 8, "n": 1, "s": 1}]},
            "data": {"dataset": "imagenet", "data_dir": tfrecord_dir, "image_size": 32,
                     "transfer_uint8": u8},
            "train": {"compute_dtype": "float32"},
        })

    full_cfg = full(True)
    prep = _input_normalizer(full_cfg)
    # max |delta| = 0.5/255 pixel quantization scaled by 1/min(std)
    tol = 0.5 / 255.0 / min(full_cfg.data.std) + 1e-6

    for a, b in zip(take(cfg_f32), take(cfg_u8)):
        assert b["image"].dtype == np.uint8  # 4x lighter on the wire
        np.testing.assert_array_equal(a["label"], b["label"])
        normed = np.asarray(prep(b["image"]))
        assert normed.dtype == np.float32
        assert np.abs(normed - a["image"]).max() <= tol

    ev_f32 = list(data_lib.as_numpy(data_lib.make_eval_dataset(cfg_f32, local_batch=10)))
    ev_u8 = list(data_lib.as_numpy(data_lib.make_eval_dataset(cfg_u8, local_batch=10)))
    assert len(ev_f32) == len(ev_u8)
    for a, b in zip(ev_f32, ev_u8):
        assert b["image"].dtype == np.uint8
        np.testing.assert_array_equal(a["label"], b["label"])
        # padded rows (label=-1) legitimately differ — f32 pads in
        # normalized space, u8 in pixel space — and are masked out of every
        # metric; compare the real rows only
        valid = a["label"] >= 0
        diff = np.abs(np.asarray(prep(b["image"])) - a["image"])[valid]
        assert diff.size == 0 or diff.max() <= tol

    # eval top-1 through the REAL eval step is unchanged by the transfer
    # encoding (same predictions on these well-separated colors)
    import jax

    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.train import steps as steps_lib

    net = get_model(full_cfg.model, image_size=32)
    params, state = net.init(jax.random.PRNGKey(0))
    ef32 = jax.jit(steps_lib.make_eval_step(net, full(False)))
    eu8 = jax.jit(steps_lib.make_eval_step(net, full_cfg))
    m32 = ef32(params, state, ev_f32[0], {})
    m8 = eu8(params, state, ev_u8[0], {})
    assert float(m32["n"]) == float(m8["n"]) == 10.0
    assert float(m32["top1"]) == float(m8["top1"])


def test_transfer_uint8_rejected_for_fake_data():
    from yet_another_mobilenet_series_tpu.data import make_train_source

    for ds_name, loader in (("fake", "tfdata"), ("fake", "synthetic")):
        cfg = DataConfig(dataset=ds_name, loader=loader, transfer_uint8=True)
        with pytest.raises(ValueError, match="transfer_uint8"):
            make_train_source(cfg, 4, seed=0)


@pytest.mark.slow
def test_transfer_uint8_cli_end_to_end(tfrecord_dir, tmp_path):
    """Real training run over the TFRecord path with transfer_uint8: u8
    batches ride shard_batch/prefetch_to_mesh onto the 8-device mesh, the
    step normalizes on device, eval counts every example exactly once."""
    from yet_another_mobilenet_series_tpu.cli import train as cli_train
    from yet_another_mobilenet_series_tpu.config import config_from_dict

    cfg = config_from_dict({
        "name": "u8_e2e",
        "model": {"arch": "mobilenet_v2", "num_classes": 3, "dropout": 0.0,
                  "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}]},
        "data": {"dataset": "imagenet", "data_dir": tfrecord_dir, "image_size": 32,
                 "eval_resize": 36, "num_train_examples": 24, "num_eval_examples": 24,
                 "transfer_uint8": True},
        "optim": {"optimizer": "sgd", "weight_decay": 0.0},
        "schedule": {"schedule": "constant", "base_lr": 0.05,
                     "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": False},
        "train": {"batch_size": 8, "eval_batch_size": 24, "epochs": 2,
                  "compute_dtype": "float32", "log_dir": str(tmp_path),
                  "eval_every_epochs": 0.0},
        "dist": {"num_devices": 8},
    })
    result = cli_train.run(cfg)
    assert result["eval_n"] == 24  # every real example counted exactly once
    assert np.isfinite(result["eval_loss"])


# --- RandAugment (beyond reference parity, data/randaugment.py) -------------


def test_randaugment_op_semantics():
    """Pin the official op definitions the magnitudes are calibrated for."""
    tf = data_lib._tf_mod()
    from yet_another_mobilenet_series_tpu.data import randaugment as ra

    rng = np.random.RandomState(0)
    img = tf.constant(rng.randint(0, 256, (224, 224, 3)), tf.uint8)
    # autocontrast: per-channel min->0, max->255
    ac = ra._autocontrast(tf, img)
    assert int(tf.reduce_min(ac)) == 0 and int(tf.reduce_max(ac)) == 255
    # solarize: invert at/above threshold only; PIL's threshold-256 identity
    sol = np.asarray(ra._solarize(tf, img, 128))
    im = np.asarray(img)
    np.testing.assert_array_equal(sol[im < 128], im[im < 128])
    np.testing.assert_array_equal(sol[im >= 128], 255 - im[im >= 128])
    np.testing.assert_array_equal(np.asarray(ra._solarize(tf, img, 256)), im)
    # posterize keeps exactly the high bits (and clamps the official
    # bits=0 uint8-shift UB to 1 kept bit)
    post = np.asarray(ra._posterize(tf, img, 4))
    np.testing.assert_array_equal(post, im & 0xF0)
    np.testing.assert_array_equal(
        np.asarray(ra._posterize(tf, img, 0)), np.asarray(ra._posterize(tf, img, 1)))
    # invert
    np.testing.assert_array_equal(np.asarray(ra._invert(tf, img)), 255 - im)
    # cutout paints a gray patch, geometric ops fill with gray
    cut = np.asarray(ra._cutout(tf, img, 20, tf.constant([1, 2], tf.int64), 0))
    assert ((cut == 128).all(axis=-1)).sum() > 0
    rot = np.asarray(ra._rotate(tf, img, tf.constant(30.0)))
    assert ((rot == 128).all(axis=-1)).sum() > 0  # corners filled
    # enhance factor 1.0 is identity for the blend ops
    np.testing.assert_array_equal(np.asarray(ra._color(tf, img, 1.0)), im)
    np.testing.assert_array_equal(np.asarray(ra._brightness(tf, img, 1.0)), im)


def test_randaugment_stateless_and_position_keyed():
    tf = data_lib._tf_mod()
    from yet_another_mobilenet_series_tpu.data import randaugment as ra

    rng = np.random.RandomState(1)
    img = tf.constant(rng.randint(0, 256, (224, 224, 3)).astype(np.float32))
    s = tf.constant([7, 1000], tf.int64)
    a = np.asarray(ra.rand_augment(tf, img, 2, 10, s))
    b = np.asarray(ra.rand_augment(tf, img, 2, 10, s))
    np.testing.assert_array_equal(a, b)  # pure function of (seed, position)
    assert a.dtype == np.float32 and a.min() >= 0.0 and a.max() <= 255.0
    # different stream positions draw different ops
    diffs = [
        np.abs(np.asarray(ra.rand_augment(tf, img, 2, 10, tf.constant([7, 1000 + k], tf.int64))) - a).max()
        for k in range(1, 5)
    ]
    assert max(diffs) > 0


@pytest.mark.slow
def test_randaugment_pipeline_deterministic(tfrecord_dir):
    """Through make_train_dataset: two fresh streams agree bitwise, and
    RandAugment actually changes pixels vs the plain pipeline."""
    kw = dict(deterministic_input=True, randaugment_layers=2, randaugment_magnitude=5)
    cfg = _cfg(tfrecord_dir, **kw)

    def take(c, n=3):
        it = data_lib.as_numpy(data_lib.make_train_dataset(c, local_batch=6, seed=3))
        return np.concatenate([next(it)["image"] for _ in range(n)])

    x1, x2 = take(cfg), take(cfg)
    np.testing.assert_array_equal(x1, x2)
    plain = take(_cfg(tfrecord_dir, deterministic_input=True))
    assert np.abs(x1 - plain).max() > 0


def test_randaugment_validation():
    from yet_another_mobilenet_series_tpu import data as data_pkg

    with pytest.raises(ValueError, match="tfdata"):
        data_pkg._check(DataConfig(dataset="folder", loader="native", data_dir="/nope",
                                   randaugment_layers=2))
    with pytest.raises(ValueError, match="randaugment"):
        data_pkg._check(DataConfig(dataset="imagenet", data_dir="/nope",
                                   randaugment_layers=2, randaugment_magnitude=11))
    # fake data would silently skip the augment map — reject like transfer_uint8
    with pytest.raises(ValueError, match="randaugment_layers=0"):
        data_pkg._check(DataConfig(dataset="fake", randaugment_layers=2))
    # tfdata + randaugment is accepted
    data_pkg._check(DataConfig(dataset="imagenet", data_dir="/nope", randaugment_layers=2))
