import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import ModelConfig
from yet_another_mobilenet_series_tpu.models import get_model, get_arch
from yet_another_mobilenet_series_tpu.utils.profiling import masked_macs, profile_network


# Golden tables from the public papers (SURVEY.md §4.1; BASELINE.md):
# (params, macs) at width 1.0, 224x224. Tolerances are tight — the block
# grammar is the top-1-parity contract (SURVEY.md §3.4).
GOLDEN = {
    "mobilenet_v1": (4.23e6, 569e6, 0.01),
    "mobilenet_v2": (3.50e6, 300e6, 0.01),
    "mobilenet_v3_large": (5.48e6, 217e6, 0.01),
    "mobilenet_v3_small": (2.54e6, 56e6, 0.02),
    "mnasnet_a1": (3.9e6, 312e6, 0.01),
    # beyond reference parity (arXiv:1905.11946). Paper MACs "0.39B" rounds
    # up from ~386M (torchvision/thop measure 386M); lite0's widely-quoted
    # 407M uses a different counting — structurally it is B0 minus SE, so
    # its multiply-adds sit just under B0's.
    "efficientnet_b0": (5.29e6, 386e6, 0.01),
    "efficientnet_lite0": (4.65e6, 385e6, 0.01),
}


@pytest.mark.parametrize("arch", sorted(GOLDEN))
def test_golden_params_macs(arch):
    params_ref, macs_ref, tol = GOLDEN[arch]
    prof = profile_network(get_model(ModelConfig(arch=arch)))
    assert abs(prof.total_params - params_ref) / params_ref < tol, prof.total_params
    assert abs(prof.total_macs - macs_ref) / macs_ref < tol, prof.total_macs


def test_efficientnet_exact_published_params():
    """The grammar reproduces EfficientNet to the PARAMETER: 5,288,548 is
    torchvision efficientnet_b0's exact count, 4,652,008 is the official
    efficientnet-lite0 count. Exact equality — any grammar drift (SE width
    rule, t=1 expand-skip, head handling) breaks this before it can hurt."""
    assert profile_network(get_model(ModelConfig(arch="efficientnet_b0"))).total_params == 5288548
    assert profile_network(get_model(ModelConfig(arch="efficientnet_lite0"))).total_params == 4652008
    # the searched-arch JSON sidecar carries the SE inner-act faithfully
    from yet_another_mobilenet_series_tpu.models.serialize import network_from_dict, network_to_dict
    net = get_model(ModelConfig(arch="efficientnet_b0"))
    assert network_from_dict(network_to_dict(net)) == net
    assert net.blocks[1].se_inner_act == "swish"
    # EfficientNet round_filters scales the head at wm<1 too (no MBV2-style
    # never-shrink floor): 1280 * 0.5 -> 640
    assert get_model(ModelConfig(arch="efficientnet_b0", width_mult=0.5)).head.out_channels == 640


@pytest.mark.slow  # ~56 s: eager B0 applies dominate (fast-gate budget, pytest.ini)
def test_stochastic_depth(tmp_path):
    """EfficientNet drop_connect: linear per-block depth ramp, per-SAMPLE
    Bernoulli residual drop at train time (inverse-scaled), exact no-op at
    eval and on rate-0 archs (arXiv:1603.09382 / 1905.11946)."""
    net = get_model(ModelConfig(arch="efficientnet_b0"), image_size=32)
    nb = len(net.blocks)
    assert net.blocks[0].drop_path == 0.0
    assert net.blocks[-1].drop_path == pytest.approx(0.2 * (nb - 1) / nb)
    # config override beats the arch default
    assert get_model(ModelConfig(arch="efficientnet_b0", drop_connect=0.0)).blocks[-1].drop_path == 0.0
    # rate-0 archs build exactly as before
    assert all(b.drop_path == 0.0 for b in get_model(ModelConfig(arch="mobilenet_v3_large")).blocks)
    # out-of-range rates fail at build time, not as NaN at step 0
    with pytest.raises(ValueError, match="drop_connect"):
        get_model(ModelConfig(arch="efficientnet_b0", drop_connect=1.0))
    # the network_spec path honors the knob too (training knob, not part of
    # the serialized architecture): the ramp is re-applied over the blocks
    import json

    from yet_another_mobilenet_series_tpu.models.serialize import network_to_dict

    spec_path = tmp_path / "arch.json"
    spec_path.write_text(json.dumps(network_to_dict(get_model(ModelConfig(arch="efficientnet_lite0")))))
    restored = get_model(ModelConfig(network_spec=str(spec_path), drop_connect=0.4))
    assert restored.blocks[-1].drop_path == pytest.approx(0.4 * (nb - 1) / nb)
    assert get_model(ModelConfig(network_spec=str(spec_path), drop_connect=0.0)).blocks[-1].drop_path == 0.0

    params, state = net.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    y_a, _ = net.apply(params, state, x, train=True, rng=jax.random.PRNGKey(2))
    y_b, _ = net.apply(params, state, x, train=True, rng=jax.random.PRNGKey(3))
    assert float(jnp.abs(y_a - y_b).max()) > 0  # streams actually differ
    # eval ignores the rng entirely
    e1, _ = net.apply(params, state, x, train=False)
    e2, _ = net.apply(params, state, x, train=False, rng=jax.random.PRNGKey(4))
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))

    # per-sample semantics on a single residual block: dropped samples pass
    # the input through EXACTLY (branch scaled to zero), kept samples are
    # inverse-scaled by 1/keep_prob
    from yet_another_mobilenet_series_tpu.ops.blocks import InvertedResidual

    blk = InvertedResidual(in_channels=8, out_channels=8, expanded_channels=24, drop_path=0.5)
    bp, bs = blk.init(jax.random.PRNGKey(5))
    xb = jax.random.normal(jax.random.PRNGKey(6), (64, 8, 8, 8))
    yb, _ = blk.apply(bp, bs, xb, train=True, rng=jax.random.PRNGKey(7))
    passed_through = np.asarray(jnp.all(jnp.isclose(yb, xb), axis=(1, 2, 3)))
    assert 0 < passed_through.sum() < 64  # some dropped, some kept
    # kept samples: (y - x) == branch/keep_prob, i.e. exactly 2x the no-drop
    # branch under the same train-mode (batch-stat) BN
    y0, _ = blk.apply(bp, bs, xb, train=True)  # rng=None -> drop disabled
    kept = ~passed_through
    np.testing.assert_allclose(
        np.asarray(yb - xb)[kept], 2.0 * np.asarray(y0 - xb)[kept], rtol=1e-5, atol=1e-6
    )


@pytest.mark.slow
def test_profiler_matches_actual_param_count():
    """Analytic profiler == number of weights actually initialized."""
    for arch in ["mobilenet_v2", "mobilenet_v3_large", "atomnas_supernet_se", "efficientnet_b0"]:
        net = get_model(ModelConfig(arch=arch))
        params, _ = net.init(jax.random.PRNGKey(0))
        n_actual = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n_actual == profile_network(net).total_params, arch


def test_width_mult_rounding():
    # torchvision MBV2-0.75 has ~2.64M params; channel rounding must match.
    prof = profile_network(get_model(ModelConfig(arch="mobilenet_v2", width_mult=0.75)))
    assert abs(prof.total_params - 2.64e6) / 2.64e6 < 0.02
    # head width must not shrink below 1280 at width<1 (MBV2 convention)
    net = get_model(ModelConfig(arch="mobilenet_v2", width_mult=0.5))
    assert net.head.out_channels == 1280
    # width>1 scales the head by default (1280*1.1 -> 1408)...
    net = get_model(ModelConfig(arch="mobilenet_v2", width_mult=1.1))
    assert net.head.out_channels == 1408
    # ...but explicit channel overrides are EXACT final widths, exempt from
    # scaling — the AtomNAS-C 1.1x-seed contract (apps/atomnas_c_se.yml)
    net = get_model(ModelConfig(arch="mobilenet_v2", width_mult=1.1, stem_channels=32, head_channels=1280))
    assert net.stem.out_channels == 32
    assert net.head.out_channels == 1280
    # an explicit 0 still means "no head layer" (classifier on block output)
    net = get_model(ModelConfig(arch="mobilenet_v2", width_mult=1.1, head_channels=0))
    assert net.head is None


@pytest.mark.parametrize("arch", [
    # v1/v2 ride the slow suite: each costs ~17 s of jit on this sandbox and
    # the flagship v3-large + the two structurally-distinct archs keep
    # forward coverage in the fast gate
    pytest.param("mobilenet_v1", marks=pytest.mark.slow),
    pytest.param("mobilenet_v2", marks=pytest.mark.slow),
    pytest.param("efficientnet_b0", marks=pytest.mark.slow),
    "mobilenet_v3_large",
    "mnasnet_a1",
    "atomnas_supernet",
])
def test_forward_shapes_and_state(arch):
    net = get_model(ModelConfig(arch=arch, num_classes=10), image_size=64)
    params, state = net.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    logits, new_state = net.apply(params, state, x, train=True, rng=jax.random.PRNGKey(2))
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # BN state must actually update in train mode
    diff = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), state, new_state)
    assert max(jax.tree.leaves(diff)) > 0
    # eval mode leaves state untouched
    _, eval_state = net.apply(params, state, x, train=False)
    same = jax.tree.map(lambda a, b: bool((a == b).all()), state, eval_state)
    assert all(jax.tree.leaves(same))


def test_supernet_masks_change_output():
    # Train mode: fresh-init running stats make eval-mode outputs decay to
    # ~0 through 17 un-normalized blocks, so compare where BN normalizes.
    net = get_model(ModelConfig(arch="atomnas_supernet", num_classes=4, dropout=0.0), image_size=32)
    params, state = net.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y0, _ = net.apply(params, state, x, train=True)
    masks = {1: jnp.zeros(net.blocks[1].expanded_channels).at[:8].set(1.0)}
    y1, _ = net.apply(params, state, x, train=True, masks=masks)
    assert not np.allclose(np.asarray(y0), np.asarray(y1), atol=1e-4)


def test_masked_macs_accounting():
    net = get_model(ModelConfig(arch="atomnas_supernet"))
    prof = profile_network(net)
    full = masked_macs(net, {})
    assert full == prof.total_macs
    # kill all atoms of block 3 -> reduction equals that block's atom cost sum
    e = net.blocks[3].expanded_channels
    red = full - masked_macs(net, {3: np.zeros(e)})
    assert abs(red - prof.atom_costs[3].sum()) < 1e-6
    # supernet with everything alive costs more than plain MBV2 (k=5,7 atoms)
    mbv2 = profile_network(get_model(ModelConfig(arch="mobilenet_v2"))).total_macs
    assert full > mbv2


def test_bad_arch_rejected():
    with pytest.raises(ValueError):
        get_arch("resnet50")


def test_v1_is_separable_not_residual():
    net = get_model(ModelConfig(arch="mobilenet_v1"))
    assert all(not b.has_residual for b in net.blocks)
    assert all(not b.has_expand for b in net.blocks)
    assert all(b.project_act == "relu" for b in net.blocks)


def test_v3_block_structure():
    net = get_model(ModelConfig(arch="mobilenet_v3_large"))
    b0 = net.blocks[0]
    assert not b0.has_expand  # exp 16 == in 16
    assert net.blocks[3].se_channels == 24  # make_divisible(72/4) = 24 (V3 table)
    assert net.blocks[3].kernel_sizes == (5,)
    assert net.head.out_channels == 960 and net.feature.out_features == 1280


def test_custom_block_specs_override():
    cfg = ModelConfig(
        arch="mobilenet_v2",
        block_specs=({"t": 4, "c": 24, "n": 2, "s": 2, "k": [3, 5]},),
        num_classes=7,
    )
    net = get_model(cfg, image_size=32)
    assert len(net.blocks) == 2
    assert net.blocks[0].kernel_sizes == (3, 5)
    params, state = net.init(jax.random.PRNGKey(0))
    logits, _ = net.apply(params, state, jnp.zeros((1, 32, 32, 3)), train=False)
    assert logits.shape == (1, 7)
