import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import Config, EMAConfig, OptimConfig, ScheduleConfig, config_from_dict
from yet_another_mobilenet_series_tpu.train import ema as ema_lib
from yet_another_mobilenet_series_tpu.train import losses, optim, schedules, steps
from yet_another_mobilenet_series_tpu.models import get_model


def test_label_smoothing_matches_torch():
    import torch

    logits = np.random.RandomState(0).normal(size=(8, 10)).astype(np.float32)
    labels = np.random.RandomState(1).randint(0, 10, size=(8,))
    ours = losses.cross_entropy_label_smooth(jnp.asarray(logits), jnp.asarray(labels), 0.1)
    ref = torch.nn.functional.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels), label_smoothing=0.1)
    np.testing.assert_allclose(float(ours), float(ref), rtol=1e-5)
    # smoothing=0 degenerates to plain CE
    ours0 = losses.cross_entropy_label_smooth(jnp.asarray(logits), jnp.asarray(labels), 0.0)
    ref0 = torch.nn.functional.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels))
    np.testing.assert_allclose(float(ours0), float(ref0), rtol=1e-5)


def test_topk_correct():
    logits = jnp.asarray([[0.1, 0.9, 0.0, 0.0], [0.9, 0.1, 0.0, 0.0], [0.0, 0.1, 0.2, 0.7]])
    labels = jnp.asarray([1, 1, 0])
    out = losses.topk_correct(logits, labels, ks=(1, 3))
    assert float(out["top1"]) == 1.0  # only first row top-1 correct
    assert float(out["top3"]) == 2.0  # row2 label 0 is rank 3 (out of top-3... rank within top3)


def test_lr_exp_decay_staircase():
    cfg = ScheduleConfig(schedule="exp_decay", base_lr=0.1, scale_by_batch=False, warmup_epochs=2.0, decay_rate=0.9, decay_epochs=1.0)
    lr = schedules.make_lr_schedule(cfg, total_batch=256, steps_per_epoch=10, total_epochs=10)
    assert float(lr(0)) == 0.0
    np.testing.assert_allclose(float(lr(10)), 0.05, rtol=1e-6)  # mid-warmup (20 steps)
    np.testing.assert_allclose(float(lr(20)), 0.1, rtol=1e-6)  # warmup done
    np.testing.assert_allclose(float(lr(29)), 0.1, rtol=1e-6)  # staircase holds
    np.testing.assert_allclose(float(lr(30)), 0.09, rtol=1e-6)  # first decay
    np.testing.assert_allclose(float(lr(50)), 0.1 * 0.9**3, rtol=1e-6)


def test_lr_cosine_endpoints():
    cfg = ScheduleConfig(schedule="cosine", base_lr=0.2, scale_by_batch=False, warmup_epochs=0.0, final_lr_factor=0.0)
    lr = schedules.make_lr_schedule(cfg, total_batch=256, steps_per_epoch=100, total_epochs=10)
    np.testing.assert_allclose(float(lr(0)), 0.2, rtol=1e-6)
    np.testing.assert_allclose(float(lr(500)), 0.1, rtol=1e-5)
    assert float(lr(1000)) < 1e-8


def test_lr_batch_scaling():
    cfg = ScheduleConfig(schedule="constant", base_lr=0.064, scale_by_batch=True, warmup_epochs=0.0)
    lr = schedules.make_lr_schedule(cfg, total_batch=1024, steps_per_epoch=10, total_epochs=1)
    np.testing.assert_allclose(float(lr(5)), 0.064 * 4, rtol=1e-6)


def test_ema_algebra_and_warmup():
    cfg = EMAConfig(enable=True, decay=0.5, warmup=False)
    shadow = {"w": jnp.asarray(1.0)}
    val = {"w": jnp.asarray(3.0)}
    out = ema_lib.ema_update(cfg, shadow, val, step=0)
    np.testing.assert_allclose(float(out["w"]), 0.5 * 1 + 0.5 * 3)
    # warmup: at step 0 effective decay = min(0.9999, 1/10) = 0.1
    cfgw = EMAConfig(enable=True, decay=0.9999, warmup=True)
    outw = ema_lib.ema_update(cfgw, shadow, val, step=0)
    np.testing.assert_allclose(float(outw["w"]), 0.1 * 1 + 0.9 * 3, rtol=1e-6)


def test_wd_mask_exemptions():
    cfg = OptimConfig(wd_skip_bn=True, wd_skip_bias=True, wd_skip_depthwise=True)
    params = {
        "stem": {"conv": {"w": 0}, "bn": {"gamma": 0, "beta": 0}},
        "blocks": {"0": {"dw0_k3": {"w": 0}, "dw_bn": {"gamma": 0, "beta": 0}, "project": {"w": 0}}},
        "classifier": {"w": 0, "b": 0},
    }
    m = optim.wd_mask(params, cfg)
    assert m["stem"]["conv"]["w"] is True
    assert m["stem"]["bn"]["gamma"] is False
    assert m["blocks"]["0"]["dw0_k3"]["w"] is False  # depthwise exempt
    assert m["blocks"]["0"]["dw_bn"]["gamma"] is False
    assert m["blocks"]["0"]["project"]["w"] is True
    assert m["classifier"]["w"] is True and m["classifier"]["b"] is False
    # depthwise decayed when flag off
    m2 = optim.wd_mask(params, OptimConfig(wd_skip_depthwise=False))
    assert m2["blocks"]["0"]["dw0_k3"]["w"] is True


def test_rmsprop_tf_semantics_one_step():
    """Manual check: nu0=1 (TF initial_scale), eps inside sqrt, momentum after."""
    cfg = OptimConfig(optimizer="rmsprop", momentum=0.9, rmsprop_decay=0.9, rmsprop_eps=0.01, weight_decay=0.0)
    params = {"w": jnp.asarray(2.0)}
    opt = optim.make_optimizer(cfg, lambda s: 0.1, params)
    st = opt.init(params)
    g = {"w": jnp.asarray(0.5)}
    upd, _ = opt.update(g, st, params)
    nu = 0.9 * 1.0 + 0.1 * 0.5**2
    rms = 0.5 / np.sqrt(nu + 0.01)
    mom = 0.9 * 0.0 + rms
    np.testing.assert_allclose(float(upd["w"]), -0.1 * mom, rtol=1e-5)


def test_rmsprop_tf_momentum_order_across_lr_boundary():
    """TF ordering bakes each step's LR into the momentum buffer; compare the
    full optax chain against hand-computed TF-RMSProp across an LR decay
    (0.1 -> 0.01 at step 2), where the torch ordering diverges."""
    d, eps, m = 0.9, 0.01, 0.9
    lrs = [0.1, 0.1, 0.01, 0.01]
    grads = [0.5, -0.3, 0.2, 0.4]

    cfg = OptimConfig(optimizer="rmsprop", momentum=m, rmsprop_decay=d, rmsprop_eps=eps, weight_decay=0.0)
    params = {"w": jnp.asarray(2.0)}
    opt = optim.make_optimizer(cfg, lambda s: jnp.asarray(lrs)[s], params)
    st = opt.init(params)
    p_opt = params
    for g in grads:
        upd, st = opt.update({"w": jnp.asarray(g)}, st, p_opt)
        p_opt = {"w": p_opt["w"] + upd["w"]}

    # hand-computed TF RMSProp: nu0=1; mom = m*mom + lr_t*g/sqrt(nu+eps)
    nu, mom, p = 1.0, 0.0, 2.0
    for lr, g in zip(lrs, grads):
        nu = d * nu + (1 - d) * g * g
        mom = m * mom + lr * g / np.sqrt(nu + eps)
        p -= mom
    np.testing.assert_allclose(float(p_opt["w"]), p, rtol=1e-6)

    # torch ordering (switch off): mom accumulates unscaled rms, lr at apply
    cfg_t = OptimConfig(optimizer="rmsprop", momentum=m, rmsprop_decay=d, rmsprop_eps=eps,
                        weight_decay=0.0, rmsprop_tf_momentum_order=False)
    opt_t = optim.make_optimizer(cfg_t, lambda s: jnp.asarray(lrs)[s], params)
    st_t = opt_t.init(params)
    p_torch = params
    for g in grads:
        upd, st_t = opt_t.update({"w": jnp.asarray(g)}, st_t, p_torch)
        p_torch = {"w": p_torch["w"] + upd["w"]}
    nu, mom, p2 = 1.0, 0.0, 2.0
    for lr, g in zip(lrs, grads):
        nu = d * nu + (1 - d) * g * g
        mom = m * mom + g / np.sqrt(nu + eps)
        p2 -= lr * mom
    np.testing.assert_allclose(float(p_torch["w"]), p2, rtol=1e-6)
    # the two orderings genuinely differ once LR decays
    assert abs(p - p2) > 1e-4


def test_rmsprop_orderings_agree_at_constant_lr():
    grads = [0.5, -0.3, 0.2]
    params = {"w": jnp.asarray(2.0)}
    outs = []
    for tf_order in (True, False):
        cfg = OptimConfig(optimizer="rmsprop", momentum=0.9, rmsprop_decay=0.9,
                          rmsprop_eps=0.01, weight_decay=0.0, rmsprop_tf_momentum_order=tf_order)
        opt = optim.make_optimizer(cfg, lambda s: 0.1, params)
        st = opt.init(params)
        p = params
        for g in grads:
            upd, st = opt.update({"w": jnp.asarray(g)}, st, p)
            p = {"w": p["w"] + upd["w"]}
        outs.append(float(p["w"]))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)


def test_weight_decay_coupled_before_rms():
    cfg = OptimConfig(optimizer="sgd", momentum=0.0, weight_decay=0.1)
    params = {"conv": {"w": jnp.asarray(2.0)}}
    opt = optim.make_optimizer(cfg, lambda s: 1.0, params)
    st = opt.init(params)
    upd, _ = opt.update({"conv": {"w": jnp.asarray(0.0)}}, st, params)
    # pure decay: grad 0 + wd*param = 0.2
    np.testing.assert_allclose(float(upd["conv"]["w"]), -0.2, rtol=1e-6)


def test_step_cadence_fires_every_boundary_exactly_once():
    from yet_another_mobilenet_series_tpu.utils.cadence import StepCadence

    # fractional-epoch chunks (spe=7, epochs=2.43): checks happen at chunk
    # ends 7, 14, 17 — boundaries 7 and 14 fire once each, 17 is no boundary
    cad = StepCadence(1.0, 7)
    assert [cad.due(s) for s in (7, 14, 17)] == [True, True, False]

    # no float drift over many epochs (the `epoch % every < 1e-6` failure)
    cad = StepCadence(1.0, 3)
    fired = sum(cad.due(s) for s in range(3, 301, 3))
    assert fired == 100

    # cadence coarser than a step-chunk: 2.5 epochs * 4 spe = every 10 steps
    cad = StepCadence(2.5, 4)
    fired_at = [s for s in range(1, 25) if cad.due(s)]
    assert fired_at == [10, 20]

    # a jump over several boundaries fires once, then resumes normally
    cad = StepCadence(1.0, 5)
    assert cad.due(17) is True  # crossed 5, 10, 15 -> one event
    assert cad.due(19) is False
    assert cad.due(20) is True

    # resume anchoring: boundaries at or before start_step already fired
    cad = StepCadence(1.0, 7, start_step=14)
    assert cad.due(14) is False
    assert cad.due(21) is True

    # disabled
    cad = StepCadence(0.0, 7)
    assert not any(cad.due(s) for s in range(100))

    # sub-step cadence clamps to every step, never to zero
    cad = StepCadence(0.25, 2)
    assert [cad.due(s) for s in (1, 2, 3)] == [True, True, True]


def _tiny_cfg(**over):
    d = {
        "model": {
            "arch": "mobilenet_v2",
            "num_classes": 4,
            "dropout": 0.0,
            "block_specs": [
                {"t": 2, "c": 8, "n": 1, "s": 2},
                {"t": 2, "c": 16, "n": 1, "s": 2, "k": [3, 5]},
            ],
        },
        "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.05, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {"compute_dtype": "float32"},
    }
    d.update(over)
    return config_from_dict(d)


@pytest.mark.parametrize("policy,bn_mode", [
    ("full", "exact"),
    ("save_conv", "exact"),
    # the composed round-3 stack: custom-VJP BN recomputed under the
    # save-conv checkpoint policy must still be a pure scheduling change
    ("save_conv", "fused_vjp"),
])
def test_remat_step_equals_plain_step(policy, bn_mode):
    """train.remat (both policies) must be a pure memory/recompute trade:
    the updated params after one step are BIT-IDENTICAL to the non-remat
    step's on CPU f32 (jax.checkpoint changes scheduling, not math).
    save_conv keeps the MXU outputs and recomputes the BN/act chains (the
    round-3 attack on the BN activation round-trips, ops/layers.py conv_out
    landmark)."""
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
        "label": jnp.arange(8) % 4,
    }
    rng = jax.random.PRNGKey(42)
    results = []
    for remat_over in ({}, {"remat": True, "remat_policy": policy}):
        cfg = _tiny_cfg(train={"compute_dtype": "float32", "bn_mode": bn_mode, **remat_over})
        net = get_model(cfg.model, image_size=16)
        lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 100)
        params, _ = net.init(jax.random.PRNGKey(0))
        opt = optim.make_optimizer(cfg.optim, lr_fn, params)
        ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
        step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))
        ts, metrics = step_fn(ts, batch, rng)
        results.append((ts, metrics))
    (ts_plain, met_plain), (ts_remat, met_remat) = results
    assert float(met_plain["loss"]) == float(met_remat["loss"])
    assert float(met_plain["grad_norm"]) == float(met_remat["grad_norm"])
    for a, b in zip(jax.tree.leaves(ts_plain.params), jax.tree.leaves(ts_remat.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_remat_policy_validated():
    cfg = _tiny_cfg(train={"compute_dtype": "float32", "remat": True, "remat_policy": "nope"})
    net = get_model(cfg.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    with pytest.raises(ValueError, match="remat_policy"):
        steps.make_train_step(net, cfg, opt, lr_fn)


@pytest.mark.slow
def test_bn_variants_converge_identically():
    """300 training steps under each bn_mode track the exact-mode loss
    trajectory (single device, f32) with bounded divergence — the
    training-dynamics half of the PROFILE.md decision rule's top-1-parity
    argument for `compute` (VERDICT r3 #5; the eval-forward half is
    test_acceptance_mbv2.py::test_full_scale_bn_mode_prediction_agreement).

    Raw losses cannot stay close for hundreds of steps: benign ~1e-7
    re-association differences compound chaotically through RMSProp's rsqrt
    (~0.5% rel by step 20, observed). The long-horizon guarantee is "same
    optimization", asserted as (a) every mode converges to the same
    overfit plateau band, and (b) end-state train-batch predictions match
    exact's exactly."""
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
        "label": jnp.arange(8) % 4,
    }
    rng = jax.random.PRNGKey(42)
    n_steps, tail = 300, 50
    traces, end_preds = {}, {}
    for mode in ("exact", "folded", "compute", "fused_vjp", "sdot", "compute_sdot"):
        cfg = _tiny_cfg(train={"compute_dtype": "float32", "bn_mode": mode})
        net = get_model(cfg.model, image_size=16)
        lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 100)
        params, _ = net.init(jax.random.PRNGKey(0))
        opt = optim.make_optimizer(cfg.optim, lr_fn, params)
        ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
        step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))
        losses = []
        for _ in range(n_steps):
            ts, metrics = step_fn(ts, batch, rng)
            losses.append(float(metrics["loss"]))
        traces[mode] = np.asarray(losses)
        logits, _ = net.apply(ts.params, ts.state, batch["image"], train=False)
        end_preds[mode] = np.asarray(jnp.argmax(logits, -1))
    for mode in ("folded", "fused_vjp", "compute", "sdot", "compute_sdot"):
        # short horizon: trajectories are still numerically locked
        np.testing.assert_allclose(traces[mode][:8], traces["exact"][:8], rtol=1e-3, atol=1e-4)
        # long horizon: same plateau (mean over the last `tail` steps) ...
        exact_tail = traces["exact"][-tail:].mean()
        mode_tail = traces[mode][-tail:].mean()
        assert abs(mode_tail - exact_tail) <= max(0.05, 0.15 * exact_tail), (
            mode, mode_tail, exact_tail)
        # ... and the same learned classification of the train batch
        np.testing.assert_array_equal(end_preds[mode], end_preds["exact"], err_msg=mode)
    # and training actually overfit in every mode (4 classes, 8 samples)
    assert all(t[-tail:].mean() < t[0] * 0.5 for t in traces.values())


def test_train_step_overfits_tiny_batch():
    # _tiny_cfg's lr=0.05 is chaotic for TF-RMSProp on this batch-8 toy net
    # (loss oscillates 0.42 -> 0.98 -> 5.6 over 30-60 steps, measured under
    # jax 0.4.37 — the step-30 reading was a coin flip). 0.02 converges
    # monotonically to ~0.25x the first loss; the 0.7 bar keeps real margin.
    cfg = _tiny_cfg(
        schedule={"schedule": "constant", "base_lr": 0.02, "scale_by_batch": False, "warmup_epochs": 0.0}
    )
    net = get_model(cfg.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))

    rng = jax.random.PRNGKey(42)
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
        "label": jnp.arange(8) % 4,
    }
    first = None
    for i in range(30):
        ts, metrics = step_fn(ts, batch, rng)
        if first is None:
            first = float(metrics["loss"])
    assert int(ts.step) == 30
    assert float(metrics["finite"]) == 1.0
    assert float(metrics["loss"]) < first * 0.7, (first, float(metrics["loss"]))
    # EMA shadow differs from raw params but has same structure
    assert jax.tree.structure(ts.ema_params) == jax.tree.structure(ts.params)
    diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), ts.ema_params, ts.params)
    assert max(jax.tree.leaves(diffs)) > 0


def test_eval_step_validates_bn_mode():
    """ADVICE r4 #4: eval pins bn_mode='exact' internally, but a misspelled
    train.bn_mode must still fail fast in an eval-only run — before this,
    the typo surfaced only if a train step was ever built."""
    cfg = _tiny_cfg(train={"compute_dtype": "float32", "bn_mode": "exactt"})
    net = get_model(cfg.model, image_size=16)
    with pytest.raises(ValueError, match="bn_mode"):
        steps.make_eval_step(net, cfg)


def test_eval_step_counts_and_padding():
    cfg = _tiny_cfg()
    net = get_model(cfg.model, image_size=16)
    eval_fn = jax.jit(steps.make_eval_step(net, cfg))
    params, state = net.init(jax.random.PRNGKey(0))
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (6, 16, 16, 3)),
        "label": jnp.asarray([0, 1, 2, 3, -1, -1]),  # 2 padded
    }
    m = eval_fn(params, state, batch, {})
    assert float(m["n"]) == 4.0
    assert 0 <= float(m["top1"]) <= float(m["top5"]) <= 4.0
    assert np.isfinite(float(m["loss_sum"]))


def test_batch_mixer_semantics():
    """In-step Mixup/CutMix (beyond reference parity, steps.make_batch_mixer):
    mixup is the exact convex combination, cutmix pastes a box whose ACTUAL
    clipped area defines lam, both deterministic per rng."""
    assert steps.make_batch_mixer(_tiny_cfg()) is None  # both alphas 0

    # mixup: per-batch convex combo preserves the batch mean exactly
    mix = steps.make_batch_mixer(_tiny_cfg(optim={"mixup_alpha": 0.4}))
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8, 8, 3))
    y = jnp.arange(16) % 4
    xm, yb, lam = mix(jax.random.PRNGKey(1), x, y)
    xm2, yb2, lam2 = mix(jax.random.PRNGKey(1), x, y)
    np.testing.assert_array_equal(np.asarray(xm), np.asarray(xm2))  # deterministic
    assert float(lam) == float(lam2)
    np.testing.assert_allclose(np.asarray(xm.mean(0)), np.asarray(x.mean(0)), atol=1e-5)
    assert 0.0 <= float(lam) <= 1.0

    # cutmix: images constant at their SAMPLE INDEX (and labels = that
    # index), so pixel provenance is fully recoverable: pasted pixels must
    # carry exactly the value of the sample whose label came back in yb —
    # i.e. images and labels are permuted by the SAME permutation — and
    # lam == 1 - (pasted fraction)
    mix = steps.make_batch_mixer(_tiny_cfg(optim={"cutmix_alpha": 1.0}))
    yc = jnp.arange(16)
    xc = jnp.broadcast_to(jnp.arange(16, dtype=jnp.float32)[:, None, None, None], (16, 8, 8, 3))
    found = False
    for k in range(6):
        xm, yb, lam = mix(jax.random.PRNGKey(k), xc, yc)
        vals = np.asarray(xm[:, :, :, 0])
        yb = np.asarray(yb)
        per_sample = []
        for i in range(16):
            pasted = vals[i][vals[i] != i]
            if pasted.size:
                # every pasted pixel comes from ONE source: the sample whose
                # label is yb[i]
                assert set(np.unique(pasted)) == {float(yb[i])}, (i, np.unique(pasted), yb[i])
                per_sample.append(pasted.size / vals[i].size)
        if per_sample and max(per_sample) < 1.0:
            found = True
            np.testing.assert_allclose(per_sample, per_sample[0])  # same box everywhere
            np.testing.assert_allclose(1.0 - per_sample[0], float(lam), atol=1e-6)
    assert found


@pytest.mark.slow  # ~32 s: two jitted step builds (fast-gate budget, pytest.ini)
def test_train_step_with_mixup_cutmix_runs_and_differs():
    cfg_mix = _tiny_cfg(optim={"mixup_alpha": 0.2, "cutmix_alpha": 1.0, "weight_decay": 1e-5})
    cfg_off = _tiny_cfg()
    net = get_model(cfg_mix.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg_mix.schedule, 8, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
        "label": jnp.arange(8) % 4,
    }
    rng = jax.random.PRNGKey(42)
    outs = {}
    for name, cfg in [("mix", cfg_mix), ("off", cfg_off)]:
        opt = optim.make_optimizer(cfg.optim, lr_fn, params)
        ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
        step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))
        for _ in range(3):
            ts, metrics = step_fn(ts, batch, rng)
        assert float(metrics["finite"]) == 1.0
        outs[name] = jax.tree.leaves(ts.params)[0]
    # the mixed program actually trains on different inputs/targets
    assert float(jnp.abs(outs["mix"] - outs["off"]).max()) > 0
