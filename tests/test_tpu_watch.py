"""Host-side decision logic of the standing TPU watcher (scripts/tpu_watch.py):
the >3% adoption rules run unattended in a scarce alive window, so their
edge cases — key ownership between the A/B and sweep decisions, stale-state
cleanup, the better-headline guard — are pinned here instead of being
discovered mid-window. Pure JSON/process-free tests (no jax, no backend)."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def tw(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "tpu_watch",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "scripts", "tpu_watch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "TUNING_PATH", str(tmp_path / "BENCH_TUNING.json"))
    mod._tmp = tmp_path
    return mod


def _ab(tmp, rows):
    p = str(tmp / "ab.json")
    json.dump({"platform": "tpu", "device_kind": "TPU v5 lite", "rows": rows}, open(p, "w"))
    return p


def _sweep(tmp, rows):
    p = str(tmp / "sw.json")
    json.dump({"bench": "xla_flags_sweep", "rows": rows}, open(p, "w"))
    return p


def _row(mode, ms, remat="off", dot=False, loss=6.9):
    return {"bn_mode": mode, "remat": remat, "conv1x1_dot": dot,
            "ms_per_step": ms, "loss": loss, "img_s_per_chip": round(256e3 / ms, 1)}


def test_ab_win_adopts_and_preserves_sweep_flags(tw):
    tw._write_tuning({"flags": "--xla_tpu_rwb_fusion=false", "flags_source": "earlier"})
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("folded", 33.0, loss=6.9001)]),
              str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert t["bn_mode"] == "folded" and t["flags"] == "--xla_tpu_rwb_fusion=false"
    dec = json.load(open(tw._tmp / "dec.json"))
    assert dec["adopted"] and dec["winner"]["speedup_vs_exact"] == pytest.approx(35.7 / 33.0, abs=1e-3)


def test_ab_no_win_clears_only_ab_keys(tw):
    tw._write_tuning({"bn_mode": "folded", "source": "old", "flags": "--xla_a=1", "flags_source": "s"})
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("folded", 35.5)]),
              str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert "bn_mode" not in t and t["flags"] == "--xla_a=1"


def test_ab_sub_threshold_and_loss_sanity(tw):
    # 2% is under the rule; a >3% candidate with a broken loss is rejected
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("folded", 35.0),
                            _row("fused_vjp", 30.0, loss=8.5)]),
              str(tw._tmp / "dec.json"), allow_compute=False)
    assert not os.path.exists(tw.TUNING_PATH)
    assert json.load(open(tw._tmp / "dec.json"))["adopted"] is False


def test_compute_family_gated_on_allow_compute(tw):
    rows = [_row("exact", 35.7), _row("compute_sdot", 28.0, loss=6.903)]
    tw.decide(_ab(tw._tmp, rows), str(tw._tmp / "dec.json"), allow_compute=False)
    assert not os.path.exists(tw.TUNING_PATH)
    tw.decide(_ab(tw._tmp, rows), str(tw._tmp / "dec.json"), allow_compute=True)
    assert tw._read_tuning()["bn_mode"] == "compute_sdot"
    # a compute-family adoption is flagged provisional in the decision
    # record (synthetic-fixture parity, not a real top-1 — VERDICT r4 weak
    # #4); parity-safe wins carry no such flag
    dec = json.load(open(tw._tmp / "dec.json"))
    assert "provisional" in dec and "real-data" in dec["provisional"]
    # the marker reaches the TUNING FILE too — that is what production runs
    # consume (train.tuning_file surfaces it at startup)
    assert "provisional" in tw._read_tuning()
    # a later parity-safe win clears both the marker and the flag
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("folded", 33.0)]),
              str(tw._tmp / "dec.json"), allow_compute=True)
    assert "provisional" not in json.load(open(tw._tmp / "dec.json"))
    assert "provisional" not in tw._read_tuning()


def test_ab_winner_maps_remat_and_dot_tokens(tw):
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("exact", 32.0, remat="save_conv", dot=True)]),
              str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert t == {"bn_mode": "exact", "remat": True, "remat_policy": "save_conv",
                 "conv1x1_dot": True, "source": t["source"]}


def test_sweep_win_merges_flags_and_no_win_removes_empty_file(tw):
    tw._write_tuning({"bn_mode": "folded", "source": "ab"})
    tw.decide_sweep(_sweep(tw._tmp, [{"flags": "", "ms_per_step": 35.7},
                                     {"flags": "--xla_tpu_scoped_vmem_limit_kib=98304",
                                      "ms_per_step": 33.0}]),
                    str(tw._tmp / "dsw.json"))
    t = tw._read_tuning()
    assert t["bn_mode"] == "folded" and t["flags"].endswith("98304")

    # flags-only tuning + no-win: the file must be REMOVED, not left stale
    tw._write_tuning({"flags": "--xla_a=1", "flags_source": "s"})
    tw.decide_sweep(_sweep(tw._tmp, [{"flags": "", "ms_per_step": 35.7},
                                     {"flags": "--xla_a=1", "ms_per_step": 35.6},
                                     {"flags": "--xla_b=1", "error": "child rc=1"}]),
                    str(tw._tmp / "dsw.json"))
    assert not os.path.exists(tw.TUNING_PATH)


def test_partition_flags_rejects_near_miss_typos():
    """ADVICE r4 #2: '--xlatpu_...' (missing underscore) used to pass the
    bare '--xla' prefix check, land in host XLA_FLAGS, and abort the backend
    with the exact fatal the guard exists to pre-empt. The check now
    requires the full '--xla_' prefix and routes '--xla_tpu_*' to
    LIBTPU_INIT_ARGS."""
    import importlib.util as ilu

    spec = ilu.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = ilu.module_from_spec(spec)
    spec.loader.exec_module(bench)
    xla, libtpu = bench.partition_flags(
        "--xla_latency_hiding_scheduler=true --xla_tpu_rwb_fusion=false")
    assert xla == "--xla_latency_hiding_scheduler=true"
    assert libtpu == "--xla_tpu_rwb_fusion=false"
    for bad in ("--xlatpu_scoped_vmem_limit_kib=98304", "xla_foo=1", "--notxla_x=1"):
        with pytest.raises(ValueError):
            bench.partition_flags(bad)


def test_sweep_loss_sanity_blocks_numerics_perturbing_flags(tw):
    """ADVICE r4 #3: a flag set that wins on speed but moves the measured
    loss beyond LOSS_SANITY_ABS must not be adopted — fusion/scheduler
    toggles can change reduction order (or worse) and would otherwise steer
    every later bench with zero correctness signal."""
    rows = [{"flags": "", "ms_per_step": 35.7, "loss": 6.9},
            {"flags": "--xla_bad=1", "ms_per_step": 30.0,
             "loss": 6.9 + 2 * tw.LOSS_SANITY_ABS},       # fastest, fails sanity
            {"flags": "--xla_ok=1", "ms_per_step": 33.0, "loss": 6.9001}]
    tw.decide_sweep(_sweep(tw._tmp, rows), str(tw._tmp / "dsw.json"))
    t = tw._read_tuning()
    assert t["flags"] == "--xla_ok=1"  # sane runner-up wins, not the perturber
    # when even the sane candidate is sub-threshold, nothing is adopted
    rows = [{"flags": "", "ms_per_step": 35.7, "loss": 6.9},
            {"flags": "--xla_bad=1", "ms_per_step": 30.0, "loss": 99.0}]
    tw.decide_sweep(_sweep(tw._tmp, rows), str(tw._tmp / "dsw.json"))
    assert not os.path.exists(tw.TUNING_PATH)
    dec = json.load(open(tw._tmp / "dsw.json"))
    assert not dec["adopted"]


def test_record_headline_keeps_better_session_number(tw):
    class R:
        returncode = 0
        stderr = ""

        def __init__(self, value):
            self.stdout = json.dumps({"metric": "m", "value": value, "platform": "tpu"})

    hp = str(tw._tmp / "head.json")
    assert tw._record_headline(R(7000.0), hp)
    assert json.load(open(hp))["value"] == 7000.0
    # a worse re-run (e.g. under adopted flags) must not overwrite
    assert tw._record_headline(R(6500.0), hp)
    assert json.load(open(hp))["value"] == 7000.0
    assert tw._record_headline(R(7500.0), hp)
    assert json.load(open(hp))["value"] == 7500.0
    # CPU-fallback / value-less output never counts as a headline
    class Bad(R):
        def __init__(self):
            self.stdout = json.dumps({"metric": "m", "value": 9.5, "platform": "cpu"})
    assert not tw._record_headline(Bad(), str(tw._tmp / "head2.json"))


def test_run_trace_builds_cli_overrides_from_tuning(tw, monkeypatch):
    tw._write_tuning({"bn_mode": "compute_sdot", "conv1x1_dot": True, "remat": True,
                      "remat_policy": "save_conv", "flags": "--xla_tpu_rwb_fusion=false"})
    captured = []
    monkeypatch.setattr(tw, "_run_job",
                        lambda cmd, t, label, env=None: captured.append((label, cmd, env)) and None)
    tw.run_trace("r9")
    label, cmd, env = captured[0]
    assert "train.bn_mode=compute_sdot" in cmd and "train.conv1x1_dot=true" in cmd
    assert "train.remat=true" in cmd and "train.remat_policy=save_conv" in cmd
    assert any(a.startswith("train.profile_start_step=") for a in cmd)
    assert env["LIBTPU_INIT_ARGS"].endswith("--xla_tpu_rwb_fusion=false")


def test_sweep_budget_covers_all_children(tw):
    # the outer sweep budget must cover every child hitting its own timeout
    # (the designed dead-window path) — r4 review finding, kept pinned
    assert tw.SWEEP_TIMEOUT_S > 5 * tw.SWEEP_CHILD_S


def test_dispatch_tax_adoption(tw):
    """The A/B probe row drives steps_per_dispatch: adopted above the tax
    threshold, cleared below it, untouched when the probe died."""
    base_rows = [_row("exact", 35.7), _row("folded", 33.0, loss=6.9001)]
    probe = {"bn_mode": "exact[scan20]", "remat": "off", "conv1x1_dot": False,
             "ms_per_step": 30.0, "ms_per_step_chained": 35.7,
             "dispatch_tax_ms": 5.7, "loss": 6.9}
    # 16% tax -> adopt (alongside the folded win)
    tw.decide(_ab(tw._tmp, base_rows + [probe]), str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert t["bn_mode"] == "folded" and t["steps_per_dispatch"] == tw.DISPATCH_K
    dec = json.load(open(tw._tmp / "dec.json"))
    assert dec["dispatch_adopted"] and dec["dispatch_probe"]["tax_fraction"] == pytest.approx(5.7 / 35.7, abs=1e-4)

    # sub-threshold tax -> cleared (bn_mode win preserved)
    probe2 = dict(probe, dispatch_tax_ms=0.5, ms_per_step=35.2)
    tw.decide(_ab(tw._tmp, base_rows + [probe2]), str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert "steps_per_dispatch" not in t and t["bn_mode"] == "folded"

    # probe died -> previous adoption left alone
    tw._write_tuning(dict(t, steps_per_dispatch=4, steps_per_dispatch_source="earlier"))
    tw.decide(_ab(tw._tmp, base_rows), str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert t["steps_per_dispatch"] == 4
    assert json.load(open(tw._tmp / "dec.json"))["dispatch_probe"] is None


def test_no_win_round_with_dead_probe_keeps_dispatch_adoption(tw):
    """Regression (r4 review): a no-win A/B whose dispatch probe died must
    NOT wipe a previously-measured steps_per_dispatch — only a live probe
    measurement may adopt or clear it."""
    tw._write_tuning({"bn_mode": "folded", "source": "old",
                      "steps_per_dispatch": 4, "steps_per_dispatch_source": "measured r4"})
    tw.decide(_ab(tw._tmp, [_row("exact", 35.7), _row("folded", 35.5)]),  # no win, no probe row
              str(tw._tmp / "dec.json"), allow_compute=False)
    t = tw._read_tuning()
    assert "bn_mode" not in t  # A/B keys cleared
    assert t["steps_per_dispatch"] == 4  # dispatch adoption preserved
