"""True multi-process distributed test (VERDICT round-1 item #8 / SURVEY §7
hard part 5): two real jax.distributed CPU processes x 4 fake devices run the
full training CLI — exercising make_array_from_process_local_data batch
assembly, cross-host psum/pmean, eval batch-count equalization, coordinator-
only checkpointing — and must agree on every reported metric."""

import glob
import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two_process(tmp_path, scenario, nproc=2):
    """Launch nproc jax.distributed worker processes, return their agreed
    RESULT dicts after asserting rc=0 and metric agreement."""
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "_multiproc_worker.py"),
             str(pid), str(nproc), str(port), str(tmp_path), scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=repo, env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        # must exceed the worker's 2400 s jax.distributed shutdown barrier
        # (set for a lagging coordinator checkpoint flush) plus runtime —
        # killing a process legitimately waiting in the barrier would turn
        # a slow flush into a flaky failure
        out, _ = p.communicate(timeout=540 if nproc == 2 else 3000)
        outs.append(out)
    if any(p.returncode != 0 for p in procs):
        # the 4-process scenario has failed ONLY inside full-suite runs
        # (passes standalone and in this module's own sequence) — persist
        # every worker's full output so the in-suite failure mode is
        # diagnosable from the artifact, not from pytest's truncated tail
        dump = os.path.join("/tmp", f"multiproc_fail_{scenario}_{os.getpid()}.log")
        with open(dump, "w") as f:
            for pid, (p, out) in enumerate(zip(procs, outs)):
                f.write(f"===== process {pid} rc={p.returncode} =====\n{out}\n")
        rcs = [p.returncode for p in procs]
        bad = next(i for i, p in enumerate(procs) if p.returncode != 0)
        raise AssertionError(
            f"workers rc={rcs}; full logs: {dump}\n"
            f"--- process {bad} tail ---\n{outs[bad][-3000:]}")

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, out[-2000:]
        results.append(json.loads(lines[-1][len("RESULT "):]))

    r0 = results[0]
    # metrics come out of cross-host collectives: every process must agree
    for ri in results[1:]:
        for k in r0:
            if k == "pid":
                continue
            assert r0[k] == ri[k], (k, r0, ri)
    # exactly one coordinated checkpoint tree (written once, not per process)
    metas = glob.glob(str(tmp_path) + "/ckpt/*/meta*")
    assert metas, "no checkpoint written"
    return r0


@pytest.mark.slow
def test_two_process_training_run(tmp_path):
    r0 = _run_two_process(tmp_path, "fake")
    # the padded-eval equalization must still count every example exactly once
    assert r0["eval_n"] == 72
    assert r0["epoch"] == 2.0
    # training on the learnable fake set must beat 8-class chance
    assert r0["eval_top1"] > 0.2, r0


@pytest.mark.slow
def test_two_process_native_folder_run(tmp_path):
    """The native/folder loader under REAL multi-process jax.distributed
    (VERDICT r3 #6): per-host file sharding (eval_n == 54 proves each val
    example is decoded by exactly one host and counted exactly once —
    overlapping shards would psum to 108), padded label=-1 eval tails, and
    equal collective step counts across hosts (the pod-deadlock guard in
    data/__init__.py — a mismatch would hang, not fail)."""
    pytest.importorskip("PIL")  # fixture JPEGs only; repo convention
    import numpy as np
    from PIL import Image

    rs = np.random.RandomState(0)
    # two brightness-separable classes so a few SGD steps learn them
    for split, per_class in (("train", 40), ("validation", 27)):
        for c, base in ((0, 50), (1, 200)):
            d = os.path.join(str(tmp_path), "data", split, f"class{c}")
            os.makedirs(d)
            for i in range(per_class):
                arr = np.clip(base + rs.randint(-30, 30, (32, 32, 3)), 0, 255).astype(np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"), quality=95)

    r0 = _run_two_process(tmp_path, "folder")
    assert r0["eval_n"] == 54
    assert r0["epoch"] == 4.0
    # 2 present classes; even a degenerate single-class predictor scores .5,
    # so this only smokes that training moved (plumbing is the real target)
    assert r0["eval_top1"] > 0.2, r0


@pytest.mark.slow
def test_four_process_training_run(tmp_path):
    """VERDICT r4 next #3 (scale axis): a 4-process jax.distributed cluster
    (16 fake devices) through the full CLI — twice the proven host count, on
    the path acceptance #5 extrapolates along. Short scenario: the plumbing
    (4-way make_array_from_process_local_data, cross-host psum over 16
    devices, 4-host eval equalization, coordinator-only save) is the target,
    not learning curves."""
    r0 = _run_two_process(tmp_path, "fake4", nproc=4)
    assert r0["eval_n"] == 72  # every example counted exactly once across 4 hosts
    assert r0["epoch"] == 1.0
