"""True multi-process distributed test (VERDICT round-1 item #8 / SURVEY §7
hard part 5): two real jax.distributed CPU processes x 4 fake devices run the
full training CLI — exercising make_array_from_process_local_data batch
assembly, cross-host psum/pmean, eval batch-count equalization, coordinator-
only checkpointing — and must agree on every reported metric."""

import glob
import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_run(tmp_path):
    port = _free_port()
    nproc = 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "_multiproc_worker.py"),
             str(pid), str(nproc), str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=repo, env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"

    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, out[-2000:]
        results.append(json.loads(lines[-1][len("RESULT "):]))

    r0, r1 = results
    # metrics come out of cross-host collectives: both processes must agree
    for k in r0:
        if k == "pid":
            continue
        assert r0[k] == r1[k], (k, r0, r1)
    # the padded-eval equalization must still count every example exactly once
    assert r0["eval_n"] == 72
    assert r0["epoch"] == 2.0
    # exactly one coordinated checkpoint tree (written once, not per process)
    metas = glob.glob(str(tmp_path) + "/ckpt/*/meta*")
    assert metas, "no checkpoint written"
    # training on the learnable fake set must beat 8-class chance
    assert r0["eval_top1"] > 0.2, r0
