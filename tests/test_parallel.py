"""Multi-chip DP correctness on 8 fake CPU devices (SURVEY.md §4.2): the
fake-backend tests covering acceptance configs #3-#5 logic without a pod."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib
from yet_another_mobilenet_series_tpu.train import optim, schedules, steps


def _cfg():
    return config_from_dict({
        "model": {
            "arch": "mnasnet_a1",  # exercises SE + sepconv stem
            "num_classes": 8,
            "dropout": 0.0,
            "block_specs": [
                {"block": "ds", "c": 8, "n": 1, "s": 1, "k": 3},
                {"t": 3, "c": 16, "n": 1, "s": 2, "k": 5, "se": 0.25},
            ],
        },
        "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.02, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.99, "warmup": False},
        "train": {"compute_dtype": "float32"},
        "dist": {"sync_bn": True},
    })


# function scope: dp steps donate their inputs, and on the fake-CPU-device
# platform replication can alias the source buffers — a donated ts must not
# be shared across tests.
@pytest.fixture()
def setup():
    cfg = _cfg()
    net = get_model(cfg.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 16, 1, 100)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (16, 16, 16, 3)),
        "label": jnp.arange(16) % 8,
    }
    return cfg, net, lr_fn, opt, ts, batch


def test_dp_step_bn_modes_agree(setup):
    """Execution variants (bn_mode, conv1x1_dot) must not change the training
    math: one 8-device DP step under each produces the same updated params
    (within fp re-association) and the same grad_norm — the steps.py pmean
    seam that a psum'd custom backward would break with device_count× BN
    affine grads."""
    import dataclasses as dc

    cfg, net, lr_fn, opt, _, batch = setup
    m = mesh_lib.make_mesh(8)
    b = mesh_lib.shard_batch(batch, m)
    variants = {
        "exact": {"bn_mode": "exact"},
        "folded": {"bn_mode": "folded"},
        "fused_vjp": {"bn_mode": "fused_vjp"},
        "exact+dot": {"bn_mode": "exact", "conv1x1_dot": True},
        "sdot": {"bn_mode": "sdot"},
    }
    results = {}
    for name, over in variants.items():
        cfg_m = dc.replace(cfg, train=dc.replace(cfg.train, **over))
        ts = mesh_lib.replicate(steps.init_train_state(net, cfg_m, opt, jax.random.PRNGKey(0)), m)
        step = dp.make_dp_train_step(net, cfg_m, opt, lr_fn, m)
        ts, met = step(ts, b, jax.random.PRNGKey(7))
        results[name] = (jax.device_get(ts.params), float(met["grad_norm"]), float(met["loss"]))
    p_ref, gn_ref, loss_ref = results["exact"]
    for mode in ("folded", "fused_vjp", "exact+dot", "sdot"):
        p, gn, loss = results[mode]
        np.testing.assert_allclose(loss, loss_ref, rtol=1e-5)
        np.testing.assert_allclose(gn, gn_ref, rtol=1e-4)
        # post-RMSProp params: rsqrt(nu) amplifies reduction-order rounding
        # where grads are tiny, so the param bound is looser than the
        # grad-level contract test's (test_ops.py, rtol=1e-4 per device)
        for a, c in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-3, atol=1e-5)


def test_dp_step_equals_single_device_large_batch(setup):
    """psum grad allreduce + SyncBN == single-device full-batch step
    (SURVEY.md §4.2) — THE data-parallel correctness contract."""
    cfg, net, lr_fn, opt, ts, batch = setup
    m = mesh_lib.make_mesh(8)

    single = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))
    ts_s, met_s = single(ts, batch, jax.random.PRNGKey(7))

    dp_step = dp.make_dp_train_step(net, cfg, opt, lr_fn, m)
    ts_d, met_d = dp_step(mesh_lib.replicate(ts, m), mesh_lib.shard_batch(batch, m), jax.random.PRNGKey(7))

    # params identical up to f32 reduction-order noise (~1e-5 after the
    # RMSProp rsqrt; a missing psum or per-shard BN would show ~1e-2+)
    for pa, pb in zip(jax.tree.leaves(ts_s.params), jax.tree.leaves(ts_d.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), rtol=1e-3, atol=3e-5)
    # BN running stats identical (SyncBN == full-batch BN)
    for sa, sb in zip(jax.tree.leaves(ts_s.state), jax.tree.leaves(ts_d.state)):
        np.testing.assert_allclose(np.asarray(sa), np.asarray(sb), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(float(met_s["loss"]), float(met_d["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(met_s["top1"]), float(met_d["top1"]), rtol=1e-6)


def test_dp_determinism(setup):
    cfg, net, lr_fn, opt, ts, batch = setup
    m = mesh_lib.make_mesh(8)
    dp_step = dp.make_dp_train_step(net, cfg, opt, lr_fn, m)
    ts_d = mesh_lib.replicate(ts, m)
    b = mesh_lib.shard_batch(batch, m)
    # independent copies: the step donates its input state
    r1 = dp_step(jax.tree.map(jnp.copy, ts_d), b, jax.random.PRNGKey(3))
    r2 = dp_step(jax.tree.map(jnp.copy, ts_d), b, jax.random.PRNGKey(3))
    for a, b in zip(jax.tree.leaves(r1[0].params), jax.tree.leaves(r2[0].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_multi_step_replicas_stay_in_sync(setup):
    cfg, net, lr_fn, opt, ts, batch = setup
    m = mesh_lib.make_mesh(8)
    dp_step = dp.make_dp_train_step(net, cfg, opt, lr_fn, m)
    check = dp.make_replica_sync_check(m)
    ts_d = mesh_lib.replicate(ts, m)
    b = mesh_lib.shard_batch(batch, m)
    for i in range(3):
        ts_d, met = dp_step(ts_d, b, jax.random.PRNGKey(11))
    assert float(check(ts_d.params)) == 0.0
    assert float(check(ts_d.state)) == 0.0
    assert float(met["finite"]) == 1.0
    assert int(ts_d.step) == 3


def test_dp_eval_counts_match_single(setup):
    cfg, net, lr_fn, opt, ts, batch = setup
    m = mesh_lib.make_mesh(8)
    params, state = ts.params, ts.state
    single_eval = jax.jit(steps.make_eval_step(net, cfg))
    dp_eval = dp.make_dp_eval_step(net, cfg, m)
    ms = single_eval(params, state, batch, {})
    md = dp_eval(mesh_lib.replicate(params, m), mesh_lib.replicate(state, m), mesh_lib.shard_batch(batch, m), {})
    for k in ms:
        np.testing.assert_allclose(float(ms[k]), float(md[k]), rtol=1e-5, err_msg=k)


@pytest.mark.slow
def test_sync_bn_off_gives_per_replica_stats(setup):
    """dist.sync_bn=false must actually disable the BN psum: running stats
    then differ from the full-batch (SyncBN) result while grads stay
    allreduced (params remain replica-identical)."""
    import dataclasses as dc

    cfg, net, lr_fn, opt, ts, batch = setup
    m = mesh_lib.make_mesh(8)
    b = mesh_lib.shard_batch(batch, m)

    cfg_off = dc.replace(cfg, dist=dc.replace(cfg.dist, sync_bn=False))
    step_on = dp.make_dp_train_step(net, cfg, opt, lr_fn, m)
    step_off = dp.make_dp_train_step(net, cfg_off, opt, lr_fn, m)
    ts_on, _ = step_on(mesh_lib.replicate(jax.tree.map(jnp.copy, ts), m), b, jax.random.PRNGKey(5))
    ts_off, _ = step_off(mesh_lib.replicate(jax.tree.map(jnp.copy, ts), m), b, jax.random.PRNGKey(5))

    # BN running stats must differ (per-replica vs global moments)...
    diffs = [
        float(jnp.abs(a - c).max())
        for a, c in zip(jax.tree.leaves(ts_on.state), jax.tree.leaves(ts_off.state))
    ]
    assert max(diffs) > 1e-6, diffs
    # ...but replicas stay in sync either way: grads are pmean'd and the
    # running stats are explicitly broadcast from device 0 (DDP rank-0
    # buffer semantics), so BOTH params and state remain replica-identical.
    check = dp.make_replica_sync_check(m)
    assert float(check(ts_off.params)) == 0.0
    assert float(check(ts_off.state)) == 0.0


def test_mesh_validation():
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(999)
    m = mesh_lib.make_mesh(8)
    with pytest.raises(ValueError):
        mesh_lib.local_batch_slice(17, m)  # not divisible by 8 devices
    assert mesh_lib.local_batch_slice(64, m) == 64  # single host
    assert mesh_lib.is_coordinator()


def test_check_vma_contract():
    """Every production shard_map must pass check_vma=False explicitly
    (ADVICE r3 #2): bn_mode='fused_vjp' returns LOCAL partial dgamma/dbeta
    by contract (ops/layers.py _bn_train_fused_bwd), which is only the
    gradient autodiff produces under check_vma=False maps. Anyone flipping
    a site to check_vma=True (or dropping the kwarg, inheriting a future
    default) must revisit that VJP — this test makes the coupling fail
    loudly instead of silently rescaling BN affine grads."""
    import ast
    import inspect

    from yet_another_mobilenet_series_tpu.parallel import zero

    for module in (dp, zero):
        tree = ast.parse(inspect.getsource(module))
        sites = [
            node for node in ast.walk(tree)
            if isinstance(node, ast.Call)
            and (getattr(node.func, "id", None) == "shard_map"
                 or getattr(node.func, "attr", None) == "shard_map")
        ]
        assert sites, f"{module.__name__}: no shard_map call sites found"
        for call in sites:
            kw = {k.arg: k.value for k in call.keywords}
            assert "check_vma" in kw, (
                f"{module.__name__}:{call.lineno}: shard_map without an explicit "
                "check_vma kwarg (the fused_vjp grad contract requires False)")
            assert isinstance(kw["check_vma"], ast.Constant) and kw["check_vma"].value is False, (
                f"{module.__name__}:{call.lineno}: check_vma is not the literal False — "
                "revisit ops/layers.py _bn_train_fused_bwd before changing this")




def _assert_single_equals_grouped(cfg, net, lr_fn, opt, ts0, *, batch_seed0,
                                  n_batches, k, metric_keys):
    """Run n_batches through k-per-dispatch grouped steps and through single
    dispatches (same batches/order, same per-step rng fold via ts.step) and
    assert params + metrics agree at the XLA fusion-boundary tolerance
    (~1e-7 rel: one k-step program fuses ACROSS steps; bit-identity is NOT
    the contract, unlike remat). Returns the grouped final state."""
    m = mesh_lib.make_mesh(8)
    rng = jax.random.PRNGKey(9)
    batches = [
        mesh_lib.shard_batch({
            "image": np.asarray(jax.random.normal(jax.random.PRNGKey(batch_seed0 + i), (16, 16, 16, 3))),
            "label": np.asarray((jnp.arange(16) + i) % 8),
        }, m)
        for i in range(n_batches)
    ]
    step = dp.make_dp_train_step(net, cfg, opt, lr_fn, m)

    # independent copies per path: the steps donate, and on fake CPU devices
    # replication can alias the source buffers (see the fixture note)
    ts_single = mesh_lib.replicate(jax.tree.map(jnp.copy, ts0), m)
    single_metrics = []
    for b in batches:
        ts_single, met = step(ts_single, b, rng)
        single_metrics.append(met)
    params_single = jax.device_get(ts_single.params)

    grouped = dp.make_grouped_train_step(step, k)
    ts_grp = mesh_lib.replicate(jax.tree.map(jnp.copy, ts0), m)
    grouped_metrics = []
    for i in range(0, n_batches, k):
        ts_grp, mets = grouped(ts_grp, tuple(batches[i:i + k]), rng)
        grouped_metrics += mets
    params_grp = jax.device_get(ts_grp.params)

    # atol: measured 3.2e-6 max abs divergence under jax 0.4.37's CPU XLA
    # (cross-step fusion reorders f32 reductions, then RMSProp's rsqrt
    # amplifies); a real bug (wrong batch order / rng fold) shows ~1e-2
    for a, b in zip(jax.tree.leaves(params_single), jax.tree.leaves(params_grp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=5e-6)
    # rtol: grad_norm is a global reduction over every param, the most
    # rounding-sensitive scalar; measured 1.4e-5 rel drift by step 3 under
    # jax 0.4.37's CPU XLA (a real divergence shows >=1e-2)
    for i, (ms, mg) in enumerate(zip(single_metrics, grouped_metrics)):
        for key in metric_keys:
            np.testing.assert_allclose(float(ms[key]), float(mg[key]),
                                       rtol=1e-4, err_msg=f"step {i} {key}")
    return ts_grp

def test_grouped_step_equals_single_steps(setup):
    """steps_per_dispatch semantics: k steps in ONE jit dispatch
    (dp.make_grouped_train_step) equal k single dispatches — same batches
    in the same order, same per-step rng fold (via ts.step) — up to XLA
    fusion-boundary rounding: compiling k steps as one program lets XLA
    fuse ACROSS steps, so f32 reduction orders differ at ~1e-7 rel
    (measured; bit-identity is NOT the contract, unlike remat)."""
    cfg, net, lr_fn, opt, ts0, _ = setup
    ts_grp = _assert_single_equals_grouped(
        cfg, net, lr_fn, opt, ts0, batch_seed0=10, n_batches=4, k=2,
        metric_keys=("loss", "grad_norm", "top1", "lr"))
    assert int(ts_grp.step) == 4

    with pytest.raises(ValueError, match="k >= 2"):
        dp.make_grouped_train_step(lambda ts, b, r: (ts, {}), 1)


@pytest.mark.slow  # ~60 s: two 8-device program builds (fast-gate budget)
def test_grouped_step_equals_single_steps_with_mixup(setup):
    """Composition pin: in-step Mixup/CutMix adds per-step rng draws inside
    the loss; grouped dispatch must reproduce the SAME mix decisions as k
    single dispatches (the mix key folds ts.step, which advances inside the
    grouped program)."""
    import dataclasses as dc

    cfg, net, lr_fn, opt, ts0, _ = setup
    cfg = dc.replace(cfg, optim=dc.replace(cfg.optim, mixup_alpha=0.2, cutmix_alpha=1.0))
    # loss depends on the drawn lam/permutation: agreement at fusion
    # tolerance proves the grouped program drew the SAME mixes
    _assert_single_equals_grouped(
        cfg, net, lr_fn, opt, ts0, batch_seed0=20, n_batches=2, k=2,
        metric_keys=("loss", "grad_norm"))
