"""Torch state_dict importer tests (VERDICT round-1 item #3): a tiny torch
model with torchvision-MobileNetV2 child structure is exported, imported into
our tree, and must produce identical logits; malformed checkpoints must fail
loudly. (Real torchvision is not installed in this sandbox and no pretrained
.pth exists on disk — the structural layout is replicated exactly here, so a
real mobilenet_v2-*.pth imports through the same code path.)"""

import numpy as np
import pytest

import torch
import torch.nn as nn

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.ckpt import torch_import
from yet_another_mobilenet_series_tpu.config import ModelConfig
from yet_another_mobilenet_series_tpu.models import get_model


def _convbnrelu(cin, cout, k, s):
    return nn.Sequential(
        nn.Conv2d(cin, cout, k, s, padding=k // 2, bias=False),
        nn.BatchNorm2d(cout),
        nn.ReLU6(inplace=False),
    )


class TorchInvRes(nn.Module):
    """torchvision.models.mobilenetv2.InvertedResidual child layout."""

    def __init__(self, cin, cout, expanded, k, s):
        super().__init__()
        layers = []
        if expanded != cin:
            layers.append(_convbnrelu(cin, expanded, 1, 1))
        layers.append(
            nn.Sequential(
                nn.Conv2d(expanded, expanded, k, s, padding=k // 2, groups=expanded, bias=False),
                nn.BatchNorm2d(expanded),
                nn.ReLU6(inplace=False),
            )
        )
        layers.append(nn.Conv2d(expanded, cout, 1, bias=False))
        layers.append(nn.BatchNorm2d(cout))
        self.conv = nn.Sequential(*layers)
        self.use_res = s == 1 and cin == cout

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class TorchTinyMBV2(nn.Module):
    """Dims derived from OUR net spec so the two always agree."""

    def __init__(self, net, num_classes):
        super().__init__()
        feats = [_convbnrelu(3, net.stem.out_channels, 3, 2)]
        for blk in net.blocks:
            feats.append(
                TorchInvRes(blk.in_channels, blk.out_channels, blk.expanded_channels, blk.kernel_sizes[0], blk.stride)
            )
        feats.append(_convbnrelu(net.head.in_channels, net.head.out_channels, 1, 1))
        self.features = nn.Sequential(*feats)
        self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(net.head.out_channels, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.mean([2, 3])
        return self.classifier(x)


def _tiny_net(num_classes=7):
    cfg = ModelConfig(
        arch="mobilenet_v2",
        num_classes=num_classes,
        dropout=0.0,
        block_specs=(
            {"t": 1, "c": 16, "n": 1, "s": 1, "k": 3},
            {"t": 6, "c": 24, "n": 2, "s": 2, "k": 5},  # n=2: second block is residual
        ),
    )
    return get_model(cfg, image_size=32)


def _randomized_torch_model(net, num_classes, seed=0):
    torch.manual_seed(seed)
    tm = TorchTinyMBV2(net, num_classes)
    # non-trivial BN running stats (fresh init would hide mean/var mapping bugs)
    for m in tm.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn_like(m.running_mean) * 0.3)
            m.running_var.copy_(torch.rand_like(m.running_var) * 2 + 0.5)
            m.weight.data.copy_(torch.rand_like(m.weight) + 0.5)
            m.bias.data.copy_(torch.randn_like(m.bias) * 0.2)
    return tm.eval()


def test_import_matches_torch_forward():
    net = _tiny_net()
    tm = _randomized_torch_model(net, 7)
    params, state = torch_import.from_torchvision_mobilenet_v2(tm.state_dict(), net)

    x = np.random.RandomState(0).normal(0, 1, (4, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    ours, _ = net.apply(params, state, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)


def test_import_round_trips_bn_buffers():
    net = _tiny_net()
    tm = _randomized_torch_model(net, 7, seed=1)
    params, state = torch_import.from_torchvision_mobilenet_v2(tm.state_dict(), net)
    # spot-check the buffer mapping on the stem BN
    np.testing.assert_allclose(
        np.asarray(state["stem"]["bn"]["mean"]), tm.features[0][1].running_mean.numpy(), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(state["stem"]["bn"]["var"]), tm.features[0][1].running_var.numpy(), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["1"]["expand_bn"]["gamma"]),
        tm.features[2].conv[0][1].weight.detach().numpy(),
        rtol=1e-6,
    )


def test_import_rejects_shape_mismatch():
    net = _tiny_net()
    tm = _randomized_torch_model(net, 7)
    sd = dict(tm.state_dict())
    sd["features.0.0.weight"] = torch.zeros(99, 3, 3, 3)
    with pytest.raises(torch_import.CheckpointImportError, match="stem.conv"):
        torch_import.from_torchvision_mobilenet_v2(sd, net)


def test_import_rejects_missing_and_leftover_keys():
    net = _tiny_net()
    tm = _randomized_torch_model(net, 7)
    sd = dict(tm.state_dict())
    del sd["classifier.1.bias"]
    with pytest.raises(torch_import.CheckpointImportError, match="missing"):
        torch_import.from_torchvision_mobilenet_v2(sd, net)
    sd = dict(tm.state_dict())
    sd["features.99.whatever"] = torch.zeros(1)
    with pytest.raises(torch_import.CheckpointImportError, match="unconsumed"):
        torch_import.from_torchvision_mobilenet_v2(sd, net)


class TorchSE(nn.Module):
    """torchvision.ops.SqueezeExcitation child layout (fc1/fc2 1x1 convs)."""

    def __init__(self, c, se):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc1 = nn.Conv2d(c, se, 1)
        self.fc2 = nn.Conv2d(se, c, 1)
        self.activation = nn.ReLU()
        self.scale_activation = nn.Hardsigmoid()

    def forward(self, x):
        s = self.avgpool(x)
        s = self.scale_activation(self.fc2(self.activation(self.fc1(s))))
        return x * s


def _convbnact(cin, cout, k, s, act, groups=1):
    return nn.Sequential(
        nn.Conv2d(cin, cout, k, s, padding=k // 2, groups=groups, bias=False),
        nn.BatchNorm2d(cout),
        act,
    )


class TorchV3InvRes(nn.Module):
    """torchvision.models.mobilenetv3.InvertedResidual child layout."""

    def __init__(self, blk):
        super().__init__()
        act = nn.Hardswish() if blk.active_fn == "hswish" else nn.ReLU()
        e, k, s = blk.expanded_channels, blk.kernel_sizes[0], blk.stride
        layers = []
        if blk.has_expand:
            layers.append(_convbnact(blk.in_channels, e, 1, 1, act))
        layers.append(_convbnact(e, e, k, s, act, groups=e))
        if blk.se_channels:
            layers.append(TorchSE(e, blk.se_channels))
        layers.append(_convbnact(e, blk.out_channels, 1, 1, nn.Identity()))
        self.block = nn.Sequential(*layers)
        self.use_res = blk.has_residual

    def forward(self, x):
        return x + self.block(x) if self.use_res else self.block(x)


class TorchTinyMBV3(nn.Module):
    def __init__(self, net, num_classes):
        super().__init__()
        feats = [_convbnact(3, net.stem.out_channels, 3, 2, nn.Hardswish())]
        feats.extend(TorchV3InvRes(blk) for blk in net.blocks)
        feats.append(_convbnact(net.head.in_channels, net.head.out_channels, 1, 1, nn.Hardswish()))
        self.features = nn.Sequential(*feats)
        self.classifier = nn.Sequential(
            nn.Linear(net.head.out_channels, net.feature.out_features),
            nn.Hardswish(),
            nn.Dropout(0.2),
            nn.Linear(net.feature.out_features, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        x = x.mean([2, 3])
        return self.classifier(x)


def test_v3_import_matches_torch_forward():
    """V3 layout: SE (fc1/fc2 1x1 convs with bias), hswish, feature FC head."""
    from yet_another_mobilenet_series_tpu.models import zoo

    cfg = ModelConfig(
        arch="mobilenet_v3_large",
        num_classes=5,
        dropout=0.0,
        block_specs=(
            {"t": 1, "c": 16, "n": 1, "s": 1, "k": 3, "act": "relu"},
            {"t": 4, "c": 24, "n": 1, "s": 2, "k": 5, "se": 0.25, "act": "hswish"},
            {"t": 4, "c": 24, "n": 1, "s": 1, "k": 3, "act": "hswish"},  # residual
        ),
    )
    net = get_model(cfg, image_size=32)
    torch.manual_seed(0)
    tm = TorchTinyMBV3(net, 5)
    for m in tm.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn_like(m.running_mean) * 0.3)
            m.running_var.copy_(torch.rand_like(m.running_var) * 2 + 0.5)
            m.weight.data.copy_(torch.rand_like(m.weight) + 0.5)
            m.bias.data.copy_(torch.randn_like(m.bias) * 0.2)
    tm.eval()

    params, state = torch_import.from_torchvision_mobilenet_v3(tm.state_dict(), net)
    x = np.random.RandomState(2).normal(0, 1, (4, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    ours, _ = net.apply(params, state, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)


def test_v3_import_warns_on_bn_eps_mismatch():
    """torchvision V3 BNs use eps=1e-3; importing into a 1e-5 net must warn
    (the drift is silent otherwise), and a 1e-3 net imports quietly."""
    import warnings

    specs = ({"t": 2, "c": 16, "n": 1, "s": 2, "k": 3, "act": "hswish"},)
    net_default = get_model(ModelConfig(arch="mobilenet_v3_large", num_classes=3, dropout=0.0, block_specs=specs), 32)
    torch.manual_seed(4)
    tm = TorchTinyMBV3(net_default, 3).eval()
    with pytest.warns(UserWarning, match="bn_eps"):
        torch_import.from_torchvision_mobilenet_v3(tm.state_dict(), net_default)
    net_match = get_model(
        ModelConfig(arch="mobilenet_v3_large", num_classes=3, dropout=0.0, block_specs=specs, bn_eps=1e-3), 32
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        torch_import.from_torchvision_mobilenet_v3(tm.state_dict(), net_match)


def test_load_torch_checkpoint_auto_detects_v3(tmp_path):
    cfg = ModelConfig(
        arch="mobilenet_v3_large",
        num_classes=3,
        dropout=0.0,
        block_specs=({"t": 2, "c": 16, "n": 1, "s": 2, "k": 3, "se": 0.25, "act": "hswish"},),
    )
    net = get_model(cfg, image_size=32)
    torch.manual_seed(1)
    tm = TorchTinyMBV3(net, 3).eval()
    path = str(tmp_path / "v3.pth")
    torch.save(tm.state_dict(), path)
    params, state = torch_import.load_torch_checkpoint(path, net)
    x = np.random.RandomState(3).normal(0, 1, (2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    ours, _ = net.apply(params, state, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)


def test_load_torch_checkpoint_file_with_ddp_prefix(tmp_path):
    net = _tiny_net()
    tm = _randomized_torch_model(net, 7, seed=2)
    wrapped = {"state_dict": {f"module.{k}": v for k, v in tm.state_dict().items()}}
    path = str(tmp_path / "ckpt.pth")
    torch.save(wrapped, path)
    params, state = torch_import.load_torch_checkpoint(path, net)
    x = np.random.RandomState(1).normal(0, 1, (2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    ours, _ = net.apply(params, state, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)
