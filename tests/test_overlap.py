"""Overlapped staging + back-to-back dispatch (serve/engine.py slot pool,
serve/pipeline.py runs — docs/SERVING.md "Overlapped staging").

The load-bearing claims, each pinned:

- **bitwise parity**: overlapped staging moves the same float32 bytes as the
  legacy synchronous copy, so logits are BITWISE identical across buckets,
  image sizes, fused K, bf16, and mixed-size coalesced groups — the async
  transfer changes scheduling, never values.
- **slot lifecycle under stress**: with ``max_inflight=2`` and a slot pool
  forced into reuse, 40 concurrent clients hammering the pipelined batcher
  get every row bitwise-correct (no torn batches), nothing hangs, and the
  drain is clean.
- **sharded copy semantics**: the mesh path snapshots a pool-owned staging
  buffer synchronously and never arms a fence — overlap cannot corrupt
  sharded inputs (the regression test for the old "defensive" bypass).
- **back-to-back runs**: a saturated bucket dispatches > 1 batch per
  completion wake-up (``serve.dispatches_per_wakeup``, which counts engine
  dispatch PIECES — the serve.dispatch_seconds granularity), bounded by the
  in-flight window.
- **failure containment**: a dispatch failing between the async device_put
  and fence arming orphans the slot's buffer instead of recycling
  possibly-in-transfer memory; a short back-to-back drain refills through
  the normal lingering path instead of dispatching a padded partial bucket.
"""

import threading

import jax
import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import ModelConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.parallel import mesh as mesh_lib
from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, fold_network
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher


@pytest.fixture(scope="module")
def bundle():
    net = get_model(
        ModelConfig(
            arch="mobilenet_v2", num_classes=10, dropout=0.0,
            block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}, {"t": 3, "c": 16, "n": 1, "s": 2}],
        ),
        image_size=24,
    )
    params, state = net.init(jax.random.PRNGKey(0))
    return InferenceBundle(net=net, params=fold_network(net, params, state), meta={})


def _engines(bundle, *, dtype="float32", fuse=(), slots=2, **kw):
    """(sync, overlapped) engine pair sharing one bundle/config."""
    common = dict(buckets=(2, 4), image_size=24, compute_dtype=dtype, fuse_ladder=fuse, **kw)
    sync = InferenceEngine(bundle, **common)
    ov = InferenceEngine(bundle, overlap_staging=True, staging_slots=slots, **common)
    return sync, ov


# ---------------------------------------------------------------------------
# bitwise parity: overlapped vs sync staging
# ---------------------------------------------------------------------------


def test_overlap_parity_across_buckets_and_sizes(bundle):
    sync, ov = _engines(bundle, image_sizes=(24, 32))
    rng = np.random.RandomState(0)
    for size in (24, 32):
        for n in (1, 2, 3, 4, 5, 7, 9):  # exact buckets, padded tails, multi-chunk
            x = rng.normal(0, 1, (n, size, size, 3)).astype(np.float32)
            assert np.array_equal(sync.predict(x), ov.predict(x)), (n, size)


def test_overlap_parity_fused(bundle):
    sync, ov = _engines(bundle, fuse=(2, 4))
    rng = np.random.RandomState(1)
    cap = sync.buckets[-1]
    for k in (1, 2, 3, 4):  # on-ladder, off-ladder decomposition, per-chunk
        x = rng.normal(0, 1, (k * cap, 24, 24, 3)).astype(np.float32)
        assert np.array_equal(sync.predict(x), ov.predict(x)), k
    # fused piece with a padded tail (the tail rides the slot pool)
    x = rng.normal(0, 1, (2 * cap + 1, 24, 24, 3)).astype(np.float32)
    assert np.array_equal(sync.predict(x), ov.predict(x))


def test_overlap_parity_bf16(bundle):
    sync, ov = _engines(bundle, dtype="bfloat16")
    rng = np.random.RandomState(2)
    for n in (3, 4, 6):
        x = rng.normal(0, 1, (n, 24, 24, 3)).astype(np.float32)
        assert np.array_equal(sync.predict(x), ov.predict(x)), n


def test_overlap_parity_slot_reuse_single_slot(bundle):
    """staging_slots=1 forces every padded dispatch through the SAME buffer:
    the fence wait is on the hot path of every call, and any torn rewrite
    would break parity on the repeated alternating batches."""
    sync, ov = _engines(bundle, slots=1)
    rng = np.random.RandomState(3)
    batches = [rng.normal(0, 1, (3, 24, 24, 3)).astype(np.float32) for _ in range(6)]
    refs = [sync.predict(x) for x in batches]
    # dispatch all, sync late: transfers from earlier calls overlap later
    # staging writes exactly as in the pipelined steady state
    handles = [ov.predict_async(x) for x in batches]
    for ref, h in zip(refs, handles):
        assert np.array_equal(h.result(), ref)


def test_overlap_parity_mixed_size_coalesced(bundle):
    """Mixed-size coalesced groups through the pipelined batcher with
    back-to-back runs enabled: every request's row matches the direct
    single-image reference bitwise."""
    sync, ov = _engines(bundle, image_sizes=(24, 32), fuse=(2,))
    ov.warmup()
    rng = np.random.RandomState(4)
    images = [rng.normal(0, 1, (s, s, 3)).astype(np.float32) for s in (24, 32) for _ in range(3)]
    refs = [sync.predict(img[None])[0] for img in images]
    b = PipelinedBatcher(ov, max_inflight=2, run_max=4, max_batch=4, max_wait_ms=5.0).start()
    try:
        futs = [b.submit(img) for img in images * 4]
        rows = [f.result(timeout=60) for f in futs]
    finally:
        b.stop()
    for i, row in enumerate(rows):
        assert np.array_equal(row, refs[i % len(refs)]), i


# ---------------------------------------------------------------------------
# slot lifecycle under concurrency
# ---------------------------------------------------------------------------


def test_slot_reuse_stress_40_clients(bundle):
    """40 concurrent clients through max_inflight=2 with a minimal slot
    pool: no torn batches (every row bitwise-correct for its input), no
    hangs (bounded future waits), clean drain (stop resolves everything)."""
    sync, ov = _engines(bundle, slots=2)
    ov.warmup()
    rng = np.random.RandomState(5)
    distinct = [rng.normal(0, 1, (24, 24, 3)).astype(np.float32) for _ in range(8)]
    refs = [sync.predict(img[None])[0] for img in distinct]
    b = PipelinedBatcher(
        ov, max_inflight=2, run_max=4, max_batch=4, max_wait_ms=1.0, queue_depth=1024
    ).start()
    errors: list = []
    lock = threading.Lock()

    def client(cid: int):
        try:
            for j in range(6):
                idx = (cid + j) % len(distinct)
                row = b.submit(distinct[idx]).result(timeout=120)
                if not np.array_equal(row, refs[idx]):
                    raise AssertionError(f"torn row for client {cid} req {j}")
        except Exception as e:  # noqa: BLE001 — surfaced below, the test must not hang
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), daemon=True) for i in range(40)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not any(t.is_alive() for t in threads), "a client hung"
    b.stop(drain=True)
    assert errors == [], errors[:3]


def test_dispatch_failure_orphans_slot_buffer(bundle):
    """An executable failure between the async device_put and fence arming
    must not return the slot to rotation with an unfenced, possibly
    in-transfer buffer (the next acquire would rewrite it unguarded): the
    buffer is orphaned — the in-flight transfer keeps the old memory, the
    slot gets fresh storage — and the engine keeps serving bitwise-correct
    answers."""
    sync, ov = _engines(bundle)
    rng = np.random.RandomState(10)
    x = rng.normal(0, 1, (3, 24, 24, 3)).astype(np.float32)
    ref = sync.predict(x)
    assert np.array_equal(ov.predict(x), ref)  # warm path, creates the pool
    key = (4, 24, 1)
    pool = ov._staging[key]
    bufs_before = [s.buf for s in pool.slots]
    ckey = ("default",) + key  # _compiled keys carry the model tenant
    exe = ov._compiled[ckey]

    class _Boom(RuntimeError):
        pass

    def failing_exe(params, xx):
        raise _Boom("injected dispatch failure")

    ov._compiled[ckey] = failing_exe
    with pytest.raises(_Boom):
        ov.predict(x)
    ov._compiled[ckey] = exe
    # exactly one slot was consumed by the failed dispatch: its buffer was
    # replaced (orphaned) and its fence left clear
    replaced = [i for i, s in enumerate(pool.slots) if s.buf is not bufs_before[i]]
    assert len(replaced) == 1
    assert pool.slots[replaced[0]].fence is None
    # the engine survives the failure and stays bitwise-correct, including
    # through the orphaned slot's replacement buffer
    for _ in range(len(pool.slots) + 1):
        assert np.array_equal(ov.predict(x), ref)


# ---------------------------------------------------------------------------
# sharded path: pinned copy semantics
# ---------------------------------------------------------------------------


def test_mesh_overlap_copy_semantics(bundle):
    """Overlap on a sharded engine must be inert: the staging buffer is
    snapshotted synchronously before shard_batch's device_put, so repeated
    padded dispatches with fresh data can never tear each other — and the
    sharded overlapped engine stays bitwise-identical to the sharded sync
    engine."""
    mesh = mesh_lib.make_mesh()
    common = dict(buckets=(8,), image_size=24, donate_input=False, mesh=mesh)
    m_sync = InferenceEngine(bundle, **common)
    m_ov = InferenceEngine(bundle, overlap_staging=True, staging_slots=2, **common)
    solo = InferenceEngine(bundle, buckets=(8,), image_size=24, donate_input=False)
    rng = np.random.RandomState(6)
    for n in (3, 5, 8):  # padded (slot-pool) and exact-fit batches
        x = rng.normal(0, 1, (n, 24, 24, 3)).astype(np.float32)
        ref = m_sync.predict(x)
        assert np.array_equal(m_ov.predict(x), ref), n
        # sharded == single-device within fp tolerance (the r04-era bar)
        np.testing.assert_allclose(solo.predict(x), ref, atol=1e-5, rtol=1e-5)
    # no fence was ever armed by the sharded path: every slot is free
    for pool in m_ov._staging.values():
        assert all(s.fence is None for s in pool.slots)


# ---------------------------------------------------------------------------
# back-to-back dispatch
# ---------------------------------------------------------------------------


class _SlowDispatchEngine:
    """Engine wrapper that delays dispatch slightly so the submit loop can
    outrun the collect thread — a deterministic way to saturate the queue
    on a 1-core test box."""

    def __init__(self, engine, delay_s=0.003):
        self._engine = engine
        self._delay_s = delay_s

    def predict(self, images, ctxs=None):
        return self._engine.predict(images, ctxs=ctxs)

    def predict_async(self, images, ctxs=None):
        import time

        time.sleep(self._delay_s)
        return self._engine.predict_async(images, ctxs=ctxs)


def test_back_to_back_runs_on_saturated_bucket(bundle):
    """Under saturation the collect thread dispatches runs: > 1 dispatch per
    completion wake-up, bounded by max_inflight, and every answer correct."""
    sync, ov = _engines(bundle)
    ov.warmup()
    reg = get_registry()
    h = reg.histogram("serve.dispatches_per_wakeup")
    count0, sum0, max_inflight = h.count, h.total, 2
    rng = np.random.RandomState(7)
    img = rng.normal(0, 1, (24, 24, 3)).astype(np.float32)
    ref = sync.predict(img[None])[0]
    b = PipelinedBatcher(
        _SlowDispatchEngine(ov), max_inflight=max_inflight, run_max=4,
        max_batch=4, max_wait_ms=1.0, queue_depth=256,
    ).start()
    try:
        futs = [b.submit(img) for _ in range(64)]
        rows = [f.result(timeout=120) for f in futs]
    finally:
        b.stop()
    assert all(np.array_equal(r, ref) for r in rows)
    wakeups = h.count - count0
    dispatches = h.total - sum0
    assert dispatches >= 16  # 64 requests / max_batch 4
    # the structural claim: fewer wake-ups than dispatches (runs formed)...
    assert dispatches / wakeups > 1.0, (dispatches, wakeups)
    # ...and the window still bounds every run
    assert h.vmax <= max_inflight


def test_run_max_1_is_per_batch(bundle):
    """run_max=1 (overlap off / legacy) never forms runs: every wake-up
    handles exactly one dispatch."""
    _, ov = _engines(bundle)
    ov.warmup()
    reg = get_registry()
    h = reg.histogram("serve.dispatches_per_wakeup")
    count0, sum0 = h.count, h.total
    rng = np.random.RandomState(8)
    img = rng.normal(0, 1, (24, 24, 3)).astype(np.float32)
    b = PipelinedBatcher(
        _SlowDispatchEngine(ov), max_inflight=2, run_max=1,
        max_batch=4, max_wait_ms=1.0, queue_depth=256,
    ).start()
    try:
        futs = [b.submit(img) for _ in range(32)]
        for f in futs:
            f.result(timeout=120)
    finally:
        b.stop()
    assert h.total - sum0 == h.count - count0  # every run a singleton


def test_dispatches_per_wakeup_counts_engine_pieces(bundle):
    """The metric counts engine dispatch PIECES, not predict_async handles:
    over any load, the histogram's observed sum equals the
    serve.dispatch_seconds.count delta (every piece attributed to exactly
    one completion wake-up). An oversized coalesced batch on a non-fused
    engine is one handle but several pieces — the handle count would
    under-report those wake-ups."""
    _, ov = _engines(bundle)  # no fuse ladder: oversized batches split per-chunk
    ov.warmup()
    reg = get_registry()
    h = reg.histogram("serve.dispatches_per_wakeup")
    sum0 = h.total
    d0 = reg.snapshot().get("serve.dispatch_seconds.count", 0)
    rng = np.random.RandomState(11)
    img = rng.normal(0, 1, (24, 24, 3)).astype(np.float32)
    b = PipelinedBatcher(ov, max_inflight=2, max_batch=8, max_wait_ms=20.0).start()
    try:
        futs = [b.submit(img) for _ in range(24)]
        for f in futs:
            f.result(timeout=120)
    finally:
        b.stop()
    pieces = reg.snapshot()["serve.dispatch_seconds.count"] - d0
    assert pieces >= 24 // 8  # 24 rows cannot fit fewer batches than that
    assert h.total - sum0 == pieces


def test_dispatches_per_wakeup_counts_ring_window_as_one_piece(bundle):
    """Ring-mode re-pin of the histogram-sum == dispatch-count invariant
    (serve/ring.py): a ring window — however many slots it consumed — is
    ONE engine piece (``handle.dispatches`` == 1, one
    ``serve.dispatch_seconds`` observation), so the equality holds with
    ring and per-batch dispatches mixed in one registry window, and a
    ring wake-up observes exactly 1 — the per-batch-mode [1, 2] contract
    bound (tests/test_bench_contract.py) deliberately does NOT apply to
    the ring arm, whose whole point is many batches per dispatch."""
    ringe = InferenceEngine(bundle, buckets=(2, 4), image_size=24,
                            overlap_staging=True, staging_slots=2, ring_slots=4)
    ringe.warmup()
    reg = get_registry()
    h = reg.histogram("serve.dispatches_per_wakeup")
    sum0 = h.total
    d0 = reg.snapshot().get("serve.dispatch_seconds.count", 0)
    r0 = reg.snapshot().get("serve.ring_dispatches", 0)
    rng = np.random.RandomState(12)
    img = rng.normal(0, 1, (24, 24, 3)).astype(np.float32)
    b = PipelinedBatcher(ringe, max_inflight=2, max_batch=8, max_wait_ms=20.0).start()
    try:
        # burst: enough queued rows for ring windows to form mid-stream
        futs = [b.submit(img) for _ in range(48)]
        for f in futs:
            f.result(timeout=120)
    finally:
        b.stop()
    snap = reg.snapshot()
    pieces = snap["serve.dispatch_seconds.count"] - d0
    assert snap.get("serve.ring_dispatches", 0) - r0 >= 1  # the ring really engaged
    assert h.total - sum0 == pieces  # a window = ONE piece, invariant intact


class _RecordingEngine:
    """Minimal engine protocol double recording dispatched batch sizes."""

    def __init__(self):
        self.batches: list[int] = []

    def predict_async(self, images):
        self.batches.append(int(images.shape[0]))
        n = int(images.shape[0])

        class _H:
            def result(self):
                return np.zeros((n, 4), np.float32)

        return _H()

    def predict(self, images):
        return self.predict_async(images).result()


def test_back_to_back_short_drain_lingers():
    """When the saturation signal (qsize) overstates what the drain finds
    (the stop sentinel inflates it; a concurrent stop() sweep can race it),
    the short batch must be topped up through the normal lingering path —
    not dispatched as a padded partial bucket with zero linger."""
    from yet_another_mobilenet_series_tpu.serve.batcher import _Request

    eng = _RecordingEngine()
    b = PipelinedBatcher(eng, max_inflight=2, run_max=4, max_batch=4, max_wait_ms=250.0)

    def mk():
        return _Request(np.zeros((8, 8, 3), np.float32), None)

    first = [mk() for _ in range(4)]
    b._q.put(mk())
    b._q.put(mk())  # the drain will come up 2 short of a full batch
    b._q.qsize = lambda: 4  # the overstated saturation signal
    late = mk()
    threading.Timer(0.01, lambda: b._q.put(late)).start()
    b._dispatch_batch(first)  # runs inline; threads are not started
    # the short drain lingered: the late request coalesced into the second
    # dispatch instead of being left behind a zero-linger partial bucket
    assert eng.batches == [4, 3]


def test_overlap_telemetry_counters(bundle):
    """The new instruments move: serve.h2d_seconds observes every staging
    transfer, serve.dispatched_bytes mirrors serve.dispatched_flops
    (cost-analysis join), and a padded dispatch through the pool leaves the
    fence armed until the next acquire."""
    _, ov = _engines(bundle)
    reg = get_registry()
    s0 = reg.snapshot()
    rng = np.random.RandomState(9)
    x = rng.normal(0, 1, (3, 24, 24, 3)).astype(np.float32)
    h = ov.predict_async(x)
    pool = ov._staging[(4, 24, 1)]
    assert any(s.fence is not None for s in pool.slots)  # armed at dispatch
    h.result()
    s1 = reg.snapshot()
    assert s1["serve.h2d_seconds.count"] - s0.get("serve.h2d_seconds.count", 0) == 1
    # CPU XLA reports cost_analysis bytes+flops, so both counters advance
    assert s1["serve.dispatched_bytes"] > s0.get("serve.dispatched_bytes", 0)
    assert s1["serve.dispatched_flops"] > s0.get("serve.dispatched_flops", 0)
