"""The driver-artifact contract for the benchmark entry points: exit 0 with
ONE parsed JSON line on stdout, structured error fields instead of stack
traces.

- bench.py (VERDICT r2 #1): against a dead/absent TPU tunnel it must emit a
  CPU fallback carrying fallback_from/tpu_error inside a driver-sized
  window. Rounds 1 and 2 shipped rc=1 and rc=124 artifacts; this pins the
  fix (the fast liveness probe) as a regression test rather than a one-off
  certification (PROFILE.md 'Round 3'). Slow (simulated probe timeout).
- scripts/serve_bench.py: the serving benchmark emits the same artifact
  shape (BENCH_SERVE_*.json — p50/p99 latency + QPS per batch bucket) and
  is fast enough to stay in the tier-1 gate via its tiny preset.
- scripts/train_chaos.py: the TRAINING chaos round (seeded corrupt records
  + one injected NaN step + a mid-epoch SIGTERM, then a resume) emits the
  same artifact shape; the contract check here is the kill-and-resume
  acceptance for the survivable-training PR.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_structural_sweep(sw, *, saturated=False, ring=False):
    """The structural-sweep contract (shared by the tiny fast run and the
    checked-in rehearsal artifacts): every serving structure present with
    sane instruments, bitwise parity across the whole ladder, the
    fused/overlapped modes halving dispatches/request vs chained, and — for
    the rehearsal artifacts (``saturated=True``) — the back-to-back claim:
    > 1 dispatch per completion wake-up on the saturated bucket, with the
    steady-state achieved-FLOPS window reported next to the single-dispatch
    reference. With ``ring=True`` (r12+ artifacts) the sweep also carries
    the ring arm: the deterministic one-dispatch window probe (R full slots
    == ONE serve.dispatch_seconds observation, bitwise, fill 1.0 >=
    min_fill), ring windows consumed under the driven burst, and the
    dispatches_per_wakeup [1, 2] per-batch bound deliberately NOT applied
    to the ring arm (a whole window is one engine piece — tests/
    test_overlap.py pins the histogram invariant). QPS magnitude is NOT
    asserted (1-core caveat, recorded)."""
    expect = {"sync", "pipelined", "fused", "overlapped"} | ({"ring"} if ring else set())
    assert set(sw["modes"]) == expect
    assert sw["bitwise_ok"], "structural ladder broke bitwise parity"
    assert sw["max_batch"] == 2 * sw["max_bucket"]
    assert sw["clients"] >= sw["max_batch"] and sw["requests_per_round"] >= sw["clients"]
    for mode, v in sw["modes"].items():
        assert v["qps"] > 0 and v["p99_ms"] > 0, (mode, v)
        assert len(v["qps_rounds"]) == sw["rounds"]
        assert v["p99_ms_registry"] >= v["p50_ms_registry"] > 0, (mode, v)
        assert v["dispatches_per_request"] > 0, (mode, v)
        # CPU XLA reports cost_analysis, so the efficiency window is real
        assert v["achieved_flops_per_s"] > 0 and v["dispatched_gflops"] > 0, (mode, v)
        assert v["dispatched_gbytes"] > 0, (mode, v)
    assert sw["modes"]["sync"]["dispatches_per_wakeup"] is None  # no completion thread
    for mode in ("pipelined", "fused"):
        # run_max=1: one handle per wake-up. The metric counts engine
        # dispatch PIECES, and a max_batch=2*cap coalesced batch decomposes
        # into at most 2 pieces (exactly 1 when the fused scan covers it),
        # so per-batch modes sit in [1, 2] — never the run depths back-to-
        # back produces
        assert 1.0 <= sw["modes"][mode]["dispatches_per_wakeup"] <= 2.0, mode
    # the structural dispatch claim: coalesced overflow rides the fused scan
    # (2 chunks -> 1 dispatch), halving dispatches/request vs chained
    for chained, fused in (("sync", "fused"), ("pipelined", "overlapped")):
        assert sw["modes"][fused]["dispatches_per_request"] <= (
            0.55 * sw["modes"][chained]["dispatches_per_request"]), (chained, fused)
    dpw = sw["modes"]["overlapped"]["dispatches_per_wakeup"]
    assert dpw is not None and dpw >= 1.0
    if saturated:
        assert dpw > 1.0, "back-to-back never engaged on the saturated bucket"
        assert sw["single_dispatch_achieved_flops_per_s"] > 0
    if ring:
        assert sw["ring_slots"] >= 2 and 0 < sw["ring_min_fill"] <= 1.0
        probe = sw["ring_probe"]
        # the tentpole's headline, registry-delta counted: a saturated
        # R-slot window ran as exactly ONE dispatch, bitwise, fully filled
        assert probe["slots"] == sw["ring_slots"]
        assert probe["rows"] == sw["ring_slots"] * sw["max_bucket"]
        assert probe["dispatch_seconds_count_delta"] == 1, probe
        assert probe["ring_dispatches_delta"] == 1, probe
        assert probe["bitwise_ok"], "ring window broke bitwise parity"
        assert probe["fill"] == 1.0 and probe["fill"] >= sw["ring_min_fill"]
        rv = sw["modes"]["ring"]
        # dpw stays reported for the ring arm but is NOT bounded by the
        # per-batch [1, 2] contract: ring windows count as one piece each,
        # so values below the per-batch regime are the point, not a bug
        assert rv["dispatches_per_wakeup"] is None or rv["dispatches_per_wakeup"] >= 1.0
        for mode in ("sync", "pipelined", "fused", "overlapped"):
            assert sw["modes"][mode]["ring_windows"] == 0, mode
            assert sw["modes"][mode]["ring_slots_per_window"] is None, mode
        if saturated:
            # the driven burst really rode the ring, with real coalescing
            assert rv["ring_windows"] > 0
            assert rv["ring_slots_per_window"] >= 1.0
    assert "cpu_rehearsal" in sw["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_fleet(fl, *, rehearsal=False, obs=True):
    """The --fleet contract (shared by the tiny fast run and the checked-in
    rehearsal artifacts): hedged-vs-unhedged on one seeded schedule with
    hedges fired and first-answer wins counted; a kill -9 round where
    completed + rejected accounts for EVERY submitted request (failed == 0,
    unresolved == 0 — no client ever hangs or sees the death) and the
    supervisor restarts the corpse; and an autoscaler trace bounded by
    [min, max] with cooldown respected. The rehearsal artifact additionally
    pins the diurnal shape — N rising under the peak and falling after —
    and the hedged tail beating the unhedged one. QPS magnitude is never
    asserted (1-core caveat, recorded in the artifact).

    ``obs`` gates the ISSUE-17 observability block (r10+; the archived r06
    artifact predates it): federated windowed p99 EXACTLY equal to the
    pooled per-replica reference, the scrape-overhead measurement, and the
    kill-chaos incident artifact."""
    assert fl["replicas"] >= 2
    assert fl["hedge_timer_ms"] is not None and fl["hedge_timer_ms"] > 0
    ab = fl["hedge_ab"]
    for mode in ("unhedged", "hedged"):
        r = ab[mode]
        assert r["unresolved"] == 0, f"{mode}: a client hung"
        assert r["submitted"] == r["completed"] + r["rejected"] + r["failed"], (mode, r)
        assert r["qps"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0, (mode, r)
    assert ab["unhedged"]["hedges"] == 0  # the control arm really was a control
    assert ab["hedged"]["hedges"] >= 1, "the straggler never triggered a hedge"
    assert 1 <= ab["hedged"]["hedge_wins"] <= ab["hedged"]["hedges"]
    # first-answer-wins is idempotent: losers' late answers are dropped and
    # COUNTED, never double-delivered (>= because a loser still inside its
    # stall when the delta is read is not yet counted)
    assert ab["hedged"]["hedge_wasted"] >= 1
    k = fl["kill"]
    assert k["chaos_kills"] == 1
    assert k["unresolved"] == 0 and k["failed"] == 0, k
    assert k["submitted"] == k["completed"] + k["rejected"], k
    assert k["restarts"] >= 1 and k["replicas_after_restart"] == fl["replicas"]
    if obs:
        o = fl["obs"]
        r = o["round"]
        assert r["unresolved"] == 0, "obs round: a client hung"
        assert r["submitted"] == r["completed"] + r["rejected"] + r["failed"], r
        # the headline: the federated windowed p99 (summed per-replica
        # bucket deltas) EQUALS the pooled reference recomputed by the
        # bench with independent delta math — lossless federation, so
        # equality is exact, not approximate
        assert o["p99_match"] is True
        assert o["federated_p99_ms"] == o["pooled_p99_ms"]
        assert o["federated_p99_ms"] > 0, "obs round produced no latency signal"
        assert o["federated_replicas"] == fl["replicas"]
        slo = o["slo"]
        assert slo["target_p99_ms"] > 0 and 0 < slo["error_budget"] < 1
        assert slo["burn_short"] >= 0 and slo["burn_long"] >= 0
        assert slo["ticks"] >= 1, "the SLO tracker never saw a scrape tick"
        # overhead is MEASURED and recorded; the <1% bound is a docs claim
        # for uncontended hardware, not an assertion on this shared core
        assert o["submit_p50_ms"] > 0 and o["submit_p50_ms_under_scrape"] > 0
        assert isinstance(o["federation_overhead_pct"], (int, float))
        assert o["scrape_mean_ms"] > 0
        assert o["amortized_overhead_pct"] >= 0
        # the kill-chaos round always pins a self-contained incident
        assert o["incident"] is not None and o["incident"].startswith("incident_")
        assert o["incident"].endswith(".json")
        assert o["incident_events"] >= 1, "the flight-recorder ring was empty"
        assert o["incident_has_fleet_snapshot"] is True
    a = fl["autoscale"]
    assert a["min_replicas"] >= 1 and a["max_replicas"] > a["min_replicas"]
    assert a["trace"], "autoscaler never ticked"
    assert all(a["min_replicas"] <= r["n"] <= a["max_replicas"] for r in a["trace"])
    assert all(r["action"] == "hold" for r in a["trace"] if r["in_cooldown"])
    assert a["cooldown_respected"]
    for p in a["phases"]:
        assert p["unresolved"] == 0, (p["phase"], "a client hung")
        assert p["submitted"] == p["completed"] + p["rejected"] + p["failed"], p
    if rehearsal:
        assert ab["hedged_tail_speedup"] is not None and ab["hedged_tail_speedup"] > 1.0
        assert a["n_peak"] > a["n_start"], "N never rose under the diurnal peak"
        assert a["n_end"] < a["n_peak"], "N never fell after the peak"
        assert any(r["action"] == "up" for r in a["actions"])
        assert any(r["action"] == "down" for r in a["actions"])
    assert "cpu_rehearsal" in fl["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_overload(ov, *, rehearsal=False):
    """The --overload contract (shared by the tiny fast run and the
    checked-in r08 rehearsal artifact): one seeded 3x-capacity open-loop
    storm played through both arms with per-class books balanced and ZERO
    unresolved futures (nobody ever hangs, storm or not); brownout-on beats
    brownout-off on interactive availability; the ladder steps up during
    the storm AND fully recovers to L0 after it, with door sheds counted;
    and the gray-failure round soft-ejects the latency-degraded (never
    crashing) replica within the window and shows the tail recovering
    after the ejection. Absolute capacity is never asserted (1-core
    caveat, recorded in the artifact)."""
    cap = ov["capacity"]
    assert cap["closed_loop_qps"] > 0 and cap["storm_qps"] > cap["closed_loop_qps"]
    assert cap["multiple"] >= 1.5 and cap["interactive_deadline_ms"] > 0
    storm = ov["storm"]
    for arm in ("off", "on"):
        rnd = storm[arm]
        assert rnd["unresolved"] == 0, f"{arm}: a client hung"
        for cls, s in rnd["classes"].items():
            assert s["submitted"] == s["completed"] + s["rejected"] + s["shed"] + s["failed"], (
                arm, cls, s)
            assert s["failed"] == 0, (arm, cls, s)  # overload is never an error
        assert sum(s["submitted"] for s in rnd["classes"].values()) == ov["requests"]
    # the headline: quality-for-goodput really bought interactive goodput
    assert storm["interactive_availability_on"] > storm["interactive_availability_off"]
    assert storm["off"]["shed_at_door_brownout"] == 0  # the control arm was a control
    assert storm["on"]["shed_at_door_brownout"] >= 1
    bo = storm["on"]["brownout"]
    assert bo["transitions_up"] >= 1, "the ladder never stepped up under the storm"
    assert 1 <= bo["peak_level"] <= 5
    assert bo["recovered_to_l0"] and bo["final_level"] == 0
    assert bo["transitions_up"] == bo["transitions_down"]  # every climb unwound
    assert bo["trace"], "controller never ticked"
    levels = [r["level"] for r in bo["trace"]]
    assert all(0 <= lv <= 5 for lv in levels)
    # one level per tick, up or down — the ladder is ordered, never a jump
    assert all(abs(b - a) <= 1 for a, b in zip(levels, levels[1:]))
    gray = ov["gray"]
    assert gray["replicas"] >= 2
    assert gray["unresolved"] == 0 and gray["failed"] == 0, gray
    assert gray["slow_ejections"] >= 1, "the gray replica was never soft-ejected"
    assert gray["time_to_eject_s"] is not None and 0 < gray["time_to_eject_s"] < 60
    assert gray["p99_ms_before_eject"] > 0 and gray["p99_ms_after_eject"] > 0
    if rehearsal:
        # the recovery claim with margin: post-eject tail well under the
        # straggler-poisoned one, and enough post-eject samples to mean it
        assert gray["tail_recovery"] is not None and gray["tail_recovery"] > 2.0
        assert gray["post_eject_samples"] >= 10
        assert gray["p99_ms_before_eject"] >= gray["straggler"]["latency_ms"]
    else:
        assert gray["tail_recovery"] is not None and gray["tail_recovery"] > 1.0
    assert "cpu_rehearsal" in ov["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_partition(pt, *, rehearsal=False):
    """The --partition contract (shared by the tiny fast run and the
    checked-in r09 rehearsal artifact): four socket-level fault rounds
    (blackhole / reset / half-open / flap) each with ZERO client-visible
    failures and zero unresolved futures (transport retry absorbs every
    partition shape), detection of the hard faults within the POLL-budget
    bound — eject_failures x (poll interval + connect-bounded poll read) +
    slack — and provably under the read timeout (the 60 s class of hang
    this PR removes), every ejection readmitted after the heal (no
    permanent capacity loss from a transient fault, no flap ping-pong),
    and the TTL-lease membership round removing a silently-vanished leased
    backend within TTL + one poll sweep while traffic keeps answering."""
    cfg = pt["config"]
    assert cfg["poll_interval_s"] > 0 and cfg["eject_failures"] >= 1
    assert 0 < cfg["connect_timeout_s"] < cfg["read_timeout_s"]
    assert pt["detect_bound_s"] > 0
    assert set(pt["rounds"]) == {"blackhole", "reset", "half_open", "flap"}
    for name, r in pt["rounds"].items():
        assert r["unresolved"] == 0, f"{name}: a client hung"
        assert r["failed"] == 0, f"{name}: client-visible failures under partition"
        assert r["submitted"] == r["completed"] + r["rejected"], (name, r)
        assert r["qps"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0, (name, r)
        # no permanent capacity loss from a transient fault: every ejection
        # the round caused was readmitted by round end
        assert r["routable_after"] == pt["replicas"], (name, r)
        assert r["ejections"] == r["readmissions"], (name, r)
    for shape in ("blackhole", "reset", "half_open"):
        r = pt["rounds"][shape]
        assert r["detection_s"] is not None and 0 < r["detection_s"] <= pt["detect_bound_s"], (
            shape, r["detection_s"], pt["detect_bound_s"])
        assert r["partition_ejections"] >= 1, f"{shape}: never attributed as a partition"
        assert r["recovery_s"] is not None and r["recovery_s"] < 30, (shape, r)
    # the headline claim: a blackholed replica ejects on the POLL budget,
    # not the read timeout (pre-split, detection == the read budget burn)
    assert pt["rounds"]["blackhole"]["detection_s"] < cfg["read_timeout_s"]
    # read-timeout-shaped legs (half-open) really re-routed instead of
    # 504ing: in-flight legs stall across the whole fault window, so at
    # least one retry is structural. (Reset legs can legitimately see zero
    # retries when poll-side detection ejects the victim before any pick
    # lands on it — its zero-failure book is the claim there.)
    assert pt["rounds"]["half_open"]["route_retries"] >= 1
    # flap must not permanently evict: bounded churn, full convergence
    # (routable_after + ejections == readmissions pinned above)
    m = pt["membership"]
    assert m["joined"], "the leased replica never joined via /register"
    assert m["unresolved"] == 0 and m["failed"] == 0, m
    assert m["registrations"] >= 1 and m["lease_renewals"] >= 1
    assert m["lease_expirations"] == 1, "the vanished lease never expired"
    assert m["removed_s"] is not None and 0 < m["removed_s"] <= m["removal_bound_s"], m
    assert m["total_after"] == pt["replicas"]
    if rehearsal:
        assert pt["replicas"] >= 3 and pt["requests_per_round"] >= 100
    assert "cpu_rehearsal" in pt["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_zoo(z, *, rehearsal=False):
    """The --zoo contract (shared by the tiny fast run and the checked-in
    r11 rehearsal artifact): a 2-replica model-sharded fleet serving an
    int8 small tier and an f32 big tier, three arms on ONE seeded trace.
    Pinned: big-only answers bitwise-match the explicit-pin references;
    the sharded arm shows ZERO misroutes (per-replica
    serve.model_requests deltas) and zero 5xx; the cascade arm escalates
    AND answers small (> 0 each), every answer bitwise-matches exactly one
    per-image reference with escalated answers EQUAL to the big-only
    arm's, and its dispatched-FLOPs/request mean sits STRICTLY below the
    big-only arm's. Latency magnitude is never asserted (1-core caveat,
    recorded in the artifact)."""
    assert z["replicas"] == 2
    m = z["models"]
    assert m["small"]["weights"] == "int8" and m["big"]["weights"] == "float32"
    # the tiers are distinct stamped identities (satellite: bundle identity)
    assert m["small"]["digest"] and m["big"]["digest"]
    assert m["small"]["digest"] != m["big"]["digest"]
    assert 0 < m["small"]["int8_top1"] <= 1.0
    assert len(z["placement"]) == 2
    assert sorted(v for vals in z["placement"].values() for v in vals) == ["big", "small"]
    assert 0.0 <= z["threshold"] <= 1.0
    assert z["margins"]["min"] <= z["margins"]["median"] <= z["margins"]["max"]
    arms = z["arms"]
    assert set(arms) == {"big_only", "sharded", "cascade"}
    for name, r in arms.items():
        assert r["unresolved"] == 0, f"{name}: a client hung"
        assert r["submitted"] == z["requests"], (name, r)
        assert r["submitted"] == r["completed"] + r["rejected"] + r["failed"], (name, r)
        assert r["qps"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0, (name, r)
        assert r["flops_per_request"] > 0, (name, r)
    assert arms["big_only"]["bitwise_match_big"] is True
    sh = arms["sharded"]
    # the headline placement claims: zero misroutes, zero 5xx, both tenants
    # exercised, every answer from the replica that serves its model
    assert sh["misroutes"] == 0
    assert sh["failed"] == 0 and sh["rejected"] == 0
    assert sh["mix"]["small"] >= 1 and sh["mix"]["big"] >= 1
    assert sh["mix"]["small"] + sh["mix"]["big"] == z["requests"]
    assert sh["bitwise_match"] is True
    assert set(sh["per_model"]) == {"small", "big"}
    for mdl, row in sh["per_model"].items():
        assert row["n"] == sh["mix"][mdl] and row["p99_ms"] >= row["p50_ms"] > 0
    ca = arms["cascade"]
    # the cascade split the trace: both outcomes populated, the counted
    # escalations equal the answers that bitwise-matched the big tier
    assert ca["escalations"] >= 1, "the cascade never escalated"
    assert ca["answered_small"] >= 1, "the cascade never answered small"
    assert ca["escalations"] + ca["answered_small"] == ca["completed"]
    assert 0.0 < ca["escalation_rate"] < 1.0
    assert ca["answer_mismatches"] == 0
    assert ca["escalated_bitwise_match_big_only"] is True
    assert ca["answers_big_bitwise"] + ca["answers_small_bitwise"] == ca["completed"]
    # the cost headline: the blended cascade cost beats all-big STRICTLY,
    # and the all-small shard mix is cheaper still (sanity on the proxy)
    cost = z["cost"]
    assert cost["cascade_flops_per_request"] < cost["big_only_flops_per_request"]
    assert cost["sharded_flops_per_request"] < cost["big_only_flops_per_request"]
    assert 0.0 < cost["cascade_vs_big_only"] < 1.0
    if rehearsal:
        # the checked-in artifact pins a real split (median-calibrated
        # threshold): a meaningful share of traffic stays on the small tier
        assert 0.2 <= ca["escalation_rate"] <= 0.8
        assert ca["deadline_skips"] == 0 and ca["escalation_failures"] == 0
    assert "cpu_rehearsal" in z["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_quant_ab(q):
    """The --quant contract (shared by the tiny fast run and the checked-in
    r07 rehearsal artifact): the three precision modes present with their
    quant_mode labels, the uint8 wire moving >= 3.5x fewer transferred
    bytes per request than the f32 wire (registry math — exactly 4x modulo
    nothing, on ANY host), the zero-mean denorm pinned BITWISE, the
    mean/std wire delta inside the configured atol, the int8 export's
    top-1 agreement over its gate with the resident-byte shrink recorded,
    and the CPU caveat explaining why QPS magnitude is not asserted."""
    assert set(q["modes"]) == {"f32", "uint8_wire", "int8"}
    assert q["modes"]["f32"]["quant_mode"] == "wire=float32,weights=float32"
    assert q["modes"]["uint8_wire"]["quant_mode"] == "wire=uint8,weights=float32"
    assert q["modes"]["int8"]["quant_mode"] == "wire=uint8,weights=int8"
    for m, v in q["modes"].items():
        assert v["h2d_bytes_per_request"] > 0, m
        assert v["dispatched_bytes_per_request"] > 0, m  # CPU XLA reports cost
    # the headline byte claim: per-request transferred bytes quarter
    assert q["wire_bytes_ratio"] >= 3.5
    assert q["modes"]["uint8_wire"]["h2d_bytes_per_request"] == (
        q["modes"]["int8"]["h2d_bytes_per_request"])  # same u8 wire
    # wire bytes are exact registry math: cap * S * S * 3 * width
    f32_per_req = q["modes"]["f32"]["h2d_bytes_per_request"]
    assert f32_per_req == 4 * q["modes"]["uint8_wire"]["h2d_bytes_per_request"]
    p = q["parity"]
    assert p["identity_norm_bitwise"] is True  # the 'fold is exact' regime
    assert p["wire_parity_ok"] and p["wire_max_abs_logit_delta"] <= p["wire_atol"]
    assert p["int8_top1_agreement_calib"] >= p["int8_top1_min"]
    assert p["int8_top1_agreement_heldout"] >= p["int8_top1_min"]
    x = q["int8_export"]
    assert x["quantized_tensors"] >= 5
    assert x["resident_shrink"] > 2.0  # int8 weights + f32 biases/scales/SE
    assert x["bytes_int8"] < x["bytes_f32"]
    assert x["calib_images"] >= 16
    for row in q["per_bucket"]:
        for m in q["modes"]:
            assert row[f"qps_{m}"] > 0 and row[f"p99_ms_{m}"] >= row[f"p50_ms_{m}"] > 0, (m, row)
    assert "cpu_rehearsal" in q["cpu_rehearsal_note"]  # the caveat is recorded


def _assert_fused_ab(fz):
    """The chained-vs-fused A/B contract (shared by the tiny fast run and
    the checked-in r04 rehearsal artifact): one row per ladder K plus one
    off-ladder K, bitwise parity everywhere, and the STRUCTURAL claim —
    dispatches per request is exactly 1 for on-ladder K (vs K chained) and
    strictly fewer than chained for the off-ladder decomposition. Speedup
    magnitude is NOT asserted: on the 1-core rehearsal box it may be ~flat,
    and the artifact must record that caveat the way r02 did."""
    assert fz["ladder"] and fz["max_bucket"] >= 1
    assert fz["off_ladder_k"] not in fz["ladder"]
    assert [r["k"] for r in fz["per_k"]] == fz["ladder"] + [fz["off_ladder_k"]]
    for r in fz["per_k"]:
        assert r["bitwise_ok"], r
        assert r["rows"] == r["k"] * fz["max_bucket"]
        assert r["p99_ms_chained"] >= r["p50_ms_chained"] > 0
        assert r["p99_ms_fused"] >= r["p50_ms_fused"] > 0
        assert r["qps_chained"] > 0 and r["qps_fused"] > 0
        assert r["dispatches_per_request_chained"] == r["k"]
        if r["on_ladder"]:
            assert r["dispatches_per_request_fused"] == 1, r
        else:
            assert 1 <= r["dispatches_per_request_fused"] < r["dispatches_per_request_chained"], r
        assert r["fused_speedup"] == pytest.approx(r["qps_fused"] / r["qps_chained"], rel=1e-3)
    assert fz["peak_speedup"] == max(r["fused_speedup"] for r in fz["per_k"])
    assert "cpu_rehearsal" in fz["cpu_rehearsal_note"]  # the caveat is recorded


@pytest.mark.slow
def test_bench_dead_tunnel_emits_parsed_cpu_fallback():
    # clean env: conftest.py mutates JAX_PLATFORMS/XLA_FLAGS for the pytest
    # process (8 fake CPU devices), which must NOT leak into bench.py — it
    # would 8x the fallback batch and, without the sitecustomize override,
    # flip the probe into the not-tpu branch instead of the dead-tunnel one
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split() if "xla_force_host_platform_device_count" not in f
    )
    env.update({
        # a 3 s probe kill simulates the dead tunnel without burning the
        # real 150 s window; the CPU fallback path below it is the real one
        "BENCH_PROBE_TIMEOUT_S": "3",
        "BENCH_CPU_WORKER_TIMEOUT_S": "420",
        # if the probe ever fast-fails instead of hanging, the TPU worker
        # ladder must stay inside this test's 600 s budget too
        "BENCH_WORKER_TIMEOUT_S": "30",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "mobilenet_v3_large_train_images_per_sec_per_chip"
    assert out["value"] is not None and out["value"] > 0
    assert out["unit"] == "images/sec/chip"
    assert out["vs_baseline"] is None  # no real reference divisor exists
    assert out["fallback_from"] == "tpu"
    # branch-agnostic: probe timeout, probe-found-cpu, or worker-ladder
    # failure all must surface a non-empty diagnostic
    assert out["tpu_error"]
    assert out["platform"] == "cpu"
    # the fallback must carry the repo's best-known real-TPU number with
    # provenance (VERDICT r3 #3) — BENCH_TPU_r2.json ships in-repo, so
    # last_tpu can never legitimately be absent
    # contract, not magnitude: a newer (possibly smaller-batch) round
    # artifact becoming the glob winner must not fail this test
    last = out["last_tpu"]
    assert last["value"] > 0 and last["device_kind"]
    assert last["source"].startswith("BENCH_TPU_r") and last["measured_date"]


def test_serve_bench_emits_parsed_artifact(tmp_path):
    """scripts/serve_bench.py: exactly one JSON line, bench.py artifact
    shape, p50/p99/QPS per (bucket, image_size) plus the sync-vs-pipelined
    and fp32-vs-bf16 A/B sections — the BENCH_SERVE_* contract."""
    out_path = tmp_path / "BENCH_SERVE_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--arch", "tiny", "--image-sizes", "24,32", "--buckets", "2,4", "--iters", "3",
         "--concurrent-iters", "2", "--ab-iters", "2", "--fused", "--fused-iters", "3",
         "--structural", "--structural-rounds", "2",
         "--quant", "--quant-iters", "2", "--quant-rounds", "2",
         "--chaos-requests", "40", "--chaos-fault-rate", "0.3", "--out", str(out_path)],
        capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "tiny_serve_images_per_sec"
    assert "error" not in out, out.get("error")
    assert out["value"] is not None and out["value"] > 0
    assert out["unit"] == "images/sec"
    assert out["vs_baseline"] is None  # no serving reference divisor exists
    assert out["platform"]
    # the shared provenance stamp (bench.py): every bench artifact is
    # version/hardware attributable
    prov = out["provenance"]
    assert prov["jax_version"] and prov["jaxlib_version"] and prov["python"]
    assert prov["platform"] == out["platform"]
    assert prov["cpu_rehearsal"] == (out["platform"] == "cpu")
    assert out["image_sizes"] == [24, 32]
    # direct rows: one per (bucket, image_size), latency quantiles ordered
    assert [(r["batch"], r["image_size"]) for r in out["buckets"]] == [
        (2, 24), (4, 24), (2, 32), (4, 32)]
    for r in out["buckets"]:
        assert r["qps"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0
        # the same window's quantiles from the registry's bucketed histogram
        # math (serve.run_seconds deltas) — the bench must report what
        # /metrics scrapes, not only its own percentile-of-a-list
        assert r["p99_ms_registry"] >= r["p95_ms_registry"] >= r["p50_ms_registry"] > 0
    # whole-run registry quantile snapshot: every serving histogram that saw
    # data carries the p50/p95/p99 columns obs_registry.json and /varz expose
    rq = out["registry_quantiles"]
    assert "serve.run_seconds" in rq and "serve.batch_size" in rq
    for name, v in rq.items():
        assert v["count"] > 0, name
        assert v["p99"] >= v["p95"] >= v["p50"] >= 0, (name, v)
    # concurrent-submit A/B: sync and pipelined QPS per (bucket, size); no
    # ordering assertion on magnitude — the tiny preset's sub-ms executables
    # are noise-dominated, the checked-in rehearsal artifact pins the win
    assert [(r["batch"], r["image_size"]) for r in out["concurrent"]] == [
        (2, 24), (4, 24), (2, 32), (4, 32)]
    for r in out["concurrent"]:
        assert r["qps_sync"] > 0 and r["qps_pipelined"] > 0
        assert r["requests"] >= r["clients"] >= 1
        assert r["pipelined_speedup"] == pytest.approx(r["qps_pipelined"] / r["qps_sync"], rel=1e-3)
    ab = out["ab"]["pipelined_vs_sync"]
    assert ab["peak_qps_pipelined"] == max(r["qps_pipelined"] for r in out["concurrent"])
    assert ab["peak_qps_sync"] == max(r["qps_sync"] for r in out["concurrent"])
    # fp32-vs-bf16 A/B: per-bucket QPS pairs + the measured parity delta
    # judged against the engine's pinned tolerance
    bf = out["ab"]["bf16_vs_fp32"]
    assert [r["batch"] for r in bf["buckets"]] == [2, 4]
    for r in bf["buckets"]:
        assert r["qps_bf16"] > 0 and r["qps_fp32"] > 0
    assert bf["peak_qps_bf16"] > 0 and bf["peak_qps_fp32"] > 0
    assert bf["max_abs_logit_delta"] >= 0
    assert bf["parity_ok"] and bf["max_abs_logit_delta"] <= bf["parity_atol"]
    _assert_fused_ab(out["ab"]["fused_vs_chained"])
    # quantized-serving A/B: the three precision modes with the exact
    # transferred-byte quartering and all parity verdicts (the r07 shape)
    _assert_quant_ab(out["ab"]["quant"])
    # structural sweep: the five serving structures interleaved; the tiny
    # preset pins structure + invariants only — including the deterministic
    # ring one-dispatch probe, which is NOT timing-dependent — while the
    # checked-in rehearsal artifacts pin the driven saturation claims
    # (dispatches_per_wakeup > 1 in r05, ring windows consumed in r12)
    _assert_structural_sweep(out["ab"]["structural_sweep"], ring=True)
    # chaos A/B: open-loop Poisson rounds with mixed priorities/sizes — the
    # books must balance per class and NOTHING may hang (unresolved == 0);
    # the healthy round must be failure-free (injected-fault counts are
    # dispatch-granular and timing-dependent under coalescing, so the tiny
    # preset pins structure + invariants; the checked-in r03 rehearsal pins
    # the measured retry/injection accounting)
    chaos = out["chaos"]
    assert chaos["requests"] == 40 and chaos["target_qps"] > 0
    assert set(chaos["class_mix"]) == {"interactive", "batch", "best_effort"}
    for round_name in ("healthy", "faulty"):
        rnd = chaos[round_name]
        assert rnd["unresolved"] == 0, f"{round_name}: a client hung"
        submitted = 0
        for cls, s in rnd["classes"].items():
            assert s["submitted"] == s["completed"] + s["rejected"] + s["shed"] + s["failed"], (
                round_name, cls, s)
            submitted += s["submitted"]
            if s["completed"]:
                assert s["p99_ms"] >= s["p50_ms"] > 0
                # per-class registry window quantiles ride every chaos row
                reg_q = s["registry_quantiles"]
                assert reg_q["count"] >= 1
                assert reg_q["p99_ms"] >= reg_q["p95_ms"] >= reg_q["p50_ms"] > 0
        assert submitted == chaos["requests"]
        assert rnd["qps"] > 0
    healthy = chaos["healthy"]
    assert healthy["injected_failures"] == 0 and healthy["breaker_opens"] == 0
    assert all(s["failed"] == 0 for s in healthy["classes"].values())
    faulty = chaos["faulty"]
    assert chaos["fault"]["failure_rate"] == 0.3
    # arrival-time rejection causes decompose the total
    for rnd in (healthy, faulty):
        assert rnd["rejected_total"] == (
            rnd["rejected_deadline"] + rnd["rejected_class_full"]
            + rnd["rejected_breaker"] + rnd["rejected_queue_full"])
    # the headline value is the overall peak across direct + concurrent
    assert out["value"] == out["peak_qps"] >= max(r["qps"] for r in out["buckets"])
    # --out writes the same artifact for the driver to collect
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_fleet_emits_parsed_artifact(tmp_path):
    """scripts/serve_bench.py --fleet: a REAL 2-replica fleet (cli/serve.py
    subprocesses behind the router tier) driven through the hedge A/B, the
    kill -9 availability round, and the autoscaler's diurnal schedule —
    one JSON line in the bench artifact shape, the r06 contract."""
    out_path = tmp_path / "BENCH_SERVE_fleet_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--fleet", "--arch", "tiny", "--image-sizes", "24", "--buckets", "1,4",
         "--fleet-requests", "24", "--fleet-phase-s", "3,10,7",
         "--out", str(out_path)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "tiny_fleet_requests_per_sec"
    assert "error" not in out, out.get("error")
    assert out["unit"] == "requests/sec" and out["vs_baseline"] is None
    prov = out["provenance"]
    assert prov["jax_version"] and prov["platform"] == out["platform"]
    # structure + invariants on the tiny run (the checked-in r06 rehearsal
    # additionally pins the diurnal rise/fall and the hedged-tail win)
    _assert_fleet(out["fleet"])
    assert out["value"] == out["fleet"]["hedge_ab"]["unhedged"]["qps"] > 0
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_overload_emits_parsed_artifact(tmp_path):
    """scripts/serve_bench.py --overload: the brownout A/B on one seeded
    3x-capacity storm (paced engine, in-process) plus the gray-failure
    fleet round (real replica subprocesses, latency-based soft ejection) —
    one JSON line in the bench artifact shape, the r08 contract."""
    out_path = tmp_path / "BENCH_SERVE_overload_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--overload", "--arch", "tiny", "--image-sizes", "24", "--buckets", "1,4",
         "--overload-storm-s", "3", "--overload-gray-requests", "48",
         "--out", str(out_path)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "tiny_overload_interactive_availability"
    assert "error" not in out, out.get("error")
    assert out["unit"] == "completed/submitted" and out["vs_baseline"] is None
    prov = out["provenance"]
    assert prov["jax_version"] and prov["platform"] == out["platform"]
    _assert_overload(out["overload"])
    assert out["value"] == out["overload"]["storm"]["interactive_availability_on"] > 0
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_partition_emits_parsed_artifact(tmp_path):
    """scripts/serve_bench.py --partition: seeded socket-level partition
    rounds (netchaos proxies between an in-process router and echo
    replicas — jax-free by design, the measurement is the TRANSPORT) plus
    the TTL-lease membership round — one JSON line in the bench artifact
    shape, the r09 contract."""
    out_path = tmp_path / "BENCH_SERVE_partition_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--partition", "--partition-replicas", "2", "--partition-requests", "40",
         "--partition-qps", "20", "--out", str(out_path)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "partition_blackhole_detect_seconds"
    assert "error" not in out, out.get("error")
    assert out["unit"] == "seconds" and out["vs_baseline"] is None
    # jax-free: provenance via importlib.metadata, cpu_rehearsal pinned by
    # the caller (no backend was ever touched)
    prov = out["provenance"]
    assert prov["jax_version"] and prov["cpu_rehearsal"] is True
    assert "platform" not in prov and out["platform"] == "cpu"
    _assert_partition(out["partition"])
    assert out["value"] == out["partition"]["rounds"]["blackhole"]["detection_s"] > 0
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_zoo_emits_parsed_artifact(tmp_path):
    """scripts/serve_bench.py --zoo: a REAL 2-replica model-sharded fleet
    (slot 0 int8 small tier, slot 1 f32 big tier via per-slot
    serve.zoo.models assignments) driven through the big-only, sharded,
    and confidence-cascade arms on one seeded trace — one JSON line in
    the bench artifact shape, the r11 contract."""
    out_path = tmp_path / "BENCH_SERVE_zoo_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_bench.py"),
         "--zoo", "--arch", "tiny", "--image-sizes", "24", "--buckets", "1",
         "--zoo-requests", "16", "--out", str(out_path)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "tiny_zoo_cascade_flops_vs_big_only"
    assert "error" not in out, out.get("error")
    assert out["unit"] == "cascade/big_only dispatched-FLOPs per request"
    assert out["vs_baseline"] is None
    prov = out["provenance"]
    assert prov["jax_version"] and prov["platform"] == out["platform"]
    _assert_zoo(out["zoo"])
    assert out["value"] == out["zoo"]["cost"]["cascade_vs_big_only"]
    assert 0.0 < out["value"] < 1.0
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_r11_zoo_rehearsal_artifact():
    """The r11 cpu_rehearsal artifact pins the multi-model zoo acceptance
    (ISSUE 18): on a live model-sharded fleet the sharded arm routes with
    ZERO misroutes and zero 5xx, the cascade escalates a real share of
    the trace (median-calibrated threshold) with every escalated answer
    bitwise-identical to the big-only arm's, and the cascade's
    dispatched-FLOPs/request mean sits strictly below big-only — the
    serving-cost claim the zoo exists for. Latency magnitude is the
    deferred accelerator measurement; the caveat is recorded in the
    artifact — r02..r10 discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r11_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_zoo(out["zoo"], rehearsal=True)
    assert out["value"] == out["zoo"]["cost"]["cascade_vs_big_only"]
    assert 0.0 < out["value"] < 1.0
    # the rehearsal trace is long enough for the split to be meaningful
    assert out["zoo"]["requests"] >= 32


def test_serve_bench_r09_partition_rehearsal_artifact():
    """The r09 cpu_rehearsal artifact pins the partition-containment
    acceptance (ISSUE 15): under a seeded blackhole through the netchaos
    proxy the router ejects the partitioned replica within the poll-budget
    bound (NOT the read timeout), with zero client-visible failures in
    every fault round (transport retry onto healthy replicas), full
    readmission after every heal, and lease expiry removing a silently-
    vanished backend within TTL + one poll sweep. Absolute rates are the
    deferred real-multi-host measurement; the caveat is recorded in the
    artifact — r02..r08 discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r09_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_partition(out["partition"], rehearsal=True)
    # the rehearsal artifact additionally pins the margin: blackhole
    # detection at least 2x under the read timeout the split removes from
    # the failure path
    pt = out["partition"]
    assert pt["rounds"]["blackhole"]["detection_s"] <= 0.5 * pt["config"]["read_timeout_s"]


def test_serve_bench_r08_overload_rehearsal_artifact():
    """The r08 cpu_rehearsal artifact pins the brownout + gray-failure
    acceptance: under the SAME seeded 3x-capacity storm the ladder arm
    completes a strictly larger share of interactive traffic than the
    control arm (quality traded for goodput at the door, with Retry-After),
    the ladder climbs during the storm and walks all the way back to L0
    after it (up-count == down-count, one level per transition), zero
    futures unresolved in either arm, and the latency-degraded never-
    crashing replica is soft-ejected within the window with the fleet tail
    recovering afterwards. Absolute capacity is the deferred accelerator
    measurement; the caveat is recorded in the artifact — r02..r07
    discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r08_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_overload(out["overload"], rehearsal=True)
    # the rehearsal artifact additionally pins a MATERIAL availability win,
    # not a statistical sliver
    storm = out["overload"]["storm"]
    assert storm["interactive_availability_on"] >= 2.0 * storm["interactive_availability_off"]


def test_serve_bench_r07_quant_rehearsal_artifact():
    """The r07 cpu_rehearsal artifact pins the quantized-serving acceptance:
    per-request serve.h2d_bytes on the uint8 wire >= 3.5x lower than the
    f32 wire (registry math, host-independent — measured exactly 4x), the
    zero-mean denorm BITWISE-identical to the f32 wire, the mean/std wire
    delta recorded under the configured atol, and the int8 export's top-1
    agreement over its gate with scales + calibration provenance
    accounted. QPS magnitude between modes is the deferred accelerator
    measurement; the caveat is recorded in the artifact — r02/r04/r05
    discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r07_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_quant_ab(out["ab"]["quant"])
    # the rehearsal artifact additionally pins the exact quartering and a
    # realistic (224px-scale) per-request byte magnitude
    q = out["ab"]["quant"]
    assert q["wire_bytes_ratio"] == 4.0
    assert q["modes"]["f32"]["h2d_bytes_per_request"] >= 4 * q["image_size"] ** 2 * 3


def test_serve_bench_r06_fleet_rehearsal_artifact():
    """The r06 cpu_rehearsal artifact pins the fleet acceptance: the hedged
    round beats the unhedged tail on the shared seeded schedule (hedges
    fired at the measured p-quantile timer, first answer wins), the kill -9
    round accounts for every submitted request as completed+rejected with
    nothing hanging and the replica restarted, and the autoscaler trace
    rises and falls across the diurnal schedule with cooldown respected.
    Absolute throughput is the deferred accelerator measurement; the caveat
    is recorded in the artifact — r02/r04/r05 discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r06_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    # archived artifact from before the observability round existed
    _assert_fleet(out["fleet"], rehearsal=True, obs=False)


def test_serve_bench_r10_fleet_obs_rehearsal_artifact():
    """The r10 cpu_rehearsal artifact pins the fleet-observability
    acceptance on top of the r06 fleet contract: the federated windowed
    p99 (per-replica histogram bucket deltas summed by obs/fleet.py)
    EXACTLY equals the pooled reference the bench recomputes with
    independent reset-aware delta math from the same scraped /varz
    documents; the scrape-under-load overhead measurement is recorded
    (magnitude is a docs claim — on this 1-core box scraper and submitter
    share the core, so the number is an upper bound); and the kill -9
    chaos round dumped a self-contained ``incident_*.json`` (event ring +
    federated fleet snapshot + last per-replica /varz)."""
    with open(os.path.join(REPO, "BENCH_SERVE_r10_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_fleet(out["fleet"], rehearsal=True)


def test_train_chaos_emits_parsed_artifact(tmp_path):
    """scripts/train_chaos.py: exactly one JSON line, bench artifact shape,
    and the survivable-training acceptance inside it — the chaos round
    skipped injected corrupt records and the NaN step (counted, bounded),
    the SIGTERM produced a clean exit with a synchronous checkpoint and a
    resume marker, and the resume round continued FROM THE KILLED STEP (no
    restart-from-zero) through to completion with a sane loss."""
    out_path = tmp_path / "TRAIN_CHAOS_test.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "train_chaos.py"),
         "--log-dir", str(tmp_path / "run"), "--out", str(out_path)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "train_chaos_recovered_steps"
    assert "error" not in out, out.get("error")
    assert out["value"] is not None and out["value"] > 0
    assert out["unit"] == "steps" and out["vs_baseline"] is None
    # provenance stamped WITHOUT importing jax in the parent (versions via
    # importlib.metadata; cpu_rehearsal pinned by the caller)
    prov = out["provenance"]
    assert prov["jax_version"] and prov["cpu_rehearsal"] is True
    assert "platform" not in prov  # the parent never touched a backend

    chaos, resume = out["chaos"], out["resume"]
    # preemption: clean exit, marker written, one preemption counted
    assert chaos["exit_code"] == 0 and chaos["preemptions"] == 1
    assert chaos["killed_step"] > 0 and chaos["reason"] == "SIGTERM"
    # chaos bookkeeping: the injected corrupt records were skipped AND
    # counted by the resilience wrapper; the injected NaN step was skipped
    # AND counted by the guard — and neither exhausted its budget
    assert chaos["injected_corrupt"] >= 1
    assert chaos["corrupt_records"] >= chaos["injected_corrupt"]
    assert chaos["injected_nan_steps"] == 1
    assert chaos["skipped_steps"] >= 1 and chaos["nonfinite_events"] >= 1
    assert not chaos["health_abort"]
    # resume: continues from the preemption checkpoint, not from zero
    assert resume["exit_code"] == 0
    assert resume["resumed_step"] == chaos["killed_step"] > 0
    assert resume["marker_consumed"]
    assert resume["final_step"] > resume["resumed_step"]
    # loss trajectory continuity: the first post-resume loss stays in the
    # same regime as the pre-kill loss (no re-init cliff, no blowup)
    assert resume["loss_after_resume"] is not None and chaos["loss_before_kill"] is not None
    assert resume["loss_after_resume"] < 3.0 * max(chaos["loss_before_kill"], 0.1)
    # --out writes the same artifact for the driver to collect
    assert json.loads(out_path.read_text()) == out


def test_serve_bench_r03_chaos_rehearsal_artifact():
    """The r03 cpu_rehearsal artifact pins the chaos A/B acceptance: a
    healthy open-loop Poisson round and a seeded 5%-fault round over mixed
    priorities, per-class accounting balanced, nothing unresolved, retries
    absorbing injected failures, and the faulty round still serving (the
    resilience edge degrades gracefully instead of collapsing)."""
    with open(os.path.join(REPO, "BENCH_SERVE_r03_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    chaos = out["chaos"]
    assert chaos["fault"]["failure_rate"] == 0.05
    for round_name in ("healthy", "faulty"):
        rnd = chaos[round_name]
        assert rnd["unresolved"] == 0, f"{round_name}: a request hung"
        submitted = 0
        for cls, s in rnd["classes"].items():
            assert s["submitted"] == s["completed"] + s["rejected"] + s["shed"] + s["failed"], (
                round_name, cls, s)
            submitted += s["submitted"]
        assert submitted == chaos["requests"]
        assert rnd["rejected_total"] == (
            rnd["rejected_deadline"] + rnd["rejected_class_full"]
            + rnd["rejected_breaker"] + rnd["rejected_queue_full"])
        assert rnd["qps"] > 0
    healthy, faulty = chaos["healthy"], chaos["faulty"]
    assert healthy["injected_failures"] == 0
    assert all(s["failed"] == 0 for s in healthy["classes"].values())
    # the faulty round really injected faults, and the edge responded:
    # every injected failure was retried or surfaced typed — and the
    # service kept serving a comparable share of the load
    assert faulty["injected_failures"] >= 1
    assert faulty["retries"] >= 1
    total_completed = {
        r: sum(s["completed"] for s in chaos[r]["classes"].values())
        for r in ("healthy", "faulty")
    }
    assert total_completed["faulty"] >= 0.5 * total_completed["healthy"]
    # per-class latency quantiles exist for every class that completed work
    for rnd in (healthy, faulty):
        for cls, s in rnd["classes"].items():
            if s["completed"]:
                assert s["p99_ms"] >= s["p50_ms"] > 0, (cls, s)


def test_serve_bench_r04_fused_rehearsal_artifact():
    """The r04 cpu_rehearsal artifact pins the fused-dispatch acceptance:
    whole requests of K max-bucket chunks served in ONE dispatch for
    on-ladder K (vs K chained dispatches), bitwise-identical logits, the
    off-ladder K decomposing into fewer dispatches than chained — and the
    1-core caveat recorded in the artifact (speedup may be ~flat there; the
    dispatch-count drop is the structural win, the throughput claim is the
    ROADMAP hardware rung), exactly the r02 caveat discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r04_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    _assert_fused_ab(out["ab"]["fused_vs_chained"])


def test_serve_bench_r05_structural_rehearsal_artifact():
    """The r05 cpu_rehearsal artifact pins the overlapped-staging /
    device-resident acceptance: the four-structure interleaved sweep with
    bitwise parity across the whole ladder, fused/overlapped halving
    dispatches per request, back-to-back dispatch REALLY engaging on the
    saturated bucket (serve.dispatches_per_wakeup > 1 — the structural
    claim a 1-core box CAN pin), and the steady-state achieved-FLOPS window
    reported next to the single-dispatch reference. Throughput magnitude is
    the deferred accelerator measurement; the caveat is recorded in the
    artifact, r02/r04 discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r05_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_structural_sweep(out["ab"]["structural_sweep"], saturated=True)
    # whole-run registry-math quantiles ride the artifact like every round
    rq = out["registry_quantiles"]
    assert "serve.run_seconds" in rq and "serve.h2d_seconds" in rq
    assert "serve.dispatches_per_wakeup" in rq


def test_serve_bench_r12_ring_rehearsal_artifact():
    """The r12 cpu_rehearsal artifact pins the device-resident request-ring
    acceptance: the five-structure interleaved sweep (r05's four + the ring
    arm) with bitwise parity everywhere, the deterministic one-dispatch
    probe — a saturated window of R full max-bucket slots registry-counted
    as exactly ONE serve.dispatch_seconds observation at fill 1.0 >=
    min_fill, bitwise vs the per-batch path — and ring windows REALLY
    consumed under the driven burst (serve.ring_dispatches > 0 with real
    slot coalescing). The per-batch dispatches_per_wakeup [1, 2] bound is
    deliberately not applied to the ring arm (one window == one piece).
    Throughput magnitude is the deferred accelerator measurement (ROADMAP
    item 2's hardware rung); the standing 1-core caveat is recorded in the
    artifact, r02/r04/r05 discipline."""
    with open(os.path.join(REPO, "BENCH_SERVE_r12_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    assert out["value"] is not None and out["value"] > 0
    prov = out["provenance"]
    assert prov["cpu_rehearsal"] is True and prov["jax_version"]
    _assert_structural_sweep(out["ab"]["structural_sweep"], saturated=True, ring=True)
    rq = out["registry_quantiles"]
    assert "serve.run_seconds" in rq and "serve.h2d_seconds" in rq
    assert "serve.ring_slots_per_dispatch" in rq


def test_serve_bench_checked_in_rehearsal_artifact():
    """The r02 cpu_rehearsal artifact carries the acceptance deltas with
    per-round transparency. What it can honestly pin on THIS rehearsal box:
    the box is single-core, so host staging/collect work and XLA "device"
    compute share one core — overlap cannot add throughput there (a direct
    experiment measured ~5% cache/context interleave tax on overlapped
    staging), and a phase-clean sync cycle is work-conserving-optimal. The
    invariant pinned here is therefore NO REGRESSION: the pipelined path
    stays within the artifact's own recorded round spread of sync on every
    bucket, with full buckets (no padded-fill collapse) and a
    within-tolerance bf16 parity delta. The actual speedup claim is a
    hardware measurement (ROADMAP serving rung): on an accelerator the
    host work this PR moves off the critical path is pure win."""
    with open(os.path.join(REPO, "BENCH_SERVE_r02_cpu_rehearsal.json")) as f:
        out = json.load(f)
    assert out["platform"] == "cpu" and "error" not in out
    for r in out["concurrent"]:
        # within the observed per-round spread of the sync mode itself
        spread = (max(r["qps_rounds_sync"]) - min(r["qps_rounds_sync"])) / r["qps_sync"]
        floor = 1.0 - max(spread, 0.05)
        assert r["qps_pipelined"] >= floor * r["qps_sync"], (r, floor)
        # batching policy held: no partial-fill collapse in either mode
        assert r["avg_fill_sync"] >= 0.9 and r["avg_fill_pipelined"] >= 0.9, r
        assert len(r["qps_rounds_sync"]) == len(r["qps_rounds_pipelined"]) == r["rounds"]
    ab = out["ab"]["pipelined_vs_sync"]
    assert ab["peak_qps_pipelined"] >= 0.9 * ab["peak_qps_sync"]
    bf = out["ab"]["bf16_vs_fp32"]
    assert bf["parity_ok"] and bf["max_abs_logit_delta"] <= bf["parity_atol"]
    assert bf["mean_abs_logit"] > 0  # the parity probe wasn't degenerate
