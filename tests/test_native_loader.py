"""Native C++ pipeline tests: build, decode correctness vs PIL reference,
augmentation behavior, determinism, threading."""

import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image

from yet_another_mobilenet_series_tpu.config import DataConfig
from yet_another_mobilenet_series_tpu.data import native_loader


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    """root/<class>/<img>.jpg with solid-color images so decoded values are
    exactly checkable."""
    root = tmp_path_factory.mktemp("imgs")
    colors = {"class_a": (255, 0, 0), "class_b": (0, 255, 0), "class_c": (0, 0, 255)}
    for cname, rgb in colors.items():
        d = root / cname
        d.mkdir()
        for i in range(6):
            img = Image.new("RGB", (96 + 8 * i, 80 + 4 * i), rgb)
            img.save(d / f"im{i}.jpg", quality=95)
    return str(root)


def _cfg(size=32):
    return DataConfig(dataset="folder", image_size=size, eval_resize=int(size * 256 / 224))


def test_build_and_list(image_tree):
    assert os.path.exists(native_loader.build_library())
    paths, labels, classes = native_loader.list_image_folder(image_tree)
    assert classes == ["class_a", "class_b", "class_c"]
    assert len(paths) == 18
    assert set(labels) == {0, 1, 2}


def test_eval_decode_matches_solid_colors(image_tree):
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=False, seed=0, num_threads=2)
    batch = ld.next_batch()
    assert batch["image"].shape == (6, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert ld.decode_failures == 0
    mean = np.asarray(cfg.mean, np.float32)
    std = np.asarray(cfg.std, np.float32)
    for img, label in zip(batch["image"], batch["label"]):
        rgb = img * std + mean  # un-normalize back to [0,1]
        expected = np.zeros(3, np.float32)
        expected[label] = 1.0
        # JPEG-of-solid-color decodes to within a couple of 8-bit steps
        np.testing.assert_allclose(rgb.mean(axis=(0, 1)), expected, atol=0.03)
    ld.close()


def test_eval_order_is_file_order(image_tree):
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=False, seed=0, num_threads=3)
    got = []
    for _ in range(3):
        got.extend(ld.next_batch()["label"].tolist())
    assert got == labels  # eval: no shuffle, strictly in-order across threads
    ld.close()


def test_train_shuffles_and_is_seed_deterministic(image_tree):
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)

    def collect(seed, threads):
        ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=True, seed=seed, num_threads=threads)
        out = [ld.next_batch() for _ in range(3)]
        ld.close()
        return out

    a = collect(7, 1)
    b = collect(7, 3)  # thread count must not change the stream
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["label"], y["label"])
        np.testing.assert_array_equal(x["image"], y["image"])
    c = collect(8, 1)
    labels_a = np.concatenate([x["label"] for x in a])
    labels_c = np.concatenate([x["label"] for x in c])
    assert not np.array_equal(labels_a, labels_c)  # different seed, different order
    assert not np.array_equal(labels_a, np.asarray(labels[:18]))  # actually shuffled


def test_train_epoch_reshuffles(image_tree):
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=True, seed=3, num_threads=2)
    epoch1 = [ld.next_batch()["label"].tolist() for _ in range(3)]
    epoch2 = [ld.next_batch()["label"].tolist() for _ in range(3)]
    assert sorted(sum(epoch1, [])) == sorted(labels)  # each epoch covers all
    assert sorted(sum(epoch2, [])) == sorted(labels)
    assert epoch1 != epoch2
    ld.close()


def test_too_few_samples_rejected(image_tree):
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    with pytest.raises(ValueError):
        native_loader.NativeLoader(paths[:3], labels[:3], cfg, batch=6, train=True, seed=0)


def test_corrupt_jpeg_eval_yields_masked_label(image_tree, tmp_path):
    cfg = _cfg()
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"not a jpeg at all")
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    paths = list(paths[:5]) + [str(bad)]
    labels = list(labels[:5]) + [2]
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=False, seed=0, num_threads=2)
    batch = ld.next_batch()
    assert batch["image"].shape == (6, 32, 32, 3)
    # the loader streams epochs continuously and the ring prefetches ahead, so
    # the counter may already include re-decodes from later epochs: >= 1.
    assert ld.decode_failures >= 1
    # eval: the corrupt sample is zeros with label -1 — masked by the eval
    # step, never a confidently-labeled black image
    assert float(np.abs(batch["image"][5]).mean()) == 0.0
    assert batch["label"][5] == -1
    assert float(np.abs(batch["image"][0]).mean()) > 0.5
    assert list(batch["label"][:5]) == labels[:5]
    ld.close()


def test_corrupt_jpeg_train_resamples_a_real_image(image_tree, tmp_path):
    cfg = _cfg()
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"not a jpeg")
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    paths = list(paths[:5]) + [str(bad)]
    labels = list(labels[:5]) + [2]
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=True, seed=0, num_threads=2)
    batch = ld.next_batch()
    assert ld.decode_failures >= 1
    # every slot holds a real decoded image (the corrupt one was resampled)
    # with a valid label
    for img, lab in zip(batch["image"], batch["label"]):
        assert float(np.abs(img).mean()) > 0.1
        assert 0 <= lab <= 2
    ld.close()


def test_eval_pad_batches_counts_every_example_once(image_tree):
    """Exact eval counting: 18 files / batch 8 -> 3 padded batches; all 18
    labels appear once, the 6 pad rows carry label -1 and zero images."""
    cfg = _cfg()
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=8, train=False, seed=0, num_threads=2, pad_batches=3)
    got_labels, got_images = [], []
    for _ in range(3):
        b = ld.next_batch()
        got_labels.extend(b["label"].tolist())
        got_images.extend(list(b["image"]))
    assert got_labels[:18] == labels
    assert got_labels[18:] == [-1] * 6
    for img in got_images[18:]:
        assert float(np.abs(img).mean()) == 0.0
    # the next pass repeats the same exact layout (streaming)
    b = ld.next_batch()
    assert b["label"].tolist() == labels[:8]
    ld.close()


def test_make_native_eval_loader_multi_host_equal_batches(image_tree, monkeypatch):
    """Both hosts run the same batch count; the union of real labels is
    exactly the full file list."""
    import dataclasses as dc

    cfg = dc.replace(_cfg(), data_dir=os.path.dirname(image_tree), val_split=os.path.basename(image_tree))
    _, all_labels, _ = native_loader.list_image_folder(image_tree)
    seen = []
    counts = []
    for pi in range(2):
        ld, n = native_loader.make_native_eval_loader(cfg, local_batch=4, process_index=pi, process_count=2)
        counts.append(n)
        for _ in range(n):
            seen.extend(l for l in ld.next_batch()["label"].tolist() if l >= 0)
        ld.close()
    assert counts[0] == counts[1] == 3  # ceil(ceil(18/2)/4)
    assert sorted(seen) == sorted(all_labels)


def test_empty_shard_padded_eval_serves_all_dummy_batches():
    """A host whose eval shard is empty must still run the agreed batch count
    (all label=-1) or its peers deadlock in the collective eval step."""
    cfg = _cfg()
    ld = native_loader.NativeLoader([], [], cfg, batch=4, train=False, seed=0, num_threads=2, pad_batches=2)
    for _ in range(2):
        b = ld.next_batch()
        assert b["label"].tolist() == [-1] * 4
        assert float(np.abs(b["image"]).max()) == 0.0
    ld.close()


def test_native_color_jitter_is_multiplicative_and_bounded(tmp_path_factory):
    """A uniform gray image is a fixed point of contrast/saturation blending,
    so with jitter on, the output stays uniform and its scale relative to the
    source spreads across [1-s, 1+s] (multiplicative brightness) — the same
    invariant the tf.data jitter satisfies (test_data.py)."""
    root = tmp_path_factory.mktemp("gray")
    d = root / "c0"
    d.mkdir()
    for i in range(8):
        Image.new("RGB", (64, 64), (128, 128, 128)).save(d / f"g{i}.jpg", quality=98)
    paths, labels, _ = native_loader.list_image_folder(str(root))
    import dataclasses as dc

    cfg = dc.replace(_cfg(), color_jitter=0.4, rrc_area_min=0.9, rrc_area_max=1.0)
    ld = native_loader.NativeLoader(paths, labels, cfg, batch=8, train=True, seed=0, num_threads=2)
    mean = np.asarray(cfg.mean, np.float32)
    std = np.asarray(cfg.std, np.float32)
    ratios = []
    for _ in range(4):
        for img in ld.next_batch()["image"]:
            rgb = img * std + mean  # back to [0,1]
            assert float(rgb.std()) < 0.02  # uniform in, uniform out
            ratios.append(float(rgb.mean()) / (128.0 / 255.0))
    ld.close()
    ratios = np.asarray(ratios)
    s = cfg.color_jitter
    assert np.all(ratios > 1 - s - 0.05) and np.all(ratios < 1 + s + 0.05)
    # multiplicative: the factor genuinely spreads (additive-at-255-scale or
    # disabled jitter would collapse this to ~0)
    assert ratios.max() - ratios.min() > 0.2, ratios


def test_transfer_uint8_matches_f32_within_quantization(image_tree):
    """The C++ loader's u8 output mode (data.transfer_uint8): same (seed,
    global_batch, i) augment pipeline, raw-pixel u8 on the wire instead of
    host-normalized f32. Applying the step-side normalizer to the u8 batch
    must match the f32 batch within the 0.5/255/std quantization bound —
    train (RRC/flip deterministic per position) and eval paths, plus dtype
    pins."""
    import dataclasses as dc

    from yet_another_mobilenet_series_tpu.config import config_from_dict
    from yet_another_mobilenet_series_tpu.train.steps import _input_normalizer

    cfg = _cfg()
    cfg_u8 = dc.replace(cfg, transfer_uint8=True)
    paths, labels, _ = native_loader.list_image_folder(image_tree)
    full_cfg = config_from_dict({
        "model": {"arch": "mobilenet_v2", "num_classes": 3,
                  "block_specs": [{"t": 1, "c": 8, "n": 1, "s": 1}]},
        "data": {"dataset": "folder", "loader": "native", "image_size": 32,
                 "transfer_uint8": True},
        "train": {"compute_dtype": "float32"},
    })
    prep = _input_normalizer(full_cfg)
    tol = 0.5 / 255.0 / min(cfg.std) + 1e-6

    for train in (True, False):
        lf = native_loader.NativeLoader(paths, labels, cfg, batch=6, train=train, seed=11)
        lu = native_loader.NativeLoader(paths, labels, cfg_u8, batch=6, train=train, seed=11)
        try:
            for _ in range(3):
                a, b = lf.next_batch(), lu.next_batch()
                assert b["image"].dtype == np.uint8
                np.testing.assert_array_equal(a["label"], b["label"])
                diff = np.abs(np.asarray(prep(b["image"])) - a["image"])
                assert diff.max() <= tol, (train, diff.max())
        finally:
            lf.close()
            lu.close()


def test_transfer_uint8_decode_failure_fill_and_mode_guard(image_tree, tmp_path):
    """u8-mode zero_sample fills with the MEAN pixel (mean*255), matching
    the f32 path's normalized zeros on decode failures; and the C ABI
    rejects a copy-out in the wrong mode instead of handing back
    uninitialized memory."""
    import ctypes
    import dataclasses as dc

    root = tmp_path / "bad"
    (root / "c0").mkdir(parents=True)
    (root / "c0" / "bad.jpg").write_bytes(b"not a jpeg")
    cfg = dc.replace(_cfg(), transfer_uint8=True)
    # eval pass over just the corrupt file: padded exact pass of 1 batch
    loader = native_loader.NativeLoader([str(root / "c0" / "bad.jpg")], [0], cfg,
                                        batch=2, train=False, pad_batches=1)
    try:
        b = loader.next_batch()
        assert b["image"].dtype == np.uint8
        assert (b["label"] == -1).all()  # decode failure + padding, both masked
        expected = np.round(np.asarray(cfg.mean) * 255).astype(np.uint8)
        np.testing.assert_array_equal(np.unique(b["image"].reshape(-1, 3), axis=0)[0], expected)
        # wrong-mode copy-out is an error, not silent garbage
        imgs = np.empty((2, cfg.image_size, cfg.image_size, 3), np.float32)
        labs = np.empty((2,), np.int32)
        rc = loader._lib.loader_next(loader._handle,
                                     imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                                     labs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        assert rc == -2
    finally:
        loader.close()
