import os

import pytest

from yet_another_mobilenet_series_tpu import config as cfg_lib


def test_defaults_roundtrip():
    cfg = cfg_lib.config_from_dict({})
    assert cfg.model.arch == "mobilenet_v2"
    assert cfg.train.batch_size == 256
    d = cfg_lib.config_to_dict(cfg)
    cfg2 = cfg_lib.config_from_dict(d)
    assert cfg2 == cfg


def test_unknown_key_rejected():
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"model": {"archh": "x"}})
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"nonsense": {}})


def test_yaml_inheritance(tmp_path):
    base = tmp_path / "base.yml"
    base.write_text("model:\n  arch: mobilenet_v3_large\ntrain:\n  epochs: 350\n  batch_size: 1024\n")
    child = tmp_path / "child.yml"
    child.write_text("_base_: base.yml\ntrain:\n  batch_size: 512\n")
    cfg = cfg_lib.load_config(str(child))
    assert cfg.model.arch == "mobilenet_v3_large"
    assert cfg.train.epochs == 350.0  # inherited + coerced to float
    assert cfg.train.batch_size == 512  # overridden


def test_circular_inheritance_detected(tmp_path):
    a = tmp_path / "a.yml"
    b = tmp_path / "b.yml"
    a.write_text("_base_: b.yml\n")
    b.write_text("_base_: a.yml\n")
    with pytest.raises(ValueError):
        cfg_lib.load_config(str(a))


def test_cli_app_and_overrides(tmp_path):
    app = tmp_path / "app.yml"
    app.write_text("name: exp\nmodel:\n  width_mult: 1.0\n")
    cfg = cfg_lib.parse_cli([f"app:{app}", "model.width_mult=0.75", "train.seed=7", "ema.enable=false"])
    assert cfg.name == "exp"
    assert cfg.model.width_mult == 0.75
    assert cfg.train.seed == 7
    assert cfg.ema.enable is False


def test_cli_rejects_garbage():
    with pytest.raises(ValueError):
        cfg_lib.parse_cli(["not-an-arg"])


def test_nested_serve_blocks_parse_and_override():
    """The front-door sub-sections (serve.listen / serve.admission /
    serve.faults) are real config sections: nested dict input, dotted CLI
    overrides, unknown-key rejection — two levels deep."""
    cfg = cfg_lib.config_from_dict({
        "serve": {
            "drain_timeout_s": 3.5,
            "listen": {"enable": True, "port": 8181},
            "admission": {"weights": [4, 2, 1], "breaker_threshold": 7},
            "faults": {"enable": True, "failure_rate": 0.05, "hang_at": 3},
        }
    })
    assert cfg.serve.drain_timeout_s == 3.5
    assert cfg.serve.listen.enable is True and cfg.serve.listen.port == 8181
    assert cfg.serve.listen.host == "127.0.0.1"  # default preserved
    assert cfg.serve.admission.weights == (4, 2, 1)
    assert cfg.serve.admission.breaker_threshold == 7
    assert cfg.serve.faults.enable and cfg.serve.faults.hang_at == 3
    # dotted CLI overrides reach two levels down (+ the --listen sugar path
    # is just this key)
    cfg = cfg_lib.parse_cli(
        ["serve.listen.enable=true", "serve.admission.max_retries=5", "serve.faults.seed=9"])
    assert cfg.serve.listen.enable is True
    assert cfg.serve.admission.max_retries == 5 and cfg.serve.faults.seed == 9
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"serve": {"listen": {"prot": 1}}})
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"serve": {"admission": {"breaker": 1}}})


def test_quant_block_parses_validates_and_overrides():
    """serve.quant (the quantized-serving knobs) is a validated section:
    enum wire/weights values, positive thresholds, dotted CLI overrides."""
    cfg = cfg_lib.config_from_dict({
        "serve": {"quant": {"wire": "uint8", "weights": "int8",
                            "calib_batches": 3, "int8_top1_min": 0.95}}
    })
    assert cfg.serve.quant.wire == "uint8" and cfg.serve.quant.weights == "int8"
    assert cfg.serve.quant.calib_batches == 3
    assert cfg.serve.quant.int8_top1_min == 0.95
    assert cfg.serve.quant.wire_atol > 0  # default preserved
    cfg = cfg_lib.parse_cli(["serve.quant.wire=uint8", "serve.quant.calib_seed=7"])
    assert cfg.serve.quant.wire == "uint8" and cfg.serve.quant.calib_seed == 7
    # the defaults are the f32 status quo: quantization is strictly opt-in
    assert cfg_lib.Config().serve.quant.wire == "float32"
    assert cfg_lib.Config().serve.quant.weights == "float32"
    with pytest.raises(ValueError, match="wire"):
        cfg_lib.config_from_dict({"serve": {"quant": {"wire": "int4"}}})
    with pytest.raises(ValueError, match="weights"):
        cfg_lib.config_from_dict({"serve": {"quant": {"weights": "fp8"}}})
    with pytest.raises(ValueError, match="calib"):
        cfg_lib.config_from_dict({"serve": {"quant": {"calib_batches": 0}}})
    with pytest.raises(ValueError, match="wire_atol"):
        cfg_lib.config_from_dict({"serve": {"quant": {"wire_atol": 0}}})
    with pytest.raises(ValueError, match="top1"):
        cfg_lib.config_from_dict({"serve": {"quant": {"int8_top1_min": 1.5}}})
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"serve": {"quant": {"wier": "uint8"}}})


def test_zoo_block_parses_validates_and_overrides():
    """serve.zoo / serve.zoo.cascade are validated sections reachable by
    dotted CLI override — cli/fleet spawns per-slot replicas via exactly
    these argv keys, so this pins the section registration itself."""
    cfg = cfg_lib.config_from_dict({
        "serve": {"zoo": {"models": "small=/b/s,big=/b/b", "default": "small",
                          "placement": "small;big", "quotas": "small=64",
                          "cascade": {"enable": True, "small": "small",
                                      "big": "big", "threshold": 0.2}}}
    })
    assert cfg.serve.zoo.models == "small=/b/s,big=/b/b"
    assert cfg.serve.zoo.default == "small"
    assert cfg.serve.zoo.placement == "small;big"
    assert cfg.serve.zoo.cascade.enable is True
    assert cfg.serve.zoo.cascade.threshold == 0.2
    # dotted CLI overrides reach three levels down — the per-slot replica
    # argv path (cli/fleet.py slot_overrides) depends on this
    cfg = cfg_lib.parse_cli(
        ["serve.zoo.models=a=/x", "serve.zoo.default=a",
         "serve.zoo.cascade.enable=false", "serve.zoo.cascade.threshold=0.3"])
    assert cfg.serve.zoo.models == "a=/x" and cfg.serve.zoo.default == "a"
    assert cfg.serve.zoo.cascade.threshold == 0.3
    # defaults: the zoo is strictly opt-in
    assert cfg_lib.Config().serve.zoo.models == ""
    assert cfg_lib.Config().serve.zoo.cascade.enable is False
    with pytest.raises(ValueError, match="threshold"):
        cfg_lib.config_from_dict({"serve": {"zoo": {"cascade": {"threshold": 1.5}}}})
    with pytest.raises(ValueError, match="small"):
        cfg_lib.config_from_dict({"serve": {"zoo": {"cascade": {"enable": True}}}})
    with pytest.raises(KeyError):
        cfg_lib.config_from_dict({"serve": {"zoo": {"modles": "a=/x"}}})


def test_shipped_apps_parse():
    apps_dir = os.path.join(os.path.dirname(cfg_lib.__file__), "apps")
    ymls = [f for f in os.listdir(apps_dir) if f.endswith(".yml")]
    assert len(ymls) >= 5  # the five acceptance configs (BASELINE.md)
    for f in ymls:
        cfg_lib.load_config(os.path.join(apps_dir, f))
