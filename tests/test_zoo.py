"""Multi-model zoo tests (docs/SERVING.md "Multi-model zoo & cascade").

The subsystem's load-bearing claims, each pinned:

- **multi-tenant parity**: a zoo engine's answer for model M is bitwise
  identical to a dedicated single-bundle engine serving M's bundle — the
  shared slot pool and dispatch path add NOTHING to any tenant's math.
- **per-model offladder isolation** (satellite): a size-churn burst on one
  tenant never evicts another tenant's warm executables; the SHARED staging
  pool for an evicted geometry survives while any tenant still holds it.
- **typed unknown-model rejection** (satellite): X-Model naming an unserved
  model is a typed arrival-time error carrying the served list, counted.
- **bundle identity** (satellite): model_name + content digest stamp the
  artifact; load verifies; an alias across names is refused; a fleet where
  one name maps to two digests refuses the late joiner's registration.
- **model-aware placement**: the router routes a request for M only to
  replicas advertising M; a healthy fleet with no M replica is a typed
  placement gap (503), distinct from NoHealthyReplicas.
- **confidence cascade**: low-margin small-tier answers escalate to the big
  tier on the cascade trace band, preserving remaining deadline; a burned
  budget or a failed escalation returns the small answer, never a failure.
- **staging-slot reuse under model churn** (satellite): two models with
  different image ladders through ONE pipelined batcher over ONE slot pool
  stay bitwise-correct and drain clean — no fence is crossed between models.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import CascadeConfig, ModelConfig, ZooConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve.admission import (
    AdmissionController,
    ModelQueueFull,
    UnknownModel,
)
from yet_another_mobilenet_series_tpu.serve.cascade import CascadeTier, softmax_margin
from yet_another_mobilenet_series_tpu.serve.context import TRACE_SEQ_CASCADE_BASE
from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
from yet_another_mobilenet_series_tpu.serve.export import (
    BundleDigestMismatch,
    export_bundle,
    load_bundle,
)
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
from yet_another_mobilenet_series_tpu.serve.router import (
    ModelDigestConflict,
    NoHealthyReplicas,
    NoReplicaForModel,
    Router,
)
from yet_another_mobilenet_series_tpu.serve.zoo import (
    ModelZoo,
    parse_image_sizes,
    parse_models,
    parse_placement,
    parse_quotas,
    slot_models,
    slot_overrides,
)


def _snap(key):
    return get_registry().snapshot().get(key, 0)


def _small_net(num_classes=10, image_size=24):
    specs = [
        {"t": 2, "c": 8, "n": 1, "s": 2},
        {"t": 3, "c": 16, "n": 2, "s": 2},
    ]
    return get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=num_classes, block_specs=specs, dropout=0.0),
        image_size=image_size,
    )


def _export(tmp_path, name, *, seed=0, num_classes=10, model_name=None):
    import jax
    import jax.numpy as jnp

    net = _small_net(num_classes=num_classes)
    params, state = net.init(jax.random.PRNGKey(seed))
    # non-trivial BN stats so the folded forward is not the identity affine
    k = jax.random.PRNGKey(seed + 1)
    leaves, treedef = jax.tree.flatten(state)
    keys = jax.random.split(k, len(leaves))
    state = jax.tree.unflatten(
        treedef,
        [l + 0.1 * jnp.abs(jax.random.normal(kk, l.shape)) + 0.01
         for l, kk in zip(leaves, keys)],
    )
    out = export_bundle(net, params, state, str(tmp_path / name), model_name=model_name)
    return load_bundle(out)


# ---------------------------------------------------------------------------
# zoo config parsers + per-slot placement overrides
# ---------------------------------------------------------------------------


def test_zoo_spec_parsers():
    assert parse_models("small=/b/s, big=/b/b") == {"small": "/b/s", "big": "/b/b"}
    for bad in ("small", "small=", "=x", "a b=/x", "small=/a,small=/b"):
        with pytest.raises(ValueError):
            parse_models(bad)
    groups = parse_placement("small|big;big", ["small", "big"])
    assert groups == [("small", "big"), ("big",)]
    # groups repeat cyclically over fleet slots
    assert [slot_models(groups, i) for i in range(3)] == [
        ("small", "big"), ("big",), ("small", "big")]
    assert parse_placement("", ["small", "big"]) == [("small", "big")]
    with pytest.raises(ValueError, match="unknown model"):
        parse_placement("small|nope", ["small"])
    with pytest.raises(ValueError, match="unroutable"):
        parse_placement("small", ["small", "big"])  # big placed nowhere
    with pytest.raises(ValueError, match="empty slot group"):
        parse_placement("small;;small", ["small"])
    assert parse_quotas("small=64,big=16") == {"small": 64, "big": 16}
    with pytest.raises(ValueError):
        parse_quotas("small=0")
    assert parse_image_sizes("small=192|160,big=224") == {
        "small": (160, 192), "big": (224,)}
    with pytest.raises(ValueError):
        parse_image_sizes("small=-3")


def test_slot_overrides_filter_to_the_slot_subset():
    zc = ZooConfig(models="small=/b/s,big=/b/b", default="small",
                   placement="small|big;big", quotas="small=64,big=16",
                   image_sizes="small=160|192,big=224")
    # slot 1 serves only "big": small's quota/sizes must NOT ride along (a
    # replica config naming a model it does not load is a validation error)
    ov = slot_overrides(zc, 1)
    assert "serve.zoo.models=big=/b/b" in ov
    assert "serve.zoo.placement=" in ov  # a replica serves its whole assignment
    assert "serve.zoo.default=big" in ov  # the configured default is absent here
    assert "serve.zoo.quotas=big=16" in ov
    assert "serve.zoo.image_sizes=big=224" in ov
    # slot 0 serves both: everything passes through, default preserved
    ov0 = slot_overrides(zc, 0)
    assert "serve.zoo.models=small=/b/s,big=/b/b" in ov0
    assert "serve.zoo.default=small" in ov0
    assert "serve.zoo.quotas=small=64,big=16" in ov0


# ---------------------------------------------------------------------------
# bundle identity: model_name + content digest (satellite)
# ---------------------------------------------------------------------------


def test_bundle_identity_stamp_verify_and_tamper(tmp_path):
    b = _export(tmp_path, "stamped", model_name="small")
    assert b.model_name == "small"
    assert b.digest and len(b.digest) >= 16
    # tamper with one weight: the load-time digest check refuses the artifact
    npz = tmp_path / "stamped" / "weights.npz"
    flat = dict(np.load(npz))
    key = sorted(flat)[0]
    flat[key] = flat[key] + 1.0
    np.savez(npz, **flat)
    with pytest.raises(BundleDigestMismatch):
        load_bundle(str(tmp_path / "stamped"))


def test_zoo_from_config_loads_and_refuses_aliases(tmp_path):
    _export(tmp_path, "s", seed=0, model_name="small")
    _export(tmp_path, "b", seed=7, model_name="big")
    zc = ZooConfig(models=f"small={tmp_path / 's'},big={tmp_path / 'b'}",
                   default="big", quotas="small=8", image_sizes="small=24")
    zoo = ModelZoo.from_config(zc)
    assert zoo.models == ("small", "big") and zoo.default == "big"
    digests = zoo.digests()
    assert digests["small"] and digests["small"] != digests["big"]
    # lease advertisement carries every name with its digest
    assert set(zoo.lease_models()) == {"small", "big"}
    assert zoo.admission_kwargs()["model_quotas"] == {"small": 8}
    # a bundle stamped "small" configured under the name "huge" is an alias
    # pointing at the wrong artifact — exactly what the stamp exists to catch
    with pytest.raises(ValueError, match="stamped model_name"):
        ModelZoo.from_config(ZooConfig(models=f"huge={tmp_path / 's'}"))
    with pytest.raises(ValueError, match="not among models"):
        ModelZoo.from_config(ZooConfig(models=f"small={tmp_path / 's'}", default="nope"))


# ---------------------------------------------------------------------------
# multi-tenant engine: parity, shared staging, per-model offladder LRU
# ---------------------------------------------------------------------------


def test_engine_multitenant_parity_and_shared_staging(tmp_path):
    """Each tenant of a zoo engine answers bitwise-identically to a
    dedicated engine serving that bundle alone; staging pools stay keyed by
    geometry only (tenants SHARE them)."""
    get_registry().reset()
    bs = _export(tmp_path, "s", seed=0, num_classes=10)
    bb = _export(tmp_path, "b", seed=7, num_classes=7)
    eng = InferenceEngine(models={"small": bs, "big": bb}, buckets=(2,),
                          fuse_ladder=())
    eng.warmup()
    ref_s = InferenceEngine(bs, buckets=(2,), fuse_ladder=())
    ref_b = InferenceEngine(bb, buckets=(2,), fuse_ladder=())
    x = np.random.RandomState(3).normal(0, 1, (2, 24, 24, 3)).astype(np.float32)
    out_s = eng.predict(x.copy(), model="small")
    out_b = eng.predict(x.copy(), model="big")
    assert out_s.shape == (2, 10) and out_b.shape == (2, 7)
    np.testing.assert_array_equal(out_s, ref_s.predict(x.copy()))
    np.testing.assert_array_equal(out_b, ref_b.predict(x.copy()))
    # default tenant answers unqualified requests (first name wins here)
    assert eng.default_model == "small"
    np.testing.assert_array_equal(eng.predict(x.copy()), out_s)
    # executables are per-tenant; the staging pool for the shared geometry
    # is ONE (keyed (bucket, size, K) — host buffers know no tenant), shared
    # by the padded dispatches both tenants just made
    np.testing.assert_array_equal(eng.predict(x[:1].copy(), model="small"),
                                  ref_s.predict(x[:1].copy()))
    np.testing.assert_array_equal(eng.predict(x[:1].copy(), model="big"),
                                  ref_b.predict(x[:1].copy()))
    assert ("small", 2, 24, 1) in eng._compiled and ("big", 2, 24, 1) in eng._compiled
    assert sum(1 for k in eng._staging if k == (2, 24, 1)) == 1


def test_offladder_lru_is_per_model_no_cross_eviction(tmp_path):
    """Satellite: a size-churn burst on one tenant fills only ITS offladder
    slice; the other tenant's warm executables survive, and a shared-geometry
    staging pool is dropped only when NO tenant still compiles it."""
    get_registry().reset()
    bs = _export(tmp_path, "s", seed=0)
    bb = _export(tmp_path, "b", seed=7)
    eng = InferenceEngine(models={"small": bs, "big": bb}, buckets=(2,),
                          fuse_ladder=(), offladder_cache=2)
    eng.warmup()
    for s in (8, 12, 16, 20):  # churn burst on "small" only
        assert eng.predict(np.zeros((1, s, s, 3), np.float32), model="small").shape == (1, 10)
    # small's slice kept the 2 most recent; evictions counted
    assert sorted(k[2] for k in eng._compiled if k[0] == "small" and k[2] != 24) == [16, 20]
    assert _snap("serve.evicted_executables") == 2
    # the OTHER tenant's ladder executable was never a candidate
    assert ("big", 2, 24, 1) in eng._compiled
    # churn on "big" lives in big's own slice; small's survivors stay warm
    for s in (8, 16):
        eng.predict(np.zeros((1, s, s, 3), np.float32), model="big")
    assert sorted(k[2] for k in eng._compiled if k[0] == "small" and k[2] != 24) == [16, 20]
    assert sorted(k[2] for k in eng._compiled if k[0] == "big" and k[2] != 24) == [8, 16]
    # now BOTH tenants hold geometry 16. Churning 16 out of small's slice
    # must keep the shared staging pool alive (big still dispatches into it)
    eng.predict(np.zeros((1, 26, 26, 3), np.float32), model="small")  # evicts 16
    eng.predict(np.zeros((1, 28, 28, 3), np.float32), model="small")  # evicts 20
    assert ("small", 2, 16, 1) not in eng._compiled
    assert ("big", 2, 16, 1) in eng._compiled
    assert (2, 16, 1) in eng._staging  # survives: big still holds it
    assert (2, 20, 1) not in eng._staging  # no tenant holds 20 anymore
    # and big's answers through the surviving shared pool stay correct
    assert eng.predict(np.zeros((1, 16, 16, 3), np.float32), model="big").shape == (1, 10)


# ---------------------------------------------------------------------------
# admission edge: unknown-model rejection + per-model quotas (satellite)
# ---------------------------------------------------------------------------


class _GateEngine:
    """predict_async double whose results block on a gate: requests stay
    in-system until released, making in-system quotas testable."""

    def __init__(self):
        self.gate = threading.Event()

    def predict_async(self, images, model=None):
        gate = self.gate

        class _H:
            def result(_self):
                assert gate.wait(10)
                return images[:, 0, 0, :1]

        return _H()

    def predict(self, images, model=None):
        return self.predict_async(images, model=model).result()


def test_admission_rejects_unknown_model_typed_and_counted():
    get_registry().reset()
    eng = _GateEngine()
    eng.gate.set()  # nothing blocks in this test
    batcher = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0,
                               drain_timeout_s=2.0).start()
    try:
        adm = AdmissionController(batcher, max_retries=0,
                                  models=("small", "big"), default_model="small")
        with pytest.raises(UnknownModel) as ei:
            adm.submit(np.zeros((4, 4, 3), np.float32), model="nope")
        assert ei.value.model == "nope" and ei.value.served == ("small", "big")
        assert _snap("serve.rejected_unknown_model") == 1
        # unqualified requests resolve to the default model and serve
        out = adm.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        assert out is not None
        assert _snap("serve.model_requests.small") == 1
        doc = adm.state()["models"]
        assert set(doc) == {"small", "big"} and doc["small"]["default"] is True
    finally:
        batcher.stop()


def test_admission_per_model_quota_cannot_starve_other_tenants():
    get_registry().reset()
    eng = _GateEngine()
    batcher = PipelinedBatcher(eng, max_batch=1, max_wait_ms=0.0,
                               drain_timeout_s=2.0).start()
    try:
        adm = AdmissionController(batcher, max_retries=0,
                                  models=("small", "big"), default_model="small",
                                  model_quotas={"big": 1})
        img = np.zeros((4, 4, 3), np.float32)
        f_big = adm.submit(img, model="big")  # occupies big's whole quota
        with pytest.raises(ModelQueueFull):
            adm.submit(img, model="big")
        assert _snap("serve.rejected_model_full") == 1
        # the full tenant does not starve the others
        f_small = adm.submit(img, model="small")
        eng.gate.set()
        assert f_big.result(timeout=5) is not None
        assert f_small.result(timeout=5) is not None
        # completion released the slot: big admits again
        assert adm.submit(img, model="big").result(timeout=5) is not None
    finally:
        eng.gate.set()
        batcher.stop()


# ---------------------------------------------------------------------------
# router: model-aware placement + digest-conflict refusal
# ---------------------------------------------------------------------------


class _FakeReplicaClient:
    def __init__(self, host, port):
        self.key = f"{host}:{port}"
        self.predicts = 0
        self.health = (200, {"breaker_state": 0, "queued_total": 0, "draining": False,
                             "replica": {"replica_id": self.key, "start_unix": 1.0}})

    def predict(self, image, **kw):
        self.predicts += 1
        return np.asarray([float(self.key.rsplit(":", 1)[1])], np.float32)

    def healthz(self, timeout_s=None):
        return self.health

    def close(self):
        pass


def _fake_router(n=2, **kw):
    fakes = {}

    def factory(host, port):
        fakes[f"{host}:{port}"] = c = _FakeReplicaClient(host, port)
        return c

    backends = [("127.0.0.1", 9000 + i) for i in range(n)]
    return Router(backends, client_factory=factory, seed=0, **kw), fakes


def test_router_model_aware_pick_and_typed_placement_gap():
    get_registry().reset()
    router, fakes = _fake_router(2)
    try:
        router.set_backend_models({"127.0.0.1:9000": {"small": ""},
                                   "127.0.0.1:9001": {"big": ""}})
        img = np.zeros((4, 4, 3), np.float32)
        # every small request lands on the only replica advertising small
        for _ in range(6):
            assert float(router.submit(img, model="small").result(timeout=5)[0]) == 9000.0
        assert fakes["127.0.0.1:9001"].predicts == 0
        # a model nobody advertises is a typed placement gap — a subclass of
        # NoHealthyReplicas so every existing 503 path still catches it
        with pytest.raises(NoReplicaForModel) as ei:
            router.submit(img, model="nope").result(timeout=5)
        assert isinstance(ei.value, NoHealthyReplicas)
        assert ei.value.model == "nope" and ei.value.served == ("big", "small")
        # clearing an advertisement returns the replica to route-everything
        router.set_backend_models({"127.0.0.1:9001": None})
        got = {float(router.submit(img, model="nope").result(timeout=5)[0])
               for _ in range(4)}
        assert got == {9001.0}
        assert router.state()["fleet"]["models"] == ["small"]
    finally:
        router.stop()


def test_router_register_refuses_digest_conflicts():
    get_registry().reset()
    router, _ = _fake_router(0)
    try:
        out = router.register("127.0.0.1", 9100, models={"m": "aaa"})
        assert out["models"] == ["m"]
        # same name + same digest: a healthy twin, admitted
        router.register("127.0.0.1", 9101, models={"m": "aaa"})
        # same name + DIFFERENT digest: split-brain artifact identity — the
        # late joiner is refused loudly, not folded into the pick lottery
        with pytest.raises(ModelDigestConflict):
            router.register("127.0.0.1", 9102, models={"m": "bbb"})
        assert _snap("fleet.rejected_digest_conflict") == 1
        assert "127.0.0.1:9102" not in {key for key, _ in router.backends()}
        # an EMPTY digest is placement-only knowledge, never a conflict
        router.register("127.0.0.1", 9103, models={"m": ""})
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# confidence cascade
# ---------------------------------------------------------------------------


def test_softmax_margin_properties():
    assert softmax_margin(np.asarray([5.0])) == 1.0  # single class: certain
    assert softmax_margin(np.asarray([3.0, 3.0])) == pytest.approx(0.0)
    assert softmax_margin(np.asarray([9.0, 0.0])) > softmax_margin(np.asarray([1.0, 0.0]))
    # shift invariance (the stable-softmax property)
    a = np.asarray([2.0, 1.0, 0.5])
    assert softmax_margin(a) == pytest.approx(softmax_margin(a + 100.0))


class _ScriptRouter:
    """submit() double: answers per-model scripted logits (or raises)."""

    def __init__(self, logits):
        self.logits = dict(logits)
        self.calls = []

    def submit(self, image, *, priority=None, deadline_ms=None, ctx=None,
               model=None, seq_base=None):
        self.calls.append({"model": model, "deadline_ms": deadline_ms,
                           "ctx": ctx, "seq_base": seq_base})
        f = Future()
        v = self.logits[model]
        if isinstance(v, Exception):
            f.set_exception(v)
        else:
            f.set_result(v)
        return f

    def state(self):
        return {"router": True}

    def register(self, host, port, **kw):
        return {"ok": True, "key": f"{host}:{port}"}


def test_cascade_confident_answers_small_no_escalation():
    get_registry().reset()
    rt = _ScriptRouter({"s": np.asarray([30.0, 0.0, 0.0]), "b": np.asarray([1.0, 2.0, 3.0])})
    tier = CascadeTier(rt, small="s", big="b", threshold=0.15)
    out = tier.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
    np.testing.assert_array_equal(out, rt.logits["s"])
    assert [c["model"] for c in rt.calls] == ["s"]
    assert _snap("serve.cascade.answered_small") == 1
    assert _snap("serve.cascade.escalations") == 0
    assert _snap("serve.cascade.escalation_rate") == 0.0


def test_cascade_escalates_low_margin_on_cascade_trace_band():
    get_registry().reset()
    rt = _ScriptRouter({"s": np.asarray([0.0, 0.0, 0.0]), "b": np.asarray([1.0, 2.0, 3.0])})
    tier = CascadeTier(rt, small="s", big="b", threshold=0.15)
    out = tier.submit(np.zeros((4, 4, 3), np.float32),
                      deadline_ms=60_000.0).result(timeout=5)
    np.testing.assert_array_equal(out, rt.logits["b"])  # the big tier answered
    assert [c["model"] for c in rt.calls] == ["s", "b"]
    esc = rt.calls[1]
    # the escalation is its own routed request: fresh ctx pinned to the big
    # tier, legs stamped in the cascade seq band (never a retry/hedge seq),
    # and the REMAINING deadline budget — not the original — rides along
    assert esc["seq_base"] == TRACE_SEQ_CASCADE_BASE
    assert esc["ctx"].model == "b"
    assert esc["deadline_ms"] is not None and 0 < esc["deadline_ms"] <= 60_000.0
    assert _snap("serve.cascade.escalations") == 1
    assert _snap("serve.cascade.escalation_rate") == 1.0
    assert tier.state()["cascade"]["escalations"] == 1
    assert tier.state()["router"] is True  # state merges over the router's


def test_cascade_burned_deadline_returns_small_answer():
    get_registry().reset()
    small = np.asarray([0.0, 0.0, 0.0])
    rt = _ScriptRouter({"s": small, "b": np.asarray([9.0, 0.0, 0.0])})
    tier = CascadeTier(rt, small="s", big="b", threshold=0.15)
    # any elapsed small-tier time exceeds this budget: escalating would be
    # a certain 504 — the degraded answer beats a typed failure
    out = tier.submit(np.zeros((4, 4, 3), np.float32),
                      deadline_ms=1e-9).result(timeout=5)
    np.testing.assert_array_equal(out, small)
    assert [c["model"] for c in rt.calls] == ["s"]
    assert _snap("serve.cascade.deadline_skips") == 1
    assert _snap("serve.cascade.escalations") == 0


def test_cascade_escalation_failure_falls_back_to_small_answer():
    get_registry().reset()
    small = np.asarray([0.0, 0.0, 0.0])
    rt = _ScriptRouter({"s": small, "b": NoReplicaForModel("b", ("s",))})
    tier = CascadeTier(rt, small="s", big="b", threshold=0.15)
    out = tier.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
    np.testing.assert_array_equal(out, small)  # never fail an answered request
    assert _snap("serve.cascade.escalation_failures") == 1
    # but a small-tier FAILURE passes through verbatim — cascading is for
    # answers, not for masking the fleet's admission verdicts
    rt2 = _ScriptRouter({"s": NoReplicaForModel("s", ()), "b": small})
    tier2 = CascadeTier(rt2, small="s", big="b")
    with pytest.raises(NoReplicaForModel):
        tier2.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)


def test_cascade_respects_explicit_model_pins_and_delegates():
    get_registry().reset()
    big = np.asarray([0.0, 0.0, 0.0])  # ambiguous — would escalate if cascaded
    rt = _ScriptRouter({"s": np.asarray([9.0, 0.0, 0.0]), "b": big})
    tier = CascadeTier(rt, small="s", big="b", threshold=0.15)
    out = tier.submit(np.zeros((4, 4, 3), np.float32), model="b").result(timeout=5)
    np.testing.assert_array_equal(out, big)  # the chosen model, uncascaded
    assert [c["model"] for c in rt.calls] == ["b"]
    assert _snap("serve.cascade.bypassed_explicit") == 1
    assert _snap("serve.cascade.escalations") == 0
    # everything but submit/state reaches the wrapped router (membership)
    assert tier.register("127.0.0.1", 9200)["ok"] is True
    with pytest.raises(ValueError, match="threshold"):
        CascadeTier(rt, small="s", big="b", threshold=1.5)
    with pytest.raises(ValueError, match="both"):
        CascadeTier(rt, small="s", big="s")


def test_cascade_config_validation():
    with pytest.raises(ValueError, match="small= and big="):
        CascadeConfig(enable=True, small="", big="b")
    with pytest.raises(ValueError, match="threshold"):
        CascadeConfig(threshold=2.0)


# ---------------------------------------------------------------------------
# staging-slot reuse under model churn (satellite): one pipelined batcher,
# two tenants with different ladders, one shared slot pool
# ---------------------------------------------------------------------------


def test_staging_slot_reuse_under_model_churn_bitwise_and_clean_drain(tmp_path):
    """Interleaved two-model traffic through ONE PipelinedBatcher over an
    overlapped engine with a SINGLE staging slot per geometry: every
    dispatch reuses the same host buffer across tenants, so a missing fence
    wait between models would tear a row. Answers stay bitwise-identical to
    dedicated sync engines, and the drain leaves every fence clear."""
    get_registry().reset()
    ba = _export(tmp_path, "a", seed=0)
    bb = _export(tmp_path, "b", seed=7)
    eng = InferenceEngine(models={"a": ba, "b": bb},
                          model_image_sizes={"a": (24, 32), "b": (24,)},
                          buckets=(2,), fuse_ladder=(),
                          overlap_staging=True, staging_slots=1)
    eng.warmup()
    ref_a = InferenceEngine(ba, buckets=(2,), image_size=24, image_sizes=(24, 32),
                            fuse_ladder=())
    ref_b = InferenceEngine(bb, buckets=(2,), image_size=24, fuse_ladder=())
    # prime the 24px pool: it is shared by both tenants and has exactly ONE
    # slot, so cross-model reuse happens on every alternation below
    eng.predict(np.zeros((1, 24, 24, 3), np.float32), model="a")
    assert len(eng._staging[(2, 24, 1)].slots) == 1
    rng = np.random.RandomState(11)
    plan = []  # (model, image, ref_row)
    for i in range(12):
        model = "a" if i % 2 == 0 else "b"
        size = 32 if (model == "a" and i % 4 == 0) else 24
        x = rng.normal(0, 1, (1, size, size, 3)).astype(np.float32)
        ref = (ref_a if model == "a" else ref_b).predict(x.copy())[0]
        plan.append((model, x[0], ref))
    b = PipelinedBatcher(eng, max_batch=2, max_wait_ms=1.0, max_inflight=2,
                         drain_timeout_s=10.0)
    b.start()
    try:
        futs = [b.submit(img, model=model) for model, img, _ in plan]
        for (model, _, ref), f in zip(plan, futs):
            np.testing.assert_array_equal(f.result(timeout=30), ref)
    finally:
        b.stop(drain=True)
    # clean drain: nothing in flight, and the pools (fences clear lazily on
    # the NEXT acquire, so one may still be armed — but its dispatch synced
    # when the drain resolved every future) keep serving bitwise answers
    assert b.inflight() == 0
    assert set(eng._staging) == {(2, 24, 1), (2, 32, 1)}
    x = rng.normal(0, 1, (1, 24, 24, 3)).astype(np.float32)
    np.testing.assert_array_equal(eng.predict(x.copy(), model="a"),
                                  ref_a.predict(x.copy()))
    np.testing.assert_array_equal(eng.predict(x.copy(), model="b"),
                                  ref_b.predict(x.copy()))
