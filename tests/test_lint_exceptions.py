"""Model tests for the escaping-exception layer (analysis/exceptions.py).

The fixture pair in test_lint_rules.py proves YAMT022 flags and stays
silent end to end; this file pins the MODEL facts the rule consumes —
raise/re-raise/raise-from propagation through the call graph, except
narrowing by the project class hierarchy AND the real builtin hierarchy,
broad-except absorption, else-block bypass, and honest degradation to
silence on opaque callees and computed raise expressions — so a resolution
regression fails here with a named fact, not as a mysteriously silent rule.
"""

import pathlib

from yet_another_mobilenet_series_tpu.analysis.core import Project, SourceFile, collect_paths

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"


def _project(paths):
    py, yml = collect_paths([str(p) for p in paths])
    files = []
    for p in py:
        with open(p, encoding="utf-8") as f:
            files.append(SourceFile(p, f.read()))
    return Project(files, yml)


def _escapes(model, tail):
    q = next(q for q in model.project.summaries if q.endswith(tail))
    return {k.rsplit(".", 1)[-1] for k in model.escape_set(q)}


# -- raise propagation -------------------------------------------------------


def test_raise_propagates_through_calls(tmp_path):
    (tmp_path / "m.py").write_text(
        "class Boom(Exception):\n"
        "    pass\n"
        "\n"
        "def deep():\n"
        "    raise Boom('x')\n"
        "\n"
        "def mid():\n"
        "    return deep()\n"
        "\n"
        "def top():\n"
        "    return mid()\n"
    )
    model = _project([tmp_path]).exceptions
    assert _escapes(model, ".top") == {"Boom"}


def test_raise_from_and_ctor_args_resolve_to_the_class(tmp_path):
    (tmp_path / "m.py").write_text(
        "class WireError(Exception):\n"
        "    pass\n"
        "\n"
        "def decode(raw):\n"
        "    try:\n"
        "        return int(raw)\n"
        "    except ValueError as e:\n"
        "        raise WireError(f'bad frame {raw!r}') from e\n"
    )
    model = _project([tmp_path]).exceptions
    assert _escapes(model, ".decode") == {"WireError"}


# -- except narrowing --------------------------------------------------------


def test_narrow_except_absorbs_subclass_and_passes_sibling(tmp_path):
    (tmp_path / "m.py").write_text(
        "class Base(Exception):\n"
        "    pass\n"
        "\n"
        "class Retryable(Base):\n"
        "    pass\n"
        "\n"
        "class Fatal(Base):\n"
        "    pass\n"
        "\n"
        "def work(flag):\n"
        "    if flag:\n"
        "        raise Retryable()\n"
        "    raise Fatal()\n"
        "\n"
        "def call():\n"
        "    try:\n"
        "        work(True)\n"
        "    except Retryable:\n"
        "        return None\n"
    )
    model = _project([tmp_path]).exceptions
    # Retryable absorbed by its own handler; the sibling provably passes
    assert _escapes(model, ".call") == {"Fatal"}


def test_catching_the_base_absorbs_project_subclasses(tmp_path):
    (tmp_path / "m.py").write_text(
        "class Base(Exception):\n"
        "    pass\n"
        "\n"
        "class Retryable(Base):\n"
        "    pass\n"
        "\n"
        "def work():\n"
        "    raise Retryable()\n"
        "\n"
        "def call():\n"
        "    try:\n"
        "        work()\n"
        "    except Base:\n"
        "        return None\n"
    )
    model = _project([tmp_path]).exceptions
    assert _escapes(model, ".call") == set()


def test_builtin_hierarchy_narrows_externals(tmp_path):
    (tmp_path / "m.py").write_text(
        "def work(d):\n"
        "    raise KeyError('k')\n"
        "\n"
        "def call(d):\n"
        "    try:\n"
        "        return work(d)\n"
        "    except LookupError:\n"
        "        return None\n"
        "\n"
        "def passes(d):\n"
        "    try:\n"
        "        return work(d)\n"
        "    except OSError:\n"
        "        return None\n"
    )
    model = _project([tmp_path]).exceptions
    # KeyError is a LookupError (real builtin hierarchy) but NOT an OSError
    assert _escapes(model, ".call") == set()
    assert _escapes(model, ".passes") == {"KeyError"}


def test_else_block_bypasses_the_handlers(tmp_path):
    (tmp_path / "m.py").write_text(
        "class Boom(Exception):\n"
        "    pass\n"
        "\n"
        "def call(x):\n"
        "    try:\n"
        "        y = x + 1\n"
        "    except Boom:\n"
        "        return None\n"
        "    else:\n"
        "        raise Boom('from else')\n"
    )
    model = _project([tmp_path]).exceptions
    assert _escapes(model, ".call") == {"Boom"}


# -- re-raise ----------------------------------------------------------------


def test_bare_raise_reescapes_the_absorbed_set(tmp_path):
    (tmp_path / "m.py").write_text(
        "class Boom(Exception):\n"
        "    pass\n"
        "\n"
        "def work():\n"
        "    raise Boom()\n"
        "\n"
        "def logged():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        raise\n"
        "\n"
        "def renamed():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as e:\n"
        "        raise e\n"
        "\n"
        "def swallowed():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        return None\n"
    )
    model = _project([tmp_path]).exceptions
    # a broad handler absorbs, but its re-raise (bare or by the bound
    # name) puts the ABSORBED set back on the wire
    assert _escapes(model, ".logged") == {"Boom"}
    assert _escapes(model, ".renamed") == {"Boom"}
    assert _escapes(model, ".swallowed") == set()


# -- honest degradation ------------------------------------------------------


def test_opaque_callee_and_computed_raise_degrade_to_silence(tmp_path):
    (tmp_path / "m.py").write_text(
        "def computed(mk):\n"
        "    raise mk()\n"
        "\n"
        "class Box:\n"
        "    def __init__(self, cb):\n"
        "        self._cb = cb\n"
        "\n"
        "    def run(self):\n"
        "        return self._cb()\n"
    )
    model = _project([tmp_path]).exceptions
    # `raise mk()` raises whatever the factory made — no guess; a callback
    # whose target the call graph cannot resolve contributes nothing
    assert _escapes(model, ".computed") == set()
    assert _escapes(model, "Box.run") == set()


def test_unknown_external_relationship_is_none_and_absorbs(tmp_path):
    (tmp_path / "m.py").write_text(
        "import thirdparty\n"
        "\n"
        "def work():\n"
        "    raise thirdparty.WeirdError('x')\n"
        "\n"
        "def call():\n"
        "    try:\n"
        "        work()\n"
        "    except thirdparty.OtherError:\n"
        "        return None\n"
    )
    model = _project([tmp_path]).exceptions
    # two externals whose bodies we never see: the hierarchy cannot answer
    assert model.is_subtype("thirdparty.WeirdError", "thirdparty.OtherError") is None
    # and the try absorbs rather than guessing an escape
    assert _escapes(model, ".call") == set()
