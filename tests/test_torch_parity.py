"""Composite-block numeric parity against a hand-built torch implementation
(SURVEY.md §4.1: 'verify against torchvision's MBV2 numerically for the
forward pass'). torchvision is absent in this image, so the torch side is
built from torch.nn primitives with the exact reference semantics (symmetric
k//2 padding, BN momentum 0.1/eps 1e-5, linear bottleneck, residual)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from yet_another_mobilenet_series_tpu.ops.blocks import InvertedResidual  # noqa: E402


class TorchMBConv(tnn.Module):
    """Reference-style MBConv: expand 1x1 -> BN -> ReLU6 -> dw kxk -> BN ->
    ReLU6 -> [SE] -> project 1x1 -> BN (+residual)."""

    def __init__(self, cin, cout, exp, k, stride, se_ch=0):
        super().__init__()
        self.expand = tnn.Conv2d(cin, exp, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(exp)
        self.dw = tnn.Conv2d(exp, exp, k, stride, padding=k // 2, groups=exp, bias=False)
        self.bn2 = tnn.BatchNorm2d(exp)
        self.se_ch = se_ch
        if se_ch:
            self.se_reduce = tnn.Linear(exp, se_ch)
            self.se_expand = tnn.Linear(se_ch, exp)
        self.project = tnn.Conv2d(exp, cout, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(cout)
        self.residual = stride == 1 and cin == cout

    def forward(self, x):
        h = tnn.functional.relu6(self.bn1(self.expand(x)))
        h = tnn.functional.relu6(self.bn2(self.dw(h)))
        if self.se_ch:
            s = h.mean(dim=(2, 3))
            s = self.se_expand(tnn.functional.relu(self.se_reduce(s)))
            gate = tnn.functional.hardsigmoid(s)  # torch: relu6(x+3)/6
            h = h * gate[:, :, None, None]
        h = self.bn3(self.project(h))
        return h + x if self.residual else h


@pytest.mark.parametrize("cin,cout,exp,k,stride,se", [
    (16, 16, 64, 3, 1, 0),    # residual, no SE
    (16, 24, 64, 5, 2, 0),    # stride 2, k=5
    (16, 16, 48, 3, 1, 16),   # SE + residual
])
def test_mbconv_block_matches_torch(cin, cout, exp, k, stride, se):
    spec = InvertedResidual(
        in_channels=cin, out_channels=cout, expanded_channels=exp, stride=stride,
        kernel_sizes=(k,), active_fn="relu6", se_channels=se, se_gate_fn="hsigmoid",
    )
    params, state = spec.init(jax.random.PRNGKey(0))

    tm = TorchMBConv(cin, cout, exp, k, stride, se).double().eval()
    with torch.no_grad():
        # copy OUR params into the torch module (HWIO -> OIHW)
        tm.expand.weight.copy_(torch.from_numpy(np.asarray(params["expand"]["w"], np.float64).transpose(3, 2, 0, 1)))
        tm.dw.weight.copy_(torch.from_numpy(np.asarray(params[f"dw0_k{k}"]["w"], np.float64).transpose(3, 2, 0, 1)))
        tm.project.weight.copy_(torch.from_numpy(np.asarray(params["project"]["w"], np.float64).transpose(3, 2, 0, 1)))
        for bn_t, key in [(tm.bn1, "expand_bn"), (tm.bn2, "dw_bn"), (tm.bn3, "project_bn")]:
            bn_t.weight.copy_(torch.from_numpy(np.asarray(params[key]["gamma"], np.float64)))
            bn_t.bias.copy_(torch.from_numpy(np.asarray(params[key]["beta"], np.float64)))
            # non-trivial running stats so eval mode is a real test
            mean = np.random.RandomState(hash(key) % 2**31).normal(0, 0.3, bn_t.weight.shape[0])
            var = np.random.RandomState(hash(key) % 2**31 + 1).uniform(0.5, 1.5, bn_t.weight.shape[0])
            bn_t.running_mean.copy_(torch.from_numpy(mean))
            bn_t.running_var.copy_(torch.from_numpy(var))
            state[key] = {"mean": jnp.asarray(mean, jnp.float32), "var": jnp.asarray(var, jnp.float32)}
        if se:
            tm.se_reduce.weight.copy_(torch.from_numpy(np.asarray(params["se"]["reduce"]["w"], np.float64).T))
            tm.se_reduce.bias.copy_(torch.from_numpy(np.asarray(params["se"]["reduce"]["b"], np.float64)))
            tm.se_expand.weight.copy_(torch.from_numpy(np.asarray(params["se"]["expand"]["w"], np.float64).T))
            tm.se_expand.bias.copy_(torch.from_numpy(np.asarray(params["se"]["expand"]["b"], np.float64)))

    x = np.random.RandomState(7).normal(size=(2, 9, 9, cin)).astype(np.float32)
    y_ours, _ = spec.apply(params, state, jnp.asarray(x), train=False)
    with torch.no_grad():
        y_torch = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)).double()).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y_ours), y_torch, rtol=1e-4, atol=1e-5)
