"""Composite-block numeric parity against a hand-built torch implementation
(SURVEY.md §4.1: 'verify against torchvision's MBV2 numerically for the
forward pass'). torchvision is absent in this image, so the torch side is
built from torch.nn primitives with the exact reference semantics (symmetric
k//2 padding, BN momentum 0.1/eps 1e-5, linear bottleneck, residual)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from yet_another_mobilenet_series_tpu.ops.blocks import InvertedResidual  # noqa: E402


def _copy_conv(torch_conv, w_hwio):
    """HWIO -> OIHW copy of one of OUR conv weights into a torch Conv2d."""
    torch_conv.weight.copy_(torch.from_numpy(np.asarray(w_hwio, np.float64).transpose(3, 2, 0, 1)))


def _copy_bn(bn_t, key, params, state):
    """gamma/beta from OUR params + non-trivial running stats (stable
    crc32-seeded so a tolerance failure reproduces across processes) written
    to BOTH sides."""
    import zlib

    bn_t.weight.copy_(torch.from_numpy(np.asarray(params[key]["gamma"], np.float64)))
    bn_t.bias.copy_(torch.from_numpy(np.asarray(params[key]["beta"], np.float64)))
    seed = zlib.crc32(key.encode()) % 2**31
    mean = np.random.RandomState(seed).normal(0, 0.3, bn_t.weight.shape[0])
    var = np.random.RandomState(seed + 1).uniform(0.5, 1.5, bn_t.weight.shape[0])
    bn_t.running_mean.copy_(torch.from_numpy(mean))
    bn_t.running_var.copy_(torch.from_numpy(var))
    state[key] = {"mean": jnp.asarray(mean, jnp.float32), "var": jnp.asarray(var, jnp.float32)}


def _copy_se(tm, params):
    tm.se_reduce.weight.copy_(torch.from_numpy(np.asarray(params["se"]["reduce"]["w"], np.float64).T))
    tm.se_reduce.bias.copy_(torch.from_numpy(np.asarray(params["se"]["reduce"]["b"], np.float64)))
    tm.se_expand.weight.copy_(torch.from_numpy(np.asarray(params["se"]["expand"]["w"], np.float64).T))
    tm.se_expand.bias.copy_(torch.from_numpy(np.asarray(params["se"]["expand"]["b"], np.float64)))



class TorchMBConv(tnn.Module):
    """Reference-style MBConv: expand 1x1 -> BN -> ReLU6 -> dw kxk -> BN ->
    ReLU6 -> [SE] -> project 1x1 -> BN (+residual)."""

    def __init__(self, cin, cout, exp, k, stride, se_ch=0):
        super().__init__()
        self.expand = tnn.Conv2d(cin, exp, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(exp)
        self.dw = tnn.Conv2d(exp, exp, k, stride, padding=k // 2, groups=exp, bias=False)
        self.bn2 = tnn.BatchNorm2d(exp)
        self.se_ch = se_ch
        if se_ch:
            self.se_reduce = tnn.Linear(exp, se_ch)
            self.se_expand = tnn.Linear(se_ch, exp)
        self.project = tnn.Conv2d(exp, cout, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(cout)
        self.residual = stride == 1 and cin == cout

    def forward(self, x):
        h = tnn.functional.relu6(self.bn1(self.expand(x)))
        h = tnn.functional.relu6(self.bn2(self.dw(h)))
        if self.se_ch:
            s = h.mean(dim=(2, 3))
            s = self.se_expand(tnn.functional.relu(self.se_reduce(s)))
            gate = tnn.functional.hardsigmoid(s)  # torch: relu6(x+3)/6
            h = h * gate[:, :, None, None]
        h = self.bn3(self.project(h))
        return h + x if self.residual else h


@pytest.mark.parametrize("cin,cout,exp,k,stride,se", [
    (16, 16, 64, 3, 1, 0),    # residual, no SE
    (16, 24, 64, 5, 2, 0),    # stride 2, k=5
    (16, 16, 48, 3, 1, 16),   # SE + residual
])
def test_mbconv_block_matches_torch(cin, cout, exp, k, stride, se):
    spec = InvertedResidual(
        in_channels=cin, out_channels=cout, expanded_channels=exp, stride=stride,
        kernel_sizes=(k,), active_fn="relu6", se_channels=se, se_gate_fn="hsigmoid",
    )
    params, state = spec.init(jax.random.PRNGKey(0))

    tm = TorchMBConv(cin, cout, exp, k, stride, se).double().eval()
    with torch.no_grad():
        _copy_conv(tm.expand, params["expand"]["w"])
        _copy_conv(tm.dw, params[f"dw0_k{k}"]["w"])
        _copy_conv(tm.project, params["project"]["w"])
        for bn_t, key in [(tm.bn1, "expand_bn"), (tm.bn2, "dw_bn"), (tm.bn3, "project_bn")]:
            _copy_bn(bn_t, key, params, state)
        if se:
            _copy_se(tm, params)

    x = np.random.RandomState(7).normal(size=(2, 9, 9, cin)).astype(np.float32)
    y_ours, _ = spec.apply(params, state, jnp.asarray(x), train=False)
    with torch.no_grad():
        y_torch = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)).double()).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y_ours), y_torch, rtol=1e-4, atol=1e-5)


class TorchEffMBConv(tnn.Module):
    """EfficientNet-style MBConv: [expand 1x1 -> BN -> SiLU] (skipped at
    t=1) -> dw kxk -> BN -> SiLU -> SE(silu inner, sigmoid gate) ->
    project 1x1 -> BN (+residual). BN eps 1e-3 (the EfficientNet value)."""

    def __init__(self, cin, cout, exp, k, stride, se_ch):
        super().__init__()
        self.has_expand = exp != cin
        if self.has_expand:
            self.expand = tnn.Conv2d(cin, exp, 1, bias=False)
            self.bn1 = tnn.BatchNorm2d(exp, eps=1e-3)
        self.dw = tnn.Conv2d(exp, exp, k, stride, padding=k // 2, groups=exp, bias=False)
        self.bn2 = tnn.BatchNorm2d(exp, eps=1e-3)
        self.se_reduce = tnn.Linear(exp, se_ch)
        self.se_expand = tnn.Linear(se_ch, exp)
        self.project = tnn.Conv2d(exp, cout, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(cout, eps=1e-3)
        self.residual = stride == 1 and cin == cout

    def forward(self, x):
        h = x
        if self.has_expand:
            h = tnn.functional.silu(self.bn1(self.expand(h)))
        h = tnn.functional.silu(self.bn2(self.dw(h)))
        s = h.mean(dim=(2, 3))
        s = self.se_expand(tnn.functional.silu(self.se_reduce(s)))
        h = h * torch.sigmoid(s)[:, :, None, None]
        h = self.bn3(self.project(h))
        return h + x if self.residual else h


@pytest.mark.parametrize("cin,cout,exp,k,stride,se", [
    (32, 16, 32, 3, 1, 8),     # B0 stage-1: t=1 expand-skip + SE, no residual
    (16, 16, 96, 3, 1, 4),     # t=6 + SE + residual
    (24, 24, 144, 5, 1, 6),    # k=5 + SE + residual
])
def test_efficientnet_block_matches_torch(cin, cout, exp, k, stride, se):
    """The EfficientNet family's block semantics (swish everywhere, SE with
    swish inner FC and sigmoid gate sized from the block INPUT, t=1 expand
    skip, BN eps 1e-3) match a torch implementation numerically; drop_path
    is an exact eval no-op."""
    spec = InvertedResidual(
        in_channels=cin, out_channels=cout, expanded_channels=exp, stride=stride,
        kernel_sizes=(k,), active_fn="swish", se_channels=se, se_gate_fn="sigmoid",
        se_inner_act="swish", bn_eps=1e-3, drop_path=0.1,
    )
    params, state = spec.init(jax.random.PRNGKey(0))
    tm = TorchEffMBConv(cin, cout, exp, k, stride, se).double().eval()
    with torch.no_grad():
        if spec.has_expand:
            _copy_conv(tm.expand, params["expand"]["w"])
        _copy_conv(tm.dw, params[f"dw0_k{k}"]["w"])
        _copy_conv(tm.project, params["project"]["w"])
        bns = [(tm.bn2, "dw_bn"), (tm.bn3, "project_bn")]
        if spec.has_expand:
            bns.append((tm.bn1, "expand_bn"))
        for bn_t, key in bns:
            _copy_bn(bn_t, key, params, state)
        _copy_se(tm, params)

    x = np.random.RandomState(7).normal(size=(2, 9, 9, cin)).astype(np.float32)
    y_ours, _ = spec.apply(params, state, jnp.asarray(x), train=False)
    # drop_path must not perturb eval even when an rng is supplied
    y_rng, _ = spec.apply(params, state, jnp.asarray(x), train=False, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(y_ours), np.asarray(y_rng))
    with torch.no_grad():
        y_torch = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)).double()).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y_ours), y_torch, rtol=1e-4, atol=1e-5)
