"""serve/ subsystem tests (docs/SERVING.md).

The three load-bearing claims, each pinned:

- **BN-fold parity**: the exported folded forward matches the masked
  eval-mode BN forward within the documented fp32 tolerance (atol 1e-4 on
  logits; measured ~1e-9..1e-6 — the fold only re-associates a per-channel
  multiply into the conv accumulation).
- **bucket-padding correctness**: padded rows change NOTHING — the real
  rows' logits are bitwise identical to an exact-bucket run of the same
  compiled executable (the forward is row-independent once BN is folded
  away).
- **batcher semantics under concurrency**: coalescing routes every request
  to its own logits row; bounded-queue backpressure and deadline shedding
  fire when provoked; a dying engine fails futures instead of hanging
  clients.

Plus the full round trip: train smoke -> checkpoint -> cli.serve export ->
bundle -> engine under concurrent load, with serve histograms visible in the
obs registry snapshot (the acceptance criterion).
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.config import ModelConfig, config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.models.serialize import (
    network_from_dict,
    network_to_dict,
    spec_is_inference,
)
from yet_another_mobilenet_series_tpu.nas import masking
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.parallel import mesh as mesh_lib
from yet_another_mobilenet_series_tpu.serve.batcher import DeadlineExceeded, MicroBatcher, QueueFull
from yet_another_mobilenet_series_tpu.serve.engine import BF16_PARITY_ATOL, InferenceEngine
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
from yet_another_mobilenet_series_tpu.serve.export import (
    InferenceBundle,
    apply_folded,
    export_bundle,
    flatten_tree,
    fold_network,
    load_bundle,
    unflatten_tree,
)

# the documented BN-fold tolerance (docs/SERVING.md): fp32 re-association only
FOLD_ATOL = 1e-4


def _small_net(num_classes=10, image_size=24, atom=False):
    specs = [
        {"t": 2, "c": 8, "n": 1, "s": 2, "k": [3, 5] if atom else 3, "se": 0.25 if atom else 0},
        {"t": 3, "c": 16, "n": 2, "s": 2},
    ]
    return get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=num_classes, block_specs=specs, dropout=0.0),
        image_size=image_size,
    )


def _init_with_stats(net, seed=0):
    """Params + NON-trivial BN running stats (fresh init has mean=0/var=1,
    which would let a broken fold hide behind the identity affine)."""
    params, state = net.init(jax.random.PRNGKey(seed))
    k = jax.random.PRNGKey(seed + 1)
    leaves, treedef = jax.tree.flatten(state)
    keys = jax.random.split(k, len(leaves))
    state = jax.tree.unflatten(
        treedef,
        [l + 0.1 * jnp.abs(jax.random.normal(kk, l.shape)) + 0.01 for l, kk in zip(leaves, keys)],
    )
    return params, state


# ---------------------------------------------------------------------------
# export: fold + bundle
# ---------------------------------------------------------------------------


def test_bn_fold_parity():
    net = _small_net(atom=True)
    params, state = _init_with_stats(net)
    x = jnp.asarray(np.random.RandomState(0).normal(0, 1, (4, 24, 24, 3)).astype(np.float32))
    ref, _ = net.apply(params, state, x, train=False)
    folded = fold_network(net, params, state)
    got = apply_folded(net, folded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=FOLD_ATOL, rtol=0)
    # the folded tree really has no BN left
    flat = flatten_tree(folded)
    assert not any("bn" in k for k in flat)
    assert any(k.endswith("/b") for k in flat)  # folded shifts became biases


def test_export_hard_applies_masks(tmp_path):
    """Bundle of a masked supernet == masked eval forward (remat is bit-exact
    vs masking; the fold adds only fp32 re-association)."""
    net = _small_net(atom=True)
    params, state = _init_with_stats(net, seed=3)
    masks = masking.init_masks(net)
    k0 = next(iter(masks))
    m = np.array(masks[k0])  # np.asarray of a jax array is read-only
    m[::3] = 0.0  # kill a third of the first prunable block's atoms
    masks[k0] = jnp.asarray(m)
    x = jnp.asarray(np.random.RandomState(1).normal(0, 1, (2, 24, 24, 3)).astype(np.float32))
    ref, _ = net.apply(params, state, x, train=False, masks={int(k): v for k, v in masks.items()})
    out = export_bundle(net, params, state, str(tmp_path / "b"), masks=masks)
    bundle = load_bundle(out)
    # the dead atoms are physically gone from the artifact
    assert sum(b.expanded_channels for b in bundle.net.blocks) < sum(
        b.expanded_channels for b in net.blocks
    )
    assert bundle.meta["prune"]["atoms_after"] < bundle.meta["prune"]["atoms_before"]
    got = apply_folded(bundle.net, jax.tree.map(jnp.asarray, bundle.params), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=FOLD_ATOL, rtol=0)


def test_bundle_round_trip_and_inference_marker(tmp_path):
    net = _small_net()
    params, state = _init_with_stats(net)
    out = export_bundle(net, params, state, str(tmp_path / "b"), extra_meta={"note": "t"})
    spec = json.loads((tmp_path / "b" / "spec.json").read_text())
    assert spec["schema"] == 2 and spec_is_inference(spec)
    bundle = load_bundle(out)
    assert bundle.net == net
    assert bundle.meta["note"] == "t"
    # flatten/unflatten is exact
    flat = flatten_tree(bundle.params)
    re = unflatten_tree(flat)
    assert jax.tree.structure(re) == jax.tree.structure(bundle.params)


def test_load_bundle_rejects_training_spec(tmp_path):
    net = _small_net()
    (tmp_path / "spec.json").write_text(json.dumps(network_to_dict(net)))  # inference=False
    np.savez(tmp_path / "weights.npz")
    with pytest.raises(ValueError, match="not an inference bundle"):
        load_bundle(str(tmp_path))


# ---------------------------------------------------------------------------
# serialize schema v2 / v1 compat
# ---------------------------------------------------------------------------


def test_serialize_v2_round_trip_and_v1_compat():
    net = _small_net(atom=True)
    d = network_to_dict(net)
    assert d["schema"] == 2 and d["inference"] is False
    assert network_from_dict(json.loads(json.dumps(d))) == net
    assert network_to_dict(net, inference=True)["inference"] is True
    # a v1 payload (pre-serving checkpoint sidecar / searched_arch.json):
    # no "inference" key, schema 1 — must still load
    v1 = dict(d)
    v1["schema"] = 1
    del v1["inference"]
    assert network_from_dict(json.loads(json.dumps(v1))) == net
    assert not spec_is_inference(v1)
    with pytest.raises(ValueError, match="unsupported network schema"):
        network_from_dict({**d, "schema": 99})


# ---------------------------------------------------------------------------
# engine: buckets, padding, AOT warmup, sharding
# ---------------------------------------------------------------------------


def _bundle(tmp_path, **kw):
    net = _small_net(**kw)
    params, state = _init_with_stats(net)
    export_bundle(net, params, state, str(tmp_path / "eng"))
    return load_bundle(str(tmp_path / "eng"))


def test_engine_bucket_padding_bitwise(tmp_path):
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2, 4), donate_input=False, image_size=24)
    eng.warmup()
    # warmup precompiled every (model, bucket, size, K): per-chunk pairs
    # plus the fused (max-bucket, size, K) scan for each K on the default
    # fuse ladder, all under the single-bundle engine's "default" tenant
    assert set(eng._compiled) == {
        ("default", 2, 24, 1), ("default", 4, 24, 1),
        ("default", 4, 24, 2), ("default", 4, 24, 4),
    }
    x = np.random.RandomState(0).normal(0, 1, (4, 24, 24, 3)).astype(np.float32)
    full = eng.predict(x)  # exact bucket, no padding
    part = eng.predict(x[:3])  # 3 -> padded to 4
    np.testing.assert_array_equal(part, full[:3])
    one = eng.predict(x[:1])  # 1 -> padded to 2
    two = eng.predict(x[:2])
    np.testing.assert_array_equal(one, two[:1])
    # > max bucket chunks through the biggest bucket
    seven = eng.predict(np.concatenate([x, x[:3]]))
    assert seven.shape == (7, 10)
    np.testing.assert_array_equal(seven[:4], full)
    snap = get_registry().snapshot()
    assert snap["serve.bucket_hits.2"] >= 2 and snap["serve.bucket_hits.4"] >= 2
    assert snap["serve.run_seconds.count"] >= 5
    assert snap["serve.padded_rows"] >= 3


def test_engine_data_parallel_matches_single_device(tmp_path):
    bundle = _bundle(tmp_path)
    x = np.random.RandomState(2).normal(0, 1, (8, 24, 24, 3)).astype(np.float32)
    solo = InferenceEngine(bundle, buckets=(8,), donate_input=False, image_size=24)
    ref = solo.predict(x)
    mesh = mesh_lib.make_mesh()
    dp = InferenceEngine(bundle, buckets=(8, 16), mesh=mesh, donate_input=False, image_size=24)
    dp.warmup()
    got = dp.predict(x)
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(bundle, buckets=(4,), mesh=mesh)


def test_engine_input_validation(tmp_path):
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2,), donate_input=False, image_size=24)
    with pytest.raises(ValueError, match="empty"):
        eng.predict(np.zeros((0, 24, 24, 3), np.float32))
    with pytest.raises(ValueError, match="expects"):
        eng.predict(np.zeros((24, 24, 3), np.float32))
    with pytest.raises(ValueError, match="bucket"):
        InferenceEngine(bundle, buckets=())


# ---------------------------------------------------------------------------
# engine: async dispatch, image-size ladder, staging, bf16
# ---------------------------------------------------------------------------


def test_engine_async_matches_sync_bitwise(tmp_path):
    """Interleaved multi-chunk predict_async == predict row-for-row, bitwise:
    both paths run the identical compiled executable, and staging-buffer
    reuse while earlier chunks are still in flight must not corrupt them."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24)
    eng.warmup()
    rs = np.random.RandomState(7)
    x = rs.normal(0, 1, (10, 24, 24, 3)).astype(np.float32)  # chunks 4, 4, 2
    y = rs.normal(0, 1, (7, 24, 24, 3)).astype(np.float32)  # chunks 4, 3->pad 4
    sync_x = eng.predict(x.copy())
    sync_y = eng.predict(y.copy())
    # two handles pending at once: all chunks of both dispatched before any sync
    hx = eng.predict_async(x)
    hy = eng.predict_async(y)
    # plus two PADDED dispatches sharing the (4, 24) staging buffer while
    # hx/hy are still unsynced — reuse must be copy-safe
    hz1 = eng.predict_async(x[:3])
    hz2 = eng.predict_async(y[:3])
    np.testing.assert_array_equal(hy.result(), sync_y)
    np.testing.assert_array_equal(hx.result(), sync_x)
    np.testing.assert_array_equal(hz1.result(), sync_x[:3])
    np.testing.assert_array_equal(hz2.result(), sync_y[:3])
    assert hx.result() is hx.result()  # the sync happens once, then caches


def test_engine_mixed_size_ladder_no_postwarmup_compile(tmp_path):
    """Mixed image-size traffic over the configured ladder hits only warm
    (bucket, size) executables — zero post-warmup compiles (the
    serve.compile_seconds counter is the recompile-cliff alarm)."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2, 4), donate_input=False, image_size=24,
                          image_sizes=(24, 32), fuse_ladder=())
    eng.warmup()
    assert set(eng._compiled) == {
        ("default", 2, 24, 1), ("default", 4, 24, 1),
        ("default", 2, 32, 1), ("default", 4, 32, 1),
    }
    reg = get_registry()
    before = reg.snapshot()["serve.compile_seconds.count"]
    rs = np.random.RandomState(3)
    for n, s in [(1, 24), (3, 32), (4, 32), (2, 24), (7, 32)]:
        out = eng.predict(rs.normal(0, 1, (n, s, s, 3)).astype(np.float32))
        assert out.shape == (n, 10)
    assert reg.snapshot()["serve.compile_seconds.count"] == before
    # a size OFF the ladder compiles lazily exactly once instead of failing
    eng.predict(np.zeros((2, 16, 16, 3), np.float32))
    eng.predict(np.zeros((2, 16, 16, 3), np.float32))
    assert reg.snapshot()["serve.compile_seconds.count"] == before + 1
    with pytest.raises(ValueError, match="expects"):
        eng.predict(np.zeros((2, 24, 32, 3), np.float32))  # non-square


def test_engine_staging_buffer_is_reused(tmp_path):
    """Padded dispatches fill one per-(bucket, size) staging buffer instead
    of np.concatenate-allocating per call."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(4,), image_size=24)
    eng.warmup()
    rs = np.random.RandomState(5)
    x = rs.normal(0, 1, (4, 24, 24, 3)).astype(np.float32)
    full = eng.predict(x)
    eng.predict(x[:2])
    buf = eng._staging[(4, 24, 1)]
    got = eng.predict(x[:3])
    assert eng._staging[(4, 24, 1)] is buf  # same buffer, not reallocated
    np.testing.assert_array_equal(got, full[:3])  # and stale rows were re-zeroed out of play


def test_engine_bf16_parity_within_pinned_tolerance(tmp_path):
    """compute_dtype=bfloat16 is a first-class serving path: logits stay
    within the pinned BF16_PARITY_ATOL of the fp32 forward on the same
    folded weights (the serve_bench A/B records the measured delta)."""
    bundle = _bundle(tmp_path, atom=True)
    fp32 = InferenceEngine(bundle, buckets=(4,), image_size=24)
    bf16 = InferenceEngine(bundle, buckets=(4,), compute_dtype="bfloat16", image_size=24)
    x = np.random.RandomState(11).normal(0, 1, (4, 24, 24, 3)).astype(np.float32)
    a, b = fp32.predict(x.copy()), bf16.predict(x.copy())
    assert a.dtype == b.dtype == np.float32  # logits are fp32 on both paths
    delta = float(np.max(np.abs(a - b)))
    assert 0 < delta <= BF16_PARITY_ATOL  # >0: bf16 genuinely computed in bf16


# ---------------------------------------------------------------------------
# fused multi-chunk dispatch: whole-request inference in one dispatch
# ---------------------------------------------------------------------------


def _dispatch_delta(reg, before):
    return reg.snapshot().get("serve.dispatch_seconds.count", 0) - before.get(
        "serve.dispatch_seconds.count", 0
    )


def test_fused_bitwise_parity_across_k(tmp_path):
    """Fused logits == per-chunk logits BITWISE for K in {1, 2, 4} and an
    off-ladder K (3 -> one 2-piece + one chunk): the scan body compiles the
    same forward at the same (bucket, size), so fusion changes the dispatch
    count, never a bit of the answer. On-ladder K is ONE dispatch."""
    bundle = _bundle(tmp_path)
    chained = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=())
    fused = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(2, 4))
    chained.warmup()
    fused.warmup()
    rs = np.random.RandomState(21)
    reg = get_registry()
    fused_base = reg.snapshot().get("serve.fused_dispatches", 0)
    # (chunk count, rows, expected fused-path dispatches)
    for k, n, want in [(1, 4, 1), (2, 8, 1), (4, 16, 1), (3, 12, 2)]:
        x = rs.normal(0, 1, (n, 24, 24, 3)).astype(np.float32)
        ref = chained.predict(x)
        before = reg.snapshot()
        got = fused.predict(x)
        np.testing.assert_array_equal(got, ref)
        assert _dispatch_delta(reg, before) == want, (k, n)
    snap = reg.snapshot()
    assert snap["serve.fused_dispatches"] - fused_base == 3  # K=2, K=4, and 3's 2-piece
    assert snap["serve.fused_chunks"] >= 2 + 4 + 2


def test_fused_tail_handling_bitwise(tmp_path):
    """Mixed tails: a tail that pads up to the max bucket joins the fused
    piece (same bucket => same executable compute => parity holds); a tail
    that fits a smaller bucket dispatches per-chunk into it, exactly as the
    chained path does. Both bitwise-equal to chained."""
    bundle = _bundle(tmp_path)
    chained = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=())
    fused = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(2, 4))
    chained.warmup()
    fused.warmup()
    rs = np.random.RandomState(29)
    reg = get_registry()
    # n=15: 4 chunks, tail of 3 pads to bucket 4 -> ONE fused K=4 dispatch
    # n=10: 3 chunks, tail of 2 fits bucket 2    -> K=2 piece + per-chunk tail
    for n, want in [(15, 1), (10, 2)]:
        x = rs.normal(0, 1, (n, 24, 24, 3)).astype(np.float32)
        ref = chained.predict(x)
        before = reg.snapshot()
        got = fused.predict(x)
        np.testing.assert_array_equal(got, ref)
        assert _dispatch_delta(reg, before) == want, n


def test_fused_bf16_bitwise_vs_chained_bf16(tmp_path):
    """The fused path is dtype-transparent: fused bf16 == chained bf16
    bitwise (and both stay within the pinned tolerance of fp32)."""
    bundle = _bundle(tmp_path, atom=True)
    chained = InferenceEngine(bundle, buckets=(4,), compute_dtype="bfloat16",
                              image_size=24, fuse_ladder=())
    fused = InferenceEngine(bundle, buckets=(4,), compute_dtype="bfloat16",
                            image_size=24, fuse_ladder=(2,))
    fp32 = InferenceEngine(bundle, buckets=(4,), image_size=24, fuse_ladder=(2,))
    x = np.random.RandomState(31).normal(0, 1, (8, 24, 24, 3)).astype(np.float32)
    ref = chained.predict(x)
    got = fused.predict(x)
    np.testing.assert_array_equal(got, ref)
    assert float(np.max(np.abs(fp32.predict(x) - got))) <= BF16_PARITY_ATOL


def test_fused_async_and_staging_reuse(tmp_path):
    """Fused predict_async == fused predict bitwise with handles pending
    concurrently, and padded fused dispatches reuse one (K, bucket, size)
    staging buffer (donation-discipline smoke: donate_input stays on)."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(2,))
    eng.warmup()
    rs = np.random.RandomState(33)
    x = rs.normal(0, 1, (7, 24, 24, 3)).astype(np.float32)  # K=2 fused, 1 pad row
    y = rs.normal(0, 1, (8, 24, 24, 3)).astype(np.float32)  # K=2 fused, exact
    sync_x, sync_y = eng.predict(x.copy()), eng.predict(y.copy())
    hx = eng.predict_async(x)
    hy = eng.predict_async(y)  # both fused dispatches pending at once
    np.testing.assert_array_equal(hy.result(), sync_y)
    np.testing.assert_array_equal(hx.result(), sync_x)
    buf = eng._staging[(4, 24, 2)]
    got = eng.predict(x)
    assert eng._staging[(4, 24, 2)] is buf  # same fused buffer, not reallocated
    np.testing.assert_array_equal(got, sync_x)


def test_batchers_route_oversized_coalesced_batch_to_fused(tmp_path):
    """Both batchers hand an oversized coalesced batch to the engine whole,
    and the engine serves it as ONE fused dispatch — continuous batching
    composes with fusion instead of falling back to the chunk loop."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(1, 4), image_size=24, fuse_ladder=(2,))
    eng.warmup()
    reg = get_registry()
    rs = np.random.RandomState(17)
    imgs = rs.normal(0, 1, (8, 24, 24, 3)).astype(np.float32)
    ref = eng.predict(imgs)
    for make in (
        lambda: MicroBatcher(eng.predict, max_batch=8, max_wait_ms=500.0),
        lambda: PipelinedBatcher(eng, max_inflight=2, max_batch=8, max_wait_ms=500.0),
    ):
        b = make().start()
        try:
            before = reg.snapshot()
            futs = [b.submit(imgs[i]) for i in range(8)]
            rows = [f.result(timeout=30) for f in futs]
        finally:
            b.stop()
        # 8 rows over max bucket 4 = 2 chunks = ONE K=2 fused dispatch
        assert _dispatch_delta(reg, before) == 1
        assert reg.snapshot()["serve.fused_dispatches"] - before.get(
            "serve.fused_dispatches", 0) == 1
        np.testing.assert_array_equal(np.stack(rows), ref)


def test_cold_compile_does_not_block_warm_dispatch(tmp_path):
    """Satellite regression: an off-ladder lazy compile used to run while
    holding the dispatch lock, stalling ALL traffic for the full compile.
    Now a warm-size dispatch completes while a cold-size compile is still
    in progress on another thread."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2,), image_size=24, fuse_ladder=())
    eng.warmup()
    gate = threading.Event()
    entered = threading.Event()
    real_build = eng._build

    def slow_build(model, bucket, size, k):
        if size == 16:  # the cold size hangs in "compile" until released
            entered.set()
            assert gate.wait(10)
        return real_build(model, bucket, size, k)

    eng._build = slow_build  # type: ignore[method-assign]
    cold_out = []
    t = threading.Thread(
        target=lambda: cold_out.append(eng.predict(np.zeros((2, 16, 16, 3), np.float32))),
        daemon=True,
    )
    try:
        t.start()
        assert entered.wait(10)  # cold compile underway, NOT holding dispatch
        warm = eng.predict(np.random.RandomState(1).normal(0, 1, (2, 24, 24, 3)).astype(np.float32))
        assert warm.shape == (2, 10)
        assert t.is_alive()  # the cold compile was still blocked: no stall
    finally:
        gate.set()
    t.join(30)
    assert not t.is_alive() and cold_out[0].shape == (2, 10)


def test_pending_prediction_result_thread_safe(tmp_path):
    """Satellite regression: concurrent result() callers used to race
    _out/_parts (double device_get, double histogram, or a dropped-parts
    crash). Now one thread syncs and every caller shares the cached array."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24)
    eng.warmup()
    x = np.random.RandomState(23).normal(0, 1, (10, 24, 24, 3)).astype(np.float32)
    ref = eng.predict(x.copy())
    reg = get_registry()
    h = eng.predict_async(x)
    before = reg.snapshot()["serve.run_seconds.count"]
    outs = [None] * 8
    barrier = threading.Barrier(8)

    def grab(i):
        barrier.wait()
        outs[i] = h.result()

    threads = [threading.Thread(target=grab, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    first = outs[0]
    assert all(o is first for o in outs)  # one sync; everyone shares the cache
    np.testing.assert_array_equal(first, ref)
    assert reg.snapshot()["serve.run_seconds.count"] - before == 1  # observed once


def test_offladder_lru_bounds_caches(tmp_path):
    """Satellite regression: a size-scanning client used to grow _compiled
    and _staging without bound. Off-ladder entries now live in a small LRU
    (on-ladder keys pinned), evictions counted."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(2,), image_size=24, fuse_ladder=(),
                          offladder_cache=2)
    eng.warmup()
    reg = get_registry()
    base = reg.snapshot().get("serve.evicted_executables", 0)
    for s in (8, 12, 16, 20):  # adversarial off-ladder size scan
        out = eng.predict(np.zeros((1, s, s, 3), np.float32))  # padded -> staging too
        assert out.shape == (1, 10)
    assert ("default", 2, 24, 1) in eng._compiled  # the ladder executable is pinned
    off = sorted(k[2] for k in eng._compiled if k[2] != 24)
    assert off == [16, 20]  # LRU kept the two most recent scan sizes
    assert reg.snapshot()["serve.evicted_executables"] - base == 2
    assert all(k[1] in (24, 16, 20) for k in eng._staging)  # staging evicts too
    # an LRU hit refreshes recency: 16 survives the next insertion, 20 goes
    eng.predict(np.zeros((1, 16, 16, 3), np.float32))
    eng.predict(np.zeros((1, 28, 28, 3), np.float32))
    assert sorted(k[2] for k in eng._compiled if k[2] != 24) == [16, 28]
    with pytest.raises(ValueError, match="offladder_cache"):
        InferenceEngine(bundle, buckets=(2,), offladder_cache=0)


# ---------------------------------------------------------------------------
# pipelined batcher: continuous batching, inflight window, completion deadlines
# ---------------------------------------------------------------------------


class _FakeAsyncEngine:
    """predict_async protocol double: records dispatches, optionally blocks
    result() on an event or fails at dispatch/sync."""

    def __init__(self, block=None, fail_dispatch=False, fail_result=False):
        self.block = block
        self.fail_dispatch = fail_dispatch
        self.fail_result = fail_result
        self.dispatches = 0
        self.batch_sizes = []

    def predict_async(self, images):
        if self.fail_dispatch:
            raise RuntimeError("dispatch died")
        self.dispatches += 1
        self.batch_sizes.append(images.shape[0])
        block, fail = self.block, self.fail_result

        class _Handle:
            def result(_self):
                if block is not None:
                    assert block.wait(10)
                if fail:
                    raise RuntimeError("sync died")
                return _row_id_predict(images)

        return _Handle()

    def predict(self, images):
        return self.predict_async(images).result()


def test_pipelined_batcher_routes_rows_concurrent():
    eng = _FakeAsyncEngine()
    b = PipelinedBatcher(eng, max_inflight=2, max_batch=8, max_wait_ms=20.0, queue_depth=64).start()
    try:
        results = {}
        lock = threading.Lock()

        def client(i):
            img = np.full((4, 4, 3), float(i), np.float32)
            val = b.submit(img).result(timeout=10)
            with lock:
                results[i] = float(val[0])

        threads = [threading.Thread(target=client, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        b.stop()
    assert results == {i: float(i) for i in range(24)}
    assert sum(eng.batch_sizes) == 24
    assert max(eng.batch_sizes) > 1, "no coalescing under 24 concurrent clients"
    snap = get_registry().snapshot()
    assert "serve.inflight" in snap  # the window gauge is registered and set


def test_pipelined_inflight_window_bounds_dispatch():
    """The window slot is reserved BEFORE dispatch: with completion blocked,
    at most max_inflight batches are ever dispatched-but-unsynced — the
    continuous-batching lookahead is bounded, not unbounded."""
    gate = threading.Event()
    eng = _FakeAsyncEngine(block=gate)
    b = PipelinedBatcher(eng, max_inflight=2, max_batch=1, max_wait_ms=0.0, queue_depth=64).start()
    img = np.zeros((2, 2, 3), np.float32)
    try:
        futs = [b.submit(img) for _ in range(10)]
        time.sleep(0.3)
        assert 1 <= eng.dispatches <= 2  # never more than the window
        gate.set()
        for f in futs:
            f.result(timeout=10)
        assert eng.dispatches == 10
    finally:
        gate.set()
        b.stop()


def test_pipelined_completion_deadline_shed():
    """A deadline that expires while the batch executes on-device sheds at
    completion: DeadlineExceeded instead of a stale answer."""
    gate = threading.Event()
    eng = _FakeAsyncEngine(block=gate)
    b = PipelinedBatcher(eng, max_inflight=1, max_batch=1, max_wait_ms=0.0).start()
    reg = get_registry()
    base = reg.snapshot().get("serve.shed_at_completion", 0)
    try:
        fut = b.submit(np.zeros((2, 2, 3), np.float32), deadline_ms=30.0)
        time.sleep(0.2)  # dispatched immediately; expires during "execution"
        gate.set()
        with pytest.raises(DeadlineExceeded, match="completed"):
            fut.result(timeout=10)
    finally:
        gate.set()
        b.stop()
    snap = reg.snapshot()
    assert snap["serve.shed_at_completion"] - base == 1
    assert snap.get("serve.shed_deadline", 0) >= 1  # feeds the shared shed counter too


def test_pipelined_engine_failures_fail_futures_not_hang():
    # failure at dispatch (collect thread)
    b = PipelinedBatcher(_FakeAsyncEngine(fail_dispatch=True), max_batch=4, max_wait_ms=1.0).start()
    try:
        with pytest.raises(RuntimeError, match="dispatch died"):
            b.submit(np.zeros((2, 2, 3), np.float32)).result(timeout=10)
        with pytest.raises(RuntimeError, match="dispatch died"):  # thread survived
            b.submit(np.zeros((2, 2, 3), np.float32)).result(timeout=10)
    finally:
        b.stop()
    # failure at sync (completion thread)
    b = PipelinedBatcher(_FakeAsyncEngine(fail_result=True), max_batch=4, max_wait_ms=1.0).start()
    try:
        with pytest.raises(RuntimeError, match="sync died"):
            b.submit(np.zeros((2, 2, 3), np.float32)).result(timeout=10)
        with pytest.raises(RuntimeError, match="sync died"):
            b.submit(np.zeros((2, 2, 3), np.float32)).result(timeout=10)
    finally:
        b.stop()


def test_pipelined_stop_drains_pending_under_load():
    gate = threading.Event()
    eng = _FakeAsyncEngine(block=gate)
    b = PipelinedBatcher(eng, max_inflight=1, max_batch=2, max_wait_ms=0.0, queue_depth=64).start()
    img = np.zeros((2, 2, 3), np.float32)
    futs = [b.submit(img) for _ in range(6)]
    stopper = threading.Thread(target=b.stop)
    stopper.start()
    time.sleep(0.1)
    gate.set()
    stopper.join(timeout=10)
    assert not stopper.is_alive(), "stop(drain=True) deadlocked under load"
    for f in futs:
        assert f.result(timeout=10) is not None  # every pre-stop request was served


def test_pipelined_mixed_image_sizes_end_to_end(tmp_path):
    """Continuous batching over mixed image sizes: interleaved 24px and 32px
    submits are partitioned by shape, served from warm (bucket, size)
    executables — correct rows, zero post-warmup compiles."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(1, 4), image_size=24, image_sizes=(24, 32))
    eng.warmup()
    rs = np.random.RandomState(13)
    im24 = rs.normal(0, 1, (6, 24, 24, 3)).astype(np.float32)
    im32 = rs.normal(0, 1, (6, 32, 32, 3)).astype(np.float32)
    ref24, ref32 = eng.predict(im24.copy()), eng.predict(im32.copy())
    before = get_registry().snapshot()["serve.compile_seconds.count"]
    b = PipelinedBatcher(eng, max_inflight=2, max_batch=8, max_wait_ms=10.0).start()
    try:
        futs = []
        for i in range(6):  # interleave the two sizes into the same queue
            futs.append((b.submit(im24[i]), ref24[i]))
            futs.append((b.submit(im32[i]), ref32[i]))
        for fut, ref in futs:
            np.testing.assert_allclose(fut.result(timeout=30), ref, atol=2e-5, rtol=1e-5)
    finally:
        b.stop()
    assert get_registry().snapshot()["serve.compile_seconds.count"] == before


def test_pipelined_batcher_with_real_engine(tmp_path):
    """End-to-end: async engine + pipelined batcher under concurrent load —
    every request's row matches the reference forward."""
    bundle = _bundle(tmp_path)
    eng = InferenceEngine(bundle, buckets=(1, 4), image_size=24)
    eng.warmup()
    rs = np.random.RandomState(9)
    imgs = rs.normal(0, 1, (12, 24, 24, 3)).astype(np.float32)
    ref = eng.predict(imgs.copy())
    b = PipelinedBatcher(eng, max_inflight=2, max_batch=4, max_wait_ms=10.0).start()
    try:
        futs = [b.submit(imgs[i]) for i in range(12)]
        rows = [f.result(timeout=30) for f in futs]
    finally:
        b.stop()
    # coalesced buckets differ from the reference's — tight allclose, not bitwise
    np.testing.assert_allclose(np.stack(rows), ref, atol=2e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# batcher satellites: accepted-only counting, event-driven idle wait
# ---------------------------------------------------------------------------


def test_submit_counts_accepted_only():
    """A rejected submit must not inflate serve.requests — only
    serve.rejected_full moves, so requests == completed + shed."""
    hold = threading.Event()

    def predict(images):
        hold.wait(5)
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, queue_depth=1).start()
    img = np.zeros((2, 2, 3), np.float32)
    reg = get_registry()
    base_req = reg.snapshot().get("serve.requests", 0)
    base_rej = reg.snapshot().get("serve.rejected_full", 0)
    try:
        futs = [b.submit(img)]
        time.sleep(0.1)  # worker holds it inside the blocked engine
        futs.append(b.submit(img))  # fills the depth-1 queue
        with pytest.raises(QueueFull):
            b.submit(img)
        snap = reg.snapshot()
        assert snap["serve.requests"] - base_req == 2  # the accepted ones only
        assert snap["serve.rejected_full"] - base_rej == 1
        hold.set()
        for f in futs:
            f.result(timeout=10)
    finally:
        hold.set()
        b.stop()


@pytest.mark.parametrize("cls", ["micro", "pipelined"])
def test_idle_batcher_does_not_spin(cls):
    """The collect wait is event-driven: an idle batcher has ZERO empty-handed
    wakeups (the old 50 ms poll produced ~5 in this window), and the first
    request of a burst is served without a poll-interval delay."""
    if cls == "micro":
        b = MicroBatcher(_row_id_predict, max_batch=4, max_wait_ms=1.0).start()
    else:
        b = PipelinedBatcher(_FakeAsyncEngine(), max_batch=4, max_wait_ms=1.0).start()
    try:
        time.sleep(0.3)  # idle
        fut = b.submit(np.zeros((2, 2, 3), np.float32))
        assert fut.result(timeout=10) is not None
    finally:
        b.stop()
    assert b._idle_wakeups == 0


def test_pipelined_rejects_bad_window():
    with pytest.raises(ValueError, match="max_inflight"):
        PipelinedBatcher(_FakeAsyncEngine(), max_inflight=0)


# ---------------------------------------------------------------------------
# robustness satellites: bounded drain, priority plumbing, crash containment
# ---------------------------------------------------------------------------


def test_micro_batcher_drain_timeout_on_hung_engine():
    """MicroBatcher.stop(drain=True) with a wedged predict fails the
    remaining futures with DrainTimeout within drain_timeout_s instead of
    hanging shutdown forever (the pre-robustness behavior)."""
    from yet_another_mobilenet_series_tpu.serve.batcher import DrainTimeout

    wedge = threading.Event()

    def predict(images):
        wedge.wait()  # never released: a truly hung engine
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, drain_timeout_s=0.4).start()
    futs = [b.submit(np.zeros((2, 2, 3), np.float32)) for _ in range(3)]
    time.sleep(0.1)
    t0 = time.perf_counter()
    b.stop()
    assert time.perf_counter() - t0 < 3.0
    for f in futs:
        with pytest.raises((DrainTimeout, RuntimeError)):
            f.result(timeout=1)
    wedge.set()  # un-wedge the abandoned daemon; its late answer is dropped
    time.sleep(0.05)
    assert get_registry().snapshot()["serve.drain_timeouts"] >= 1


def test_late_answer_after_drain_timeout_is_dropped():
    """The abandoned worker eventually returns: its set_result on an
    already-failed future must be swallowed, not crash the thread."""
    wedge = threading.Event()

    def predict(images):
        wedge.wait(10)
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, drain_timeout_s=0.2).start()
    fut = b.submit(np.zeros((2, 2, 3), np.float32))
    time.sleep(0.05)
    base_crashes = get_registry().snapshot().get("serve.thread_crashes", 0)
    b.stop()
    with pytest.raises(Exception):
        fut.result(timeout=1)
    wedge.set()
    time.sleep(0.2)  # the abandoned worker resolves into the failed future
    assert get_registry().snapshot().get("serve.thread_crashes", 0) == base_crashes


@pytest.mark.parametrize("cls", ["micro", "pipelined"])
def test_priority_plumbs_through_and_sheds_per_class(cls):
    """submit(priority=...) rides the request into the batcher; a shed is
    attributed to its class (serve.shed_deadline.<class>)."""
    release = threading.Event()

    def predict(images):
        release.wait(5)
        return _row_id_predict(images)

    class _Eng:
        def predict_async(self, images):
            class _H:
                def result(_self):
                    release.wait(5)
                    return _row_id_predict(images)
            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    if cls == "micro":
        b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, drain_timeout_s=5.0).start()
    else:
        b = PipelinedBatcher(_Eng(), max_inflight=1, max_batch=1, max_wait_ms=0.0,
                             drain_timeout_s=5.0).start()
    img = np.zeros((2, 2, 3), np.float32)
    reg = get_registry()
    base = reg.snapshot().get("serve.shed_deadline.best_effort", 0)
    try:
        first = b.submit(img, priority="interactive")  # occupies the engine
        time.sleep(0.05)
        doomed = b.submit(img, deadline_ms=10.0, priority="best_effort")
        time.sleep(0.1)
        release.set()
        first.result(timeout=10)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
    finally:
        release.set()
        b.stop()
    assert reg.snapshot()["serve.shed_deadline.best_effort"] - base == 1


def test_worker_crash_fails_live_futures_not_silent():
    """YAMT011's runtime counterpart: a bug that escapes the collect loop
    fails every live future and counts serve.thread_crashes — clients see
    the error immediately instead of hanging on a dead thread."""
    b = MicroBatcher(_row_id_predict, max_batch=4, max_wait_ms=1.0).start()
    reg = get_registry()
    base = reg.snapshot().get("serve.thread_crashes", 0)
    # sabotage an internal the loop touches on every batch: the next collect
    # raises inside the worker, OUTSIDE the engine try/except
    b._shed_expired = None  # type: ignore[assignment]
    fut = b.submit(np.zeros((2, 2, 3), np.float32))
    with pytest.raises(TypeError):  # 'NoneType' object is not callable
        fut.result(timeout=10)
    assert reg.snapshot()["serve.thread_crashes"] - base == 1
    b._thread = None  # the worker is dead; skip stop()'s join bookkeeping


# ---------------------------------------------------------------------------
# batcher: coalescing, backpressure, shedding
# ---------------------------------------------------------------------------


def _row_id_predict(images):
    # each request's image is a constant plane carrying its id; the "logits"
    # echo it so row routing is verifiable per request
    return images[:, 0, 0, :1]


def test_batcher_concurrent_clients_route_rows():
    batch_sizes = []

    def predict(images):
        batch_sizes.append(images.shape[0])
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=8, max_wait_ms=20.0, queue_depth=64).start()
    try:
        results = {}
        lock = threading.Lock()

        def client(i):
            img = np.full((4, 4, 3), float(i), np.float32)
            val = b.submit(img).result(timeout=10)
            with lock:
                results[i] = float(val[0])

        threads = [threading.Thread(target=client, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        b.stop()
    assert results == {i: float(i) for i in range(24)}
    assert max(batch_sizes) > 1, "no coalescing happened under 24 concurrent clients"
    assert sum(batch_sizes) == 24
    snap = get_registry().snapshot()
    assert snap["serve.queue_wait_seconds.count"] >= 24
    assert snap["serve.batch_size.max"] > 1


def test_batcher_backpressure_queue_full():
    hold = threading.Event()

    def predict(images):
        hold.wait(5)
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, queue_depth=2).start()
    img = np.zeros((2, 2, 3), np.float32)
    try:
        futs = [b.submit(img)]
        time.sleep(0.1)  # let the worker pull one into the (blocked) engine
        with pytest.raises(QueueFull):
            for _ in range(8):
                futs.append(b.submit(img))
        hold.set()
        for f in futs:
            f.result(timeout=10)
    finally:
        hold.set()
        b.stop()
    assert get_registry().snapshot()["serve.rejected_full"] >= 1


def test_batcher_deadline_shedding():
    release = threading.Event()

    def predict(images):
        release.wait(5)
        return _row_id_predict(images)

    b = MicroBatcher(predict, max_batch=1, max_wait_ms=0.0, queue_depth=16).start()
    img = np.zeros((2, 2, 3), np.float32)
    try:
        first = b.submit(img)  # occupies the engine
        time.sleep(0.05)
        doomed = b.submit(img, deadline_ms=10.0)  # expires while queued
        time.sleep(0.1)
        release.set()
        first.result(timeout=10)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
    finally:
        release.set()
        b.stop()
    assert get_registry().snapshot()["serve.shed_deadline"] >= 1


def test_batcher_engine_failure_fails_futures_not_hangs():
    def predict(images):
        raise RuntimeError("engine died")

    b = MicroBatcher(predict, max_batch=4, max_wait_ms=1.0).start()
    try:
        fut = b.submit(np.zeros((2, 2, 3), np.float32))
        with pytest.raises(RuntimeError, match="engine died"):
            fut.result(timeout=10)
        # the worker survived the exception and keeps serving
        fut2 = b.submit(np.zeros((2, 2, 3), np.float32))
        with pytest.raises(RuntimeError, match="engine died"):
            fut2.result(timeout=10)
    finally:
        b.stop()


def test_batcher_lifecycle_errors():
    b = MicroBatcher(_row_id_predict)
    with pytest.raises(RuntimeError, match="not started"):
        b.submit(np.zeros((2, 2, 3), np.float32))
    b.start()
    with pytest.raises(RuntimeError, match="already started"):
        b.start()
    b.stop()
    with pytest.raises(ValueError, match="max_batch"):
        MicroBatcher(_row_id_predict, max_batch=0)


# ---------------------------------------------------------------------------
# the acceptance round trip: train -> ckpt -> cli.serve export -> serve
# ---------------------------------------------------------------------------


def test_train_export_serve_round_trip(tmp_path):
    from yet_another_mobilenet_series_tpu.cli import serve as cli_serve
    from yet_another_mobilenet_series_tpu.cli import train as cli_train

    train_dir = tmp_path / "run"
    cfg = config_from_dict({
        "name": "serve-smoke",
        "model": {
            "arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
            "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}],
        },
        "data": {"dataset": "fake", "image_size": 24, "fake_train_size": 64, "fake_eval_size": 16},
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.01, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {
            "batch_size": 32, "eval_batch_size": 16, "epochs": 1, "log_every": 10,
            "compute_dtype": "float32", "log_dir": str(train_dir),
        },
        "dist": {"num_devices": 8},
    })
    cli_train.run(cfg)

    serve_dir = tmp_path / "serving"
    serve_cfg = config_from_dict({
        "data": {"image_size": 24},
        "train": {"log_dir": str(serve_dir)},
        "serve": {
            "export_from": str(train_dir / "ckpt"),
            "bundle": str(tmp_path / "bundle"),
            "buckets": [2, 8],
            "max_batch": 8,
            "max_wait_ms": 5.0,
            "requests": 24,
            "clients": 6,
        },
    })
    result = cli_serve.run(serve_cfg)
    assert result["bundle"] == str(tmp_path / "bundle")
    assert result["completed"] == 24 and result["shed"] == 0
    assert result["p99_ms"] >= result["p50_ms"] > 0
    assert result["qps"] > 0

    # the bundle is a valid folded artifact of the TRAINED (EMA) weights
    bundle = load_bundle(str(tmp_path / "bundle"))
    assert bundle.meta["ema"] is True and bundle.meta["step"] > 0
    assert spec_is_inference(json.loads((tmp_path / "bundle" / "spec.json").read_text()))

    # acceptance: queue-wait + run-latency histograms visible in the snapshot
    snap = json.loads((serve_dir / "obs_registry.json").read_text())
    assert snap["serve.queue_wait_seconds.count"] >= 24
    assert snap["serve.run_seconds.count"] >= 1
    assert snap["serve.exports"] >= 1
    assert snap["serve.completed"] >= 24
    # ≥ 2 buckets compiled (warmup) — both hit across the suite's traffic
    assert snap["serve.compile_seconds.count"] >= 2

    # scripts/obs_report.py renders serving runs too
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.summarize(str(serve_dir))
    assert "## serving" in report
    assert "queue wait" in report and "run latency" in report
