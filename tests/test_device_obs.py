"""obs/device.py — the device-side telemetry layer (compile/cost accounting,
memory gauges, dispatch efficiency, profiler capture) and its wiring through
the serve engine (every warmed executable cost-accounted in the snapshot)
and the watchdog hang report."""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import ModelConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs import device as obs_device
from yet_another_mobilenet_series_tpu.obs.registry import MetricsRegistry, get_registry
from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, fold_network


def _tiny_bundle(num_classes=8, image_size=24):
    mc = ModelConfig(arch="mobilenet_v2", num_classes=num_classes, dropout=0.0,
                     block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}])
    net = get_model(mc, image_size)
    params, state = net.init(jax.random.PRNGKey(0))
    return InferenceBundle(net=net, params=fold_network(net, params, state), meta={})


# ---------------------------------------------------------------------------
# timed_compile / record_cost primitives
# ---------------------------------------------------------------------------


def test_timed_compile_records_time_and_cost():
    reg = MetricsRegistry()
    lowered = jax.jit(lambda x: jnp.tanh(x @ x)).lower(
        jax.ShapeDtypeStruct((8, 8), jnp.float32))
    exe = obs_device.timed_compile(lowered, "t_unit_matmul", registry=reg)
    # the wrapper returns a runnable executable
    y = exe(jnp.ones((8, 8), jnp.float32))
    assert y.shape == (8, 8)
    snap = reg.snapshot()
    assert snap["obs.compiles"] == 1.0
    assert snap["obs.compile_seconds.count"] == 1.0 and snap["obs.compile_seconds.sum"] > 0
    # XLA knows this program's FLOPs: 8x8x8 matmul -> 2*512 plus the tanh
    assert snap["obs.cost_flops.t_unit_matmul"] >= 2 * 8 * 8 * 8
    assert snap["obs.cost_bytes.t_unit_matmul"] > 0
    rep = obs_device.compile_report()["t_unit_matmul"]
    assert rep["flops"] == snap["obs.cost_flops.t_unit_matmul"]
    assert rep["compile_seconds"] > 0
    assert obs_device.flops_for("t_unit_matmul") == rep["flops"]
    assert obs_device.flops_for("never_compiled") == 0.0


def test_record_cost_survives_broken_stage():
    """Cost analysis is telemetry: a stage whose cost_analysis raises (or
    returns garbage) records nothing and never raises."""
    reg = MetricsRegistry()

    class _Broken:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

    class _Garbage:
        def cost_analysis(self):
            return "not a dict"

    assert obs_device.record_cost("t_broken", _Broken(), registry=reg) == {}
    assert obs_device.record_cost("t_garbage", _Garbage(), registry=reg) == {}
    snap = reg.snapshot()
    assert "obs.cost_flops.t_broken" not in snap
    # the report still names the executable (empty cost), so a hang report
    # shows the compile happened even when the backend hid its cost
    assert obs_device.compile_report()["t_broken"] == {}


def test_extract_cost_merges_list_of_modules():
    """Compiled.cost_analysis returns a LIST of per-module dicts on some
    backends — entries must merge additively."""
    raw = [{"flops": 10.0, "bytes accessed": 5.0}, {"flops": 2.0}]
    assert obs_device._extract_cost(raw) == {"flops": 12.0, "bytes": 5.0}
    assert obs_device._extract_cost(None) == {}
    assert obs_device._extract_cost({"utilization": 1.0}) == {}


# ---------------------------------------------------------------------------
# memory gauges + build info
# ---------------------------------------------------------------------------


def test_memory_gauges_pull_without_device_sync():
    reg = MetricsRegistry()
    obs_device._MEM_INSTALLED = False  # idempotence latch: reset for the test
    obs_device.install_memory_gauges(reg)
    obs_device.install_memory_gauges(reg)  # idempotent: no double-install error
    snap = reg.snapshot()
    assert snap["host.rss_bytes"] > 1e6  # a live python process
    assert snap["device.live_buffer_bytes"] >= 0


def test_build_info_fields_and_exposition():
    info = obs_device.build_info()
    assert info["jax_version"] == jax.__version__
    assert info["platform"] == jax.default_backend()
    assert len(info["git_sha"]) >= 7  # a real checkout sha (this repo is one)
    reg = MetricsRegistry()
    reg.set_build_info(info)
    text = reg.render_prometheus()
    assert "# TYPE build_info gauge" in text
    line = next(l for l in text.splitlines() if l.startswith("build_info{"))
    assert f'jax_version="{jax.__version__}"' in line
    assert f'git_sha="{info["git_sha"]}"' in line
    assert line.endswith("} 1")
    assert reg.build_info == info


# ---------------------------------------------------------------------------
# engine wiring: warmed executables cost-accounted, dispatch efficiency
# ---------------------------------------------------------------------------


def test_engine_warmup_cost_accounts_every_executable():
    """The acceptance claim: every warmed serve executable reports
    cost_analysis flops/bytes in the obs snapshot, dispatches feed the
    dispatched-FLOPs counter, and the achieved-FLOPS gauge derives from
    cost / measured run seconds."""
    reg = get_registry()
    engine = InferenceEngine(_tiny_bundle(), buckets=(2, 4), image_size=24,
                             fuse_ladder=(2,))
    engine.warmup()
    snap = reg.snapshot()
    for bucket, size, k in [(2, 24, 1), (4, 24, 1), (4, 24, 2)]:
        key = f"serve_b{bucket}_s{size}_k{k}"
        assert snap[f"obs.cost_flops.{key}"] > 0, key
        assert snap[f"obs.cost_bytes.{key}"] > 0, key
    assert snap["obs.compiles"] >= 3

    flops0 = snap.get("serve.dispatched_flops", 0.0)
    x = np.random.RandomState(0).normal(0, 1, (3, 24, 24, 3)).astype(np.float32)
    engine.predict(x)
    snap = reg.snapshot()
    # a 3-row request pads into the 4-bucket: its executable's full cost hit
    # the device regardless of padding
    assert snap["serve.dispatched_flops"] - flops0 == pytest.approx(
        snap["obs.cost_flops.serve_b4_s24_k1"])
    assert snap["serve.achieved_flops_per_s"] > 0
    # fused dispatch: k chunks account k x the per-chunk cost (XLA costs a
    # scan body once; the program runs it k times)
    flops1 = snap["serve.dispatched_flops"]
    x8 = np.random.RandomState(1).normal(0, 1, (8, 24, 24, 3)).astype(np.float32)
    engine.predict(x8)
    snap = reg.snapshot()
    assert snap["serve.dispatched_flops"] - flops1 == pytest.approx(
        2 * snap["obs.cost_flops.serve_b4_s24_k1"])


def test_hang_report_carries_executable_costs(tmp_path):
    """The watchdog hang report names every compiled executable with its
    cost — a hang right after a compile is attributable."""
    from yet_another_mobilenet_series_tpu.obs.watchdog import StallWatchdog

    reg = MetricsRegistry()
    lowered = jax.jit(lambda x: x * 2).lower(jax.ShapeDtypeStruct((4,), jnp.float32))
    obs_device.timed_compile(lowered, "t_hang_probe", registry=reg)
    wd = StallWatchdog(str(tmp_path), deadline_s=0.2, poll_s=0.05, registry=reg)
    wd.start()
    wd.arm(step=1)
    deadline = time.time() + 10
    report_path = tmp_path / "hang_report.json"
    while time.time() < deadline and not report_path.exists():
        time.sleep(0.05)
    wd.stop()
    rep = json.loads(report_path.read_text())
    assert "t_hang_probe" in rep["executables"]
    assert rep["executables"]["t_hang_probe"]["compile_seconds"] > 0


# ---------------------------------------------------------------------------
# profiler capture
# ---------------------------------------------------------------------------


def test_profiler_capture_single_flight(tmp_path):
    cap = obs_device.ProfilerCapture(str(tmp_path / "trace"))
    assert not cap.active
    out = cap.start()
    assert cap.active and out["trace_dir"].endswith("trace")
    with pytest.raises(RuntimeError, match="already active"):
        cap.start()
    jnp.square(jnp.arange(128.0)).block_until_ready()  # something to capture
    out = cap.stop()
    assert not cap.active and out["captured_s"] >= 0
    with pytest.raises(RuntimeError, match="no profiler capture"):
        cap.stop()
    # the xplane dump landed where trace_ops reads
    assert list((tmp_path / "trace").rglob("*.xplane.pb"))
    # stop_if_active on an idle capture is a no-op, on an open one it closes
    cap.stop_if_active()
    cap.start()
    cap.stop_if_active()
    assert not cap.active
