"""Worker for the true multi-process distributed test (tests/test_multiproc.py).

Runs as `python tests/_multiproc_worker.py <pid> <nproc> <port> <tmpdir> [scenario]`:
joins a real jax.distributed cluster of <nproc> CPU processes (4 fake devices
each), then drives the full cli_train.run() — per-process data sharding
(make_array_from_process_local_data), psum SyncBN + grad pmean across hosts,
eval batch-count equalization, coordinator-only logging, and the coordinated
Orbax save. Prints one `RESULT {json}` line for the parent to compare.

Scenarios (VERDICT r3 #6 added the second):
  fake   — tf.data synthetic pipeline (default)
  folder — ImageFolder tree under <tmpdir>/data through the native C++
           loader: per-host file sharding, padded label=-1 eval tails, and
           the equal-collective-step-count (pod-deadlock) guard exercised
           under REAL multi-process jax.distributed.
"""

import json
import os
import sys


def main():
    pid, nproc, port, tmpdir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    scenario = sys.argv[5] if len(sys.argv) > 5 else "fake"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    import jax

    # env var is not enough: sitecustomize force-registers the TPU platform
    jax.config.update("jax_platforms", "cpu")
    # generous shutdown barrier: on a loaded single-core sandbox the
    # coordinator's final checkpoint flush can lag the other process by
    # minutes, and the default 300 s barrier then kills the whole test
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=nproc, process_id=pid,
        # 4 heavy processes on ONE visible core: under a contended full
        # suite the coordinator's final flush can lag far beyond the 2-proc
        # case — an expired barrier turns scheduler starvation into a
        # nonzero exit (seen once at nproc=4 in the round-5 full suite)
        shutdown_timeout_seconds=2400,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.local_device_count() == 4
    assert jax.device_count() == 4 * nproc

    from yet_another_mobilenet_series_tpu.cli import train as cli_train
    from yet_another_mobilenet_series_tpu.config import config_from_dict

    if scenario == "fake4":
        # 4-process scale scenario (VERDICT r4 next #3): same fake pipeline,
        # shortened — the 16-device/4-host collective plumbing is the
        # target, and 4 processes share ONE visible core, so keep the step
        # count minimal. eval 72 does not divide 4 hosts x batch evenly
        # either (18/host), so padded-tail equalization is still exercised.
        data = {"dataset": "fake", "image_size": 32, "fake_train_size": 320, "fake_eval_size": 72}
        epochs = 1.0
    elif scenario == "folder":
        # 80 train JPEGs (40/host >= one local batch of 32) and 54 val
        # JPEGs: 27/host at local eval batch 16 -> 2 padded batches/host
        # with label=-1 tails; eval_n must still psum to exactly 54
        data = {"dataset": "folder", "loader": "native",
                "data_dir": os.path.join(tmpdir, "data"), "image_size": 32,
                "num_train_examples": 80, "num_eval_examples": 54,
                "decode_threads": 2}
        epochs = 4.0
    else:
        # fake_eval_size 72 does NOT divide eval batches evenly: 72/2 hosts =
        # 36 each, batch 16 -> 3 padded batches/host (equalization exercised)
        data = {"dataset": "fake", "image_size": 32, "fake_train_size": 1280, "fake_eval_size": 72}
        epochs = 2.0
    # fake scenario also exercises grouped dispatch under REAL multi-process
    # jax.distributed (2 steps/jit call; cross-host collectives inside the
    # unrolled program). folder's 1 step/epoch never reaches a full group.
    steps_per_dispatch = 2 if scenario in ("fake", "fake4") else 1
    cfg = config_from_dict({
        "name": "multiproc",
        "model": {
            "arch": "mobilenet_v2",
            "num_classes": 8,
            "dropout": 0.0,
            "block_specs": [
                {"t": 3, "c": 16, "n": 1, "s": 2, "k": 3},
                {"t": 3, "c": 24, "n": 1, "s": 2, "k": 3},
            ],
        },
        "data": data,
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.05, "scale_by_batch": False, "warmup_epochs": 0.2},
        "ema": {"enable": True, "decay": 0.99},
        "train": {
            "batch_size": 64,
            "eval_batch_size": 32,
            "epochs": epochs,
            "steps_per_dispatch": steps_per_dispatch,
            "log_every": 2,
            "compute_dtype": "float32",
            "log_dir": tmpdir,
            "eval_every_epochs": 1.0,
            "param_checksum_every": 5,  # cross-HOST divergence check in-loop
        },
        "dist": {"num_devices": 4 * nproc},
    })
    result = cli_train.run(cfg)
    # every process must agree on the metrics (they come out of collectives)
    print(f"RESULT {json.dumps({'pid': pid, **{k: round(float(v), 6) for k, v in result.items()}})}", flush=True)


if __name__ == "__main__":
    main()
