"""scripts/trace_ops.py on a tiny checked-in xplane fixture (network-free):
the aggregation functions the profiler-capture endpoints feed, previously
untested — including the jaxlib-0.4.36 regression where the CPU-client
thunk line was named ``tf_XLATfrtCpuClient`` and the exact-name match
aggregated zero events."""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "xplane")

pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2",
                    reason="xplane proto unavailable")


def _mod():
    spec = importlib.util.spec_from_file_location(
        "trace_ops", os.path.join(REPO, "scripts", "trace_ops.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_op_kind_collapse():
    m = _mod()
    assert m.op_kind("fusion.123") == "fusion"
    assert m.op_kind("%dot.2") == "dot"
    assert m.op_kind("all-reduce-start") == "all-reduce-start"
    assert m.op_kind("tanh") == "tanh"


def test_fixture_host_aggregation_sees_cpu_client_thunks():
    """The fixture traces a jitted tanh(x @ x) on CPU: the host fallback must
    find the dot + tanh thunk events on the tf_XLATfrtCpuClient line (the
    old XLAEigen/PjRtCpuClient exact match returned zero events here)."""
    m = _mod()
    xs, path = m.load_xspace(FIXTURE_DIR)
    assert path.endswith("vm.xplane.pb")
    host = m.aggregate_host(xs)
    assert host["n_events"] > 0, "CPU-client thunk line not matched"
    kinds = set(host["per_cat"])
    assert "dot" in kinds and "tanh" in kinds
    assert host["total_ps"] == sum(host["per_cat"].values()) > 0
    # the fixture has no device plane — the TPU aggregator must say so, not
    # fabricate one
    assert not any(p.name.startswith("/device:TPU") for p in xs.planes)


def test_load_xspace_missing_dir():
    m = _mod()
    with pytest.raises(FileNotFoundError, match="no .xplane.pb"):
        m.load_xspace("/definitely/not/a/dir")


def test_main_renders_fallback_and_table_check(tmp_path, capsys):
    """End-to-end CLI pass over the fixture, including the latency-table
    cross-check (table total + trace total + the provenance warning)."""
    m = _mod()
    table = tmp_path / "LATENCY_TABLE_t.json"
    table.write_text(json.dumps({
        "entries": [
            {"key": "a", "alive_channels": [4, 8], "latency_s": [1e-4, 2e-4]},
            {"key": "b", "alive_channels": [8, 16], "latency_s": [3e-4, 5e-4]},
        ],
        "provenance": {"device_kind": "cpu", "cpu_rehearsal": True},
    }))
    rc = m.main([FIXTURE_DIR, "5", "--check-table", str(table)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no /device:TPU plane" in out
    assert "/host:CPU" in out and "dot" in out
    assert "latency-table cross-check" in out
    # predicted total = sum of full-width points = 0.2 + 0.5 ms
    assert "0.700 ms/image" in out
    assert "cpu_rehearsal=True" in out


def test_table_prediction_full_width_points(tmp_path):
    m = _mod()
    table = tmp_path / "t.json"
    # unsorted ladder: the full-width point is the LARGEST channels entry,
    # not the last list element
    table.write_text(json.dumps({"entries": [
        {"key": "a", "alive_channels": [8, 4], "latency_s": [2e-4, 1e-4]}]}))
    pred = m.table_prediction(str(table))
    assert pred["entries"] == 1
    assert pred["blocks_total_ms"] == pytest.approx(0.2)
