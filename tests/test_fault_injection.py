"""Failure-recovery tests (SURVEY.md §5 failure detection / §4.3).

Two halves:

- **training** (slow): SIGKILL a training process mid-run, then verify a
  relaunch resumes cleanly from the latest checkpoint and finishes — the
  preemption-recovery story of the framework (gang-scheduled SPMD: a dead
  process means relaunch + resume).
- **serving** (fast, tier-1): seeded chaos via serve/faults.py against the
  admission/retry/breaker/drain stack (serve/admission.py,
  serve/batcher.py) — engine failures hit only their own clients, retries
  absorb transients, the breaker opens on a failure streak and recovers
  through its half-open probe, an injected hang trips the drain timeout
  instead of hanging shutdown, and under mixed chaos NO client call ever
  hangs: every future resolves to a result or a typed error. The fault
  schedule is deterministic (seeded), so these are regression tests, not
  flaky chaos monkeys.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve.admission import (
    AdmissionController,
    BreakerOpen,
    BREAKER_CLOSED,
    BREAKER_OPEN,
)
from yet_another_mobilenet_series_tpu.serve.batcher import DeadlineExceeded, DrainTimeout, QueueFull
from yet_another_mobilenet_series_tpu.serve.faults import FaultyEngine, InjectedFault
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DRIVER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import jax
jax.config.update("jax_platforms", "cpu")
from yet_another_mobilenet_series_tpu.cli.train import main
main(sys.argv[1:])
"""


def _args(log_dir, epochs):
    return [
        "data.dataset=fake", "data.image_size=24", "data.fake_train_size=320", "data.fake_eval_size=32",
        "model.arch=mobilenet_v2", "model.num_classes=4", "model.dropout=0.0",
        "model.block_specs=[{t: 2, c: 8, n: 1, s: 2}]",
        "train.batch_size=32", "train.eval_batch_size=32", "train.log_every=5",
        "train.compute_dtype=float32", f"train.log_dir={log_dir}",
        "train.eval_every_epochs=100",  # keep the victim run simple
        "schedule.base_lr=0.02", "schedule.warmup_epochs=0", "schedule.scale_by_batch=false",
        "dist.num_devices=8", f"train.epochs={epochs}",
    ]


# ---------------------------------------------------------------------------
# serve-side chaos (fast, tier-1): serve/faults.py against the resilience edge
# ---------------------------------------------------------------------------


def _row_id_predict(images):
    return images[:, 0, 0, :1]


class _EchoEngine:
    """Pure-host engine protocol double: logits echo each image's id plane,
    so row routing survives any amount of chaos re-batching."""

    def predict_async(self, images):
        class _Handle:
            def result(_self):
                return _row_id_predict(images)

        return _Handle()

    def predict(self, images):
        return self.predict_async(images).result()


def _img(val=0.0):
    return np.full((4, 4, 3), float(val), np.float32)


def _batcher(engine, **kw):
    kw.setdefault("max_batch", 1)  # one request per dispatch: fault schedule == request order
    kw.setdefault("max_wait_ms", 0.0)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("drain_timeout_s", 2.0)
    return PipelinedBatcher(engine, **kw).start()


def test_faulty_engine_schedule_is_deterministic():
    """Same seed -> bitwise-identical fault schedule; different seed differs
    (the chaos suite is a regression suite, not a dice roll)."""
    def schedule(seed):
        eng = FaultyEngine(_EchoEngine(), seed=seed, failure_rate=0.3, latency_s=0.001, latency_rate=0.2)
        out = []
        for _ in range(64):
            try:
                eng.predict(_img()[None])  # direct engine call: batched input
                out.append("ok")
            except InjectedFault:
                out.append("fail")
        return out

    assert schedule(7) == schedule(7)
    assert schedule(7) != schedule(11)


def test_latency_after_n_gates_the_degrade_onset():
    """latency_after_n: the first N dispatches run CLEAN, then the latency
    injection begins — the mid-run gray-failure knob (a replica that was
    healthy when the router learned its baseline, then degraded)."""
    reg = get_registry()
    eng = FaultyEngine(_EchoEngine(), seed=0, latency_s=0.05, latency_rate=1.0,
                       latency_after_n=3)
    d0 = reg.snapshot().get("serve.faults.delays", 0)
    clean_t0 = time.perf_counter()
    for _ in range(3):
        eng.predict(_img()[None])
    clean_s = time.perf_counter() - clean_t0
    assert reg.snapshot().get("serve.faults.delays", 0) == d0  # onset not reached
    t0 = time.perf_counter()
    eng.predict(_img()[None])  # dispatch #3: the onset
    assert time.perf_counter() - t0 >= 0.05
    assert reg.snapshot().get("serve.faults.delays", 0) == d0 + 1
    assert clean_s < 0.05  # the pre-onset dispatches really were undelayed


@pytest.mark.parametrize("fail_at", ["dispatch", "result"])
def test_fail_n_batches_only_those_clients_error(fail_at):
    """The first N dispatches fail (at either failure edge): exactly those
    clients see the error, everyone after gets correct rows — the engine
    failure stays contained to its own batch."""
    eng = FaultyEngine(_EchoEngine(), fail_first_n=2, fail_at=fail_at)
    b = _batcher(eng)
    try:
        outcomes = []
        for i in range(6):
            fut = b.submit(_img(i))
            try:
                outcomes.append(float(fut.result(timeout=10)[0]))
            except InjectedFault:
                outcomes.append("fault")
        assert outcomes == ["fault", "fault", 2.0, 3.0, 4.0, 5.0]
    finally:
        b.stop()


def test_retry_absorbs_transient_failures():
    """A transient failure costs a bounded retry, not a client error:
    fail-1-then-recover resolves correctly with serve.retries counted."""
    eng = FaultyEngine(_EchoEngine(), fail_first_n=1)
    b = _batcher(eng)
    ac = AdmissionController(b, max_retries=2, retry_backoff_ms=1.0, breaker_threshold=10)
    base = get_registry().snapshot()
    try:
        assert float(ac.submit(_img(3)).result(timeout=10)[0]) == 3.0
    finally:
        b.stop()
    snap = get_registry().snapshot()
    assert snap["serve.retries"] - base.get("serve.retries", 0) == 1
    assert snap["serve.retries.interactive"] - base.get("serve.retries.interactive", 0) == 1
    assert snap["serve.completed.interactive"] - base.get("serve.completed.interactive", 0) == 1


def test_retries_are_bounded():
    """A hard-down engine exhausts max_retries and surfaces the error —
    never an unbounded retry loop."""
    eng = FaultyEngine(_EchoEngine(), failure_rate=1.0)
    b = _batcher(eng)
    ac = AdmissionController(b, max_retries=2, retry_backoff_ms=1.0, breaker_threshold=100)
    base = get_registry().snapshot()
    try:
        with pytest.raises(InjectedFault):
            ac.submit(_img()).result(timeout=10)
    finally:
        b.stop()
    snap = get_registry().snapshot()
    assert snap["serve.retries"] - base.get("serve.retries", 0) == 2  # bounded: 1 try + 2 retries
    assert snap["serve.engine_failures"] - base.get("serve.engine_failures", 0) == 3


def test_breaker_opens_on_streak_and_recovers_via_probe():
    """The full breaker lifecycle: a failure streak opens it (fast-fail, no
    engine traffic), the cooldown admits ONE half-open probe, probe success
    closes it and traffic resumes."""
    eng = FaultyEngine(_EchoEngine(), fail_first_n=3)
    b = _batcher(eng)
    ac = AdmissionController(b, max_retries=0, breaker_threshold=3, breaker_cooldown_s=0.15)
    reg = get_registry()
    base = reg.snapshot()
    try:
        for _ in range(3):
            with pytest.raises(InjectedFault):
                ac.submit(_img()).result(timeout=10)
        assert ac.breaker.state == BREAKER_OPEN
        assert reg.snapshot()["serve.breaker_state"] == BREAKER_OPEN
        dispatched_when_open = eng._idx
        with pytest.raises(BreakerOpen):
            ac.submit(_img())
        assert eng._idx == dispatched_when_open  # fast fail: the engine saw nothing
        time.sleep(0.2)  # cooldown elapses -> next arrival is the probe
        assert float(ac.submit(_img(9)).result(timeout=10)[0]) == 9.0
        assert ac.breaker.state == BREAKER_CLOSED
        assert reg.snapshot()["serve.breaker_state"] == BREAKER_CLOSED
        assert float(ac.submit(_img(4)).result(timeout=10)[0]) == 4.0  # traffic resumed
    finally:
        b.stop()
    snap = reg.snapshot()
    assert snap["serve.breaker_opens"] - base.get("serve.breaker_opens", 0) == 1
    assert snap["serve.rejected_breaker"] - base.get("serve.rejected_breaker", 0) == 1


def test_failed_probe_reopens_breaker():
    """A half-open probe that fails re-opens the breaker for another full
    cooldown instead of closing it."""
    eng = FaultyEngine(_EchoEngine(), fail_first_n=4)  # streak of 3 + the probe
    b = _batcher(eng)
    ac = AdmissionController(b, max_retries=0, breaker_threshold=3, breaker_cooldown_s=0.15)
    try:
        for _ in range(3):
            with pytest.raises(InjectedFault):
                ac.submit(_img()).result(timeout=10)
        time.sleep(0.2)
        with pytest.raises(InjectedFault):  # the probe itself fails
            ac.submit(_img()).result(timeout=10)
        assert ac.breaker.state == BREAKER_OPEN
        with pytest.raises(BreakerOpen):  # re-opened: fast fail again
            ac.submit(_img())
        time.sleep(0.2)  # second cooldown; engine recovered by now
        assert float(ac.submit(_img(5)).result(timeout=10)[0]) == 5.0
        assert ac.breaker.state == BREAKER_CLOSED
    finally:
        b.stop()


def test_injected_hang_trips_drain_timeout():
    """A wedged engine cannot hang shutdown: stop(drain=True) fails the
    still-unresolved requests with DrainTimeout within drain_timeout_s and
    abandons the wedged (daemon) worker."""
    eng = FaultyEngine(_EchoEngine(), hang_at=0)
    b = _batcher(eng, drain_timeout_s=0.5)
    reg = get_registry()
    base = reg.snapshot()
    futs = [b.submit(_img(i)) for i in range(3)]
    time.sleep(0.1)  # first batch dispatched and wedged
    t0 = time.perf_counter()
    b.stop()
    stop_s = time.perf_counter() - t0
    assert stop_s < 3.0, f"stop took {stop_s:.1f}s — the drain bound did not hold"
    for fut in futs:
        with pytest.raises((DrainTimeout, RuntimeError)):
            fut.result(timeout=1)
    snap = reg.snapshot()
    assert snap["serve.drain_timeouts"] - base.get("serve.drain_timeouts", 0) == 1
    assert snap["serve.faults.hangs"] - base.get("serve.faults.hangs", 0) == 1


def test_hang_release_recovers():
    """hang-until-event is a hang, not a kill: releasing the event serves
    the wedged batch for real."""
    eng = FaultyEngine(_EchoEngine(), hang_at=0)
    b = _batcher(eng, drain_timeout_s=5.0)
    try:
        fut = b.submit(_img(8))
        time.sleep(0.05)
        assert not fut.done()
        eng.hang_release.set()
        assert float(fut.result(timeout=10)[0]) == 8.0
    finally:
        b.stop()


def test_mixed_chaos_no_client_ever_hangs():
    """The acceptance criterion: under seeded failures + latency spikes,
    with retries, deadlines, and concurrent clients, EVERY call resolves —
    a result or a typed error, never a hang — and the books balance."""
    eng = FaultyEngine(_EchoEngine(), seed=3, failure_rate=0.25, latency_s=0.01, latency_rate=0.3)
    b = _batcher(eng, max_batch=4, max_wait_ms=1.0, drain_timeout_s=5.0)
    ac = AdmissionController(
        b, max_retries=2, retry_backoff_ms=1.0, breaker_threshold=50, breaker_cooldown_s=0.1
    )
    classes = ("interactive", "batch", "best_effort")
    outcomes = {"ok": 0, "typed_error": 0, "rejected": 0}
    lock = threading.Lock()

    def client(i):
        try:
            fut = ac.submit(_img(i), priority=classes[i % 3], deadline_ms=5000.0)
        except (QueueFull, BreakerOpen) as e:  # typed arrival rejection
            with lock:
                outcomes["rejected"] += 1
            return
        try:
            val = fut.result(timeout=30)  # a hang fails the test right here
            assert float(val[0]) == float(i)
            with lock:
                outcomes["ok"] += 1
        except (InjectedFault, DeadlineExceeded, DrainTimeout):
            with lock:
                outcomes["typed_error"] += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True) for i in range(40)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "a client hung"
    b.stop()
    assert sum(outcomes.values()) == 40  # every call resolved, one way or another
    assert outcomes["ok"] > 0  # chaos did not take the service down


@pytest.mark.slow
def test_sigkill_midrun_then_resume(tmp_path):
    log_dir = str(tmp_path / "run")
    env = dict(os.environ, PYTHONPATH=REPO)

    # victim: many epochs, checkpointing every epoch
    victim = subprocess.Popen(
        [sys.executable, "-c", _DRIVER] + _args(log_dir, epochs=50),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # wait until at least one checkpoint is fully written, then SIGKILL
    deadline = time.time() + 300
    ckpt_dir = os.path.join(log_dir, "ckpt")
    seen = False
    while time.time() < deadline:
        if victim.poll() is not None:
            out = victim.stdout.read()
            pytest.fail(f"victim exited early:\n{out[-2000:]}")
        steps = [d for d in (os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []) if d.isdigit()]
        # orbax renames the tmp dir into place when complete
        if steps and all("tmp" not in d for d in steps):
            seen = True
            time.sleep(1.0)  # let another save start mid-flight for extra chaos
            break
        time.sleep(0.5)
    assert seen, "no checkpoint appeared within the deadline"
    victim.send_signal(signal.SIGKILL)
    victim.wait()
    victim.stdout.read()

    # relaunch with a small total epoch budget: must resume and complete
    out = subprocess.run(
        [sys.executable, "-c", _DRIVER] + _args(log_dir, epochs=6),
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "resumed at step" in out.stdout
    assert "done:" in out.stdout
