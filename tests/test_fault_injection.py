"""Failure-recovery test (SURVEY.md §5 failure detection / §4.3): SIGKILL a
training process mid-run, then verify a relaunch resumes cleanly from the
latest checkpoint and finishes — the preemption-recovery story of the
framework (gang-scheduled SPMD: a dead process means relaunch + resume)."""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DRIVER = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"
import jax
jax.config.update("jax_platforms", "cpu")
from yet_another_mobilenet_series_tpu.cli.train import main
main(sys.argv[1:])
"""


def _args(log_dir, epochs):
    return [
        "data.dataset=fake", "data.image_size=24", "data.fake_train_size=320", "data.fake_eval_size=32",
        "model.arch=mobilenet_v2", "model.num_classes=4", "model.dropout=0.0",
        "model.block_specs=[{t: 2, c: 8, n: 1, s: 2}]",
        "train.batch_size=32", "train.eval_batch_size=32", "train.log_every=5",
        "train.compute_dtype=float32", f"train.log_dir={log_dir}",
        "train.eval_every_epochs=100",  # keep the victim run simple
        "schedule.base_lr=0.02", "schedule.warmup_epochs=0", "schedule.scale_by_batch=false",
        "dist.num_devices=8", f"train.epochs={epochs}",
    ]


@pytest.mark.slow
def test_sigkill_midrun_then_resume(tmp_path):
    log_dir = str(tmp_path / "run")
    env = dict(os.environ, PYTHONPATH=REPO)

    # victim: many epochs, checkpointing every epoch
    victim = subprocess.Popen(
        [sys.executable, "-c", _DRIVER] + _args(log_dir, epochs=50),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # wait until at least one checkpoint is fully written, then SIGKILL
    deadline = time.time() + 300
    ckpt_dir = os.path.join(log_dir, "ckpt")
    seen = False
    while time.time() < deadline:
        if victim.poll() is not None:
            out = victim.stdout.read()
            pytest.fail(f"victim exited early:\n{out[-2000:]}")
        steps = [d for d in (os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []) if d.isdigit()]
        # orbax renames the tmp dir into place when complete
        if steps and all("tmp" not in d for d in steps):
            seen = True
            time.sleep(1.0)  # let another save start mid-flight for extra chaos
            break
        time.sleep(0.5)
    assert seen, "no checkpoint appeared within the deadline"
    victim.send_signal(signal.SIGKILL)
    victim.wait()
    victim.stdout.read()

    # relaunch with a small total epoch budget: must resume and complete
    out = subprocess.run(
        [sys.executable, "-c", _DRIVER] + _args(log_dir, epochs=6),
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "resumed at step" in out.stdout
    assert "done:" in out.stdout
