"""32-virtual-device scale evidence (VERDICT r4 next #3): acceptance #5 is
8→256 chips (BASELINE.json:11), and until round 5 every virtual-mesh proof
stopped at 8 devices. These run in a subprocess with its own
``--xla_force_host_platform_device_count=32`` env (the pytest process is
pinned to 8 fake devices by conftest.py):

- the driver-facing ``__graft_entry__.dryrun_multichip(32)`` — all three
  sharded variant stacks compile + execute on a 32-device mesh;
- ZeRO step-vs-replicated equivalence and the gather/scatter round-trip at
  mesh 32, where most leaves have total % 32 != 0 (ragged chunk paths at 4x
  the proven mesh size).
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_py32(code: str, timeout=1500) -> str:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32 " + " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    r = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                       capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)
    assert r.returncode == 0, f"32-device subprocess failed:\n{r.stdout[-2000:]}\n{r.stderr[-4000:]}"
    return r.stdout


def test_dryrun_multichip_accepts_32_devices():
    out = _run_py32("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import __graft_entry__ as g
        g.dryrun_multichip(32)
        print("DRYRUN32 OK", len(jax.devices()))
    """)
    assert "DRYRUN32 OK 32" in out


def test_zero_ragged_chunks_at_mesh_32():
    out = _run_py32("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import jax.numpy as jnp
        from yet_another_mobilenet_series_tpu.config import config_from_dict
        from yet_another_mobilenet_series_tpu.models import get_model
        from yet_another_mobilenet_series_tpu.parallel import dp, mesh as mesh_lib, zero
        from yet_another_mobilenet_series_tpu.train import optim, schedules, steps

        def cfg(shard):
            return config_from_dict({
                "model": {"arch": "mobilenet_v2", "num_classes": 5, "dropout": 0.0,
                          "block_specs": [{"t": 3, "c": 12, "n": 1, "s": 2, "k": 3}]},
                "optim": {"optimizer": "rmsprop", "weight_decay": 1e-5},
                "schedule": {"schedule": "constant", "base_lr": 0.05,
                             "scale_by_batch": False, "warmup_epochs": 0.0},
                "ema": {"enable": True, "decay": 0.99, "warmup": False},
                "train": {"compute_dtype": "float32"},
                "dist": {"sync_bn": True, "shard_optimizer": shard},
            })

        n = 32
        net = get_model(cfg(False).model, image_size=16)
        mesh = mesh_lib.make_mesh(n)
        lr_fn = schedules.make_lr_schedule(cfg(False).schedule, 2 * n, 1, 100)
        params, _ = net.init(jax.random.PRNGKey(0))
        opt = optim.make_optimizer(cfg(False).optim, lr_fn, params)
        batch = {"image": np.asarray(jax.random.normal(jax.random.PRNGKey(1), (2 * n, 16, 16, 3))),
                 "label": np.asarray(jnp.arange(2 * n) % 5)}
        b = mesh_lib.shard_batch(batch, mesh)

        ts_rep = mesh_lib.replicate(steps.init_train_state(net, cfg(False), opt, jax.random.PRNGKey(0)), mesh)
        ts_rep, met_rep = dp.make_dp_train_step(net, cfg(False), opt, lr_fn, mesh)(ts_rep, b, jax.random.PRNGKey(7))

        c = cfg(True)
        ts_z = steps.init_train_state(net, c, opt, jax.random.PRNGKey(0), with_opt=False)
        ts_z = mesh_lib.replicate(ts_z, mesh)
        ts_z = ts_z.replace(opt_state=zero.init_opt_state(opt, ts_z.params, mesh))
        ts_z, met_z = dp.make_dp_train_step(net, c, opt, lr_fn, mesh)(ts_z, b, jax.random.PRNGKey(7))

        # ragged chunks genuinely occur at 32 (else the test is vacuous)
        assert any(l.size % n for l in jax.tree.leaves(ts_z.params))
        np.testing.assert_allclose(float(met_rep["loss"]), float(met_z["loss"]), rtol=1e-6)
        for a, cc in zip(jax.tree.leaves(ts_rep.params), jax.tree.leaves(ts_z.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(cc), rtol=1e-4, atol=1e-6)

        gathered = jax.jit(zero.gather_opt_state)(ts_z.opt_state, ts_z.params)
        back = zero.scatter_opt_state(jax.device_get(gathered), ts_z.params, mesh)
        gathered2 = jax.jit(zero.gather_opt_state)(back, ts_z.params)
        for a, cc in zip(jax.tree.leaves(gathered), jax.tree.leaves(gathered2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(cc))
        print("ZERO32 OK")
    """)
    assert "ZERO32 OK" in out
