"""Resolution tests for the interprocedural layer (analysis/symbols.py,
analysis/callgraph.py, analysis/summaries.py).

Pins the call shapes the graph must resolve — direct calls, aliased imports,
method calls on locally-constructed instances, self-attr callables, factory
results — and the one it must NOT: a dynamic ``getattr`` call degrades to
opaque (None), never to a crash or a guess. The summary fixpoint is pinned
on the same fixture package plus a synthetic PRNG/donation module.
"""

import ast
import pathlib

import pytest

from yet_another_mobilenet_series_tpu.analysis.core import Project, SourceFile, collect_paths
from yet_another_mobilenet_series_tpu.analysis.summaries import summary_for_target

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "lint" / "callgraph"


def _project(paths):
    py, yml = collect_paths([str(p) for p in paths])
    files = []
    for p in py:
        with open(p, encoding="utf-8") as f:
            files.append(SourceFile(p, f.read()))
    return Project(files, yml)


@pytest.fixture(scope="module")
def project():
    return _project([FIXTURE])


def _app_src(project):
    return next(s for s in project.files if s.path.endswith("app.py"))


def _call_in(project, src, fn_name):
    """The single Call expression in the fixture function's return statement."""
    fn = next(
        n for n in ast.walk(src.tree) if isinstance(n, ast.FunctionDef) and n.name == fn_name
    )
    calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
    # the LAST call lexically is the one under test (constructors come first)
    call = calls[-1]
    return call, fn


@pytest.mark.parametrize(
    "fn_name, expect_qualname, expect_bound",
    [
        ("direct", "pkg.core.helper", False),  # from .core import helper as h2
        ("via_module", "pkg.core.helper", False),  # from . import core as eng
        ("via_instance", "pkg.core.Trainer.train_step", True),  # local Trainer()
        ("via_self_attr", "pkg.core.helper", False),  # self._fn = helper
        ("via_factory", "pkg.core.make_step.step", False),  # returned local def
        ("via_tuple", "pkg.core.helper", False),  # fwd, make = h2, eng.make_step
        ("via_container", "pkg.core.helper", False),  # steps = (...); steps[1](x)
        ("via_dict", "pkg.core.helper", False),  # constant-keyed dict literal
    ],
)
def test_resolves(project, fn_name, expect_qualname, expect_bound):
    src = _app_src(project)
    call, fn = _call_in(project, src, fn_name)
    target = project.callgraph.resolve_call(src, call, fn)
    assert target is not None, f"{fn_name}: expected a resolution, got opaque"
    assert target.kind == "function"
    assert target.func.qualname == expect_qualname
    assert target.bound == expect_bound


def test_dynamic_call_degrades_to_opaque(project):
    src = _app_src(project)
    call, fn = _call_in(project, src, "dynamic")
    assert project.callgraph.resolve_call(src, call, fn) is None


def test_fixture_package_lints_clean(project):
    # resolution over the fixture package must neither crash nor flag
    from yet_another_mobilenet_series_tpu import analysis

    assert analysis.run_lint([FIXTURE]) == []


def test_symbol_table_module_names(project):
    names = set(project.symbols.modules)
    assert {"pkg", "pkg.core", "pkg.app"} <= names


# -- dataflow summaries -----------------------------------------------------


def test_summaries_key_and_donation(tmp_path):
    (tmp_path / "m.py").write_text(
        "import jax\n"
        "\n"
        "def consume(rng):\n"
        "    return jax.random.normal(rng, (2,))\n"
        "\n"
        "def forwards(k):\n"
        "    return consume(k)\n"  # transitive key consumption
        "\n"
        "def make_step():\n"
        "    return jax.jit(lambda s, b: s + b, donate_argnums=(0,))\n"
        "\n"
        "def wrapper(ts, b):\n"
        "    step = make_step()\n"
        "    return step(ts, b)\n"  # ts donated through the factory result
    )
    project = _project([tmp_path])
    s = project.summaries
    names = {q.rsplit(".", 1)[-1]: q for q in s}
    assert s[names["consume"]].key_params == {"rng"}
    assert s[names["forwards"]].key_params == {"k"}
    ret = s[names["make_step"]].returns
    assert ret is not None and ret.kind == "jit" and ret.donate == (0,)
    assert s[names["wrapper"]].donated_params == {0}


def test_summary_for_bound_method_shifts_self(tmp_path):
    (tmp_path / "m.py").write_text(
        "import jax\n"
        "\n"
        "class Net:\n"
        "    def init(self, rng):\n"
        "        return jax.random.normal(rng, (2,))\n"
        "\n"
        "def use(rng):\n"
        "    net = Net()\n"
        "    return net.init(rng)\n"
    )
    project = _project([tmp_path])
    src = project.files[0]
    call, fn = _call_in(project, src, "use")
    target = project.callgraph.resolve_call(src, call, fn)
    assert target is not None and target.bound
    summary = summary_for_target(project, target)
    # caller position 0 maps to the method's `rng` (self already bound)
    assert summary.param_at(0, bound=True) == "rng"
    assert "rng" in summary.key_params
