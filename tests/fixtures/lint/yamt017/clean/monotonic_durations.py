"""Clean: the sanctioned shapes — monotonic durations, wall-clock readings
used as TIMESTAMPS (stored/compared for identity, never differenced), and
an explicitly suppressed intentional wall-clock age."""

import time

# a process birth timestamp other processes compare for IDENTITY (restart
# detection): the reading is the point, nothing subtracts it
PROC_START_UNIX = time.time()


def measure(work):
    t0 = time.perf_counter()
    work()
    return time.perf_counter() - t0


def wait_with_deadline(poll, timeout_s):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if poll():
            return True
    return False


def stamp_row(row):
    # wall clock as data: provenance rows carry absolute timestamps
    row["measured_unix"] = time.time()
    return row


def restarted(previous_identity, current_identity):
    # equality of wall timestamps is identity, not a duration
    return previous_identity["start_unix"] != current_identity["start_unix"]


def log_age_s(mtime):
    return time.time() - mtime  # yamt-lint: disable=YAMT017 — mtime IS wall clock
