"""Bad: time.time() readings differenced into durations/deadlines — every
one of these jumps when NTP steps the wall clock."""

import time
from time import time as now


def measure(work):
    t0 = time.time()
    work()
    return time.time() - t0  # duration off the wall clock


def wait_with_deadline(poll, timeout_s):
    deadline = time.time() + timeout_s
    while time.time() < deadline:  # deadline comparison off the wall clock
        if poll():
            return True
    return False


def backoff_elapsed(last_attempt_t):
    # both operands tainted through names (one via the aliased import)
    t1 = now()
    return t1 - last_attempt_t if last_attempt_t else None


def cooldown_ok(opened_at, cooldown_s):
    return time.time() - opened_at >= cooldown_s
