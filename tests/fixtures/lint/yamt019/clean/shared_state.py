"""Clean: both sides of ``_seen`` hold ``self._lock``, and the stop flag is
a ``threading.Event`` — synchronization objects are sanctioned cross-thread
state, not races."""

import threading


class Collector:
    def __init__(self):
        self._lock = threading.Lock()
        self._seen = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        try:
            while not self._stop.is_set():
                with self._lock:
                    self._seen.append(1)
        except Exception:
            self._crashed = True

    def drain(self):
        with self._lock:
            return list(self._seen)
