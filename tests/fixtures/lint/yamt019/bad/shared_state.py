"""BAD: ``_seen`` is appended to by the worker thread and read by
``drain()`` from the caller's thread with no common lock — the cross-thread
shared-state race YAMT019 exists for."""

import threading


class Collector:
    def __init__(self):
        self._seen = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        try:
            while not self._stop.is_set():
                self._seen.append(1)
        except Exception:
            self._crashed = True

    def drain(self):
        return list(self._seen)
