"""__init__.py makes this fixture tree PACKAGE code for YAMT007."""
