"""YAMT007 must flag: bare print() in package code outside sanctioned surfaces."""

print("[data] pipeline starting")  # module-level side-channel output


def warn_uneven_shards(total, est):
    # a runtime warning that bypasses Logger/metrics.jsonl entirely
    print(f"[data] WARNING: counted {total} records, estimate was {est}", flush=True)
    return total
