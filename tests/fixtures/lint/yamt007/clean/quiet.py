"""YAMT007 must stay silent: Logger-routed output + __main__-guard prints."""


class _Logger:
    def log(self, msg):
        return msg


def warn_uneven_shards(log, total, est):
    # runtime signals go through the logger, not a bare print
    log.log(f"[data] counted {total} records, estimate was {est}")
    return total


if __name__ == "__main__":
    # module CLI output is a sanctioned surface
    print(warn_uneven_shards(_Logger(), 10, 12))
