"""The sanctioned logging surface (utils/logging.py) may print: it IS the sink."""


def emit(msg):
    print(msg, flush=True)
