"""The per-label family registrations for /metrics rendering."""

PROM_LABEL_FAMILIES: dict[str, str] = {
    "pkg.latency_seconds": "class",
    "pkg.queue_wait_seconds": "class",
}
