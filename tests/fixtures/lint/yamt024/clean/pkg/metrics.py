"""CLEAN: every emitted name is in the taxonomy (``pkg.completed`` via the
elided-sibling doc idiom) and every dotted family is registered in
PROM_LABEL_FAMILIES."""


def record(reg, cls, wait_s, latency_s):
    reg.counter("pkg.requests").inc()
    reg.counter("pkg.completed").inc()
    reg.histogram(f"pkg.queue_wait_seconds.{cls}").observe(wait_s)
    reg.histogram(f"pkg.latency_seconds.{cls}").observe(latency_s)
