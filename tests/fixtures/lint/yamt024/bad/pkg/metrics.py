"""BAD: ``pkg.mystery_count`` is emitted but absent from the taxonomy
(../docs/OBSERVABILITY.md), and the ``pkg.queue_wait_seconds.<class>``
family is neither registered in PROM_LABEL_FAMILIES nor documented —
every sample renders as its own unlabeled series. The documented +
registered emissions stay silent."""


def record(reg, cls, wait_s, latency_s):
    reg.counter("pkg.requests").inc()
    reg.counter("pkg.mystery_count").inc()
    reg.histogram(f"pkg.queue_wait_seconds.{cls}").observe(wait_s)
    reg.histogram(f"pkg.latency_seconds.{cls}").observe(latency_s)
