"""BAD (half 2): the handler parses ``X-Deadline-Ms`` but no sending side
in the package ever sets it — a dead parse that reads as a live contract."""


def handle(handler):
    deadline_ms = handler.headers.get("X-Deadline-Ms")
    if deadline_ms is not None:
        return float(deadline_ms)
    return None
