"""BAD (half 1): ``X-Request-Class`` is set on every outbound request but
no receiving side in the package ever reads it — the bytes cross the wire
and die. (``Content-Type`` is not a custom contract header; not checked.)"""

import http.client


def call(host, port, body):
    conn = http.client.HTTPConnection(host, port, timeout=5.0)
    conn.putrequest("POST", "/infer")
    conn.putheader("Content-Type", "application/octet-stream")
    conn.putheader("X-Request-Class", "interactive")
    conn.endheaders()
    conn.send(body)
    return conn.getresponse()
