"""CLEAN: both custom headers this package sends are parsed by the
receiving side (receiver.py) — no drift in either direction."""

import http.client


def call(host, port, body, deadline_ms):
    conn = http.client.HTTPConnection(host, port, timeout=5.0)
    conn.putrequest("POST", "/infer")
    conn.putheader("Content-Type", "application/octet-stream")
    conn.putheader("X-Request-Class", "interactive")
    conn.putheader("X-Deadline-Ms", str(deadline_ms))
    conn.endheaders()
    conn.send(body)
    return conn.getresponse()
