"""CLEAN: the receiving side of both custom headers sender.py sets."""


def handle(handler):
    cls = handler.headers.get("X-Request-Class") or "best_effort"
    deadline_ms = handler.headers.get("X-Deadline-Ms")
    return cls, (float(deadline_ms) if deadline_ms is not None else None)
