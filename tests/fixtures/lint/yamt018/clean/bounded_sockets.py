"""Clean: every socket carries an explicit bound — a positional/keyword
timeout, a settimeout() in the same scope, or non-blocking mode. An
explicit timeout=None is a deliberate operator choice, not a silent
default, and stays clean."""

import http.client
import socket
from http.client import HTTPConnection


def dial(host, port):
    return socket.create_connection((host, port), 5.0)


def dial_kw(host, port):
    return socket.create_connection((host, port), timeout=2.5)


def dial_forever_on_purpose(host, port):
    # loud: the operator said forever
    return socket.create_connection((host, port), timeout=None)


def fetch(host, port):
    conn = http.client.HTTPConnection(host, port, timeout=10.0)
    conn.request("GET", "/healthz")
    return conn.getresponse().read()


def fetch_aliased(host, port):
    conn = HTTPConnection(host, port, timeout=10.0)
    conn.request("GET", "/")
    return conn.getresponse().read()


def listen_bounded(port):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.settimeout(0.5)
    s.bind(("127.0.0.1", port))
    s.listen(8)
    return s.accept()


class Server:
    def open(self, port):
        self.sock = socket.socket()
        self.sock.settimeout(1.0)
        self.sock.bind(("127.0.0.1", port))


def with_block(port):
    with socket.socket() as s:
        s.settimeout(2.0)
        s.connect(("127.0.0.1", port))
        return s.recv(1024)


def nonblocking(port):
    s = socket.socket()
    s.setblocking(False)
    return s
