"""Bad: sockets opened with no timeout — every one of these blocks forever
against a blackholed or half-open peer."""

import http.client
import socket
from http.client import HTTPConnection


def dial(host, port):
    # no timeout argument: connect hangs on a SYN blackhole
    return socket.create_connection((host, port))


def fetch(host, port):
    # stdlib default timeout is None = block forever
    conn = http.client.HTTPConnection(host, port)
    conn.request("GET", "/healthz")
    return conn.getresponse().read()


def fetch_aliased(host, port):
    conn = HTTPConnection(host, port)
    conn.request("GET", "/")
    return conn.getresponse().read()


def listen_forever(port):
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", port))
    s.listen(8)
    return s.accept()  # never bounded: a wedged accept thread


class Server:
    def open(self, port):
        # self-attr socket never given a timeout in this scope
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", port))


def with_block(port):
    with socket.socket() as s:
        s.connect(("127.0.0.1", port))
        return s.recv(1024)
