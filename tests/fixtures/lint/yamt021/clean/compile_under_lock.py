"""Clean: the PR 8 fix shape — compile OUTSIDE the dispatch lock, then take
the lock only to publish (``setdefault`` keeps the first winner when two
cold callers race the same key)."""

import threading

import jax


class Engine:
    def __init__(self, fn):
        self._fn = fn
        self._dispatch_lock = threading.Lock()
        self._cache = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._warm_loop, daemon=True)

    def start(self):
        self._thread.start()
        self.predict(0)

    def _warm_loop(self):
        try:
            while not self._stop.is_set():
                self.predict(1)
        except Exception:
            self._crashed = True

    def predict(self, key):
        with self._dispatch_lock:
            exe = self._cache.get(key)
        if exe is None:
            exe = jax.jit(self._fn).lower(key).compile()
            with self._dispatch_lock:
                exe = self._cache.setdefault(key, exe)
        with self._dispatch_lock:
            return exe(key)
