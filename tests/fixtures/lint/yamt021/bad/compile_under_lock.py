"""BAD: the cold path builds an executable (``.lower().compile()``) while
holding ``_dispatch_lock`` — the lock every warm dispatch (from the warm
loop thread AND direct callers) also takes, so one cold key stalls the whole
dispatch path. The exact PR 8 serving bug, pinned as a must-flag fixture."""

import threading

import jax


class Engine:
    def __init__(self, fn):
        self._fn = fn
        self._dispatch_lock = threading.Lock()
        self._cache = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._warm_loop, daemon=True)

    def start(self):
        self._thread.start()
        self.predict(0)

    def _warm_loop(self):
        try:
            while not self._stop.is_set():
                self.predict(1)
        except Exception:
            self._crashed = True

    def predict(self, key):
        with self._dispatch_lock:
            exe = self._cache.get(key)
            if exe is None:
                exe = jax.jit(self._fn).lower(key).compile()
                self._cache[key] = exe
            return exe(key)
