"""YAMT005 fixture schema: a miniature config.py (name matters — the rule
finds the schema by basename)."""

from dataclasses import dataclass, field


@dataclass(frozen=True)
class TrainConfig:
    epochs: float = 1.0
    batch_size: int = 256


@dataclass(frozen=True)
class Config:
    name: str = "experiment"
    train: TrainConfig = field(default_factory=TrainConfig)
