"""YAMT016 clean fixture: every conversion of a wire-typed buffer routes its
dtype through a config-resolved variable (the serve/engine.py +
serve/batcher.py discipline), or never touches a narrow buffer at all."""

import jax.numpy as jnp
import numpy as np

WIRE_DTYPE = np.uint8  # resolved from serve.quant.wire in real code


def stage_request(image, wire_dtype):
    # the sanctioned idiom: the dtype is a VARIABLE a config flip reaches
    buf = np.zeros((8, 24, 24, 3), wire_dtype)
    buf[: len(image)] = image
    return np.asarray(buf, wire_dtype)


def explicit_wire_dtype(pixels):
    wire = pixels.astype(np.uint8)
    # stating the dtype is the point — the contract is visible, not erased
    return jnp.asarray(wire, WIRE_DTYPE)


def np_asarray_preserves(batch):
    staged = np.asarray(batch, np.uint8)
    # dtype-less NUMPY conversions preserve dtype (no device boundary) and
    # never flag; only the jnp device hop must state the wire
    return np.ascontiguousarray(staged)


def f32_path_untouched(image):
    # a genuinely-f32 pipeline may say so: the buffer was never narrow
    buf = np.zeros((8, 24, 24, 3), np.float32)
    buf[: len(image)] = image
    return jnp.asarray(buf, jnp.float32)


def rebound_name_clears(image):
    buf = np.zeros((4, 8), np.uint8)
    buf = compute_floats(buf)  # rebinding to an opaque call clears the mark
    return buf.astype(np.float32)


def compute_floats(x):
    return x.sum(axis=-1)
