"""YAMT016 bad fixture: wire-typed (narrow) staging buffers silently widened
back to f32 with literal dtypes — the conversion a serve.quant.wire config
flip can never reach."""

import jax.numpy as jnp
import numpy as np


def stage_request(image):
    # the batcher's historical hazard shape: a buffer deliberately staged
    # uint8 (the quantized wire), then force-converted with a literal f32
    buf = np.zeros((8, 24, 24, 3), np.uint8)
    buf[: len(image)] = image
    return np.asarray(buf, np.float32)


def explicit_astype(pixels):
    wire = pixels.astype(np.uint8)
    return wire.astype(np.float32)  # silent 4x widening of the wire buffer


def dtype_less_device_conversion(batch):
    staged = np.asarray(batch, "uint8")
    # erases the wire contract at the host/device boundary: whatever dtype
    # arrives rides through unstated
    return jnp.asarray(staged)


def mark_survives_views(image):
    buf = np.empty((4, 16, 16, 3), dtype=np.uint8)
    flat = buf.reshape(4, -1)  # views share the wire dtype
    return jnp.asarray(flat, dtype=jnp.float32)


def staging_loop(batches):
    out = []
    buf = np.zeros((8, 32, 32, 3), np.int8)
    for batch in batches:
        buf[: len(batch)] = batch
        out.append(buf.astype("float32"))  # per-iteration widening
    return out
