"""YAMT014 clean fixture: the sanctioned fence idiom — a staging buffer is
rewritten only after its last transfer is known complete
(serve/engine.py ``_SlotPool``)."""

import jax
import numpy as np


def staging_loop(batches):
    # fence idiom: wait on the previous transfer (or its consumer's
    # outputs) before rewriting the buffer it read from
    buf = np.zeros((8, 32, 32, 3), np.float32)
    fence = None
    outs = []
    for batch in batches:
        if fence is not None:
            jax.block_until_ready(fence)
        buf[: len(batch)] = batch
        fence = jax.device_put(buf)
        outs.append(fence)
    return outs


def stage_two(a, b):
    buf = np.empty((4, 8), np.float32)
    buf[:] = a
    xa = jax.device_put(buf)
    xa.block_until_ready()
    buf[:] = b
    xb = jax.device_put(buf)
    return xa, xb


def fresh_buffer_per_transfer(batches):
    # no reuse, no hazard: each transfer gets its own buffer
    return [jax.device_put(np.ascontiguousarray(b)) for b in batches]
