"""YAMT014 clean fixture: the ring slot-feed idiom (serve/engine.py
``ring_stage`` / ``ring_dispatch``). Host threads feed a window of slot
buffers with async device_put — one transfer per slot, no dispatch — and
the consuming window dispatch's OUTPUT logits become the fence of every
slot it consumed (the donated inputs are deleted by donation and cannot be
waited on). A buffer is rewritten only after that fence is ready."""

import jax
import numpy as np


def feed_and_dispatch_windows(windows, ring_exe, params, r=4):
    # 2R host buffers: R possibly consumed by the in-flight window plus R
    # being fed for the next one — the fence wait stays ~0 at steady state
    bufs = [np.zeros((8, 24, 24, 3), np.float32) for _ in range(2 * r)]
    fences = [None] * (2 * r)
    nxt = 0
    outs = []
    for window in windows:
        fed = []
        for rows in window:
            i = nxt
            nxt = (nxt + 1) % len(bufs)
            if fences[i] is not None:
                # fence idiom: the previous consumer's outputs existing
                # proves its input transfer finished with this host memory
                jax.block_until_ready(fences[i])
                fences[i] = None
            bufs[i][: len(rows)] = rows
            bufs[i][len(rows) :] = 0.0
            fed.append((i, jax.device_put(bufs[i])))  # async feed, no dispatch
        ys = ring_exe(params, *[x for _, x in fed])  # ONE dispatch per window
        for i, _ in fed:
            fences[i] = ys  # one fence arms every consumed slot
        outs.append(ys)
    return outs
