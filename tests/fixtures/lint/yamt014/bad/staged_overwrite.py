"""YAMT014 bad fixture: host staging buffers rewritten while their async
jax.device_put transfer may still be reading them."""

import jax
import numpy as np


def staging_loop(batches):
    # the canonical staging-loop hazard: the transfer at the bottom of one
    # iteration races the rewrite at the top of the next (flagged on the
    # rule's second loop pass)
    buf = np.zeros((8, 32, 32, 3), np.float32)
    outs = []
    for batch in batches:
        buf[: len(batch)] = batch
        outs.append(jax.device_put(buf))
    return outs


def stage_two(a, b):
    buf = np.empty((4, 8), np.float32)
    buf[:] = a
    xa = jax.device_put(buf)
    buf[:] = b  # overwrites while xa's transfer may be in flight
    xb = jax.device_put(buf)
    return xa, xb
