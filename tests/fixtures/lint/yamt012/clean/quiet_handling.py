"""Clean: the sanctioned shapes — narrow swallows, broad handlers that act,
__del__ finalizers, and an explicitly suppressed intentional swallow."""

import os


def cleanup(tmp):
    try:
        os.unlink(tmp)
    except OSError:
        pass  # narrow: the one failure this means to ignore


def guarded(work, log):
    try:
        return work()
    except Exception as e:
        log(f"work failed: {e}")  # broad, but the failure is visible
        return None


def reraised(work):
    try:
        return work()
    except Exception:
        raise RuntimeError("work failed")


class Holder:
    def close(self):
        pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # finalizer: raising only prints unraisable noise


def last_good(read, fallback):
    try:
        return read()
    except Exception:  # yamt-lint: disable=YAMT012 — keep the last good reading
        pass
    return fallback
