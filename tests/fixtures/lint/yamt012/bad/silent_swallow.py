"""Bad: broad excepts whose pass-only bodies make failures vanish — the
restore-path bug class (corruption retried as a benign legacy quirk)."""


def read_config(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:
        pass  # which failure? nobody will ever know


def read_state(path):
    try:
        with open(path) as f:
            return f.read()
    except:  # noqa: E722 — bare except is the worst variant
        pass


def read_tree(path):
    try:
        with open(path) as f:
            return f.read()
    except (OSError, Exception):  # the tuple still contains a broad type
        ...
