"""Clean: every path acquires ``_alock`` before ``_block`` — one global
acquisition order, no cycle."""

import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self._a = 0
        self._b = 0

    def move_ab(self, n):
        with self._alock:
            with self._block:
                self._a -= n
                self._b += n

    def move_ba(self, n):
        with self._alock:
            with self._block:
                self._b -= n
                self._a += n
