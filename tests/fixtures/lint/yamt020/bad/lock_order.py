"""BAD: ``move_ab`` nests ``_alock`` -> ``_block`` while ``move_ba`` nests
``_block`` -> ``_alock`` — two callers deadlock holding one lock each,
waiting for the other (the cycle YAMT020 flags)."""

import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self._a = 0
        self._b = 0

    def move_ab(self, n):
        with self._alock:
            with self._block:
                self._a -= n
                self._b += n

    def move_ba(self, n):
        with self._block:
            with self._alock:
                self._b -= n
                self._a += n
