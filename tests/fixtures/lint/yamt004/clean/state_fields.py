"""YAMT004 must stay silent: tuple and dataclass agree exactly, in order."""

from typing import Any

import flax.struct


@flax.struct.dataclass
class TrainState:
    step: int
    params: Any
    opt_state: Any


TRAIN_STATE_FIELDS = ("step", "params", "opt_state")

# a FIELDS tuple with no matching dataclass anywhere is out of scope
UNRELATED_FIELDS = ("a", "b")
