"""YAMT004 must flag: FIELDS tuple drifted from its dataclass."""

from typing import Any

import flax.struct


@flax.struct.dataclass
class TrainState:
    step: int
    params: Any
    opt_state: Any
    ema_params: Any


# missing 'ema_params' — a checkpoint built from this tuple silently drops it
TRAIN_STATE_FIELDS = ("step", "params", "opt_state")
