"""The package-side consumer of every plain config field."""


def serve(cfg):
    return cfg.host, cfg.port, cfg.zoo.models.split(",")
