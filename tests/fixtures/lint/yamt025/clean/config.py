"""CLEAN: every section dataclass is registered in ``_SECTION_TYPES`` and
every plain field has a package-code reader (server.py)."""

from dataclasses import dataclass, field


@dataclass
class ZooConfig:
    models: str = ""


@dataclass
class ServeConfig:
    zoo: ZooConfig = field(default_factory=ZooConfig)
    host: str = "127.0.0.1"
    port: int = 8000


_SECTION_TYPES = {
    "ZooConfig": ZooConfig,
    "ServeConfig": ServeConfig,
}


def build(overrides):
    cfg = ServeConfig()
    for key, value in overrides.items():
        setattr(cfg, key, value)
    return cfg
