"""BAD: the exact PR 18 shape — ``ServeConfig`` grew a ``zoo: ZooConfig``
section but ``ZooConfig`` was never added to ``_SECTION_TYPES``, so every
dotted ``serve.zoo.*`` override raises TypeError at build time (the nested
dict is handed to the dataclass constructor uncoerced)."""

from dataclasses import dataclass, field


@dataclass
class ZooConfig:
    models: str = ""


@dataclass
class ServeConfig:
    zoo: ZooConfig = field(default_factory=ZooConfig)
    host: str = "127.0.0.1"
    port: int = 8000


_SECTION_TYPES = {
    "ServeConfig": ServeConfig,
}


def build(overrides):
    cfg = ServeConfig()
    for key, value in overrides.items():
        setattr(cfg, key, value)
    return cfg
