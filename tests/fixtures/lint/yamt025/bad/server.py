"""The package-side consumer of every plain config field (so the only
finding the bad twin can produce is the unregistered section)."""


def serve(cfg):
    return cfg.host, cfg.port, cfg.zoo.models.split(",")
