"""YAMT009 must stay silent: hashable statics, build-time-only rebinds."""

import functools

import jax


def f(x, y, opts):
    return x + y


step = jax.jit(f, static_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("act",))
def g(x, *, act):
    return x * 2


def run(x, y, mode):
    a = step(x, y, 4)  # int: hashable, cache-stable
    b = g(x, act="relu")  # string static: fine
    c = step(x, y, mode)  # a runtime name: hashability is the caller's contract
    d = step(x, y, tuple(range(3)))  # tuple() hashes by value
    return a + b + c + d


def make_step(cfg, use_remat):
    def fwd(v):
        return v * cfg

    if use_remat:
        # rebinding BEFORE the jit exists is build-time setup (the
        # forward = jax.checkpoint(forward) idiom in train/steps.py)
        fwd = jax.checkpoint(fwd)

    @jax.jit
    def stepper(v):
        return fwd(v)

    return stepper


def loop_without_capture(xs):
    # building a jitted fn inside a loop is fine when it does NOT read the
    # loop variable (the value rides in as a traced argument)
    total = 0.0
    for scale in range(3):
        @jax.jit
        def scaled(v, s):
            return v * s

        total = total + scaled(xs, scale)
    return total
