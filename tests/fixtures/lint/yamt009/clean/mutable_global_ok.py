"""Clean: module globals a jitted function may read — immutable constants,
and a mutable table that is built once at import and only ever read
(no mutation evidence anywhere in the module)."""

import jax

AXES = ("batch", "model")
WIDTH = 128
LOOKUP = {"relu": 0, "swish": 1}  # built once, read-only from here on


@jax.jit
def apply(x):
    return x * WIDTH + LOOKUP["relu"] + len(AXES)


def describe():
    return dict(LOOKUP)  # copying out is a read, not a mutation
