"""YAMT009 must flag: jitted functions reading module-level MUTABLE globals
that the module also mutates — the trace bakes the first-call contents in
and every later mutation is silently ignored."""

import collections

import jax

SCALES = {"base": 1.0}
HISTORY = collections.deque()


@jax.jit
def apply(x):
    return x * SCALES["base"]  # trace freezes the dict contents


def nested_reader():
    @jax.jit
    def inner(x):
        return x + len(HISTORY)  # scope chain exhausts: HISTORY is the global
    return inner


def retune(v):
    SCALES["base"] = v  # the mutation apply() never sees


def record(x):
    HISTORY.append(x)
