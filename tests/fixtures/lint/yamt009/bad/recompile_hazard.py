"""YAMT009 must flag: static-position hazards and per-call-varying closures."""

import functools

import jax


class Cfg:
    def __init__(self, depth):
        self.depth = depth


def f(x, y, opts):
    return x + y


step = jax.jit(f, static_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("cfg",))
def g(x, *, cfg):
    return x * 2


def run(x, y):
    a = step(x, y, [1, 2])  # unhashable literal at a static position
    b = g(x, cfg=Cfg(3))  # fresh object identity every call: recompiles per step
    c = step(x, y, dict(mode=1))  # dict() builder: unhashable, fresh each call
    return a + b + c


def loop(xs):
    total = 0.0
    for scale in range(3):
        @jax.jit
        def scaled(v):
            return v * scale  # closure over the loop variable: re-jit per iteration

        total = total + scaled(xs)
    return total


def stale(xs):
    counter = 0

    @jax.jit
    def stepper(v):
        return v + counter  # baked at trace time...

    out = stepper(xs)
    counter = counter + 1  # ...then varied per call: stale constant / recompile
    return out, stepper(xs)
