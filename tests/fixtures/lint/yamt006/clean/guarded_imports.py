"""YAMT006 must stay silent: version-guarded imports are the sanctioned idiom
(this is the shape of utils/compat.py)."""

try:  # newer jax: public top-level export
    from jax import shard_map
except ImportError:  # jax <= 0.5
    from jax.experimental.shard_map import shard_map  # noqa: F401

from jax import lax  # stable public surface is fine
from jax.experimental import pallas  # experimental-but-present is not flagged
