"""YAMT006 must flag: every import below resolves on only some jax versions."""

from jax import shard_map  # absent before jax 0.6 — the exact seed-breaking bug
from jax.experimental import maps  # deleted (xmap is gone)
import jax._src.core as jax_core  # private internals, reshuffled every release
from jax.experimental.shard_map import shard_map as old_shard_map  # removed in newer jax
