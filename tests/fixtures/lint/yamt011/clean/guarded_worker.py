"""CLEAN: every thread target carries a top-level try/except guard (setup
statements before the try are fine), opaque targets degrade to silence."""

import functools
import threading

from . import helpers  # noqa: F401 — stands in for a cross-module callable


def worker(q):
    """Docstrings and setup bindings before the guard are allowed."""
    backoff = 0.01
    try:
        while True:
            item = q.get()
            item.process(backoff)
    except Exception:
        q.fail_all("worker crashed")


def start_worker(q):
    t = threading.Thread(target=worker, args=(q,), daemon=True)
    t.start()
    return t


def start_closure_worker(q):
    def drain():
        try:
            while True:
                q.get().process()
        except Exception:
            q.fail_all("drain crashed")

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    return t


class Server:
    def _loop(self):
        try:
            self._loop_inner()
        except Exception as e:
            self._crashed(e)

    def _loop_inner(self):
        while True:
            self.step()

    def _crashed(self, e):
        self.log(e)

    def start(self):
        self._thread = threading.Thread(target=self._loop, name="srv", daemon=True)
        self._thread.start()


def opaque_targets_are_silent(q):
    # callables the file cannot see into: no finding, no noise
    t1 = threading.Thread(target=helpers.run, daemon=True)
    t2 = threading.Thread(target=functools.partial(worker, q), daemon=True)
    return t1, t2
