"""BAD: thread targets without a top-level exception guard — every shape
the rule must catch (plain def, nested closure, self.method, try/finally
without except, lambda)."""

import threading


def worker(q):
    while True:  # an exception here kills the thread silently
        item = q.get()
        item.process()


def start_worker(q):
    t = threading.Thread(target=worker, args=(q,), daemon=True)
    t.start()
    return t


def start_closure_worker(q):
    def drain():
        while True:
            q.get().process()

    t = threading.Thread(target=drain, daemon=True)
    t.start()
    return t


def finally_is_not_a_guard(q):
    def run():
        try:
            q.get().process()
        finally:
            q.close()  # the exception still escapes and kills the thread

    return threading.Thread(target=run)


def lambda_target(q):
    return threading.Thread(target=lambda: q.get().process())


class Server:
    def _loop(self):
        while True:
            self.step()

    def start(self):
        self._thread = threading.Thread(target=self._loop, name="srv", daemon=True)
        self._thread.start()
