"""YAMT003 must flag: collectives over an axis name no mesh defines."""

from jax import lax

DATA_AXIS = "data"  # the project's one mesh axis


def allreduce(x):
    return lax.psum(x, "batch")  # no mesh defines 'batch'


def rank():
    return lax.axis_index("model")  # nor 'model'
