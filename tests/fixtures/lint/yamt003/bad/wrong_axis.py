"""YAMT003 must flag: collectives over an axis name no mesh defines."""

from jax import lax
from jax.sharding import Mesh

DATA_AXIS = "data"  # the project's one mesh axis


def make_mesh(devices):
    return Mesh(devices, ("data", "fsdp"))  # a 2-D mesh adds 'fsdp'


def allreduce(x):
    return lax.psum(x, "batch")  # no mesh defines 'batch'


def rank():
    return lax.axis_index("model")  # nor 'model'


def scatter(x):
    return lax.psum_scatter(x, "fsdp2")  # near-miss of the Mesh tuple's 'fsdp'
