"""YAMT003 must stay silent: known literals, axis constants, runtime names."""

from jax import lax

DATA_AXIS = "data"


def allreduce(x):
    return lax.psum(x, DATA_AXIS)  # the constant itself


def mean(x):
    return lax.pmean(x, "data")  # literal matching a defined axis


def generic(x, axis_name):
    if axis_name is None:
        return x
    return lax.psum(x, axis_name)  # runtime value: not statically checkable
