"""YAMT003 must stay silent: Mesh axis-name tuples define known axes too."""

from jax import lax
from jax.sharding import Mesh


def make_mesh(devices):
    return Mesh(devices, ("rows", "cols"))


def make_named(devices):
    return Mesh(devices, axis_names=("stage",))


def reduce_rows(x):
    return lax.psum(x, "rows")


def reduce_both(x):
    return lax.pmean(x, ("rows", "cols"))


def stage_rank():
    return lax.axis_index("stage")
