"""Typed serve exceptions for the unmapped-escape clean fixture."""


class EngineError(Exception):
    """Base of every typed serve verdict in this package."""


class QueueFull(EngineError):
    """Bounded queue at capacity — the caller's backpressure signal."""


class QuotaExceeded(EngineError):
    """Per-tenant quota exhausted — mapped here, unlike the bad twin."""


class TransientSlot(EngineError):
    """Retryable slot contention: absorbed on the submit path, never wired."""
