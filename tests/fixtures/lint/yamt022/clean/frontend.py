"""The wire boundary: typed exception -> (status, tag, retry-after?)."""

from .errors import QueueFull, QuotaExceeded

_ERROR_MAP = [
    (QueueFull, 429, "queue_full", True),
    (QuotaExceeded, 429, "quota_exceeded", True),
]


def classify(exc):
    for typ, status, tag, _retry_after in _ERROR_MAP:
        if isinstance(exc, typ):
            return status, tag
    return 500, "engine_error"
