"""CLEAN: every typed exception that escapes ``Gate.submit`` has an
``_ERROR_MAP`` row. ``TransientSlot`` is raised two frames down but the
submit path absorbs it with a narrow except (bounded retry), so it never
crosses the tier — the escape model must see the narrowing, not the raise."""

from .errors import QueueFull, QuotaExceeded, TransientSlot


class Gate:
    def __init__(self, limit, quota):
        self._limit = limit
        self._quota = quota
        self._used = 0
        self._backlog = 0

    def submit(self, job):
        self._admit()
        for _ in range(3):
            try:
                return self._reserve(job)
            except TransientSlot:
                continue
        raise QueueFull(f"backlog at capacity ({self._limit})")

    def _admit(self):
        if self._used >= self._quota:
            raise QuotaExceeded(f"quota {self._quota} exhausted")
        self._used += 1

    def _reserve(self, job):
        if self._backlog >= self._limit:
            raise TransientSlot("slot contended; retry")
        self._backlog += 1
        return job
