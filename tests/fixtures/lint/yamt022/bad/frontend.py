"""The wire boundary: typed exception -> (status, tag, retry-after?)."""

from .errors import QueueFull

_ERROR_MAP = [
    (QueueFull, 429, "queue_full", True),
]


def classify(exc):
    for typ, status, tag, _retry_after in _ERROR_MAP:
        if isinstance(exc, typ):
            return status, tag
    return 500, "engine_error"
