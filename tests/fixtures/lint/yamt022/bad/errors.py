"""Typed serve exceptions for the unmapped-escape fixture."""


class EngineError(Exception):
    """Base of every typed serve verdict in this package."""


class QueueFull(EngineError):
    """Bounded queue at capacity — the caller's backpressure signal."""


class QuotaExceeded(EngineError):
    """Per-tenant quota exhausted. No _ERROR_MAP row: the bug under test."""
