"""BAD: ``QuotaExceeded`` escapes ``Gate.submit`` (via the ``_admit``
helper, one frame down) but ``frontend._ERROR_MAP`` has no row for it —
the frontend degrades the typed verdict to a generic 500. ``QueueFull``
escapes too, but its row keeps it silent."""

from .errors import QueueFull, QuotaExceeded


class Gate:
    def __init__(self, limit, quota):
        self._limit = limit
        self._quota = quota
        self._used = 0
        self._backlog = 0

    def submit(self, job):
        self._admit()
        if self._backlog >= self._limit:
            raise QueueFull(f"backlog at capacity ({self._limit})")
        self._backlog += 1
        return job

    def _admit(self):
        if self._used >= self._quota:
            raise QuotaExceeded(f"quota {self._quota} exhausted")
        self._used += 1
