"""YAMT013 clean fixture: both sanctioned guard shapes — the canonical
``start(); try: ... finally: stop()`` idiom, and a start inside a try whose
(outer) finally flushes a still-open window."""

import jax


def capture_window(step_fn, batches):
    jax.profiler.start_trace("/tmp/trace")
    try:
        for batch in batches:
            step_fn(batch)
    finally:
        jax.profiler.stop_trace()


def capture_loop(step_fn, batches, start_at):
    active = False
    try:
        for i, batch in enumerate(batches):
            if i == start_at:
                jax.profiler.start_trace("/tmp/trace")
                active = True
            step_fn(batch)
    finally:
        if active:
            jax.profiler.stop_trace()
