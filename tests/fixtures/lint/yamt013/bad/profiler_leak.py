"""YAMT013 bad fixture: a capture window with no finally — any exception in
the profiled region leaks the trace (and wedges the next start on TPU)."""

import jax


def capture_window(step_fn, batches):
    jax.profiler.start_trace("/tmp/trace")
    for batch in batches:
        step_fn(batch)
    jax.profiler.stop_trace()
