"""Clean: the pipelined serving-engine dispatch shape (serve/engine.py).

A donated device input is created from a reused host staging buffer and
dispatched WITHOUT a sync; only the returned handle is read afterwards. The
donated array is rebound before the next dispatch, so no read of a deleted
buffer exists — the async engine's YAMT008 discipline, pinned clean."""

import jax
import jax.numpy as jnp
import numpy as np


def make_dispatcher(forward, params):
    run = jax.jit(forward, donate_argnums=(1,))
    staging = np.zeros((8, 24, 24, 3), np.float32)

    def dispatch_all(chunks):
        handles = []
        for chunk in chunks:
            staging[: chunk.shape[0]] = chunk
            staging[chunk.shape[0] :] = 0.0
            x = jnp.asarray(staging)  # rebound every iteration, pre-donation
            handles.append(run(params, x))  # x donated: never read after
        return handles

    def collect(handles):
        return [np.asarray(jax.device_get(h)) for h in handles]

    return dispatch_all, collect
