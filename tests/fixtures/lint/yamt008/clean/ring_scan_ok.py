"""Clean: the device-resident ring dispatch shape (serve/engine.py).

A window of R pre-staged slot arrays plus an active-slot mask feeds ONE
jitted masked-scan program: the slots are stacked inside the program, the
scan runs the per-slot forward over the leading axis, and a scalar-bool
where discards padded slots' outputs. Every slot argument is donated —
staged entries and the device-side zero pads alike — and none is read
after the dispatch; only the returned window handle is synced. The ring
engine's YAMT008 discipline, pinned clean."""

import jax
import jax.numpy as jnp
import numpy as np


def make_ring_dispatcher(forward, params, r=4, bucket=8):
    def run(params, mask, *slots):
        xs = jnp.stack(slots)

        def body(carry, xm):
            x, m = xm
            y = forward(params, x)
            return carry, jnp.where(m, y, jnp.zeros_like(y))

        _, ys = jax.lax.scan(body, None, (xs, mask))
        return ys

    ring = jax.jit(run, donate_argnums=tuple(range(2, 2 + r)))

    def dispatch_window(staged):
        # staged: device arrays fed earlier by the host threads; the pads
        # are DISTINCT device-side zero buffers (all slot args are donated)
        mask = np.zeros((r,), np.bool_)
        mask[: len(staged)] = True
        pads = [jnp.zeros((bucket, 24, 24, 3), jnp.float32) for _ in range(r - len(staged))]
        xs = list(staged) + pads
        return ring(params, jnp.asarray(mask), *xs)  # slots donated: never read after

    def drain(handle, rows):
        arr = np.asarray(jax.device_get(handle))
        return arr.reshape(-1, arr.shape[-1])[:rows]

    return dispatch_window, drain
