"""YAMT008 must stay silent: rebind-before-read, and non-donating jits."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
plain = jax.jit(lambda s: s * 2)


def train(state, batches):
    for b in batches:
        # the canonical idiom: the donated var is rebound by the SAME
        # statement that donates it (cli/train.py's dispatch loop)
        state = step(state, b)
    return state


def rebound_before_read(state, b):
    new = step(state, b)
    state = new
    return step(state, b)


def branches(state, b, flag):
    if flag:
        state = step(state, b)
    else:
        state = state + 1.0
    return jnp.sum(state)  # every path rebound state


def no_donation(state):
    y = plain(state)
    return y + state  # plain does not donate: reads stay legal
