"""YAMT008 must stay silent: resolved attribute calls with the rebind idiom,
and OPAQUE attribute calls (an unannotated parameter) that must never be
guessed into a donation."""

import jax


def _step(s, b):
    return s + b


class Trainer:
    def __init__(self):
        self.train_step = jax.jit(_step, donate_argnums=(0,))
        self.eval_step = jax.jit(_step)  # no donation


def train(state, batches):
    trainer = Trainer()
    for b in batches:
        state = trainer.train_step(state, b)  # rebound by the same statement
    return state


def evals(state, batches):
    trainer = Trainer()
    total = 0.0
    for b in batches:
        m = trainer.eval_step(state, b)
        total = total + m + 0 * state  # eval_step does not donate: reads stay legal
    return total


def opaque_loop(runner, state, batches):
    # `runner` is an unannotated parameter: the call graph degrades to
    # opaque and the rule must not invent a donation
    out = None
    for b in batches:
        out = runner.train_step(state, b)
        out = out + state
    return out
