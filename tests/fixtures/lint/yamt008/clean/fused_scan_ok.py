"""Clean: the fused multi-chunk dispatch shape (serve/engine.py).

All K chunks of an oversized request stage into one reused
(K, bucket, S, S, 3) host buffer, transfer once, and a lax.scan inside the
jitted program runs the per-chunk forward over the leading chunk axis — ONE
donated dispatch for the whole request. The donated device array is rebound
before the next dispatch and never read afterwards; only the returned handle
is synced. The fused engine's YAMT008 discipline, pinned clean."""

import jax
import jax.numpy as jnp
import numpy as np


def make_fused_dispatcher(forward, params, k=4, bucket=8):
    def run(params, xs):
        def body(carry, x):
            return carry, forward(params, x)

        _, ys = jax.lax.scan(body, None, xs)
        return ys

    fused = jax.jit(run, donate_argnums=(1,))
    staging = np.zeros((k, bucket, 24, 24, 3), np.float32)

    def dispatch_all(requests):
        handles = []
        for rows in requests:
            flat = staging.reshape(k * bucket, 24, 24, 3)
            flat[: rows.shape[0]] = rows
            flat[rows.shape[0] :] = 0.0
            xs = jnp.asarray(staging)  # rebound every iteration, pre-donation
            handles.append(fused(params, xs))  # xs donated: never read after
        return handles

    def collect(handles):
        return [np.asarray(jax.device_get(h)) for h in handles]

    return dispatch_all, collect
