"""YAMT008 must flag: reads of a buffer after jit donation deleted it."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
multi = jax.pmap(lambda s, m, b: s + m + b, donate_argnums=(0, 1))


def train(state, batches):
    total = 0.0
    for b in batches:
        state_new = step(state, b)  # donates `state`...
        total = total + jnp.sum(state)  # ...then reads the deleted buffer
        state = state_new
    return state, total


def double_dispatch(state, b):
    a = step(state, b)
    c = step(state, b)  # the donated buffer passed to a second dispatch
    return a, c


def pmap_reuse(state, momentum, b):
    out = multi(state, momentum, b)
    return out, momentum  # momentum was donated at position 1
