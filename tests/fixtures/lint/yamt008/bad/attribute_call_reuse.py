"""YAMT008 must flag: donation through shapes only the call graph can see.

The `trainer.train_step` attribute call was the documented blind spot of the
intra-module rule ("attribute calls remain out of static reach" —
ROADMAP.md); the factory-result donor is the live cli/train.py shape
(`make_dp_train_step` returns `jax.jit(fn, donate_argnums=(0,))`).
"""

import jax


def _step(s, b):
    return s + b


class Trainer:
    def __init__(self):
        self.train_step = jax.jit(_step, donate_argnums=(0,))


def train(state, batches):
    trainer = Trainer()
    total = None
    for b in batches:
        new_state = trainer.train_step(state, b)  # donates `state`...
        total = state if total is None else total + state  # ...then reads it
        state = new_state
    return state, total


def make_step():
    return jax.jit(_step, donate_argnums=(0,))


def factory_result_donor(state, b):
    step = make_step()  # the summary records the returned jit's donation
    out = step(state, b)
    return out + state  # read after the donated dispatch
