"""YAMT015 bad fixture: subprocess spawns with no bounded cleanup path."""

import subprocess


def wait_for_socket(proc):
    return proc


def launch_worker(cmd):
    # flagged: anything between the spawn and the return can raise, and
    # nothing on the exception edge terminates or bounded-waits the child
    proc = subprocess.Popen(cmd)
    wait_for_socket(proc)
    return proc


class LeakySupervisor:
    def spawn(self, cmd):
        # flagged: the handle lands on self, but no function in the file
        # ever terminates/kills/bounded-waits self._proc
        self._proc = subprocess.Popen(cmd)
        return self._proc

    def running(self):
        return self._proc.poll() is None


def build_native(cmd):
    # flagged: no timeout — a wedged child wedges the parent forever
    subprocess.run(cmd, check=True)


def read_version(cmd):
    # flagged: check_output with no timeout is the same unbounded wait
    return subprocess.check_output(cmd)
