"""YAMT015 clean fixture: the sanctioned bounded-supervision shapes."""

import subprocess


def wait_for_socket(proc):
    return proc


def launch_worker(cmd):
    # clean: the exception edge terminates the child with a bounded reap
    proc = subprocess.Popen(cmd)
    try:
        wait_for_socket(proc)
    except Exception:
        proc.terminate()
        proc.wait(timeout=10)
        raise
    return proc


def launch_with_finally(cmd):
    # clean: finally-guaranteed bounded cleanup is equally sanctioned
    proc = subprocess.Popen(cmd)
    ok = False
    try:
        wait_for_socket(proc)
        ok = True
    finally:
        if not ok:
            proc.kill()
            proc.wait(timeout=5)
    return proc


class BoundedSupervisor:
    def spawn(self, cmd):
        # clean: the handle lands on self and stop() below can reap it
        self._proc = subprocess.Popen(cmd)
        return self._proc

    def stop(self):
        self._proc.terminate()
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait(timeout=5)


def build_native(cmd):
    # clean: the blocking helper carries an explicit bound
    subprocess.run(cmd, check=True, timeout=600)
