"""YAMT001 must flag: a host effect reached THROUGH a resolved call.

Pre-interprocedural, the rule stopped at the call boundary: `helper` is not
itself decorated or registered, so its `time.time()` was invisible even
though `stepfn` executes it under trace every compile.
"""

import time

import jax


def helper(x):
    t = time.time()  # runs at trace time only, baked in as a constant
    return x * t


@jax.jit
def stepfn(x):
    return helper(x)


class Stepper:
    def run(self, x):
        return print("step", x)  # host print, reached via jax.jit(obj.method)


def build(stepper: Stepper):
    return jax.jit(stepper.run)  # attribute-call registration, now resolved
