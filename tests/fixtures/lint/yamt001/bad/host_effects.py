"""YAMT001 must flag: host-side effects inside jit/shard_map-traced fns."""

import time

import jax
import numpy as np


def step(state, x):
    print("stepping", x)  # trace-time only, never per step
    t0 = time.time()  # frozen at trace time
    noise = np.random.rand()  # host RNG baked into the program as a constant
    loss = float(x)  # host sync / ConcretizationTypeError on a tracer
    return state + x * noise + t0 + loss


def readback(x):
    return x.mean().item()  # forces a device->host sync inside the program


def make_step(optimizer):
    # the inner fn is returned and jitted in ANOTHER module, but its lax
    # collective proves it is a traced context — the print must flag
    from jax import lax

    def step_fn(ts, batch):
        print("loss", ts)
        return lax.pmean(ts, "data")

    return step_fn


step_jit = jax.jit(step)
readback_jit = jax.jit(readback)
