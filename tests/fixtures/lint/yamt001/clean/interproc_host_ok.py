"""YAMT001 must stay silent: host effects only on host-side paths.

A helper that prints is fine when nothing traced ever calls it — the
interprocedural follow must not smear traced-ness onto build-time code.
"""

import time

import jax
import jax.numpy as jnp


def report(label, value):
    print(label, value)  # host-side logging, never reached under trace


def pure_helper(x):
    return jnp.tanh(x)


@jax.jit
def stepfn(x):
    return pure_helper(x)  # followed, and clean


def main(xs):
    t0 = time.time()
    out = stepfn(xs)
    report("elapsed", time.time() - t0)
    return out
