"""YAMT001 must stay silent: effects outside trace, jax.debug inside."""

import time

import jax
import jax.numpy as jnp


def step(state, x):
    jax.debug.print("stepping {x}", x=x)  # the sanctioned in-trace print
    return state + jnp.mean(x)


step_jit = jax.jit(step)


def driver(batches):
    # host-side timing/printing OUTSIDE the traced function is fine
    t0 = time.time()
    state = 0.0
    for b in batches:
        state = step_jit(state, b)
    print("took", time.time() - t0)
    return float(state)  # host readback after the step is fine


def make_step(optimizer):
    # BUILD-TIME host code in a step factory is host code: the collective
    # lives in the nested def, which makes its own (clean) root decision
    from jax import lax

    print("building step with", optimizer)

    def step_fn(ts, batch):
        return lax.pmean(ts, "data") + jnp.mean(batch)

    return step_fn
