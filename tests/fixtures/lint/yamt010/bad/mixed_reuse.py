"""YAMT010 must flag the MIXED pair: one direct draw plus one whole-key
pass to a consuming callee. YAMT002 sees a single draw (silent) and the
pure-callee beat sees a single pass — this pair used to slip between the
two rules (the docs/LINT.md gap carried since PR 4)."""

import jax


def init_params(rng):
    return jax.random.normal(rng, (4,))


def build(rng):
    noise = jax.random.uniform(rng, (2,))  # direct draw consumes the key...
    params = init_params(rng)  # ...then the same key goes whole to a callee
    return params, noise


def build_flipped(rng):
    params = init_params(rng)  # callee consumes first...
    noise = jax.random.uniform(rng, (2,))  # ...then a direct draw repeats it
    return params, noise
