"""YAMT010 must flag: one key passed whole to two key-consuming callees."""

import jax


def init_params(rng):
    return jax.random.normal(rng, (4,))


def sample_noise(rng):
    return jax.random.uniform(rng, (2,))


def derive(rng):
    # split/fold_in consumption counts too: two callees splitting the SAME
    # key derive the same subkey streams
    return jax.random.split(rng, 2)


class Net:
    def init(self, rng):
        return jax.random.normal(rng, (4,))


def build(rng):
    params = init_params(rng)
    noise = sample_noise(rng)  # same key, second consuming callee
    return params, noise


def build_via_method(rng):
    net = Net()
    w = net.init(rng)  # method on a locally-constructed instance consumes...
    keys = derive(rng)  # ...and the same key is then split by another callee
    return w, keys
