"""Clean: the direct draw and the consuming callee each get their own
subkey — and a single direct draw with no callee pass is nobody's finding."""

import jax


def init_params(rng):
    return jax.random.normal(rng, (4,))


def build(rng):
    k_noise, k_init = jax.random.split(rng)
    noise = jax.random.uniform(k_noise, (2,))
    params = init_params(k_init)
    return params, noise


def single_draw(rng):
    return jax.random.uniform(rng, (2,))
