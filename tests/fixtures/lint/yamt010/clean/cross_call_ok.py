"""YAMT010 must stay silent: split-per-callee, opaque degrade, loop idiom."""

import jax


def init_params(rng):
    return jax.random.normal(rng, (4,))


def sample_noise(rng):
    return jax.random.uniform(rng, (2,))


def describe(tag, rng):
    # takes a key but never consumes it: passing the same key here twice
    # derives nothing
    return f"{tag}: {rng.shape}"


def build(rng):
    r_init, r_noise = jax.random.split(rng)
    params = init_params(r_init)
    noise = sample_noise(r_noise)
    return params, noise


def rebind_between(rng):
    params = init_params(rng)
    rng = jax.random.fold_in(rng, 1)  # rebound: the second pass is a new key
    return params, sample_noise(rng)


def non_consuming(rng):
    a = describe("a", rng)
    b = describe("b", rng)
    return a, b


def opaque_callees(loader, rng):
    # unresolvable targets never count — soundness over recall
    x = loader.init(rng)
    y = loader.sample(rng)
    return x, y


def train_loop(step_rng, batches):
    # the sanctioned training-loop idiom: the SAME key goes to the SAME
    # callee every iteration, and the step derives per-call streams by
    # folding in its step counter (cli/train.py / train/steps.py)
    out = []
    for b in batches:
        out.append(init_params(step_rng))
    return out
