"""YAMT002 must stay silent: per-element keys via split/fold_in."""

import jax


def split_comp_ok(key, n):
    # the comprehension target IS the key: rebound fresh every element
    return [jax.random.normal(k) for k in jax.random.split(key, n)]


def fold_comp_ok(key, n):
    return [jax.random.normal(jax.random.fold_in(key, i)) for i in range(n)]


def iterable_draw_ok(key, n):
    # a single draw in the ITERABLE evaluates once, outside the loop
    return [x * 2 for x in jax.random.normal(key, (n,))]
