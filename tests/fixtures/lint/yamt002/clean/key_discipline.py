"""YAMT002 must stay silent: split/fold_in before every draw, branches merge."""

import jax


def sample(rng):
    r_a, r_b = jax.random.split(rng)
    a = jax.random.normal(r_a, (4,))
    b = jax.random.uniform(r_b, (4,))
    return a + b


def loop_ok(key, n):
    total = 0.0
    for i in range(n):
        total = total + jax.random.normal(jax.random.fold_in(key, i))
    return total


def branches_ok(rng, flag):
    # mutually exclusive draws off one key are fine (exactly one executes)
    if flag:
        return jax.random.normal(rng, (2,))
    return jax.random.uniform(rng, (2,))


def rebind_ok(rng):
    x = jax.random.normal(rng, (2,))
    rng = jax.random.fold_in(rng, 1)
    y = jax.random.normal(rng, (2,))
    return x + y


def ternary_ok(rng, flag):
    # a conditional expression's arms are exclusive, like if/else branches
    return jax.random.normal(rng) if flag else jax.random.uniform(rng)
