"""YAMT002 must flag: the same PRNG key consumed by two draws / in a loop."""

import jax


def sample(rng):
    a = jax.random.normal(rng, (4,))
    b = jax.random.uniform(rng, (4,))  # second draw off the SAME key
    return a + b


def loop_reuse(key, n):
    total = 0.0
    for _ in range(n):
        total = total + jax.random.normal(key)  # same key every iteration
    return total
