"""YAMT002 must flag: comprehension-scoped draws off a key bound outside."""

import jax


def list_comp_reuse(key, n):
    return [jax.random.normal(key) for _ in range(n)]  # same key per element


def genexpr_reuse(rng, xs):
    return sum(jax.random.uniform(rng) for _ in xs)  # same key per element
