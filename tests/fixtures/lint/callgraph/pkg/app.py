"""Call shapes the graph must pin: direct, aliased, instance-method,
self-attr, factory-result, tuple-unpacked, container-indexed, and an
unresolvable dynamic call."""

from . import core as eng
from .core import helper as h2

STAGES = {"warm": eng.helper}


def direct(x):
    return h2(x)


def via_module(x):
    return eng.helper(x)


def via_instance(x):
    trainer = eng.Trainer()
    return trainer.train_step(x)


def via_self_attr(x):
    trainer = eng.Trainer()
    return trainer._fn(x)


def via_factory(x):
    step = eng.make_step(2)
    return step(x)


def via_tuple(x):
    fwd, make = h2, eng.make_step
    return fwd(x)


def via_container(x):
    steps = (eng.make_step, h2)
    return steps[1](x)


def via_dict(x):
    return STAGES["warm"](x)


def dynamic(x, name):
    fn = getattr(eng, name)
    return fn(x)
