"""Fixture package for call-graph resolution tests (tests/test_lint_callgraph.py)."""
