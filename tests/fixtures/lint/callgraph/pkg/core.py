"""Callees for the resolution fixtures: a function, a class with a method
and a self-attr callable, and a factory returning a local def."""


def helper(x):
    return x + 1


class Trainer:
    def __init__(self):
        self._fn = helper

    def train_step(self, ts):
        return helper(ts)


def make_step(scale):
    def step(x):
        return x * scale

    return step
