"""Property-based fuzz of the central NAS invariant (masked supernet forward
== rematerialized forward) over random block shapes, kernel mixes, SE
configurations, strides, and masks."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.nas import rematerialize
from yet_another_mobilenet_series_tpu.ops.blocks import InvertedResidual


@st.composite
def block_and_mask(draw):
    cin = draw(st.sampled_from([4, 8, 12]))
    residual = draw(st.booleans())
    cout = cin if residual else draw(st.sampled_from([6, 10]))
    stride = 1 if residual else draw(st.sampled_from([1, 2]))
    kernels = tuple(sorted(draw(st.sets(st.sampled_from([3, 5, 7]), min_size=1, max_size=3))))
    groups = tuple(draw(st.integers(1, 6)) for _ in kernels)
    expanded = sum(groups)
    se = draw(st.sampled_from([0, max(expanded // 3, 1)]))
    block = InvertedResidual(
        in_channels=cin, out_channels=cout, expanded_channels=expanded, stride=stride,
        kernel_sizes=kernels, group_channels=groups, active_fn=draw(st.sampled_from(["relu6", "hswish", "swish"])),
        se_channels=se, force_expand=True,
    )
    mask = np.asarray(draw(st.lists(st.sampled_from([0.0, 1.0]), min_size=expanded, max_size=expanded)), np.float32)
    if mask.sum() == 0 and not block.has_residual:
        mask[draw(st.integers(0, expanded - 1))] = 1.0
    return block, mask


@settings(max_examples=25, deadline=None)
@given(data=block_and_mask(), seed=st.integers(0, 2**20))
@pytest.mark.slow
def test_masked_equals_rematerialized_fuzz(data, seed):
    block, mask = data
    params, state = block.init(jax.random.PRNGKey(seed))
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (2, 6, 6, block.in_channels))
    # exercise non-fresh BN state
    _, state = block.apply(params, state, x, train=True)

    y_masked, _ = block.apply(params, state, x, train=False, mask=jnp.asarray(mask))

    # wrap the single block as a one-block "network" for rematerialize
    from dataclasses import replace as dc_replace

    from yet_another_mobilenet_series_tpu.models.specs import Network
    from yet_another_mobilenet_series_tpu.ops.blocks import ConvBNAct
    from yet_another_mobilenet_series_tpu.ops.layers import Dense

    net = Network(
        stem=ConvBNAct(3, block.in_channels, 3, 1),
        blocks=(block,),
        head=None,
        feature=None,
        feature_act="relu",
        classifier=Dense(block.out_channels, 2),
        dropout=0.0,
        image_size=6,
    )
    full_params = {"stem": {}, "blocks": {"0": params}, "classifier": {}}
    full_state = {"stem": {}, "blocks": {"0": state}}
    new_net, new_p, new_s, _, _, report = rematerialize.rematerialize(
        net, full_params, full_state, {"0": jnp.asarray(mask)}
    )
    if report.dropped_blocks:
        np.testing.assert_allclose(np.asarray(y_masked), np.asarray(x), rtol=1e-5, atol=1e-6)
        return
    y_remat, _ = new_net.blocks[0].apply(new_p["blocks"]["0"], new_s["blocks"]["0"], x, train=False)
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_remat), rtol=1e-4, atol=1e-5)
