"""The replica fleet (ROADMAP item 1): the shared HTTP client
(serve/client.py), weighted routing with ejection/readmission
(serve/router.py), p99-derived request hedging (serve/hedge.py), the
cooldown-hysteresis autoscaler (serve/autoscale.py), and the fleet
supervisor (cli/fleet.py).

Policy layers (hedge resolution, routing, scaling decisions, supervision)
are unit-tested against fakes — no subprocesses, deterministic. The one
end-to-end smoke spawns a REAL 2-replica fleet behind the real router
frontend, kills a replica with SIGKILL mid-traffic, and asserts the
availability contract: zero client-visible 5xx, the corpse restarted, a
clean SIGTERM drain.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from yet_another_mobilenet_series_tpu.cli.fleet import FleetChaos, FleetSupervisor
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve.autoscale import Autoscaler
from yet_another_mobilenet_series_tpu.serve.client import (
    ClientConnectError,
    ClientHTTPError,
    ReplicaClient,
)
from yet_another_mobilenet_series_tpu.serve.hedge import ROUTER_LATENCY, HedgedCall, Hedger
from yet_another_mobilenet_series_tpu.serve.router import NoHealthyReplicas, Router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _future():
    from concurrent.futures import Future

    return Future()


def _snap(key):
    return get_registry().snapshot().get(key, 0)


# ---------------------------------------------------------------------------
# hedge idempotence (serve/hedge.py)
# ---------------------------------------------------------------------------


def test_hedged_call_resolves_exactly_once_and_counts_the_loser():
    """Duplicate responses for one request id resolve the future exactly
    once; the loser's late answer is dropped and counted."""
    wasted0, wins0 = _snap("serve.hedge_wasted"), _snap("serve.hedge_wins")
    fut = _future()
    call = HedgedCall(fut)
    assert call.launch_hedge()
    assert call.ok(HedgedCall.PRIMARY, "first") is True
    # the hedge's late duplicate answer: dropped, counted, never delivered
    assert call.ok(HedgedCall.HEDGE, "late") is False
    assert fut.result(timeout=1) == "first"
    assert _snap("serve.hedge_wasted") == wasted0 + 1
    assert _snap("serve.hedge_wins") == wins0


def test_hedge_win_counts_and_primary_late_answer_dropped():
    wins0, wasted0 = _snap("serve.hedge_wins"), _snap("serve.hedge_wasted")
    fut = _future()
    call = HedgedCall(fut)
    assert call.launch_hedge()
    assert call.ok(HedgedCall.HEDGE, "dup") is True
    assert call.ok(HedgedCall.PRIMARY, "slow") is False
    assert fut.result(timeout=1) == "dup"
    assert _snap("serve.hedge_wins") == wins0 + 1
    assert _snap("serve.hedge_wasted") == wasted0 + 1


@pytest.mark.parametrize("primary_first", [True, False])
def test_both_legs_failing_surfaces_the_primary_error(primary_first):
    """A hedged request that fails on BOTH replicas surfaces the primary's
    error (the hedge is an optimization, not a new failure mode) — in
    either failure order."""
    fut = _future()
    call = HedgedCall(fut)
    assert call.launch_hedge()
    primary_exc, hedge_exc = RuntimeError("primary boom"), RuntimeError("hedge boom")
    if primary_first:
        assert call.err(HedgedCall.PRIMARY, primary_exc) is False  # hedge pending
        assert call.err(HedgedCall.HEDGE, hedge_exc) is True
    else:
        assert call.err(HedgedCall.HEDGE, hedge_exc) is False  # primary pending
        assert call.err(HedgedCall.PRIMARY, primary_exc) is True
    with pytest.raises(RuntimeError, match="primary boom"):
        fut.result(timeout=1)


def test_one_leg_failure_waits_for_the_other_to_win():
    fut = _future()
    call = HedgedCall(fut)
    assert call.launch_hedge()
    call.err(HedgedCall.PRIMARY, RuntimeError("primary boom"))
    assert not fut.done()  # the hedge can still save it
    assert call.ok(HedgedCall.HEDGE, "saved") is True
    assert fut.result(timeout=1) == "saved"


def test_unhedged_failure_resolves_immediately_and_late_hedge_never_launches():
    fut = _future()
    call = HedgedCall(fut)
    assert call.err(HedgedCall.PRIMARY, RuntimeError("boom")) is True
    with pytest.raises(RuntimeError):
        fut.result(timeout=1)
    assert call.launch_hedge() is False  # resolved: the timer's leg must not fire


def test_hedger_timer_derives_from_histogram_with_min_samples_and_clamps():
    get_registry().reset()
    h = Hedger(quantile=0.99, min_samples=10, min_timer_ms=5.0, max_timer_ms=100.0)
    assert h.timer_s("interactive") is None  # cold: no hedging on no data
    for _ in range(20):
        h.observe("interactive", 0.02)
    t = h.timer_s("interactive")
    assert t is not None and 0.005 <= t <= 0.1
    for _ in range(50):
        h.observe("batch", 10.0)  # a slow class clamps at max_timer
    assert h.timer_s("batch") == pytest.approx(0.1)
    with pytest.raises(ValueError):
        Hedger(quantile=1.5)


# ---------------------------------------------------------------------------
# router policy against fake clients (no sockets)
# ---------------------------------------------------------------------------


class _FakeReplicaClient:
    """Scriptable stand-in for ReplicaClient: predict behavior + healthz."""

    def __init__(self, host, port):
        self.key = f"{host}:{port}"
        self.predict_fn = lambda image, **kw: np.asarray([float(port)], np.float32)
        self.health = (200, {"breaker_state": 0, "queued_total": 0, "draining": False,
                             "replica": {"replica_id": self.key, "start_unix": 1.0}})
        self.predicts = 0
        self.polls = 0
        self.closed = False

    def predict(self, image, **kw):
        self.predicts += 1
        return self.predict_fn(image, **kw)

    def healthz(self, timeout_s=None):
        self.polls += 1
        h = self.health
        if isinstance(h, Exception):
            raise h
        return h

    def close(self):
        self.closed = True


def _fake_router(n=2, **kw):
    fakes = {}

    def factory(host, port):
        fakes[f"{host}:{port}"] = c = _FakeReplicaClient(host, port)
        return c

    backends = [("127.0.0.1", 9000 + i) for i in range(n)]
    router = Router(backends, client_factory=factory, seed=0, **kw)
    return router, fakes


def test_router_routes_and_passes_typed_verdicts_through():
    get_registry().reset()
    router, fakes = _fake_router(2)
    try:
        out = router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        assert float(out[0]) in (9000.0, 9001.0)
        assert _snap("fleet.routed") == 1
        # a replica's typed 429 crosses the router verbatim (no retry)
        for c in fakes.values():
            c.predict_fn = lambda image, **kw: (_ for _ in ()).throw(
                ClientHTTPError(429, "queue_full", "full"))
        with pytest.raises(ClientHTTPError) as ei:
            router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        assert ei.value.status == 429 and ei.value.tag == "queue_full"
        with pytest.raises(ValueError, match="platinum"):
            router.submit(np.zeros((4, 4, 3), np.float32), priority="platinum")
    finally:
        router.stop()


def test_router_retries_dead_socket_on_another_replica():
    """A killed replica's connect error re-routes the request (inference is
    pure): the client sees success, the router scores the failure."""
    get_registry().reset()
    router, fakes = _fake_router(2)
    try:
        dead = fakes["127.0.0.1:9000"]
        dead.predict_fn = lambda image, **kw: (_ for _ in ()).throw(
            ClientConnectError("connection refused"))
        for _ in range(6):
            out = router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
            assert float(out[0]) == 9001.0  # always lands on the live one
        assert _snap("fleet.route_retries") >= 1
        # the dead replica's failures ejected it from rotation
        assert not next(r for r in router.replicas_state() if r["key"] == dead.key)["routable"]
    finally:
        router.stop()


def test_router_poll_ejects_and_readmits_and_detects_restart():
    get_registry().reset()
    router, fakes = _fake_router(2, eject_failures=2)
    try:
        sick = fakes["127.0.0.1:9000"]
        router.poll_once()  # learn identities while healthy
        sick.health = ClientConnectError("down")
        router.poll_once()
        assert router.n_routable() == 2  # one strike is not ejection
        router.poll_once()
        assert router.n_routable() == 1
        assert _snap("fleet.ejections") == 1
        state = router.state()
        assert state["breaker_state"] == 0  # still serving on the healthy one
        assert state["fleet"]["routable"] == 1 and state["fleet"]["total"] == 2
        # recovery WITH a new start_unix = a restarted process behind the
        # same address: readmitted AND counted as a detected restart
        sick.health = (200, {"breaker_state": 0, "queued_total": 0, "draining": False,
                             "replica": {"replica_id": sick.key, "start_unix": 2.0}})
        router.poll_once()
        assert router.n_routable() == 2
        assert _snap("fleet.readmissions") == 1
        assert _snap("fleet.replica_restarts") == 1
        # all replicas down -> typed unavailability, state flips to open
        for c in fakes.values():
            c.health = ClientConnectError("down")
        router.poll_once()
        router.poll_once()
        assert router.state()["breaker_state"] == 1
        with pytest.raises(NoHealthyReplicas):
            router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
    finally:
        router.stop()


def test_router_draining_replica_is_ejected_and_requests_reroute():
    get_registry().reset()
    router, fakes = _fake_router(2)
    try:
        draining = fakes["127.0.0.1:9001"]
        draining.health = (200, {"breaker_state": 0, "queued_total": 0, "draining": True,
                                 "replica": {"replica_id": draining.key, "start_unix": 1.0}})
        router.poll_once()
        assert router.n_routable() == 1
        out = router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        assert float(out[0]) == 9000.0
    finally:
        router.stop()


def test_router_weighted_pick_skews_away_from_backlog():
    get_registry().reset()
    router, fakes = _fake_router(2)
    try:
        deep = fakes["127.0.0.1:9000"]
        deep.health = (200, {"breaker_state": 0, "queued_total": 10_000, "draining": False,
                             "replica": {"replica_id": deep.key, "start_unix": 1.0}})
        router.poll_once()
        assert router.mean_queue_depth() == pytest.approx(5000.0)
        for _ in range(12):
            router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        # weight 1/(1+10000) vs 1: the backlogged replica sees (almost) none
        assert fakes["127.0.0.1:9001"].predicts >= 11
    finally:
        router.stop()


def test_router_hedges_straggler_to_second_replica_first_answer_wins():
    """The tentpole behavior end-to-end in-process: the straggler's request
    is duplicated to the other replica at the p-derived timer and the
    duplicate's answer lands first (serve.hedges / serve.hedge_wins)."""
    get_registry().reset()
    hedger = Hedger(quantile=0.9, min_samples=5, min_timer_ms=10.0)
    for _ in range(10):
        hedger.observe("interactive", 0.01)  # learned: normally ~10ms
    router, fakes = _fake_router(2, hedger=hedger)
    try:
        slow = fakes["127.0.0.1:9000"]
        slow_called = threading.Event()

        def slow_predict(image, **kw):
            slow_called.set()
            time.sleep(1.0)
            return np.asarray([9000.0], np.float32)

        slow.predict_fn = slow_predict
        # pin the primary pick to the straggler: the fast replica reports a
        # huge backlog, so weight collapses onto the slow one
        fast = fakes["127.0.0.1:9001"]
        fast.health = (200, {"breaker_state": 0, "queued_total": 10_000, "draining": False,
                             "replica": {"replica_id": fast.key, "start_unix": 1.0}})
        router.poll_once()
        t0 = time.perf_counter()
        out = router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=10)
        elapsed = time.perf_counter() - t0
        assert slow_called.wait(1)  # the primary really went to the straggler
        assert float(out[0]) == 9001.0  # ...and the hedge's answer won
        assert elapsed < 0.9  # did not wait out the straggler
        snap = get_registry().snapshot()
        assert snap["serve.hedges"] >= 1 and snap["serve.hedge_wins"] >= 1
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# gray-failure soft ejection (latency outlier -> weight decay -> eject ->
# probation readmission), backpressure 503s, and the jittered poll schedule
# ---------------------------------------------------------------------------


def _set_leg_latency(router, key, seconds):
    """Install a per-leg latency estimate directly (the EWMA the router
    builds from measured dispatch legs) so sweep decisions are clock-free."""
    with router._lock:
        router._replicas[key].lat_ewma_s = seconds


def test_slow_replica_soft_ejection_lifecycle():
    """A slow-but-alive replica: weight decays on the first outlier sweeps,
    ejection lands after slow_eject_after consecutive ones
    (fleet.slow_ejections), probation blocks the healthy-poll readmission
    until the cooldown, then readmission grants a FRESH estimate."""
    get_registry().reset()
    router, fakes = _fake_router(3, slow_eject=True, slow_factor=3.0,
                                 slow_eject_after=3, slow_cooldown_s=5.0,
                                 slow_min_ms=1.0)
    try:
        slow_key = "127.0.0.1:9000"
        for key in fakes:
            _set_leg_latency(router, key, 0.2 if key == slow_key else 0.004)
        router.poll_once()  # sweep 1: strike, weight halves
        state = {r["key"]: r for r in router.replicas_state()}
        assert state[slow_key]["routable"]  # decay first, never instant ejection
        assert state[slow_key]["slow_strikes"] == 1
        assert state[slow_key]["weight_scale"] == pytest.approx(0.5)
        assert all(state[k]["weight_scale"] == 1.0 for k in fakes if k != slow_key)
        router.poll_once()  # sweep 2
        router.poll_once()  # sweep 3: ejected
        state = {r["key"]: r for r in router.replicas_state()}
        assert not state[slow_key]["routable"]
        assert _snap("fleet.slow_ejections") == 1
        assert _snap("fleet.ejections") == 1
        assert state[slow_key]["lat_ewma_ms"] is None  # probation starts clean
        assert state[slow_key]["weight_scale"] == 1.0
        # the replica keeps answering /healthz 200 — but probation holds it
        # out until the cooldown passes (fake-clock polls)
        t0 = time.monotonic()
        router.poll_once(now=t0 + 1.0)
        # force-refresh every schedule so the due-filter can't skip it
        router.poll_once()
        assert not next(r for r in router.replicas_state()
                        if r["key"] == slow_key)["routable"]
        # after the cooldown, the next healthy poll readmits it
        with router._lock:
            until = router._replicas[slow_key].slow_until
        router.poll_once(now=until + 0.1)
        assert next(r for r in router.replicas_state()
                    if r["key"] == slow_key)["routable"]
        assert _snap("fleet.readmissions") == 1
    finally:
        router.stop()


def test_slow_ejection_needs_a_fleet_and_respects_floor():
    """No ejection with a single scored replica (no fleet to be an outlier
    of), and sub-floor absolute latencies never look like gray failures
    however large the RATIO is."""
    get_registry().reset()
    router, fakes = _fake_router(2, slow_eject=True, slow_factor=3.0,
                                 slow_eject_after=1, slow_min_ms=50.0)
    try:
        # 10x ratio but both under the 50ms floor: fast jitter, not gray
        _set_leg_latency(router, "127.0.0.1:9000", 0.020)
        _set_leg_latency(router, "127.0.0.1:9001", 0.002)
        for _ in range(4):
            router.poll_once()
        assert router.n_routable() == 2
        assert _snap("fleet.slow_ejections") == 0
        # only one replica has data: nothing to compare against
        _set_leg_latency(router, "127.0.0.1:9000", 10.0)
        with router._lock:
            router._replicas["127.0.0.1:9001"].lat_ewma_s = None
        router.poll_once()
        assert router.n_routable() == 2
    finally:
        router.stop()


def test_slow_ejection_off_by_default_and_crash_path_unchanged():
    """Routers built without slow_eject never latency-eject (r06 bench
    compatibility), and crash ejection still uses the same consecutive-
    failure counter it always did."""
    get_registry().reset()
    router, fakes = _fake_router(2)  # slow_eject defaults False
    try:
        _set_leg_latency(router, "127.0.0.1:9000", 10.0)
        _set_leg_latency(router, "127.0.0.1:9001", 0.001)
        for _ in range(5):
            router.poll_once()
        assert router.n_routable() == 2
        assert _snap("fleet.slow_ejections") == 0
    finally:
        router.stop()


def test_router_learns_per_leg_latency_ewma_from_real_legs():
    get_registry().reset()
    router, fakes = _fake_router(2, slow_eject=True)
    try:
        for _ in range(6):
            router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        states = router.replicas_state()
        served = [r for r in states if r["lat_ewma_ms"] is not None]
        assert served, "no replica learned a latency estimate"
        assert all(r["lat_ewma_ms"] > 0 for r in served)
    finally:
        router.stop()


def test_retry_after_503_is_backpressure_not_ejection():
    """A Retry-After-bearing 503 (breaker cooldown / brownout shed) re-routes
    but never scores the replica's ejection counter; a 503 WITHOUT the hint
    (draining, nothing routable behind it) ejects after eject_failures."""
    get_registry().reset()
    router, fakes = _fake_router(2, eject_failures=2)
    try:
        sick = fakes["127.0.0.1:9000"]
        # pin the first pick onto the sick replica: the healthy one reports
        # a huge backlog so its weight collapses
        fast = fakes["127.0.0.1:9001"]
        fast.health = (200, {"breaker_state": 0, "queued_total": 100_000, "draining": False,
                             "replica": {"replica_id": fast.key, "start_unix": 1.0}})
        router.poll_once()
        sick.predict_fn = lambda image, **kw: (_ for _ in ()).throw(
            ClientHTTPError(503, "brownout", "shed", retry_after=1.0))
        for _ in range(6):
            out = router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
            assert float(out[0]) == 9001.0  # re-routed and served
        assert _snap("fleet.backpressure") >= 6
        assert router.n_routable() == 2, "backpressure 503s must never eject"
        assert _snap("fleet.ejections") == 0
        # the SAME shape without Retry-After scores toward ejection
        sick.predict_fn = lambda image, **kw: (_ for _ in ()).throw(
            ClientHTTPError(503, "draining", "going away"))
        for _ in range(4):
            router.submit(np.zeros((4, 4, 3), np.float32)).result(timeout=5)
        assert not next(r for r in router.replicas_state()
                        if r["key"] == sick.key)["routable"]
    finally:
        router.stop()


def test_poll_schedule_jitter_on_fake_clock():
    """Per-replica jittered poll deadlines: seeded, distinct across
    replicas, inside [interval*(1-j), interval*(1+j)], and the due-filter
    only polls replicas whose deadline has passed."""
    get_registry().reset()
    router, fakes = _fake_router(4, poll_interval_s=1.0, poll_jitter=0.2)
    try:
        router.poll_once(now=100.0)  # all due at t=0 schedule start
        assert all(c.polls == 1 for c in fakes.values())
        with router._lock:
            deadlines = {r.key: r.next_poll_t for r in router._replicas.values()}
        assert all(100.0 + 0.8 <= t <= 100.0 + 1.2 for t in deadlines.values()), deadlines
        # seeded jitter really staggers them (not one synchronized herd)
        assert len({round(t, 6) for t in deadlines.values()}) == len(deadlines)
        # before any deadline: nothing polls
        router.poll_once(now=100.5)
        assert all(c.polls == 1 for c in fakes.values())
        # between the earliest and latest deadline: exactly the due subset
        mid = sorted(deadlines.values())[1]
        router.poll_once(now=mid)
        polled = sum(c.polls - 1 for c in fakes.values())
        assert polled == sum(1 for t in deadlines.values() if t <= mid) >= 1
        # a bare poll_once (tests / bench) still force-polls everything
        router.poll_once()
        assert all(c.polls >= 2 for c in fakes.values())
        # determinism: the same seed reproduces the same schedule
        router2, fakes2 = _fake_router(4, poll_interval_s=1.0, poll_jitter=0.2)
        try:
            router2.poll_once(now=100.0)
            with router2._lock:
                deadlines2 = {r.key: r.next_poll_t for r in router2._replicas.values()}
            assert deadlines2 == deadlines
        finally:
            router2.stop()
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# TTL-leased membership (fake clients, fake clock — deterministic)
# ---------------------------------------------------------------------------


def test_lease_register_renew_expire_lifecycle():
    """A self-registered backend joins with a TTL, renews by heartbeat, and
    is REMOVED (not just ejected) when the lease lapses — the silently-
    vanished-host path no crash signal can cover across machines."""
    get_registry().reset()
    router, fakes = _fake_router(1)
    try:
        t0 = time.monotonic()
        doc = router.register("127.0.0.1", 9100, ttl_s=10.0, replica_id="remote-1")
        assert doc["ok"] and doc["new"] and doc["source"] == "lease"
        assert _snap("fleet.registrations") == 1
        assert len(router.replicas_state()) == 2
        assert router.state()["membership"] == {"static": 1, "leased": 1,
                                                "lease_ttl_s": 5.0}
        # renewal pushes the lease out; counted separately from admission
        doc = router.register("127.0.0.1", 9100, ttl_s=10.0)
        assert doc["ok"] and not doc["new"]
        assert _snap("fleet.registrations") == 1
        assert _snap("fleet.lease_renewals") == 1
        # a mid-lease sweep keeps it; one past the TTL removes it
        router.poll_once(now=t0 + 5.0)
        assert len(router.replicas_state()) == 2
        router.poll_once(now=t0 + 30.0)
        assert len(router.replicas_state()) == 1
        assert _snap("fleet.lease_expirations") == 1
        # the expired member's client was closed, not leaked
        assert fakes["127.0.0.1:9100"].closed
    finally:
        router.stop()


def test_lease_deregister_and_static_precedence():
    get_registry().reset()
    router, fakes = _fake_router(1)
    try:
        router.register("127.0.0.1", 9200, ttl_s=60.0)
        # deregister = the clean-drain fast path (no TTL wait)
        assert router.deregister("127.0.0.1", 9200)["ok"]
        assert len(router.replicas_state()) == 1
        assert _snap("fleet.deregistrations") == 1
        # static members are supervisor-owned: deregister refuses
        out = router.deregister("127.0.0.1", 9000)
        assert not out["ok"] and out["reason"] == "static"
        assert router.deregister("127.0.0.1", 9999)["reason"] == "unknown"
        # a supervisor membership push must NOT evict a live leased member
        router.register("127.0.0.1", 9300, ttl_s=60.0)
        router.set_backends([("127.0.0.1", 9000)])
        keys = {r["key"]: r["source"] for r in router.replicas_state()}
        assert keys == {"127.0.0.1:9000": "static", "127.0.0.1:9300": "lease"}
        # ...and adopting a leased address promotes it to static (no lease)
        router.set_backends([("127.0.0.1", 9000), ("127.0.0.1", 9300)])
        keys = {r["key"]: r["source"] for r in router.replicas_state()}
        assert keys["127.0.0.1:9300"] == "static"
        router.poll_once(now=time.monotonic() + 3600.0)  # no lease to expire
        assert len(router.replicas_state()) == 2
        with pytest.raises(ValueError, match="ttl_s"):
            router.register("127.0.0.1", 9400, ttl_s=-1.0)
    finally:
        router.stop()


def test_ejection_probation_damps_flap_ping_pong():
    """A flapping link (fail, recover, fail, ...) must produce ONE bounded
    eject/readmit cycle per eject_cooldown_s, not one per flap: a healthy
    poll inside the probation may NOT readmit."""
    get_registry().reset()
    router, fakes = _fake_router(2, eject_failures=2, eject_cooldown_s=10.0)
    try:
        flappy = fakes["127.0.0.1:9000"]
        healthy = (200, {"breaker_state": 0, "queued_total": 0, "draining": False,
                         "replica": {"replica_id": flappy.key, "start_unix": 1.0}})
        t0 = time.monotonic()
        flappy.health = ClientConnectError("link down")
        router.poll_once(now=t0)
        # the due-filter spaces polls by the jittered interval: step past it
        router.poll_once(now=t0 + 0.4)  # 2nd strike: ejected, probation starts
        assert router.n_routable() == 1
        assert _snap("fleet.ejections") == 1
        assert _snap("fleet.partition_ejections") == 1
        # the link flaps UP: healthy polls INSIDE the probation do not readmit
        flappy.health = healthy
        for dt in (1.0, 3.0, 9.0):
            router.poll_once(now=t0 + dt)
            assert router.n_routable() == 1, f"readmitted {dt}s into a 10s probation"
        assert _snap("fleet.readmissions") == 0
        # past the probation, the next healthy poll readmits — once
        router.poll_once(now=t0 + 10.5)
        assert router.n_routable() == 2
        assert _snap("fleet.readmissions") == 1
        assert _snap("fleet.ejections") == 1  # the flap cost ONE cycle
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# the connect/read timeout split (client-side unit tests)
# ---------------------------------------------------------------------------


def test_client_connect_timeout_is_typed_counted_and_conclusive(monkeypatch):
    """A handshake that hangs past connect_timeout_s surfaces as a
    ClientConnectError (retry-another-replica, the request never left) —
    not a 60 s read-timeout burn — and counts serve.client.connect_timeouts."""
    import socket as socket_mod

    from yet_another_mobilenet_series_tpu.serve import client as client_mod

    get_registry().reset()
    seen = []

    def hang(addr, timeout=None, *a, **kw):
        seen.append(timeout)
        raise socket_mod.timeout("timed out")

    monkeypatch.setattr(client_mod.socket, "create_connection", hang)
    c = ReplicaClient("10.255.0.1", 9, timeout_s=60.0, connect_timeout_s=0.25)
    t0 = time.monotonic()
    with pytest.raises(ClientConnectError, match="connect"):
        c.predict(np.zeros((4, 4, 3), np.float32))
    elapsed = time.monotonic() - t0
    # conclusive: no second fresh-connect attempt, no read-budget burn
    assert seen == [0.25], seen
    assert elapsed < 5.0
    assert _snap("serve.client.connect_timeouts") == 1
    c.close()


def test_client_conn_table_prunes_on_reconnect():
    """The per-thread connection table must stay bounded against a flapping
    replica: every reconnect REPLACES this thread's entry instead of
    appending (the long-lived-router leak)."""
    dead = ReplicaClient("127.0.0.1", 1, timeout_s=0.5, connect_timeout_s=0.5)
    for _ in range(6):
        with pytest.raises(ClientConnectError):
            dead.predict(np.zeros((2, 2, 3), np.float32))
        assert len(dead._conns) <= 1, "reconnects must not grow the conn table"
    dead.close()
    assert len(dead._conns) == 0


# ---------------------------------------------------------------------------
# router partition suite: real sockets through the netchaos proxy
# ---------------------------------------------------------------------------


def _echo_replica(tag):
    """A real Frontend over a trivial echo engine: the replica stand-in for
    socket-level partition drills (no jax, milliseconds to start)."""
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.frontend import Frontend
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    class _EchoEngine:
        def predict_async(self, images):
            class _H:
                def result(_self):
                    return images[:, 0, 0, :1].astype(np.float32)

            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    b = PipelinedBatcher(_EchoEngine(), max_batch=8, max_wait_ms=1.0,
                         queue_depth=64, drain_timeout_s=2.0).start()
    fe = Frontend(AdmissionController(b), port=0, replica_id=tag).start()
    return b, fe


def _partition_fixture(n=2, **router_kw):
    """n echo replicas, each behind its own netchaos proxy, one router over
    the PROXY addresses — the bench's partition topology, in-process."""
    from yet_another_mobilenet_series_tpu.serve.netchaos import NetChaosProxy

    stacks = [_echo_replica(f"pr-{i}") for i in range(n)]
    proxies = [NetChaosProxy("127.0.0.1", fe.port, seed=i).start()
               for i, (_, fe) in enumerate(stacks)]
    kw = dict(poll_interval_s=0.1, eject_failures=2, route_attempts=3,
              client_timeout_s=3.0, connect_timeout_s=0.4,
              eject_cooldown_s=0.3, seed=0)
    kw.update(router_kw)
    router = Router([p.addr for p in proxies], **kw).start()

    def teardown():
        router.stop()
        for p in proxies:
            p.stop()
        for b, fe in stacks:
            fe.stop()
            b.stop()

    return router, proxies, teardown


def _watch_counter(key, baseline, t0, holder, timeout_s=20.0):
    """Background watcher stamping the instant a counter moves past its
    baseline (detection time must not be measured from a submit loop that
    itself blocks on the faulted leg)."""
    def watch():
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if _snap(key) > baseline:
                holder["t"] = time.monotonic() - t0
                return
            time.sleep(0.02)

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    return t


def test_router_blackhole_ejects_within_poll_budget_not_read_timeout():
    """The acceptance bound: a blackholed replica (connect succeeds, nothing
    answers) ejects within ~eject_failures x (poll interval + connect
    budget) — poll reads are bounded by the connect budget — with ZERO
    client-visible failures (transport retry), never the read timeout."""
    get_registry().reset()
    router, proxies, teardown = _partition_fixture(2)
    try:
        img = np.full((4, 4, 3), 5.0, np.float32)
        assert router.submit(img).result(timeout=10) is not None
        eject0 = _snap("fleet.ejections")
        detected = {}
        t0 = time.monotonic()
        proxies[0].set_fault("blackhole")
        watcher = _watch_counter("fleet.ejections", eject0, t0, detected)
        errors = []
        for _ in range(12):
            try:
                router.submit(img).result(timeout=20)
            except Exception as e:  # noqa: BLE001 — the contract is ZERO of these
                errors.append(e)
            time.sleep(0.05)
        watcher.join(timeout=20)
        assert errors == [], f"client-visible failures under blackhole: {errors}"
        assert "t" in detected, "the blackholed replica was never ejected"
        # poll reads are bounded by the connect budget: detection is a few
        # poll cycles, not the 3 s read timeout and never a 60 s default.
        # Bound: eject_failures x (interval + poll read bound) + slack for
        # a loaded 1-core box
        poll_read = max(0.4, 2 * 0.1)
        bound = 2 * (0.1 + poll_read) + 1.5
        assert detected["t"] < bound, (detected, bound)
        assert _snap("fleet.partition_ejections") >= 1
        # heal -> probation -> readmission
        proxies[0].clear()
        deadline = time.monotonic() + 15
        while router.n_routable() < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.n_routable() == 2, "the healed replica never readmitted"
    finally:
        teardown()


def test_router_reset_and_half_open_retry_onto_healthy_replica():
    """RST legs (connect-shaped) and half-open legs (read-timeout-shaped)
    both re-route: the client sees success, the faulted replica scores
    toward a partition ejection."""
    get_registry().reset()
    router, proxies, teardown = _partition_fixture(2, client_timeout_s=0.8)
    try:
        img = np.full((4, 4, 3), 5.0, np.float32)
        assert router.submit(img).result(timeout=10) is not None
        for fault in ("reset", "half_open"):
            retries0 = _snap("fleet.route_retries")
            proxies[0].set_fault(fault)
            outs = [router.submit(img).result(timeout=20) for _ in range(6)]
            assert all(o is not None for o in outs), fault
            assert _snap("fleet.route_retries") > retries0, (
                f"{fault}: no leg was ever re-routed")
            proxies[0].clear()
            deadline = time.monotonic() + 15
            while router.n_routable() < 2 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert router.n_routable() == 2, f"{fault}: never readmitted after heal"
        assert _snap("fleet.partition_ejections") >= 1
    finally:
        teardown()


def test_router_survives_flapping_link_with_zero_client_errors():
    """A flapping link (timed down windows) through the proxy: every request
    still answers (retry absorbs the down windows), and after the flapping
    stops the fleet converges back to fully routable. The deterministic
    anti-ping-pong mechanics are pinned by
    test_ejection_probation_damps_flap_ping_pong."""
    get_registry().reset()
    router, proxies, teardown = _partition_fixture(2, eject_cooldown_s=0.8)
    try:
        img = np.full((4, 4, 3), 5.0, np.float32)
        assert router.submit(img).result(timeout=10) is not None
        proxies[0].set_fault(None, flap_period_s=0.8, flap_down_s=0.4)
        errors = []
        for _ in range(20):
            try:
                router.submit(img).result(timeout=20)
            except Exception as e:  # noqa: BLE001 — the contract is ZERO of these
                errors.append(e)
            time.sleep(0.05)
        assert errors == [], f"client-visible failures under flap: {errors}"
        proxies[0].clear()
        deadline = time.monotonic() + 15
        while router.n_routable() < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.n_routable() == 2, "never converged after the flapping stopped"
    finally:
        teardown()


# ---------------------------------------------------------------------------
# autoscaler decisions (fakes; no threads)
# ---------------------------------------------------------------------------


class _FakeFleet:
    def __init__(self, n=1):
        self.n = n
        self.calls = []

    @property
    def n_replicas(self):
        return self.n

    def scale_to(self, n):
        self.calls.append(n)
        self.n = n
        return n


class _FakeRouterSignals:
    def __init__(self):
        self.queue_depth = 0.0

    def mean_queue_depth(self):
        return self.queue_depth


def _observe_latency(cls, value, n=20):
    h = get_registry().histogram(f"{ROUTER_LATENCY}.{cls}")
    for _ in range(n):
        h.observe(value)


def test_autoscaler_scales_up_on_tail_latency_and_respects_cooldown():
    get_registry().reset()
    fleet, sig = _FakeFleet(1), _FakeRouterSignals()
    a = Autoscaler(fleet, sig, min_replicas=1, max_replicas=3, cooldown_s=5.0,
                   up_p99_ms=100.0, down_p99_ms=20.0,
                   up_queue_depth=8.0, down_queue_depth=1.0)
    _observe_latency("interactive", 0.5)
    row = a.step(now=100.0)
    assert row["action"] == "up" and fleet.n == 2
    _observe_latency("interactive", 0.5)
    row = a.step(now=102.0)  # still overloaded, but inside the cooldown
    assert row["action"] == "hold" and row["in_cooldown"] and fleet.n == 2
    _observe_latency("interactive", 0.5)
    row = a.step(now=106.0)  # cooldown passed
    assert row["action"] == "up" and fleet.n == 3
    _observe_latency("interactive", 0.5)
    row = a.step(now=112.0)
    assert row["action"] == "hold" and fleet.n == 3  # max bound
    assert _snap("fleet.scale_ups") == 2


def test_autoscaler_scales_up_on_queue_depth_alone():
    get_registry().reset()
    fleet, sig = _FakeFleet(1), _FakeRouterSignals()
    a = Autoscaler(fleet, sig, min_replicas=1, max_replicas=2, cooldown_s=1.0,
                   up_p99_ms=100.0, down_p99_ms=20.0,
                   up_queue_depth=4.0, down_queue_depth=1.0)
    sig.queue_depth = 9.0  # no latency samples at all: backlog decides
    assert a.step(now=10.0)["action"] == "up" and fleet.n == 2


def test_autoscaler_scales_down_only_when_both_signals_relax():
    get_registry().reset()
    fleet, sig = _FakeFleet(3), _FakeRouterSignals()
    a = Autoscaler(fleet, sig, min_replicas=1, max_replicas=3, cooldown_s=2.0,
                   up_p99_ms=100.0, down_p99_ms=20.0,
                   up_queue_depth=8.0, down_queue_depth=1.0)
    _observe_latency("interactive", 0.005)
    sig.queue_depth = 3.0  # latency relaxed but backlog is not: hold
    assert a.step(now=10.0)["action"] == "hold" and fleet.n == 3
    sig.queue_depth = 0.0
    _observe_latency("interactive", 0.005)
    assert a.step(now=20.0)["action"] == "down" and fleet.n == 2
    # an EMPTY window (idle fleet) also counts as relaxed
    assert a.step(now=30.0)["action"] == "down" and fleet.n == 1
    assert a.step(now=40.0)["action"] == "hold" and fleet.n == 1  # min bound
    assert _snap("fleet.scale_downs") == 2
    assert [r["n"] for r in a.trace] == [3, 2, 1, 1]


def test_autoscaler_rejects_overlapping_thresholds():
    with pytest.raises(ValueError, match="dead band|thresholds"):
        Autoscaler(_FakeFleet(), _FakeRouterSignals(), up_p99_ms=50.0, down_p99_ms=50.0)
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(_FakeFleet(), _FakeRouterSignals(), min_replicas=3, max_replicas=2)


# ---------------------------------------------------------------------------
# supervisor policy with fake handles (no subprocesses)
# ---------------------------------------------------------------------------


class _FakeHandle:
    def __init__(self, slot, generation):
        self.slot = slot
        self.addr = {"host": "127.0.0.1", "port": 9100 + slot, "pid": 100 + slot}
        self.pid = self.addr["pid"]
        self._alive = True
        self.generation = generation
        self.drained = False
        self.signals = []
        self.returncode = None

    def alive(self):
        return self._alive

    def die(self, rc=-9):
        self._alive = False
        self.returncode = rc

    def drain(self, timeout_s=30.0):
        self.drained = True
        self._alive = False
        return True

    def send_signal(self, sig):
        self.signals.append(sig)
        self._alive = False
        self.returncode = -sig
        return True

    def _close_log(self):
        pass


class _FakeFactory:
    def __init__(self):
        self.spawned = []
        self.lock = threading.Lock()

    def __call__(self, slot):
        with self.lock:
            self.spawned.append(slot)
            return _FakeHandle(slot, len(self.spawned))


def _fake_supervisor(n=2, **kw):
    factory = _FakeFactory()
    changes = []
    sup = FleetSupervisor(
        replica_argv=[], log_dir="/tmp/unused", replicas=n,
        restart_backoff_ms=1.0, restart_backoff_max_s=0.05,
        supervise_poll_s=0.02, spawn_fn=factory,
        on_change=lambda addrs: changes.append(list(addrs)), **kw,
    )
    return sup, factory, changes


def test_supervisor_restarts_dead_replica_with_backoff_counter():
    get_registry().reset()
    sup, factory, changes = _fake_supervisor(2)
    sup.start()
    try:
        assert len(sup.addresses()) == 2 and _snap("fleet.spawns") == 2
        victim = next(s for s in sup._slots.values() if s.idx == 0)
        victim.handle.die()
        deadline = time.monotonic() + 5
        while _snap("fleet.restarts") < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert _snap("fleet.restarts") >= 1
        deadline = time.monotonic() + 5
        while len(sup.addresses()) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(sup.addresses()) == 2
        assert victim.generation == 2  # the slot respawned, not a new slot
        assert changes  # the router was told about every membership change
    finally:
        sup.stop()


def test_supervisor_scale_up_and_down_drains_newest_first():
    get_registry().reset()
    sup, factory, changes = _fake_supervisor(2)
    sup.start()
    try:
        assert sup.scale_to(4) == 4
        assert len(sup.addresses()) == 4
        assert sorted(factory.spawned) == [0, 1, 2, 3]
        victims_before = {s.idx: s.handle for s in sup._slots.values()}
        assert sup.scale_to(2) == 2
        assert len(sup.addresses()) == 2
        # the NEWEST slots drained; the original two kept serving
        assert victims_before[3].drained and victims_before[2].drained
        assert not victims_before[0].drained and not victims_before[1].drained
    finally:
        sup.stop()


def test_supervisor_rolling_restart_recycles_every_slot():
    get_registry().reset()
    sup, factory, changes = _fake_supervisor(2)
    sup.start()
    try:
        old = {s.idx: s.handle for s in sup._slots.values()}
        assert sup.rolling_restart() == 2
        new = {s.idx: s.handle for s in sup._slots.values()}
        for idx in old:
            assert old[idx].drained  # graceful drain, not a kill
            assert new[idx] is not old[idx] and new[idx].alive()
        assert _snap("fleet.rolling_restarts") == 1
    finally:
        sup.stop()


def test_supervisor_seeded_chaos_kills_a_live_replica():
    get_registry().reset()
    sup, factory, changes = _fake_supervisor(3)
    sup.start()
    try:
        chaos = FleetChaos(sup, seed=0, kill_after_s=0.05, kill_period_s=0.0)
        chaos.start()
        deadline = time.monotonic() + 5
        while _snap("fleet.chaos_kills") < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        chaos.stop()
        assert _snap("fleet.chaos_kills") == 1
        # the kill was delivered (-9 on a live handle) and the supervisor
        # restarts the corpse
        deadline = time.monotonic() + 5
        while _snap("fleet.restarts") < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert _snap("fleet.restarts") >= 1
    finally:
        sup.stop()


class _StunnableHandle(_FakeHandle):
    """Records delivered signals WITHOUT dying: SIGSTOP/SIGCONT pulses
    leave a real process alive, and the fake must match or the degrade
    drill would look like a kill."""

    def send_signal(self, sig):
        self.signals.append(sig)
        return self._alive


def test_supervisor_seeded_chaos_degrades_without_killing():
    """mode=degrade: the seeded victim gets a bounded SIGSTOP/SIGCONT pulse
    train, stays ALIVE throughout, always ends released (trailing SIGCONT),
    and the episode is counted fleet.chaos_degrades — never a chaos kill."""
    get_registry().reset()
    spawned = []
    lock = threading.Lock()

    def stunnable(slot):
        with lock:
            spawned.append(slot)
            return _StunnableHandle(slot, len(spawned))

    sup = FleetSupervisor(
        replica_argv=[], log_dir="/tmp/unused", replicas=2,
        restart_backoff_ms=1.0, restart_backoff_max_s=0.05,
        supervise_poll_s=0.02, spawn_fn=stunnable,
    )
    sup.start()
    try:
        chaos = FleetChaos(sup, seed=3, mode="degrade", kill_after_s=0.02,
                           degrade_stop_ms=10.0, degrade_period_ms=30.0,
                           degrade_duration_s=0.2)
        chaos.start()
        deadline = time.monotonic() + 5
        while _snap("fleet.chaos_degrades") < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        time.sleep(0.4)  # let the pulse train finish
        chaos.stop()
        assert _snap("fleet.chaos_degrades") == 1
        assert _snap("fleet.chaos_kills") == 0
        victims = [s.handle for s in sup._slots.values() if s.handle.signals]
        assert len(victims) == 1  # one seeded victim
        sigs = victims[0].signals
        assert signal.SIGSTOP in sigs and signal.SIGCONT in sigs
        assert sigs[-1] == signal.SIGCONT, "a degrade drill must end released"
        assert victims[0].alive(), "degrade must not kill"
        assert _snap("fleet.restarts") == 0  # the supervisor saw no exit
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# the shared client against a real frontend
# ---------------------------------------------------------------------------


def test_client_round_trip_typed_errors_and_connection_reuse():
    from yet_another_mobilenet_series_tpu.serve.admission import AdmissionController
    from yet_another_mobilenet_series_tpu.serve.frontend import Frontend
    from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher

    class _EchoEngine:
        def predict_async(self, images):
            class _H:
                def result(_self):
                    return images[:, 0, 0, :1]

            return _H()

        def predict(self, images):
            return self.predict_async(images).result()

    b = PipelinedBatcher(_EchoEngine(), max_batch=8, max_wait_ms=1.0,
                         queue_depth=64, drain_timeout_s=2.0).start()
    ac = AdmissionController(b)
    fe = Frontend(ac, port=0, replica_id="r-test").start()
    port = fe.port
    try:
        client = ReplicaClient("127.0.0.1", fe.port, timeout_s=10.0)
        img = np.full((4, 4, 3), 7.0, np.float32)
        out = client.predict(img, priority="batch", deadline_ms=30000, request_id="cli-1")
        assert out.tolist() == [7.0]
        client.predict(img)
        # keep-alive: both requests rode ONE socket on this thread
        assert len(client._conns) == 1
        # typed verdicts: unknown class -> 400 with the wire tag
        with pytest.raises(ClientHTTPError) as ei:
            client.predict(img, priority="platinum")
        assert ei.value.status == 400 and ei.value.tag == "bad_request"
        # healthz carries the replica identity block (satellite): the
        # router keys restart detection on start_unix behind one address
        status, doc = client.healthz()
        assert status == 200
        ident = doc["replica"]
        assert ident["replica_id"] == "r-test" and ident["pid"] == os.getpid()
        assert ident["start_unix"] > 0 and "git_sha" in ident
        status, varz = client.varz()
        assert status == 200 and varz["replica"]["replica_id"] == "r-test"
        assert "serve_requests" in client.metrics_text()
        client.close()
    finally:
        fe.stop()
        b.stop()
    # a dead port is a typed connect error (after the one stale-socket retry)
    dead = ReplicaClient("127.0.0.1", port, timeout_s=2.0)
    with pytest.raises(ClientConnectError):
        dead.predict(np.zeros((4, 4, 3), np.float32))


def test_write_listen_addr_is_atomic_rename(tmp_path):
    from yet_another_mobilenet_series_tpu.serve.frontend import write_listen_addr

    path = write_listen_addr(str(tmp_path), {"host": "127.0.0.1", "port": 123, "pid": 9})
    assert json.loads(open(path).read())["port"] == 123
    # no temp residue: the only artifact is the renamed final file
    assert os.listdir(tmp_path) == ["listen_addr.json"]


# ---------------------------------------------------------------------------
# end-to-end: real 2-replica fleet, kill -9, zero client-visible 5xx, drain
# ---------------------------------------------------------------------------


def _get(url, timeout=30):
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _free_port():
    import socket as socket_mod

    s = socket_mod.socket()
    s.settimeout(1.0)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_fleet_attach_e2e_lease_join_expiry_and_u8_wire(tmp_path):
    """The multi-host rung, rehearsed on loopback (ISSUE 15 acceptance):
    `cli/fleet.py --attach` runs the router tier over EXTERNALLY-started
    replica subprocesses (no local spawn), one replica joins LATE purely
    via the /register TTL lease, one is SIGKILLed mid-traffic and removed
    by lease expiry (nobody supervises it — only the lease notices), and
    the uint8 wire rides router->replica end-to-end with the exact 4x-
    fewer per-request serve.h2d_bytes visible on the replicas' /varz."""
    import jax

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.serve.export import export_bundle

    net = get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=4, dropout=0.0,
                    block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}]),
        image_size=24,
    )
    params, state = net.init(jax.random.PRNGKey(0))
    bundle_dir = str(tmp_path / "bundle")
    export_bundle(net, params, state, bundle_dir)

    router_port = _free_port()
    common = [f"serve.bundle={bundle_dir}", "serve.buckets=[1,4]",
              "data.image_size=24", "serve.quant.wire=uint8",
              "serve.listen.enable=true", "serve.listen.port=0",
              "serve.drain_timeout_s=10"]

    def spawn_replica(tag, extra=()):
        log_dir = str(tmp_path / tag)
        return subprocess.Popen(
            [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.serve",
             *common, f"serve.listen.replica_id={tag}",
             f"train.log_dir={log_dir}", *extra],
            env=dict(os.environ, PYTHONPATH=REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
        ), log_dir

    # externally-managed replicas: ra/rb join via --attach, rc joins LATE
    # purely through the lease (its heartbeat retries until the router is
    # up — spawned now so the jax imports overlap)
    procs = {}
    log_dirs = {}
    procs["ra"], log_dirs["ra"] = spawn_replica("ra")
    procs["rb"], log_dirs["rb"] = spawn_replica("rb")
    procs["rc"], log_dirs["rc"] = spawn_replica(
        "rc", [f"serve.listen.register_to=127.0.0.1:{router_port}",
               "serve.listen.register_ttl_s=2.0"])
    fleet_proc = None
    try:
        addrs = {}
        deadline = time.time() + 180
        for tag in ("ra", "rb", "rc"):
            path = os.path.join(log_dirs[tag], "listen_addr.json")
            while not os.path.exists(path):
                assert procs[tag].poll() is None, (
                    f"replica {tag} died early:\n{procs[tag].stdout.read()[-3000:]}")
                assert time.time() < deadline, f"replica {tag} never bound"
                time.sleep(0.2)
            addrs[tag] = json.loads(open(path).read())

        attach = ",".join(f"127.0.0.1:{addrs[t]['port']}" for t in ("ra", "rb"))
        router_log = str(tmp_path / "router")
        fleet_proc = subprocess.Popen(
            [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.fleet",
             "--attach", attach,
             f"serve.listen.port={router_port}",
             "serve.fleet.poll_interval_s=0.1", "serve.fleet.connect_timeout_s=1.0",
             "serve.fleet.eject_cooldown_s=0.5", "serve.fleet.lease_ttl_s=2.0",
             "serve.fleet.hedge.enable=false", f"train.log_dir={router_log}"],
            env=dict(os.environ, PYTHONPATH=REPO),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
        )
        addr_path = os.path.join(router_log, "listen_addr.json")
        deadline = time.time() + 60  # attach mode never imports jax: fast
        while not os.path.exists(addr_path):
            assert fleet_proc.poll() is None, (
                f"fleet died early:\n{fleet_proc.stdout.read()[-3000:]}")
            assert time.time() < deadline, "router never bound"
            time.sleep(0.1)
        addr = json.loads(open(addr_path).read())
        assert addr["role"] == "router" and addr["replicas"] == 2
        assert addr["attach"] == attach.split(",")
        base = f"http://{addr['host']}:{addr['port']}"

        # rc self-registers via the lease: the fleet grows to 3 with the
        # router having spawned NOTHING
        # wait for identities too: a leased member is routable at
        # registration, one poll cycle BEFORE its identity block arrives
        deadline = time.time() + 60
        health, idents = {}, set()
        while time.time() < deadline:
            status, health = _get(base + "/healthz")
            if status == 200 and health["fleet"]["routable"] == 3:
                idents = {r["identity"].get("replica_id")
                          for r in health["fleet"]["replicas"]}
                if idents == {"ra", "rb", "rc"}:
                    break
            time.sleep(0.2)
        assert health["fleet"]["routable"] == 3, health
        assert health["membership"] == {"static": 2, "leased": 1, "lease_ttl_s": 2.0}
        assert idents == {"ra", "rb", "rc"}

        # uint8 wire through the fleet: raw u8 pixels, X-Dtype: u8
        img = np.full((24, 24, 3), 128, np.uint8)

        def post():
            req = urllib.request.Request(
                base + "/predict", data=img.tobytes(),
                headers={"Content-Type": "application/octet-stream",
                         "X-Shape": "24,24,3", "X-Dtype": "u8"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code

        def replica_h2d(tag):
            _, varz = _get(f"http://127.0.0.1:{addrs[tag]['port']}/varz")
            assert varz["build_info"]["quant_mode"].startswith("wire=uint8")
            return varz["metrics"].get("serve.h2d_bytes", 0)

        h2d_before = {t: replica_h2d(t) for t in ("ra", "rb", "rc")}
        n_posts = 6
        for _ in range(n_posts):
            assert post() == 200
        # sequential single-image requests pad to bucket 1: EXACTLY
        # S*S*3 u8 bytes per request crossed H2D — the f32 wire would have
        # moved 4x that. Measured on the replicas the router routed to.
        deadline = time.time() + 30
        while time.time() < deadline:
            h2d_delta = sum(replica_h2d(t) - h2d_before[t] for t in ("ra", "rb", "rc"))
            if h2d_delta >= n_posts * 24 * 24 * 3:
                break
            time.sleep(0.2)
        assert h2d_delta == n_posts * 24 * 24 * 3, (
            f"u8 wire h2d: {h2d_delta} != {n_posts} * {24 * 24 * 3}")

        # SIGKILL the leased replica mid-traffic: no supervisor owns it, so
        # only the LEASE can remove it — traffic keeps answering 200
        # through ejection + retry while the TTL runs out
        os.kill(addrs["rc"]["pid"], signal.SIGKILL)
        statuses = [post() for _ in range(20)]
        assert all(s == 200 for s in statuses), f"client-visible failures: {statuses}"
        deadline = time.time() + 30
        while time.time() < deadline:
            status, health = _get(base + "/healthz")
            if health["fleet"]["total"] == 2:
                break
            time.sleep(0.2)
        assert health["fleet"]["total"] == 2, health
        status, varz = _get(base + "/varz")
        assert varz["metrics"]["fleet.lease_expirations"] >= 1
        assert varz["metrics"]["fleet.registrations"] >= 1
        assert varz["metrics"].get("fleet.spawns", 0) == 0  # attach spawns nothing
        assert post() == 200

        # clean drain: the router exits 0; the external replicas are OURS
        # to stop (that is what externally-managed means)
        fleet_proc.send_signal(signal.SIGTERM)
        rc_code = fleet_proc.wait(timeout=60)
        assert rc_code == 0
        assert "fleet drained" in fleet_proc.stdout.read()
        for tag in ("ra", "rb"):
            procs[tag].send_signal(signal.SIGTERM)
        for tag in ("ra", "rb"):
            assert procs[tag].wait(timeout=60) == 0
    finally:
        for p in [fleet_proc, *procs.values()]:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()


def test_fleet_e2e_kill_minus_9_zero_5xx_and_drain(tmp_path):
    """The CI fleet smoke (ISSUE satellite): spawn a real 2-replica fleet
    behind the router frontend, serve through it, SIGKILL one replica
    mid-traffic, and assert the availability contract — every request
    answers 200 (the router's transport retry + ejection masks the death),
    the supervisor restarts the corpse, SIGTERM drains rc=0.

    Extended for fleet observability (ISSUE 17): the run traces every
    process, a seeded hedged round duplicates requests onto the second
    replica (p50-derived timer with a 1 ms floor), the router frontend
    exposes the federated /varz fleet section + replica-labeled fleet_
    /metrics families, and after the drain scripts/trace_merge.py must
    join the 3 per-process traces into ONE file where each POST has
    exactly one router envelope, every replica envelope carries the
    router-issued request id in args.trace, and at least one hedged
    request shows BOTH legs flow-linked into two different replica
    lanes."""
    import jax

    from yet_another_mobilenet_series_tpu.config import ModelConfig
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.serve.export import export_bundle

    net = get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=4, dropout=0.0,
                    block_specs=[{"t": 2, "c": 8, "n": 1, "s": 2}]),
        image_size=24,
    )
    params, state = net.init(jax.random.PRNGKey(0))
    bundle_dir = str(tmp_path / "bundle")
    export_bundle(net, params, state, bundle_dir)

    log_dir = str(tmp_path / "fleet")
    proc = subprocess.Popen(
        [sys.executable, "-m", "yet_another_mobilenet_series_tpu.cli.fleet",
         f"serve.bundle={bundle_dir}", "serve.buckets=[1,4]", "data.image_size=24",
         "serve.fleet.replicas=2", "serve.fleet.poll_interval_s=0.1",
         # an aggressive hedge timer (p50 with a 1 ms floor) so the seeded
         # round below reliably duplicates legs onto the second replica
         "serve.fleet.hedge.min_samples=5", "serve.fleet.hedge.quantile=0.5",
         "serve.fleet.hedge.min_timer_ms=1",
         "obs.trace=true",  # every process dumps obs_trace.json at drain
         "serve.drain_timeout_s=10", f"train.log_dir={log_dir}"],
        env=dict(os.environ, PYTHONPATH=REPO),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
    )
    try:
        addr_path = os.path.join(log_dir, "listen_addr.json")
        deadline = time.time() + 180
        while not os.path.exists(addr_path):
            assert proc.poll() is None, f"fleet died early:\n{proc.stdout.read()[-3000:]}"
            assert time.time() < deadline, "router never bound"
            time.sleep(0.2)
        addr = json.loads(open(addr_path).read())
        assert addr["role"] == "router" and addr["replicas"] == 2
        base = f"http://{addr['host']}:{addr['port']}"

        # both replicas routable, each with its own identity block. Bounded
        # wait: identity lands with the router's first health poll, and a
        # slow first poll on this contended box can transiently eject a
        # replica (healthz 503) until the next poll readmits it
        deadline = time.time() + 60
        status, health, idents = None, None, set()
        while time.time() < deadline:
            status, health = _get(base + "/healthz")
            idents = {r["identity"].get("replica_id") for r in health["fleet"]["replicas"]}
            if status == 200 and health["fleet"]["routable"] == 2 and idents == {"r0", "r1"}:
                break
            time.sleep(0.2)
        assert status == 200 and health["fleet"]["routable"] == 2, health
        assert idents == {"r0", "r1"}

        img = np.full((24, 24, 3), 1.0, np.float32)
        n_posts = [0]  # every POST mints one router rid: the trace oracle

        def post():
            n_posts[0] += 1
            req = urllib.request.Request(
                base + "/predict", data=img.tobytes(),
                headers={"Content-Type": "application/octet-stream", "X-Shape": "24,24,3"},
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code

        assert post() == 200

        # kill -9 replica r0 mid-traffic: the fleet must not surface it
        r0 = json.loads(open(os.path.join(log_dir, "r0", "listen_addr.json")).read())
        os.kill(r0["pid"], signal.SIGKILL)
        statuses = []
        for _ in range(30):
            statuses.append(post())
            time.sleep(0.05)
        assert all(s == 200 for s in statuses), f"client-visible failures: {statuses}"

        # the supervisor restarts the corpse; the router readmits it
        deadline = time.time() + 120
        while time.time() < deadline:
            status, health = _get(base + "/healthz")
            if health["fleet"]["routable"] == 2:
                break
            time.sleep(0.3)
        assert health["fleet"]["routable"] == 2, health
        status, varz = _get(base + "/varz")
        assert varz["metrics"]["fleet.restarts"] >= 1
        # ejection vs removal is a race the supervisor usually wins (it
        # notices the death and drops the dead address from the backend set
        # before the router's failure counter reaches the ejection bar), so
        # only the DETERMINISTIC counters are asserted here — the ejection
        # and readmission paths are pinned by the unit tests above and the
        # r06 rehearsal artifact. Likewise no readmission: the corpse comes
        # back on a NEW ephemeral port, a fresh backend to the router.
        assert varz["metrics"]["fleet.spawns"] >= 3
        assert varz["replica"]["replica_id"] == "router"
        # the restarted r0 published a FRESH atomic address with its new pid
        r0b = json.loads(open(os.path.join(log_dir, "r0", "listen_addr.json")).read())
        assert r0b["pid"] != r0["pid"] and r0b["replica_id"] == "r0"
        assert post() == 200

        # --- seeded hedged round (ISSUE 17): both replicas healthy again,
        # the p50 timer duplicates ~half the legs — keep posting until the
        # router's hedge counter moves
        _, varz = _get(base + "/varz")
        hedges0 = varz["metrics"].get("serve.hedges", 0)
        deadline = time.time() + 90
        while time.time() < deadline:
            assert post() == 200
            _, varz = _get(base + "/varz")
            if varz["metrics"].get("serve.hedges", 0) > hedges0:
                break
            time.sleep(0.02)
        assert varz["metrics"].get("serve.hedges", 0) > hedges0, varz["metrics"]

        # federated observability on the router frontend: /varz grows the
        # fleet section (scrape-loop output over both replicas) + the raw
        # histogram state, /metrics the replica-labeled fleet_ families
        deadline = time.time() + 30
        while time.time() < deadline:
            _, varz = _get(base + "/varz")
            if (varz.get("fleet", {}).get("scrapes", 0) >= 1
                    and len(varz["fleet"].get("replicas", {})) == 2):
                break
            time.sleep(0.2)
        assert varz["fleet"]["scrapes"] >= 1, varz.get("fleet")
        assert len(varz["fleet"]["replicas"]) == 2, varz["fleet"]
        assert "histograms" in varz
        assert "slo" in varz["fleet"]  # the SLO tracker rides the scrape loop
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            metrics_text = r.read().decode()
        assert "# TYPE fleet_build_info gauge" in metrics_text
        assert 'fleet_build_info{replica="r0"' in metrics_text
        assert 'fleet_build_info{replica="r1"' in metrics_text
        assert 'fleet_serve_latency_seconds_bucket{replica=' in metrics_text

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        assert rc == 0
        out = proc.stdout.read()
        assert "fleet drained" in out
        snap = json.loads(open(os.path.join(log_dir, "obs_registry.json")).read())
        assert snap["fleet.spawns"] >= 3  # 2 initial + >= 1 restart
        assert snap["fleet.routed"] >= len(statuses)

        # --- merged cross-process trace (scripts/trace_merge.py): router +
        # both replicas joined into ONE Perfetto doc on a shared timeline
        import importlib.util

        from yet_another_mobilenet_series_tpu.serve.context import (
            TRACE_SEQ_HEDGE_BASE, trace_flow_id)

        spec = importlib.util.spec_from_file_location(
            "trace_merge", os.path.join(REPO, "scripts", "trace_merge.py"))
        tm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tm)
        paths = tm.discover(log_dir)
        assert len(paths) == 3, paths  # router + r0 + r1
        merged = tm.merge_files(paths)
        assert "warnings" not in merged, merged.get("warnings")
        lanes = {p["process_name"]: p["pid"] for p in merged["processes"]}
        assert set(lanes) == {"router", "r0", "r1"}, lanes
        ev = merged["traceEvents"]

        # exactly one router serve/request envelope per POST, the merged
        # (process-scoped) ids recovering the frontend-minted rids 1..N
        router_envs = {e["id"] for e in ev
                       if e.get("ph") == "b" and e.get("name") == "serve/request"
                       and e["pid"] == lanes["router"]}
        assert len(router_envs) == n_posts[0], (len(router_envs), n_posts[0])
        router_rids = {i % tm.ID_STRIDE for i in router_envs}
        assert router_rids == set(range(1, n_posts[0] + 1))

        # every replica-side request envelope carries the ROUTER-issued
        # request id in args.trace (the cross-process correlation key)
        rep_pids = {lanes["r0"], lanes["r1"]}
        rep_envs = [e for e in ev
                    if e.get("ph") == "b" and e.get("name") == "serve/request"
                    and e["pid"] in rep_pids]
        assert rep_envs
        bad = [e for e in rep_envs
               if (e.get("args") or {}).get("trace") not in router_rids]
        assert not bad, [e.get("args") for e in bad[:5]]

        # at least one hedged request reads as one waterfall with BOTH legs:
        # primary (seq 0) and hedge (seq TRACE_SEQ_HEDGE_BASE) flow-starts
        # on the router lane whose UNREMAPPED fleet/leg ids land as
        # flow-ends on two DIFFERENT replica lanes
        leg_seqs: dict = {}
        for e in ev:
            if e.get("name") == "fleet/leg" and e.get("ph") == "s":
                tid, seq = divmod(e["id"], 2 * TRACE_SEQ_HEDGE_BASE)
                leg_seqs.setdefault(tid, set()).add(seq)
        ends = {e["id"]: e["pid"] for e in ev
                if e.get("name") == "fleet/leg" and e.get("ph") == "f"}
        hedged = [tid for tid, seqs in leg_seqs.items()
                  if 0 in seqs and TRACE_SEQ_HEDGE_BASE in seqs]
        assert hedged, leg_seqs
        linked = [
            tid for tid in hedged
            if trace_flow_id(tid, 0) in ends
            and trace_flow_id(tid, TRACE_SEQ_HEDGE_BASE) in ends
            and ends[trace_flow_id(tid, 0)]
            != ends[trace_flow_id(tid, TRACE_SEQ_HEDGE_BASE)]
        ]
        assert linked, {"hedged": hedged, "ends": len(ends)}
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
