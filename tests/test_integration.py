"""End-to-end integration tests through cli.train.run() (SURVEY.md §4.3):
fake-data training loss decreases, checkpoint save->resume, eval-only path,
and the AtomNAS shrink-mid-run->resume survival test."""

import glob
import json
import os

import numpy as np
import pytest

import jax

from yet_another_mobilenet_series_tpu.cli import train as cli_train
from yet_another_mobilenet_series_tpu.config import config_from_dict


def _base_cfg(tmp_path, **over):
    d = {
        "name": "itest",
        "model": {
            "arch": "mobilenet_v2",
            "num_classes": 8,
            "dropout": 0.0,
            "block_specs": [
                {"t": 3, "c": 16, "n": 1, "s": 2, "k": 3},
                {"t": 3, "c": 24, "n": 1, "s": 2, "k": 3},
            ],
        },
        "data": {"dataset": "fake", "image_size": 32, "fake_train_size": 1280, "fake_eval_size": 64},
        # SGD+momentum: stable on tiny toy nets under ANY data order (tf.data
        # shuffle depends on the process-global TF seed, which other test
        # modules may set; RMSProp diverged on some orderings)
        "optim": {"optimizer": "sgd", "momentum": 0.9, "weight_decay": 1e-5},
        "schedule": {"schedule": "constant", "base_lr": 0.05, "scale_by_batch": False, "warmup_epochs": 0.5},
        "ema": {"enable": True, "decay": 0.99, "warmup": True},
        "train": {
            "batch_size": 64,
            "eval_batch_size": 64,
            "epochs": 2,
            "log_every": 2,
            "compute_dtype": "float32",
            "log_dir": str(tmp_path),
            "eval_every_epochs": 1.0,
        },
        "dist": {"num_devices": 8},
    }
    for k, v in over.items():
        cur = d
        ks = k.split(".")
        for kk in ks[:-1]:
            cur = cur.setdefault(kk, {})
        cur[ks[-1]] = v
    return config_from_dict(d)


def test_train_run_learns_and_checkpoints(tmp_path):
    cfg = _base_cfg(tmp_path, **{"train.epochs": 3})
    result = cli_train.run(cfg)
    assert result["epoch"] == pytest.approx(3.0)
    # learnable synthetic task: far above chance (1/8) once EMA/BN warm up
    assert result["eval_top1"] > 0.5, result
    assert result["eval_n"] == 64
    # a checkpoint with spec sidecar exists
    assert glob.glob(str(tmp_path) + "/ckpt/*/meta*")


@pytest.mark.slow
def test_resume_continues_from_checkpoint(tmp_path, capsys):
    cfg = _base_cfg(tmp_path, **{"train.epochs": 1})
    cli_train.run(cfg)
    cfg2 = _base_cfg(tmp_path, **{"train.epochs": 2})
    cli_train.run(cfg2)
    out = capsys.readouterr().out
    assert "resumed at step 20" in out  # 1280/64 = 20 steps/epoch


@pytest.mark.slow
def test_eval_only_with_pretrained(tmp_path):
    cfg = _base_cfg(tmp_path)
    trained = cli_train.run(cfg)
    cfg_eval = _base_cfg(tmp_path, **{"train.test_only": True})
    result = cli_train.run(cfg_eval)
    np.testing.assert_allclose(result["top1"], trained["eval_top1"], atol=1e-6)


@pytest.mark.parametrize("zero,k_dispatch", [
    # the plain variant's path is fully covered by the other two (each adds
    # exactly one knob to it) — opt-in only, to keep the suite bar ~3 min
    # lighter without dropping a unique path (VERDICT r4 next #8)
    pytest.param(False, 1, id="replicated", marks=pytest.mark.exhaustive),
    pytest.param(True, 1, id="zero"),
    pytest.param(False, 2, id="grouped"),
])
@pytest.mark.slow
def test_atomnas_search_shrinks_and_resumes(tmp_path, capsys, zero, k_dispatch):
    over = {
        # zero=True exercises the shipped atomnas_c_se combination: remat must
        # gather the ZeRO shards before slicing and re-scatter after.
        # k_dispatch=2 runs the SEARCH grouped (VERDICT r4 next #4): the
        # in-device prune event fires inside the grouped program, remat
        # rebuilds the grouped step, and no forcing warning may appear.
        "dist.shard_optimizer": zero,
        "train.steps_per_dispatch": k_dispatch,
        "model.arch": "atomnas_supernet",
        "model.block_specs": [
            {"t": 6, "c": 16, "n": 2, "s": 2, "k": [3, 5, 7]},
            {"t": 6, "c": 24, "n": 1, "s": 2, "k": [3, 5, 7], "se": 0.25},
        ],
        "prune.enable": True,
        "prune.rho": 0.05,
        "prune.gamma_threshold": 0.6,  # aggressive: init gamma=1 must be pushed below
        "prune.mask_interval": 2,
        "prune.remat_epochs": 1.0,
        "prune.stop_epoch_frac": 1.0,
        "train.epochs": 2,
        "schedule.base_lr": 0.12,
    }
    cfg = _base_cfg(tmp_path, **over)
    result = cli_train.run(cfg)
    out = capsys.readouterr().out
    assert "penalty=" in out
    if k_dispatch > 1:
        assert "forcing 1" not in out  # pruning no longer disables grouping
    assert result["epoch"] == pytest.approx(2.0)
    _check_resume(tmp_path, over, capsys)


@pytest.mark.slow
def test_adaptive_rho_reaches_target_where_constant_does_not(tmp_path):
    """SURVEY.md §2 #11 rho schedule: with a deliberately too-small base rho
    the constant schedule never pushes any gamma below threshold, while the
    adaptive controller multiplies rho up on the FLOPs gap until the search
    actually shrinks toward target_flops."""
    base = {
        "model.arch": "atomnas_supernet",
        "model.block_specs": [
            {"t": 6, "c": 16, "n": 2, "s": 2, "k": [3, 5, 7]},
            {"t": 6, "c": 24, "n": 1, "s": 2, "k": [3, 5, 7]},
        ],
        "prune.enable": True,
        # raw (unnormalized) atom costs with a base rho far too small to move
        # any gamma on its own — only the adaptive multiplier can make the
        # penalty bite (verified: constant ends at full 3.4M MACs, adaptive
        # at 0.7M)
        "prune.rho": 3e-7,
        "prune.normalize_cost": False,
        "prune.gamma_threshold": 0.6,
        "prune.mask_interval": 2,
        "prune.remat_epochs": 0.0,  # keep shapes; judge by effective (masked) MACs
        "prune.stop_epoch_frac": 1.0,
        "prune.target_flops": 1.0,  # unreachably low => constant pressure up
        "train.epochs": 2,
        "schedule.base_lr": 0.12,
    }

    def final_macs(subdir, **extra):
        cfg = _base_cfg(tmp_path / subdir, **{**base, **extra})
        cli_train.run(cfg)
        with open(str(tmp_path / subdir / "searched_arch.json")) as f:
            return json.load(f)["macs"]

    macs_const = final_macs("const")
    macs_adapt = final_macs(
        "adapt",
        **{
            "prune.rho_schedule": "adaptive",
            "prune.rho_adapt_rate": 0.35,
            "prune.rho_adapt_max": 1000.0,
        },
    )
    # constant stays at the full supernet (~3.4M); adaptive shrinks hard
    assert macs_adapt < 0.5 * macs_const, (macs_adapt, macs_const)


@pytest.mark.slow
def test_search_emit_retrain_seam(tmp_path):
    """The acceptance #4 -> #5 handoff (VERDICT r2 next-round #5): an AtomNAS
    search run emits searched_arch.json; the emitted spec then trains and
    evals STANDALONE through model.network_spec (the retrain_searched.yml
    path) with pruning off, and its MACs equal the emitted spec's."""
    from yet_another_mobilenet_series_tpu.models import get_model
    from yet_another_mobilenet_series_tpu.utils.profiling import profile_network

    search_over = {
        "model.arch": "atomnas_supernet",
        "model.block_specs": [
            {"t": 6, "c": 16, "n": 2, "s": 2, "k": [3, 5, 7]},
            {"t": 6, "c": 24, "n": 1, "s": 2, "k": [3, 5, 7], "se": 0.25},
        ],
        "prune.enable": True,
        # the adaptive-controller recipe the rho-schedule test proves shrinks
        # hard (constant rho at this base never prunes); remat at epoch
        # boundaries so the EMITTED spec is physically pruned
        "prune.rho": 3e-7,
        "prune.normalize_cost": False,
        "prune.rho_schedule": "adaptive",
        "prune.rho_adapt_rate": 0.35,
        "prune.rho_adapt_max": 1000.0,
        "prune.target_flops": 1.0,
        "prune.gamma_threshold": 0.6,
        "prune.mask_interval": 2,
        "prune.remat_epochs": 1.0,
        "prune.stop_epoch_frac": 1.0,
        "train.epochs": 2,
        "schedule.base_lr": 0.12,
    }
    cli_train.run(_base_cfg(tmp_path / "search", **search_over))
    spec_path = str(tmp_path / "search" / "searched_arch.json")
    with open(spec_path) as f:
        emitted = json.load(f)
    # the search must actually have pruned below the full supernet
    full = profile_network(
        get_model(_base_cfg(tmp_path / "search", **search_over).model, 32), 32
    ).total_macs
    assert emitted["macs"] < full, (emitted["macs"], full)

    # standalone retrain from the emitted spec (pruning off, fresh log dir)
    retrain_cfg = _base_cfg(
        tmp_path / "retrain",
        **{"model.network_spec": spec_path, "train.epochs": 3},
    )
    rebuilt = get_model(retrain_cfg.model, 32)
    assert profile_network(rebuilt, 32).total_macs == emitted["macs"]
    result = cli_train.run(retrain_cfg)
    assert result["epoch"] == pytest.approx(3.0)
    # learnable synthetic task, 8 classes: clearly above chance (0.125)
    assert result["eval_top1"] > 0.3, result


def _check_resume(tmp_path, over, capsys):
    # the saved spec sidecar must encode the (possibly pruned) live network
    metas = sorted(glob.glob(str(tmp_path) + "/ckpt/*/meta/*"))
    assert metas
    # resume must rebuild from the sidecar without shape errors
    cfg3 = _base_cfg(tmp_path, **{**over, "train.epochs": 2.5})
    result2 = cli_train.run(cfg3)
    assert result2["epoch"] >= 2.0


@pytest.mark.slow
def test_warm_start_finetune_from_checkpoint(tmp_path, capsys):
    """train.pretrained on a fresh (non-resumed) training run warm-starts the
    weights with a fresh optimizer/step — after a few finetune steps accuracy
    stays near the source's, which a fresh init cannot reach that fast."""
    src_dir, ft_dir = tmp_path / "src", tmp_path / "ft"
    trained = cli_train.run(_base_cfg(src_dir, **{"train.epochs": 3}))
    assert trained["eval_top1"] > 0.5
    cfg_ft = _base_cfg(ft_dir, **{
        "train.epochs": 0.25,  # 5 steps
        "train.pretrained": str(src_dir / "ckpt"),
        "schedule.base_lr": 0.005,
    })
    result = cli_train.run(cfg_ft)
    out = capsys.readouterr().out
    assert "warm start from checkpoint" in out
    assert result["eval_top1"] > 0.5, result  # fresh init gets ~0.125 in 5 steps


@pytest.mark.slow
def test_warm_start_finetune_from_torch_checkpoint(tmp_path, capsys):
    import torch

    from tests.test_torch_import import _randomized_torch_model, _tiny_net

    net = _tiny_net(num_classes=8)
    tm = _randomized_torch_model(net, 8)
    torch.save(tm.state_dict(), str(tmp_path / "w.pth"))
    cfg = _base_cfg(tmp_path, **{
        "model.block_specs": [
            {"t": 1, "c": 16, "n": 1, "s": 1, "k": 3},
            {"t": 6, "c": 24, "n": 2, "s": 2, "k": 5},
        ],
        "train.epochs": 0.25,
        "train.torch_pretrained": str(tmp_path / "w.pth"),
    })
    result = cli_train.run(cfg)
    out = capsys.readouterr().out
    assert "warm start from torch checkpoint" in out
    assert result["epoch"] == pytest.approx(0.25)


@pytest.mark.slow
def test_best_checkpoint_kept_and_evaluable(tmp_path):
    """train.keep_best maintains a single-slot best-top1 checkpoint (the
    reference's best.pth); evaluating it reproduces the recorded best."""
    cfg = _base_cfg(tmp_path, **{"train.epochs": 3})
    result = cli_train.run(cfg)
    assert glob.glob(str(tmp_path) + "/ckpt_best/*/meta*")
    cfg_eval = _base_cfg(
        tmp_path, **{"train.test_only": True, "train.pretrained": str(tmp_path) + "/ckpt_best"}
    )
    best_eval = cli_train.run(cfg_eval)
    np.testing.assert_allclose(best_eval["top1"], result["eval_best_top1"], atol=1e-6)


@pytest.mark.slow
def test_resume_from_legacy_checkpoint_without_rho_mult(tmp_path, monkeypatch, capsys):
    """Checkpoints written before TrainState grew rho_mult must still resume
    (restore retries without the field and injects the neutral multiplier)."""
    from yet_another_mobilenet_series_tpu.train import steps as steps_mod

    over = {
        "model.arch": "atomnas_supernet",
        "model.block_specs": [{"t": 4, "c": 16, "n": 1, "s": 2, "k": [3, 5]}],
        "prune.enable": True,
        "prune.mask_interval": 4,
        "prune.remat_epochs": 0.0,
        "train.epochs": 1,
    }
    # simulate the legacy on-disk layout: save without the rho_mult leaf
    legacy_fields = tuple(f for f in steps_mod.TRAIN_STATE_FIELDS if f != "rho_mult")
    monkeypatch.setattr(steps_mod, "TRAIN_STATE_FIELDS", legacy_fields)
    cli_train.run(_base_cfg(tmp_path, **over))
    monkeypatch.undo()

    result = cli_train.run(_base_cfg(tmp_path, **{**over, "train.epochs": 1.5}))
    out = capsys.readouterr().out
    assert "retrying as legacy checkpoint" in out
    assert result["epoch"] >= 1.5
