"""The socket-level network-chaos proxy (serve/netchaos.py): deterministic
seeded fault plans, and each fault shape exercised against a trivial echo
server — blackhole (connect succeeds, nothing flows, established pipes
stall too), reset (RST, not FIN), half-open (request consumed, reads hang),
asymmetric response loss (the server did the work), added latency, a
bandwidth throttle, and timed link flaps. The router-facing partition
behaviors (ejection bounds, retry, lease expiry) live in tests/test_fleet.py.
"""

import socket
import threading
import time

import pytest

from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve.netchaos import NetChaosProxy, NetChaosTier


@pytest.fixture
def echo_server():
    """A line-for-line TCP echo server on an ephemeral loopback port."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.settimeout(5.0)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(16)

    def accept_loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return

            def handle(conn=conn):
                conn.settimeout(5.0)
                try:
                    while True:
                        data = conn.recv(4096)
                        if not data:
                            return
                        conn.sendall(data)
                except OSError:
                    pass
                finally:
                    conn.close()

            threading.Thread(target=handle, daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    yield srv.getsockname()
    srv.close()


def _dial(addr, timeout=2.0):
    c = socket.create_connection(addr, 2.0)
    c.settimeout(timeout)
    return c


def _round_trip(addr, payload=b"ping", timeout=2.0):
    c = _dial(addr, timeout)
    try:
        c.sendall(payload)
        got = b""
        while len(got) < len(payload):
            chunk = c.recv(4096)
            if not chunk:
                break
            got += chunk
        return got
    finally:
        c.close()


# ---------------------------------------------------------------------------
# determinism: same seed + settings -> same per-connection plans
# ---------------------------------------------------------------------------


def test_fault_plans_are_deterministic_per_seed(echo_server):
    host, port = echo_server
    kw = dict(fault="blackhole", fault_rate=0.5, latency_ms=7.0, jitter_ms=3.0)
    a = NetChaosProxy(host, port, seed=11, **kw)
    b = NetChaosProxy(host, port, seed=11, **kw)
    plans_a = [a.plan_for(i).as_dict() for i in range(32)]
    plans_b = [b.plan_for(i).as_dict() for i in range(32)]
    assert plans_a == plans_b, "same seed + settings must give identical plans"
    # the rate really thins the schedule, deterministically
    applied = [p for p in plans_a if p["applies"]]
    assert 0 < len(applied) < 32
    assert all(p["shape"] == "blackhole" for p in applied)
    assert all(p["shape"] is None and p["latency_s"] == 0 for p in plans_a if not p["applies"])
    # a different seed draws a different schedule
    c = NetChaosProxy(host, port, seed=12, **kw)
    assert [c.plan_for(i).as_dict() for i in range(32)] != plans_a


# ---------------------------------------------------------------------------
# fault shapes against the echo server
# ---------------------------------------------------------------------------


def test_clean_proxy_passes_traffic_through(echo_server):
    p = NetChaosProxy(*echo_server, seed=0).start()
    try:
        assert _round_trip(p.addr, b"hello") == b"hello"
        # a payload bigger than one pump chunk crosses intact
        big = bytes(range(256)) * 512  # 128 KiB
        assert _round_trip(p.addr, big, timeout=10.0) == big
    finally:
        p.stop()


def test_blackhole_hangs_new_and_established_connections(echo_server):
    p = NetChaosProxy(*echo_server, seed=0).start()
    try:
        est = _dial(p.addr, timeout=0.5)
        est.sendall(b"warm")
        assert est.recv(10) == b"warm"
        p.set_fault("blackhole")
        # established keep-alive pipe: stalls (a partition spares no socket)
        est.sendall(b"x")
        with pytest.raises(socket.timeout):
            est.recv(10)
        # new connection: connect SUCCEEDS (the deceptive part), reads hang
        c = _dial(p.addr, timeout=0.5)
        c.sendall(b"y")
        with pytest.raises(socket.timeout):
            c.recv(10)
        c.close()
        # heal: the stalled chunk flows again on the established pipe
        p.clear()
        assert est.recv(10) == b"x"
        est.close()
    finally:
        p.stop()


def test_reset_aborts_with_rst(echo_server):
    p = NetChaosProxy(*echo_server, seed=0, fault="reset").start()
    try:
        try:
            c = _dial(p.addr)
        except ConnectionResetError:
            return  # the RST beat the handshake: same abort, surfaced at connect
        try:
            c.sendall(b"z")
            out = c.recv(10)
            # a race-free RST may surface as ECONNRESET on either call, or
            # as an immediate EOF if the FIN/RST landed before the recv
            assert out == b""
        except ConnectionResetError:
            pass
        finally:
            c.close()
    finally:
        p.stop()


def test_half_open_consumes_request_and_never_answers(echo_server):
    get_registry().reset()
    p = NetChaosProxy(*echo_server, seed=0, fault="half_open").start()
    try:
        c = _dial(p.addr, timeout=0.5)
        c.sendall(b"request bytes")  # consumed without error
        with pytest.raises(socket.timeout):
            c.recv(10)
        c.close()
        assert get_registry().snapshot().get("serve.netchaos.half_open", 0) >= 1
    finally:
        p.stop()


def test_drop_response_forwards_request_but_eats_answer(echo_server):
    """Asymmetric loss: the upstream really received the request (did the
    work) but the client never sees the answer — the shape that makes
    idempotence-aware retry mandatory."""
    get_registry().reset()
    host, port = echo_server
    received = []
    # a recording upstream so the forward is observable
    rec = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    rec.settimeout(5.0)
    rec.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    rec.bind(("127.0.0.1", 0))
    rec.listen(4)

    def record():
        try:
            conn, _ = rec.accept()
        except OSError:
            return
        conn.settimeout(5.0)
        try:
            data = conn.recv(4096)
            received.append(data)
            conn.sendall(b"answer:" + data)
        except OSError:
            pass

    threading.Thread(target=record, daemon=True).start()
    p = NetChaosProxy("127.0.0.1", rec.getsockname()[1], seed=0,
                      fault="drop_response").start()
    try:
        c = _dial(p.addr, timeout=0.7)
        c.sendall(b"the work")
        with pytest.raises(socket.timeout):
            c.recv(100)
        c.close()
        deadline = time.monotonic() + 2.0
        while not received and time.monotonic() < deadline:
            time.sleep(0.02)
        assert received == [b"the work"], "the request must reach the upstream"
        assert get_registry().snapshot().get("serve.netchaos.dropped_chunks", 0) >= 1
    finally:
        p.stop()
        rec.close()


def test_latency_and_jitter_delay_responses(echo_server):
    p = NetChaosProxy(*echo_server, seed=0, latency_ms=150.0, jitter_ms=50.0).start()
    try:
        t0 = time.monotonic()
        assert _round_trip(p.addr, b"slow") == b"slow"
        rtt = time.monotonic() - t0
        assert rtt >= 0.14, f"latency injection missing: rtt={rtt * 1e3:.0f}ms"
    finally:
        p.stop()


def test_bandwidth_throttle_paces_large_responses(echo_server):
    # 64 kbit/s = 8000 bytes/s: a 4 KB echo must take >= ~0.4s to stream back
    p = NetChaosProxy(*echo_server, seed=0, bandwidth_kbps=64.0).start()
    try:
        payload = b"\x5a" * 4096
        t0 = time.monotonic()
        assert _round_trip(p.addr, payload, timeout=10.0) == payload
        elapsed = time.monotonic() - t0
        assert elapsed >= 0.3, f"throttle missing: {elapsed:.2f}s for 4KB at 64kbps"
    finally:
        p.stop()


def test_flap_schedule_alternates_down_and_up_windows(echo_server):
    p = NetChaosProxy(*echo_server, seed=0, flap_period_s=0.6, flap_down_s=0.3).start()
    try:
        results = []
        t_end = time.monotonic() + 1.3
        while time.monotonic() < t_end:
            try:
                c = _dial(p.addr, timeout=0.15)
                c.sendall(b"f")
                results.append(c.recv(10) == b"f")
                c.close()
            except (socket.timeout, OSError):
                results.append(False)
            time.sleep(0.04)
        # the schedule starts DOWN (phase 0 < down_s) and must come up
        # within the first period, then drop again: both states observed
        assert any(results) and not all(results), results
        assert results[0] is False, "the flap schedule must start in its down window"
        assert get_registry().snapshot().get("serve.netchaos.flap_transitions", 0) >= 1
    finally:
        p.stop()


def test_fault_rate_spares_the_unlucky_subset(echo_server):
    """rate < 1: the seeded subset hangs, the rest pass — per-connection
    plans, not a coin flip per chunk."""
    p = NetChaosProxy(*echo_server, seed=11, fault="blackhole", fault_rate=0.5).start()
    try:
        expected = [p.plan_for(i).applies for i in range(8)]
        got = []
        for _ in range(8):
            try:
                got.append(_round_trip(p.addr, b"r", timeout=0.4) != b"r")
            except (socket.timeout, OSError):
                got.append(True)
        assert got == expected, "traffic must follow the deterministic plan schedule"
    finally:
        p.stop()


# ---------------------------------------------------------------------------
# the tier: reconcile + victim pick
# ---------------------------------------------------------------------------


def test_tier_reconciles_proxies_and_routes_addresses(echo_server):
    host, port = echo_server
    tier = NetChaosTier(seed=0)
    try:
        out = tier.route([(host, port)])
        assert len(out) == 1 and out[0][1] != port  # a real interposed port
        assert _round_trip(out[0], b"via-tier") == b"via-tier"
        first_proxy = tier.proxies()[0]
        # same membership: same proxies (no churn)
        assert tier.route([(host, port)]) == out
        assert tier.proxies()[0] is first_proxy
        # removed upstream: its proxy stops; re-added: a fresh one
        assert tier.route([]) == []
        assert tier.proxies() == []
        out2 = tier.route([(host, port)])
        assert _round_trip(out2[0], b"again") == b"again"
        assert tier.pick() is tier.proxies()[0]
    finally:
        tier.stop()
