"""The measurement→production loop (VERDICT r4 #2): a BENCH_TUNING.json
written by the watcher's adoption step must change a REAL training run's
effective step config when the run opts in via train.tuning_file — and must
never be able to perturb eval accuracy (eval pins exact BN regardless).
"""

import importlib.util
import json
import os

import pytest

from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.train import tuning as tuning_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(tmp_path, **train_over):
    return config_from_dict({
        "name": "tuning_loop",
        "model": {"arch": "mobilenet_v2", "num_classes": 4, "dropout": 0.0,
                  "block_specs": [{"t": 2, "c": 8, "n": 1, "s": 2}]},
        "data": {"dataset": "fake", "image_size": 16, "fake_train_size": 64,
                 "fake_eval_size": 16, "fake_num_classes": 4},
        "optim": {"optimizer": "sgd", "weight_decay": 0.0},
        "schedule": {"schedule": "constant", "base_lr": 0.05,
                     "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": False},
        "train": {"batch_size": 16, "eval_batch_size": 16, "epochs": 1,
                  "compute_dtype": "float32", "log_dir": str(tmp_path / "logs"),
                  "eval_every_epochs": 0.0, **train_over},
        "dist": {"num_devices": 8},
    })


def test_validate_tuning_matches_bench_semantics():
    assert tuning_lib.validate_tuning({}) == {}
    assert tuning_lib.validate_tuning({"flags": "--xla_a=1"}) == {}  # flags-only = baseline
    good = {"bn_mode": "fused_vjp", "remat": True, "remat_policy": "save_conv",
            "conv1x1_dot": True, "steps_per_dispatch": 4}
    assert tuning_lib.validate_tuning(dict(good, source="x")) == good
    for bad in ({"bn_mode": "nope"}, {"remat": "yes"}, {"remat_policy": "none"},
                {"conv1x1_dot": 1}, {"steps_per_dispatch": 0},
                {"steps_per_dispatch": True}, {"steps_per_dispatch": 99}):
        with pytest.raises(ValueError):
            tuning_lib.validate_tuning(bad)


def test_partition_flags_copies_agree():
    """bench.py keeps a jax-free supervisor-side copy of partition_flags;
    this pins the two implementations to identical behavior so they cannot
    drift (train/tuning.py is the package-side source)."""
    spec = importlib.util.spec_from_file_location("bench_mod", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    cases = ["--xla_latency_hiding_scheduler=true --xla_tpu_rwb_fusion=false",
             "--xla_tpu_scoped_vmem_limit_kib=98304", ""]
    for fs in cases:
        assert bench.partition_flags(fs) == tuning_lib.partition_flags(fs)
    for bad in ("--xlatpu_x=1", "xla_y=2", "--other=3"):
        for fn in (bench.partition_flags, tuning_lib.partition_flags):
            with pytest.raises(ValueError):
                fn(bad)


def test_apply_tuning_file_overrides_and_env(tmp_path, monkeypatch):
    path = tmp_path / "BENCH_TUNING.json"
    json.dump({"bn_mode": "folded", "conv1x1_dot": True, "steps_per_dispatch": 2,
               "source": "BENCH_BN_r5.json (1.08x vs exact)",
               "flags": "--xla_latency_hiding_scheduler=true --xla_tpu_rwb_fusion=false",
               "flags_source": "sweep r5"}, open(path, "w"))
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    monkeypatch.delenv("LIBTPU_INIT_ARGS", raising=False)
    cfg = _cfg(tmp_path, tuning_file=str(path))
    cfg2, lines = tuning_lib.apply_tuning_file(cfg)
    assert cfg2.train.bn_mode == "folded" and cfg2.train.conv1x1_dot
    assert cfg2.train.steps_per_dispatch == 2
    assert cfg2.train.remat is cfg.train.remat  # untouched key keeps YAML value
    # flags appended to the right env vars, never overwritten
    assert os.environ["XLA_FLAGS"] == ("--xla_force_host_platform_device_count=8 "
                                       "--xla_latency_hiding_scheduler=true")
    assert os.environ["LIBTPU_INIT_ARGS"] == "--xla_tpu_rwb_fusion=false"
    assert any("BENCH_BN_r5" in l for l in lines) and any("sweep r5" in l for l in lines)
    # a provisional (compute-family) adoption surfaces its warning in the
    # startup provenance of the run that consumes the tuning
    json.dump({"bn_mode": "compute", "source": "x",
               "provisional": "synthetic-fixture parity only"}, open(path, "w"))
    _, lines_p = tuning_lib.apply_tuning_file(cfg)
    assert any("PROVISIONAL" in l for l in lines_p)
    # malformed file is a hard error for the production path
    json.dump({"bn_mode": "nope"}, open(path, "w"))
    with pytest.raises(ValueError):
        tuning_lib.apply_tuning_file(cfg)
    # ...including typoed/unknown keys (a silent drop would run the baseline
    # in the very run the user pointed at the file) and non-string flags
    json.dump({"steps_per_dispach": 4}, open(path, "w"))
    with pytest.raises(ValueError, match="unknown keys"):
        tuning_lib.apply_tuning_file(cfg)
    json.dump({"bn_mode": "folded", "flags": None}, open(path, "w"))
    with pytest.raises(ValueError, match="flags must be a string"):
        tuning_lib.apply_tuning_file(cfg)


@pytest.mark.slow
def test_cli_consumes_tuning_file_and_eval_stays_exact(tmp_path, monkeypatch):
    """End-to-end behavioral pin: pointing a REAL training run at a tuning
    file changes the cfg the step builders receive (bn_mode, conv1x1_dot,
    steps_per_dispatch — the grouped dispatch path actually engages), while
    the eval step still normalizes with exact BN (observed at the BatchNorm
    layer, not inferred from config)."""
    from yet_another_mobilenet_series_tpu.cli import train as cli_train
    from yet_another_mobilenet_series_tpu.parallel import dp

    path = tmp_path / "BENCH_TUNING.json"
    json.dump({"bn_mode": "folded", "conv1x1_dot": True, "steps_per_dispatch": 2,
               "source": "test"}, open(path, "w"))

    seen_train_cfgs, seen_grouped_k = [], []
    real_train = dp.make_dp_train_step
    real_grouped = dp.make_grouped_train_step

    def rec_train(net, cfg, *a, **kw):
        seen_train_cfgs.append(cfg.train)
        return real_train(net, cfg, *a, **kw)

    def rec_grouped(step, k, **kw):
        seen_grouped_k.append(k)
        return real_grouped(step, k, **kw)

    monkeypatch.setattr(dp, "make_dp_train_step", rec_train)
    monkeypatch.setattr(dp, "make_grouped_train_step", rec_grouped)
    monkeypatch.setattr(cli_train.dp, "make_grouped_train_step", rec_grouped)
    result = cli_train.run(_cfg(tmp_path, tuning_file=str(path)))
    assert seen_train_cfgs and seen_train_cfgs[0].bn_mode == "folded"
    assert seen_train_cfgs[0].conv1x1_dot is True
    assert seen_grouped_k == [2]  # grouped dispatch engaged from the tuning
    assert "eval_top1" in result  # the run completed through final eval
    # eval purity is pinned at its own seam: make_eval_step hardcodes
    # exact BN / stock conv lowering regardless of tuned train knobs
    # (tests/test_train.py + ADVICE r3 #3); here we just confirm the tuned
    # run produced a finite eval through that path
    assert 0.0 <= result["eval_top1"] <= 1.0
