"""Model tests for the concurrency layer (analysis/concurrency.py) plus the
rule-level regressions that motivated it.

The fixture-pair tests in test_lint_rules.py prove YAMT019/020/021 flag and
stay silent end to end; this file pins the MODEL facts those rules consume —
thread-root discovery (method and lambda targets), lock-domain summaries
(with-statement and acquire/release held-sets), callee absorption through
the fixpoint, and honest degradation to silence when the thread target is
opaque — so a resolution regression fails here with a named fact, not as a
mysteriously silent rule. The PR 8 compile-under-dispatch-lock bug is pinned
as a must-flag regression."""

import pathlib

from yet_another_mobilenet_series_tpu import analysis
from yet_another_mobilenet_series_tpu.analysis.core import Project, SourceFile, collect_paths

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"


def _project(paths):
    py, yml = collect_paths([str(p) for p in paths])
    files = []
    for p in py:
        with open(p, encoding="utf-8") as f:
            files.append(SourceFile(p, f.read()))
    return Project(files, yml)


def _summary(model, tail):
    return next(v for q, v in model.summaries.items() if q.endswith(tail))


# -- lock-domain summaries ---------------------------------------------------


def test_with_lock_heldsets(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._v = 0\n"
        "\n"
        "    def set(self, v):\n"
        "        with self._lock:\n"
        "            self._v = v\n"
        "\n"
        "    def peek(self):\n"
        "        return self._v\n"
    )
    model = _project([tmp_path]).concurrency
    tok = next(t for t in model.lock_types if t.endswith("Box._lock"))
    assert model.lock_types[tok] == "Lock"

    set_acc = _summary(model, "Box.set").accesses
    ((key, heldsets),) = set_acc.items()
    assert key[1] == "_v" and key[2] == "w"
    assert heldsets == {frozenset({tok})}

    peek_acc = _summary(model, "Box.peek").accesses
    ((key, heldsets),) = peek_acc.items()
    assert key[2] == "r" and heldsets == {frozenset()}


def test_acquire_release_tracked_linearly(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._v = 0\n"
        "\n"
        "    def manual(self):\n"
        "        self._lock.acquire()\n"
        "        self._v = 1\n"
        "        self._lock.release()\n"
        "        self._v = 2\n"
    )
    model = _project([tmp_path]).concurrency
    tok = next(t for t in model.lock_types if t.endswith("Box._lock"))
    acc = _summary(model, "Box.manual").accesses
    by_line = {key[4]: heldsets for key, heldsets in acc.items()}
    assert by_line[10] == {frozenset({tok})}  # between acquire and release
    assert by_line[12] == {frozenset()}  # after release


def test_callee_events_absorb_caller_locks(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._v = 0\n"
        "\n"
        "    def _helper(self):\n"
        "        self._v = 3\n"
        "\n"
        "    def locked_call(self):\n"
        "        with self._lock:\n"
        "            self._helper()\n"
    )
    model = _project([tmp_path]).concurrency
    tok = next(t for t in model.lock_types if t.endswith("Box._lock"))
    # the helper's own summary stays lock-free...
    ((_, helper_held),) = _summary(model, "Box._helper").accesses.items()
    assert helper_held == {frozenset()}
    # ...but absorbed into the caller it carries the caller's held lock
    caller = _summary(model, "Box.locked_call").accesses
    ((key, caller_held),) = ((k, v) for k, v in caller.items() if k[1] == "_v")
    assert key[2] == "w" and caller_held == {frozenset({tok})}


# -- thread roots ------------------------------------------------------------


def test_thread_root_method_target(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=self._loop, daemon=True)\n"
        "\n"
        "    def _loop(self):\n"
        "        pass\n"
    )
    model = _project([tmp_path]).concurrency
    assert [r.target.name for r in model.roots] == ["_loop"]
    (root,) = model.roots
    assert root.line == 5 and root.spawner_cls.endswith("Worker")
    assert root.spawn_span is not None  # __init__'s span: setup/teardown gate


def test_thread_root_lambda_target(tmp_path):
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=lambda: self._tick())\n"
        "\n"
        "    def _tick(self):\n"
        "        pass\n"
    )
    model = _project([tmp_path]).concurrency
    assert [r.target.name for r in model.roots] == ["_tick"]


def test_opaque_thread_target_degrades_to_silence(tmp_path):
    # an unresolvable target must produce NO root (and so no findings),
    # never a guess
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "class Worker:\n"
        "    def __init__(self, name):\n"
        "        self._t = threading.Thread(target=getattr(self, name))\n"
        "\n"
        "    def _loop(self):\n"
        "        self._count = 1\n"
    )
    project = _project([tmp_path])
    assert project.concurrency.roots == []


# -- rule-level regressions --------------------------------------------------


def test_pr8_compile_under_dispatch_lock_flags():
    # THE motivating bug: .lower().compile() inside the dispatch lock that
    # the warm loop thread and main-thread callers contend for (fixed in the
    # serving engine by compiling outside and publishing under the lock)
    findings = analysis.run_lint([FIXTURES / "yamt021" / "bad"])
    assert [f.rule for f in findings] == ["YAMT021"]
    assert "compile" in findings[0].message and "dispatch_lock" in findings[0].message


def test_lock_order_cycle_message_names_both_edges():
    findings = analysis.run_lint([FIXTURES / "yamt020" / "bad"])
    assert [f.rule for f in findings] == ["YAMT020"]
    msg = findings[0].message
    assert "_alock" in msg and "_block" in msg and "closing edge" in msg


def test_cross_thread_race_names_both_regions():
    findings = analysis.run_lint([FIXTURES / "yamt019" / "bad"])
    assert [f.rule for f in findings] == ["YAMT019"]
    msg = findings[0].message
    assert "thread" in msg and "no common lock" in msg
