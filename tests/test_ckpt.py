"""Checkpoint semantics (SURVEY.md §4.3): save -> restore -> next step is
bit-identical to never having checkpointed; pruned-shape-first restore."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.ckpt.manager import CheckpointManager
from yet_another_mobilenet_series_tpu.config import config_from_dict
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.nas import masking
from yet_another_mobilenet_series_tpu.train import optim, schedules, steps


def _mk(tmp_path):
    cfg = config_from_dict({
        "model": {
            "arch": "atomnas_supernet",
            "num_classes": 4,
            "dropout": 0.0,
            "block_specs": [{"t": 4, "c": 8, "n": 1, "s": 2, "k": [3, 5]}],
        },
        "schedule": {"schedule": "constant", "base_lr": 0.02, "scale_by_batch": False, "warmup_epochs": 0.0},
        "ema": {"enable": True, "decay": 0.9, "warmup": False},
        "train": {"compute_dtype": "float32", "log_dir": str(tmp_path)},
        "prune": {"enable": True},
    })
    net = get_model(cfg.model, image_size=16)
    lr_fn = schedules.make_lr_schedule(cfg.schedule, 8, 1, 10)
    params, _ = net.init(jax.random.PRNGKey(0))
    opt = optim.make_optimizer(cfg.optim, lr_fn, params)
    ts = steps.init_train_state(net, cfg, opt, jax.random.PRNGKey(0))
    ts = ts.replace(masks=masking.init_masks(net))
    step_fn = jax.jit(steps.make_train_step(net, cfg, opt, lr_fn))
    batch = {"image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)), "label": jnp.arange(8) % 4}
    return cfg, net, opt, ts, step_fn, batch


def test_save_restore_step_bit_equivalence(tmp_path):
    cfg, net, opt, ts, step_fn, batch = _mk(tmp_path)
    ts, _ = step_fn(ts, batch, jax.random.PRNGKey(2))

    mgr = CheckpointManager(str(tmp_path) + "/ck", async_save=False)
    mgr.save(int(ts.step), net, jax.device_get(ts), extra={"epoch": 0.5})
    mgr.wait()

    # continue WITHOUT restoring
    ts_cont, _ = step_fn(ts, batch, jax.random.PRNGKey(2))

    # restore (two-phase: spec first, then tree against abstract target)
    step, net2, extra = mgr.restore_spec()
    assert net2 == net and extra["epoch"] == 0.5
    abstract = jax.eval_shape(lambda: ts)
    tree = mgr.restore_tree(step, steps.train_state_to_dict(abstract))
    ts_rest = steps.TrainState(**tree)
    ts_rest2, _ = step_fn(ts_rest, batch, jax.random.PRNGKey(2))

    for a, b in zip(jax.tree.leaves(ts_cont), jax.tree.leaves(ts_rest2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_restore_spec_none_when_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path) + "/empty", async_save=False)
    assert mgr.restore_spec() is None
    assert mgr.all_steps() == []
    mgr.close()


def test_save_records_digests_and_restore_verifies(tmp_path):
    """The crash-consistency sidecar: save records per-item digests, an
    abstract-targeted restore verifies them, and a recorded-vs-restored
    mismatch raises CheckpointCorrupt (the as-saved export path is exempt:
    optax containers restore as dicts there, changing leaf order)."""
    import json

    from yet_another_mobilenet_series_tpu.ckpt import manager as mgr_mod

    cfg, net, opt, ts, step_fn, batch = _mk(tmp_path)
    ts, _ = step_fn(ts, batch, jax.random.PRNGKey(2))
    mgr = CheckpointManager(str(tmp_path) + "/ckd", async_save=False)
    mgr.save(int(ts.step), net, jax.device_get(ts), extra={})
    mgr.wait()

    digest_path = tmp_path / "ckd" / mgr_mod.DIGEST_NAME
    index = json.loads(digest_path.read_text())
    items = index[str(int(ts.step))]
    # every non-empty TrainState item is protected
    assert {"step", "params", "state", "opt_state", "ema_params",
            "ema_state", "masks", "rho_mult"} <= set(items)

    abstract = steps.train_state_to_dict(jax.eval_shape(lambda: ts))
    tree = mgr.restore_tree(int(ts.step), abstract)  # verifies, passes
    assert set(tree) == set(abstract)

    # simulate value corruption Orbax's storage checks can't see
    items["params"] = "0" * 64
    digest_path.write_text(json.dumps(index))
    from yet_another_mobilenet_series_tpu.ckpt.manager import CheckpointCorrupt

    with pytest.raises(CheckpointCorrupt, match="params"):
        mgr.restore_tree(int(ts.step), abstract)
    mgr.restore_tree(int(ts.step))  # as-saved export read stays unverified
    mgr.close()


def test_tree_keys_reports_saved_items(tmp_path):
    """tree_keys is the legacy-vs-corruption discriminator: it must list the
    items actually on disk (including None-valued fields) without reading
    any array bytes."""
    cfg, net, opt, ts, step_fn, batch = _mk(tmp_path)
    mgr = CheckpointManager(str(tmp_path) + "/ckk", async_save=False)
    mgr.save(3, net, jax.device_get(ts), extra={})
    mgr.wait()
    keys = mgr.tree_keys(3)
    assert keys is not None and {"params", "opt_state", "rho_mult"} <= keys
    assert mgr.tree_keys(99) is None  # nonexistent step degrades to None
    mgr.close()


def test_restore_pruned_shape_first(tmp_path):
    """The sidecar must rebuild the pruned architecture before weights load
    (SURVEY.md §3.5)."""
    import numpy as np

    from yet_another_mobilenet_series_tpu.nas import rematerialize

    cfg, net, opt, ts, step_fn, batch = _mk(tmp_path)
    masks = {k: jnp.asarray(np.r_[np.ones(8), np.zeros(v.shape[0] - 8)].astype(np.float32)) for k, v in ts.masks.items()}
    new_net, p, s, m, extras, _ = rematerialize.rematerialize(
        net, jax.device_get(ts.params), jax.device_get(ts.state),
        {k: np.asarray(v) for k, v in masks.items()},
        opt_state=jax.device_get(ts.opt_state),
        ema_params=jax.device_get(ts.ema_params), ema_state=jax.device_get(ts.ema_state),
    )
    ts2 = steps.TrainState(step=ts.step, params=p, state=s, opt_state=extras["opt_state"],
                           ema_params=extras["ema_params"], ema_state=extras["ema_state"], masks=m)
    mgr = CheckpointManager(str(tmp_path) + "/ck2", async_save=False)
    mgr.save(7, new_net, ts2, extra={})
    mgr.wait()
    step, net3, _ = mgr.restore_spec()
    assert step == 7
    assert net3 == new_net  # pruned shape, not the supernet
    assert net3.blocks[0].expanded_channels == 8
    mgr.close()
