"""Device-resident request ring tests (docs/SERVING.md "Device-resident
ring", serve/ring.py + engine ring mode).

The ring's load-bearing claims, each pinned:

- **bitwise parity by construction**: a ring window's logits are bitwise
  identical to the per-batch path for every staged row — across window
  fills, partial last slots, the uint8 wire, int8-weight bundles, and
  every tenant of a 2-model zoo. The scan body IS the per-chunk forward;
  the mask is a scalar-bool output select, never an input blend.
- **one dispatch per window**: a window of R staged slots costs exactly
  ONE ``serve.dispatch_seconds`` observation (the registry-delta probe),
  and the ring accounting (``serve.ring_dispatches``,
  ``serve.ring_slots_per_dispatch``, ``serve.ring_fill``) matches.
- **typed feed/consume contract**: the window shape is validated with
  typed errors — only the LAST slot may be partial, 1..R slots, ring off
  is a RuntimeError — and the config block refuses nonsense depths/fills.
- **pipeline engagement and fallback**: a saturated burst rides the ring
  (``serve.ring_dispatches`` advances, answers correct); trickle traffic
  and off-ladder sizes ride the untouched per-batch path.

Heavy matrix corners (u8 wire x int8 weights x zoo x both ladder sizes)
are ``@pytest.mark.slow`` to hold the tier-1 wall-time budget.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from yet_another_mobilenet_series_tpu.config import ModelConfig, RingConfig
from yet_another_mobilenet_series_tpu.models import get_model
from yet_another_mobilenet_series_tpu.obs.registry import get_registry
from yet_another_mobilenet_series_tpu.serve import quant
from yet_another_mobilenet_series_tpu.serve.engine import InferenceEngine
from yet_another_mobilenet_series_tpu.serve.export import InferenceBundle, fold_network
from yet_another_mobilenet_series_tpu.serve.pipeline import PipelinedBatcher
from yet_another_mobilenet_series_tpu.serve.ring import RingEntry, min_slots, window_chunks


def _small_net(num_classes=10, image_size=24):
    specs = [
        {"t": 2, "c": 8, "n": 1, "s": 2},
        {"t": 3, "c": 16, "n": 2, "s": 2},
    ]
    return get_model(
        ModelConfig(arch="mobilenet_v2", num_classes=num_classes, block_specs=specs, dropout=0.0),
        image_size=image_size,
    )


def _folded_bundle(seed=0, num_classes=10, int8=False):
    net = _small_net(num_classes=num_classes)
    params, state = net.init(jax.random.PRNGKey(seed))
    k = jax.random.PRNGKey(seed + 1)
    leaves, treedef = jax.tree.flatten(state)
    keys = jax.random.split(k, len(leaves))
    state = jax.tree.unflatten(
        treedef,
        [l + 0.1 * jnp.abs(jax.random.normal(kk, l.shape)) + 0.01 for l, kk in zip(leaves, keys)],
    )
    folded = fold_network(net, params, state)
    if int8:
        folded, _ = quant.quantize_folded(folded)
    return InferenceBundle(net=net, params=folded, meta={})


@pytest.fixture(scope="module")
def bundle():
    return _folded_bundle()


def _images(counts, size, *, wire, seed=0):
    """Per-slot input arrays in the wire's client dtype: raw u8 pixels on
    the uint8 wire, already-normalized floats on the f32 wire."""
    rng = np.random.RandomState(seed)
    out = []
    for i, n in enumerate(counts):
        if wire == "uint8":
            out.append(rng.randint(0, 256, (n, size, size, 3)).astype(np.uint8))
        else:
            out.append(rng.normal(0, 1, (n, size, size, 3)).astype(np.float32))
    return out


def _ring_vs_per_batch(eng, counts, size, *, wire, model=None, ref_eng=None, seed=0):
    """Stage one window of ``counts`` slots, dispatch it, and assert the
    drained logits are bitwise identical to the per-batch path, slot by
    slot (per-slot references use each slot's own bucket, the strictest
    comparison: different executable, same math)."""
    parts = _images(counts, size, wire=wire, seed=seed)
    entries = [eng.ring_stage(p.copy()) for p in parts]
    out = eng.ring_dispatch(entries, model=model).result()
    assert out.shape[0] == sum(counts)
    ref = ref_eng if ref_eng is not None else eng
    # a dedicated single-bundle reference engine serves its bundle as the
    # default tenant: query it unqualified
    ref_model = model if ref_eng is None else None
    at = 0
    for p in parts:
        want = (ref.predict(p.copy(), model=ref_model)
                if ref_model is not None else ref.predict(p.copy()))
        np.testing.assert_array_equal(out[at:at + len(p)], want)
        at += len(p)
    return out


# ---------------------------------------------------------------------------
# pure helpers + config surface
# ---------------------------------------------------------------------------


def test_ring_min_slots_and_window_chunks():
    assert min_slots(4, 0.5) == 2
    assert min_slots(4, 1.0) == 4
    assert min_slots(4, 0.01) == 1
    assert min_slots(3, 1 / 3) == 1  # the epsilon keeps exact thirds exact
    chunks, leftover = window_chunks(list(range(10)), 4, 4)
    assert [len(c) for c in chunks] == [4, 4, 2] and leftover == []
    chunks, leftover = window_chunks(list(range(20)), 4, 4)
    assert [len(c) for c in chunks] == [4, 4, 4, 4] and leftover == [16, 17, 18, 19]
    assert window_chunks([], 4, 4) == ([], [])
    with pytest.raises(ValueError):
        window_chunks([1], 0, 4)
    with pytest.raises(ValueError):
        window_chunks([1], 4, 0)


def test_ring_config_validation():
    rc = RingConfig(enable=True, slots=6, min_fill=0.25)
    assert rc.slots == 6
    with pytest.raises(ValueError):
        RingConfig(slots=1)
    with pytest.raises(ValueError):
        RingConfig(min_fill=0.0)
    with pytest.raises(ValueError):
        RingConfig(min_fill=1.5)


def test_ring_engine_ctor_validation(bundle):
    with pytest.raises(ValueError):
        InferenceEngine(bundle, buckets=(2,), fuse_ladder=(), ring_slots=1)
    eng = InferenceEngine(bundle, buckets=(2,), fuse_ladder=())
    assert eng.ring_slots == 0
    with pytest.raises(RuntimeError):
        eng.ring_stage(np.zeros((1, 24, 24, 3), np.float32))
    with pytest.raises(RuntimeError):
        eng.ring_dispatch([RingEntry(None, 1)])


# ---------------------------------------------------------------------------
# parity matrix: window fills x wire x sizes (engine level)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("wire", ["float32", "uint8"])
def test_ring_parity_across_window_fills(bundle, wire):
    """The core matrix cell: f32 and u8 wires, every window fill shape a
    4-deep ring admits over bucket 4 — saturated, partial last slot,
    single full slot, single partial slot — all bitwise."""
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(),
                          wire=wire, ring_slots=4)
    eng.warmup()
    for seed, counts in enumerate([(4, 4, 4, 4), (4, 4, 2), (4,), (3,)]):
        _ring_vs_per_batch(eng, counts, 24, wire=wire, seed=seed)


def test_ring_parity_on_second_ladder_size(bundle):
    """Both rungs of a 2-size ladder get their own warmed ring executable
    and both serve bitwise; an off-ladder size reports not ring-ready."""
    eng = InferenceEngine(bundle, buckets=(2, 4), image_sizes=(24, 32),
                          fuse_ladder=(), ring_slots=4)
    eng.warmup()
    for size in (24, 32):
        assert eng.ring_ready(None, size)
        _ring_vs_per_batch(eng, (4, 3), size, wire="float32", seed=size)
    assert not eng.ring_ready(None, 48)


def test_ring_parity_int8_weights():
    """int8-weight bundles need no ring plumbing: apply_folded dequantizes
    in-program, in the ring scan body exactly as in the per-chunk
    executables — parity stays bitwise."""
    b8 = _folded_bundle(seed=3, int8=True)
    eng = InferenceEngine(b8, buckets=(2, 4), image_size=24, fuse_ladder=(),
                          ring_slots=4)
    eng.warmup()
    _ring_vs_per_batch(eng, (4, 4, 1), 24, wire="float32", seed=11)


def test_ring_parity_two_model_zoo():
    """Each tenant of a ring-enabled zoo engine answers bitwise-identically
    to a DEDICATED ring-less engine serving that bundle alone — the shared
    ring staging pools and the per-tenant ring executables add nothing to
    any tenant's math."""
    bs = _folded_bundle(seed=0, num_classes=10)
    bb = _folded_bundle(seed=7, num_classes=7)
    eng = InferenceEngine(models={"small": bs, "big": bb}, buckets=(2, 4),
                          fuse_ladder=(), ring_slots=4)
    eng.warmup()
    refs = {"small": InferenceEngine(bs, buckets=(2, 4), fuse_ladder=()),
            "big": InferenceEngine(bb, buckets=(2, 4), fuse_ladder=())}
    for model, seed in (("small", 1), ("big", 2)):
        out = _ring_vs_per_batch(eng, (4, 2), 24, wire="float32", model=model,
                                 ref_eng=refs[model], seed=seed)
        assert out.shape[1] == (10 if model == "small" else 7)


@pytest.mark.slow
def test_ring_parity_heavy_matrix_corner():
    """The expensive matrix corner in one engine: uint8 wire x int8-weight
    bundles x 2-model zoo x a 2-size ladder x overlapped staging, every
    cell bitwise against dedicated ring-less engines."""
    bs = _folded_bundle(seed=0, num_classes=10, int8=True)
    bb = _folded_bundle(seed=7, num_classes=7, int8=True)
    common = dict(buckets=(2, 4), fuse_ladder=(), wire="uint8",
                  model_image_sizes={"small": (24, 32), "big": (24, 32)})
    eng = InferenceEngine(models={"small": bs, "big": bb}, ring_slots=4,
                          overlap_staging=True, staging_slots=2, **common)
    eng.warmup()
    refs = {"small": InferenceEngine(models={"small": bs}, **common),
            "big": InferenceEngine(models={"big": bb}, **common)}
    for size in (24, 32):
        for model, seed in (("small", size), ("big", size + 1)):
            for counts in ((4, 4, 4, 4), (4, 1)):
                _ring_vs_per_batch(eng, counts, size, wire="uint8", model=model,
                                   ref_eng=refs[model], seed=seed)


# ---------------------------------------------------------------------------
# one-dispatch probe + accounting (registry deltas)
# ---------------------------------------------------------------------------


def test_ring_window_is_one_dispatch(bundle):
    """The tentpole's headline, registry-delta counted: a saturated window
    of R full slots is exactly ONE serve.dispatch_seconds observation, one
    serve.ring_dispatches tick, fill == 1.0, and R slots in the
    slots-per-dispatch histogram."""
    get_registry().reset()
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(),
                          ring_slots=4)
    eng.warmup()
    snap0 = get_registry().snapshot()
    parts = _images((4, 4, 4, 4), 24, wire="float32", seed=5)
    entries = [eng.ring_stage(p) for p in parts]
    out = eng.ring_dispatch(entries).result()
    assert out.shape == (16, 10)
    snap = get_registry().snapshot()

    def delta(key):
        return snap.get(key, 0) - snap0.get(key, 0)

    assert delta("serve.dispatch_seconds.count") == 1
    assert delta("serve.ring_dispatches") == 1
    assert delta("serve.ring_slots_per_dispatch.count") == 1
    assert delta("serve.ring_slots_per_dispatch.sum") == 4
    assert snap["serve.ring_fill"] == 1.0
    assert delta("serve.infer_images") == 16
    assert delta("serve.bucket_hits.4") == 4
    assert delta("serve.dispatched_flops") > 0
    # a half-filled window still runs the same executable; fill says so
    _ring_vs_per_batch(eng, (4, 4), 24, wire="float32", seed=6)
    assert get_registry().snapshot()["serve.ring_fill"] == 0.5


def test_ring_dispatch_typed_window_errors(bundle):
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(),
                          ring_slots=4)
    eng.warmup()
    with pytest.raises(ValueError, match="1..4 rows|ring slot holds"):
        eng.ring_stage(np.zeros((5, 24, 24, 3), np.float32))
    with pytest.raises(ValueError, match="ring_stage expects"):
        eng.ring_stage(np.zeros((2, 24, 32, 3), np.float32))
    partial = eng.ring_stage(np.zeros((2, 24, 24, 3), np.float32))
    full = eng.ring_stage(np.zeros((4, 24, 24, 3), np.float32))
    with pytest.raises(ValueError, match="LAST ring slot"):
        eng.ring_dispatch([partial, full])
    with pytest.raises(ValueError, match="ring window holds"):
        eng.ring_dispatch([])
    # the staged-but-refused slots are still dispatchable in the right order
    out = eng.ring_dispatch([full, partial]).result()
    assert out.shape == (6, 10)


# ---------------------------------------------------------------------------
# pipeline engagement + fallback
# ---------------------------------------------------------------------------


def test_ring_pipeline_burst_rides_ring_trickle_does_not(bundle):
    """A concurrent burst deep enough to fill min_fill * R slots rides the
    ring (serve.ring_dispatches advances; every answer bitwise vs direct
    predict); afterwards, sequential trickle traffic leaves the ring
    counter untouched and still answers correctly."""
    get_registry().reset()
    eng = InferenceEngine(bundle, buckets=(2, 4), image_size=24, fuse_ladder=(),
                          ring_slots=4)
    eng.warmup()
    b = PipelinedBatcher(eng, max_inflight=2, max_batch=8, max_wait_ms=20.0,
                         queue_depth=64, ring_min_fill=0.5).start()
    try:
        rng = np.random.RandomState(0)
        imgs = [rng.normal(0, 1, (24, 24, 3)).astype(np.float32) for _ in range(32)]
        results = {}
        lock = threading.Lock()

        def client(i):
            val = b.submit(imgs[i].copy()).result(timeout=30)
            with lock:
                results[i] = val

        threads = [threading.Thread(target=client, args=(i,)) for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        burst_rings = get_registry().snapshot().get("serve.ring_dispatches", 0)
        assert burst_rings >= 1, "a 32-deep burst never engaged the ring"
        for i in range(32):
            np.testing.assert_array_equal(
                results[i], eng.predict(imgs[i][None].copy())[0])
        # trickle: one request at a time can never stage min_fill * R slots
        for i in range(3):
            np.testing.assert_array_equal(
                b.submit(imgs[i].copy()).result(timeout=30),
                eng.predict(imgs[i][None].copy())[0])
        assert get_registry().snapshot().get("serve.ring_dispatches", 0) == burst_rings
    finally:
        b.stop()
